"""Occupancy-adaptive merge gears: ladder resolution + the chunk controller.

The exchange merge's (dst, t, order) sort runs over the full static outbox
width N = H x B every round, even though the round tracer shows most rounds
carry a small fraction of that (BASELINE.md round-cost split: the merge is
~1.3 ms of a ~2.1 ms round on v5e). Conservative-PDES merge cost should
track ACTUAL per-round traffic, not the worst-case bound — so the engine
compiles the round body at a small ladder of outbox column widths ("gears",
`Engine.run_chunk_gear`) and the driver picks next chunk's gear here, from
the always-on `stats.outbox_hwm` signal (the most sends any one host staged
in a round).

Exactness is preserved by construction, not by prediction: a gear that
would shed (some host staged more sends than the gear's column width —
detected exactly by `ops.merge.gear_shed_count` feeding `stats.gear_shed`)
aborts the chunk at the first shedding round, and the driver restores the
pre-chunk `SimState` snapshot (`core.checkpoint.snapshot_state`) and
replays that chunk one gear up. The top gear is always the full send budget
and can never shed, so the replay loop terminates, and accepted chunks are
bit-identical to the full-width engine on every workload — digests, event
counts, and drop counters included (tests/test_gears.py is the gate).

The hierarchical exchange composes with gears through the SAME abort
contract: the gear width rescales the inter-shard block size too
(`EngineConfig.hier_block_size` derives from rows_g = hosts_per_shard x
effective_gear_cols), so a narrow gear also thins the alltoall blocks —
and a block overflow under a gear is psum'd into `stats.gear_shed`
exactly like a sort-width shed, tripping the same abort-and-replay one
gear up. At the top gear the hierarchical block size equals the flat
alltoall's, so the ladder's termination argument carries over unchanged
(core/engine.py `_exchange_hierarchical`; tests/test_hier.py gates the
geared matrix).

The controller is deliberately simple and deterministic:
  - upshift immediately (on a shed, or when the observed high-water
    reaches the current gear's width — headroom of one lane column);
  - downshift only after `down_lag` consecutive chunks whose high-water
    fits the lower gear (hysteresis: a replay costs a whole chunk, a
    too-wide sort costs only its width).
Determinism note: gear choices affect WHICH program runs, never what it
computes — a controller bug can cost replays, not correctness.
"""

from __future__ import annotations

DOWN_LAG = 2  # chunks of low occupancy before shifting down


def resolve_gear_ladder(spec, send_budget: int) -> list[int]:
    """`experimental.merge_gears` -> sorted ladder of outbox column widths.

    Accepted specs:
      0 / None / False / "off"  -> []  (gears disabled, full width always)
      "auto" / True             -> ~{B/8, B/4, B/2, B} (deduped, >= 1)
      [ints]                    -> explicit widths, validated against the
                                   send budget; the full width B is always
                                   appended so the replay loop terminates.
    """
    if not spec or (isinstance(spec, str) and spec.lower() == "off"):
        return []
    b = int(send_budget)
    if spec is True or (isinstance(spec, str) and spec.lower() == "auto"):
        ladder = sorted({max(1, b // 8), max(1, b // 4), max(1, b // 2), b})
    else:
        if isinstance(spec, int):
            spec = [spec]
        try:
            gears = sorted({int(g) for g in spec})
        except (TypeError, ValueError) as e:
            raise ValueError(
                f"merge_gears must be 'auto', an int, or a list of ints, "
                f"got {spec!r}"
            ) from e
        for g in gears:
            if g < 1 or g > b:
                raise ValueError(
                    f"merge gear {g} out of range [1, sends_per_host_round"
                    f"={b}]"
                )
        ladder = gears if gears[-1] == b else gears + [b]
    return [] if ladder == [b] else ladder


class GearController:
    """Per-run gear state: pick next chunk's gear, account replays.

    Drivers call, per chunk:
        gear = ctl.gear                       # width to dispatch at
        ... run, on shed: gear = ctl.note_shed(); restore + replay ...
        ctl.note_chunk(gear, ob_hwm)          # accepted chunk's signal
    """

    def __init__(self, ladder: list[int], down_lag: int = DOWN_LAG):
        if not ladder:
            raise ValueError("GearController needs a non-empty ladder")
        self.ladder = list(ladder)
        # start at the TOP gear: the boot chunk's occupancy is unknown and
        # a replay costs a whole chunk; the first observation adapts down
        self.gear = self.ladder[-1]
        self.down_lag = int(down_lag)
        self.replays = 0  # chunks re-run one gear up after a shed
        self.chunks: dict[int, int] = {}  # accepted chunks per gear
        self._low_streak = 0

    @property
    def top(self) -> int:
        return self.ladder[-1]

    def _fit(self, hwm: int) -> int:
        """Smallest ladder gear with headroom over the observed high-water
        (strictly greater: hwm == gear means the width was exactly filled,
        one more send next chunk would shed — step up preemptively)."""
        for g in self.ladder:
            if hwm < g:
                return g
        return self.top

    def note_shed(self, observed_hwm: int | None = None) -> int:
        """A chunk shed at the current gear: pick the replay gear and
        reset the downshift streak. With `observed_hwm` (the ABORTED
        chunk's outbox high-water, read before the snapshot restore) the
        replay jumps straight to a gear that fits the burst it actually
        saw — one replay instead of walking the ladder rung by rung when
        traffic jumped several gears at once. The jump is a floor, not a
        guarantee: the aborted chunk stopped at its first shedding round,
        so later rounds may burst higher and shed again — each replay
        still moves strictly up the ladder, so the loop terminates."""
        self.replays += 1
        self._low_streak = 0
        idx = self.ladder.index(self.gear)
        up = self.ladder[min(idx + 1, len(self.ladder) - 1)]
        if observed_hwm is not None:
            up = max(up, self._fit(observed_hwm))
        self.gear = up
        return self.gear

    def note_chunk(self, gear: int, ob_hwm: int) -> int:
        """Record an ACCEPTED chunk run at `gear` whose outbox high-water
        was `ob_hwm`; returns the gear for the next chunk."""
        self.chunks[gear] = self.chunks.get(gear, 0) + 1
        want = self._fit(ob_hwm)
        if want > self.gear:
            self.gear = want  # headroom exhausted: step up before a shed
            self._low_streak = 0
        elif want < self.gear:
            self._low_streak += 1
            if self._low_streak >= self.down_lag:
                self.gear = want
                self._low_streak = 0
        else:
            self._low_streak = 0
        return self.gear

    def report(self) -> dict:
        """JSON-able summary for sim-stats / BENCH rows."""
        return {
            "ladder": list(self.ladder),
            "chunks_per_gear": {str(g): n for g, n in sorted(self.chunks.items())},
            "replays": self.replays,
        }


def run_adaptive_chunk(ctl: GearController, state, dispatch, rounds0=None):
    """One ACCEPTED chunk at the controller's gear, with shed-exact replay
    — the gears-only face of the shared snapshot-replay loop, which now
    lives in `core.pressure.ResilienceController` (the pressure plane
    generalized this loop to arbitrate capacity regrows from the same
    seam; with no pressure policy the controller reduces exactly to the
    gear behavior shipped here in PR 4).

    `dispatch(state, gear)` runs one chunk program at that gear and
    returns the new state (donation-safe: the pre-chunk snapshot is an
    independent device copy, so the dispatch may consume its input).
    On a shed the chunk's entire result — queue, digests, counters, trace
    ring — is discarded by restoring the snapshot, and the SAME chunk
    re-runs one gear up; the top gear is the full send budget and cannot
    shed, so this terminates. Accepted results are therefore bit-identical
    to a full-width run by construction.

    `rounds0` (the dispatch-entry `stats.rounds`, hybrid driver): when
    given and the dispatch retired ZERO rounds — a guarded window that
    exited immediately on its probe or horizon — the controller is NOT
    fed: an idle window's hwm of 0 says nothing about traffic, and
    counting it would downshift past real occupancy and buy the next busy
    window a guaranteed shed + full-chunk replay.

    Returns (state, accepted_gear, chunk_outbox_hwm). The per-chunk
    `stats.outbox_hwm` is folded into the controller and RESET (a running
    max could never signal a downshift); callers wanting the run-wide
    high-water track the returned value."""
    from shadow_tpu.core.pressure import ResilienceController

    rc = ResilienceController(gearctl=ctl)
    return rc.run_chunk(
        state, lambda s, g, _cap, _budget: dispatch(s, g), rounds0=rounds0
    )
