"""Lane registry: the single source of truth for simulation-lane widths.

Every invariant this engine lives on — bit-identical digests, i64
time/order keys, counter-based RNG purity — depends on lanes keeping
their declared widths. The reference Shadow leans on Rust's type system
for this (SimulationTime is a newtype over u64; a narrowing conversion
does not compile). The JAX port has no static types, so this module
declares the widths once and two enforcement layers read it:

  * shadowlint stage A (tools/lint/astlint.py, rule R2) — pure-AST scan
    of shadow_tpu/core + shadow_tpu/ops + obs/tracer.py flagging
    `.astype(...)` narrowing and implicit-dtype construction of any
    registered lane;
  * the jaxpr audit (tools/lint/jaxpr_audit.py) — traces the round body
    and asserts the actual carry dtypes of `STATE_LANES` match.

The planned SimState "memory diet" (ROADMAP item 1) narrows lanes HERE,
deliberately, and both layers follow — instead of an `astype` somewhere
in the round body silently truncating event times.

IMPORTANT: this module is imported by stage A, which must run without
JAX (the tier-1 pre-stage survives jaxlib corruption that kills compiled
runs). Keep it stdlib-only: names and dtype strings, no jnp.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Terminal lane names -> required width, used by the AST narrowing rule (R2).
# A "terminal name" is the last attribute/variable name of an expression
# (`ev.t` -> "t", `ring.cursor[0]` -> "cursor"). Narrowing a 64-bit lane
# below its registered width, or constructing one without an explicit
# dtype, is a lint error.
# ---------------------------------------------------------------------------

# Simulated-time lanes: int64 nanoseconds (reference SimulationTime).
# i32 ns wraps at ~2.1 sim-seconds; i32 ms would break the deterministic
# (time, order) total key. Never narrow.
TIME_LANES = frozenset({
    "t",
    "now",
    "window_start",
    "window_end",
    "cpu_busy_until",
    "busy_until",
    "min_used_lat",
    "down_t",
    "up_t",
    "win_start",
    "win_end",
    "arrive",
    "depart",
    "eg_depart",
    "next_time",
    "exec_t",
    "t_push",
    "t_cand",
    "resume",
    "lat_ns",
    "jitter_ns",
    # bucketed-queue / timer-wheel block-minimum TIME cache (bt): a min
    # over t entries is itself a time — same 64-bit obligation
    "bt",
})

# Event-ordering lanes: int64 packed (locality, src-host, seq) keys
# (ops/events.py pack_order). The packing uses the full 63 bits; any
# narrowing collides order keys and breaks determinism.
ORDER_LANES = frozenset({"order", "seq", "bo"})

# Monotone counter lanes: int64. A long campaign overflows i32 counters
# (events at 10k hosts pass 2^31 in under an hour of sim time), and the
# trace/flow rings' cursor arithmetic assumes no wrap. The network
# observatory's class/flow/safe-window counters are the same species
# (fl_bytes at 10k flows/s of 100 KiB flows passes 2^31 in minutes).
COUNTER_LANES = frozenset({
    "cursor", "rounds", "microsteps", "events",
    "ec_timer", "ec_pkt", "ec_app",
    "fl_done", "fl_bytes", "fl_rtx", "win_bound",
    # integrity sentinel (core/integrity.py): the psum'd violation
    # count, the per-shard invariant bitmask, and the first-violation
    # round index (-1 = none) — i64 like every control-signal lane
    "integrity", "iv_mask", "iv_round",
    # fluid traffic plane (net/fluid.py): cumulative background bytes
    # delivered / DropTail-dropped — bytes at Gbit-scale demand pass
    # 2^31 in seconds of sim time, so i64 like fl_bytes
    "fl_bg_bytes", "fl_bg_dropped",
})

# Fluid-plane f64 lanes (net/fluid.py FluidState): the per-class carried
# rates and per-link offered utilization the round body's forward-Euler
# step maintains. float64 deliberately — the ODE is replicated global
# math whose drift across shards would break the mesh-shape determinism
# gate; f32 accumulation error at Gbit rates over long horizons is a
# real divergence risk. Never narrow.
FLUID_LANES: dict[str, str] = {
    "rates": "float64",
    "link_util": "float64",
}

# Digest lanes: uint64 (FNV-1a fold, core/engine.py _digest_update;
# digest2 is the integrity sentinel's independently-folded dual lane,
# core/engine.py _digest_update2).
DIGEST_LANES = frozenset({"digest", "digest2"})

# Deliberately-32-bit lanes (ids and per-round cursors bounded by
# construction): narrowing TO these widths is fine, narrowing below is
# not. Kept here so the registry names every engine lane, not only the
# wide ones.
NARROW_LANES = {
    "dst": "int32",
    "kind": "int32",
    "payload": "int32",
    "sent_round": "int32",
    # exchange-wire fill accounting (core/engine.py alltoall/hierarchical
    # paths): per-destination-shard valid-row counts and the hierarchical
    # exchange's fill-counter wire vectors, all bounded by block/slot
    # counts (LANE_MIN_WIDTH_BITS states each bound) — i32 on the wire is
    # the lane diet, and riding them at i64 would silently double the
    # counter tier's ICI bytes
    "seg_len": "int32",
    "sent_counts": "int32",
    "recv_counts": "int32",
    # staging/queue fill counters bounded by slot counts: the outbox
    # append cursor (<= H_local x sends_per_host_round) and the bucketed
    # queue's per-block occupancy (<= queue_block)
    "count": "int32",
    "bfill": "int32",
}

# ---------------------------------------------------------------------------
# Lane diet (ISSUE 17): minimum EXACT width in bits per lane — the
# smallest width at which the lane's full value range provably
# round-trips, independent of the width it is registered at. Two uses:
#
#   * shadowlint rule R7 (tools/lint/schema.py check_lane_diet) asserts
#     every EXCHANGE_WIRE_LANES member has an entry here, that no lane is
#     registered NARROWER than its minimum, and that wire lanes whose
#     minimum is <= 32 are actually registered at 32 (the diet is real —
#     a bounded counter riding the wire at i64 is a silent 2x on
#     `stats.ici_inter`), while wire lanes whose minimum is 64 must be
#     time/order/digest lanes (the only species with a genuine 64-bit
#     range).
#   * the bounds below are the PROOF OBLIGATIONS: each entry names the
#     capacity/slot count that caps the lane. Growing one of those caps
#     past 2^31 must come back here first.
#
# Bounds (all static config values, enforced at EngineConfig build time):
#   dst          host id < num_hosts; ops/events.check_order_limits caps
#                num_hosts far below 2^31 (the packed order key budget)
#   kind         model event-kind enum (single-digit cardinality)
#   payload      i32 words by the EVENT_PAYLOAD_WORDS contract
#   sent_round   <= sends_per_host_round (per-round budget)
#   count        <= hosts_per_shard x sends_per_host_round (outbox slots)
#   bfill        <= queue_block (per-block slot count)
#   seg_len      <= hosts_per_shard x sends_per_host_round (local rows)
#   sent_counts  <= hier_block_size (minimum of seg_len and the block)
#   recv_counts  <= hier_block_size (a peer's sent_counts)
#   t, bt        int64 ns — i32 ns wraps at ~2.1 sim-seconds (TIME_LANES)
#   order, bo    full 63-bit packed (locality, src, seq) key (ORDER_LANES)
#   digest(2)    64-bit FNV state by definition (DIGEST_LANES)
# ---------------------------------------------------------------------------

LANE_MIN_WIDTH_BITS: dict[str, int] = {
    "dst": 32,
    "kind": 32,
    "payload": 32,
    "sent_round": 32,
    "count": 32,
    "bfill": 32,
    "seg_len": 32,
    "sent_counts": 32,
    "recv_counts": 32,
    "t": 64,
    "bt": 64,
    "order": 64,
    "bo": 64,
    "digest": 64,
    "digest2": 64,
}

#: lanes that cross an exchange collective in SOME exchange kind: the
#: gather path all_gathers the (sliced) outbox lanes wholesale; the
#: alltoall and hierarchical paths pack (dst, t, order, kind, payload)
#: into wire blocks; the hierarchical counter tier moves
#: sent_counts/recv_counts. R7's wire-width table is derived from this
#: set x LANE_MIN_WIDTH_BITS (docs/architecture.md reproduces it).
EXCHANGE_WIRE_LANES = frozenset({
    "dst", "t", "order", "kind", "payload", "count",
    "sent_counts", "recv_counts",
})

#: terminal lane name -> required dtype string
LANE_WIDTHS: dict[str, str] = {
    **{n: "int64" for n in TIME_LANES},
    **{n: "int64" for n in ORDER_LANES},
    **{n: "int64" for n in COUNTER_LANES},
    **{n: "uint64" for n in DIGEST_LANES},
    **FLUID_LANES,
    **NARROW_LANES,
}

#: ops helpers whose RETURN value is a lane (the AST rule resolves
#: `q_next_time(q).astype(...)` through this map)
FUNC_RETURN_LANES: dict[str, str] = {
    "q_next_time": "t",
    "next_time": "t",
    "bq_next_time": "t",
    "pack_order": "order",
}

BITS = {
    "bool": 1,
    "int8": 8, "uint8": 8,
    "int16": 16, "uint16": 16,
    "int32": 32, "uint32": 32, "float32": 32,
    "int64": 64, "uint64": 64, "float64": 64,
}


def lane_width_bits(name: str) -> int | None:
    """Registered width in bits for a terminal lane name, else None."""
    dt = LANE_WIDTHS.get(name)
    return BITS[dt] if dt else None


# ---------------------------------------------------------------------------
# SimState carry paths -> required dtype, asserted by the jaxpr audit on
# the TRACED round body (jax.eval_shape of core/engine._run_chunk). Paths
# are dotted attribute chains from SimState. Every Stats counter is also
# required to appear here — stage A rule R3 cross-checks the Stats
# NamedTuple against this dict, so adding a stats field without declaring
# its width fails lint.
# ---------------------------------------------------------------------------

_STATS_I64 = (
    "events", "pkts_sent", "pkts_lost", "pkts_unreachable",
    "pkts_codel_dropped", "pkts_delivered", "monotonic_violations",
    "pkts_budget_dropped", "faults_dropped", "faults_delayed",
    "ob_dropped", "a2a_shed", "microsteps", "bq_rebuilds",
    "popk_deferred", "ici_bytes", "q_occ_hwm", "outbox_hwm",
    "gear_shed", "rounds",
    # hierarchical-exchange tier counters (present only when
    # experimental.exchange: hierarchical on a multi-device mesh): byte
    # accumulators like ici_bytes, i64 for the same no-wrap reason
    "ici_intra", "ici_inter",
)

STATE_LANES: dict[str, str] = {
    "now": "int64",
    "done": "bool",
    "queue.t": "int64",
    "queue.order": "int64",
    "queue.kind": "int32",
    "queue.payload": "int32",
    "queue.dropped": "int64",
    # bucketed-queue cache planes (present only when queue_block > 0)
    "queue.bt": "int64",
    "queue.bo": "int64",
    "queue.bfill": "int32",
    "rng.s": "uint64",
    "seq": "int64",
    "sent_round": "int32",
    "cpu_busy_until": "int64",
    "min_used_lat": "int64",
    "outbox.dst": "int32",
    "outbox.t": "int64",
    "outbox.order": "int64",
    "outbox.kind": "int32",
    "outbox.payload": "int32",
    "outbox.count": "int32",
    "trace.rows": "int64",
    "trace.cursor": "int64",
    **{f"stats.{f}": "int64" for f in _STATS_I64},
    # pressure-abort signal (present only when the pressure policy is
    # escalate/abort — core/pressure.py; the default drop policy carries
    # None here and traces no pressure code)
    "stats.pressure": "int64",
    # network-observatory lanes (obs/netobs.py; present only when
    # observability.network is on — the fl_*/flows planes additionally
    # require an active flow ledger). Event-class counts, flow-ledger
    # totals, safe-window binder counts, and the ledger ring itself.
    "stats.ec_timer": "int64",
    "stats.ec_pkt": "int64",
    "stats.ec_app": "int64",
    "stats.fl_done": "int64",
    "stats.fl_bytes": "int64",
    "stats.fl_rtx": "int64",
    "stats.win_bound": "int64",
    "flows.rows": "int64",
    "flows.cursor": "int64",
    # integrity-sentinel lanes (core/integrity.py; present only when
    # the `integrity:` block enables the guards — the default program
    # carries None here and traces no sentinel code)
    "stats.integrity": "int64",
    "stats.iv_mask": "int64",
    "stats.iv_round": "int64",
    "stats.digest2": "uint64",
    "stats.digest": "uint64",
    # fluid traffic plane (net/fluid.py; present only when the `fluid:`
    # block declares classes — the default program carries None here and
    # traces no fluid code). The ODE carry lanes are replicated f64; the
    # byte counters are replicated i64 scalars (the ODE is global, so a
    # per-shard lane would multiply the total at export).
    "fluid.rates": "float64",
    "fluid.link_util": "float64",
    "stats.fl_bg_bytes": "int64",
    "stats.fl_bg_dropped": "int64",
    # timer-wheel planes (ops/wheel.py; present only when
    # experimental.timer_wheel > 0). The wheel IS the BucketQueue
    # machinery re-aimed at timers, so every wheel lane mirrors its
    # queue.* counterpart's width — WHEEL_LANE_OF_QUEUE below states the
    # pairing and the shadowlint wheel rule enforces the lockstep.
    "wheel.t": "int64",
    "wheel.order": "int64",
    "wheel.kind": "int32",
    "wheel.payload": "int32",
    "wheel.dropped": "int64",
    "wheel.bt": "int64",
    "wheel.bo": "int64",
    "wheel.bfill": "int32",
    "stats.wheel_spilled": "int64",
    "stats.wheel_occ_hwm": "int64",
}

# ---------------------------------------------------------------------------
# Timer-wheel lane pairing (ops/wheel.py): the wheel reuses the bucketed
# queue's slab + cache machinery verbatim, so each wheel.* lane must keep
# the SAME registered width as the queue.* lane the shared ops read and
# write. Narrowing one side but not the other would make the shared ops
# silently reinterpret bits. shadowlint's wheel rule (tools/lint/schema.py
# check_wheel_registry) asserts this dict is total over the wheel.* paths
# and that every pair agrees; the jaxpr audit pins the traced dtypes.
# ---------------------------------------------------------------------------

WHEEL_LANE_OF_QUEUE: dict[str, str] = {
    "wheel.t": "queue.t",
    "wheel.order": "queue.order",
    "wheel.kind": "queue.kind",
    "wheel.payload": "queue.payload",
    "wheel.dropped": "queue.dropped",
    "wheel.bt": "queue.bt",
    "wheel.bo": "queue.bo",
    "wheel.bfill": "queue.bfill",
}

# ---------------------------------------------------------------------------
# Shape formulas for the registered SimState carry paths, consumed by the
# memory observatory (shadow_tpu/obs/memory.py): dtype widths come from
# STATE_LANES above, shapes from here, so the static HBM byte model has
# exactly ONE source to drift from. Dimension tokens (resolved by the
# observatory against a concrete EngineConfig):
#
#   H   hosts per shard (num_hosts / world)
#   C   queue_capacity (per-host event slots)
#   NB  bucket-cache blocks = C // queue_block (planes absent on flat
#       queues — queue_block == 0 drops the queue.bt/bo/bfill entries)
#   P   EVENT_PAYLOAD_WORDS (ops/events.py)
#   SB  sends_per_host_round (outbox columns)
#   S   the per-shard element of a [world]-sharded plane (always 1)
#   R   trace_rounds (ring rows; plane absent when 0)
#   F   len(TRACE_FIELDS) (obs/tracer.py ring columns)
#   FR  flow_records (flow-ledger ring rows; flows planes absent when 0)
#   FF  len(FLOW_FIELDS) (obs/netobs.py ledger columns)
#   WS  wheel_slots (timer-wheel slots per host; wheel planes absent
#       when 0 — the wheel-off carry has no wheel at all)
#   WNB wheel block-cache blocks = WS // resolved wheel block
#   FK  fluid background-traffic classes (net/fluid.py; fluid planes
#       absent when 0 — the fluid-off carry has no fluid at all)
#   FN  fluid links (graph nodes the per-link ODE state covers)
#
# Integer entries are literal dimensions. Stage A stays jax-free: tokens
# only, no imports. tests/test_memory.py asserts this dict covers
# STATE_LANES exactly and that the formula bytes equal the real carry
# leaves' bytes on built engine states (flat/bucketed x trace x pressure).
# ---------------------------------------------------------------------------

_STATS_PER_HOST = (
    "events", "pkts_sent", "pkts_lost", "pkts_unreachable",
    "pkts_codel_dropped", "pkts_delivered", "monotonic_violations",
    "pkts_budget_dropped", "faults_dropped", "faults_delayed", "q_occ_hwm",
)
_STATS_PER_SHARD = (
    "ob_dropped", "a2a_shed", "microsteps", "bq_rebuilds", "popk_deferred",
    "ici_bytes", "outbox_hwm", "gear_shed", "pressure",
    "ec_timer", "ec_pkt", "ec_app", "fl_done", "fl_bytes", "fl_rtx",
    "win_bound", "integrity", "iv_mask", "iv_round",
    "ici_intra", "ici_inter",
)

STATE_LANE_SHAPES: dict[str, tuple] = {
    "now": (),
    "done": (),
    "queue.t": ("H", "C"),
    "queue.order": ("H", "C"),
    "queue.kind": ("H", "C"),
    "queue.payload": ("H", "C", "P"),
    "queue.dropped": ("H",),
    "queue.bt": ("H", "NB"),
    "queue.bo": ("H", "NB"),
    "queue.bfill": ("H", "NB"),
    "rng.s": ("H", 4),
    "seq": ("H",),
    "sent_round": ("H",),
    "cpu_busy_until": ("H",),
    "min_used_lat": (),
    "outbox.dst": ("H", "SB"),
    "outbox.t": ("H", "SB"),
    "outbox.order": ("H", "SB"),
    "outbox.kind": ("H", "SB"),
    "outbox.payload": ("H", "SB", "P"),
    "outbox.count": ("S",),
    "trace.rows": ("S", "R", "F"),
    "trace.cursor": ("S",),
    "flows.rows": ("S", "FR", "FF"),
    "flows.cursor": ("S",),
    **{f"stats.{f}": ("H",) for f in _STATS_PER_HOST},
    **{f"stats.{f}": ("S",) for f in _STATS_PER_SHARD},
    "stats.digest": ("H",),
    "stats.digest2": ("H",),
    "stats.rounds": (),
    "wheel.t": ("H", "WS"),
    "wheel.order": ("H", "WS"),
    "wheel.kind": ("H", "WS"),
    "wheel.payload": ("H", "WS", "P"),
    "wheel.dropped": ("H",),
    "wheel.bt": ("H", "WNB"),
    "wheel.bo": ("H", "WNB"),
    "wheel.bfill": ("H", "WNB"),
    "stats.wheel_spilled": ("H",),
    "stats.wheel_occ_hwm": ("H",),
    # fluid plane (net/fluid.py): replicated global ODE state + the
    # replicated scalar byte counters (shape () like stats.rounds)
    "fluid.rates": ("FK",),
    "fluid.link_util": ("FN",),
    "stats.fl_bg_bytes": (),
    "stats.fl_bg_dropped": (),
}

# ---------------------------------------------------------------------------
# Stats fields that are deliberately NOT exported in sim-stats.json
# (rule R3 requires every Stats field to be either read by
# shadow_tpu/sim.py stats_report or listed here with a reason).
# ---------------------------------------------------------------------------

_NETOBS_EXPORT_REASON = (
    "exported through the sim-stats network{} block assembled by "
    "obs/netobs.assemble_network_report (the ONE shared helper sim.py, "
    "cosim.py, and bench.py all call — it reads the lane directly so "
    "the block's shape cannot drift between exporters); gated on "
    "observability.network, None otherwise"
)

STATS_EXPORT_EXEMPT: dict[str, str] = {
    **{f: _NETOBS_EXPORT_REASON for f in (
        "ec_timer", "ec_pkt", "ec_app",
        "fl_done", "fl_bytes", "fl_rtx", "win_bound",
    )},
    **{f: (
        "exported through the sim-stats fluid{} block assembled by "
        "net/fluid.assemble_fluid_report (the ONE shared helper sim.py "
        "and bench.py both call — it reads the lane directly so the "
        "block's shape cannot drift between exporters); gated on the "
        "fluid: block declaring classes, None otherwise"
    ) for f in ("fl_bg_bytes", "fl_bg_dropped")},
    "gear_shed": (
        "transient gear-abort control signal: a shedding chunk is "
        "discarded and replayed from its pre-chunk snapshot, so the "
        "counter is structurally zero in any accepted final state; the "
        "gears{} block in sim-stats carries the replay accounting"
    ),
    "pressure": (
        "transient pressure-abort control signal (core/pressure.py): a "
        "dropping chunk is discarded and replayed at a grown shape "
        "(escalate) or the run stops (abort), so the counter is "
        "structurally zero in any escalate-accepted final state and "
        "redundant with the per-category drop counters otherwise; the "
        "pressure{} block in sim-stats carries the regrow/replay "
        "accounting"
    ),
    **{f: (
        "transient integrity-abort control signal (core/integrity.py): "
        "a violating chunk is discarded and replayed from its pre-chunk "
        "snapshot (transient SDC) or the run stops (IntegrityAbort), so "
        "the lanes are structurally zero/-1 in any accepted final "
        "state; the integrity{} block in sim-stats carries the "
        "transient/replay accounting and the deterministic-violation "
        "naming"
    ) for f in ("integrity", "iv_mask", "iv_round")},
}

# ---------------------------------------------------------------------------
# Heartbeat-format registry (rule R5). Keys that older emitters produced
# but no current code path emits — the parser must keep matching them so
# recorded logs stay parseable. (`windows=` is still live: the hybrid
# cosim driver emits it.)
# ---------------------------------------------------------------------------

HEARTBEAT_LEGACY_KEYS: frozenset[str] = frozenset()
