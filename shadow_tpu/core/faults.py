"""Deterministic in-sim fault injection: schedule compilation + jit helpers.

The reference simulates a *healthy* network; adversarial conditions (host
crashes, lossy windows, latency spikes) had to be baked into the graph or
the workload. This module adds a first-class fault plane (ISSUE 5; COREC in
PAPERS.md makes the same robustness-as-design-axis argument for receive
drivers): a `faults:` config block compiles — at build time, on the host —
into a small set of device arrays (`FaultParams`) that the jitted round
body consults:

  * per-host up/down windows (`down_t`/`up_t`, i64[H, W]): a host is DOWN
    while any window contains the current event time. Down hosts execute
    nothing; what happens to their pending events is the static
    `restart_queue` policy — "hold" defers them to the restart time
    (exactly the CPU-model busy-horizon mechanics, host.rs:820-847),
    "clear" discards every event whose execution time falls inside a down
    window (counted in `stats.faults_dropped`, never silent). Events
    scheduled past the restart survive either way — a full queue wipe
    would leave self-timed models (phold, timers) permanently silent,
    which is a dead lane, not a crash-restart.
  * link-fault windows (`win_start`/`win_end`, i64[L] + per-window loss
    probability and latency multiplier): while a window is active, every
    send draws one extra per-host loss uniform from the engine's
    counter-based RNG lanes (`ops/rng.py`, masked advance — so the draw
    sequence depends only on the sending host's own history and results
    are bit-identical across mesh shapes) and surviving packets have
    their path latency multiplied by `latency_factor` (>= 1.0: inflation
    can only grow latency, so the conservative-lookahead bound — which
    uses the pre-inflation minimum — stays valid). Fault loss and
    latency inflation both honor `general.bootstrap_end_time` exactly
    like path loss: disabled before it. Drops count into
    `stats.faults_dropped`, delays into `stats.faults_delayed`.

Determinism: the schedule itself is a pure function of (fault seed,
host id, draw counter) through the same splitmix64 recipe `ops/rng.py`
seeds with, evaluated host-side in numpy at build time — two runs with the
same seed get byte-identical `FaultParams`, and the in-jit draws use the
per-host masked-advance lanes, so the digest contract is: same fault seed
=> same digest, across reruns AND across mesh shapes AND across a mid-run
snapshot/restore (tests/test_faults.py is the gate). With the block absent
the engine traces none of this in and stays bit-identical to the
fault-free program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import numpy as np

from shadow_tpu.simtime import TIME_MAX

# latency multipliers are carried as parts-per-thousand integers so the
# inflation is pure i64 math in-jit (float scaling could round differently
# across backends and break the cross-platform determinism scope note)
LAT_SCALE = 1000


class FaultParams(NamedTuple):
    """Device-side fault schedule (EngineParams.faults). Crash fields are
    None when no host ever crashes (W = 0); window fields are None when no
    link-fault window exists (L = 0) — the engine gates each feature on
    the matching static dim so absent features trace to nothing."""

    down_t: Any  # i64[H, W] crash times (TIME_MAX = unused slot) | None
    up_t: Any  # i64[H, W] restart times | None
    win_start: Any  # i64[L] link-fault window starts | None
    win_end: Any  # i64[L] | None
    win_loss: Any  # f32[L] extra loss probability while active | None
    win_lat: Any  # i64[L] latency multiplier x1000 (1000 = 1.0x) | None


@dataclasses.dataclass(frozen=True)
class FaultSchedule:
    """compile_faults result: the static dims the EngineConfig needs plus
    the compiled arrays."""

    crash_windows: int  # W (0 = no crash plumbing traced in)
    loss_windows: int  # L (0 = no link-fault plumbing traced in)
    queue_clear: bool  # restart_queue == "clear"
    params: FaultParams | None  # None when nothing is scheduled

    @property
    def active(self) -> bool:
        return self.crash_windows > 0 or self.loss_windows > 0


# ---------------------------------------------------------------- RNG
# Counter-based draws, numpy mirror of ops/rng.py's splitmix64 seeding:
# u64(seed, host, ctr) is a pure function of its inputs — no sequential
# state — so the compiled schedule cannot depend on iteration order.

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_HOST_STRIDE = np.uint64(0xD1342543DE82EF95)  # same stride rng_init uses
_CTR_STRIDE = np.uint64(0xA0761D6478BD642F)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    x = (x + _GOLDEN).astype(np.uint64)
    z = x
    z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(
        np.uint64
    )
    z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(
        np.uint64
    )
    return (z ^ (z >> np.uint64(31))).astype(np.uint64)


def fault_u64(seed: int, host, ctr) -> np.ndarray:
    """Counter-based u64 draw: pure in (seed, host, ctr)."""
    host = np.asarray(host, np.uint64)
    ctr = np.asarray(ctr, np.uint64)
    x = (np.uint64(seed & (2**64 - 1)) + host * _HOST_STRIDE
         + ctr * _CTR_STRIDE).astype(np.uint64)
    return _splitmix64(_splitmix64(x))


def fault_uniform(seed: int, host, ctr) -> np.ndarray:
    """float64 in [0, 1): top 53 bits of the counter draw."""
    return (fault_u64(seed, host, ctr) >> np.uint64(11)).astype(
        np.float64
    ) * (1.0 / (1 << 53))


# ---------------------------------------------------------------- compile


def _merge_windows(wins: list[tuple[int, int]]) -> list[tuple[int, int]]:
    """Sort + coalesce overlapping/touching [down, up) windows per host —
    the in-jit containment test assumes disjoint windows (the resume time
    is the up of THE window containing t)."""
    out: list[tuple[int, int]] = []
    for d, u in sorted(wins):
        if out and d <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], u))
        else:
            out.append((d, u))
    return out


def compile_faults(
    fopts,
    *,
    num_hosts: int,
    num_real: int | None = None,
    stop_time: int,
    bootstrap_end: int = 0,
    default_seed: int = 1,
    name_to_id: dict[str, int] | None = None,
) -> FaultSchedule:
    """FaultOptions -> FaultSchedule. Host-side, numpy, deterministic in
    the fault seed. `num_hosts` is the engine's (possibly mesh-padded)
    lane count; churn draws run over the `num_real` prefix only, so the
    schedule is invariant to mesh padding (like the model builders)."""
    import jax.numpy as jnp

    num_real = num_hosts if num_real is None else num_real
    if fopts.restart_queue not in ("hold", "clear"):
        # FaultOptions.from_dict validates the YAML path; this catches the
        # CLI-override path (merge_cli_overrides setattr's fields raw) —
        # an unknown policy must not silently degrade to "hold"
        raise ValueError(
            f"restart_queue must be hold|clear, got {fopts.restart_queue!r}"
        )
    seed = default_seed if fopts.seed is None else fopts.seed
    per_host: list[list[tuple[int, int]]] = [[] for _ in range(num_hosts)]

    # explicit crash entries (host by id or name)
    for c in fopts.crashes:
        hid = c.host
        if isinstance(hid, str):
            if name_to_id is None or hid not in (name_to_id or {}):
                raise ValueError(f"faults.crashes: unknown host {hid!r}")
            hid = name_to_id[hid]
        hid = int(hid)
        if not 0 <= hid < num_real:
            raise ValueError(
                f"faults.crashes: host id {hid} out of range [0, {num_real})"
            )
        if c.up_at <= c.down_at:
            raise ValueError(
                f"faults.crashes: up_at {c.up_at} <= down_at {c.down_at}"
            )
        per_host[hid].append((int(c.down_at), int(c.up_at)))

    # seeded churn: each real host crashes once with probability `prob`,
    # at a uniform time in [bootstrap_end, stop), down for an exponential
    # draw around mean_downtime (floored at 1 ms so a restart is distinct
    # from the crash)
    ch = fopts.host_churn
    if ch is not None and ch.prob > 0 and num_real > 0:
        hosts = np.arange(num_real)
        hit = fault_uniform(seed, hosts, 0) < ch.prob
        span = max(stop_time - bootstrap_end, 1)
        down_at = bootstrap_end + (
            fault_uniform(seed, hosts, 1) * span
        ).astype(np.int64)
        # inverse-CDF exponential; u is bounded away from 1 so log is finite
        u = np.minimum(fault_uniform(seed, hosts, 2), 1.0 - 2**-53)
        downtime = np.maximum(
            (-np.log1p(-u) * ch.mean_downtime).astype(np.int64), 1_000_000
        )
        for h in np.nonzero(hit)[0]:
            per_host[int(h)].append(
                (int(down_at[h]), int(down_at[h] + downtime[h]))
            )

    merged = [_merge_windows(w) for w in per_host]
    w_max = max((len(w) for w in merged), default=0)

    lws = list(fopts.loss_windows)
    for lw in lws:
        if not 0.0 <= lw.loss <= 1.0:
            raise ValueError(f"faults.loss_windows: loss {lw.loss} not in [0, 1]")
        if lw.latency_factor < 1.0:
            raise ValueError(
                f"faults.loss_windows: latency_factor {lw.latency_factor} < 1.0 "
                f"(deflation would break the conservative-lookahead bound)"
            )
        if lw.end <= lw.start:
            raise ValueError(
                f"faults.loss_windows: end {lw.end} <= start {lw.start}"
            )

    if w_max == 0 and not lws:
        return FaultSchedule(0, 0, fopts.restart_queue == "clear", None)

    if w_max:
        down = np.full((num_hosts, w_max), TIME_MAX, np.int64)
        up = np.full((num_hosts, w_max), TIME_MAX, np.int64)
        for h, wins in enumerate(merged):
            for i, (d, u_) in enumerate(wins):
                down[h, i] = d
                up[h, i] = u_
        down_t, up_t = jnp.asarray(down, jnp.int64), jnp.asarray(up, jnp.int64)
    else:
        down_t = up_t = None

    if lws:
        win_start = jnp.asarray([int(w.start) for w in lws], jnp.int64)
        win_end = jnp.asarray([int(w.end) for w in lws], jnp.int64)
        win_loss = jnp.asarray([float(w.loss) for w in lws], jnp.float32)
        win_lat = jnp.asarray(
            [int(round(w.latency_factor * LAT_SCALE)) for w in lws], jnp.int64
        )
    else:
        win_start = win_end = win_loss = win_lat = None

    return FaultSchedule(
        crash_windows=w_max,
        loss_windows=len(lws),
        queue_clear=fopts.restart_queue == "clear",
        params=FaultParams(
            down_t=down_t, up_t=up_t,
            win_start=win_start, win_end=win_end,
            win_loss=win_loss, win_lat=win_lat,
        ),
    )


# ---------------------------------------------------------------- jit side


def down_and_resume(fp: FaultParams, t):
    """Per-host down mask + restart floor at times `t` (i64[H]).

    Returns (down[H] bool, resume[H] i64) with resume = the containing
    window's up time where down, 0 elsewhere — so callers can fold it into
    an execution-time floor with a plain `maximum` (the same shape the CPU
    model's busy_until floor takes)."""
    import jax.numpy as jnp

    in_w = (fp.down_t <= t[:, None]) & (t[:, None] < fp.up_t)  # [H, W]
    down = jnp.any(in_w, axis=1)
    resume = jnp.min(jnp.where(in_w, fp.up_t, TIME_MAX), axis=1)
    return down, jnp.where(down, resume, jnp.int64(0))


def window_effects(fp: FaultParams, t):
    """Link-fault effects active at per-host times `t` (i64[H]).

    Returns (loss[H] f32, lat_x1000[H] i64): the max loss probability and
    max latency multiplier over active windows (max, not product — the
    windows model alternative severities of one underlying fault, and max
    keeps the draw count at exactly one per send)."""
    import jax.numpy as jnp

    act = (fp.win_start[None, :] <= t[:, None]) & (
        t[:, None] < fp.win_end[None, :]
    )  # [H, L]
    loss = jnp.max(
        jnp.where(act, fp.win_loss[None, :], jnp.float32(0.0)), axis=1
    )
    lat = jnp.max(
        jnp.where(act, fp.win_lat[None, :], jnp.int64(LAT_SCALE)), axis=1
    )
    return loss, jnp.maximum(lat, LAT_SCALE)
