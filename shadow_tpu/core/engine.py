"""The conservative-PDES round engine, entirely on device.

One *round* advances every host through the safe window
[window_start, window_end):

  1. barrier: global min next-event time over the mesh (`lax.pmin` — the
     device form of the per-thread min reduction at reference
     manager.rs:459-464 + controller.rs:88-112);
  2. window_end = min(global_min + runahead, stop_time) where runahead is the
     minimum network latency, optionally shrinking dynamically
     (reference core/runahead.rs:44-57);
  3. microsteps: while any local host has an event < window_end, every host
     pops its earliest event (deterministic total order) and one vectorized
     model dispatch executes for all active hosts (Host::execute,
     host.rs:809-864). Packet arrivals pass ingress shaping (downlink token
     bucket + CoDel) first; sends pass egress shaping and are staged in the
     shard-local outbox;
  4. exchange: outboxes all-gather across the mesh and merge into destination
     queues with the deterministic sorted scatter (the lock-free replacement
     for worker.rs:644-654's per-host mutex push). Conservative lookahead
     guarantees every cross-host packet arrives >= window_end, which is what
     makes the once-per-round exchange exact, not an approximation.

Microstep loops have NO collectives, so shards run them at their own pace;
rounds are the only synchronization points — exactly the reference's
"hosts are the unit of parallelism" invariant (scheduler/src/lib.rs:3-6).

Determinism: pops follow the packed (time, order) key; RNG advances are
per-host masked; the cross-shard merge sorts by (dst, time, order); integer
scatter-adds are order-free. Result: per-host event digests are bit-identical
across runs AND across mesh shapes (the device analogue of the reference's
determinism gate, src/test/determinism/). Scope note: bit-equality across
*platforms* (TPU vs CPU) holds for the integer engine core and integer-only
models, but models using float transcendentals (e.g. PHOLD's exponential
draw) may diverge across backends — the reference likewise promises identical
re-runs on one machine, not cross-machine equality.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array, lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shadow_tpu.models.base import (
    HandlerCtx,
    KIND_INGRESS_DONE,
    KIND_MASK,
    KIND_PKT,
    PAYLOAD_SIZE_WORD,
)
from shadow_tpu.net import (
    TBParams,
    TBState,
    codel_init,
    codel_on_packet,
    tb_conforming_remove,
    tb_init,
)
from shadow_tpu.ops import (
    BucketQueue,
    EventQueue,
    ORDER_MAX,
    block_minima,
    bucket_rebuild,
    as_flat,
    check_order_limits,
    merge_flat_events,
    pack_order,
    q_clear_popped,
    q_head,
    q_len,
    q_next_time,
    q_pop_k,
    q_pop_min,
    q_push_many,
)
from shadow_tpu.ops.wheel import (
    wheel_free,
    wheel_next_time,
    wheel_pop_min,
    wheel_push_many,
)
from shadow_tpu.obs.tracer import (
    COL_A2A_SHED,
    COL_BQ_REBUILDS,
    COL_EVENTS,
    COL_GEAR,
    COL_ICI_BYTES,
    COL_MICROSTEPS,
    COL_NEXT_TIME,
    COL_OB_HWM,
    COL_OCC_HWM,
    COL_POPK_DEFERRED,
    COL_ROUND,
    COL_SENDS,
    COL_WINDOW_END,
    COL_WINDOW_START,
    TRACE_COLS,
    TraceRing,
    make_trace_ring,
)
from shadow_tpu.obs.tracer import (
    COL_CAP,
    COL_FAULTS_DELAYED,
    COL_FAULTS_DROPPED,
    COL_HOSTS_DOWN,
)
from shadow_tpu.obs.tracer import (
    COL_BIND_SHARD,
    COL_EC_APP,
    COL_EC_PKT,
    COL_EC_TIMER,
    COL_FLOWS,
    COL_XW_INTER,
    COL_XW_INTRA,
)
from shadow_tpu.obs.netobs import FlowLedger, make_flow_ledger
from shadow_tpu.net.fluid import (
    FluidParams,
    FluidState,
    fluid_advance,
    fluid_host_effects,
    fluid_send_uniform,
    make_fluid_state,
)
from shadow_tpu.ops.events import kind_in
from shadow_tpu.core.faults import (
    FaultParams,
    LAT_SCALE,
    down_and_resume,
    window_effects,
)
from shadow_tpu.ops.events import unpack_order_src
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS, Event
from shadow_tpu.ops.rng import RngState, rng_init, rng_uniform
from shadow_tpu.simtime import TIME_MAX

AXIS = "hosts"  # mesh axis name for the host dimension


# the jax<0.5 shard_map shim lives in core/compat.py (shared with the
# co-simulation bridge); the old private name stays importable here
from shadow_tpu.core.compat import shard_map_compat as _shard_map

_FNV_PRIME = jnp.uint64(1099511628211)
_MIX1 = jnp.uint64(0x9E3779B97F4A7C15)
_MIX2 = jnp.uint64(0xC2B2AE3D27D4EB4F)
_FNV_OFFSET = jnp.uint64(0xCBF29CE484222325)
# dual-digest fold constants (integrity sentinel, core/integrity.py):
# no constant shared with the primary fold, and the mix roles of t and
# order are swapped, so the two planes cannot agree by accident
from shadow_tpu.core.integrity import DIGEST2_OFFSET, DIGEST2_PRIME

_DIGEST2_OFFSET = jnp.uint64(DIGEST2_OFFSET)
_DIGEST2_PRIME = jnp.uint64(DIGEST2_PRIME)
_MIX3 = jnp.uint64(0xD6E8FEB86659FD93)
_MIX4 = jnp.uint64(0xA0761D6478BD642F)


class Outbox(NamedTuple):
    """Per-host staging lanes for this round's outgoing packets.

    Layout is [H, B] with B = `sends_per_host_round`: host h's k-th send of
    the round lands in row h, column k (`sent_round` is the column cursor).
    This makes the append a one-hot masked write — no scatter (TPU scatters
    into the old flat [OB] buffer were a measured hot spot) — and makes the
    flattened exchange order host-major, i.e. invariant to mesh shape and
    microstep interleaving by construction."""

    dst: Array  # i32[H, B] global destination host id
    t: Array  # i64[H, B] arrival time (>= window_end); TIME_MAX = empty
    order: Array  # i64[H, B]
    kind: Array  # i32[H, B]
    payload: Array  # i32[H, B, P]
    count: Array  # i32[1] entries appended this round (per shard)


class Stats(NamedTuple):
    """Device-side counters (reference: tracker.c per-host counters +
    sim_stats.rs global counters + the determinism digest)."""

    events: Array  # i64[H] events processed
    pkts_sent: Array  # i64[H]
    pkts_lost: Array  # i64[H] random path loss
    pkts_unreachable: Array  # i64[H] no route to dst
    pkts_codel_dropped: Array  # i64[H] (charged to the receiving host)
    pkts_delivered: Array  # i64[H]
    monotonic_violations: Array  # i64[H] pushes scheduled in the past
    pkts_budget_dropped: Array  # i64[H] over the per-host round send budget
    # fault plane (core/faults.py): events/packets discarded by an injected
    # fault — queue-clear crash drops (charged to the down host) plus
    # link-fault-window packet loss (charged to the sender). Distinct from
    # pkts_lost so a faulty run's excess loss is attributable.
    faults_dropped: Array  # i64[H]
    # events deferred to a crash restart (queue-hold) plus packets whose
    # latency a fault window inflated (charged to the sender)
    faults_delayed: Array  # i64[H]
    ob_dropped: Array  # i64[1] outbox-overflow losses (invariant check: always 0)
    a2a_shed: Array  # i64[1] all-to-all block-overflow losses (size blocks so 0)
    microsteps: Array  # i64[1] total microsteps (per shard)
    bq_rebuilds: Array  # i64[1] wholesale block-cache rebuilds (bucketed queue)
    popk_deferred: Array  # i64[1] K-way batch events peeked but deferred
    ici_bytes: Array  # i64[1] exchange-collective bytes moved per shard
    # per-host queue-occupancy high-water mark, sampled once per round
    # after the exchange merge (the post-merge peak — the fullest the slab
    # gets before the next round's pops drain it). Pure observation: reads
    # the queue, feeds nothing back (tracker.c's per-host gauges analogue).
    q_occ_hwm: Array  # i64[H]
    # outbox-send high-water: the most sends any ONE host staged in a
    # single round (the [H, B] outbox's column high-water), sampled
    # pre-exchange every round. Always on; the gear controller reads it
    # between chunks to pick the next merge gear (and resets it per chunk
    # so the signal tracks recent rounds, not the whole run).
    outbox_hwm: Array  # i64[world]
    # gear-shed detector: cumulative count of sends beyond the active
    # merge gear's column width (psum'd across the mesh inside the
    # exchange, so every shard carries the GLOBAL count and the chunk
    # loop's abort condition stays uniform). Structurally zero at full
    # width. A nonzero per-chunk delta means the sliced merge lost
    # entries: the driver discards the chunk, restores the pre-chunk
    # snapshot, and replays one gear up — accepted chunks always carry a
    # zero delta, which is what keeps gear-ladder runs bit-identical to
    # the full-width engine.
    gear_shed: Array  # i64[world]
    digest: Array  # u64[H] rolling per-host event-order digest
    rounds: Array  # i64[] scheduling rounds completed (replicated)
    # pressure-abort signal (core/pressure.py; None unless the pressure
    # policy is escalate/abort — the default `drop` policy traces no
    # pressure code and keeps the program bit-identical to before the
    # pressure plane existed). Cumulative GLOBAL count of capacity drops
    # (queue-push overflow, merge/a2a/outbox sheds, send-budget drops),
    # psum'd across the mesh inside the round like gear_shed, so the
    # chunk loop's first-drop abort condition is uniform on every shard.
    # Structurally zero in any state an escalate run accepts.
    pressure: Any = None  # i64[world] | None
    # Network observatory lanes (obs/netobs.py; None unless cfg.netobs —
    # the default program carries none of them and stays byte-identical).
    # Event-class accounting: executed events bucketed as timer (the
    # model's declared timer_kinds), packet (KIND_PKT flag), or app (the
    # rest). ec_timer + ec_pkt + ec_app == sum(events) by construction —
    # the reconciliation tests/net_report.py --check pin.
    ec_timer: Any = None  # i64[world] | None
    ec_pkt: Any = None  # i64[world] | None
    ec_app: Any = None  # i64[world] | None
    # Flow-ledger totals (None unless cfg.flow_ledger_active): cumulative
    # completions/bytes/retransmits counted INDEPENDENTLY of the ring
    # cursor path, so ledger-vs-counters reconciliation is a real check
    # and stays exact across ring wraps.
    fl_done: Any = None  # i64[world] | None
    fl_bytes: Any = None  # i64[world] | None
    fl_rtx: Any = None  # i64[world] | None
    # Safe-window telemetry (None unless cfg.netobs): rounds where THIS
    # shard's local min event time bound the all-reduce-min barrier
    # (ties to the lowest shard id) — the critical-path/straggler view.
    win_bound: Any = None  # i64[world] | None
    # Integrity sentinel lanes (core/integrity.py; None unless
    # cfg.integrity — the default program traces zero sentinel code and
    # stays byte-identical). `integrity` is the psum'd GLOBAL cumulative
    # violation count (the chunk loop's mesh-uniform first-violation
    # abort signal, same mechanism as gear_shed/pressure); `iv_mask` is
    # the PER-SHARD bitwise-OR of violated invariant bits (bit positions
    # in core/integrity.IV_NAMES) and `iv_round` the per-shard round
    # index of the first local violation (-1 = none) — together the
    # (shard, round, mask) reproduction signature the replay classifier
    # compares. Structurally zero/-1 in any accepted final state: a
    # violating chunk always aborts and is replayed or the run stops.
    integrity: Any = None  # i64[world] | None
    iv_mask: Any = None  # i64[world] | None
    iv_round: Any = None  # i64[world] | None
    # Dual digest (None unless cfg.integrity_dual): a second,
    # independently-folded per-host event digest sharing NO constants
    # with the primary FNV fold, so a scribble on one digest plane is
    # detectable by cross-checking the two (core/integrity.
    # classify_digest_pair) instead of silently reporting a wrong digest.
    digest2: Any = None  # u64[H] | None
    # Timer-wheel lanes (ops/wheel.py; None unless cfg.wheel_active —
    # the default program carries neither and stays byte-identical).
    # `wheel_spilled` counts timer pushes diverted to the event queue
    # because the wheel was full (spill-to-queue semantics: never a
    # loss, but a sizing signal — sweep tools/bench_wheel.py);
    # `wheel_occ_hwm` is the per-host wheel-occupancy high-water,
    # sampled once per round like q_occ_hwm.
    wheel_spilled: Any = None  # i64[H] | None
    wheel_occ_hwm: Any = None  # i64[H] | None
    # Fluid traffic plane (net/fluid.py; None unless cfg.fluid_active —
    # the default program carries neither and stays byte-identical).
    # Cumulative background bytes the fluid ODE delivered / DropTail-
    # dropped, as REPLICATED i64 scalars (shape (), like stats.rounds):
    # the ODE is global, computed identically on every shard from psum'd
    # inputs, so a per-shard lane would multiply the total at export.
    fl_bg_bytes: Any = None  # i64[] | None
    fl_bg_dropped: Any = None  # i64[] | None
    # Hierarchical-exchange tier accounting (None unless cfg.hier_active —
    # the flat-exchange program carries neither and stays byte-identical).
    # `ici_intra` charges the INTRA-shard compaction tier (the local
    # (dshard, t, order) sort's staging bytes: the gear-sliced outbox rows
    # repacked into per-destination-shard prefixes — HBM traffic, not
    # wire); `ici_inter` charges the INTER-shard tier (the alltoall blocks
    # plus the i32 fill-counter word per peer — the actual ICI wire).
    # `stats.ici_bytes` keeps its meaning ("exchange-collective bytes")
    # and carries only the inter tier on hierarchical runs, so the
    # counter == model x rounds dryrun assertion stays uniform across
    # exchange kinds.
    ici_intra: Any = None  # i64[world] | None
    ici_inter: Any = None  # i64[world] | None


class SimState(NamedTuple):
    now: Array  # i64[] completed-up-to time (replicated)
    done: Array  # bool[] (replicated)
    queue: EventQueue
    rng: RngState
    seq: Array  # i64[H] per-host emission counter (order-key seq)
    sent_round: Array  # i32[H] sends staged this round (budget accounting)
    cpu_busy_until: Array  # i64[H] CPU model: host busy below this time
    tb_egress: TBState
    tb_ingress: TBState
    codel: Any  # CodelState
    min_used_lat: Array  # i64[] min latency seen (dynamic runahead)
    model: Any  # model state pytree
    outbox: Outbox
    stats: Stats
    # device-resident round tracer (obs/tracer.py): None unless
    # cfg.trace_rounds > 0. The ring is written inside the jitted round
    # loop and drained by the driver at chunk boundaries; it observes the
    # round's own values and feeds nothing back, so enabling it cannot
    # change digests, events, or drop counters.
    trace: Any = None  # TraceRing | None
    # flow-completion ledger (obs/netobs.py): None unless
    # cfg.flow_ledger_active. Same contract as the trace ring — written
    # in-jit at model flow completion (the FlowDone port), drained at
    # chunk boundaries, observes values the handler already computed,
    # feeds nothing back into scheduling.
    flows: Any = None  # FlowLedger | None
    # device-resident timer wheel (ops/wheel.py): None unless
    # cfg.wheel_active. A per-host [H, S] calendar slab (BucketQueue
    # machinery) holding the model's timer events (timer_kinds) so they
    # never occupy event-queue slots or feed the exchange-merge's free
    # ranking; the microstep pops the (time, order) minimum of
    # queue ∪ wheel, so dispatch order is bit-identical to wheel-off
    # (tests/test_wheel.py is the gate).
    wheel: Any = None  # TimerWheel | None
    # fluid traffic plane (net/fluid.py): None unless cfg.fluid_active.
    # The background-flow ODE's carry lanes (per-class carried rates +
    # per-link offered utilization), advanced once per round inside the
    # round body; replicated across the mesh (the ODE is global math
    # over psum'd foreground byte counts, identical on every shard).
    fluid: Any = None  # FluidState | None


class EngineParams(NamedTuple):
    """Immutable per-sim arrays. Sharding: per-host arrays (bucket params,
    model params) shard over the mesh; the routing tables (node_of, lat, loss)
    are replicated — packet sends need arbitrary dst lookups. Dense node×node
    tables bound graph size (~2k nodes ≈ 32 MiB); hosts-per-node is unbounded.
    """

    node_of: Array  # i32[H_total] host -> graph node (replicated)
    lat_ns: Array  # i64[N, N] path latency; <0 = unreachable (replicated)
    loss: Array  # f32[N, N] path loss probability (replicated)
    jitter_ns: Array  # i64[N, N] path jitter amplitude (replicated)
    eg_tb: TBParams  # uplink buckets (sharded per host)
    in_tb: TBParams  # downlink buckets (sharded per host)
    model: Any  # model param pytree (sharded per host)
    # per-host ROW views of the path tables, built by init_state for
    # multi-node graphs (r4, VERDICT r3 weak #1): lat_rows[h] =
    # lat_ns[node_of[h]]. Measured on v5e: data-dependent gathers are the
    # multi-node egress cost and are scalar-core bound (uniform indices
    # time the same as divergent; packing the three tables into one
    # 3-wide slice gather is 2x WORSE). The rows are therefore consumed
    # by a one-hot masked REDUCTION over the node axis — pure vector work
    # on the VPU, no gather at all — leaving node_of[dst] as the single
    # gather per send. Sharded over hosts; None on single-node graphs
    # (the (1,1) broadcast path) where rows would only waste HBM at the
    # 1M-host point.
    lat_rows: Any = None  # i64[H_total, N] | None
    loss_rows: Any = None  # f32[H_total, N] | None
    jit_rows: Any = None  # i64[H_total, N] | None
    # compiled fault schedule (core/faults.py FaultParams): per-host crash
    # windows sharded over the mesh, link-fault windows replicated. None
    # when the `faults:` block is absent — the engine then traces no fault
    # code at all and the program is bit-identical to the fault-free build.
    faults: Any = None  # FaultParams | None
    # compiled fluid schedule (net/fluid.py FluidParams): per-class
    # zones/demand/windows + per-link capacity, all replicated (classes
    # and links are global). None when the `fluid:` block declares no
    # classes — the engine then traces no fluid code at all.
    fluid: Any = None  # FluidParams | None


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Static (trace-time) configuration."""

    num_hosts: int
    stop_time: int
    bootstrap_end_time: int = 0
    runahead_floor: int = 1_000_000  # 1 ms (reference runahead.rs default)
    static_min_latency: int = 1_000_000
    use_dynamic_runahead: bool = False
    tb_interval_ns: int = 1_000_000  # token bucket refill quantum (1 ms)
    use_codel: bool = True
    # Static shaping skip: when NO host has a bandwidth limit, unlimited
    # token buckets never delay (depart == arrival) and CoDel's sojourn is
    # always 0 (never drops), so the whole ingress/egress shaping pipeline
    # is an exact no-op — eliding it at trace time removes ~40% of the
    # microstep's ops with bit-identical results (digests unchanged).
    shaping: bool = True
    # Cheap overflow-shed: the exchange merge groups by destination with a
    # 2xi32 sort (append-order shed) instead of the 3-key urgency sort —
    # identical results whenever queues never overflow (see
    # ops/merge.py merge_flat_events). Opt-in for sized workloads.
    cheap_shed: bool = False
    # Per-packet latency jitter (graph edges carry a `jitter` amplitude):
    # statically elided when no edge has jitter so jitter-free sims draw no
    # extra RNG (digest stability).
    use_jitter: bool = False
    # CPU model (reference host/cpu.rs + host.rs:820-847): every handled
    # event charges `cpu_delay_ns` of simulated CPU time; events that pop
    # while the host CPU is still busy are deferred to busy_until instead of
    # executing. 0 = off (statically elided).
    cpu_delay_ns: int = 0
    queue_capacity: int = 64
    # Two-level bucketed event queue (ops/events.py BucketQueue): split the
    # capacity axis into queue_capacity/queue_block blocks and carry
    # incrementally-maintained per-block (min-time, min-order, fill) caches
    # so the microstep's pop/push reductions run over [H, C/B] + [H, B]
    # instead of the whole [H, C] slab. Bit-identical digests, events, and
    # drop counters to the flat queue by construction (tests/test_bucketq.py
    # is the gate). 0 = flat queue (the B=C degenerate case).
    queue_block: int = 0
    # K-way microstep pop (experimental.microstep_events): fold up to K
    # events per host through the model handler per queue dispatch. The
    # queue slab is read once per microstep (ops/events.py pop_k) and the
    # executed prefix cleared once (clear_popped), so per-event queue cost
    # drops up to K-fold on event-dense hosts — the density ceiling the
    # one-event microstep hits on tgen-TCP (BENCH r5: 20-33 microsteps per
    # round at ~0.5 ms each). Exactness guard: event j+1 of a host's batch
    # executes only if no push emitted so far this microstep landed at an
    # earlier (time, order) key on that host (and, under the CPU model,
    # only while busy_until stays inside the window) — otherwise the rest
    # of the batch stays in the queue untouched and re-pops next
    # microstep. Execution order, digests, event counts, and drop counters
    # are bit-identical to K=1 by construction for both queue layouts
    # (tests/test_popk.py is the gate). 1 = today's exact single-event
    # microstep (the default).
    microstep_events: int = 1
    # Per-HOST send budget per round. Budget-drop decisions depend only on a
    # host's own send count, and the shard outbox is sized hosts_per_shard *
    # budget so aggregate overflow is impossible — this is what keeps drop
    # behavior (hence digests) identical across mesh shapes.
    sends_per_host_round: int = 8
    max_round_inserts: int = 64  # per host per round
    microstep_limit: int = 0  # 0 -> queue_capacity * 2
    rounds_per_chunk: int = 64
    world: int = 1  # mesh size (1 = single device)
    # cross-shard exchange strategy (multi-device only):
    #   "gather"   — all_gather the full outbox to every shard; each shard
    #                filters its rows. Exact, but per-shard ICI bytes and
    #                merge input grow O(world).
    #   "alltoall" — sort the local outbox by destination shard and
    #                lax.all_to_all fixed-width blocks: per-shard ICI bytes
    #                and merge input are O(global sends / world). Blocks
    #                hold `a2a_block` entries per (src, dst-shard) pair;
    #                overflow sheds the LATEST entries per the urgency
    #                contract and counts in stats.a2a_shed (size the block
    #                so it stays 0 — every test asserts it).
    exchange: str = "gather"
    a2a_block: int = 0  # 0 -> auto: 2 * outbox_rows / world, >= 64
    # Static cap on post-sort merge gather work (ops/merge.py): only the
    # first `merge_rows` sorted exchange rows are materialized. Exact while
    # (valid rows + num_hosts + 1) <= merge_rows; beyond it rows shed by
    # sorted position and count in queue.dropped. 0 = unbounded (the full
    # worst-case outbox, num_hosts * sends_per_host_round rows).
    merge_rows: int = 0
    # Active merge gear (experimental.merge_gears): the number of outbox
    # LANE COLUMNS the exchange flattens, sorts, and merges. The outbox is
    # [H, B] with host h's k-th send of the round in column k, so when no
    # host stages more than `gear_cols` sends in a round the first
    # `gear_cols` columns hold EVERY valid entry and the truncation is
    # exact — the (dst, t, order) sort runs over H x gear_cols rows
    # instead of the worst-case H x B. Sends beyond the width are counted
    # (globally) into stats.gear_shed and the chunk loop aborts; the
    # driver then restores its pre-chunk snapshot and replays one gear up
    # (core/gears.py), so results stay bit-identical to full width on
    # every workload. 0 = full width (byte-identical program to before
    # gears existed). The driver's EngineConfig always carries 0 here —
    # geared chunk programs are built via Engine.run_chunk_gear with a
    # dataclasses.replace'd copy, so checkpoint fingerprints never vary
    # with the transient gear choice.
    gear_cols: int = 0
    # Device-resident round tracer (observability.trace): capacity of the
    # in-scan trace ring in rounds. 0 = off (no ring in the carry, no row
    # writes — the traced program is byte-identical to before the tracer
    # existed). The drivers size it to rounds_per_chunk so a drain per
    # chunk can never wrap. Rows are observations of values each round
    # already computes; scheduling never reads them, so digests, events,
    # and drop counters are bit-identical on or off (tests/test_tracer.py).
    trace_rounds: int = 0
    # Fault plane statics (core/faults.py; config `faults:`). The ARRAYS
    # live in EngineParams.faults; these are the trace-time shape/policy
    # knobs the round body specializes on. All 0/False = no fault code
    # traced in (the program is bit-identical to the fault-free engine).
    fault_crash_windows: int = 0  # W: max up/down windows per host
    fault_loss_windows: int = 0  # L: link-fault (loss/latency) windows
    # crashed-host queue policy: False = "hold" (pending events defer to
    # the restart time, the CPU-model busy-floor mechanics), True =
    # "clear" (events whose execution time falls in a down window are
    # dropped and counted in stats.faults_dropped)
    fault_queue_clear: bool = False
    # Pressure plane (core/pressure.py; config `pressure:`): when True
    # (policies escalate/abort) the round body maintains the psum'd
    # `stats.pressure` drop total and the chunk while_loop aborts at the
    # first round where ANY host dropped for capacity — the exact
    # detector the escalation/abort drivers replay or stop on. False
    # (policy drop, the default) traces no pressure code at all: the
    # program is bit-identical to the pre-pressure engine.
    pressure_abort: bool = False
    # Network observatory (obs/netobs.py; observability.network): when
    # True the round body classifies every executed event as timer /
    # packet / app into per-shard stats lanes, tracks the shard that
    # bound each round's safe-window barrier, and (with flow_records > 0)
    # appends flow-completion records to a per-shard ledger ring. All of
    # it observes values the round already computes and feeds nothing
    # back, so digests/events/drops are bit-identical on or off; False
    # (the default) traces NO observatory code — the program stays
    # byte-identical to before the observatory existed.
    netobs: bool = False
    # flow-ledger ring capacity in records per shard (0 = no ledger in
    # the carry — models without a flow port, or the observatory off).
    # The drivers size it from observability.network_flows and drain at
    # chunk boundaries; a burst past capacity overwrites the OLDEST
    # records, counted by the FlowCollector (never silent), while the
    # fl_* stats lanes keep exact totals regardless.
    flow_records: int = 0
    # Integrity sentinel (core/integrity.py; config `integrity:`): when
    # True the round body evaluates the per-round invariant guards
    # (conservation laws the state must satisfy regardless of workload)
    # into the psum'd `stats.integrity` violation lane plus the
    # per-shard `iv_mask`/`iv_round` signature lanes, and the chunk
    # while_loop aborts mesh-uniformly at the first violating round —
    # the detector the quarantine-and-replay classifier
    # (core/pressure.ResilienceController) restores and replays on.
    # False (the default) traces ZERO sentinel code: the program is
    # byte-identical to the pre-sentinel engine (the echo/phold jaxpr
    # fingerprints are the gate).
    integrity: bool = False
    # dual-digest lane (requires integrity): maintain the second,
    # independently-folded per-host digest (stats.digest2) so a scribble
    # on the digest plane itself is detectable host-side.
    integrity_dual: bool = False
    # strict window-monotonicity sub-check of IV_TIME (window_end never
    # below the committed now). Unconditional on the pure-device engine
    # under static runahead; the HYBRID bridge legitimately injects
    # CPU-plane packets whose conservative arrival bound (until +
    # min-latency) can sit below the device's last guarded window_end
    # when runahead_floor exceeds the graph's min latency — cosim
    # therefore builds with False and keeps the slab-floor sub-check
    # plus its own host-side bridge guards (cosim._bridge_guard).
    integrity_strict_time: bool = True
    # Device-resident timer wheel (ops/wheel.py; experimental.timer_wheel):
    # per-host calendar slots for the model's declared timer_kinds. 0 = off
    # (no wheel in the carry, no routing/pop-merge code traced — the
    # program stays byte-identical to before the wheel existed). With
    # S > 0, model timer pushes route to the [H, S] wheel (overflow spills
    # to the event queue, counted in stats.wheel_spilled, never silent),
    # wheel heads fold into the round's min-next-event reduction, and the
    # microstep pops the lexicographic (time, order) minimum of
    # queue ∪ wheel — dispatch order, digests, events, and drop counters
    # are bit-identical to the wheel-off path whenever the queue itself
    # does not overflow (the wheel frees queue slots, so a run the off
    # path would overflow can only drop LESS; sized workloads see zero
    # drops either way — tests/test_wheel.py is the gate).
    wheel_slots: int = 0
    # wheel block size (slots per block of the wheel's block-min caches);
    # 0 = auto (a divisor of wheel_slots near sqrt — ops/wheel.py
    # resolve_wheel_block). Must divide wheel_slots.
    wheel_block: int = 0
    # Sort-free calendar-queue exchange merge (ops/merge.py
    # merge_scatter_free): bucket incoming exchange rows by destination
    # via scatter-add + scatter-max peeling instead of the full
    # (dst, t, order) sort on the non-shedding fast path; any round where
    # a destination would overflow falls back to the sort path in-jit, so
    # digests/events/drops are bit-identical on every workload. False
    # (default) keeps the sort merge and traces no scatter code.
    merge_scatter: bool = False
    # Fluid traffic plane statics (net/fluid.py; config `fluid:`). The
    # ARRAYS live in EngineParams.fluid; these are the trace-time
    # shape/coupling knobs the round body specializes on. fluid_classes
    # = 0 (the default) traces ZERO fluid code — the program is
    # byte-identical to the fluid-free engine (the default jaxpr
    # fingerprints are the gate; `tgen_fluid` pins the gated surface).
    fluid_classes: int = 0  # K background traffic classes
    fluid_links: int = 0  # N links (graph nodes) the ODE state covers
    fluid_tau_ns: int = 50_000_000  # rate-relaxation time constant
    fluid_util_threshold: float = 0.7  # coupling ramp start (RED min-th)
    fluid_loss_max: float = 0.0  # extra fg loss prob at full overload
    fluid_lat_max_x1000: int = 2000  # fg latency multiplier cap (x1000)
    fluid_seed: int = 1  # the counter-based loss-draw hash seed
    # Trace-time affine-routing constant, set by Engine.init_state when the
    # host->node map is uniform contiguous blocks (node_of[h] == h // g, the
    # shape every `count:`-group config produces): the per-send node lookup
    # becomes an integer divide on the VPU instead of a 10k-descriptor
    # gather (measured 83 us per microstep per send port at H=10k). 0 = map
    # is irregular, gather stays.
    hosts_per_node: int = 0

    def __post_init__(self):
        check_order_limits(self.num_hosts)
        if self.num_hosts % self.world != 0:
            raise ValueError(
                f"num_hosts={self.num_hosts} must divide evenly over "
                f"world={self.world} mesh devices"
            )
        if self.exchange not in ("gather", "alltoall", "hierarchical"):
            raise ValueError(
                f"exchange must be gather|alltoall|hierarchical, got "
                f"{self.exchange!r}"
            )
        if self.a2a_block < 0:
            raise ValueError(
                f"a2a_block must be >= 0 (0 = auto), got {self.a2a_block}"
            )
        if self.queue_block < 0 or (
            self.queue_block and self.queue_capacity % self.queue_block
        ):
            raise ValueError(
                f"queue_block={self.queue_block} must be 0 (flat) or divide "
                f"queue_capacity={self.queue_capacity} evenly"
            )
        if self.microstep_events < 1:
            raise ValueError(
                f"microstep_events={self.microstep_events} must be >= 1"
            )
        if self.trace_rounds < 0:
            raise ValueError(
                f"trace_rounds={self.trace_rounds} must be >= 0 (0 = off)"
            )
        if self.gear_cols < 0 or self.gear_cols > self.sends_per_host_round:
            raise ValueError(
                f"gear_cols={self.gear_cols} must be in "
                f"[0, sends_per_host_round={self.sends_per_host_round}] "
                f"(0 = full width)"
            )
        if self.fault_crash_windows < 0 or self.fault_loss_windows < 0:
            raise ValueError(
                f"fault window counts must be >= 0, got crash="
                f"{self.fault_crash_windows} loss={self.fault_loss_windows}"
            )
        if self.flow_records < 0:
            raise ValueError(
                f"flow_records={self.flow_records} must be >= 0 (0 = no "
                f"ledger)"
            )
        if self.flow_records and not self.netobs:
            raise ValueError(
                "flow_records > 0 requires netobs=True (the flow ledger "
                "is a network-observatory instrument)"
            )
        if self.integrity_dual and not self.integrity:
            raise ValueError(
                "integrity_dual requires integrity=True (the dual digest "
                "is an integrity-sentinel lane)"
            )
        if self.wheel_slots < 0:
            raise ValueError(
                f"wheel_slots={self.wheel_slots} must be >= 0 (0 = off)"
            )
        if self.wheel_block < 0 or (
            self.wheel_slots and self.wheel_block
            and self.wheel_slots % self.wheel_block
        ):
            raise ValueError(
                f"wheel_block={self.wheel_block} must be 0 (auto) or divide "
                f"wheel_slots={self.wheel_slots} evenly"
            )
        if self.fluid_classes < 0 or self.fluid_links < 0:
            raise ValueError(
                f"fluid dims must be >= 0, got classes="
                f"{self.fluid_classes} links={self.fluid_links}"
            )
        if self.fluid_classes and self.fluid_links < 1:
            raise ValueError(
                "fluid_classes > 0 requires fluid_links >= 1 (the ODE "
                "needs at least one link to cover)"
            )
        if self.fluid_classes:
            if self.fluid_tau_ns <= 0:
                raise ValueError(
                    f"fluid_tau_ns must be > 0, got {self.fluid_tau_ns}"
                )
            if not 0.0 <= self.fluid_util_threshold < 1.0:
                raise ValueError(
                    f"fluid_util_threshold must be in [0, 1), got "
                    f"{self.fluid_util_threshold}"
                )
            if not 0.0 <= self.fluid_loss_max <= 1.0:
                raise ValueError(
                    f"fluid_loss_max must be in [0, 1], got "
                    f"{self.fluid_loss_max}"
                )
            if self.fluid_lat_max_x1000 < 1000:
                raise ValueError(
                    f"fluid_lat_max_x1000 must be >= 1000 (inflation "
                    f"only — the conservative-lookahead bound), got "
                    f"{self.fluid_lat_max_x1000}"
                )
        if self.wheel_slots and self.microstep_events > 1:
            raise ValueError(
                "unsupported knob pair: experimental.timer_wheel (wheel_"
                f"slots={self.wheel_slots}) x experimental.microstep_events="
                f"{self.microstep_events} — the wheel's pop path merges ONE "
                "wheel candidate against the queue head per microstep, and "
                "the K-way fold would need a merged 2K-candidate batch with "
                "split clear/reserve accounting to stay exact. ROADMAP item "
                "1 tracks that follow-up. Until it lands, drop one knob: "
                "run the wheel with microstep_events=1 (the measured CPU "
                "winner) or keep the wheel off (docs/usage.md 'Timer "
                "wheel')."
            )

    @property
    def a2a_block_size(self) -> int:
        if self.a2a_block:
            return self.a2a_block
        rows = self.hosts_per_shard * self.sends_per_host_round
        return min(rows, max(64, 2 * rows // max(self.world, 1)))

    @property
    def hosts_per_shard(self) -> int:
        return self.num_hosts // self.world

    @property
    def effective_microstep_limit(self) -> int:
        """The per-round safety valve. For K=1 it bounds microsteps (and so
        events per host per round); for K>1 the round loop carries a
        PER-HOST executed-event vector and stops when any host's count
        reaches this value, so the same number keeps denominating an event
        budget — microsteps needed shrink up to K-fold when batches fold
        fully, while a deferral-heavy microstep charges a host only what
        it actually retired. Dividing the valve by K instead would bind
        EARLIER than K=1 under bursty-push deferral, and a global
        sum-of-dispatch-maxima charge would overcharge multi-host rounds;
        the per-host vector can only bind in rounds where some host
        genuinely retires `limit` events — exactly the K=1 livelock
        condition — and never cuts short a round K=1 would finish (a
        host's count before its final dispatch is at most total - 1 <
        limit). It is a livelock valve, not a scheduler."""
        return self.microstep_limit or 2 * self.queue_capacity

    @property
    def effective_microstep_events(self) -> int:
        """K clamped to the queue capacity (popping more than C events in
        one batch is impossible by construction)."""
        return min(self.microstep_events, self.queue_capacity)

    @property
    def effective_gear_cols(self) -> int:
        """The merge width actually in force (0 resolves to full width)."""
        return self.gear_cols or self.sends_per_host_round

    @property
    def faults_active(self) -> bool:
        """True iff any fault plumbing is traced into the round body."""
        return self.fault_crash_windows > 0 or self.fault_loss_windows > 0

    @property
    def fault_hold(self) -> bool:
        """Crash windows with queue-HOLD semantics: down hosts' events
        defer to the restart time (execution-time floor)."""
        return self.fault_crash_windows > 0 and not self.fault_queue_clear

    @property
    def fault_clear(self) -> bool:
        """Crash windows with queue-CLEAR semantics: events executing
        while down are popped and dropped (stats.faults_dropped)."""
        return self.fault_crash_windows > 0 and self.fault_queue_clear

    @property
    def flow_ledger_active(self) -> bool:
        """True iff the flow-completion ledger is traced into the round
        body (network observatory on AND a ring capacity declared)."""
        return self.netobs and self.flow_records > 0

    @property
    def wheel_active(self) -> bool:
        """True iff the timer wheel is traced into the round body (the
        wheel carry, push routing, and merged pops exist only then —
        the wheel-off program stays byte-identical)."""
        return self.wheel_slots > 0

    @property
    def fluid_active(self) -> bool:
        """True iff the fluid traffic plane is traced into the round
        body (the ODE carry, the per-round advance, the outbox byte
        fold, and the coupling factors exist only then — the fluid-off
        program stays byte-identical)."""
        return self.fluid_classes > 0

    @property
    def gear_active(self) -> bool:
        """True iff this program runs a TRUNCATED merge (shed detection,
        gear-abort chunk condition, and the sliced exchange are traced in
        only then — the full-width program stays byte-identical)."""
        return 0 < self.gear_cols < self.sends_per_host_round

    @property
    def hier_active(self) -> bool:
        """True iff the two-tier hierarchical exchange is traced into the
        round body (the tier counters ici_intra/ici_inter exist only then;
        a world-1 'hierarchical' config degenerates to the local gather
        path like every other exchange kind and carries neither)."""
        return self.exchange == "hierarchical" and self.world > 1

    @property
    def hier_block_size(self) -> int:
        """Inter-shard block width of the hierarchical exchange (rows per
        destination shard per round). Same shape law as `a2a_block_size`
        but derived from the GEAR-SLICED row count: the intra-shard
        compaction tier sorts only hosts_per_shard x effective_gear_cols
        rows, so the blocks the wire carries shrink with the merge gear
        instead of staying sized to the full [H, B] outbox — that delta is
        the hierarchical path's wire-byte win (`stats.ici_inter` vs the
        flat alltoall model). An explicit `a2a_block` wins here too, so
        one knob pins both exchange kinds' block math in A/B runs."""
        if self.a2a_block:
            return self.a2a_block
        rows_g = self.hosts_per_shard * self.effective_gear_cols
        return min(rows_g, max(64, 2 * rows_g // max(self.world, 1)))

    @property
    def effective_rounds_per_chunk(self) -> int:
        """The chunk loop's iteration bound actually traced into
        `_run_chunk`/`_run_guarded_chunk`.

        Below ~524k hosts this is `rounds_per_chunk` unchanged. Above it,
        the bound is clamped to the microstep valve (2 x queue_capacity
        when unset): the XLA while-loop pathology documented in
        `config/options.resolve_shapes` (BASELINE.md r3 — per-CALL cost of
        the jitted loop grows superlinearly with the trip bound at >= 1M
        lanes; rpc=64 took 13.5 s where rpc=8 took 0.36 s for the same 30
        rounds) makes a large constant bound poison EVERY dispatch at that
        scale, while results are invariant to it (the drivers loop chunks
        until `state.done`, so a smaller bound only means more host
        round-trips). The valve reproduces `resolve_shapes`' measured
        auto-tier rpc exactly (tier-3 qcap 4 -> 8, tier-2 qcap 16 -> 32);
        the host-count gate keeps explicitly-tuned small-H configs (e.g.
        bench_config's rpc=512 at 10k hosts) untouched."""
        if self.num_hosts <= 1 << 19:
            return self.rounds_per_chunk
        return min(self.rounds_per_chunk, max(self.effective_microstep_limit, 1))


# --------------------------------------------------------------------------
# state construction (host side)
# --------------------------------------------------------------------------


def host_build_context():
    """Run state construction on the host CPU backend. Over a tunneled TPU
    every individual `jnp.zeros`/`asarray` is a network round-trip; building
    on CPU and shipping the finished pytree in ONE device_put turns minutes
    of setup into seconds (measured 187s -> ~2s at 512 hosts)."""
    try:
        cpu = jax.local_devices(backend="cpu")[0]
        return jax.default_device(cpu)
    except RuntimeError:
        return contextlib.nullcontext()


def _init_stats(cfg: EngineConfig) -> Stats:
    h = cfg.num_hosts

    # distinct buffers per field: the state pytree is donated to the jitted
    # chunk, and donating one buffer through two leaves is an error
    def zi():
        return jnp.zeros((h,), jnp.int64)

    def zw():
        return jnp.zeros((cfg.world,), jnp.int64)

    return Stats(
        events=zi(),
        pkts_sent=zi(),
        pkts_lost=zi(),
        pkts_unreachable=zi(),
        pkts_codel_dropped=zi(),
        pkts_delivered=zi(),
        monotonic_violations=zi(),
        pkts_budget_dropped=zi(),
        faults_dropped=zi(),
        faults_delayed=zi(),
        ob_dropped=jnp.zeros((cfg.world,), jnp.int64),
        a2a_shed=jnp.zeros((cfg.world,), jnp.int64),
        microsteps=jnp.zeros((cfg.world,), jnp.int64),
        bq_rebuilds=jnp.zeros((cfg.world,), jnp.int64),
        popk_deferred=jnp.zeros((cfg.world,), jnp.int64),
        ici_bytes=jnp.zeros((cfg.world,), jnp.int64),
        q_occ_hwm=zi(),
        outbox_hwm=jnp.zeros((cfg.world,), jnp.int64),
        gear_shed=jnp.zeros((cfg.world,), jnp.int64),
        digest=jnp.full((h,), _FNV_OFFSET, jnp.uint64),  # FNV offset basis
        rounds=jnp.zeros((), jnp.int64),
        pressure=(
            jnp.zeros((cfg.world,), jnp.int64) if cfg.pressure_abort
            else None
        ),
        # network-observatory lanes: absent (None) unless the observatory
        # is traced in — a distinct buffer per field (donation rule above)
        ec_timer=zw() if cfg.netobs else None,
        ec_pkt=zw() if cfg.netobs else None,
        ec_app=zw() if cfg.netobs else None,
        fl_done=zw() if cfg.flow_ledger_active else None,
        fl_bytes=zw() if cfg.flow_ledger_active else None,
        fl_rtx=zw() if cfg.flow_ledger_active else None,
        win_bound=zw() if cfg.netobs else None,
        # integrity sentinel lanes (core/integrity.py): absent unless
        # the sentinel is traced in; iv_round's -1 = "no violation yet"
        integrity=zw() if cfg.integrity else None,
        iv_mask=zw() if cfg.integrity else None,
        iv_round=(
            jnp.full((cfg.world,), -1, jnp.int64) if cfg.integrity
            else None
        ),
        digest2=(
            jnp.full((h,), _DIGEST2_OFFSET, jnp.uint64)
            if cfg.integrity_dual else None
        ),
        # timer-wheel lanes (ops/wheel.py): absent unless the wheel is
        # traced in — distinct buffers per field (donation rule above)
        wheel_spilled=zi() if cfg.wheel_active else None,
        wheel_occ_hwm=zi() if cfg.wheel_active else None,
        # fluid-plane byte counters (net/fluid.py): replicated scalars,
        # absent unless the fluid ODE is traced in
        fl_bg_bytes=(
            jnp.zeros((), jnp.int64) if cfg.fluid_active else None
        ),
        fl_bg_dropped=(
            jnp.zeros((), jnp.int64) if cfg.fluid_active else None
        ),
        # hierarchical-exchange tier counters: absent unless the two-tier
        # exchange is traced in — distinct buffers (donation rule above)
        ici_intra=zw() if cfg.hier_active else None,
        ici_inter=zw() if cfg.hier_active else None,
    )


def make_empty_outbox(num_hosts: int, send_budget: int, count) -> Outbox:
    """A fresh (empty) [H, B] staging outbox. The single source of the
    empty layout — the engine build, the pressure plane's outbox
    migration, and the checkpoint restore paths all construct through
    here so a new Outbox field or sentinel change cannot silently
    diverge between them. `count` provides the per-shard count word's
    shape/sharding (zeroed)."""
    h, b = num_hosts, send_budget
    return Outbox(
        dst=jnp.zeros((h, b), jnp.int32),
        t=jnp.full((h, b), TIME_MAX, jnp.int64),
        order=jnp.zeros((h, b), jnp.int64),
        kind=jnp.zeros((h, b), jnp.int32),
        payload=jnp.zeros((h, b, EVENT_PAYLOAD_WORDS), jnp.int32),
        count=jnp.zeros_like(count),
    )


def _init_outbox(cfg: EngineConfig) -> Outbox:
    return make_empty_outbox(
        cfg.num_hosts, cfg.sends_per_host_round,
        jnp.zeros((cfg.world,), jnp.int32),
    )


def seed_queue(
    cfg: EngineConfig, initial_events: list[tuple[int, int, int, tuple]]
) -> tuple[EventQueue, Array]:
    """Build the t=0 queue from (host_id, t_ns, kind, payload) events — the
    boot round (reference manager.rs:357-367 / host.rs:392 add_application).

    Returns (queue, seq[H]) with per-host seq counters advanced past the
    seeded events so later emissions keep globally unique order keys.
    """
    queue, _, seq = _seed_slabs(cfg, initial_events, ())
    return queue, seq


def seed_queue_wheel(
    cfg: EngineConfig,
    initial_events: list[tuple[int, int, int, tuple]],
    timer_kinds: tuple[int, ...],
) -> tuple[EventQueue, Any, Array]:
    """`seed_queue` for wheel-active programs: seeded TIMER events (model
    kind in `timer_kinds`) land in the wheel slab, everything else in the
    queue — the boot-time form of the runtime push routing, so a
    timer-dominant boot population (the 1M-lane phold/tgen seeds) never
    constrains the queue capacity. A full wheel spills seeds back to the
    queue (same contract as the runtime route); order keys advance in
    event-list order regardless of destination, so the (time, order)
    total order — hence dispatch and digests — is identical to seeding
    everything into one queue. Returns (queue, wheel_slabs, seq) with
    wheel_slabs the flat (t, order, kind, payload) numpy planes (the
    caller wraps them via bucket_rebuild)."""
    return _seed_slabs(cfg, initial_events, tuple(timer_kinds))


def _seed_slabs(
    cfg: EngineConfig,
    initial_events: list[tuple[int, int, int, tuple]],
    timer_kinds: tuple[int, ...],
):
    h, c = cfg.num_hosts, cfg.queue_capacity
    s = cfg.wheel_slots if timer_kinds else 0
    t = np.full((h, c), TIME_MAX, np.int64)
    order = np.full((h, c), ORDER_MAX, np.int64)
    kind = np.zeros((h, c), np.int32)
    payload = np.zeros((h, c, EVENT_PAYLOAD_WORDS), np.int32)
    fill = np.zeros((h,), np.int32)
    if s:
        wt = np.full((h, s), TIME_MAX, np.int64)
        worder = np.full((h, s), ORDER_MAX, np.int64)
        wkind = np.zeros((h, s), np.int32)
        wpayload = np.zeros((h, s, EVENT_PAYLOAD_WORDS), np.int32)
        wfill = np.zeros((h,), np.int32)
    seq = np.zeros((h,), np.int64)
    # order keys are packed in numpy for the whole batch: calling the
    # (jax) pack_order per event built three traced scalars per call and
    # dominated 1M-host builds (~290 s of a 318 s construction)
    from shadow_tpu.ops.events import _LOCAL_SHIFT, _SRC_SHIFT, SEQ_MASK

    for host, t_ns, k, pl in initial_events:
        okey = (
            (np.int64(1) << _LOCAL_SHIFT)
            | (np.int64(host) << _SRC_SHIFT)
            | (np.int64(seq[host]) & SEQ_MASK)
        )
        seq[host] += 1
        if s and k in timer_kinds and wfill[host] < s:
            slot = wfill[host]
            wt[host, slot] = t_ns
            worder[host, slot] = okey
            wkind[host, slot] = k
            wpayload[host, slot, : len(pl)] = pl
            wfill[host] += 1
            continue
        slot = fill[host]
        if slot >= c:
            raise ValueError(
                f"host {host}: {slot + 1} initial events exceed queue capacity {c}"
            )
        t[host, slot] = t_ns
        order[host, slot] = okey
        kind[host, slot] = k
        payload[host, slot, : len(pl)] = pl
        fill[host] += 1
    queue = EventQueue(
        t=jnp.asarray(t, jnp.int64),
        order=jnp.asarray(order, jnp.int64),
        kind=jnp.asarray(kind, jnp.int32),
        payload=jnp.asarray(payload, jnp.int32),
        dropped=jnp.zeros((h,), jnp.int64),
    )
    wheel = None
    if s:
        wheel = EventQueue(
            t=jnp.asarray(wt, jnp.int64),
            order=jnp.asarray(worder, jnp.int64),
            kind=jnp.asarray(wkind, jnp.int32),
            payload=jnp.asarray(wpayload, jnp.int32),
            dropped=jnp.zeros((h,), jnp.int64),
        )
    return queue, wheel, jnp.asarray(seq, jnp.int64)


# --------------------------------------------------------------------------
# device-side helpers
# --------------------------------------------------------------------------


def _digest_update(digest, active, t, kind, order):
    x = t.astype(jnp.uint64) * _MIX1
    x = x ^ (kind.astype(jnp.uint64) * _MIX2)
    x = x ^ order.astype(jnp.uint64)
    return jnp.where(active, (digest ^ x) * _FNV_PRIME, digest)


def _digest_update2(digest2, active, t, kind, order):
    """The integrity sentinel's SECOND per-host fold: same inputs, no
    shared constants, and order (not t) carries the first multiplier —
    a scribble flipping bits on one digest plane cannot land on a value
    consistent with the other (core/integrity.classify_digest_pair)."""
    x = order.astype(jnp.uint64) * _MIX3
    x = x ^ (t.astype(jnp.uint64) * _MIX4)
    x = x ^ kind.astype(jnp.uint64)
    return jnp.where(active, (digest2 ^ x) * _DIGEST2_PRIME, digest2)


def _outbox_append(ob: Outbox, mask, col, dst, t, order, kind, payload):
    """Write each masked host's entry into its own lane at column `col`
    (the host's `sent_round` cursor). One-hot masked writes only; `mask`
    implies `col < B` (the send budget is checked upstream), so `n_lost` is
    structurally zero — but it is computed, not assumed, so `ob_dropped`
    remains a real invariant check against future call sites."""
    b = ob.t.shape[1]
    oh = mask[:, None] & (jnp.arange(b, dtype=jnp.int32)[None, :] == col[:, None])
    n_lost = jnp.sum(mask & (col >= b), dtype=jnp.int64)
    new = Outbox(
        dst=jnp.where(oh, dst.astype(jnp.int32)[:, None], ob.dst),
        t=jnp.where(oh, t[:, None], ob.t),
        order=jnp.where(oh, order[:, None], ob.order),
        kind=jnp.where(oh, kind.astype(jnp.int32)[:, None], ob.kind),
        payload=jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :], ob.payload
        ),
        count=ob.count + jnp.sum(mask, dtype=jnp.int32)[None],
    )
    return new, n_lost


def _outbox_append_multi(ob: Outbox, entries):
    """Apply ALL of a microstep's outbox appends in one slab pass.

    `entries` is a list of (mask, col, dst, t, order, kind, payload) with
    per-host [H] arrays; columns are cursor-assigned upstream so at most one
    entry targets any (host, col). Applying them as a chained one-hot write
    (no reductions interleaved between the [H, B] selects) lets XLA fuse the
    whole append into a single read+write of the outbox — the per-port
    `_outbox_append` chain materialized the full slab once per port, which
    was the measured cost of multi-port TCP bursts. Overflow (`col >= B`) is
    counted, never silent, exactly as in `_outbox_append`."""
    b = ob.t.shape[1]
    h = ob.t.shape[0]
    cols = jnp.arange(b, dtype=jnp.int32)[None, :]
    dst_n, t_n, order_n = ob.dst, ob.t, ob.order
    kind_n, payload_n = ob.kind, ob.payload
    # reductions are accumulated ELEMENTWISE in the loop and summed once at
    # the end: a jnp.sum between the one-hot selects is a fusion fence that
    # re-materializes the whole [H, B] slab per entry (measured: 8-entry
    # bursts ran ~25% slower with in-loop sums)
    lost_acc = jnp.zeros((h,), jnp.int64)
    total_acc = jnp.zeros((h,), jnp.int32)
    for mask, col, dst, t, order, kind, payload in entries:
        oh = mask[:, None] & (cols == col[:, None])
        dst_n = jnp.where(oh, dst.astype(jnp.int32)[:, None], dst_n)
        t_n = jnp.where(oh, t[:, None], t_n)
        order_n = jnp.where(oh, order[:, None], order_n)
        kind_n = jnp.where(oh, kind.astype(jnp.int32)[:, None], kind_n)
        payload_n = jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :],
            payload_n,
        )
        lost_acc = lost_acc + (mask & (col >= b))
        total_acc = total_acc + mask
    return (
        Outbox(
            dst=dst_n, t=t_n, order=order_n, kind=kind_n, payload=payload_n,
            count=ob.count + jnp.sum(total_acc, dtype=jnp.int32)[None],
        ),
        jnp.sum(lost_acc, dtype=jnp.int64),
    )


class Engine:
    """Builds and runs the jitted round loop for a fixed (config, model).

    Single-device: `run_chunk(state, params)`. Multi-device: the same function
    wrapped in shard_map over a 1-D mesh of `cfg.world` devices. The Python
    driver loop (`shadow_tpu.sim`) calls chunks until `state.done`.
    """

    def __init__(self, cfg: EngineConfig, model, mesh: Mesh | None = None):
        if (mesh is None) != (cfg.world == 1):
            raise ValueError("mesh must be provided iff cfg.world > 1")
        if cfg.wheel_active and not tuple(getattr(model, "timer_kinds", ())):
            raise ValueError(
                f"timer wheel enabled (wheel_slots={cfg.wheel_slots}) but "
                f"model {getattr(model, 'name', model)!r} declares no "
                f"timer_kinds — nothing would ever route to the wheel; "
                f"drop experimental.timer_wheel or use a model with timers"
            )
        self.cfg = cfg
        self.model = model
        self.mesh = mesh
        self.run_chunk = None  # built by init_state (needs model pytree shapes)
        self._gear_chunks: dict[int, Any] = {}  # gear_cols -> jitted chunk
        # (gear_cols, queue_capacity, send_budget) -> jitted chunk: the
        # pressure plane's escalated programs (core/pressure.py). Bounded
        # by the escalation ladders (a handful of rungs per axis).
        self._resized_chunks: dict[tuple, Any] = {}
        # runtime observatory (obs/runtime.CompileLedger): when attached,
        # every cached chunk program is wrapped so its first (compiling)
        # call is recorded with its trigger. HOST-SIDE only — wrapping a
        # jitted callable cannot change the traced program.
        self.compile_ledger = None

    def attach_compile_ledger(self, ledger):
        """Attach an `obs.runtime.CompileLedger` so cache misses in the
        chunk-program caches record their compile walls. Safe before OR
        after `init_state` (jit compiles lazily — a not-yet-called
        program still records on its first call); attach before the
        first dispatch or the base program's compile goes unrecorded."""
        self.compile_ledger = ledger
        if self.run_chunk is not None and ledger is not None:
            self.run_chunk = ledger.instrument(
                "chunk", "base", "cold_start", self.run_chunk
            )

    def _jit_chunk(self, cfg: EngineConfig):
        """Build one jitted chunk program for `cfg` — shared by the
        full-width build and every gear variant so specs/donation can
        never diverge between them."""
        axis = AXIS if self.mesh is not None else None
        chunk = functools.partial(_run_chunk, cfg, self.model, axis)
        if self.mesh is not None:
            state_spec = self.state_specs()
            chunk = _shard_map(
                chunk, self.mesh, (state_spec, self.param_specs()), state_spec
            )
        return jax.jit(chunk, donate_argnums=0)

    def _build_run_chunk(self):
        fn = self._jit_chunk(self.cfg)
        if self.compile_ledger is not None:
            fn = self.compile_ledger.instrument(
                "chunk", "base", "cold_start", fn
            )
        self.run_chunk = fn

    def run_chunk_gear(self, state: SimState, params: EngineParams, gear_cols: int):
        """Run one chunk at a merge gear (`gear_cols` outbox columns in the
        exchange sort). Gear programs are jitted lazily and cached per
        width — the ladder is small (<= 4 gears), so at most a handful of
        compiles per run. `gear_cols` of 0 or the full send budget routes
        to the ordinary `run_chunk` (the byte-identical full-width
        program). Callable only after `init_state` (like `run_chunk`).

        State shapes are IDENTICAL across gears (the outbox stays [H, B];
        only the slice the exchange sorts changes), so the pre-chunk
        snapshot/replay loop in the drivers can hand the same pytree to
        any gear."""
        if gear_cols <= 0 or gear_cols >= self.cfg.sends_per_host_round:
            return self.run_chunk(state, params)
        fn = self._gear_chunks.get(gear_cols)
        if fn is None:
            fn = self._jit_chunk(
                dataclasses.replace(self.cfg, gear_cols=gear_cols)
            )
            if self.compile_ledger is not None:
                fn = self.compile_ledger.instrument(
                    "chunk", f"gear={gear_cols}", "gear_shift", fn
                )
            self._gear_chunks[gear_cols] = fn
        return fn(state, params)

    def run_chunk_resized(
        self, state: SimState, params: EngineParams, gear_cols: int,
        queue_capacity: int, send_budget: int,
    ):
        """Run one chunk at an escalated shape: `queue_capacity` slots per
        host and a `send_budget`-wide outbox (the pressure plane's
        regrown programs, core/pressure.py), at merge gear `gear_cols`
        (0 = full width). Base shapes route to the gear/full-width cache.

        The resized config pins the knobs that would otherwise drift
        with capacity, so the escalated trajectory stays bit-identical
        to a run LAUNCHED at the final shape with the same pins:
          - `microstep_limit` is fixed at the BASE config's effective
            valve (the valve is a livelock bound, not a scheduler, but
            letting it scale with capacity could cut a pathological
            round at a different microstep across rungs);
          - `max_round_inserts` scales with capacity only when the base
            left it auto-sized (== base capacity), matching what the
            driver would derive at the bigger shape.
        Callable only after `init_state` (like `run_chunk`). A
        `queue_capacity`/`send_budget` of 0 means the base shape (the
        gears-only controller passes 0s — it never reads the state's
        shapes), exactly like `gear_cols` 0 means full width."""
        base = self.cfg
        if queue_capacity in (0, base.queue_capacity) and send_budget in (
            0, base.sends_per_host_round
        ):
            return self.run_chunk_gear(state, params, gear_cols)
        key = (int(gear_cols), int(queue_capacity), int(send_budget))
        fn = self._resized_chunks.get(key)
        if fn is None:
            fn = self._jit_chunk(self.resized_cfg(
                gear_cols, queue_capacity, send_budget
            ))
            if self.compile_ledger is not None:
                fn = self.compile_ledger.instrument(
                    "chunk",
                    f"cap={queue_capacity}/box={send_budget}"
                    f"/gear={gear_cols}",
                    "pressure_regrow", fn,
                )
            self._resized_chunks[key] = fn
        return fn(state, params)

    def resized_cfg(
        self, gear_cols: int, queue_capacity: int, send_budget: int
    ) -> EngineConfig:
        """The escalated EngineConfig `run_chunk_resized` compiles (shared
        so tests can assert the pinning rules)."""
        base = self.cfg
        return dataclasses.replace(
            base,
            queue_capacity=queue_capacity,
            sends_per_host_round=send_budget,
            gear_cols=gear_cols if 0 < gear_cols < send_budget else 0,
            microstep_limit=base.effective_microstep_limit,
            max_round_inserts=(
                queue_capacity
                if base.max_round_inserts == base.queue_capacity
                else base.max_round_inserts
            ),
        )

    def build_capture_step(self):
        """Jitted single round returning (state, sent-outbox) for pcap
        synthesis; built on demand (capture trades speed for observability)."""
        axis = AXIS if self.mesh is not None else None
        step = functools.partial(_round_step_capture, self.cfg, self.model, axis)
        if self.mesh is not None:
            state_spec = self.state_specs()
            sh = P(AXIS)
            ob_spec = Outbox(dst=sh, t=sh, order=sh, kind=sh, payload=sh, count=sh)
            step = _shard_map(
                step, self.mesh, (state_spec, self.param_specs()),
                (state_spec, ob_spec),
            )
        return jax.jit(step)

    # ---- sharding specs ----------------------------------------------------

    def _model_specs(self, tree):
        """Model pytree sharding: host-dim sharded, EXCEPT dict keys named
        `global_*`, which stay replicated — cross-host lookup tables a lane
        must gather by GLOBAL host id (e.g. the mixed model's plane map;
        same role as the engine's replicated node_of)."""

        def walk(t):
            if isinstance(t, dict):
                return {
                    k: (jax.tree.map(lambda _: P(), v)
                        if k.startswith("global_") else walk(v))
                    for k, v in t.items()
                }
            return jax.tree.map(lambda _: P(AXIS), t)

        return walk(tree)

    def state_specs(self):
        sh, rep = P(AXIS), P()
        if self.cfg.queue_block:
            qspec = BucketQueue(
                t=sh, order=sh, kind=sh, payload=sh, dropped=sh,
                bt=sh, bo=sh, bfill=sh,
            )
        else:
            qspec = EventQueue(t=sh, order=sh, kind=sh, payload=sh, dropped=sh)
        return SimState(
            now=rep,
            done=rep,
            queue=qspec,
            rng=RngState(s=sh),
            seq=sh,
            sent_round=sh,
            cpu_busy_until=sh,
            tb_egress=TBState(tokens=sh, last_itv=sh),
            tb_ingress=TBState(tokens=sh, last_itv=sh),
            codel=jax.tree.map(lambda _: sh, codel_init(1)),
            min_used_lat=rep,
            model=self._model_state_spec_tree,
            outbox=Outbox(dst=sh, t=sh, order=sh, kind=sh, payload=sh, count=sh),
            stats=Stats(
                events=sh,
                pkts_sent=sh,
                pkts_lost=sh,
                pkts_unreachable=sh,
                pkts_codel_dropped=sh,
                pkts_delivered=sh,
                monotonic_violations=sh,
                pkts_budget_dropped=sh,
                faults_dropped=sh,
                faults_delayed=sh,
                ob_dropped=sh,
                a2a_shed=sh,
                microsteps=sh,
                bq_rebuilds=sh,
                popk_deferred=sh,
                ici_bytes=sh,
                q_occ_hwm=sh,
                outbox_hwm=sh,
                gear_shed=sh,
                digest=sh,
                rounds=rep,
                pressure=sh if self.cfg.pressure_abort else None,
                ec_timer=sh if self.cfg.netobs else None,
                ec_pkt=sh if self.cfg.netobs else None,
                ec_app=sh if self.cfg.netobs else None,
                fl_done=sh if self.cfg.flow_ledger_active else None,
                fl_bytes=sh if self.cfg.flow_ledger_active else None,
                fl_rtx=sh if self.cfg.flow_ledger_active else None,
                win_bound=sh if self.cfg.netobs else None,
                integrity=sh if self.cfg.integrity else None,
                iv_mask=sh if self.cfg.integrity else None,
                iv_round=sh if self.cfg.integrity else None,
                digest2=sh if self.cfg.integrity_dual else None,
                wheel_spilled=sh if self.cfg.wheel_active else None,
                wheel_occ_hwm=sh if self.cfg.wheel_active else None,
                fl_bg_bytes=rep if self.cfg.fluid_active else None,
                fl_bg_dropped=rep if self.cfg.fluid_active else None,
                ici_intra=sh if self.cfg.hier_active else None,
                ici_inter=sh if self.cfg.hier_active else None,
            ),
            trace=(
                TraceRing(rows=sh, cursor=sh) if self.cfg.trace_rounds
                else None
            ),
            flows=(
                FlowLedger(rows=sh, cursor=sh)
                if self.cfg.flow_ledger_active else None
            ),
            wheel=(
                BucketQueue(
                    t=sh, order=sh, kind=sh, payload=sh, dropped=sh,
                    bt=sh, bo=sh, bfill=sh,
                )
                if self.cfg.wheel_active else None
            ),
            fluid=(
                FluidState(rates=rep, link_util=rep)
                if self.cfg.fluid_active else None
            ),
        )

    def param_specs(self):
        sh, rep = P(AXIS), P()
        rows = sh if getattr(self, "_has_rows", False) else None
        # fault schedule: crash windows are per-host (sharded), the
        # link-fault windows are global (replicated). Mirrors the None
        # structure of EngineParams.faults exactly.
        faults = None
        if self.cfg.faults_active:
            cw = self.cfg.fault_crash_windows > 0
            lw = self.cfg.fault_loss_windows > 0
            faults = FaultParams(
                down_t=sh if cw else None,
                up_t=sh if cw else None,
                win_start=rep if lw else None,
                win_end=rep if lw else None,
                win_loss=rep if lw else None,
                win_lat=rep if lw else None,
            )
        # fluid schedule: classes and links are global — replicated,
        # mirroring the None structure of EngineParams.fluid exactly
        fluid = None
        if self.cfg.fluid_active:
            fluid = FluidParams(
                src_zone=rep, dst_zone=rep, demand=rep,
                win_start=rep, win_end=rep, capacity=rep,
            )
        return EngineParams(
            node_of=rep,
            lat_ns=rep,
            loss=rep,
            jitter_ns=rep,
            eg_tb=TBParams(capacity=sh, refill=sh),
            in_tb=TBParams(capacity=sh, refill=sh),
            model=self._model_param_spec_tree,
            lat_rows=rows,
            loss_rows=rows,
            jit_rows=rows,
            faults=faults,
            fluid=fluid,
        )

    # ---- initialization ----------------------------------------------------

    def init_state(
        self,
        params: EngineParams,
        model_state,
        initial_events: list[tuple[int, int, int, tuple]],
        seed: int,
    ) -> tuple[SimState, EngineParams]:
        """Returns (state, params) — params come back re-device_put with the
        mesh sharding when running multi-device; always use the returned pair."""
        cfg = self.cfg
        if (params.faults is not None) != cfg.faults_active:
            raise ValueError(
                "EngineParams.faults must be provided iff the EngineConfig "
                "declares fault windows (fault_crash_windows/"
                "fault_loss_windows) — build both from one FaultSchedule "
                "(core/faults.compile_faults)"
            )
        if (params.fluid is not None) != cfg.fluid_active:
            raise ValueError(
                "EngineParams.fluid must be provided iff the EngineConfig "
                "declares fluid classes (fluid_classes > 0) — build both "
                "from one FluidSchedule (net/fluid.compile_fluid)"
            )
        self._model_state_spec_tree = self._model_specs(model_state)
        self._model_param_spec_tree = self._model_specs(params.model)
        n_nodes = params.lat_ns.shape[0]
        # rows cost H x N x 20 bytes of HBM and the reduction reads them
        # per send: cap the product (beyond it the 2-D gather path is the
        # lesser evil — e.g. 100k hosts on a 2k-node graph)
        import os as _os  # experiment gate, see BASELINE.md routing notes

        rows_ok = cfg.num_hosts * n_nodes <= 32 << 20 and not _os.environ.get(
            "SHADOW_TPU_FORCE_GATHER_ROUTING"
        )
        # affine host->node detection (see EngineConfig.hosts_per_node)
        if n_nodes > 1:
            node_np = np.asarray(params.node_of)
            counts = np.bincount(node_np, minlength=n_nodes)
            g = int(counts[0])
            if (
                g > 0
                and (counts == g).all()
                and (node_np == np.arange(node_np.shape[0]) // g).all()
            ):
                self.cfg = cfg = dataclasses.replace(cfg, hosts_per_node=g)
        if params.lat_ns.shape != (1, 1) and rows_ok and params.lat_rows is None:
            # materialize the per-host routing rows (see EngineParams)
            with host_build_context():
                node = np.asarray(params.node_of)
                params = params._replace(
                    lat_rows=jnp.asarray(np.asarray(params.lat_ns)[node]),
                    loss_rows=jnp.asarray(np.asarray(params.loss)[node]),
                    jit_rows=jnp.asarray(np.asarray(params.jitter_ns)[node]),
                )
        self._has_rows = params.lat_rows is not None
        self._build_run_chunk()
        with host_build_context():
            if cfg.wheel_active:
                # seeded timer events boot straight into the wheel —
                # same routing as runtime pushes, so a timer-dominant
                # boot population never constrains queue capacity
                from shadow_tpu.ops.wheel import resolve_wheel_block

                queue, wheel_flat, seq = seed_queue_wheel(
                    cfg, initial_events,
                    tuple(getattr(self.model, "timer_kinds", ())),
                )
                wheel = bucket_rebuild(
                    wheel_flat,
                    resolve_wheel_block(cfg.wheel_slots, cfg.wheel_block),
                )
            else:
                queue, seq = seed_queue(cfg, initial_events)
                wheel = None
            if cfg.queue_block:
                queue = bucket_rebuild(queue, cfg.queue_block)
            state = SimState(
                now=jnp.zeros((), jnp.int64),
                done=jnp.zeros((), bool),
                queue=queue,
                rng=rng_init(cfg.num_hosts, seed),
                seq=seq,
                sent_round=jnp.zeros((cfg.num_hosts,), jnp.int32),
                cpu_busy_until=jnp.zeros((cfg.num_hosts,), jnp.int64),
                tb_egress=tb_init(params.eg_tb),
                tb_ingress=tb_init(params.in_tb),
                codel=codel_init(cfg.num_hosts),
                min_used_lat=jnp.asarray(cfg.static_min_latency, jnp.int64),
                model=model_state,
                outbox=_init_outbox(cfg),
                stats=_init_stats(cfg),
                trace=(
                    make_trace_ring(cfg.world, cfg.trace_rounds)
                    if cfg.trace_rounds
                    else None
                ),
                flows=(
                    make_flow_ledger(cfg.world, cfg.flow_records)
                    if cfg.flow_ledger_active
                    else None
                ),
                wheel=wheel,
                fluid=(
                    make_fluid_state(cfg.fluid_classes, cfg.fluid_links)
                    if cfg.fluid_active
                    else None
                ),
            )
        if self.mesh is not None:
            state = jax.device_put(
                state,
                jax.tree.map(
                    lambda s: NamedSharding(self.mesh, s), self.state_specs()
                ),
            )
            params = jax.device_put(
                params,
                jax.tree.map(
                    lambda s: NamedSharding(self.mesh, s), self.param_specs()
                ),
            )
        else:
            dev = jax.devices()[0]
            state = jax.device_put(state, dev)
            params = jax.device_put(params, dev)
        return state, params


# --------------------------------------------------------------------------
# the round loop (pure function of (cfg, model, axis); shard-local arrays)
# --------------------------------------------------------------------------


def _pmin(x, axis):
    return lax.pmin(x, axis) if axis else x


def _run_chunk(cfg: EngineConfig, model, axis, state: SimState, params: EngineParams):
    # gear-abort: once a round's sliced exchange sheds, every further round
    # of this chunk is wasted work (the driver will discard the result and
    # replay from its snapshot one gear up), so the loop stops at the first
    # shed. gear_shed carries the psum'd GLOBAL count, so the condition is
    # uniform across shards and the mesh exits together. The pressure
    # plane's first-drop abort (cfg.pressure_abort) is the same mechanism
    # on the psum'd capacity-drop total: the driver either regrows and
    # replays (escalate) or stops with honest artifacts (abort).
    shed0 = state.stats.gear_shed[0] if cfg.gear_active else None
    press0 = state.stats.pressure[0] if cfg.pressure_abort else None
    # integrity sentinel: stop at the first violating round — every
    # further round of this chunk would run on state a guard already
    # called corrupt, and the driver's classifier discards the attempt
    # and replays from its pre-chunk snapshot anyway. `stats.integrity`
    # is psum'd, so the condition is uniform across the mesh.
    iv0 = state.stats.integrity[0] if cfg.integrity else None

    def cond(carry):
        st, i = carry
        # effective_rounds_per_chunk, not rounds_per_chunk: at million-host
        # scale the valve-clamped bound sidesteps the XLA while-loop
        # pathology (the property's docstring has the numbers)
        ok = (~st.done) & (i < cfg.effective_rounds_per_chunk)
        if shed0 is not None:
            ok = ok & (st.stats.gear_shed[0] <= shed0)
        if press0 is not None:
            ok = ok & (st.stats.pressure[0] <= press0)
        if iv0 is not None:
            ok = ok & (st.stats.integrity[0] <= iv0)
        return ok

    def body(carry):
        st, i = carry
        st = _round_step(cfg, model, axis, st, params)
        return st, i + 1

    state, _ = lax.while_loop(cond, body, (state, jnp.zeros((), jnp.int64)))
    return state


def _run_guarded_chunk(
    cfg: EngineConfig, model, axis, stop_probe, st: SimState,
    params: EngineParams, until,
):
    """Run rounds while the global min event time stays below `until` AND
    `stop_probe(model_state)` is False. The co-simulation bridge uses this
    to batch many device rounds into one dispatch while the CPU plane is
    idle, exiting as soon as a round produces host-bound deliveries (the
    probe) so the CPU plane can react — conservative lookahead stays exact
    because the CPU plane's earliest possible influence is `until` +
    min-latency (SURVEY.md §7 hard parts 5-6).

    Runs at whatever merge gear `cfg.gear_cols` selects, with the same
    first-shed abort as `_run_chunk` (the hybrid driver snapshots before
    the dispatch and replays one gear up on a shed), and the same
    first-drop pressure abort when `cfg.pressure_abort` is set."""
    shed0 = st.stats.gear_shed[0] if cfg.gear_active else None
    press0 = st.stats.pressure[0] if cfg.pressure_abort else None
    iv0 = st.stats.integrity[0] if cfg.integrity else None

    def cond(carry):
        stc, i = carry
        gmin = _pmin(
            jnp.min(_effective_next(cfg, stc, _hold_faults(cfg, params))), axis
        )
        probe = stop_probe(stc.model)
        if axis:
            # the probe sees only the LOCAL shard's model state; the loop
            # decision must be global or shards exit at different rounds and
            # the survivors deadlock in the next round's collectives
            probe = lax.pmax(probe.astype(jnp.int32), axis) > 0
        ok = (
            (~stc.done)
            & (i < cfg.effective_rounds_per_chunk)
            & (gmin < until)
            & (~probe)
        )
        if shed0 is not None:
            ok = ok & (stc.stats.gear_shed[0] <= shed0)
        if press0 is not None:
            ok = ok & (stc.stats.pressure[0] <= press0)
        if iv0 is not None:
            # first-violation stop, same mechanism as the pressure abort
            # (the hybrid driver raises IntegrityAbort on it — the CPU
            # plane cannot roll back, so no replay classification there)
            ok = ok & (stc.stats.integrity[0] <= iv0)
        return ok

    def body(carry):
        stc, i = carry
        stc = _round_step(cfg, model, axis, stc, params)
        return stc, i + 1

    state, _ = lax.while_loop(cond, body, (st, jnp.zeros((), jnp.int64)))
    return state


def _compute_window(cfg: EngineConfig, axis, st: SimState, faults=None):
    """Barrier + window (controller.rs:88-112): (window_end, done)."""
    lmin = jnp.min(_effective_next(cfg, st, faults))
    gmin = _pmin(lmin, axis)
    done = gmin >= cfg.stop_time  # TIME_MAX (empty everywhere) implies done
    gmin_safe = jnp.minimum(gmin, cfg.stop_time)
    runahead = (
        jnp.maximum(jnp.asarray(cfg.runahead_floor, jnp.int64), st.min_used_lat)
        if cfg.use_dynamic_runahead
        else jnp.asarray(max(cfg.runahead_floor, cfg.static_min_latency), jnp.int64)
    )
    window_end = jnp.minimum(gmin_safe + jnp.maximum(runahead, 1), cfg.stop_time)
    return window_end, done


def _round_step(cfg: EngineConfig, model, axis, st: SimState, params: EngineParams):
    window_end, done = _compute_window(cfg, axis, st, _hold_faults(cfg, params))
    return _window_step(cfg, model, axis, st, params, window_end, done)


def _round_step_capture(
    cfg: EngineConfig, model, axis, st: SimState, params: EngineParams
):
    """One round that ALSO returns the pre-exchange outbox — the packets
    sent this round, for host-side pcap synthesis (the modeled-sim analogue
    of the reference's per-interface capture, network_interface.c). One
    dispatch per round: capture runs trade throughput for observability."""
    window_end, done = _compute_window(cfg, axis, st, _hold_faults(cfg, params))
    return _window_step(
        cfg, model, axis, st, params, window_end, done, capture=True
    )


def _window_step(
    cfg: EngineConfig, model, axis, st: SimState, params: EngineParams,
    window_end, done, capture: bool = False,
):
    """Execute one scheduling window [*, window_end): microsteps + exchange.

    Split out of `_round_step` so the co-simulation bridge
    (`shadow_tpu.cosim`) can drive lockstep windows whose end is computed
    jointly with the CPU host plane instead of from the device queues alone.
    """
    h_local = st.queue.t.shape[0]
    shard_start = (
        lax.axis_index(axis).astype(jnp.int64) * h_local if axis else jnp.int64(0)
    )
    host_gid = shard_start + jnp.arange(h_local, dtype=jnp.int64)

    # ---- fluid traffic plane (net/fluid.py): the fluid->packet half of
    # the conservative coupling, computed ONCE per round from the
    # PREVIOUS round's ODE state (the round's committed window has not
    # run yet — using last round's utilization keeps the factors
    # loop-invariant across this round's microsteps). Per-host extra
    # loss probability and latency multiplier (>= 1.0 by construction:
    # inflation only, so the conservative-lookahead bound — which uses
    # the pre-inflation minimum — stays valid; the safe-window psum is
    # untouched). Zero background load yields loss 0.0 / multiplier
    # exactly 1.0x on every host — value-identical to fluid-off.
    fluid_fx = None
    if cfg.fluid_active:
        fluid_fx = fluid_host_effects(
            cfg, params.fluid, st.fluid, _host_nodes(cfg, params, host_gid)
        )

    # ---- safe-window telemetry (network observatory): which shard's
    # local min event time bound this round's all-reduce-min barrier —
    # the critical-path shard (ties to the lowest shard id, so the value
    # is deterministic and identical on every shard). One extra local
    # min + pmin per round, traced only when the observatory is on.
    bind_shard = None
    if cfg.netobs:
        nb_lmin = jnp.min(
            _effective_next(cfg, st, _hold_faults(cfg, params))
        )
        if axis:
            nb_gmin = _pmin(nb_lmin, axis)
            me = lax.axis_index(axis).astype(jnp.int64)
            bind_shard = _pmin(
                jnp.where(nb_lmin == nb_gmin, me, jnp.int64(cfg.world)),
                axis,
            )
        else:
            me = jnp.int64(0)
            bind_shard = jnp.int64(0)

    # ---- 3: microsteps (no collectives inside — shards proceed independently)
    if cfg.effective_microstep_events > 1:
        # K-way fold: the valve is a PER-HOST executed-event vector, bound
        # by its max — not a global sum of per-dispatch maxima, which
        # could overcharge (dispatch 1 charges host A's fold of 8 while
        # host B retired 1) and bind EARLIER than K=1. Per-host, a host
        # that would finish its round under K=1's limit always finishes
        # here too: its count before its last dispatch is at most
        # total - 1 < limit, so the strict < never cuts a non-pathological
        # round short — see EngineConfig.effective_microstep_limit.
        # `steps` keeps counting real dispatches for stats. Progress is
        # still guaranteed (batch index 0 can never defer, so every
        # dispatch with the cond held retires >= 1 event on some host).
        h_local = st.queue.t.shape[0]

        def micro_cond(carry):
            stc, valve, steps = carry
            return jnp.any(
                _effective_next(cfg, stc, _hold_faults(cfg, params))
                < window_end
            ) & (jnp.max(valve) < cfg.effective_microstep_limit)

        def micro_body(carry):
            stc, valve, steps = carry
            stc, executed = _microstep_k(
                cfg, model, stc, params, host_gid, window_end, fluid_fx
            )
            return stc, valve + executed.astype(jnp.int64), steps + 1

        with jax.named_scope("shadow_microsteps"):
            st_m, _, steps = lax.while_loop(
                micro_cond,
                micro_body,
                (st, jnp.zeros((h_local,), jnp.int64), jnp.zeros((), jnp.int64)),
            )
    else:
        def micro_cond(carry):
            stc, steps = carry
            return jnp.any(
                _effective_next(cfg, stc, _hold_faults(cfg, params))
                < window_end
            ) & (steps < cfg.effective_microstep_limit)

        def micro_body(carry):
            stc, steps = carry
            stc = _microstep(
                cfg, model, stc, params, host_gid, window_end, fluid_fx
            )
            return stc, steps + 1

        with jax.named_scope("shadow_microsteps"):
            st_m, steps = lax.while_loop(
                micro_cond, micro_body, (st, jnp.zeros((), jnp.int64))
            )

    # ---- 4: exchange staged packets across the mesh
    with jax.named_scope("shadow_exchange"):
        st_x = _exchange(cfg, axis, st_m)

    # queue-occupancy high-water, sampled at the post-merge peak (cheap:
    # the bucketed queue reads its bfill caches; flat pays one [H, C]
    # compare+sum per ROUND, noise next to the microsteps it follows)
    occ = q_len(st_x.queue).astype(jnp.int64)
    # outbox-send high-water: the most sends any one host staged THIS
    # round (pre-exchange cursor max — the gear controller's signal).
    # Always on: one [H] max per round, noise next to the occ pass above.
    ob_hwm = jnp.max(st_m.sent_round).astype(jnp.int64)
    stats = st_x.stats._replace(
        rounds=st_x.stats.rounds + jnp.where(done, 0, 1),
        microsteps=st_x.stats.microsteps + steps[None],
        q_occ_hwm=jnp.maximum(st_x.stats.q_occ_hwm, occ),
        outbox_hwm=jnp.maximum(st_x.stats.outbox_hwm, ob_hwm[None]),
    )
    if cfg.wheel_active:
        # wheel-occupancy high-water, same cadence as q_occ_hwm (cheap:
        # the wheel always reads its bfill caches). The exchange never
        # touches the wheel, so the post-exchange sample is the round's
        # post-push peak.
        w_occ = q_len(st_x.wheel).astype(jnp.int64)
        stats = stats._replace(
            wheel_occ_hwm=jnp.maximum(stats.wheel_occ_hwm, w_occ)
        )
    if cfg.netobs:
        # this shard bound the barrier this round (done-rounds are not
        # scheduling rounds and do not count, mirroring stats.rounds)
        stats = stats._replace(
            win_bound=stats.win_bound
            + jnp.where(done | (me != bind_shard), 0, 1)[None]
        )
    if cfg.pressure_abort:
        # pressure signal: the shard-local capacity-drop total (queue-push
        # overflow + merge/merge_rows sheds in queue.dropped, alltoall
        # block sheds, outbox overflow, per-host send-budget drops),
        # psum'd so every shard carries the GLOBAL cumulative count and
        # the chunk loop's first-drop abort stays mesh-uniform. Two [H]
        # sums + one psum per round — noise next to the occ pass above.
        local = (
            jnp.sum(st_x.queue.dropped)
            + jnp.sum(stats.pkts_budget_dropped)
            + stats.a2a_shed[0]
            + stats.ob_dropped[0]
        )
        total = lax.psum(local, axis) if axis else local
        stats = stats._replace(pressure=total[None])
    if cfg.integrity:
        # integrity sentinel (core/integrity.py): evaluate the per-round
        # invariant guards on the post-exchange state. The count is
        # psum'd so the chunk loop's first-violation abort is uniform
        # across the mesh; the (shard, round, mask) signature lanes stay
        # per-shard so the replay classifier can name the violating
        # shard. The final done-round is not a scheduling round and is
        # never judged (mirrors stats.rounds).
        iv_viol, iv_m = _integrity_round_check(
            cfg, axis, st, st_m, st_x, stats, window_end, done, ob_hwm
        )
        iv_total = lax.psum(iv_viol, axis) if axis else iv_viol
        stats = stats._replace(
            integrity=stats.integrity + iv_total[None],
            iv_mask=stats.iv_mask | iv_m[None],
            iv_round=jnp.where(
                (stats.iv_round < 0) & (iv_m != 0),
                st.stats.rounds,
                stats.iv_round,
            ),
        )
    fluid_new = None
    if cfg.fluid_active:
        # packet->fluid half of the coupling + the ODE advance: the
        # pre-exchange outbox's bytes per link (psum'd — every shard
        # sees the GLOBAL count) subtract from fluid capacity, then one
        # forward-Euler step over the committed window updates the
        # replicated rate/utilization lanes and the background byte
        # counters. Runs on the post-microstep outbox (st_m), BEFORE the
        # exchange cleared it.
        fg_link = _fluid_fg_link_bytes(cfg, axis, st_m.outbox, params,
                                       host_gid)
        fluid_new, bg_dlv, bg_drp = fluid_advance(
            cfg, params.fluid, st.fluid, fg_link, st.now, window_end, done
        )
        stats = stats._replace(
            fl_bg_bytes=stats.fl_bg_bytes + bg_dlv,
            fl_bg_dropped=stats.fl_bg_dropped + bg_drp,
        )
    min_used = _pmin(st_x.min_used_lat, axis)
    out = st_x._replace(
        now=jnp.where(done, st.now, window_end),
        done=done,
        min_used_lat=min_used,
        stats=stats,
    )
    if cfg.fluid_active:
        out = out._replace(fluid=fluid_new)
    if cfg.trace_rounds:
        out = out._replace(
            trace=_trace_round(
                cfg, st, st_m, st_x, window_end, done, steps, occ, ob_hwm,
                params.faults, bind_shard=bind_shard,
            )
        )
    if capture:
        return out, st_m.outbox  # this round's sends, pre-exchange
    return out


def _trace_round(
    cfg: EngineConfig, st0: SimState, st_m: SimState, st_x: SimState,
    window_end, done, steps, occ, ob_hwm, faults=None, bind_shard=None,
):
    """Append this round's record to the in-scan trace ring.

    Strictly an observer: every value is either already computed by the
    round (window bounds, steps, occ) or a difference of counters the
    round maintains anyway — nothing downstream reads the ring, so the
    scheduling dataflow is untouched and digests/events/drops stay
    bit-identical with tracing on or off. The final done-round (which
    does not count in stats.rounds) is skipped the same way.

    `st0` is the round-entry state (for counter deltas), `st_m` the
    post-microstep state (for the pre-exchange outbox count), `st_x` the
    post-exchange state."""
    ring: TraceRing = st_x.trace

    def delta(get):
        return (get(st_x.stats) - get(st0.stats))[0]

    vals = [jnp.zeros((), jnp.int64)] * TRACE_COLS
    vals[COL_ROUND] = st0.stats.rounds
    vals[COL_WINDOW_START] = st0.now
    vals[COL_WINDOW_END] = window_end
    vals[COL_EVENTS] = jnp.sum(st_x.stats.events - st0.stats.events)
    vals[COL_MICROSTEPS] = steps
    vals[COL_POPK_DEFERRED] = delta(lambda s: s.popk_deferred)
    vals[COL_BQ_REBUILDS] = delta(lambda s: s.bq_rebuilds)
    vals[COL_ICI_BYTES] = delta(lambda s: s.ici_bytes)
    vals[COL_SENDS] = st_m.outbox.count[0].astype(jnp.int64)
    vals[COL_A2A_SHED] = delta(lambda s: s.a2a_shed)
    vals[COL_OCC_HWM] = jnp.max(occ)
    vals[COL_NEXT_TIME] = jnp.min(q_next_time(st_x.queue))
    vals[COL_OB_HWM] = ob_hwm
    vals[COL_GEAR] = jnp.asarray(cfg.effective_gear_cols, jnp.int64)
    vals[COL_CAP] = jnp.asarray(cfg.queue_capacity, jnp.int64)
    if cfg.faults_active:
        vals[COL_FAULTS_DROPPED] = jnp.sum(
            st_x.stats.faults_dropped - st0.stats.faults_dropped
        )
        vals[COL_FAULTS_DELAYED] = jnp.sum(
            st_x.stats.faults_delayed - st0.stats.faults_delayed
        )
    if cfg.fault_crash_windows and faults is not None:
        h = st_x.queue.t.shape[0]
        down, _ = down_and_resume(
            faults, jnp.broadcast_to(window_end, (h,))
        )
        vals[COL_HOSTS_DOWN] = jnp.sum(down, dtype=jnp.int64)
    if cfg.netobs:
        # network-observatory columns (netobs-off traced runs keep zeros
        # here — the columns exist so recorded traces stay positional)
        vals[COL_EC_TIMER] = delta(lambda s: s.ec_timer)
        vals[COL_EC_PKT] = delta(lambda s: s.ec_pkt)
        vals[COL_EC_APP] = delta(lambda s: s.ec_app)
        if cfg.flow_ledger_active:
            vals[COL_FLOWS] = delta(lambda s: s.fl_done)
        if bind_shard is not None:
            vals[COL_BIND_SHARD] = bind_shard
    if cfg.hier_active:
        # hierarchical-exchange tier columns (flat-exchange traced runs
        # keep zeros here — positional like the netobs columns)
        vals[COL_XW_INTRA] = delta(lambda s: s.ici_intra)
        vals[COL_XW_INTER] = delta(lambda s: s.ici_inter)
    row = jnp.stack([jnp.asarray(v, jnp.int64) for v in vals])
    # the cursor is a registered i64 lane (core/lanes.py); the slice index
    # stays i64 rather than narrowing the lane value (shadowlint R2)
    idx = ring.cursor[0] % cfg.trace_rounds
    written = lax.dynamic_update_slice(
        ring.rows, row[None, None, :], (jnp.int64(0), idx, jnp.int64(0))
    )
    # the done-round is not a scheduling round: no row, no cursor bump
    return TraceRing(
        rows=jnp.where(done, ring.rows, written),
        cursor=ring.cursor + jnp.where(done, 0, 1),
    )


def _integrity_round_check(
    cfg: EngineConfig, axis, st0: SimState, st_m: SimState, st_x: SimState,
    stats: Stats, window_end, done, ob_hwm,
):
    """The integrity sentinel's per-round invariant guards
    (core/integrity.py names the bits). Returns (local violation count
    i64, local invariant bitmask i64), both zeroed on the done-round.

    Every check below is UNCONDITIONAL — satisfied by construction on
    every legal engine trajectory, so a trip always means corrupted
    state (or a real engine bug, which the replay classifier
    distinguishes). The derivations:

      IV_TIME (a) window monotonicity: window_end = min(gmin_eff + ra,
        stop) with gmin_eff >= committed now (leftover events are >= the
        previous window end, floor-held events' effective time is their
        restart/busy horizon >= now) and stop >= now — so a regressing
        window means a past-time value appeared in the time plane. The
        one legal exception is a valve-bound round (the livelock
        condition leaves in-window events behind) combined with DYNAMIC
        runahead shrink, so (a) is traced only under static runahead.
      IV_TIME (b) slab floor: every event present at round entry is
        >= the entry's global raw minimum; pops remove, pushes carry
        t >= the executing event's time >= that minimum, and merged
        arrivals are >= window_end > it — so no post-round slot may
        hold a smaller time (catches in-flight scribbles on the time
        plane within the round, any runahead mode).
      IV_EC: ec_timer/ec_pkt/ec_app bucket the exact `active` mask
        stats.events counts (`_event_body`), so the class sums equal
        the event total per shard — the netobs reconciliation CHECK
        promoted to a hard guard (traced only when the observatory is).
      IV_QFILL: the bucketed queue's per-block fill caches are
        incrementally maintained to equal the slab's true occupancy
        (tests/test_bucketq.py gates the ops); one [H, C] compare+sum
        re-derives the truth (bucketed layouts only).
      IV_COUNTER: every event/drop/fault counter only ever adds
        non-negative masks — deltas are >= 0 and values never negative.
      IV_OUTBOX: sent_round increments by booleans gated on the budget,
        so no host's round cursor exceeds sends_per_host_round, cursors
        stay non-negative, and the count word stays in [0, H x B].
      IV_DIGEST: a host with zero executed events has never passed
        through `_digest_update`, so both digest lanes still carry
        their initial offsets (the dual lane shares no constants with
        the primary — core/integrity.classify_digest_pair)."""
    from shadow_tpu.core.integrity import (
        IV_COUNTER,
        IV_DIGEST,
        IV_EC,
        IV_OUTBOX,
        IV_QFILL,
        IV_TIME,
    )

    checks: list[tuple[int, Any]] = []
    entry_min = jnp.min(st0.queue.t)
    post_min = jnp.min(st_x.queue.t)
    if cfg.wheel_active:
        # the wheel's time plane is part of the same slab-floor law:
        # pending timers obey the identical >= entry-minimum argument
        entry_min = jnp.minimum(entry_min, jnp.min(st0.wheel.t))
        post_min = jnp.minimum(post_min, jnp.min(st_x.wheel.t))
    gmin_raw = _pmin(entry_min, axis)
    t_bad = post_min < gmin_raw
    if cfg.integrity_strict_time and not cfg.use_dynamic_runahead:
        # see the IV_TIME (a) derivation above: valve-bound rounds under
        # DYNAMIC runahead (shrinking ra) and the hybrid bridge
        # (cfg.integrity_strict_time False) are the two legal exceptions
        t_bad = t_bad | (window_end < st0.now)
    checks.append((IV_TIME, t_bad))
    if cfg.netobs:
        ec_sum = stats.ec_timer[0] + stats.ec_pkt[0] + stats.ec_app[0]
        checks.append((IV_EC, ec_sum != jnp.sum(stats.events)))
    if cfg.queue_block:
        # judged PRE-exchange (st_m): the merge rebuilds the caches
        # wholesale whenever any shard sent, which would erase a
        # divergence before a post-exchange read could see it — the
        # incrementally-maintained pre-merge caches carry one through
        occ_true = jnp.sum(
            st_m.queue.t != TIME_MAX, axis=1, dtype=jnp.int32
        )
        checks.append((
            IV_QFILL,
            jnp.any(occ_true != jnp.sum(st_m.queue.bfill, axis=1)),
        ))
    if cfg.wheel_active:
        # the wheel's fill caches obey the same incremental-maintenance
        # invariant (it IS the BucketQueue machinery; no merge rebuild
        # ever masks a divergence, so post-exchange is equally valid)
        w_occ_true = jnp.sum(
            st_m.wheel.t != TIME_MAX, axis=1, dtype=jnp.int32
        )
        checks.append((
            IV_QFILL,
            jnp.any(w_occ_true != jnp.sum(st_m.wheel.bfill, axis=1)),
        ))
    c_bad = jnp.any(st_x.queue.dropped < st0.queue.dropped) | jnp.any(
        st_x.queue.dropped < 0
    )
    if cfg.wheel_active:
        # spill routing pre-empts every wheel overflow: a nonzero wheel
        # drop counter means the free accounting (or the slab) is corrupt
        c_bad = c_bad | jnp.any(st_x.wheel.dropped != 0)
        c_bad = c_bad | jnp.any(
            stats.wheel_spilled < st0.stats.wheel_spilled
        ) | jnp.any(stats.wheel_spilled < 0)
    for get in (
        lambda s: s.events,
        lambda s: s.pkts_sent,
        lambda s: s.pkts_lost,
        lambda s: s.pkts_unreachable,
        lambda s: s.pkts_codel_dropped,
        lambda s: s.pkts_delivered,
        lambda s: s.pkts_budget_dropped,
        lambda s: s.faults_dropped,
        lambda s: s.faults_delayed,
    ):
        post, pre = get(stats), get(st0.stats)
        c_bad = c_bad | jnp.any(post < pre) | jnp.any(post < 0)
    checks.append((IV_COUNTER, c_bad))
    b = cfg.sends_per_host_round
    count = st_m.outbox.count[0]
    checks.append((
        IV_OUTBOX,
        (ob_hwm > b)
        | (jnp.min(st_m.sent_round) < 0)
        | (count < 0)
        | (count > st_m.outbox.t.shape[0] * b),
    ))
    virgin = stats.digest != _FNV_OFFSET
    if cfg.integrity_dual:
        virgin = virgin | (stats.digest2 != _DIGEST2_OFFSET)
    checks.append((IV_DIGEST, jnp.any((stats.events == 0) & virgin)))

    mask = jnp.zeros((), jnp.int64)
    viol = jnp.zeros((), jnp.int64)
    for bit, bad in checks:
        bad = bad & ~done
        mask = mask | jnp.where(bad, jnp.int64(1 << bit), jnp.int64(0))
        viol = viol + bad.astype(jnp.int64)
    return viol, mask


def _host_nodes(cfg: EngineConfig, params: EngineParams, host_gid):
    """Per-host graph-node index (the fluid plane's link id): the affine
    divide when init_state detected the uniform-blocks map, else one
    gather from the replicated node_of table — the same two routes the
    send path's destination lookup takes."""
    if cfg.hosts_per_node > 0:
        return (host_gid // cfg.hosts_per_node).astype(jnp.int32)
    return params.node_of[host_gid].astype(jnp.int32)


def _fluid_fg_link_bytes(cfg: EngineConfig, axis, ob: Outbox,
                         params: EngineParams, host_gid):
    """The packet->fluid half of the coupling: this round's foreground
    bytes per fluid link, folded from the pre-exchange outbox — uplink
    bytes charge each sender's access link, downlink bytes the
    destination's. INTEGER scatter-adds only (order-free, so the fold
    is bit-deterministic), psum'd across the mesh so every shard sees
    the GLOBAL count and the replicated ODE stays identical on every
    shard and across mesh shapes."""
    n = cfg.fluid_links
    valid = ob.t != TIME_MAX
    size = jnp.where(
        valid, ob.payload[:, :, PAYLOAD_SIZE_WORD].astype(jnp.int64),
        jnp.int64(0),
    )
    src_node = jnp.clip(_host_nodes(cfg, params, host_gid), 0, n - 1)
    up = jnp.zeros((n,), jnp.int64).at[src_node].add(
        jnp.sum(size, axis=1)
    )
    dst_f = jnp.clip(
        ob.dst.reshape(-1).astype(jnp.int64), 0, cfg.num_hosts - 1
    )
    if cfg.hosts_per_node > 0:
        dnode = (dst_f // cfg.hosts_per_node).astype(jnp.int32)
    else:
        dnode = params.node_of[dst_f].astype(jnp.int32)
    down = jnp.zeros((n,), jnp.int64).at[jnp.clip(dnode, 0, n - 1)].add(
        size.reshape(-1)
    )
    tot = up + down
    return lax.psum(tot, axis) if axis else tot


def _hold_faults(cfg: EngineConfig, params: EngineParams):
    """The fault schedule iff queue-HOLD crash semantics are in force —
    the only fault mode that floors next-event times (clear mode drops at
    pop and never defers)."""
    return params.faults if cfg.fault_hold else None


def _effective_next(cfg: EngineConfig, st: SimState, faults=None):
    """Per-host next *executable* time: queue head, floored by the CPU
    model's busy horizon (a busy host keeps its events queued — order
    intact — and resumes at busy_until, exactly the reference's CPU-delay
    rescheduling, host.rs:820-847) and, under queue-hold crash faults, by
    the host's restart time (a down host's events defer to its up_t —
    same mechanics, different clock)."""
    nt = q_next_time(st.queue)
    if cfg.wheel_active:
        # the timer wheel's head folds into the same min: a due timer is
        # as executable as a due queue event (TIME_MAX sentinels pass
        # through the minimum unchanged)
        nt = jnp.minimum(nt, wheel_next_time(st.wheel))
    if cfg.cpu_delay_ns > 0:
        nt = jnp.where(nt == TIME_MAX, nt, jnp.maximum(nt, st.cpu_busy_until))
    if faults is not None:
        _, resume = down_and_resume(faults, nt)
        nt = jnp.where(nt == TIME_MAX, nt, jnp.maximum(nt, resume))
    return nt


class _EvCarry(NamedTuple):
    """The state threads an executed event reads/writes — everything a
    microstep touches EXCEPT the queue and the outbox, which the two
    microstep shapes (single-event vs K-way fold) apply differently:
    K=1 applies pushes/appends immediately; the K-way fold accumulates
    them across the batch and applies each in ONE fused pass."""

    stats: Stats
    rng: RngState
    seq: Array
    sent_round: Array
    tb_egress: TBState
    tb_ingress: TBState
    codel: Any
    model: Any


def _event_body(cfg, model, c: _EvCarry, params, host_gid, window_end, ev,
                active, fluid_fx=None):
    """Execute one event per `active` host: digest, ingress shaping, model
    dispatch, and egress staging. Returns (carry', push_list, ob_entries,
    used_lats): queue pushes and outbox appends are RETURNED, not applied —
    dataflow-identical for the K=1 caller (pure functions; application
    order does not change any value) and the enabler for the K-way fold's
    amortized single-pass application."""
    stats = c.stats
    stats = stats._replace(
        events=stats.events + active,
        digest=_digest_update(stats.digest, active, ev.t, ev.kind, ev.order),
    )
    if cfg.integrity_dual:
        stats = stats._replace(
            digest2=_digest_update2(
                stats.digest2, active, ev.t, ev.kind, ev.order
            )
        )

    is_pkt = (ev.kind & KIND_PKT) != 0

    if cfg.netobs:
        # event-class accounting (obs/netobs.py): every EXECUTED event —
        # the same `active` mask stats.events counts, so the class sums
        # reconcile exactly with the event total — buckets as packet
        # (engine KIND_PKT flag), timer (the model's declared
        # timer_kinds), or app (the rest). Three [H] masks + sums per
        # event; traced only when the observatory is on.
        cls_timer = active & ~is_pkt & kind_in(
            ev.kind & KIND_MASK, tuple(getattr(model, "timer_kinds", ()))
        )
        stats = stats._replace(
            ec_timer=stats.ec_timer
            + jnp.sum(cls_timer, dtype=jnp.int64)[None],
            ec_pkt=stats.ec_pkt
            + jnp.sum(active & is_pkt, dtype=jnp.int64)[None],
            ec_app=stats.ec_app
            + jnp.sum(active & ~is_pkt & ~cls_timer, dtype=jnp.int64)[None],
        )

    if cfg.shaping:
        needs_ingress = active & is_pkt & ((ev.kind & KIND_INGRESS_DONE) == 0)

        # ---- ingress pipeline: CoDel at the router queue, then the downlink
        # token bucket. The law sees the delay the packet WOULD experience,
        # and only survivors consume bandwidth (reference: the relay pulls
        # from the CoDel queue, so dropped packets are never charged;
        # router/mod.rs:47-62).
        size_bits = jnp.asarray(ev.payload[:, PAYLOAD_SIZE_WORD], jnp.int64) * 8
        no_mask = jnp.zeros_like(needs_ingress)
        _, depart_probe = tb_conforming_remove(
            c.tb_ingress, params.in_tb, cfg.tb_interval_ns, ev.t, size_bits, no_mask
        )
        sojourn = depart_probe - ev.t
        if cfg.use_codel:
            codel, codel_drop = codel_on_packet(c.codel, ev.t, sojourn, needs_ingress)
        else:
            codel, codel_drop = c.codel, jnp.zeros_like(needs_ingress)
        tb_in, depart = tb_conforming_remove(
            c.tb_ingress,
            params.in_tb,
            cfg.tb_interval_ns,
            ev.t,
            size_bits,
            needs_ingress & ~codel_drop,
        )
        delay = needs_ingress & ~codel_drop & (depart > ev.t)
        # the requeue (bucket-delayed packet goes back in the queue past
        # shaping) is deferred into the fused push pass below. It used to
        # be a lax.cond-guarded push_one "optimization" — the profiler
        # showed the conditional itself costing ~40% of the microstep at
        # 10k hosts x capacity 64: an XLA cond is a fusion barrier that
        # copies the full queue slab at the branch boundary every
        # microstep, far more than the one-hot write it was skipping.
        requeue = (delay, depart, ev.order, ev.kind | KIND_INGRESS_DONE,
                   ev.payload)
        stats = stats._replace(
            pkts_codel_dropped=stats.pkts_codel_dropped + codel_drop
        )
        dispatch = active & ~(needs_ingress & (codel_drop | delay))
    else:
        codel, tb_in = c.codel, c.tb_ingress
        requeue = None
        dispatch = active

    # ---- model dispatch (Host::execute -> TaskRef::execute / packet receive)
    stats = stats._replace(pkts_delivered=stats.pkts_delivered + (dispatch & is_pkt))
    ctx = HandlerCtx(
        t=ev.t,
        window_end=window_end,
        kind=ev.kind & KIND_MASK,
        payload=ev.payload,
        active=dispatch,
        is_packet=is_pkt,
        src=unpack_order_src(ev.order),
        host_id=host_gid,
        state=c.model,
        params=params.model,
        rng=c.rng,
    )
    out = model.handle(ctx)
    rng, model_state = out.rng, out.state
    seq = c.seq
    sent_round = c.sent_round
    tb_eg = c.tb_egress

    # ---- flow-completion port (network observatory): the model's
    # FlowDone record becomes one ledger entry (applied in a fused pass
    # by _finish_microstep) and the fl_* totals advance on an
    # INDEPENDENT path from the ring cursor, so reconciliation between
    # the two is a real check. Not traced unless the ledger is on.
    flow_list = []
    if cfg.flow_ledger_active and out.flow is not None:
        f = out.flow
        fmask = f.mask & dispatch
        fbytes = jnp.asarray(f.bytes, jnp.int64)
        frtx = jnp.asarray(f.retransmits, jnp.int64)
        stats = stats._replace(
            fl_done=stats.fl_done + jnp.sum(fmask, dtype=jnp.int64)[None],
            fl_bytes=stats.fl_bytes
            + jnp.sum(jnp.where(fmask, fbytes, 0))[None],
            fl_rtx=stats.fl_rtx
            + jnp.sum(jnp.where(fmask, frtx, 0))[None],
        )
        flow_list.append((
            fmask,
            jnp.asarray(f.dst, jnp.int64),
            jnp.asarray(f.flow, jnp.int64),
            jnp.asarray(f.t_start, jnp.int64),
            ev.t,  # completion time = this event's execution time
            fbytes,
            frtx,
        ))

    # ---- local pushes (schedule_task_* analogue). All ports are applied
    # in ONE slab pass (push_many): sequential push_one calls each pay a
    # full [H, C] read+write because the free-slot reduction between them
    # fences XLA fusion — measured as a dominant per-microstep cost.
    # the ingress requeue goes FIRST so slot-assignment order matches the
    # golden oracle (its qpush runs during ingress, before model pushes)
    push_list = [requeue] if requeue is not None else []
    for p in out.pushes:
        mask = p.mask & dispatch
        t_req = jnp.asarray(p.t, jnp.int64)
        stats = stats._replace(
            monotonic_violations=stats.monotonic_violations + (mask & (t_req < ev.t))
        )
        t_push = jnp.maximum(t_req, ev.t)
        order = pack_order(1, host_gid, seq)
        seq = seq + mask
        push_list.append((
            mask, t_push, order,
            jnp.asarray(p.kind, jnp.int32) & KIND_MASK, p.payload,
        ))

    # ---- sends: egress pipeline (worker.rs:330-425 send_packet). Each
    # port may carry a BURST (PacketSend.count/count_max): up to count_max
    # packets to one destination, sharing the routing lookup (the H x N
    # table reduction is the per-port cost that made one-packet-per-port
    # TCP windows unaffordable) while each segment keeps its own loss
    # draw, bandwidth charge, order key, and budget slot. Outbox writes
    # are deferred and applied in one slab pass after the loop.
    entries = []  # (send_ok, col, dst, arrive, order, kind, payload)
    used_lats = []
    if cfg.fault_loss_windows:
        # link-fault windows active at this event's time: one [H, L] pass
        # per event, shared by every port/segment below. Loss draws come
        # from the per-host masked-advance RNG lanes (mesh-shape
        # invariant); inflation is integer x1000 math so the arrive time
        # is bit-reproducible. Inflation can only GROW latency
        # (latency_factor >= 1.0 is validated at config parse), so the
        # conservative-lookahead bound — which uses the pre-inflation
        # minimum — stays valid.
        f_loss, f_lat = window_effects(params.faults, ev.t)
        # inflation honors bootstrap_end_time like the loss side of the
        # same window: bootstrap-phase traffic stays undisturbed (and
        # uncounted in faults_delayed)
        f_inflate = (f_lat > LAT_SCALE) & (ev.t >= cfg.bootstrap_end_time)
    if cfg.fluid_active:
        # fluid congestion coupling (net/fluid.py): this round's per-host
        # extra-loss probability and latency multiplier, computed once at
        # round start from the background ODE's utilization. Inflation
        # honors bootstrap_end_time like every loss plane, and the loss
        # draw below is a COUNTER-BASED hash (fluid_send_uniform) that
        # never advances the RNG lanes — at zero background load the
        # factors are exactly (0.0, 1.0x) and every value downstream is
        # bit-identical to the fluid-off program.
        bg_loss, bg_lat = fluid_fx
        bg_inflate = (bg_lat > LAT_SCALE) & (
            ev.t >= cfg.bootstrap_end_time
        )
    for s in out.sends:
        cmax = int(getattr(s, "count_max", 1) or 1)
        mask0 = s.mask & dispatch
        # gate on count is None (the documented contract, mirrored by the
        # golden oracle) — NOT on count_max: count=None with count_max>1 is
        # legal, and an explicit count of 0 must suppress the send
        if getattr(s, "count", None) is None:
            counts = mask0.astype(jnp.int32)
        else:
            counts = jnp.where(mask0, jnp.asarray(s.count, jnp.int32), 0)
        sz = jnp.asarray(s.size_bytes, jnp.int32)
        dst_raw = jnp.asarray(s.dst, jnp.int64)
        bad_dst = mask0 & ((dst_raw < 0) | (dst_raw >= cfg.num_hosts))
        dst = jnp.clip(dst_raw, 0, cfg.num_hosts - 1)  # safe gather only
        if params.lat_ns.shape == (1, 1):
            # single graph node (e.g. the 1-gbit-switch topology): the path
            # lookup is a constant — elide the node_of/table gathers, which
            # are a measured per-microstep hot spot on TPU
            lat = jnp.broadcast_to(params.lat_ns[0, 0], dst.shape)
            lossp = jnp.broadcast_to(params.loss[0, 0], dst.shape)
            jit = jnp.broadcast_to(params.jitter_ns[0, 0], dst.shape)
        elif params.lat_rows is not None:
            # node lookup (then a one-hot masked reduction over the node
            # axis for each table — vector work on the VPU instead of
            # scalar-core gathers, see EngineParams.lat_rows). With an
            # affine host->node map even the lookup's gather disappears
            # into a VPU divide (EngineConfig.hosts_per_node).
            if cfg.hosts_per_node > 0:
                dst_node = (dst // cfg.hosts_per_node).astype(jnp.int32)
            else:
                dst_node = params.node_of[dst].astype(jnp.int32)
            n_nodes = params.lat_rows.shape[1]
            eq = (
                jnp.arange(n_nodes, dtype=jnp.int32)[None, :]
                == dst_node[:, None]
            )
            lat = jnp.sum(jnp.where(eq, params.lat_rows, 0), axis=1)
            lossp = jnp.sum(jnp.where(eq, params.loss_rows, 0.0), axis=1)
            jit = jnp.sum(jnp.where(eq, params.jit_rows, 0), axis=1)
        else:
            if cfg.hosts_per_node > 0:
                src_node = host_gid // cfg.hosts_per_node
                dst_node = dst // cfg.hosts_per_node
            else:
                src_node = params.node_of[host_gid]
                dst_node = params.node_of[dst]
            lat = params.lat_ns[src_node, dst_node]
            lossp = params.loss[src_node, dst_node]
            jit = params.jitter_ns[src_node, dst_node]
        lat_bound0 = lat  # pre-jitter: the conservative lookahead quantity
        if cfg.use_jitter:
            lat_bound0 = lat_bound0 - jit
        port_kind = jnp.asarray(s.kind, jnp.int32) | KIND_PKT
        for j in range(cmax):
            mask = mask0 & (counts > j)
            # per-host round budget: the drop decision is a function of
            # this host's own sends only, so it cannot vary with mesh
            # shape. Decided BEFORE the bandwidth charge: a budget-dropped
            # packet must be side-effect-free (no debited bits, no
            # borrowed refill intervals).
            over_budget = sent_round >= cfg.sends_per_host_round
            if cfg.shaping:
                tb_eg, eg_depart = tb_conforming_remove(
                    tb_eg,
                    params.eg_tb,
                    cfg.tb_interval_ns,
                    ev.t,
                    sz.astype(jnp.int64) * 8,
                    mask & ~over_budget,
                )
            else:
                eg_depart = ev.t  # unlimited uplink: no serialization delay
            lat_j = lat
            if cfg.use_jitter:
                # uniform in [lat - j, lat + j] (deterministic per-host
                # lane draw, one per segment); the lookahead bound uses
                # lat - j
                rng, uj = rng_uniform(rng, mask)
                lat_j = lat + (
                    (uj * 2.0 - 1.0) * jit.astype(jnp.float32)
                ).astype(jnp.int64)
            # a model emitting an out-of-range dst is a bug: surface it as
            # unreachable rather than silently delivering to a clamped
            # host. Uses the PRE-jitter bound so the predicate is
            # independent of the jitter draw (float32 jitter math could
            # otherwise flip the sign for amplitudes >= 2^24 ns, diverging
            # from golden which tests lat_bound)
            unreachable = mask & ((lat_bound0 < 0) | bad_dst)
            rng, u = rng_uniform(rng, mask)
            lost = mask & (u < lossp) & (ev.t >= cfg.bootstrap_end_time)
            if cfg.fault_loss_windows:
                # fault loss draws AFTER the path-loss draw (stable
                # position in the per-host stream) and honors the same
                # bootstrap gate; precedence: path loss > unreachable >
                # fault loss > budget, each counted exactly once
                rng, uf = rng_uniform(rng, mask)
                flost = (
                    mask & ~lost & ~unreachable & (uf < f_loss)
                    & (ev.t >= cfg.bootstrap_end_time)
                )
                lat_j = jnp.where(
                    f_inflate, (lat_j * f_lat) // LAT_SCALE, lat_j
                )
            else:
                flost = None
            if cfg.fluid_active:
                bglost = None
                if cfg.fluid_loss_max > 0.0:
                    # fluid congestion loss AFTER the path/fault draws
                    # (precedence: path loss > unreachable > fault loss
                    # > fluid loss > budget, each counted exactly once).
                    # The uniform is a pure hash of (fluid seed, global
                    # host id, emission counter) — unique per send,
                    # mesh-shape invariant, and side-effect-free on the
                    # RNG stream. Drops fold into pkts_lost (congestion
                    # loss IS path loss to the protocol; the links fold
                    # attributes it). loss_max is a trace-time static:
                    # latency-only coupling (the default) traces NO draw
                    # — bg_loss would be identically 0.0 yet the hash is
                    # per send segment on the measured dispatch path.
                    ub = fluid_send_uniform(cfg.fluid_seed, host_gid, seq)
                    bglost = (
                        mask & ~lost & ~unreachable & (ub < bg_loss)
                        & (ev.t >= cfg.bootstrap_end_time)
                    )
                    if flost is not None:
                        bglost = bglost & ~flost
                lat_j = jnp.where(
                    bg_inflate, (lat_j * bg_lat) // LAT_SCALE, lat_j
                )
            else:
                bglost = None
            send_ok = mask & ~lost & ~unreachable & ~over_budget
            budget_dropped = mask & ~lost & ~unreachable & over_budget
            if flost is not None:
                send_ok = send_ok & ~flost
                budget_dropped = budget_dropped & ~flost
                stats = stats._replace(
                    faults_dropped=stats.faults_dropped + flost,
                    faults_delayed=stats.faults_delayed
                    + (send_ok & f_inflate),
                )
            if bglost is not None:
                send_ok = send_ok & ~bglost
                budget_dropped = budget_dropped & ~bglost
            ob_col = sent_round  # lane column (cursor pre-increment)
            sent_round = sent_round + send_ok.astype(jnp.int32)
            # conservative-PDES clamp (worker.rs:411-414): never before
            # round end
            arrive = jnp.maximum(eg_depart + jnp.maximum(lat_j, 0), window_end)
            order = pack_order(0, host_gid, seq)
            seq = seq + mask
            payload = s.payload
            if j > 0 and s.payload_inc is not None:
                payload = payload + j * jnp.asarray(s.payload_inc, jnp.int32)
            payload = payload.at[:, PAYLOAD_SIZE_WORD].set(sz)
            entries.append(
                (send_ok, ob_col, dst, arrive, order, port_kind, payload)
            )
            used_lats.append(jnp.where(send_ok, lat_bound0, TIME_MAX))
            stats = stats._replace(
                pkts_sent=stats.pkts_sent + mask,
                # bglost is disjoint from lost by construction (drawn on
                # the ~lost survivors), so the OR is an exact sum
                pkts_lost=stats.pkts_lost
                + (lost if bglost is None else lost | bglost),
                pkts_unreachable=stats.pkts_unreachable + unreachable,
                pkts_budget_dropped=stats.pkts_budget_dropped + budget_dropped,
            )
    return (
        _EvCarry(
            stats=stats, rng=rng, seq=seq, sent_round=sent_round,
            tb_egress=tb_eg, tb_ingress=tb_in, codel=codel, model=model_state,
        ),
        push_list,
        entries,
        used_lats,
        flow_list,
    )


def _ev_carry_of(st: SimState) -> _EvCarry:
    return _EvCarry(
        stats=st.stats, rng=st.rng, seq=st.seq, sent_round=st.sent_round,
        tb_egress=st.tb_egress, tb_ingress=st.tb_ingress, codel=st.codel,
        model=st.model,
    )


def _flow_append(cfg: EngineConfig, ledger: FlowLedger, host_gid, entries):
    """Append a microstep's flow-completion entries to the per-shard
    ledger ring, in chronological entry order with host-major slot
    assignment inside each entry (an exclusive prefix-sum over the mask
    gives every completing host its own slot — no collisions by
    construction). Writes land at `cursor % R`; hosts beyond the mask
    scatter to index R, which `mode="drop"` discards — counted later by
    the FlowCollector against the monotone cursor, never silent."""
    fr = cfg.flow_records
    rows = ledger.rows[0]  # shard-local [R, F] plane
    cur = ledger.cursor[0]
    for mask, dst, fidx, t0, t1, fbytes, frtx in entries:
        m64 = mask.astype(jnp.int64)
        ofs = jnp.cumsum(m64) - m64  # exclusive prefix: per-host slot
        n = jnp.sum(m64)
        slot = (cur + ofs) % fr
        # only the NEWEST fr completions of this entry get a live slot:
        # with more than fr completions in ONE microstep (H > fr shards
        # under synchronized FIN-ACKs) slots would wrap WITHIN a single
        # scatter, and duplicate scatter indices have an unspecified
        # winner — masking ofs < n - fr keeps the indices unique (a
        # window of fr consecutive offsets maps injectively mod fr) and
        # preserves the ring's newest-overwrites-oldest contract. The
        # cursor still advances by n, so the collector counts exactly
        # these drops as wrap losses — nothing silent.
        live = mask & (ofs >= n - fr)
        idx = jnp.where(live, slot, jnp.int64(fr))  # others -> dropped
        row = jnp.stack(
            [host_gid, dst, fidx, t0, t1, fbytes, frtx], axis=1
        )  # [H, FLOW_COLS] i64, netobs.FLOW_FIELDS column order
        rows = rows.at[idx].set(row, mode="drop")
        cur = cur + n
    return FlowLedger(rows=rows[None], cursor=cur[None])


def _finish_microstep(
    cfg: EngineConfig, st: SimState, c: _EvCarry, queue, ob_entries,
    used_lats, flow_entries, host_gid, wheel=None,
):
    """Apply a microstep's accumulated outbox appends (one fused slab pass)
    and flow-ledger appends, fold the used-latency lookahead, and
    reassemble the SimState. `wheel` is the post-pop/post-push timer
    wheel on wheel-active programs (None otherwise — SimState.wheel
    stays None)."""
    outbox = st.outbox
    ob_lost = jnp.zeros((), jnp.int64)
    if ob_entries:
        outbox, n_lost = _outbox_append_multi(outbox, ob_entries)
        ob_lost = ob_lost + n_lost
        st = st._replace(
            min_used_lat=jnp.minimum(
                st.min_used_lat,
                jnp.min(jnp.stack([jnp.min(u) for u in used_lats])),
            )
        )
    if flow_entries:
        st = st._replace(
            flows=_flow_append(cfg, st.flows, host_gid, flow_entries)
        )
    stats = c.stats._replace(ob_dropped=c.stats.ob_dropped + ob_lost[None])
    return st._replace(
        queue=queue,
        wheel=wheel,
        rng=c.rng,
        seq=c.seq,
        sent_round=c.sent_round,
        tb_egress=c.tb_egress,
        tb_ingress=c.tb_ingress,
        codel=c.codel,
        model=c.model,
        outbox=outbox,
        stats=stats,
    )


def _microstep(cfg, model, st: SimState, params, host_gid, window_end,
               fluid_fx=None):
    """The single-event microstep (microstep_events = 1): pop each host's
    earliest event, execute, apply pushes and appends. `fluid_fx` is the
    round's loop-invariant fluid coupling factors (None when the fluid
    plane is off)."""
    # execution-time floor: the CPU model's busy horizon and/or the fault
    # plane's queue-hold restart time. A host floored past the window does
    # not pop at all; events stay in the queue so their (time, order)
    # sequence is preserved verbatim. An event popped while the floor is
    # *within* the window executes at the floor (host.rs:820-847 for the
    # CPU case; a crash restart is the same mechanics on a different
    # clock): rewrite ev.t to the execution time so every downstream
    # consumer (handler ctx, digest, pushes, egress departure) sees the
    # delayed clock, never a stale one. Both the floor and ev.t are
    # < window_end here, so the execution time stays inside the window.
    floor = None
    down_h = None
    if cfg.cpu_delay_ns > 0:
        floor = st.cpu_busy_until
    if cfg.fault_hold:
        # the down check reads the BUSY-FLOORED head time — the candidate
        # execution time — not the raw queue head: a CPU-delayed event
        # whose busy horizon lands inside a down window must defer to the
        # restart exactly as _effective_next (the barrier's view) says it
        # will. TIME_MAX heads stay TIME_MAX through the maximum.
        ht = q_next_time(st.queue)
        if cfg.wheel_active:
            # the candidate execution time is the COMBINED head: a due
            # wheel timer is the next event exactly like a queue head
            ht = jnp.minimum(ht, wheel_next_time(st.wheel))
        if floor is not None:
            ht = jnp.maximum(ht, floor)
        down_h, resume_h = down_and_resume(params.faults, ht)
        floor = resume_h if floor is None else jnp.maximum(floor, resume_h)
    limit = window_end
    if floor is not None:
        limit = jnp.where(floor < window_end, window_end, jnp.int64(0))
    if cfg.wheel_active:
        queue, wheel, ev, active = _pop_min_merged(st.queue, st.wheel, limit)
    else:
        queue, ev, active = q_pop_min(st.queue, limit)
        wheel = st.wheel
    if floor is not None:
        exec_t = jnp.maximum(ev.t, floor)
        ev = ev._replace(t=jnp.where(active, exec_t, ev.t))
        if cfg.cpu_delay_ns > 0:
            st = st._replace(
                cpu_busy_until=jnp.where(
                    active, exec_t + cfg.cpu_delay_ns, st.cpu_busy_until
                )
            )
        if cfg.fault_hold:
            # events executing at a crash restart (the head was inside a
            # down window) count as fault-delayed, charged to the host
            st = st._replace(
                stats=st.stats._replace(
                    faults_delayed=st.stats.faults_delayed + (active & down_h)
                )
            )

    if cfg.fault_clear:
        # queue-clear crash semantics: an event whose execution time falls
        # inside a down window is consumed (popped) but never dispatched —
        # no digest, no pushes, no sends; counted, never silent
        down_e, _ = down_and_resume(params.faults, ev.t)
        fdrop = active & down_e
        active = active & ~fdrop
        st = st._replace(
            stats=st.stats._replace(
                faults_dropped=st.stats.faults_dropped + fdrop
            )
        )

    c, push_list, ob_entries, used_lats, flow_entries = _event_body(
        cfg, model, _ev_carry_of(st), params, host_gid, window_end, ev,
        active, fluid_fx,
    )
    if cfg.wheel_active and push_list:
        # route model timer pushes to the wheel (spill-to-queue when
        # full); everything else — packets, app events, ingress
        # requeues, spills — stays queue-bound. Static pre-filter: the
        # requeue (first entry under shaping) is a packet by
        # construction, and models may declare which push PORTS can
        # carry timers (`timer_push_ports`, e.g. tgen's port_b) — the
        # other entries skip the wheel pass entirely.
        n_req = 1 if cfg.shaping else 0
        tports = getattr(model, "timer_push_ports", None)
        route_mask = [
            i >= n_req and (tports is None or (i - n_req) in tports)
            for i in range(len(push_list))
        ]
        push_list, push_w, spilled = _route_timer_pushes(
            cfg, wheel, push_list,
            tuple(getattr(model, "timer_kinds", ())), route_mask,
        )
        wheel = wheel_push_many(wheel, push_w)
        c = c._replace(
            stats=c.stats._replace(
                wheel_spilled=c.stats.wheel_spilled + spilled
            )
        )
    if push_list:
        queue = q_push_many(queue, push_list)
    return _finish_microstep(
        cfg, st, c, queue, ob_entries, used_lats, flow_entries, host_gid,
        wheel=wheel,
    )


def _lex_less(at, ao, bt, bo):
    """(at, ao) < (bt, bo) on the (time, order) total key."""
    return (at < bt) | ((at == bt) & (ao < bo))


def _pop_min_merged(queue, wheel, limit):
    """Pop each host's earliest event from queue ∪ wheel under the
    (time, order) total key — the wheel integration's dispatch-order
    exactness hinge: the winner is chosen by comparing the two heads
    (cache-cheap for the wheel and bucketed queues), then each structure
    runs its pop masked to the hosts it won, so exactly one event pops
    per active host and it is the same event the wheel-off path would
    pop from its single combined queue. Ties are impossible between live
    events (order keys are globally unique); the all-empty tie on the
    (TIME_MAX, ORDER_MAX) sentinels picks the wheel side, whose pop then
    does nothing (TIME_MAX is never < limit). Returns
    (queue', wheel', event, active)."""
    qt, qo = q_head(queue)
    wt, wo = q_head(wheel)
    q_wins = _lex_less(qt, qo, wt, wo)
    z = jnp.int64(0)
    queue2, ev_q, act_q = q_pop_min(queue, jnp.where(q_wins, limit, z))
    wheel2, ev_w, act_w = wheel_pop_min(wheel, jnp.where(q_wins, z, limit))
    ev = Event(
        t=jnp.where(act_w, ev_w.t, ev_q.t),
        order=jnp.where(act_w, ev_w.order, ev_q.order),
        kind=jnp.where(act_w, ev_w.kind, ev_q.kind),
        payload=jnp.where(act_w[:, None], ev_w.payload, ev_q.payload),
    )
    return queue2, wheel2, ev, act_q | act_w


def _route_timer_pushes(cfg: EngineConfig, wheel, push_list, timer_kinds,
                        route_mask=None):
    """Split a microstep's push list into queue-bound and wheel-bound
    entries. A push routes to the wheel iff it is a model timer event
    (no KIND_PKT flag, model kind in the STATIC timer_kinds tuple — the
    exact predicate the network observatory's ec_timer class uses) AND
    the wheel has a free slot left after this microstep's earlier wheel
    pushes; otherwise it stays queue-bound. Timer pushes that found the
    wheel full SPILL to the queue — behaviorally identical to the
    wheel-off path for that event (the pop merge re-derives the total
    order from wherever events sit), counted per host into
    stats.wheel_spilled, never silent. The running `taken` counter makes
    the free check exact across multiple wheel pushes in one microstep,
    so the wheel itself can never overflow (its `dropped` lane is an
    invariant zero — the sentinel's IV_COUNTER asserts it).

    `route_mask` is a per-entry STATIC list: False entries are known at
    trace time to never carry a timer (the ingress requeue — packets by
    construction — and model ports outside `timer_push_ports`), so they
    skip the classification AND the wheel's one-hot write pass entirely.
    Each skipped entry removes one [H, S]-shaped push pass per
    microstep, which is most of the wheel's routing overhead on models
    with several ports (tgen: 3 pushes, 1 possible timer).

    Returns (queue_pushes, wheel_pushes, spilled i64[H])."""
    free = wheel_free(wheel)  # [H] i32, post-pop occupancy
    taken = jnp.zeros_like(free)
    push_q, push_w = [], []
    spilled = jnp.zeros((free.shape[0],), jnp.int64)
    for i, push in enumerate(push_list):
        mask, t, order, kind, payload = push[:5]
        if route_mask is not None and not route_mask[i]:
            push_q.append(push)
            continue
        kind = jnp.asarray(kind, jnp.int32)
        is_timer = (
            mask
            & ((kind & KIND_PKT) == 0)
            & kind_in(kind & KIND_MASK, timer_kinds)
        )
        fits = is_timer & (taken < free)
        taken = taken + fits.astype(jnp.int32)
        spilled = spilled + (is_timer & ~fits)
        push_w.append((fits, t, order, kind, payload))
        push_q.append((mask & ~fits, t, order, kind, payload))
    return push_q, push_w, spilled


def _microstep_k(cfg, model, st: SimState, params, host_gid, window_end,
                 fluid_fx=None):
    """The K-way microstep (microstep_events = K > 1): peek each host's K
    earliest in-window events in ONE slab pass (`q_pop_k`), fold them
    through the model handler with an unrolled inner loop, then remove the
    executed prefix and apply ALL pushes and outbox appends in one fused
    pass each. Returns (state', executed[H]) — each host's executed count,
    the round loop's per-host event-denominated valve charge.

    Exactness guard (the reason this is bit-identical to K=1 by
    construction): batch event j+1 of a host executes only if no push this
    host emitted so far this microstep (model pushes AND ingress requeues)
    landed at an earlier (time, order) key — in K=1 that pushed event would
    pop before batch event j+1 — and, under the CPU model, only while the
    host's busy horizon stays inside the window (K=1 would stop popping).
    Deferral is monotone (the batch is key-sorted and push keys only
    accumulate), so execution is always a PREFIX of the batch; deferred
    events were only peeked, never removed, and re-pop next microstep in
    their original order.

    Drop exactness: pushes run AFTER the executed prefix is cleared, in
    K=1 chronological order (requeue_0, pushes_0, requeue_1, ...), each
    carrying a RESERVE equal to the number of batch events that executed
    after it (in K=1 those still occupied queue slots when the push
    landed) — see ops/events.py `_push_fields`. Outbox columns are cursor-
    assigned exactly as across separate microsteps."""
    k = cfg.effective_microstep_events
    h = st.queue.t.shape[0]
    if cfg.cpu_delay_ns > 0 or cfg.fault_hold:
        # combined execution floor at the HEAD event: CPU busy horizon
        # and/or crash-restart time (every peeked in-window event of a
        # down host shares the head's down window — the window extends to
        # >= window_end whenever the head is blocked — so head-time
        # gating is exact; within-window restarts are handled per batch
        # event below)
        floor0 = jnp.zeros((h,), jnp.int64)
        if cfg.cpu_delay_ns > 0:
            floor0 = jnp.maximum(floor0, st.cpu_busy_until)
        if cfg.fault_hold:
            # down check at the busy-floored head (the candidate execution
            # time) — same rule as _microstep and _effective_next
            _, resume0 = down_and_resume(
                params.faults,
                jnp.maximum(q_next_time(st.queue), floor0),
            )
            floor0 = jnp.maximum(floor0, resume0)
        limit = jnp.where(floor0 < window_end, window_end, jnp.int64(0))
    else:
        limit = window_end
    popped = q_pop_k(st.queue, limit, k)

    c = _ev_carry_of(st)
    deferred = jnp.zeros((h,), bool)
    pm_t = jnp.full((h,), TIME_MAX, jnp.int64)  # earliest push key so far
    pm_o = jnp.full((h,), ORDER_MAX, jnp.int64)
    busy = st.cpu_busy_until
    fault_held = jnp.zeros((h,), jnp.int64)  # hold: events run at restart
    fault_drop = jnp.zeros((h,), jnp.int64)  # clear: events consumed+dropped
    cons_ks = []  # [H] bool per batch index: CONSUMED (cleared from queue)
    push_lists = []  # per batch index, K=1 chronological order
    ob_entries = []
    used_lats = []
    flow_entries = []  # flow-ledger appends, K=1 chronological order
    for j in range(k):
        ev = popped.event(j)
        down_j = resume_j = None
        if cfg.fault_hold:
            # evaluated at the busy-floored event time (the candidate
            # execution time) so a mid-batch busy horizon that lands in a
            # down window defers exactly where K=1 would
            t_cand = (
                jnp.maximum(ev.t, busy) if cfg.cpu_delay_ns > 0 else ev.t
            )
            down_j, resume_j = down_and_resume(params.faults, t_cand)
        if j > 0:
            deferred = deferred | _lex_less(pm_t, pm_o, ev.t, ev.order)
            if cfg.cpu_delay_ns > 0:
                deferred = deferred | (busy >= window_end)
            if cfg.fault_hold:
                # a later batch event entering a down window whose restart
                # is past the horizon: K=1 would stop popping this host
                deferred = deferred | (down_j & (resume_j >= window_end))
        cons_j = popped.active[:, j] & ~deferred
        if cfg.cpu_delay_ns > 0 or cfg.fault_hold:
            fl = busy if cfg.cpu_delay_ns > 0 else jnp.zeros((h,), jnp.int64)
            if cfg.fault_hold:
                fl = jnp.maximum(fl, jnp.where(down_j, resume_j, 0))
            exec_t = jnp.maximum(ev.t, fl)
            ev = ev._replace(t=jnp.where(cons_j, exec_t, ev.t))
            if cfg.cpu_delay_ns > 0:
                busy = jnp.where(cons_j, exec_t + cfg.cpu_delay_ns, busy)
            if cfg.fault_hold:
                fault_held = fault_held + (cons_j & down_j)
        exec_j = cons_j
        if cfg.fault_clear:
            # consumed but never dispatched — same contract as K=1. The
            # down check reads ev.t AFTER the CPU-busy rewrite above (the
            # EXECUTION time), exactly where the K=1 path evaluates it.
            down_x, _ = down_and_resume(params.faults, ev.t)
            fd = cons_j & down_x
            fault_drop = fault_drop + fd
            exec_j = cons_j & ~fd
        c, push_list, entries, lats, flows_j = _event_body(
            cfg, model, c, params, host_gid, window_end, ev, exec_j,
            fluid_fx,
        )
        flow_entries += flows_j
        # accumulate this event's push keys into the guard minimum AFTER
        # its own execution (an event's pushes cannot defer itself)
        for push in push_list:
            mask, p_t, p_o = push[0], jnp.asarray(push[1], jnp.int64), push[2]
            better = mask & _lex_less(p_t, p_o, pm_t, pm_o)
            pm_t = jnp.where(better, p_t, pm_t)
            pm_o = jnp.where(better, p_o, pm_o)
        cons_ks.append(cons_j)
        push_lists.append(push_list)
        ob_entries += entries
        used_lats += lats

    # consumed prefix length per host, and the per-push reserves
    cons_i32 = [e.astype(jnp.int32) for e in cons_ks]
    m = functools.reduce(jnp.add, cons_i32)  # [H] i32
    queue = q_clear_popped(st.queue, popped, m)
    all_pushes = []
    for j, push_list in enumerate(push_lists):
        if not push_list:
            continue
        # batch events consumed AFTER event j still held their slots
        # when event j's pushes landed in K=1
        reserve = (
            functools.reduce(jnp.add, cons_i32[j + 1 :])
            if j + 1 < k
            else jnp.zeros((h,), jnp.int32)
        )
        all_pushes += [p + (reserve,) for p in push_list]
    if all_pushes:
        queue = q_push_many(queue, all_pushes)

    n_deferred = jnp.sum(
        (popped.active & ~jnp.stack(cons_ks, axis=1)).astype(jnp.int64)
    )
    stats = c.stats._replace(
        popk_deferred=c.stats.popk_deferred + n_deferred[None]
    )
    if cfg.fault_hold:
        stats = stats._replace(
            faults_delayed=stats.faults_delayed + fault_held
        )
    if cfg.fault_clear:
        stats = stats._replace(
            faults_dropped=stats.faults_dropped + fault_drop
        )
    c = c._replace(stats=stats)
    if cfg.cpu_delay_ns > 0:
        st = st._replace(cpu_busy_until=busy)
    st = _finish_microstep(
        cfg, st, c, queue, ob_entries, used_lats, flow_entries, host_gid
    )
    return st, m


def exchange_ici_bytes_per_round(cfg: EngineConfig, kind: str | None = None) -> int:
    """Per-shard ICI bytes one exchange moves — the cost model written out
    in `_exchange_alltoall`'s docstring, as a checkable number.

    gather:   every shard RECEIVES the other (W-1) shards' whole outboxes:
              (W-1) x rows_local x row_bytes (+ the 4-byte count word),
              with rows_local = hosts_per_shard x sends_per_host_round and
              row_bytes = dst + t + order + kind + payload words.
    alltoall: every shard sends/receives (W-1) fixed blocks of
              `a2a_block_size` packed rows (1 dst word + the packed event,
              ops/merge._pack_words) — O(global sends / world) once blocks
              are sized to traffic instead of O(world-replicated) like the
              gather.
    hierarchical: the INTER tier of `exchange_tier_bytes_per_round` —
              (W-1) gear-aware blocks of `hier_block_size` packed rows
              plus the 4-byte i32 fill counter per peer. The intra tier
              (local compaction staging) is charged to `stats.ici_intra`
              only, never here: `ici_bytes` stays "bytes the exchange
              COLLECTIVE moves" across all three kinds.

    The engine charges exactly these numbers into `stats.ici_bytes` every
    round (the collectives run unconditionally, empty rounds included), so
    the counter is the model made observable: the multichip dryrun asserts
    counter == model x rounds, and on a real mesh the counter can be held
    against profiler ICI traffic to validate the model itself."""
    kind = kind or cfg.exchange
    if cfg.world <= 1:
        return 0
    # the gather collective moves the SLICED outbox, so a lower merge gear
    # shrinks ICI bytes too; the alltoall's fixed blocks are gear-invariant
    # (the gear trims its local sort input, not the wire format)
    rows_local = cfg.hosts_per_shard * cfg.effective_gear_cols
    row_bytes = 4 + 8 + 8 + 4 + 4 * EVENT_PAYLOAD_WORDS
    if kind == "gather":
        return (cfg.world - 1) * (rows_local * row_bytes + 4)
    if kind == "hierarchical":
        return exchange_tier_bytes_per_round(cfg)[1]
    packed_words = 1 + (2 + 2 + 1 + EVENT_PAYLOAD_WORDS)  # dst + packed event
    return (cfg.world - 1) * cfg.a2a_block_size * packed_words * 4


def exchange_tier_bytes_per_round(cfg: EngineConfig) -> tuple[int, int]:
    """(intra, inter) bytes the hierarchical exchange charges per round,
    per shard — the two-tier cost model as checkable numbers.

    intra: the compaction tier's staging traffic — every gear-sliced local
           outbox row (hosts_per_shard x effective_gear_cols) repacked
           once into the [world, k] block layout at packed width (1 dst
           word + the packed event, ops/merge._pack_words). HBM bytes,
           not wire: charged to `stats.ici_intra` so the weak-scaling
           bench can hold local-compaction work against wire savings.
    inter: the wire tier — (W-1) blocks of `hier_block_size` packed rows
           plus the 4-byte i32 fill counter per peer (the lane-diet wire
           element). Charged to `stats.ici_inter` AND `stats.ici_bytes`.

    Both tiers shrink with the merge gear (the flat alltoall's blocks are
    gear-invariant) — that delta is the hierarchical path's win, and
    `tests/test_hier.py` pins counter == model x rounds for both lanes."""
    if cfg.world <= 1:
        return 0, 0
    packed_words = 1 + (2 + 2 + 1 + EVENT_PAYLOAD_WORDS)
    rows_g = cfg.hosts_per_shard * cfg.effective_gear_cols
    intra = rows_g * packed_words * 4
    inter = (cfg.world - 1) * (cfg.hier_block_size * packed_words + 1) * 4
    return intra, inter


def _gear_sliced_outbox(cfg, axis, ob: Outbox, sent_round):
    """Truncate the outbox to the active merge gear's column width.

    Host h's k-th send of a round lands in lane column k (`_outbox_append`
    cursor layout), so when no host staged more than `gear_cols` sends the
    first `gear_cols` columns hold EVERY valid entry and the slice is
    exact — the downstream (dst, t, order) sort sees the same entry set in
    a host-major order that is monotone in the full-width flattening
    (identical selection even on the cheap-shed index-tiebreak path).
    Sends beyond the width are counted into the returned shed, psum'd so
    every shard carries the global value; the chunk loop aborts on the
    first nonzero delta and the driver replays from its pre-chunk snapshot
    one gear up, so a shed never reaches accepted results.

    Returns (outbox-view, shed | None); None means the full-width program
    (no slicing traced in at all)."""
    if not cfg.gear_active:
        return ob, None
    from shadow_tpu.ops.merge import gear_shed_count

    gc = cfg.gear_cols
    local = gear_shed_count(sent_round, gc)
    shed = lax.psum(local, axis) if axis else local
    sliced = Outbox(
        dst=ob.dst[:, :gc],
        t=ob.t[:, :gc],
        order=ob.order[:, :gc],
        kind=ob.kind[:, :gc],
        payload=ob.payload[:, :gc, :],
        count=ob.count,
    )
    return sliced, shed


def _exchange(cfg, axis, st: SimState):
    if axis and cfg.exchange == "alltoall":
        return _exchange_alltoall(cfg, axis, st)
    if axis and cfg.exchange == "hierarchical":
        return _exchange_hierarchical(cfg, axis, st)
    ob_full = st.outbox
    ob, gear_shed = _gear_sliced_outbox(cfg, axis, ob_full, st.sent_round)
    if axis:
        g = jax.tree.map(
            lambda a: lax.all_gather(a, axis, tiled=True),
            Outbox(ob.dst, ob.t, ob.order, ob.kind, ob.payload, ob.count),
        )
    else:
        g = ob
    h_local = st.queue.t.shape[0]
    shard_start = (
        lax.axis_index(axis).astype(jnp.int32) * h_local if axis else jnp.int32(0)
    )

    # flatten the [H, B] lanes host-major: entry order (and therefore
    # cheap-shed overflow selection) is identical for every mesh shape
    dst_f = g.dst.reshape(-1)
    t_f = g.t.reshape(-1)
    local = dst_f - shard_start
    valid = (t_f != TIME_MAX) & (local >= 0) & (local < h_local)
    flat = (
        local, t_f, g.order.reshape(-1), g.kind.reshape(-1),
        g.payload.reshape(-1, g.payload.shape[-1]), valid,
    )
    has_sends = jnp.sum(g.count) > 0
    with jax.named_scope("shadow_merge"):
        queue = _merge_into_queue(cfg, st.queue, flat, has_sends)
    stats = st.stats
    if gear_shed is not None:
        stats = stats._replace(gear_shed=stats.gear_shed + gear_shed[None])
    if axis:
        stats = stats._replace(
            ici_bytes=stats.ici_bytes
            + jnp.int64(exchange_ici_bytes_per_round(cfg, "gather"))[None]
        )
    if isinstance(st.queue, BucketQueue):
        stats = stats._replace(
            bq_rebuilds=stats.bq_rebuilds + has_sends.astype(jnp.int64)[None]
        )
    return st._replace(
        queue=queue,
        outbox=_fresh_outbox(ob_full),
        sent_round=jnp.zeros_like(st.sent_round),
        stats=stats,
    )


def _fresh_outbox(ob: Outbox) -> Outbox:
    return Outbox(
        dst=jnp.zeros_like(ob.dst),
        t=jnp.full_like(ob.t, TIME_MAX),
        order=jnp.zeros_like(ob.order),
        kind=jnp.zeros_like(ob.kind),
        payload=jnp.zeros_like(ob.payload),
        count=jnp.zeros_like(ob.count),
    )


def _merge_into_queue(cfg, queue0, flat, has_sends):
    """Insert flat (local, t, order, kind, payload, valid) rows, skipping
    the merge in empty rounds.

    A `BucketQueue` merges through its flat slab view and its block caches
    are rebuilt wholesale afterwards — the exchange merge is the one hot-path
    point where incremental maintenance is not worth it (a merge can touch
    every block). The rebuild sits under the same `has_sends` cond as the
    merge plan: its outputs are the small [H, C/B] cache planes, so the
    branch-boundary copies that rule out whole-slab conds do not apply, and
    empty rounds keep their caches for free.

    The merge's sort dominates round cost; rounds where NO shard sent
    anything (timer-heavy workloads, drained phases) skip it entirely —
    `has_sends` is identical on all shards, so the branch is uniform
    across the mesh. The cond wraps only the PLAN (sorts + SoA sorted
    vectors): branches returning the whole queue forced XLA to copy every
    slab at the branch boundary each round — traced at ~55% of the
    PHOLD-torus round cost — while the plan is one [H, C] index map plus
    [K]-vector sorted fields, cheap to copy at every capacity. The apply
    runs unconditionally as a single where-pass."""
    q_flat = as_flat(queue0)
    if cfg.merge_scatter:
        # sort-free calendar scatter (ops/merge.py merge_scatter_free):
        # non-shedding rounds bucket rows by destination via scatter-add
        # peeling — no (dst, t, order) sort at all; a round where any
        # destination would overflow falls back to the sort path IN-JIT,
        # so shed order (hence digests/drops) is identical on every
        # workload. Runs in the fused-cond form: the fast path reads the
        # whole queue for its free ranking, so the plan split's
        # time-plane-only cond has nothing to buy here.
        from shadow_tpu.ops.merge import merge_scatter_free

        merged = lax.cond(
            has_sends,
            lambda queue: merge_scatter_free(
                queue, *flat, cfg.max_round_inserts,
                shed_urgency=not cfg.cheap_shed,
                merge_rows=cfg.merge_rows,
            ),
            lambda queue: queue,
            q_flat,
        )
    elif jax.default_backend() == "cpu" or cfg.queue_capacity < 48:
        # Fused merge inside the cond. On CPU the scatter path is faster
        # and branch copies are cheap. On TPU this wins at SMALL slab
        # capacities (measured: PHOLD-torus cap 16 ran 40% slower with the
        # plan split — the [H, C, W] plan materialization costs more than
        # the small branch-boundary copies it avoids; at cap >= ~48 the
        # copy volume dominates and the split below wins).
        merged = lax.cond(
            has_sends,
            lambda queue: merge_flat_events(
                queue, *flat, cfg.max_round_inserts,
                shed_urgency=not cfg.cheap_shed,
                merge_rows=cfg.merge_rows,
            ),
            lambda queue: queue,
            q_flat,
        )
    else:
        from shadow_tpu.ops.merge import (
            merge_apply, merge_empty_plan, merge_plan,
        )

        p_words = flat[4].shape[-1]
        # the cond consumes ONLY the time plane (free-slot source): feeding
        # the whole queue through would add a second consumer per slab and
        # reintroduce the branch-boundary copies this split removes
        take, gw, dropped_add = lax.cond(
            has_sends,
            lambda q_t: merge_plan(
                q_t, *flat, cfg.max_round_inserts,
                shed_urgency=not cfg.cheap_shed,
                merge_rows=cfg.merge_rows,
            ),
            lambda q_t: merge_empty_plan(q_t, p_words),
            q_flat.t,
        )
        merged = merge_apply(q_flat, take, gw, dropped_add)
    if not isinstance(queue0, BucketQueue):
        return merged
    nb = queue0.bt.shape[1]
    bt, bo, bfill = lax.cond(
        has_sends,
        lambda to: block_minima(to[0], to[1], nb),
        lambda _to: (queue0.bt, queue0.bo, queue0.bfill),
        (merged.t, merged.order),
    )
    return BucketQueue(
        merged.t, merged.order, merged.kind, merged.payload, merged.dropped,
        bt, bo, bfill,
    )


def _exchange_alltoall(cfg, axis, st: SimState):
    """Destination-sharded exchange (VERDICT r4 #4): instead of replicating
    the whole outbox to every shard (O(world) ICI bytes and merge input per
    shard), sort the LOCAL outbox by destination shard and move fixed-width
    blocks with `lax.all_to_all`.

    Cost model (written out in BASELINE.md): with S = global sends/round
    and W = shard count, the gather exchange moves (W-1) x rows_local x
    row_bytes per shard over ICI and feeds W x rows_local rows into every
    shard's merge sort; this path moves ~rows_local x row_bytes and feeds
    ~rows_local rows — both O(S / W) for balanced traffic.

    Determinism: rows are grouped per destination shard in (t, order)
    urgency order, so when a block overflows the LATEST entries shed —
    the same contract as the merge — and the final per-queue insertion
    order is re-derived by the merge sort from (dst, t, order), identical
    to the gather path whenever nothing sheds (`stats.a2a_shed` counts
    sheds; size `a2a_block` so it stays zero).

    Merge gears trim the LOCAL dst-shard sort input (the [H, B] lanes
    sliced to gear_cols columns) exactly like the gather path; the
    alltoall blocks themselves stay full width, so the wire format and
    `a2a_shed` semantics are gear-invariant."""
    ob_full = st.outbox
    ob, gear_shed = _gear_sliced_outbox(cfg, axis, ob_full, st.sent_round)
    h_local = st.queue.t.shape[0]
    world = cfg.world
    k = cfg.a2a_block_size
    my = lax.axis_index(axis).astype(jnp.int32)

    blocks, _seg_len, shed = _dshard_pack_blocks(ob, h_local, world, k)

    recv = lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0)
    flat_rows = recv.reshape(world * k, -1)
    r_dst = flat_rows[:, 0]
    r_t, r_order, r_kind, r_payload = _unpack_words_rows(
        flat_rows[:, 1:], ob.payload.shape[-1]
    )
    local = r_dst - my * h_local
    r_valid = (r_t != TIME_MAX) & (local >= 0) & (local < h_local)
    flat = (local, r_t, r_order, r_kind, r_payload, r_valid)

    has_sends = lax.psum(jnp.sum(ob.count), axis) > 0
    with jax.named_scope("shadow_merge"):
        queue = _merge_into_queue(cfg, st.queue, flat, has_sends)
    stats = st.stats._replace(
        a2a_shed=st.stats.a2a_shed + shed[None],
        ici_bytes=st.stats.ici_bytes
        + jnp.int64(exchange_ici_bytes_per_round(cfg, "alltoall"))[None],
    )
    if gear_shed is not None:
        stats = stats._replace(gear_shed=stats.gear_shed + gear_shed[None])
    if isinstance(st.queue, BucketQueue):
        stats = stats._replace(
            bq_rebuilds=stats.bq_rebuilds + has_sends.astype(jnp.int64)[None]
        )
    return st._replace(
        queue=queue,
        outbox=_fresh_outbox(ob_full),
        sent_round=jnp.zeros_like(st.sent_round),
        stats=stats,
    )


def _dshard_pack_blocks(ob: Outbox, h_local: int, world: int, k: int):
    """Sort the local outbox by (dst shard, t, order) and pack each
    destination group's first `k` rows into fixed wire blocks.

    The shared front half of the flat alltoall AND the hierarchical
    exchange's intra-shard compaction tier — one definition of "compacted
    per-destination prefix" (ops/merge.dshard_segments does the grouping),
    so the two exchange kinds select bit-identical row sets for any given
    block width. Block j carries group j's first k rows in urgency order;
    later rows shed (counted, never silent).

    Returns (blocks i32[world, k, 1 + packed], seg_len i32[world],
    shed i64[]) — `seg_len` is the per-destination valid-row count before
    truncation (the hierarchical path's fill-counter source), `shed` the
    local count of rows beyond `k`."""
    from shadow_tpu.ops.merge import dshard_segments

    dst_f = ob.dst.reshape(-1)
    t_f = ob.t.reshape(-1)
    order_f = ob.order.reshape(-1)
    kind_f = ob.kind.reshape(-1)
    payload_f = ob.payload.reshape(-1, ob.payload.shape[-1])
    valid = t_f != TIME_MAX
    dshard = jnp.where(valid, dst_f // h_local, world).astype(jnp.int32)

    s_tag, first, seg_len = dshard_segments(dshard, t_f, order_f, world)

    # pack rows (dst word + event words) and permute into sorted order
    words = jnp.concatenate(
        [
            dst_f[:, None].astype(jnp.int32),
            _pack_words_rows(t_f, order_f, kind_f, payload_f),
        ],
        axis=1,
    )
    s_idx = s_tag - 1
    w_sorted = words[s_idx]  # [M, W+1]; token rows harmless (never taken)

    # block j carries group j's first k rows (urgency order); later rows shed
    rr = jnp.arange(k, dtype=jnp.int32)
    in_seg = rr[None, :] < jnp.minimum(seg_len, k)[:, None]  # [world, k]
    src_pos = jnp.where(in_seg, first[:world, None] + 1 + rr[None, :], 0)
    blocks = w_sorted[src_pos]  # [world, k, W+1]
    inval = _invalid_row(ob.payload.shape[-1])
    blocks = jnp.where(in_seg[:, :, None], blocks, inval[None, None, :])
    shed = jnp.sum(jnp.maximum(seg_len - k, 0), dtype=jnp.int64)
    return blocks, seg_len, shed


def _exchange_hierarchical(cfg, axis, st: SimState):
    """Two-tier exchange (ROADMAP item 2 — the million-host climb): an
    INTRA-shard compaction tier, then an INTER-shard alltoall that moves
    only the compacted prefixes.

    Tier 1 (intra-shard, no wire): the gear-sliced [H_local, gear] outbox
    is sorted by (dst shard, t, order) — `ops/merge.dshard_segments`, the
    exact machinery the flat alltoall uses — compacting this shard's sends
    into dense per-destination-shard prefixes in urgency order. Charged to
    `stats.ici_intra` (staging-buffer traffic; obs/memory.py prices the
    buffers themselves).

    Tier 2 (inter-shard, the ICI wire): two collectives — the i32
    fill-counter vector `sent_counts` (the lane-diet wire element: bounded
    by `hier_block_size`, so i32 is provably lossless — core/lanes.py
    LANE_MIN_WIDTH_BITS and shadowlint R7 pin the bound) and the
    [world, k] packed blocks with k = `hier_block_size`. Charged to
    `stats.ici_inter` AND `stats.ici_bytes`.

    Where the wire shrinks vs the flat alltoall: `hier_block_size` derives
    from the GEAR-SLICED row count (hosts_per_shard x effective_gear_cols)
    where `a2a_block_size` is fixed at the full [H, B] row count — geared
    runs move proportionally smaller blocks. Gears off, the two block
    sizes coincide and the wire rows are identical.

    Exactness: local sort, urgency-order block selection, and merge input
    are identical to the flat alltoall whenever nothing sheds. Receive
    validity is derived from the counts AND the invalid-row time marker —
    identical truth sets by construction, so a counts-vs-payload drift
    surfaces as dropped rows the digest gate catches rather than phantom
    inserts. Block overflow on a geared run counts into `gear_shed`
    (psum'd): the chunk aborts and replays one gear up, and the TOP gear's
    k equals the flat k, so the ladder always has an exact escape; at full
    width overflow counts into `a2a_shed` exactly like the flat path.
    Digests, events, and every drop counter are therefore bit-identical to
    `alltoall` (tests/test_hier.py is the gate)."""
    ob_full = st.outbox
    ob, gear_shed = _gear_sliced_outbox(cfg, axis, ob_full, st.sent_round)
    h_local = st.queue.t.shape[0]
    world = cfg.world
    k = cfg.hier_block_size
    my = lax.axis_index(axis).astype(jnp.int32)

    with jax.named_scope("shadow_hier_intra"):
        blocks, seg_len, shed = _dshard_pack_blocks(ob, h_local, world, k)
    sent_counts = jnp.minimum(seg_len, k).astype(jnp.int32)

    with jax.named_scope("shadow_hier_inter"):
        recv_counts = lax.all_to_all(
            sent_counts, axis, split_axis=0, concat_axis=0
        )
        recv = lax.all_to_all(blocks, axis, split_axis=0, concat_axis=0)
    flat_rows = recv.reshape(world * k, -1)
    r_dst = flat_rows[:, 0]
    r_t, r_order, r_kind, r_payload = _unpack_words_rows(
        flat_rows[:, 1:], ob.payload.shape[-1]
    )
    local = r_dst - my * h_local
    rr = jnp.arange(world * k, dtype=jnp.int32)
    by_count = (rr % k) < recv_counts[rr // k]
    r_valid = by_count & (r_t != TIME_MAX) & (local >= 0) & (local < h_local)
    flat = (local, r_t, r_order, r_kind, r_payload, r_valid)

    has_sends = lax.psum(jnp.sum(ob.count), axis) > 0
    with jax.named_scope("shadow_merge"):
        queue = _merge_into_queue(cfg, st.queue, flat, has_sends)

    intra_b, inter_b = exchange_tier_bytes_per_round(cfg)
    stats = st.stats._replace(
        ici_bytes=st.stats.ici_bytes + jnp.int64(inter_b)[None],
        ici_intra=st.stats.ici_intra + jnp.int64(intra_b)[None],
        ici_inter=st.stats.ici_inter + jnp.int64(inter_b)[None],
    )
    if cfg.gear_active:
        # geared block overflow rides the gear-abort path, not a2a_shed:
        # the driver replays one gear up, whose wider k re-derives the
        # block — the exact-escape contract the docstring argues
        shed_g = lax.psum(shed, axis)
        stats = stats._replace(
            gear_shed=stats.gear_shed + (gear_shed + shed_g)[None]
        )
    else:
        stats = stats._replace(a2a_shed=stats.a2a_shed + shed[None])
    if isinstance(st.queue, BucketQueue):
        stats = stats._replace(
            bq_rebuilds=stats.bq_rebuilds + has_sends.astype(jnp.int64)[None]
        )
    return st._replace(
        queue=queue,
        outbox=_fresh_outbox(ob_full),
        sent_round=jnp.zeros_like(st.sent_round),
        stats=stats,
    )


def _pack_words_rows(t, order, kind, payload):
    from shadow_tpu.ops.merge import _pack_words

    return _pack_words(t, order, kind.astype(jnp.int32), payload)


def _unpack_words_rows(g, p_words):
    from shadow_tpu.ops.merge import _unpack_words

    return _unpack_words(g, p_words)


def _invalid_row(p_words: int):
    """A packed row whose unpack yields t == TIME_MAX (the empty marker)."""
    t = jnp.full((1,), TIME_MAX, jnp.int64)
    o = jnp.full((1,), ORDER_MAX, jnp.int64)
    row = _pack_words_rows(
        t, o, jnp.zeros((1,), jnp.int32), jnp.zeros((1, p_words), jnp.int32)
    )[0]
    return jnp.concatenate([jnp.zeros((1,), jnp.int32), row])
