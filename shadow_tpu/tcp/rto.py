"""RTT estimation and retransmission timeout (RFC 6298).

Reference: the retransmit bookkeeping of `src/lib/tcp` and the legacy
`tcp_retransmit_tally.cc` (C++, retransmit tracking). Times are simulated
nanoseconds, like everything in this framework.
"""

from __future__ import annotations

NS_PER_SEC = 1_000_000_000

K = 4
ALPHA_SHIFT = 3  # alpha = 1/8
BETA_SHIFT = 2  # beta = 1/4
MIN_RTO = NS_PER_SEC  # 1 s (RFC 6298 recommendation; Linux uses 200ms)
MAX_RTO = 60 * NS_PER_SEC
INITIAL_RTO = NS_PER_SEC
GRANULARITY = 1_000_000  # 1 ms clock granularity


class RttEstimator:
    def __init__(self, min_rto: int = MIN_RTO, max_rto: int = MAX_RTO):
        self.srtt: int | None = None
        self.rttvar = 0
        self.rto = INITIAL_RTO
        self.min_rto = min_rto
        self.max_rto = max_rto
        self.backoff = 0  # consecutive timeouts (Karn exponential backoff)

    def on_measurement(self, rtt: int):
        """Valid RTT sample (never from a retransmitted segment — Karn)."""
        rtt = max(rtt, 1)
        if self.srtt is None:
            self.srtt = rtt
            self.rttvar = rtt // 2
        else:
            err = abs(self.srtt - rtt)
            self.rttvar += (err - self.rttvar) >> BETA_SHIFT
            self.srtt += (rtt - self.srtt) >> ALPHA_SHIFT
        self.backoff = 0
        base = self.srtt + max(GRANULARITY, K * self.rttvar)
        self.rto = min(max(base, self.min_rto), self.max_rto)

    def on_timeout(self):
        """Exponential backoff; caller retransmits."""
        self.backoff += 1

    def current_rto(self) -> int:
        return min(self.rto << min(self.backoff, 12), self.max_rto)
