"""Sans-I/O TCP state machine.

Capability mirror of the reference's clean-room TCP crate
(`/root/reference/src/lib/tcp/`, Rust ~8k LoC: `tcp/src/lib.rs:1-60,244-345`,
per-state modules in `states.rs`, mod-2^32 sequence arithmetic in `seq.rs`,
send/receive buffers, window scaling) — re-designed, not translated.

The machine is *sans-I/O*: it never touches wires or clocks. Callers feed it
wall input (`on_segment`, `on_timer(now)`) and app input (`connect`, `send`,
`recv`, `close`, `shutdown`), and drain output with `poll_segments(now)`.
Time is always an explicit `now` argument (simulated nanoseconds) — the
dependency-injection equivalent of the reference's `TcpState<X: Dependencies>`
type parameter. This is what lets the same machine run under the simulated
clock of the PDES host plane (`shadow_tpu.host`) and under real time in unit
tests.

Feature set (matching the reference crate): 3-way handshake (active +
passive + simultaneous open), MSS + window-scaling options, cumulative ACKs,
out-of-order reassembly, RFC 6298 RTO with Karn's algorithm + exponential
backoff, fast retransmit on 3 dup-ACKs, Reno congestion control (slow start /
congestion avoidance / fast recovery — the reference's default pluggable CC,
`tcp_cong_reno.c`), zero-window probing, all close paths incl. simultaneous
close and TIME_WAIT 2MSL, RST generation/handling.
"""

from shadow_tpu.tcp.seq import Seq, seq_ge, seq_gt, seq_le, seq_lt, seq_max, wrapping_add
from shadow_tpu.tcp.segment import FIN, SYN, RST, PSH, ACK, Segment, flags_str
from shadow_tpu.tcp.buffers import RecvBuffer, SendBuffer
from shadow_tpu.tcp.congestion import RenoCongestion
from shadow_tpu.tcp.rto import RttEstimator
from shadow_tpu.tcp.state import TcpConfig, TcpError, TcpState, State

__all__ = [
    "ACK",
    "FIN",
    "PSH",
    "RST",
    "SYN",
    "RecvBuffer",
    "RenoCongestion",
    "RttEstimator",
    "Segment",
    "SendBuffer",
    "Seq",
    "State",
    "TcpConfig",
    "TcpError",
    "TcpState",
    "flags_str",
    "seq_ge",
    "seq_gt",
    "seq_le",
    "seq_lt",
    "seq_max",
    "wrapping_add",
]
