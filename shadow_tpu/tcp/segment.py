"""TCP segment representation.

A `Segment` is the sans-I/O wire unit: header fields the state machine cares
about plus an opaque payload. Ports are carried for the socket layer's demux
(the reference keeps ports in its `TcpHeader`, `src/lib/tcp/src/lib.rs`);
the state machine itself never inspects them.
"""

from __future__ import annotations

from dataclasses import dataclass, field

FIN = 0x01
SYN = 0x02
RST = 0x04
PSH = 0x08
ACK = 0x10

_NAMES = [(SYN, "S"), (FIN, "F"), (RST, "R"), (PSH, "P"), (ACK, ".")]


def flags_str(flags: int) -> str:
    return "".join(n for bit, n in _NAMES if flags & bit) or "-"


@dataclass(frozen=True)
class Segment:
    flags: int
    seq: int  # sequence number of first payload byte (or of SYN/FIN)
    ack: int = 0  # acknowledgment number (valid iff flags & ACK)
    wnd: int = 0  # receive window advertised (pre-scaling units on SYN)
    payload: bytes = b""
    # options (present only on SYN segments, like the reference)
    mss: int | None = None
    wscale: int | None = None
    # SACK (RFC 2018; reference tcp.c:151-177 selectiveACKs): `sack_ok` is
    # the SYN-time capability option; `sack` carries up to 3 blocks of
    # received-out-of-order sequence ranges [start, end) on ACKs
    sack_ok: bool = False
    sack: tuple = ()
    # addressing for the socket layer (opaque to the state machine)
    src_port: int = 0
    dst_port: int = 0

    @property
    def seg_len(self) -> int:
        """Sequence space consumed: payload + SYN/FIN flags (RFC 793)."""
        n = len(self.payload)
        if self.flags & SYN:
            n += 1
        if self.flags & FIN:
            n += 1
        return n

    def __repr__(self) -> str:  # compact, strace-friendly
        p = f" len={len(self.payload)}" if self.payload else ""
        o = ""
        if self.mss is not None:
            o += f" mss={self.mss}"
        if self.wscale is not None:
            o += f" ws={self.wscale}"
        if self.sack:
            o += f" sack={list(self.sack)}"
        return (
            f"<{flags_str(self.flags)} seq={self.seq} ack={self.ack} "
            f"wnd={self.wnd}{p}{o}>"
        )
