"""Reno congestion control (slow start / congestion avoidance / fast recovery).

Reference: the pluggable congestion interface + Reno implementation
(`src/main/host/descriptor/tcp_cong.c`, `tcp_cong_reno.c` — the reference's
default and only in-tree algorithm). Mirrors the same plug-point shape: the
state machine calls `on_ack`, `on_dup_ack`, `on_retransmit_timeout`, reads
`cwnd`, so alternative algorithms drop in by duck type.
"""

from __future__ import annotations


class RenoCongestion:
    DUP_ACK_THRESH = 3  # fast-retransmit trigger (RFC 5681)

    def __init__(self, mss: int, initial_window_mss: int = 10):
        self.mss = mss
        self.cwnd = initial_window_mss * mss  # RFC 6928 IW10
        self.ssthresh = 1 << 30
        self.dup_acks = 0
        self.in_fast_recovery = False
        self._avoid_acc = 0  # byte accumulator for congestion avoidance

    # -- queries -------------------------------------------------------------

    @property
    def in_slow_start(self) -> bool:
        return self.cwnd < self.ssthresh

    def wants_fast_retransmit(self) -> bool:
        return self.dup_acks == self.DUP_ACK_THRESH and not self.in_fast_recovery

    # -- events --------------------------------------------------------------

    def on_ack(self, newly_acked: int):
        """Cumulative ACK advancing SND.UNA by `newly_acked` bytes."""
        self.dup_acks = 0
        if self.in_fast_recovery:
            # exit fast recovery: deflate to ssthresh (RFC 5681 step 6)
            self.in_fast_recovery = False
            self.cwnd = self.ssthresh
            return
        if self.in_slow_start:
            self.cwnd += min(newly_acked, self.mss)
        else:
            self._avoid_acc += min(newly_acked, self.mss)
            if self._avoid_acc >= self.cwnd:
                self._avoid_acc -= self.cwnd
                self.cwnd += self.mss

    def on_dup_ack(self):
        self.dup_acks += 1
        if self.dup_acks == self.DUP_ACK_THRESH and not self.in_fast_recovery:
            # enter fast recovery: halve, inflate by 3 segments
            self.ssthresh = max(self.cwnd // 2, 2 * self.mss)
            self.cwnd = self.ssthresh + 3 * self.mss
            self.in_fast_recovery = True
        elif self.in_fast_recovery:
            self.cwnd += self.mss  # window inflation per extra dup-ACK

    def on_retransmit_timeout(self):
        self.ssthresh = max(self.cwnd // 2, 2 * self.mss)
        self.cwnd = self.mss  # RFC 5681: back to 1 MSS (loss window)
        self.dup_acks = 0
        self.in_fast_recovery = False
        self._avoid_acc = 0
