"""Mod-2^32 TCP sequence-number arithmetic (RFC 793 §3.3).

Reference: `src/lib/tcp/src/seq.rs` — a newtype over u32 with wrapping
comparison. Here sequence numbers are plain ints in [0, 2^32); comparisons
use the signed-difference trick so they are correct across wraparound as
long as the true distance is < 2^31.
"""

from __future__ import annotations

MOD = 1 << 32
HALF = 1 << 31

Seq = int  # alias for readability in signatures


def wrapping_add(a: Seq, n: int) -> Seq:
    return (a + n) % MOD


def seq_diff(a: Seq, b: Seq) -> int:
    """Signed distance a - b in (-2^31, 2^31]."""
    d = (a - b) % MOD
    return d - MOD if d >= HALF else d


def seq_lt(a: Seq, b: Seq) -> bool:
    return seq_diff(a, b) < 0


def seq_le(a: Seq, b: Seq) -> bool:
    return seq_diff(a, b) <= 0


def seq_gt(a: Seq, b: Seq) -> bool:
    return seq_diff(a, b) > 0


def seq_ge(a: Seq, b: Seq) -> bool:
    return seq_diff(a, b) >= 0


def seq_max(a: Seq, b: Seq) -> Seq:
    return a if seq_ge(a, b) else b


def seq_min(a: Seq, b: Seq) -> Seq:
    return a if seq_le(a, b) else b


def in_window(x: Seq, start: Seq, length: int) -> bool:
    """Is x in [start, start+length) with wraparound?"""
    return 0 <= seq_diff(x, start) < length if length > 0 else False
