"""Send / receive buffers for the TCP state machine.

Reference: `src/lib/tcp/src/buffer.rs` (send queue with retransmit tracking,
receive reassembly). Design differences: the send buffer is a flat byte
deque indexed by absolute (unwrapped) stream offset — retransmission slices
it by range, so no per-segment bookkeeping survives an ACK; the receive
buffer keeps a small sorted list of out-of-order runs and merges on insert.
"""

from __future__ import annotations

from shadow_tpu.tcp.seq import MOD, seq_diff


class SendBuffer:
    """Bytes the app has written, keyed by absolute stream offset.

    `una_off` .. `end_off` are *unwrapped* 64-bit offsets; the state machine
    maps sequence numbers to offsets via its own SND.UNA tracking (this is
    what makes mod-2^32 wraparound a non-issue here).
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._chunks: list[bytes] = []
        self._len = 0
        self.una_off = 0  # offset of first unacked byte == start of buffer
        self.fin_queued = False

    @property
    def end_off(self) -> int:
        return self.una_off + self._len

    def space(self) -> int:
        return self.capacity - self._len

    def write(self, data: bytes) -> int:
        """Append up to space() bytes; returns bytes accepted."""
        if self.fin_queued:
            raise ValueError("write after shutdown")
        n = min(len(data), self.space())
        if n:
            self._chunks.append(bytes(data[:n]))
            self._len += n
        return n

    def ack_to(self, off: int) -> int:
        """Drop bytes below absolute offset `off`; returns bytes freed."""
        drop = off - self.una_off
        if drop <= 0:
            return 0
        if drop > self._len:
            raise ValueError(f"ack beyond buffered data: {off} > {self.end_off}")
        freed = drop
        self.una_off = off
        self._len -= drop
        while drop:
            head = self._chunks[0]
            if len(head) <= drop:
                drop -= len(head)
                self._chunks.pop(0)
            else:
                self._chunks[0] = head[drop:]
                drop = 0
        return freed

    def slice(self, off: int, n: int) -> bytes:
        """Read n bytes starting at absolute offset off (for (re)transmit)."""
        start = off - self.una_off
        if start < 0 or start + n > self._len:
            raise ValueError(
                f"slice [{off},{off + n}) outside [{self.una_off},{self.end_off})"
            )
        out = bytearray()
        for c in self._chunks:
            if start >= len(c):
                start -= len(c)
                continue
            take = c[start : start + n - len(out)]
            out += take
            start = 0
            if len(out) == n:
                break
        return bytes(out)


class RecvBuffer:
    """In-order delivery queue + out-of-order reassembly runs.

    RCV.NXT advancement is the caller's job; this buffer stores payload by
    32-bit sequence number and hands back contiguous data. Out-of-order runs
    are kept as a sorted list of (seq, bytes) merged on insert — network
    reordering windows are tiny compared to buffer sizes, so a list beats an
    interval tree here.
    """

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._ready = bytearray()  # contiguous, app-readable
        self._runs: list[tuple[int, bytes]] = []  # sorted by seq (wrapping)
        self.fin_seq: int | None = None  # seq of FIN byte, once known

    def readable(self) -> int:
        return len(self._ready)

    def window(self) -> int:
        """Advertisable receive window (free contiguous capacity)."""
        return max(0, self.capacity - len(self._ready))

    def insert(self, rcv_nxt: int, seq: int, data: bytes) -> int:
        """Insert payload at `seq` given current RCV.NXT; returns new RCV.NXT.

        Data at/below rcv_nxt is trimmed (retransmitted overlap); data beyond
        the window is trimmed (the state machine already bounds this).
        """
        if data:
            off = seq_diff(seq, rcv_nxt)
            if off < 0:  # overlaps already-received data
                data = data[-off:]
                off = 0
            if data and off <= self.window():
                data = data[: self.window() - off]
            if data:
                if off == 0:
                    self._ready += data
                    rcv_nxt = (rcv_nxt + len(data)) % MOD
                    rcv_nxt = self._drain_runs(rcv_nxt)
                else:
                    self._add_run((rcv_nxt + off) % MOD, bytes(data), rcv_nxt)
        if self.fin_seq is not None and seq_diff(self.fin_seq, rcv_nxt) == 0:
            rcv_nxt = (rcv_nxt + 1) % MOD
            self.fin_seq = None
        return rcv_nxt

    def _add_run(self, seq: int, data: bytes, rcv_nxt: int):
        self._runs.append((seq, data))
        # normalize: sort by distance from rcv_nxt, then merge overlaps
        self._runs.sort(key=lambda r: seq_diff(r[0], rcv_nxt))
        merged: list[tuple[int, bytes]] = []
        for s, d in self._runs:
            if merged:
                ps, pd = merged[-1]
                overlap = len(pd) - seq_diff(s, ps)  # bytes of d already held
                if overlap >= 0:
                    # keep existing bytes, append only d's new tail
                    if overlap < len(d):
                        merged[-1] = (ps, pd + d[overlap:])
                    continue
            merged.append((s, d))
        self._runs = merged

    def _drain_runs(self, rcv_nxt: int) -> int:
        changed = True
        while changed:
            changed = False
            for i, (s, d) in enumerate(self._runs):
                off = seq_diff(s, rcv_nxt)
                if off < 0 and off + len(d) <= 0:
                    self._runs.pop(i)
                    changed = True
                    break
                if off <= 0:
                    take = d[-off:]
                    self._ready += take
                    rcv_nxt = (rcv_nxt + len(take)) % MOD
                    self._runs.pop(i)
                    changed = True
                    break
        return rcv_nxt

    def ooo_ranges(self) -> list[tuple[int, int]]:
        """Out-of-order runs as wire-seq [start, end) blocks — the SACK
        blocks this receiver advertises (RFC 2018)."""
        return [(s, (s + len(d)) % MOD) for s, d in self._runs if d]

    def read(self, n: int) -> bytes:
        out = bytes(self._ready[:n])
        del self._ready[: len(out)]
        return out
