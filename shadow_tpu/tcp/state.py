"""The sans-I/O TCP connection state machine.

Reference: `src/lib/tcp/src/lib.rs:244-345` (`TcpState<X: Dependencies>`) and
its per-state modules (`states.rs`) — rebuilt, not translated. All times are
absolute simulated nanoseconds passed in by the caller; the machine never
reads a clock. Typical driving loop:

    tcp = TcpState(cfg, iss=123)
    tcp.connect(now)
    for seg in tcp.poll_segments(now):  wire.send(seg)
    ...
    tcp.on_segment(now, seg_from_wire)
    t = tcp.next_timer()                # absolute ns or None
    if t is not None and now >= t: tcp.on_timer(now)

Internally, send-side bookkeeping uses *unwrapped 64-bit stream offsets*
(`una_off`/`nxt_off` into `SendBuffer`) with sequence numbers computed at
segment-emission time — mod-2^32 wraparound lives only at the wire boundary,
which removes the reference's pervasive `Seq` arithmetic from the hot paths.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass

from shadow_tpu.tcp.buffers import RecvBuffer, SendBuffer
from shadow_tpu.tcp.congestion import RenoCongestion
from shadow_tpu.tcp.rto import RttEstimator
from shadow_tpu.tcp.segment import ACK, FIN, PSH, RST, SYN, Segment
from shadow_tpu.tcp.seq import (
    MOD,
    in_window,
    seq_diff,
    seq_ge,
    seq_gt,
    seq_le,
    seq_lt,
    wrapping_add,
)

NS_PER_SEC = 1_000_000_000


class State(enum.Enum):
    CLOSED = "closed"
    LISTEN = "listen"
    SYN_SENT = "syn-sent"
    SYN_RECEIVED = "syn-received"
    ESTABLISHED = "established"
    FIN_WAIT_1 = "fin-wait-1"
    FIN_WAIT_2 = "fin-wait-2"
    CLOSING = "closing"
    TIME_WAIT = "time-wait"
    CLOSE_WAIT = "close-wait"
    LAST_ACK = "last-ack"


class TcpError(enum.Enum):
    RESET = "connection reset by peer"  # ECONNRESET
    REFUSED = "connection refused"  # ECONNREFUSED
    TIMED_OUT = "connection timed out"  # ETIMEDOUT


# states in which the app may still queue data for transmission
_SENDABLE = frozenset({State.ESTABLISHED, State.CLOSE_WAIT})
# states with a fully synchronized connection
SYNCHRONIZED = frozenset(
    {
        State.ESTABLISHED,
        State.FIN_WAIT_1,
        State.FIN_WAIT_2,
        State.CLOSING,
        State.TIME_WAIT,
        State.CLOSE_WAIT,
        State.LAST_ACK,
    }
)


@dataclass(frozen=True)
class TcpConfig:
    mss: int = 1460
    send_buf: int = 256 * 1024
    recv_buf: int = 256 * 1024
    window_scaling: bool = True
    time_wait: int = 60 * NS_PER_SEC  # 2*MSL
    max_retries: int = 12  # consecutive RTO expirations before TIMED_OUT
    initial_window_mss: int = 10
    # SACK (RFC 2018; reference tcp.c:151-177): negotiated on SYN, blocks on
    # ACKs; the sender keeps a scoreboard and skips sacked ranges when
    # retransmitting (selective repeat instead of a full go-back-N resend)
    sack: bool = True
    # delayed ACK (RFC 1122 4.2.3.2; reference tcp.c:1254,2014): hold the
    # ACK for one in-order segment up to `delack_ns`, ack every 2nd
    # immediately; out-of-order arrivals always ack immediately
    delayed_ack: bool = True
    delack_ns: int = 40_000_000  # 40 ms (Linux's default delack ceiling)
    # Nagle (RFC 896): hold a sub-MSS tail while any data is unacked.
    # Default off: the reference's sans-I/O machine ships without Nagle and
    # most corpus binaries would set TCP_NODELAY anyway
    nagle: bool = False
    # buffer autotuning (reference HostDefaultOptions autotune flags):
    # double a buffer under pressure — recv when the advertised window
    # drops below one MSS, send when the app fills it — up to `buf_max`.
    # The receive wscale is chosen from buf_max so a grown window stays
    # advertisable (RFC 7323 fixes the shift at SYN time).
    autotune: bool = True
    buf_max: int = 4 * 1024 * 1024


def _wscale_for(recv_buf: int) -> int:
    s = 0
    while s < 14 and (recv_buf >> s) > 0xFFFF:
        s += 1
    return s


class TcpState:
    def __init__(self, config: TcpConfig | None = None, *, iss: int = 0):
        self.cfg = config or TcpConfig()
        self.state = State.CLOSED
        self.error: TcpError | None = None

        # send side
        self.iss = iss % MOD
        self.snd_buf = SendBuffer(self.cfg.send_buf)
        self.una_off = 0  # first unacked stream byte (== snd_buf.una_off)
        self.nxt_off = 0  # next stream byte to transmit
        self.snd_wnd = 0  # peer-advertised window (post-scaling bytes)
        self.snd_wl1 = 0  # seq of segment used for last window update
        self.snd_wl2 = 0  # ack of segment used for last window update
        self.snd_max_seq = self.iss  # highest snd_nxt ever (for ack validation)
        self.syn_sent = False
        self.syn_acked = False
        self.fin_sent = False
        self.fin_acked = False
        self.snd_wscale = 0  # shift applied to windows the peer advertises
        self.mss = self.cfg.mss

        # receive side
        self.irs = 0
        self.rcv_nxt = 0
        self.rcv_buf = RecvBuffer(self.cfg.recv_buf)
        self.rcv_wscale = (
            _wscale_for(
                max(self.cfg.recv_buf, self.cfg.buf_max)
                if self.cfg.autotune
                else self.cfg.recv_buf
            )
            if self.cfg.window_scaling
            else 0
        )
        self.rcv_fin_seen = False  # FIN consumed (EOF reached)

        # congestion + timing
        self.cong = RenoCongestion(self.mss, self.cfg.initial_window_mss)
        self.rtt = RttEstimator()
        self._timed: tuple[int, int] | None = None  # (end_off, sent_at)
        self._max_sent_off = 0  # high-water transmit mark (Karn guard)
        self.rto_deadline: int | None = None
        self.probe_deadline: int | None = None
        self.tw_deadline: int | None = None
        self.retries = 0

        # pending output control
        self._pending_syn = False
        self._pending_ack = False
        self._dup_ack_owed = 0  # RFC 5681: one immediate ACK per ooo segment
        self._fast_rexmit = False
        self._probe_due = False
        self._pending_rst: Segment | None = None

        # SACK: negotiated capability + sender scoreboard of peer-held
        # ranges as disjoint sorted UNWRAPPED offset pairs [start, end)
        self.sack_ok = False
        self._sacked: list[tuple[int, int]] = []
        # delayed ACK: deadline for a held in-order-data ACK
        self._delack_deadline: int | None = None

        # stats (reference tcp crate keeps similar counters)
        self.segs_sent = 0
        self.segs_received = 0
        self.retransmits = 0

    # ------------------------------------------------------------------ app

    def listen(self):
        assert self.state == State.CLOSED
        self.state = State.LISTEN

    def connect(self, now: int):
        assert self.state in (State.CLOSED, State.LISTEN)
        self.state = State.SYN_SENT
        self._pending_syn = True
        self._arm_rto(now)

    def send(self, data: bytes) -> int:
        """Queue app data; returns bytes accepted (0 = buffer full)."""
        if self.state not in _SENDABLE and not (
            self.state in (State.SYN_SENT, State.SYN_RECEIVED)
        ):
            raise BrokenPipeError(f"send in state {self.state.value}")
        if self.snd_buf.fin_queued:
            raise BrokenPipeError("send after shutdown")
        n = self.snd_buf.write(data)
        if (
            n < len(data)
            and self.cfg.autotune
            and self.snd_buf.capacity < self.cfg.buf_max
        ):
            # sender autotune: the app outpaces the buffer — double it
            self.snd_buf.capacity = min(
                self.snd_buf.capacity * 2, self.cfg.buf_max
            )
            n += self.snd_buf.write(data[n:])
        return n

    def recv(self, n: int) -> bytes | None:
        """Read up to n bytes. None = would block; b'' = EOF."""
        if self.rcv_buf.readable():
            data = self.rcv_buf.read(n)
            self._pending_ack = True  # window opened; let peer know
            return data
        if self.rcv_fin_seen or self.error is not None:
            return b""
        if self.state in (State.CLOSED, State.LISTEN):
            return b""
        return None

    def shutdown_write(self, now: int):
        """Half-close: FIN after all queued data (like shutdown(SHUT_WR))."""
        if self.snd_buf.fin_queued:
            return
        self.snd_buf.fin_queued = True
        if self.state == State.ESTABLISHED:
            self.state = State.FIN_WAIT_1
        elif self.state == State.CLOSE_WAIT:
            self.state = State.LAST_ACK
        elif self.state == State.SYN_RECEIVED:
            # no data was ever accepted: close becomes FIN after handshake
            self.state = State.FIN_WAIT_1
        elif self.state in (State.SYN_SENT, State.LISTEN):
            self._enter_closed(None)
            return
        self._arm_rto(now)

    def close(self, now: int):
        """Full close (like close(2)): no more reads or writes."""
        self.shutdown_write(now)

    def abort(self, now: int):
        """Hard reset (SO_LINGER 0 close / process death)."""
        if self.state in SYNCHRONIZED or self.state == State.SYN_RECEIVED:
            self._pending_rst = Segment(
                RST | ACK, seq=self._snd_nxt_seq(), ack=self.rcv_nxt
            )
        self._enter_closed(None)

    # -------------------------------------------------------------- queries

    def readable(self) -> bool:
        return self.rcv_buf.readable() > 0 or self.rcv_fin_seen or self.error is not None

    def writable(self) -> bool:
        return (
            self.state in _SENDABLE
            and not self.snd_buf.fin_queued
            and self.snd_buf.space() > 0
        )

    def is_closed(self) -> bool:
        return self.state == State.CLOSED

    # --------------------------------------------------------------- listen

    def accept_segment(self, now: int, seg: Segment, *, child_iss: int) -> "TcpState | None":
        """LISTEN-socket demux: a SYN forks a child connection in
        SYN_RECEIVED (the reference's listener spawns per-connection state
        the same way); anything else is the socket layer's problem
        (`rst_for` below). Returns the child or None."""
        assert self.state == State.LISTEN
        if seg.flags & (RST | ACK) or not (seg.flags & SYN):
            return None
        child = TcpState(self.cfg, iss=child_iss)
        child.state = State.SYN_RECEIVED
        child._accept_syn_options(seg)
        child.irs = seg.seq
        child.rcv_nxt = wrapping_add(seg.seq, 1)
        child._pending_syn = True
        child._arm_rto(now)
        return child

    # ----------------------------------------------------------------- wire

    def on_segment(self, now: int, seg: Segment):
        self.segs_received += 1
        handler = {
            State.CLOSED: self._seg_closed,
            State.LISTEN: self._seg_closed,  # direct use; normally via accept
            State.SYN_SENT: self._seg_syn_sent,
        }.get(self.state, self._seg_synchronized)
        handler(now, seg)

    def _seg_closed(self, now: int, seg: Segment):
        if not (seg.flags & RST):
            self._pending_rst = rst_for(seg)

    def _seg_syn_sent(self, now: int, seg: Segment):
        acceptable_ack = False
        if seg.flags & ACK:
            if seq_le(seg.ack, self.iss) or seq_gt(seg.ack, self.snd_max_seq):
                if not (seg.flags & RST):
                    self._pending_rst = rst_for(seg)
                return
            acceptable_ack = True
        if seg.flags & RST:
            if acceptable_ack:
                self.error = TcpError.REFUSED
                self._enter_closed(TcpError.REFUSED)
            return
        if not (seg.flags & SYN):
            return
        self._accept_syn_options(seg)
        self.irs = seg.seq
        self.rcv_nxt = wrapping_add(seg.seq, 1)
        if acceptable_ack:
            self._ack_advance(now, seg.ack)  # manages the RTO timer itself
            self._update_snd_wnd(seg, syn=True)
            self.state = State.ESTABLISHED
            self._pending_ack = True
        else:
            # simultaneous open: resend our SYN as SYN-ACK
            self.state = State.SYN_RECEIVED
            self._pending_syn = True
            self._arm_rto(now)

    def _seg_synchronized(self, now: int, seg: Segment):
        # RFC 793 trimming: strip sequence space below RCV.NXT (retransmitted
        # SYN / payload prefix) so the remainder is judged on its own. This is
        # what lets a simultaneous-open SYN-ACK (whose SYN unit is already
        # consumed) deliver its ACK.
        d = seq_diff(seg.seq, self.rcv_nxt)
        if d < 0 and seg.seg_len > 0:
            old = -d
            flags, payload, seq = seg.flags, seg.payload, seg.seq
            if flags & SYN:
                flags &= ~SYN
                seq = wrapping_add(seq, 1)
                old -= 1
            if old > 0:
                drop = min(old, len(payload))
                payload = payload[drop:]
                seq = wrapping_add(seq, drop)
            seg = dataclasses.replace(seg, flags=flags, payload=payload, seq=seq)
            # an old duplicate must still elicit an ACK (so a sender that
            # rewound past data the peer already holds re-syncs its SND.UNA)
            self._pending_ack = True

        # RFC 793 p.69 acceptability: does the segment overlap RCV window?
        wnd = self.rcv_buf.window()
        if seg.seg_len == 0:
            ok = seq_diff(seg.seq, self.rcv_nxt) == 0 or in_window(
                seg.seq, self.rcv_nxt, wnd
            )
        else:
            ok = in_window(seg.seq, self.rcv_nxt, wnd) or in_window(
                wrapping_add(seg.seq, seg.seg_len - 1), self.rcv_nxt, wnd
            )
        if not ok:
            if not (seg.flags & RST):
                self._pending_ack = True
            return
        if seg.flags & RST:
            self._enter_closed(TcpError.RESET)
            return
        if seg.flags & SYN:
            # SYN in window in a synchronized state: error, reset
            self._pending_rst = Segment(RST, seq=self._snd_nxt_seq())
            self._enter_closed(TcpError.RESET)
            return
        if not (seg.flags & ACK):
            return

        # --- ACK processing
        if self.state == State.SYN_RECEIVED:
            if seq_le(seg.ack, self.iss) or seq_gt(seg.ack, self.snd_max_seq):
                self._pending_rst = rst_for(seg)
                return
            self.state = State.ESTABLISHED
            # the handshake-completing ACK carries no SYN, so its window is
            # already scaled (RFC 7323: only SYN-flagged segments are
            # unscaled) — but snd_wl1/wl2 are still at their init values, so
            # the update must be forced, not gated on the wl ordering check
            self._update_snd_wnd(seg, force=True)
        dup_candidate = (
            seg.seg_len == 0
            and (seg.wnd << self.snd_wscale) == self.snd_wnd
        )
        if self.sack_ok and seg.sack:
            self._absorb_sack(seg.sack)
        self._ack_advance(now, seg.ack, dup_candidate)
        self._update_snd_wnd(seg)

        # state transitions on our-FIN-acked
        if self.fin_acked:
            if self.state == State.FIN_WAIT_1:
                self.state = State.FIN_WAIT_2
            elif self.state == State.CLOSING:
                self._enter_time_wait(now)
            elif self.state == State.LAST_ACK:
                self._enter_closed(None)
                return

        # --- payload
        if seg.payload and self.state in (
            State.ESTABLISHED,
            State.FIN_WAIT_1,
            State.FIN_WAIT_2,
        ):
            before = self.rcv_nxt
            had_fin_pending = self.rcv_buf.fin_seq is not None
            had_runs = bool(self.rcv_buf._runs)
            self.rcv_nxt = self.rcv_buf.insert(self.rcv_nxt, seg.seq, seg.payload)
            if self.rcv_nxt != before and self.cfg.delayed_ack and not had_runs:
                # in-order data: ack every SECOND segment immediately, hold
                # a single segment's ACK up to delack_ns (RFC 1122 4.2.3.2;
                # reference tcp.c:1254,2014). Anything out of order below
                # acks immediately via the dup-ACK path.
                if self._delack_deadline is not None:
                    self._pending_ack = True
                    self._delack_deadline = None
                else:
                    self._delack_deadline = now + self.cfg.delack_ns
            else:
                self._pending_ack = True
            if self.rcv_nxt == before and seg.payload:
                # out-of-order: each such segment owes its own immediate
                # dup-ACK so the peer's fast-retransmit counter sees every
                # arrival even when the wire delivers a whole batch at once
                self._dup_ack_owed += 1
            if had_fin_pending and self.rcv_buf.fin_seq is None:
                # this insert filled the hole before an out-of-order FIN:
                # the buffer consumed it, so run the FIN transitions now
                self._on_fin_reached(now)
            if (
                self.cfg.autotune
                and self.rcv_buf.window() < self.mss
                and self.rcv_buf.capacity < self.cfg.buf_max
            ):
                # receiver autotune: the window is about to close on a
                # sender that is keeping it full — double the buffer (the
                # wscale chosen at SYN already covers buf_max)
                self.rcv_buf.capacity = min(
                    self.rcv_buf.capacity * 2, self.cfg.buf_max
                )
                self._pending_ack = True  # advertise the opened window

        # --- FIN (a fully-old retransmitted FIN never reaches here: the
        # acceptability check above already rejected it with an ACK)
        if seg.flags & FIN and not self.rcv_fin_seen:
            fin_seq = wrapping_add(seg.seq, len(seg.payload))
            self.rcv_buf.fin_seq = fin_seq
            self.rcv_nxt = self.rcv_buf.insert(self.rcv_nxt, fin_seq, b"")
            if self.rcv_buf.fin_seq is None:  # FIN consumed in order
                self._on_fin_reached(now)
            else:
                self._pending_ack = True  # out-of-order FIN: dup-ACK

    def _on_fin_reached(self, now: int):
        """RCV.NXT has passed the peer's FIN: EOF + state transitions."""
        if self.rcv_fin_seen:
            return
        self.rcv_fin_seen = True
        self._pending_ack = True
        if self.state == State.ESTABLISHED:
            self.state = State.CLOSE_WAIT
        elif self.state == State.FIN_WAIT_1:
            # if our own FIN is already acked this is a straight TIME_WAIT
            # entry; otherwise simultaneous close -> CLOSING
            if self.fin_acked:
                self._enter_time_wait(now)
            else:
                self.state = State.CLOSING
        elif self.state == State.FIN_WAIT_2:
            self._enter_time_wait(now)
        elif self.state == State.TIME_WAIT:
            self._enter_time_wait(now)  # restart 2MSL

    # ------------------------------------------------------------- ack math

    def _snd_nxt_seq(self) -> int:
        seq = wrapping_add(self.iss, (1 if self.syn_sent else 0) + self.nxt_off)
        if self.fin_sent:
            seq = wrapping_add(seq, 1)
        return seq

    def _snd_una_seq(self) -> int:
        return wrapping_add(self.iss, (1 if self.syn_acked else 0) + self.una_off)

    def _ack_advance(self, now: int, ack: int, dup_candidate: bool = False):
        """`dup_candidate`: segment was empty with an unchanged window, so an
        unmoved ACK counts toward fast retransmit (RFC 5681 dup-ACK rules)."""
        una = self._snd_una_seq()
        d = seq_diff(ack, una)
        if d < 0:
            return  # old ACK
        if seq_gt(ack, self.snd_max_seq):
            self._pending_ack = True  # ACK for unsent data
            return
        if d == 0:
            if (
                dup_candidate
                and self.syn_acked
                and self.nxt_off > self.una_off
                and not (self.fin_sent and not self.fin_acked)
            ):
                self.cong.on_dup_ack()
                if self.cong.dup_acks == self.cong.DUP_ACK_THRESH:
                    self._fast_rexmit = True
            return

        newly_acked_bytes = 0
        if not self.syn_acked and self.syn_sent:
            self.syn_acked = True
            d -= 1
        # bound by bytes ever transmitted, not nxt_off: after an RTO
        # go-back-N rewind (nxt_off = una_off) a late ACK may still cover
        # data sent before the rewind
        take = min(d, self._max_sent_off - self.una_off)
        if take:
            self.snd_buf.ack_to(self.una_off + take)
            self.una_off += take
            self.nxt_off = max(self.nxt_off, self.una_off)
            newly_acked_bytes = take
            d -= take
        if d and self.fin_sent and not self.fin_acked:
            self.fin_acked = True
            d -= 1
        if self._sacked:
            self._prune_sacked()
        # RTT sample (Karn: only if the timed range wasn't retransmitted)
        if self._timed is not None and self.una_off >= self._timed[0]:
            self.rtt.on_measurement(now - self._timed[1])
            self._timed = None
        self.cong.on_ack(max(newly_acked_bytes, 1))
        self.retries = 0
        self._fast_rexmit = False
        # restart or clear the retransmission timer
        if self._bytes_in_flight() or (self.fin_sent and not self.fin_acked):
            self._arm_rto(now)
        else:
            self.rto_deadline = None

    def _update_snd_wnd(self, seg: Segment, syn: bool = False, force: bool = False):
        """`syn`: the segment's window is unscaled (RFC 7323). `force`:
        bypass the snd_wl1/wl2 staleness check (used when wl1/wl2 still hold
        their pre-handshake init values and would reject ~half of ISS space)."""
        if not (seg.flags & ACK) and not syn:
            return
        wnd = seg.wnd if (syn or seg.flags & SYN) else seg.wnd << self.snd_wscale
        if (
            syn
            or force
            or seq_lt(self.snd_wl1, seg.seq)
            or (self.snd_wl1 == seg.seq and seq_le(self.snd_wl2, seg.ack))
        ):
            was_zero = self.snd_wnd == 0
            self.snd_wnd = wnd
            self.snd_wl1 = seg.seq
            self.snd_wl2 = seg.ack
            if was_zero and wnd > 0:
                self.probe_deadline = None
                self._probe_due = False

    def _accept_syn_options(self, seg: Segment):
        if seg.mss is not None:
            self.mss = min(self.cfg.mss, seg.mss)
            self.cong.mss = self.mss
        if seg.wscale is not None and self.cfg.window_scaling:
            self.snd_wscale = min(seg.wscale, 14)
        else:
            self.snd_wscale = 0
            self.rcv_wscale = 0  # peer didn't offer: RFC 7323 both-or-neither
        self.sack_ok = bool(seg.sack_ok) and self.cfg.sack

    def _bytes_in_flight(self) -> int:
        return self.nxt_off - self.una_off

    # ----------------------------------------------------------------- sack

    def _absorb_sack(self, blocks):
        """Merge wire-seq SACK blocks into the offset scoreboard. Blocks are
        anchored at SND.UNA (seq_diff is safe because peers only SACK data
        within the current send window)."""
        una_seq = self._snd_una_seq()
        changed = False
        for s, e in blocks:
            start = self.una_off + seq_diff(s, una_seq)
            end = self.una_off + seq_diff(e, una_seq)
            start = max(start, self.una_off)
            end = min(end, self._max_sent_off)
            if end > start:
                self._sacked.append((start, end))
                changed = True
        if changed:
            self._sacked.sort()
            merged: list[tuple[int, int]] = []
            for s0, e0 in self._sacked:
                if merged and s0 <= merged[-1][1]:
                    merged[-1] = (merged[-1][0], max(merged[-1][1], e0))
                else:
                    merged.append((s0, e0))
            self._sacked = merged

    def _prune_sacked(self):
        self._sacked = [
            (max(s, self.una_off), e)
            for s, e in self._sacked
            if e > self.una_off
        ]

    def _sack_jump(self, off: int) -> int:
        """Next offset at/after `off` NOT held by the peer (scoreboard skip);
        also returns the transmit ceiling imposed by the next sacked block
        via `_sack_limit`."""
        for s, e in self._sacked:
            if s <= off < e:
                return e
        return off

    def _sack_limit(self, off: int, limit: int) -> int:
        """Clamp a transmission starting at `off` so it stops at the next
        sacked block (no point retransmitting data the peer already holds)."""
        for s, e in self._sacked:
            if s > off:
                return min(limit, s)
        return limit

    # --------------------------------------------------------------- timers

    def next_timer(self) -> int | None:
        cands = [
            t
            for t in (
                self.rto_deadline,
                self.probe_deadline,
                self.tw_deadline,
                self._delack_deadline,
            )
            if t is not None
        ]
        return min(cands) if cands else None

    def on_timer(self, now: int):
        if self._delack_deadline is not None and now >= self._delack_deadline:
            self._delack_deadline = None
            self._pending_ack = True
        if self.tw_deadline is not None and now >= self.tw_deadline:
            self.tw_deadline = None
            if self.state == State.TIME_WAIT:
                self._enter_closed(None)
                return
        if self.rto_deadline is not None and now >= self.rto_deadline:
            self.rto_deadline = None
            self._on_rto(now)
        if self.probe_deadline is not None and now >= self.probe_deadline:
            self.probe_deadline = None
            self._probe_due = True
            self.rtt.on_timeout()

    def _arm_rto(self, now: int):
        self.rto_deadline = now + self.rtt.current_rto()

    def _on_rto(self, now: int):
        self.retries += 1
        if self.retries > self.cfg.max_retries:
            self._enter_closed(TcpError.TIMED_OUT)
            return
        self.rtt.on_timeout()
        self.cong.on_retransmit_timeout()
        self.retransmits += 1
        self._timed = None  # Karn: no sample from retransmitted data
        # go-back-N: rewind transmission to the oldest unacked octet
        if self.state in (State.SYN_SENT, State.SYN_RECEIVED) or (
            self.syn_sent and not self.syn_acked
        ):
            self._pending_syn = True
        self.nxt_off = self.una_off
        if self.fin_sent and not self.fin_acked:
            self.fin_sent = False  # re-emit FIN after data
        self._arm_rto(now)

    def _enter_time_wait(self, now: int):
        self.state = State.TIME_WAIT
        self.tw_deadline = now + self.cfg.time_wait
        self.rto_deadline = None
        self.probe_deadline = None
        self._pending_ack = True

    def _enter_closed(self, err: TcpError | None):
        self.state = State.CLOSED
        if err is not None and self.error is None:
            self.error = err
        self.rto_deadline = None
        self.probe_deadline = None
        self.tw_deadline = None

    # --------------------------------------------------------------- output

    def _recv_window_field(self) -> int:
        w = self.rcv_buf.window() >> self.rcv_wscale
        return min(w, 0xFFFF)

    def poll_segments(self, now: int) -> list[Segment]:
        """Drain all segments the machine wants on the wire right now."""
        out: list[Segment] = []
        if self._pending_rst is not None:
            out.append(self._pending_rst)
            self._pending_rst = None
        if self.state in (State.CLOSED, State.LISTEN):
            self.segs_sent += len(out)
            return out

        # SYN / SYN-ACK
        if self._pending_syn:
            self._pending_syn = False
            self.syn_sent = True
            flags = SYN
            ack = 0
            if self.state == State.SYN_RECEIVED:
                flags |= ACK
                ack = self.rcv_nxt
            out.append(
                Segment(
                    flags,
                    seq=self.iss,
                    ack=ack,
                    wnd=min(self.rcv_buf.window(), 0xFFFF),
                    mss=self.cfg.mss,
                    wscale=self.rcv_wscale if self.cfg.window_scaling else None,
                    # a SYN-ACK echoes the capability only if the peer's SYN
                    # offered it (negotiation); a plain SYN offers our config
                    sack_ok=(
                        self.sack_ok
                        if self.state == State.SYN_RECEIVED
                        else self.cfg.sack
                    ),
                )
            )
            self.snd_max_seq = wrapping_add(self.iss, 1)
            self._pending_ack = False
            self.segs_sent += len(out)
            return out  # nothing else until handshake progresses

        if not self.syn_acked:
            self.segs_sent += len(out)
            return out

        # fast retransmit: one segment from the oldest unacked octet,
        # bounded by the first SACKed block (only the hole is resent)
        if self._fast_rexmit and self.una_off < self.snd_buf.end_off:
            self._fast_rexmit = False
            hole_end = self._sack_limit(self.una_off, self.snd_buf.end_off)
            n = min(self.mss, hole_end - self.una_off)
            if n > 0:
                out.append(self._data_segment(self.una_off, n))
                self.retransmits += 1
                self._timed = None  # Karn: its ACK would be ambiguous

        # regular data: bounded by peer window + cwnd. After an RTO rewind
        # the SACK scoreboard turns the go-back-N into selective repeat:
        # ranges the peer already holds are skipped, transmissions stop at
        # the next held block (tcp.c's selectiveACKs retransmit behavior).
        limit_off = self.una_off + min(
            self.snd_wnd, self.cong.cwnd
        )  # first non-sendable offset
        end = self.snd_buf.end_off
        while self.nxt_off < end and self.nxt_off < limit_off:
            if self._sacked:
                jumped = self._sack_jump(self.nxt_off)
                if jumped != self.nxt_off:  # peer holds this range: skip
                    self.nxt_off = min(jumped, end)
                    continue
            stop = (
                self._sack_limit(self.nxt_off, limit_off)
                if self._sacked
                else limit_off
            )
            n = min(self.mss, end - self.nxt_off, stop - self.nxt_off)
            if n <= 0:
                break
            if (
                self.cfg.nagle
                and n < self.mss
                and self.nxt_off + n == end
                and self._bytes_in_flight() > 0
                and not self.snd_buf.fin_queued
            ):
                # Nagle: hold the sub-MSS tail while data is in flight
                break
            seg = self._data_segment(self.nxt_off, n)
            out.append(seg)
            if self.nxt_off < self._max_sent_off:
                self.retransmits += 1  # rewound range: this is a resend
            # Karn: only time ranges never transmitted before
            if self._timed is None and self.nxt_off >= self._max_sent_off:
                self._timed = (self.nxt_off + n, now)
            self.nxt_off += n
            self._max_sent_off = max(self._max_sent_off, self.nxt_off)
            if self.rto_deadline is None:
                self._arm_rto(now)
        # zero-window probe (persist timer): 1 byte past the window. The first
        # probe advances nxt_off (so the peer's ACK is accounted normally);
        # re-probes retransmit the in-flight octet.
        if self._probe_due:
            self._probe_due = False
            if self.snd_wnd == 0:
                if self._bytes_in_flight():
                    out.append(self._data_segment(self.una_off, 1))
                elif self.nxt_off < end:
                    out.append(self._data_segment(self.nxt_off, 1))
                    self.nxt_off += 1
                    self._max_sent_off = max(self._max_sent_off, self.nxt_off)
                # a lost probe byte must still retransmit once the peer's
                # window update clears the persist timer
                if self.rto_deadline is None and self._bytes_in_flight():
                    self._arm_rto(now)

        if (
            self.snd_wnd == 0
            and (self.nxt_off < end or self._bytes_in_flight())
            and self.probe_deadline is None
        ):
            self._arm_probe(now)

        # FIN once all data is out
        if (
            self.snd_buf.fin_queued
            and not self.fin_sent
            and self.nxt_off == end
            and self.state
            in (State.FIN_WAIT_1, State.LAST_ACK, State.CLOSING, State.TIME_WAIT)
        ):
            self.fin_sent = True
            out.append(
                Segment(
                    FIN | ACK,
                    seq=wrapping_add(self.iss, 1 + self.nxt_off),
                    ack=self.rcv_nxt,
                    wnd=self._recv_window_field(),
                )
            )
            self._pending_ack = False
            if self.rto_deadline is None:
                self._arm_rto(now)

        seq_after = self._snd_nxt_seq()
        if seq_gt(seq_after, self.snd_max_seq):
            self.snd_max_seq = seq_after

        # explicit dup-ACK train for out-of-order arrivals, carrying the
        # SACK blocks that tell the peer exactly which ranges arrived
        if self._dup_ack_owed:
            ack_seg = Segment(
                ACK,
                seq=self._snd_nxt_seq(),
                ack=self.rcv_nxt,
                wnd=self._recv_window_field(),
                sack=self._sack_blocks(),
            )
            out.extend([ack_seg] * self._dup_ack_owed)
            self._dup_ack_owed = 0
            self._pending_ack = False

        # pure ACK if still owed
        if self._pending_ack and not any(s.flags & ACK for s in out):
            out.append(
                Segment(
                    ACK,
                    seq=self._snd_nxt_seq(),
                    ack=self.rcv_nxt,
                    wnd=self._recv_window_field(),
                    sack=self._sack_blocks(),
                )
            )
        if any(s.flags & ACK for s in out):
            self._pending_ack = False
            self._delack_deadline = None  # the held ACK rode along
        self.segs_sent += len(out)
        return out

    def _sack_blocks(self) -> tuple:
        if not self.sack_ok:
            return ()
        return tuple(self.rcv_buf.ooo_ranges()[:3])

    def _data_segment(self, off: int, n: int) -> Segment:
        payload = self.snd_buf.slice(off, n)
        return Segment(
            ACK | (PSH if off + n == self.snd_buf.end_off else 0),
            seq=wrapping_add(self.iss, 1 + off),
            ack=self.rcv_nxt,
            wnd=self._recv_window_field(),
            payload=payload,
        )

    def _arm_probe(self, now: int):
        self.probe_deadline = now + self.rtt.current_rto()


def rst_for(seg: Segment) -> Segment | None:
    """RST replying to `seg` arriving for a nonexistent/closed endpoint
    (RFC 793 reset generation; the socket layer sends this for unmatched
    demux, like the reference's closed-port handling)."""
    if seg.flags & RST:
        return None
    if seg.flags & ACK:
        return Segment(RST, seq=seg.ack, src_port=seg.dst_port, dst_port=seg.src_port)
    return Segment(
        RST | ACK,
        seq=0,
        ack=wrapping_add(seg.seq, seg.seg_len),
        src_port=seg.dst_port,
        dst_port=seg.src_port,
    )
