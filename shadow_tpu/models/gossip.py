"""Gossip/pubsub flood: high-fan-out scatter workload.

BASELINE.json config #3 ("100k-host gossip/pubsub flood, sparse adjacency").
A source publishes a message; every host forwards it once to `fanout` random
static neighbors. Fan-out uses the engine's continuation pattern: one packet
per microstep, with a same-timestamp local continuation event walking the
neighbor list — deterministic order, no dynamic shapes (see
models/base.py contract).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

KIND_MSG = 0  # gossip packet arrives
KIND_FWD = 1  # forwarding continuation (payload word1 = neighbor index)
KIND_PUB = 2  # publisher's initial event


@register_model
class GossipModel:
    name = "gossip"

    def build(self, hosts, seed):
        h = len(hosts)
        fanout = np.array(
            [int(hh["model_args"].get("fanout", 8)) for hh in hosts], np.int32
        )
        size = np.array(
            [int(hh["model_args"].get("payload_bytes", 256)) for hh in hosts],
            np.int32,
        )
        rng = np.random.default_rng(seed)
        # static random neighbor lists (sparse adjacency, CSR-like [H, K]);
        # K = max fanout, per-host fanout masks the tail of each row
        k = max(int(fanout.max()), 1)
        neighbors = rng.integers(0, h, size=(h, k), dtype=np.int64)
        # avoid self-loops deterministically
        self_rows = neighbors == np.arange(h)[:, None]
        neighbors = np.where(self_rows, (neighbors + 1) % h, neighbors)
        params = {
            "neighbors": jnp.asarray(neighbors),
            "size": jnp.asarray(size),
            "fanout": jnp.asarray(fanout),
        }
        state = {
            "seen": jnp.zeros((h,), bool),
            "recv_time": jnp.full((h,), -1, jnp.int64),
            "hops": jnp.full((h,), -1, jnp.int32),
            "fwd_idx": jnp.zeros((h,), jnp.int32),
        }
        events = []
        for hh in hosts:
            if hh["model_args"].get("publisher", False):
                events.append((hh["host_id"], hh["start_time"], KIND_PUB, ()))
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        seen = ctx.state["seen"]
        msg = ctx.active & ((ctx.kind == KIND_MSG) | (ctx.kind == KIND_PUB))
        fresh = msg & ~seen
        hop = jnp.where(ctx.kind == KIND_PUB, 0, ctx.payload[:, 1] + 1)

        # first sight: record + start the forwarding walk at neighbor 0
        state = {
            "seen": seen | fresh,
            "recv_time": jnp.where(fresh, ctx.t, ctx.state["recv_time"]),
            "hops": jnp.where(fresh, hop, ctx.state["hops"]),
            "fwd_idx": ctx.state["fwd_idx"],
        }
        zeros_payload = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32)
        start_fwd = LocalPush(
            mask=fresh,
            t=ctx.t,
            kind=jnp.full((h,), KIND_FWD, jnp.int32),
            payload=zeros_payload.at[:, 1].set(hop),
        )

        # continuation: send to neighbors[fwd_idx], re-push until fanout done
        fwd = ctx.active & (ctx.kind == KIND_FWD)
        idx = state["fwd_idx"]
        more = fwd & (idx < ctx.params["fanout"])
        nbr = jnp.take_along_axis(
            ctx.params["neighbors"],
            jnp.clip(idx, 0, ctx.params["neighbors"].shape[1] - 1)[:, None].astype(
                jnp.int64
            ),
            axis=1,
        )[:, 0]
        send = PacketSend(
            mask=more,
            dst=nbr,
            size_bytes=ctx.params["size"],
            kind=jnp.full((h,), KIND_MSG, jnp.int32),
            payload=ctx.payload,  # hop count rides in word 1
        )
        state["fwd_idx"] = jnp.where(more, idx + 1, idx)
        cont = LocalPush(
            mask=more & ((idx + 1) < ctx.params["fanout"]),
            t=ctx.t,
            kind=jnp.full((h,), KIND_FWD, jnp.int32),
            payload=ctx.payload,
        )
        return HandlerOut(
            state=state, rng=ctx.rng, pushes=(start_fwd, cont), sends=(send,)
        )

    def report(self, state, hosts):
        seen = np.asarray(state["seen"])
        hops = np.asarray(state["hops"])
        rt = np.asarray(state["recv_time"])
        reached = seen.sum()
        return {
            "reached": int(reached),
            "coverage": float(reached / len(seen)),
            "max_hops": int(hops.max()),
            "spread_ms": float((rt.max() - rt[rt >= 0].min()) / 1e6) if reached else 0.0,
        }
