"""Gossip/pubsub flood: high-fan-out scatter workload.

BASELINE.json config #3 ("100k-host gossip/pubsub flood, sparse adjacency").
A source publishes a message; every host forwards it once to `fanout` random
static neighbors. Fan-out uses the engine's continuation pattern: one packet
per microstep, with a same-timestamp local continuation event walking the
neighbor list — deterministic order, no dynamic shapes (see
models/base.py contract).

Repeated-flood mode: `publisher: true` + `publish_interval: "1 s"` floods a
fresh GENERATION every interval (the steady-state pubsub measurement —
one-shot floods are compile-dominated at 100k hosts). Hosts adopt a message
whose generation exceeds their own, reset their forwarding walk, and drop
stale continuations; assumes a single publisher (generations are its
sequence numbers).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

KIND_MSG = 0  # gossip packet arrives
KIND_FWD = 1  # forwarding continuation (payload word1 = neighbor index)
KIND_PUB = 2  # publisher's initial event


@register_model
class GossipModel:
    name = "gossip"
    wire_kind = KIND_MSG  # cross-plane packets arrive as gossip messages (mixed sims)

    def build(self, hosts, seed):
        h = len(hosts)
        fanout = np.array(
            [int(hh["model_args"].get("fanout", 8)) for hh in hosts], np.int32
        )
        size = np.array(
            [int(hh["model_args"].get("payload_bytes", 256)) for hh in hosts],
            np.int32,
        )
        rng = np.random.default_rng(seed)
        # static random neighbor lists (sparse adjacency, CSR-like [H, K]);
        # K = max fanout, per-host fanout masks the tail of each row
        k = max(int(fanout.max()), 1)
        neighbors = rng.integers(0, h, size=(h, k), dtype=np.int64)
        # avoid self-loops deterministically
        self_rows = neighbors == np.arange(h)[:, None]
        neighbors = np.where(self_rows, (neighbors + 1) % h, neighbors)
        from shadow_tpu.config.units import TimeUnit, parse_time_ns

        interval = np.array(
            [
                parse_time_ns(
                    hh["model_args"].get("publish_interval", 0), TimeUnit.MS
                )
                for hh in hosts
            ],
            np.int64,
        )
        params = {
            "neighbors": jnp.asarray(neighbors),
            "size": jnp.asarray(size),
            "fanout": jnp.asarray(fanout),
            "interval": jnp.asarray(interval),
        }
        state = {
            "gen": jnp.zeros((h,), jnp.int32),
            "recv_time": jnp.full((h,), -1, jnp.int64),
            "hops": jnp.full((h,), -1, jnp.int32),
            "fwd_idx": jnp.zeros((h,), jnp.int32),
            "adopted": jnp.zeros((h,), jnp.int64),  # total fresh adoptions
        }
        events = []
        for hh in hosts:
            if hh["model_args"].get("publisher", False):
                events.append((hh["host_id"], hh["start_time"], KIND_PUB, ()))
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        gen = ctx.state["gen"]
        pub = ctx.active & (ctx.kind == KIND_PUB)
        msg = ctx.active & (ctx.kind == KIND_MSG)
        # a publish starts generation own_gen+1; a message carries its
        # generation in payload word 2 and is fresh if it beats ours
        msg_gen = jnp.where(pub, gen + 1, ctx.payload[:, 2])
        fresh = (pub | msg) & (msg_gen > gen)
        hop = jnp.where(pub, 0, ctx.payload[:, 1] + 1)

        # fresh adoption: record + restart the forwarding walk at neighbor 0
        state = {
            "gen": jnp.where(fresh, msg_gen, gen),
            "recv_time": jnp.where(fresh, ctx.t, ctx.state["recv_time"]),
            "hops": jnp.where(fresh, hop, ctx.state["hops"]),
            "fwd_idx": jnp.where(fresh, 0, ctx.state["fwd_idx"]),
            "adopted": ctx.state["adopted"] + fresh,
        }
        zeros_payload = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32)
        start_fwd = LocalPush(
            mask=fresh,
            t=ctx.t,
            kind=jnp.full((h,), KIND_FWD, jnp.int32),
            payload=zeros_payload.at[:, 1].set(hop).at[:, 2].set(msg_gen),
        )
        # repeated-flood mode: the publisher re-arms its own tick
        repub = pub & (ctx.params["interval"] > 0)
        pub_push = LocalPush(
            mask=repub,
            t=ctx.t + ctx.params["interval"],
            kind=jnp.full((h,), KIND_PUB, jnp.int32),
            payload=zeros_payload,
        )

        # continuation: send to neighbors[fwd_idx], re-push until fanout
        # done; a continuation from a SUPERSEDED generation is dropped
        fwd = (
            ctx.active
            & (ctx.kind == KIND_FWD)
            & (ctx.payload[:, 2] == state["gen"])
        )
        idx = state["fwd_idx"]
        more = fwd & (idx < ctx.params["fanout"])
        nbr = jnp.take_along_axis(
            ctx.params["neighbors"],
            jnp.clip(idx, 0, ctx.params["neighbors"].shape[1] - 1)[:, None].astype(
                jnp.int64
            ),
            axis=1,
        )[:, 0]
        send = PacketSend(
            mask=more,
            dst=nbr,
            size_bytes=ctx.params["size"],
            kind=jnp.full((h,), KIND_MSG, jnp.int32),
            payload=ctx.payload,  # hop count rides in word 1
        )
        state["fwd_idx"] = jnp.where(more, idx + 1, idx)
        cont = LocalPush(
            mask=more & ((idx + 1) < ctx.params["fanout"]),
            t=ctx.t,
            kind=jnp.full((h,), KIND_FWD, jnp.int32),
            payload=ctx.payload,
        )
        return HandlerOut(
            state=state, rng=ctx.rng,
            pushes=(start_fwd, pub_push, cont), sends=(send,),
        )

    def report(self, state, hosts):
        g = np.asarray(state["gen"])
        hops = np.asarray(state["hops"])
        rt = np.asarray(state["recv_time"])
        gmax = int(g.max())
        reached = int((g == gmax).sum()) if gmax > 0 else 0
        return {
            "reached": reached,  # of the latest generation
            "coverage": float(reached / len(g)) if gmax > 0 else 0.0,
            "generations": gmax,
            "adoptions": int(np.asarray(state["adopted"]).sum()),
            "max_hops": int(hops.max()),
            "spread_ms": float((rt.max() - rt[rt >= 0].min()) / 1e6) if reached else 0.0,
        }
