"""Vectorized host-model interface.

A model is the device-side analogue of the reference's managed process +
syscall surface for simulated-only hosts: instead of one process per host
issuing syscalls, ONE set of handlers executes for ALL hosts per microstep,
with per-host masks selecting who is active (classic SoA/SPMD recast of
Host::execute's per-event dispatch, reference src/main/host/host.rs:809-864).

Contract (what keeps the simulation deterministic — violating these breaks the
determinism gate, tests/test_determinism.py):
  - `handle` must be a pure jax function of (ctx, model params);
  - RNG draws go through ops.rng with mask = the hosts actually consuming the
    draw (never draw unconditionally for all hosts);
  - state updates must be masked by `ctx.active` (inactive lanes unchanged);
  - at most one event is handled per host per microstep; fan-out patterns
    re-push a local continuation event at the same timestamp (the engine's
    order key keeps continuation order deterministic).

Emission ports are static: `HandlerOut.pushes` / `.sends` are tuples whose
length is fixed at trace time (each port costs one scatter per microstep —
keep them few; use continuations for wide fan-out).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Protocol

from jax import Array

from shadow_tpu.ops.rng import RngState

# Event-kind space: models use kinds 0..KIND_MASK; the engine owns flag bits.
KIND_MASK = 0xFFFF
KIND_PKT = 1 << 16  # event is a packet arrival (set by the engine at send)
KIND_INGRESS_DONE = 1 << 17  # packet already passed ingress shaping

# Packet payload convention: word 0 = size in bytes (engine-owned: drives
# bandwidth shaping); words 1..3 are model-defined.
PAYLOAD_SIZE_WORD = 0


@dataclasses.dataclass
class HandlerCtx:
    """Per-microstep context handed to Model.handle (all arrays shard-local)."""

    t: Array  # i64[H] event time (valid where active)
    window_end: Array  # i64[] current round end
    kind: Array  # i32[H] model kind (engine flags stripped)
    payload: Array  # i32[H, P]
    active: Array  # bool[H] host handles an event this microstep
    is_packet: Array  # bool[H] event is a delivered packet
    src: Array  # i64[H] sending host's global id (valid for packets)
    host_id: Array  # i64[H] global host ids of this shard
    state: Any  # model state pytree ([H, ...] arrays)
    params: Any  # model param pytree ([H, ...] arrays, immutable)
    rng: RngState


class LocalPush(NamedTuple):
    """Schedule a future event on the host's own queue (timer/task analogue,
    reference host.rs:731-738 schedule_task_*)."""

    mask: Array  # bool[H]
    t: Array  # i64[H] absolute time, must be >= ctx.t
    kind: Array  # i32[H] model kind
    payload: Array  # i32[H, P]


class PacketSend(NamedTuple):
    """Send a packet to a (possibly remote) host — enters the egress pipeline:
    token bucket → latency/loss → round-barrier exchange (worker.rs:330-425).

    Burst sends (count_max > 1): one port emits up to `count_max` back-to-back
    packets to the SAME destination in a single microstep — segment k of the
    burst (k < count[h]) carries payload + k * payload_inc and its own loss
    draw, bandwidth charge, and order key. The destination lookup runs once
    per port instead of once per packet, which is what makes a TCP window
    burst affordable on device (the routing reduction reads H x N tables)."""

    mask: Array  # bool[H]
    dst: Array  # i64[H] global destination host id
    size_bytes: Array  # i32[H]
    kind: Array  # i32[H] model kind dispatched at the destination
    payload: Array  # i32[H, P] (word 0 overwritten with size_bytes)
    count: Any = None  # i32[H] burst length (None -> mask as 0/1)
    payload_inc: Any = None  # i32[H, P] per-segment payload increment
    count_max: int = 1  # static burst width (trace-time)


class FlowDone(NamedTuple):
    """A flow-completion record for the network observatory's flow ledger
    (obs/netobs.py): emitted by models that track application flows (the
    tgen client's FIN-ACK), consumed by the engine ONLY when the ledger is
    traced in (`EngineConfig.flow_ledger_active`) — an observer, so
    emitting it never changes digests, events, or drops. All arrays are
    per-host lanes; at most one flow completes per host per microstep
    (the same one-event-per-host contract every emission port obeys)."""

    mask: Array  # bool[H] this host completed a flow at this event
    dst: Array  # i32/i64[H] the peer (server) host id
    flow: Array  # i32[H] model flow index (tgen: the completed phase)
    t_start: Array  # i64[H] flow start sim-time (ns)
    bytes: Array  # i32/i64[H] application payload bytes transferred
    retransmits: Array  # i32/i64[H] retransmitted segments of THIS flow


class HandlerOut(NamedTuple):
    state: Any
    rng: RngState
    pushes: tuple[LocalPush, ...] = ()
    sends: tuple[PacketSend, ...] = ()
    # flow-completion port (network observatory): None for models without
    # application flows. The engine reads it only when the flow ledger is
    # traced in, so carrying it costs nothing when the observatory is off.
    flow: Any = None  # FlowDone | None


class Model(Protocol):
    """A host application model (see module docstring for the contract).

    Network-observatory hooks (all optional, observer-only):
      - `timer_kinds`: tuple of model event kinds that are TIMER events
        (retransmit/delayed-ACK/periodic timers) for the observatory's
        event-class accounting. Packet arrivals classify as `packet` via
        the engine's KIND_PKT flag; non-packet kinds outside this tuple
        classify as `app`. Default () = no timer kinds.
      - `flow_ledger`: True when `handle` emits `HandlerOut.flow`
        completion records (the drivers size a device flow ledger only
        for such models).
      - `per_host_network(state) -> dict[str, array]`: host-side hook
        returning per-host [H] network counters from final model state
        (e.g. {"bytes": ..., "retransmits": ...}) folded into the
        per-link/per-host report. Default absent = engine counters only.
    """

    name: str

    def build(self, hosts: list[dict], seed: int) -> tuple[Any, Any, list]:
        """Host-side setup. `hosts` is one dict per simulated host:
        {"host_id": int, "model_args": {...}, "start_time": ns, ...}.

        Returns (params, state, initial_events) where initial_events is a list
        of (host_id, t_ns, kind, payload_tuple) seeded into the event queue
        (the analogue of Host::add_application scheduling process start tasks,
        reference host.rs:392)."""
        ...

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        ...

    def report(self, state, hosts: list[dict]) -> dict:
        """Host-side end-of-sim summary from final model state (the analogue
        of per-process exit status / stdout, used by tests and sim-stats)."""
        ...


MODEL_REGISTRY: dict[str, type] = {}


def register_model(cls):
    MODEL_REGISTRY[cls.name] = cls
    return cls


def get_model(name: str):
    if name not in MODEL_REGISTRY:
        raise KeyError(
            f"unknown model {name!r}; available: {sorted(MODEL_REGISTRY)}"
        )
    return MODEL_REGISTRY[name]
