"""tgen-style bulk TCP flows on device-modeled endpoints.

Reference analogue: the tgen traffic-generator system tests
(/root/reference/src/test/tgen/ — fixed-size TCP flows between hosts) on
top of the sans-I/O TCP machine (/root/reference/src/lib/tcp/src/lib.rs:
244-345: per-connection snd_una/snd_nxt/cwnd/ssthresh/rto state advanced
by segment arrivals and timers). In the reference EVERY simulated socket
speaks full TCP via one state machine object per connection; the device
recast keeps the same protocol dynamics but holds the connection state as
per-host SoA lanes advanced by one vectorized handler — the same
engine-contract recast the other models use (models/base.py docstring).

What is modeled (capability target = tgen bulk flows, VERDICT r4 #1):
  - three-way-ish handshake (SYN -> SYN-ACK -> first DATA acks the SYN),
    FIN/FIN-ACK teardown, client retries on timeout;
  - segment-granular Reno congestion control: slow start, congestion
    avoidance (1/cwnd per ACK, fixed-point), fast retransmit on 3 dup
    ACKs, NewReno partial-ACK hole repair during recovery, cwnd inflation
    on further dup ACKs, RTO with exponential backoff and go-back-N reset
    (reference tcp_cong_reno.c / lib/tcp states.rs semantics);
  - RFC 6298 RTT estimation (srtt/rttvar in integer ns, Karn's rule:
    no samples from retransmitted segments);
  - receiver-side out-of-order reassembly via a 32-segment SACK bitmap
    (the device form of the reference's selectiveACKs block list,
    tcp.c:151-177): cumulative ACKs jump once a hole fills, and every
    ACK carries the bitmap so a future sender-side SACK policy has the
    wire format it needs.

Deliberate divergences from the byte-exact CPU-plane machine
(shadow_tpu/tcp/state.py), documented per the project's divergence rule:
  - sequence space is SEGMENT-granular (one MSS per unit): SoA lanes stay
    i32 and the reassembly window is one u32 bitmap; flow sizes round up
    to whole segments. Wire sizes still account mss+40 bytes per DATA
    segment so bandwidth shaping and pcap sizing stay byte-faithful.
  - delayed ACK follows RFC 1122's "at least every second full-sized
    segment" with a lazy timer lane (`delack`, default 40 ms; 0 disables);
    out-of-order and duplicate segments are always acked immediately so
    dup-ACK-driven fast retransmit keeps its timing. Nagle is senseless at
    segment granularity (every send is a full MSS) and lives only in the
    CPU-plane machine.
  - a TX continuation transmits up to `tx_batch` segments per microstep
    (one engine send port each) instead of one: pure event-count economy —
    the wire result is identical because all of a window's sends depart
    within the same round anyway.
  - cwnd is capped by `cwnd_cap` (standing in for the peer's advertised
    window); the engine's per-round send budget must exceed
    cwnd_cap + a few control packets or budget drops act as extra loss.

Workload: phased all-to-all. Each host runs a client and a server lane;
in phase k client i transfers `flow_segs` segments to peer
(i + 1 + k mod (H-1)) mod H, so every host serves exactly one inbound
flow per phase and over H-1 phases the pattern is a full all-to-all.
Phases advance per client as flows complete (loss can skew clients;
a busy server drops the incoming SYN and the client retries on RTO —
listen-queue-full semantics). Packets are stamped with the flow phase so
stale segments from a previous flow are discarded, not misdelivered.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from shadow_tpu.config.units import TimeUnit, parse_time_ns
from shadow_tpu.models.base import (
    FlowDone,
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS
from shadow_tpu.simtime import TIME_MAX

KIND_TICK = 0  # client: start the next flow
KIND_SEG = 1  # wire segment (ftype in the meta word)
KIND_TX = 2  # client: transmit continuation (up to tx_batch DATA per step)
KIND_RTO = 3  # client: retransmission timer lane
KIND_DELACK = 4  # server: delayed-ACK timer lane

# segment types (meta word low byte)
FT_SYN = 1
FT_SYNACK = 2
FT_DATA = 3
FT_ACK = 4
FT_FIN = 5
FT_FINACK = 6

# payload words (word 0 is the engine-owned size)
PW_SEQ = 1  # DATA/SYN/FIN: segment index; ACK: SACK bitmap beyond ack
PW_ACK = 2  # ACK/SYNACK: cumulative ack (next expected segment)
PW_META = 3  # ftype | flow_phase << 8

# client connection states
CST_IDLE = 0
CST_SYN = 1
CST_EST = 2
CST_FIN = 3
CST_DONE = 4

HDR_BYTES = 40  # IP + TCP header burden (matches host/sockets.py TCP sizing)
_CWND_ONE = 1 << 10  # fixed-point unit: cwnd_x == cwnd << 10


def _ctz32(x):
    """Count trailing zeros of a u32 (32 for x == 0) — used to pop the
    run of contiguously received segments off the reassembly bitmap."""
    low = x & (jnp.uint32(0) - x)
    return jnp.where(
        x == 0, jnp.uint32(32), lax.population_count(low - jnp.uint32(1))
    )


@register_model
class TgenTcpModel:
    name = "tgen_tcp"
    wire_kind = KIND_SEG
    # network-observatory hooks (models/base.py Model docstring): the TCP
    # timer lanes are the retransmit and delayed-ACK timers — exactly the
    # events ROADMAP item 2's timer-wheel decision needs counted. TICK
    # (flow pacing) and TX (transmit continuation) classify as app.
    timer_kinds = (KIND_RTO, KIND_DELACK)
    # static routing hint for the device timer wheel (core/engine.py
    # _route_timer_pushes): only push port B (timer chain / tick /
    # delack) can carry a timer kind — port A is always KIND_TX, so the
    # wheel router skips its per-microstep classification + write pass
    timer_push_ports = (1,)
    flow_ledger = True  # handle() emits FlowDone records at FIN-ACK

    def build(self, hosts, seed):
        h = len(hosts)
        if h < 2:
            raise ValueError("tgen_tcp needs at least 2 hosts")

        def arg(hh, key, default):
            return hh["model_args"].get(key, default)

        def tns(hh, key, default):
            return parse_time_ns(arg(hh, key, default), TimeUnit.MS)

        flow_segs = np.array(
            [int(arg(hh, "flow_segs", 64)) for hh in hosts], np.int32
        )
        if (flow_segs < 1).any():
            raise ValueError("tgen_tcp: flow_segs must be >= 1 "
                             "(a zero-length flow would never FIN)")
        params = {
            "flow_segs": jnp.asarray(flow_segs),
            "mss": jnp.asarray(
                [int(arg(hh, "mss", 1460)) for hh in hosts], np.int32
            ),
            "flows": jnp.asarray(
                [int(arg(hh, "flows", 1)) for hh in hosts], np.int32
            ),
            "cwnd_init": jnp.asarray(
                [int(arg(hh, "cwnd_init", 2)) for hh in hosts], np.int32
            ),
            "cwnd_cap": jnp.asarray(
                [int(arg(hh, "cwnd_cap", 16)) for hh in hosts], np.int32
            ),
            "rto_init": jnp.asarray(
                [tns(hh, "rto_init", "1 s") for hh in hosts], np.int64
            ),
            "rto_min": jnp.asarray(
                [tns(hh, "rto_min", "200 ms") for hh in hosts], np.int64
            ),
            "rto_max": jnp.asarray(
                [tns(hh, "rto_max", "60 s") for hh in hosts], np.int64
            ),
            "flow_gap": jnp.asarray(
                [tns(hh, "flow_gap", "10 ms") for hh in hosts], np.int64
            ),
            "delack": jnp.asarray(
                [tns(hh, "delack", "40 ms") for hh in hosts], np.int64
            ),
            "num_hosts": jnp.full((h,), h, jnp.int32),
        }
        # static trace-time knob: segments transmitted per TX continuation
        # (= engine send ports). Event-count economy vs per-microstep port
        # cost; 4 measured best at the 10k-host bench point.
        self.tx_batch = max(
            max(int(arg(hh, "tx_batch", 4)) for hh in hosts), 1
        )

        def zi32():
            return jnp.zeros((h,), jnp.int32)

        def zi64():
            return jnp.zeros((h,), jnp.int64)

        state = {
            # client lane
            "c_state": zi32(),
            "c_phase": zi32(),
            "c_peer": zi32(),
            "snd_una": zi32(),
            "snd_nxt": zi32(),
            "cwnd_x": jnp.full((h,), _CWND_ONE, jnp.int32),
            "ssth_x": jnp.full((h,), 0x7FFFFFFF, jnp.int32),
            "dup": zi32(),
            "recover": jnp.full((h,), -1, jnp.int32),
            "srtt": zi64(),  # 0 = no sample yet (RFC 6298 first-sample rule)
            "rttvar": zi64(),
            # copy, don't alias: state is DONATED to the jitted chunk while
            # params ride alongside — sharing a buffer is a donation error
            "rto": jnp.array(params["rto_init"], copy=True),
            "rtt_seq": jnp.full((h,), -1, jnp.int32),
            "rtt_t0": zi64(),
            "deadline": jnp.full((h,), TIME_MAX, jnp.int64),
            "timer_alive": jnp.zeros((h,), bool),
            "tx_alive": jnp.zeros((h,), bool),
            "flow_t0": zi64(),
            # server lane
            "sv_state": zi32(),  # 0 LISTEN, 1 ESTABLISHED
            "sv_peer": zi32(),
            "sv_phase": zi32(),
            "rcv_nxt": zi32(),
            "sv_bm": jnp.zeros((h,), jnp.uint32),
            "da_pend": jnp.zeros((h,), bool),  # delayed ACK held
            "da_t": jnp.full((h,), TIME_MAX, jnp.int64),
            "da_alive": jnp.zeros((h,), bool),  # DELACK timer event queued
            # counters
            "d_sent": zi64(),
            "d_rtx": zi64(),
            "fast_rtx": zi64(),
            "timeouts": zi64(),
            "flows_done": zi64(),
            # per-flow retransmit base: d_rtx at the current flow's start,
            # so the flow ledger's per-flow retransmit count is a cheap
            # subtraction at FIN-ACK (inert when the observatory is off)
            "flow_rtx0": zi64(),
            "fct_sum": zi64(),
            "segs_rcvd": zi64(),
            "dup_segs": zi64(),
            "bytes_rcvd": zi64(),
            "done_t": zi64(),
        }
        # clients with work kick off at their start time
        events = [
            (hh["host_id"], hh["start_time"], KIND_TICK, ())
            for i, hh in enumerate(hosts)
            if int(arg(hh, "flows", 1)) > 0
        ]
        return params, state, events

    # ------------------------------------------------------------------ #

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        st = dict(ctx.state)
        p = ctx.params
        t = ctx.t

        tick = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_TICK)
        seg = ctx.active & ctx.is_packet & (ctx.kind == KIND_SEG)
        tx = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_TX)
        rto_ev = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_RTO)
        da_ev = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_DELACK)

        meta = ctx.payload[:, PW_META]
        ftype = meta & 0xFF
        ph = meta >> 8
        w_seq = ctx.payload[:, PW_SEQ]
        w_ack = ctx.payload[:, PW_ACK]
        src = ctx.src.astype(jnp.int32)
        my_phase = st["c_phase"]
        L = p["flow_segs"]

        # ================= server lane (pure reaction to arrivals) ======
        syn_in = seg & (ftype == FT_SYN)
        data_in = seg & (ftype == FT_DATA)
        fin_in = seg & (ftype == FT_FIN)

        listen = st["sv_state"] == 0
        same_conn = (st["sv_peer"] == src) & (st["sv_phase"] == ph)
        new_conn = syn_in & listen
        dup_syn = syn_in & ~listen & same_conn  # SYN-ACK was lost: resend
        synack_out = new_conn | dup_syn
        # busy server (established with another client): drop the SYN; the
        # client retries on RTO — listen-queue-full semantics.

        data_ok = data_in & (st["sv_state"] == 1) & same_conn
        rel = w_seq - st["rcv_nxt"]
        inorder = data_ok & (rel == 0)
        ooo = data_ok & (rel > 0) & (rel <= 32)
        dup_seg = data_ok & ((rel < 0) | (rel > 32))  # past or beyond window
        bm = st["sv_bm"]
        bm_set = jnp.where(
            ooo,
            bm | (jnp.uint32(1) << jnp.clip(rel - 1, 0, 31).astype(jnp.uint32)),
            bm,
        )
        # in-order arrival: also drain the contiguous run buffered beyond it
        run = _ctz32(~bm_set).astype(jnp.int32)  # buffered segs now in order
        adv = jnp.where(inorder, 1 + run, 0)
        rcv_nxt2 = st["rcv_nxt"] + adv
        shift = jnp.clip(adv, 0, 32).astype(jnp.uint32)
        bm2 = jnp.where(
            inorder,
            jnp.where(shift >= 32, jnp.uint32(0), bm_set >> shift),
            bm_set,
        )
        # FIN: accept when the full flow is in order; a re-FIN after the
        # server already closed (our FIN-ACK was lost) answers statelessly.
        fin_acc = (
            fin_in & (st["sv_state"] == 1) & same_conn
            & (st["rcv_nxt"] == w_seq)
        )
        fin_stateless = fin_in & listen
        finack_out = fin_acc | fin_stateless

        # ---- delayed ACK (RFC 1122: ack at least every 2nd segment; OOO
        # and duplicate segments ack immediately so fast-retransmit timing
        # is unchanged). `delack` 0 disables coalescing entirely.
        da_dis = p["delack"] == 0
        # a hole-filling arrival (non-empty pre-insert bitmap) must ack
        # IMMEDIATELY (RFC 5681: gap-fill acks end recovery without delay;
        # the CPU-plane machine has the same had_runs carve-out)
        filling = bm != 0
        ack_2nd = inorder & (st["da_pend"] | da_dis | filling)
        hold = inorder & ~st["da_pend"] & ~da_dis & ~filling
        ack_imm = ooo | dup_seg
        da_fire = da_ev & st["da_pend"] & (t >= st["da_t"])
        da_repush = da_ev & st["da_pend"] & (t < st["da_t"])
        ack_out = ack_2nd | ack_imm | da_fire
        da_t_new = jnp.where(hold, t + p["delack"], st["da_t"])
        da_arm = hold & ~st["da_alive"]
        st["da_pend"] = jnp.where(
            hold, True,
            jnp.where(ack_out | fin_acc | new_conn, False, st["da_pend"]),
        )
        st["da_t"] = da_t_new
        st["da_alive"] = jnp.where(
            da_ev, da_repush, jnp.where(da_arm, True, st["da_alive"])
        )

        st["sv_state"] = jnp.where(
            new_conn, 1, jnp.where(fin_acc, 0, st["sv_state"])
        )
        st["sv_peer"] = jnp.where(new_conn, src, st["sv_peer"])
        st["sv_phase"] = jnp.where(new_conn, ph, st["sv_phase"])
        st["rcv_nxt"] = jnp.where(
            new_conn, 0, jnp.where(fin_acc, 0, rcv_nxt2)
        )
        st["sv_bm"] = jnp.where(new_conn | fin_acc, jnp.uint32(0), bm2)
        st["segs_rcvd"] = st["segs_rcvd"] + inorder + ooo
        st["dup_segs"] = st["dup_segs"] + dup_seg
        # actual payload bytes from the wire size word (the SENDER's mss
        # sets segment size; crediting the receiver's own mss would be
        # wrong under heterogeneous mss args)
        wire_sz = ctx.payload[:, 0]
        st["bytes_rcvd"] = st["bytes_rcvd"] + jnp.where(
            inorder | ooo, (wire_sz - HDR_BYTES).astype(jnp.int64), 0
        )

        # ================= client lane ==================================
        for_me = seg & (src == st["c_peer"]) & (ph == my_phase)
        synack_in = for_me & (ftype == FT_SYNACK) & (st["c_state"] == CST_SYN)
        ack_in = for_me & (ftype == FT_ACK) & (st["c_state"] == CST_EST)
        finack_in = for_me & (ftype == FT_FINACK) & (st["c_state"] == CST_FIN)

        # ---- SYN-ACK: connection up, start the transmit chain
        st["c_state"] = jnp.where(synack_in, CST_EST, st["c_state"])

        # ---- ACK processing (Reno + NewReno recovery)
        ack = w_ack
        una0 = st["snd_una"]
        new_acked = ack_in & (ack > una0)
        dup_ack = ack_in & (ack == una0) & (st["snd_nxt"] > una0)

        # RTT sample (Karn's: rtt_seq is cleared on any retransmission)
        samp = new_acked & (st["rtt_seq"] >= 0) & (ack > st["rtt_seq"])
        r = t - st["rtt_t0"]
        first = samp & (st["srtt"] == 0)
        later = samp & (st["srtt"] != 0)
        rttvar1 = jnp.where(
            first,
            r // 2,
            jnp.where(
                later,
                (3 * st["rttvar"] + jnp.abs(st["srtt"] - r)) // 4,
                st["rttvar"],
            ),
        )
        srtt1 = jnp.where(
            first, r, jnp.where(later, (7 * st["srtt"] + r) // 8, st["srtt"])
        )
        rto1 = jnp.where(
            samp,
            jnp.clip(
                srtt1 + jnp.maximum(1_000_000, 4 * rttvar1),
                p["rto_min"],
                p["rto_max"],
            ),
            st["rto"],
        )
        st["srtt"], st["rttvar"], st["rto"] = srtt1, rttvar1, rto1
        st["rtt_seq"] = jnp.where(samp, -1, st["rtt_seq"])

        in_rec = st["recover"] >= 0
        exit_rec = new_acked & in_rec & (ack >= st["recover"])
        partial = new_acked & in_rec & (ack < st["recover"])

        # cwnd growth on forward ACKs outside recovery
        grow = new_acked & ~in_rec
        acked_segs = jnp.where(grow, ack - una0, 0)
        ss = st["cwnd_x"] < st["ssth_x"]
        ca_inc = (1 << 20) // jnp.maximum(st["cwnd_x"], 1)
        cwnd1 = jnp.where(
            grow,
            jnp.where(
                ss,
                st["cwnd_x"] + (acked_segs << 10),
                st["cwnd_x"] + ca_inc,
            ),
            st["cwnd_x"],
        )
        # dup-ACK counting / fast retransmit / inflation
        dup1 = jnp.where(new_acked, 0, jnp.where(dup_ack, st["dup"] + 1, st["dup"]))
        fast = dup_ack & (dup1 == 3) & ~in_rec
        inflight = st["snd_nxt"] - una0
        ssth_fast = jnp.maximum((inflight << 10) // 2, 2 << 10)
        cwnd1 = jnp.where(
            fast,
            ssth_fast + (3 << 10),
            jnp.where(dup_ack & in_rec, cwnd1 + _CWND_ONE, cwnd1),
        )
        st["ssth_x"] = jnp.where(fast, ssth_fast, st["ssth_x"])
        st["recover"] = jnp.where(
            fast, st["snd_nxt"], jnp.where(exit_rec, -1, st["recover"])
        )
        cwnd1 = jnp.where(exit_rec, st["ssth_x"], cwnd1)
        cwnd_cap_x = p["cwnd_cap"] << 10
        st["cwnd_x"] = jnp.clip(cwnd1, _CWND_ONE, cwnd_cap_x)
        st["dup"] = dup1
        st["snd_una"] = jnp.where(new_acked, ack, una0)
        # Karn's rule on the retransmissions triggered below
        st["rtt_seq"] = jnp.where(fast | partial, -1, st["rtt_seq"])

        # all data acked -> send FIN
        all_acked = new_acked & (st["snd_una"] >= L) & (st["c_state"] == CST_EST)
        st["c_state"] = jnp.where(all_acked, CST_FIN, st["c_state"])

        # ---- FIN-ACK: flow complete; next phase or done. The flow-ledger
        # record is captured HERE, before the phase/flow_t0 lanes advance:
        # the completed flow's identity is (this host, c_peer, my_phase),
        # its span [flow_t0, t), its payload L segments x mss bytes, and
        # its retransmits the d_rtx delta since the flow started. Pure
        # observation — the engine reads it only when the ledger is on.
        flow_done = FlowDone(
            mask=finack_in,
            dst=st["c_peer"],
            flow=my_phase,
            t_start=st["flow_t0"],
            bytes=L.astype(jnp.int64) * p["mss"].astype(jnp.int64),
            retransmits=st["d_rtx"] - st["flow_rtx0"],
        )
        phase1 = jnp.where(finack_in, my_phase + 1, my_phase)
        more = finack_in & (phase1 < p["flows"])
        st["c_phase"] = phase1
        st["c_state"] = jnp.where(
            finack_in, jnp.where(more, CST_IDLE, CST_DONE), st["c_state"]
        )
        st["flows_done"] = st["flows_done"] + finack_in
        st["fct_sum"] = st["fct_sum"] + jnp.where(finack_in, t - st["flow_t0"], 0)
        st["done_t"] = jnp.where(finack_in & ~more, t, st["done_t"])

        # ---- TICK: start the next flow (SYN out)
        start = tick & (st["c_state"] == CST_IDLE) & (my_phase < p["flows"])
        nh = p["num_hosts"]
        hid = ctx.host_id.astype(jnp.int32)
        peer = (hid + 1 + my_phase % (nh - 1)) % nh
        st["c_peer"] = jnp.where(start, peer, st["c_peer"])
        st["c_state"] = jnp.where(start, CST_SYN, st["c_state"])
        st["snd_una"] = jnp.where(start, 0, st["snd_una"])
        st["snd_nxt"] = jnp.where(start, 0, st["snd_nxt"])
        st["cwnd_x"] = jnp.where(start, p["cwnd_init"] << 10, st["cwnd_x"])
        st["ssth_x"] = jnp.where(start, 0x7FFFFFFF, st["ssth_x"])
        st["dup"] = jnp.where(start, 0, st["dup"])
        st["recover"] = jnp.where(start, -1, st["recover"])
        st["srtt"] = jnp.where(start, 0, st["srtt"])
        st["rttvar"] = jnp.where(start, 0, st["rttvar"])
        st["rto"] = jnp.where(start, p["rto_init"], st["rto"])
        st["rtt_seq"] = jnp.where(start, -1, st["rtt_seq"])
        st["flow_t0"] = jnp.where(start, t, st["flow_t0"])
        st["flow_rtx0"] = jnp.where(start, st["d_rtx"], st["flow_rtx0"])

        # ---- TX continuation: up to tx_batch DATA segments per microstep
        # (one send port each; same-round departure makes the wire result
        # identical to one-per-microstep, at a fraction of the event count)
        txb = self.tx_batch
        cwnd_segs = st["cwnd_x"] >> 10
        lim_seq = jnp.minimum(st["snd_una"] + cwnd_segs, L)
        n_can = jnp.where(
            tx & (st["c_state"] == CST_EST),
            jnp.clip(lim_seq - st["snd_nxt"], 0, txb),
            0,
        )
        can_tx = n_can > 0
        tx_seq = st["snd_nxt"]  # first segment of this batch
        st["snd_nxt"] = st["snd_nxt"] + n_can
        st["d_sent"] = st["d_sent"] + n_can
        # time exactly one segment in flight (Karn-safe: first transmission)
        time_it = can_tx & (st["rtt_seq"] < 0)
        st["rtt_seq"] = jnp.where(time_it, tx_seq, st["rtt_seq"])
        st["rtt_t0"] = jnp.where(time_it, t, st["rtt_t0"])
        chain_more = can_tx & (st["snd_nxt"] < lim_seq)

        # ---- RTO timer lane (single lazy timer event per host)
        armed = st["deadline"] != TIME_MAX
        expired = rto_ev & armed & (t >= st["deadline"])
        resched = rto_ev & armed & (t < st["deadline"])
        timer_dies = rto_ev & ~armed
        st["timer_alive"] = jnp.where(timer_dies, False, st["timer_alive"])

        syn_to = expired & (st["c_state"] == CST_SYN)
        est_to = expired & (st["c_state"] == CST_EST) & (st["snd_nxt"] > st["snd_una"])
        fin_to = expired & (st["c_state"] == CST_FIN)
        timeout = syn_to | est_to | fin_to
        st["timeouts"] = st["timeouts"] + timeout
        # go-back-N on data timeout: collapse the window, retransmit una
        st["ssth_x"] = jnp.where(
            est_to,
            jnp.maximum(((st["snd_nxt"] - st["snd_una"]) << 10) // 2, 2 << 10),
            st["ssth_x"],
        )
        st["cwnd_x"] = jnp.where(est_to, _CWND_ONE, st["cwnd_x"])
        st["snd_nxt"] = jnp.where(est_to, st["snd_una"] + 1, st["snd_nxt"])
        st["dup"] = jnp.where(est_to, 0, st["dup"])
        st["recover"] = jnp.where(est_to, -1, st["recover"])
        st["rtt_seq"] = jnp.where(est_to, -1, st["rtt_seq"])
        st["rto"] = jnp.where(
            timeout, jnp.minimum(st["rto"] * 2, p["rto_max"]), st["rto"]
        )

        # ---- deadline maintenance (restart on forward progress; clear
        # when nothing is outstanding)
        idleish = (st["c_state"] == CST_IDLE) | (st["c_state"] == CST_DONE)
        quiet = ack_in & ~idleish & (st["snd_nxt"] == st["snd_una"]) & (
            st["c_state"] == CST_EST
        )
        rearm = (
            start
            | synack_in
            | new_acked
            | all_acked
            | can_tx
            | timeout
        )
        st["deadline"] = jnp.where(
            finack_in | quiet | timer_dies,
            TIME_MAX,
            jnp.where(rearm, t + st["rto"], st["deadline"]),
        )

        # ================= emissions ====================================
        # push port A: the TX chain (restart after SYN-ACK / forward ACK)
        can_send_more = (
            (st["c_state"] == CST_EST)
            & (st["snd_nxt"] < st["snd_una"] + (st["cwnd_x"] >> 10))
            & (st["snd_nxt"] < L)
        )
        # dup ACKs restart the chain too: cwnd inflation during fast
        # recovery exists precisely to let NEW data flow while the
        # retransmit is in flight (RFC 5681 §3.2 step 4)
        restart = (
            (synack_in | new_acked | dup_ack) & can_send_more & ~st["tx_alive"]
        )
        push_tx = chain_more | restart
        st["tx_alive"] = jnp.where(
            tx, chain_more, jnp.where(restart, True, st["tx_alive"])
        )
        port_a = LocalPush(
            mask=push_tx,
            t=t,
            kind=jnp.full((h,), KIND_TX, jnp.int32),
            payload=jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32),
        )

        # push port B: timer chain + next-flow tick + delack timer — all
        # mutually exclusive per host (timer pushes come from TICK/RTO
        # events, tick pushes from FINACK, delack pushes from DATA/DELACK)
        # (re)arm whenever THIS event left a live deadline and no chain is
        # queued — not just at flow start: the chain legitimately dies
        # whenever it fires during a quiet spell (deadline == TIME_MAX),
        # and the next rearming event must resurrect it or the client
        # never hears its RTO again (found as a wedged flow: deadline
        # armed, timer_alive False, simulation idle forever).
        arm_timer = (
            (tick | seg | tx | rto_ev)
            & (st["deadline"] != TIME_MAX)
            & ~st["timer_alive"]
        )
        st["timer_alive"] = jnp.where(arm_timer, True, st["timer_alive"])
        timer_push = arm_timer | resched | expired
        timer_t = jnp.where(
            arm_timer,
            st["deadline"],
            jnp.where(expired, t + st["rto"], st["deadline"]),
        )
        next_tick = finack_in & more
        da_push = da_arm | da_repush
        port_b = LocalPush(
            mask=timer_push | next_tick | da_push,
            t=jnp.where(
                next_tick,
                t + p["flow_gap"],
                jnp.where(da_push, st["da_t"], timer_t),
            ),
            kind=jnp.where(
                next_tick, KIND_TICK, jnp.where(da_push, KIND_DELACK, KIND_RTO)
            ).astype(jnp.int32),
            payload=jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32),
        )

        # send port: at most one wire segment per host per microstep — the
        # masks below are mutually exclusive by construction (each host
        # handles one event, and each event type emits at most one packet).
        rtx_data = fast | partial | est_to
        rtx_seq = jnp.where(fast | est_to, st["snd_una"], ack)
        st["d_rtx"] = st["d_rtx"] + rtx_data
        st["fast_rtx"] = st["fast_rtx"] + fast
        send_syn = start | syn_to
        send_fin = all_acked | fin_to
        send_data = can_tx | rtx_data

        m = send_syn | send_fin | send_data | synack_out | ack_out | finack_out
        # destinations: ACKs address via the stored connection (a delack
        # timer firing is a LOCAL event whose payload src/phase are
        # meaningless); SYNACK/FINACK echo the triggering packet (the
        # stateless re-FIN answer must reach a peer no longer in sv_peer);
        # client-side emissions go to c_peer.
        dst = jnp.where(
            ack_out,
            st["sv_peer"],
            jnp.where(synack_out | finack_out, src, st["c_peer"]),
        ).astype(jnp.int64)
        ft = jnp.where(
            send_syn,
            FT_SYN,
            jnp.where(
                send_fin,
                FT_FIN,
                jnp.where(
                    send_data,
                    FT_DATA,
                    jnp.where(
                        synack_out,
                        FT_SYNACK,
                        jnp.where(ack_out, FT_ACK, FT_FINACK),
                    ),
                ),
            ),
        ).astype(jnp.int32)
        # phase stamp: ACKs carry the stored connection phase; SYNACK/
        # FINACK echo the packet's phase; client emissions use their own
        out_phase = jnp.where(
            ack_out,
            st["sv_phase"],
            jnp.where(synack_out | finack_out, ph, my_phase),
        )
        seq_word = jnp.where(
            send_data,
            jnp.where(rtx_data, rtx_seq, tx_seq),
            jnp.where(send_fin, L, jnp.where(ack_out, st["sv_bm"].astype(jnp.int32), 0)),
        )
        ack_word = jnp.where(ack_out, st["rcv_nxt"], 0)
        payload = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32)
        payload = payload.at[:, PW_SEQ].set(seq_word)
        payload = payload.at[:, PW_ACK].set(ack_word)
        payload = payload.at[:, PW_META].set(ft | (out_phase << 8))
        size = jnp.where(
            send_data, p["mss"] + HDR_BYTES, jnp.full((h,), HDR_BYTES, jnp.int32)
        ).astype(jnp.int32)
        # segments 2..tx_batch of a TX batch ride the SAME port as a burst
        # (engine PacketSend.count): the per-segment payload differs only
        # by +1 in the seq word, expressed via payload_inc. Non-TX
        # emissions are count 1.
        seq_inc = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32).at[
            :, PW_SEQ
        ].set(1)
        send = PacketSend(
            mask=m,
            dst=dst,
            size_bytes=size,
            kind=jnp.full((h,), KIND_SEG, jnp.int32),
            payload=payload,
            count=jnp.where(can_tx, n_can, 1).astype(jnp.int32),
            payload_inc=seq_inc,
            count_max=txb,
        )

        return HandlerOut(
            state=st, rng=ctx.rng, pushes=(port_a, port_b), sends=(send,),
            flow=flow_done,
        )

    # ------------------------------------------------------------------ #

    def per_host_network(self, state):
        """Per-host network counters for the observatory's per-link fold
        (models/base.py Model docstring): payload bytes RECEIVED (charged
        to the server side) and data segments retransmitted (charged to
        the client side)."""
        return {
            "bytes": np.asarray(state["bytes_rcvd"]),
            "retransmits": np.asarray(state["d_rtx"]),
        }

    def report(self, state, hosts):
        done = np.asarray(state["flows_done"])
        fct = np.asarray(state["fct_sum"])
        n = int(done.sum())
        return {
            "flows_completed": n,
            "flows_expected": int(
                sum(int(hh["model_args"].get("flows", 1)) for hh in hosts)
            ),
            "data_segments_sent": int(np.asarray(state["d_sent"]).sum()),
            "retransmits": int(np.asarray(state["d_rtx"]).sum()),
            "fast_retransmits": int(np.asarray(state["fast_rtx"]).sum()),
            "timeouts": int(np.asarray(state["timeouts"]).sum()),
            "dup_segments": int(np.asarray(state["dup_segs"]).sum()),
            "mean_fct_ms": (float(fct.sum()) / n / 1e6) if n else None,
            "payload_bytes_received": int(
                np.asarray(state["bytes_rcvd"]).sum()
            ),
        }
