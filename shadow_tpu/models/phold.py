"""PHOLD: the classic PDES benchmark workload.

Reference: src/test/phold/ — Shadow's PHOLD is a real socket program (10 hosts,
50 ms latency) where each node holds messages and forwards them to random peers
after random delays. Device recast: each host starts with `population` jobs;
handling a job draws a random peer and sends it a small packet after an
exponential holding delay; receiving the packet is the next job. Event
population is conserved, so this stresses the steady-state round loop +
exchange path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.units import TimeUnit, parse_time_ns
from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS
from shadow_tpu.ops.rng import rng_uniform

KIND_JOB = 0  # a held message matures: pick a peer, send
KIND_MSG = 1  # message arrives: hold it, then it matures


@register_model
class PholdModel:
    name = "phold"
    wire_kind = KIND_MSG  # cross-plane packets count as held messages (mixed sims)
    # observatory event classes: a matured job IS a timer fire (the held
    # message's exponential delay elapsing); arrivals classify as packets
    timer_kinds = (KIND_JOB,)

    def build(self, hosts, seed):
        h = len(hosts)
        mean_delay = np.array(
            [
                parse_time_ns(hh["model_args"].get("mean_delay", "100 ms"), TimeUnit.MS)
                for hh in hosts
            ],
            np.int64,
        )
        size = np.array(
            [int(hh["model_args"].get("payload_bytes", 64)) for hh in hosts],
            np.int32,
        )
        params = {
            "mean_delay": jnp.asarray(mean_delay),
            "size": jnp.asarray(size),
            "num_hosts": jnp.full((h,), h, jnp.int64),
        }
        state = {"handled": jnp.zeros((h,), jnp.int64)}
        events = []
        for hh in hosts:
            for _ in range(int(hh["model_args"].get("population", 1))):
                events.append((hh["host_id"], hh["start_time"], KIND_JOB, ()))
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        job = ctx.active & (ctx.kind == KIND_JOB)
        arrived = ctx.active & (ctx.kind == KIND_MSG)

        # an arrived message is held: schedule its maturity after an
        # exponential delay drawn from the receiver's RNG lane
        rng, u_hold = rng_uniform(ctx.rng, arrived)
        hold = _exp_delay(u_hold, ctx.params["mean_delay"])
        push = LocalPush(
            mask=arrived,
            t=ctx.t + hold,
            kind=jnp.full((h,), KIND_JOB, jnp.int32),
            payload=jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32),
        )

        # a matured job picks a uniform random peer and sends
        rng, u_dst = rng_uniform(rng, job)
        n = ctx.params["num_hosts"]
        dst = jnp.minimum((u_dst * n.astype(jnp.float32)).astype(jnp.int64), n - 1)
        send = PacketSend(
            mask=job,
            dst=dst,
            size_bytes=ctx.params["size"],
            kind=jnp.full((h,), KIND_MSG, jnp.int32),
            payload=jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32),
        )

        state = {"handled": ctx.state["handled"] + ctx.active}
        return HandlerOut(state=state, rng=rng, pushes=(push,), sends=(send,))

    def report(self, state, hosts):
        handled = np.asarray(state["handled"])
        return {"total_events": int(handled.sum()), "min": int(handled.min()), "max": int(handled.max())}


def _exp_delay(u, mean_ns):
    """Exponential holding time, floored at 1 us so jobs always advance."""
    x = -jnp.log(jnp.maximum(1e-7, 1.0 - u))
    d = (x * mean_ns.astype(jnp.float32)).astype(jnp.int64)
    return jnp.maximum(d, 1_000)
