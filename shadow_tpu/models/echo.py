"""UDP echo / request-response traffic (tgen-style fixed-size flows).

The device recast of the reference's tgen integration workloads
(src/test/tgen/fixed_size) and BASELINE.json configs #1-2: clients send
fixed-size requests to a server on a schedule; servers echo; clients record
RTTs. Roles are per-host params in ONE model (a simulation runs one model;
heterogeneous behavior lives in the role/arg arrays).

RTT measurement packs the i64 send timestamp into two i32 payload words — the
device analogue of tgen stamping payloads.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.units import TimeUnit, parse_time_ns
from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

KIND_TICK = 0  # client send timer
KIND_REQ = 1  # request packet (server handles)
KIND_RESP = 2  # response packet (client handles)

ROLE_CLIENT = 0
ROLE_SERVER = 1


def _pack_t(t):
    lo = (t & 0xFFFFFFFF).astype(jnp.int32)
    hi = (t >> 32).astype(jnp.int32)
    return lo, hi


def _unpack_t(lo, hi):
    return (hi.astype(jnp.int64) << 32) | (lo.astype(jnp.int64) & 0xFFFFFFFF)


@register_model
class UdpEchoModel:
    name = "udp_echo"
    wire_kind = KIND_REQ  # cross-plane packets arrive as requests (mixed sims)
    # observatory event classes: the client send tick is the model's one
    # timer lane; requests/responses classify as packets via KIND_PKT
    timer_kinds = (KIND_TICK,)
    # this protocol IS echo-the-payload: a native request's payload words
    # (byte-store key + magic) must ride back verbatim so the bridge can
    # reconstruct the exact reply bytes (cosim._drain_captures); the server
    # path reads only word 0 (size), so raw hybrid words are harmless here
    sanitize_wire_payload = False

    def build(self, hosts, seed):
        h = len(hosts)
        role = np.zeros((h,), np.int32)
        peer = np.zeros((h,), np.int64)
        interval = np.zeros((h,), np.int64)
        size = np.zeros((h,), np.int32)
        by_name = {hh["name"]: hh["host_id"] for hh in hosts}
        for i, hh in enumerate(hosts):
            a = hh["model_args"]
            r = a.get("role", "client")
            role[i] = ROLE_SERVER if r == "server" else ROLE_CLIENT
            if role[i] == ROLE_CLIENT:
                p = a.get("peer")
                if p is None:
                    raise ValueError(f"echo client {hh['name']} needs model_args.peer")
                if isinstance(p, str):
                    if p not in by_name:
                        raise ValueError(
                            f"echo client {hh['name']}: unknown peer {p!r} "
                            f"(hosts: {sorted(by_name)[:10]}...)"
                        )
                    peer[i] = by_name[p]
                else:
                    peer[i] = int(p)
            interval[i] = parse_time_ns(a.get("interval", "1 s"), TimeUnit.SEC)
            size[i] = int(a.get("size_bytes", 512))
        params = {
            "role": jnp.asarray(role),
            "peer": jnp.asarray(peer),
            "interval": jnp.asarray(interval),
            "size": jnp.asarray(size),
        }
        state = {
            "sent": jnp.zeros((h,), jnp.int64),
            "rcvd": jnp.zeros((h,), jnp.int64),  # responses (client) / requests (server)
            "rtt_sum": jnp.zeros((h,), jnp.int64),
            "rtt_max": jnp.zeros((h,), jnp.int64),
        }
        events = [
            (hh["host_id"], hh["start_time"], KIND_TICK, ())
            for i, hh in enumerate(hosts)
            if role[i] == ROLE_CLIENT
        ]
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        is_client = ctx.params["role"] == ROLE_CLIENT
        is_server = ctx.params["role"] == ROLE_SERVER
        zeros_payload = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32)

        # --- client tick: send a stamped request, schedule next tick
        tick = ctx.active & (ctx.kind == KIND_TICK) & is_client
        lo, hi = _pack_t(ctx.t)
        req_payload = zeros_payload.at[:, 1].set(lo).at[:, 2].set(hi)
        send_req = PacketSend(
            mask=tick,
            dst=ctx.params["peer"],
            size_bytes=ctx.params["size"],
            kind=jnp.full((h,), KIND_REQ, jnp.int32),
            payload=req_payload,
        )
        push_tick = LocalPush(
            mask=tick,
            t=ctx.t + ctx.params["interval"],
            kind=jnp.full((h,), KIND_TICK, jnp.int32),
            payload=zeros_payload,
        )

        # --- server: echo the request payload (timestamp rides back)
        req = ctx.active & (ctx.kind == KIND_REQ) & is_server
        send_resp = PacketSend(
            mask=req,
            dst=ctx.src,
            size_bytes=ctx.payload[:, 0],  # echo the request size
            kind=jnp.full((h,), KIND_RESP, jnp.int32),
            payload=ctx.payload,
        )

        # --- client response: record RTT
        resp = ctx.active & (ctx.kind == KIND_RESP) & is_client
        t_sent = _unpack_t(ctx.payload[:, 1], ctx.payload[:, 2])
        rtt = jnp.where(resp, ctx.t - t_sent, 0)

        state = {
            "sent": ctx.state["sent"] + tick,
            "rcvd": ctx.state["rcvd"] + (resp | req),
            "rtt_sum": ctx.state["rtt_sum"] + rtt,
            "rtt_max": jnp.maximum(ctx.state["rtt_max"], rtt),
        }
        return HandlerOut(
            state=state,
            rng=ctx.rng,
            pushes=(push_tick,),
            sends=(send_req, send_resp),
        )

    def report(self, state, hosts):
        sent = np.asarray(state["sent"])
        rcvd = np.asarray(state["rcvd"])
        rtt_sum = np.asarray(state["rtt_sum"])
        roles = np.array(
            [1 if hh["model_args"].get("role") == "server" else 0 for hh in hosts]
        )
        client = roles == 0
        n_resp = rcvd[client].sum()
        return {
            "requests_sent": int(sent[client].sum()),
            "responses_received": int(n_resp),
            "requests_served": int(rcvd[~client].sum()),
            "mean_rtt_ms": float(rtt_sum[client].sum() / max(n_resp, 1) / 1e6),
            "max_rtt_ms": float(np.asarray(state["rtt_max"]).max() / 1e6),
        }
