"""Mixed plane: device-modeled hosts and CPU-emulated hosts in ONE simulation.

The reference runs every host as a managed process; this framework's scale
comes from modeling most hosts on device. Mixed simulations combine both:
e.g. thousands of modeled servers under load from a handful of REAL
binaries — the traffic all flows through one device network (same token
buckets, loss, latency, exchange), so the real processes experience the
modeled fleet's congestion and vice versa.

Mechanics: every host owns one device lane. Native lanes run the hybrid
proxy (capture ring + send requests, models/hybrid.py); modeled lanes run
the inner model. A replicated `global_is_native` table (gathered by global
host id, like the engine's node_of) routes each event to the right handler
and translates packet kinds at the plane boundary:

  native -> model : delivered as `inner.wire_kind` (the kind the model
                    treats as its network packet; models declare it)
  model -> native : delivered as the hybrid KIND_DATA so the capture ring
                    picks it up

Cross-plane BYTES: device payloads carry no bytes. When a model lane
*echoes* a request payload back (udp_echo does), the bridge reconstructs
the reply from the requester's own byte store (endpoint-swapped) — exact
echo semantics including ports. Non-echo model->native deliveries have no
bytes to reconstruct and are synthesized as zero-filled datagrams
(cosim._drain_captures), mirroring the modeled-pcap convention.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.models.base import HandlerCtx, HandlerOut, KIND_MASK
from shadow_tpu.models.hybrid import KIND_DATA, HybridModel


class MixedModel:
    name = "mixed"

    def __init__(self, inner, inner_name: str):
        self.hybrid = HybridModel()
        self.inner = inner
        self.inner_name = inner_name
        self.wire_kind = getattr(inner, "wire_kind", None)
        self.capture_cap = self.hybrid.capture_cap

    def build(self, hosts, seed):
        """`hosts`: per-lane dicts with "plane" in {"native", "model"};
        modeled lanes carry real model_args, native lanes get a benign
        stand-in (they are fully masked in the inner handler)."""
        is_native = np.array(
            [h.get("plane") == "native" for h in hosts], bool
        )
        model_hosts = [h for h in hosts if not is_native[h["host_id"]]]
        if model_hosts:
            proto_args = model_hosts[0].get("model_args", {})
        else:
            proto_args = {}
        inner_hosts = [
            dict(h) if not is_native[h["host_id"]]
            else {**h, "model_args": dict(proto_args)}
            for h in hosts
        ]
        hyb_params, hyb_state, _ = self.hybrid.build(hosts, seed)
        in_params, in_state, in_events = self.inner.build(inner_hosts, seed)
        self._inner_hosts = inner_hosts  # for report(): per-lane args/roles
        # keep only REAL modeled lanes' initial events: native lanes boot
        # their processes on the CPU plane; mesh-padding lanes stay inert
        live_model = np.array(
            [not h.get("pad") and h.get("plane") != "native" for h in hosts],
            bool,
        )
        events = [e for e in in_events if live_model[e[0]]]
        params = {
            **hyb_params,
            "inner": in_params,
            "global_is_native": is_native,
        }
        state = {**hyb_state, "inner": in_state}
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        p = ctx.params
        # replicated table gathered by GLOBAL host id: this lane's plane
        native_lane = p["global_is_native"][ctx.host_id]

        hyb_ctx = HandlerCtx(
            t=ctx.t, window_end=ctx.window_end, kind=ctx.kind,
            payload=ctx.payload, active=ctx.active & native_lane,
            is_packet=ctx.is_packet, src=ctx.src, host_id=ctx.host_id,
            state={k: v for k, v in ctx.state.items() if k != "inner"},
            params={k: v for k, v in p.items()
                    if k not in ("inner", "global_is_native")},
            rng=ctx.rng,
        )
        hyb_out = self.hybrid.handle(hyb_ctx)

        # packets crossing INTO the model plane arrive with hybrid kinds;
        # deliver them as the inner model's wire kind so its handler fires
        in_kind = ctx.kind
        in_payload = ctx.payload
        if self.wire_kind is not None:
            from_native = ctx.is_packet & p["global_is_native"][
                jnp.clip(ctx.src, 0, p["global_is_native"].shape[0] - 1)
            ]
            in_kind = jnp.where(
                from_native, jnp.int32(self.wire_kind), in_kind
            )
            if getattr(self.inner, "sanitize_wire_payload", True):
                # native-origin payload words are bridge bookkeeping (dst,
                # byte-store key, magic), not the inner protocol's fields —
                # e.g. gossip would adopt the monotonically increasing key
                # as a fresh generation. Keep only word 0 (packet size, the
                # one cross-plane-meaningful word) so foreign packets count
                # as network load without forging protocol state. Models
                # whose protocol IS echo-the-payload opt out (udp_echo: the
                # echoed words carry the byte-store key back to the bridge).
                keep = jnp.zeros_like(ctx.payload).at[:, 0].set(
                    ctx.payload[:, 0]
                )
                in_payload = jnp.where(
                    from_native[:, None], keep, ctx.payload
                )
        in_ctx = HandlerCtx(
            t=ctx.t, window_end=ctx.window_end, kind=in_kind,
            payload=in_payload, active=ctx.active & ~native_lane,
            is_packet=ctx.is_packet, src=ctx.src, host_id=ctx.host_id,
            state=ctx.state["inner"], params=p["inner"], rng=hyb_out.rng,
        )
        in_out = self.inner.handle(in_ctx)

        # packets crossing OUT of the model plane become hybrid data so the
        # destination's capture ring picks them up
        def translate(send):
            dst_safe = jnp.clip(
                send.dst, 0, p["global_is_native"].shape[0] - 1
            )
            to_native = send.mask & p["global_is_native"][dst_safe]
            return send._replace(
                kind=jnp.where(
                    to_native, jnp.int32(KIND_DATA), send.kind & KIND_MASK
                )
            )

        state = {
            **hyb_out.state,
            "inner": in_out.state,
        }
        return HandlerOut(
            state=state,
            rng=in_out.rng,
            pushes=tuple(hyb_out.pushes) + tuple(in_out.pushes),
            sends=tuple(hyb_out.sends)
            + tuple(translate(s) for s in in_out.sends),
        )

    def report(self, state, hosts):
        # state arrives mesh-PADDED; slice every leaf back to the real
        # lanes so inner reports line up with their host list
        n = len(self._inner_hosts)
        state = {
            k: (jnp.asarray(v)[:n] if not isinstance(v, dict)
                else {kk: jnp.asarray(vv)[:n] for kk, vv in v.items()})
            for k, v in state.items()
        }
        rep = dict(self.hybrid.report(
            {k: v for k, v in state.items() if k != "inner"}, hosts
        ))
        rep[f"model_{self.inner_name}"] = self.inner.report(
            state["inner"], hosts if hosts is not None else self._inner_hosts
        )
        return rep
