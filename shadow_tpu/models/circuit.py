"""Tor-like relay circuits: the mixed-event workload of BASELINE config 4.

Reference analogue: the minimal Tor network integration test
(src/test/tor/minimal/tor-minimal.yaml — clients pushing cells through
3-hop relay circuits). Device recast: every client owns a fixed 3-relay
circuit (guard, middle, exit) drawn deterministically at build time; a
cell travels client -> guard -> middle -> exit, turns around, and returns
exit -> middle -> guard -> client. Each relay charges a processing delay
(a LocalPush continuation) before forwarding — so the load is an even mix
of packet events, local continuations, and timer ticks, unlike PHOLD's
pure packet churn.

The full route rides in the packet payload as 16-bit host ids (the event
payload is 4 words and params are shard-local, so a relay cannot gather
the client's route from its own tables) — circuit sims are therefore
bounded to 65,535 hosts, enforced at build. Clients keep at most one cell
outstanding (send-on-tick when idle), giving an exact per-cell RTT without
carrying timestamps in the payload.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.units import TimeUnit, parse_time_ns
from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    register_model,
)
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

KIND_TICK = 0  # client timer
KIND_CELL = 1  # cell packet arriving at a relay or back at the client
KIND_FWD = 2  # relay continuation: processing delay elapsed, forward now

ROLE_RELAY = 0
ROLE_CLIENT = 1

# payload words (word 0 is the engine-owned size)
PW_GM = 1  # guard | middle << 16
PW_EC = 2  # exit | client << 16
PW_HD = 3  # hop | dir << 8


@register_model
class CircuitModel:
    name = "circuit"
    wire_kind = KIND_CELL  # cross-plane packets arrive as cells (mixed sims)

    def build(self, hosts, seed):
        h = len(hosts)
        if h > 0xFFFF:
            raise ValueError(
                f"circuit model routes via 16-bit host ids: {h} hosts > 65535"
            )
        role = np.zeros((h,), np.int32)
        interval = np.zeros((h,), np.int64)
        proc = np.zeros((h,), np.int64)
        size = np.zeros((h,), np.int32)
        for i, hh in enumerate(hosts):
            a = hh["model_args"]
            role[i] = ROLE_CLIENT if a.get("role", "relay") == "client" else ROLE_RELAY
            interval[i] = parse_time_ns(a.get("interval", "200 ms"), TimeUnit.MS)
            proc[i] = parse_time_ns(a.get("relay_delay", "2 ms"), TimeUnit.MS)
            size[i] = int(a.get("cell_bytes", 512))
        relays = np.nonzero(role == ROLE_RELAY)[0]
        clients = np.nonzero(role == ROLE_CLIENT)[0]
        if len(relays) < 3 and len(clients):
            raise ValueError("circuit model needs >= 3 relay hosts")
        rng = np.random.default_rng(seed)
        route = np.zeros((h, 3), np.int32)
        for c in clients:
            route[c] = relays[rng.choice(len(relays), size=3, replace=False)]
        params = {
            "role": jnp.asarray(role),
            "route": jnp.asarray(route),
            "interval": jnp.asarray(interval),
            "proc": jnp.asarray(proc),
            "size": jnp.asarray(size),
        }
        state = {
            "outstanding": jnp.zeros((h,), bool),
            "launch_t": jnp.zeros((h,), jnp.int64),
            "cells_done": jnp.zeros((h,), jnp.int64),
            "rtt_sum": jnp.zeros((h,), jnp.int64),
            "fwd": jnp.zeros((h,), jnp.int64),
        }
        events = [
            (hh["host_id"], hh["start_time"], KIND_TICK, ())
            for i, hh in enumerate(hosts)
            if role[i] == ROLE_CLIENT
        ]
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        h = ctx.kind.shape[0]
        st = ctx.state
        p = ctx.params
        is_client = p["role"] == ROLE_CLIENT
        tick = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_TICK)
        cell_in = ctx.active & ctx.is_packet & (ctx.kind == KIND_CELL)
        fwd = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_FWD)

        cell_back = cell_in & is_client  # full round trip completed
        cell_at_relay = cell_in & ~is_client

        # ---- client tick: launch a cell when idle; always re-arm the tick
        launch = tick & ~st["outstanding"]
        guard = p["route"][:, 0].astype(jnp.int64)
        gm = p["route"][:, 0].astype(jnp.int32) | (
            p["route"][:, 1].astype(jnp.int32) << 16
        )
        ec = p["route"][:, 2].astype(jnp.int32) | (
            ctx.host_id.astype(jnp.int32) << 16
        )
        launch_payload = jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32)
        launch_payload = launch_payload.at[:, PW_GM].set(gm)
        launch_payload = launch_payload.at[:, PW_EC].set(ec)
        launch_payload = launch_payload.at[:, PW_HD].set(0)  # hop 0, dir 0
        send_launch = PacketSend(
            mask=launch,
            dst=guard,
            size_bytes=p["size"],
            kind=jnp.full((h,), KIND_CELL, jnp.int32),
            payload=launch_payload,
        )
        tick_push = LocalPush(
            mask=tick,
            t=ctx.t + p["interval"],
            kind=jnp.full((h,), KIND_TICK, jnp.int32),
            payload=jnp.zeros((h, EVENT_PAYLOAD_WORDS), jnp.int32),
        )

        # ---- relay: charge the processing delay, then forward (KIND_FWD)
        proc_push = LocalPush(
            mask=cell_at_relay,
            t=ctx.t + p["proc"],
            kind=jnp.full((h,), KIND_FWD, jnp.int32),
            payload=ctx.payload,
        )

        # ---- forward continuation: next hop from the packed route
        pl = ctx.payload
        g = (pl[:, PW_GM] & 0xFFFF).astype(jnp.int64)
        m = ((pl[:, PW_GM] >> 16) & 0xFFFF).astype(jnp.int64)
        e = (pl[:, PW_EC] & 0xFFFF).astype(jnp.int64)
        c = ((pl[:, PW_EC] >> 16) & 0xFFFF).astype(jnp.int64)
        hop = pl[:, PW_HD] & 0xFF
        dn = (pl[:, PW_HD] >> 8) & 1
        at_exit = (dn == 0) & (hop == 2)
        nxt_dst = jnp.where(
            dn == 0,
            jnp.where(hop == 0, m, jnp.where(hop == 1, e, m)),
            jnp.where(hop == 1, g, c),
        )
        nxt_hop = jnp.where(
            dn == 0,
            jnp.where(hop == 0, 1, jnp.where(hop == 1, 2, 1)),
            jnp.where(hop == 1, 0, 0),
        )
        nxt_dir = jnp.where(at_exit, 1, dn)
        fwd_payload = pl.at[:, PW_HD].set(
            (nxt_hop | (nxt_dir << 8)).astype(jnp.int32)
        )
        send_fwd = PacketSend(
            mask=fwd,
            dst=nxt_dst,
            size_bytes=p["size"],
            kind=jnp.full((h,), KIND_CELL, jnp.int32),
            payload=fwd_payload,
        )

        rtt = ctx.t - st["launch_t"]
        state = {
            "outstanding": jnp.where(
                launch, True, jnp.where(cell_back, False, st["outstanding"])
            ),
            "launch_t": jnp.where(launch, ctx.t, st["launch_t"]),
            "cells_done": st["cells_done"] + cell_back,
            "rtt_sum": st["rtt_sum"] + jnp.where(cell_back, rtt, 0),
            "fwd": st["fwd"] + fwd,
        }
        return HandlerOut(
            state=state,
            rng=ctx.rng,
            pushes=(tick_push, proc_push),
            sends=(send_launch, send_fwd),
        )

    def report(self, state, hosts):
        done = np.asarray(state["cells_done"])
        rtt = np.asarray(state["rtt_sum"])
        n = int(done.sum())
        return {
            "cells_completed": n,
            "mean_rtt_ms": (float(rtt.sum()) / n / 1e6) if n else None,
            "relay_forwards": int(np.asarray(state["fwd"]).sum()),
        }
