"""Periodic-timer workload: the pure sort/barrier stress model.

BASELINE.json config #5 ("1M-host synthetic timer-only workload"). Each host
fires a timer every `interval`, counts the fire, and reschedules — no packets,
so rounds exercise only the pop/push/min-reduction kernels. The device
analogue of a managed process sitting in a nanosleep loop.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.config.units import TimeUnit, parse_time_ns
from shadow_tpu.models.base import HandlerCtx, HandlerOut, LocalPush, register_model
from shadow_tpu.ops.events import EVENT_PAYLOAD_WORDS

KIND_FIRE = 0


@register_model
class TimerModel:
    name = "timer"
    # observatory event classes: every event this model handles is a timer
    timer_kinds = (KIND_FIRE,)

    def build(self, hosts, seed):
        h = len(hosts)
        interval = np.array(
            [
                parse_time_ns(hh["model_args"].get("interval", "10 ms"), TimeUnit.MS)
                for hh in hosts
            ],
            np.int64,
        )
        params = {"interval": jnp.asarray(interval)}
        state = {"fires": jnp.zeros((h,), jnp.int64)}
        events = [(hh["host_id"], hh["start_time"], KIND_FIRE, ()) for hh in hosts]
        return params, state, events

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        fire = ctx.active & (ctx.kind == KIND_FIRE)
        state = {"fires": ctx.state["fires"] + fire}
        push = LocalPush(
            mask=fire,
            t=ctx.t + ctx.params["interval"],
            kind=jnp.full_like(ctx.kind, KIND_FIRE),
            payload=jnp.zeros((ctx.kind.shape[0], EVENT_PAYLOAD_WORDS), jnp.int32),
        )
        return HandlerOut(state=state, rng=ctx.rng, pushes=(push,))

    def report(self, state, hosts):
        fires = np.asarray(state["fires"])
        return {
            "total_fires": int(fires.sum()),
            "min_fires": int(fires.min()),
            "max_fires": int(fires.max()),
        }
