"""The hybrid host model: device-side proxy for CPU-emulated hosts.

This is the device half of the co-simulation bridge (`shadow_tpu.cosim`).
Each emulated host (a `CpuHost` running coroutine/real processes) owns one
device lane. Two event kinds flow through it:

  - KIND_SENDREQ (local event, injected by the bridge): "this host's CPU
    plane emitted a packet at time t". The handler converts it into a
    `PacketSend`, so CPU-originated traffic goes through the FULL device
    egress pipeline — send budget, token bucket, loss draw from the device
    RNG, latency lookup, conservative arrival clamp, mesh exchange — exactly
    like modeled-host traffic (worker.rs:330-425).
  - KIND_DATA (packet event): a delivery for this host. The handler appends
    (arrival time, src, payload key) to a per-host capture ring that the
    bridge drains after every window and maps back to real packet bytes.

Packet *bytes* never touch the device: the bridge keys each send with
(src host, per-src counter) carried in payload words, and holds the bytes
host-side — the TPU-native recast of the reference's payload-by-reference
packets (src/main/routing/packet.c + payload.c refcounted chunks).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    PacketSend,
    register_model,
)

KIND_SENDREQ = 1  # bridge-injected send request (local event at the source)
KIND_DATA = 2  # packet delivery at the destination

# payload word layout (word 0 is engine-owned size_bytes)
PW_SIZE = 0
PW_DST_OR_SRC = 1  # sendreq: dst host id; after send: unused (engine keeps it)
PW_KEY = 2  # per-src payload key (bridge-side bytes lookup)
PW_FLAGS = 3  # reserved


@register_model
class HybridModel:
    """One device lane per emulated host (see module docstring)."""

    name = "hybrid"

    def __init__(self, capture_cap: int = 128):
        self.capture_cap = capture_cap

    # ---- build -------------------------------------------------------------

    def build(self, hosts, seed):
        h = len(hosts)
        c = self.capture_cap
        state = {
            "cap_t": np.full((h, c), 0, np.int64),
            "cap_src": np.zeros((h, c), np.int64),
            "cap_key": np.zeros((h, c), np.int32),
            "cap_size": np.zeros((h, c), np.int32),
            "cap_flags": np.zeros((h, c), np.int32),
            "cap_n": np.zeros((h,), np.int32),
            "cap_lost": np.zeros((h,), np.int64),  # ring overflow (observability)
        }
        params = {"_hosts": np.arange(h, dtype=np.int32)}  # placeholder shardable
        return params, state, []  # no initial device events: the CPU plane drives

    # ---- device handler ----------------------------------------------------

    def handle(self, ctx: HandlerCtx) -> HandlerOut:
        st = ctx.state
        is_send = ctx.active & ~ctx.is_packet & (ctx.kind == KIND_SENDREQ)
        is_data = ctx.active & ctx.is_packet & (ctx.kind == KIND_DATA)

        # capture deliveries into the ring
        n = st["cap_n"]
        cap = st["cap_t"].shape[1]
        slot_ok = is_data & (n < cap)
        slot = jnp.where(slot_ok, n, cap)  # cap = out-of-range -> dropped
        hh = jnp.arange(st["cap_t"].shape[0])
        new_state = {
            "cap_t": st["cap_t"].at[hh, slot].set(ctx.t, mode="drop"),
            "cap_src": st["cap_src"].at[hh, slot].set(ctx.src, mode="drop"),
            "cap_key": st["cap_key"]
            .at[hh, slot]
            .set(ctx.payload[:, PW_KEY], mode="drop"),
            "cap_size": st["cap_size"]
            .at[hh, slot]
            .set(ctx.payload[:, PW_SIZE], mode="drop"),
            "cap_flags": st["cap_flags"]
            .at[hh, slot]
            .set(ctx.payload[:, PW_FLAGS], mode="drop"),
            "cap_n": n + slot_ok.astype(jnp.int32),
            "cap_lost": st["cap_lost"] + (is_data & ~slot_ok),
        }

        send = PacketSend(
            mask=is_send,
            dst=ctx.payload[:, PW_DST_OR_SRC].astype(jnp.int64),
            size_bytes=ctx.payload[:, PW_SIZE],
            kind=jnp.full_like(ctx.kind, KIND_DATA),
            payload=ctx.payload,
        )
        return HandlerOut(state=new_state, rng=ctx.rng, sends=(send,))

    # ---- reporting ---------------------------------------------------------

    def report(self, state, hosts) -> dict:
        return {
            "capture_overflow_lost": int(np.asarray(state["cap_lost"]).sum()),
        }
