"""Host application models, vectorized over all simulated hosts.

The reference runs real Linux binaries per host (the managed-process plane,
SURVEY.md L0/L2). The TPU build additionally provides *device models*: app
behaviors expressed as vectorized event handlers that run entirely on device —
the "synthetic app model" of SURVEY.md §7 step 4 — so pure-simulation
workloads (PHOLD, tgen-style traffic, gossip, timers) never leave HBM.

Model registry: config `processes: [{model: <name>, model_args: {...}}]`
resolves here.
"""

from shadow_tpu.models.base import (
    HandlerCtx,
    HandlerOut,
    LocalPush,
    PacketSend,
    Model,
    register_model,
    get_model,
    MODEL_REGISTRY,
)
from shadow_tpu.models import timer as _timer  # noqa: F401  (registers)
from shadow_tpu.models import phold as _phold  # noqa: F401
from shadow_tpu.models import echo as _echo  # noqa: F401
from shadow_tpu.models import gossip as _gossip  # noqa: F401
from shadow_tpu.models import circuit as _circuit  # noqa: F401
from shadow_tpu.models import tgen as _tgen  # noqa: F401

__all__ = [
    "HandlerCtx",
    "HandlerOut",
    "LocalPush",
    "PacketSend",
    "Model",
    "register_model",
    "get_model",
    "MODEL_REGISTRY",
]
