"""Unit-string parsing ("10 Mbit", "50 ms", "1 GiB").

Reference: src/main/utility/units.rs — Shadow accepts SI and binary prefixes on
time, bit-rate, and byte quantities throughout the YAML config and CLI. This
module provides the same surface: a quantity is an integer or a string
"<number> <prefix><unit>" (space optional).
"""

from __future__ import annotations

import enum
import re

_NUM_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([A-Za-zμ]*)\s*$")

_SI = {"": 1, "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12}
_BIN = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40}

_TIME_SUFFIX_NS = {
    "ns": 1,
    "nsec": 1,
    "us": 1_000,
    "usec": 1_000,
    "μs": 1_000,
    "ms": 1_000_000,
    "msec": 1_000_000,
    "s": 1_000_000_000,
    "sec": 1_000_000_000,
    "second": 1_000_000_000,
    "seconds": 1_000_000_000,
    "m": 60 * 1_000_000_000,
    "min": 60 * 1_000_000_000,
    "minute": 60 * 1_000_000_000,
    "minutes": 60 * 1_000_000_000,
    "h": 3600 * 1_000_000_000,
    "hour": 3600 * 1_000_000_000,
    "hours": 3600 * 1_000_000_000,
}


class TimeUnit(enum.Enum):
    NS = 1
    US = 1_000
    MS = 1_000_000
    SEC = 1_000_000_000


def _split(value: str) -> tuple[float, str]:
    m = _NUM_RE.match(value)
    if not m:
        raise ValueError(f"cannot parse quantity: {value!r}")
    return float(m.group(1)), m.group(2)


def parse_time_ns(value: int | float | str, default_unit: TimeUnit = TimeUnit.SEC) -> int:
    """Parse a time quantity to int64 nanoseconds (rounded, not truncated).

    Bare numbers take `default_unit` (the reference defaults bare config times
    to seconds, e.g. `stop_time: 10`).
    """
    if isinstance(value, (int, float)):
        return int(value * default_unit.value)
    num, suffix = _split(value)
    if suffix == "":
        return round(num * default_unit.value)
    if suffix not in _TIME_SUFFIX_NS:
        raise ValueError(f"unknown time unit {suffix!r} in {value!r}")
    return round(num * _TIME_SUFFIX_NS[suffix])


def parse_bits_per_sec(value: int | float | str) -> int:
    """Parse a bandwidth quantity ("10 Mbit", "81920 Kibit") to bits/sec."""
    if isinstance(value, (int, float)):
        return int(value)
    num, suffix = _split(value)
    if suffix == "":
        return round(num)
    for unit in ("bit", "Bit"):
        if suffix.endswith(unit):
            prefix = suffix[: -len(unit)]
            if prefix in _SI:
                return round(num * _SI[prefix])
            if prefix in _BIN:
                return round(num * _BIN[prefix])
            # lowercase SI prefixes are accepted too ("mbit" in the wild)
            if prefix.upper() in _SI:
                return round(num * _SI[prefix.upper()])
            raise ValueError(f"unknown bit-rate prefix {prefix!r} in {value!r}")
    raise ValueError(f"unknown bit-rate unit in {value!r}")


def parse_bytes(value: int | float | str) -> int:
    """Parse a byte quantity ("1 GiB", "512 KB", "100 B") to bytes."""
    if isinstance(value, (int, float)):
        return int(value)
    num, suffix = _split(value)
    if suffix == "":
        return round(num)
    for unit in ("bytes", "byte", "B"):
        if suffix.endswith(unit):
            prefix = suffix[: -len(unit)]
            if prefix in _SI:
                return round(num * _SI[prefix])
            if prefix in _BIN:
                return round(num * _BIN[prefix])
            if prefix.upper() in _SI:
                return round(num * _SI[prefix.upper()])
            break
    raise ValueError(f"unknown byte unit in {value!r}")
