"""Config / flag system (reference: src/main/core/configuration.rs).

One schema serves both the YAML file and the CLI: every option dataclass field
is a YAML key and a `--kebab-case` flag, with CLI overriding file (reference
configuration.rs:19-24). Units strings ("10 Mbit", "50 ms") are accepted
everywhere a quantity is expected (reference utility/units.rs).
"""

from shadow_tpu.config.units import (
    parse_time_ns,
    parse_bits_per_sec,
    parse_bytes,
    TimeUnit,
)
from shadow_tpu.config.options import (
    ConfigOptions,
    GeneralOptions,
    NetworkOptions,
    ExperimentalOptions,
    HostOptions,
    HostDefaultOptions,
    ProcessOptions,
    GraphOptions,
    load_config,
    merge_cli_overrides,
)

__all__ = [
    "parse_time_ns",
    "parse_bits_per_sec",
    "parse_bytes",
    "TimeUnit",
    "ConfigOptions",
    "GeneralOptions",
    "NetworkOptions",
    "ExperimentalOptions",
    "HostOptions",
    "HostDefaultOptions",
    "ProcessOptions",
    "GraphOptions",
    "load_config",
    "merge_cli_overrides",
]
