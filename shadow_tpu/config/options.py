"""Config schema: YAML file + CLI flags over one set of dataclasses.

Reference: src/main/core/configuration.rs — `GeneralOptions` (:197),
`NetworkOptions` (:282), `ExperimentalOptions` (:314), `HostDefaultOptions`
(:550), `ProcessOptions` (:643), `HostOptions` (:674). The reference derives
both serde (YAML) and clap (CLI) from the same structs; here `from_dict`
consumes YAML and `merge_cli_overrides` applies `--dotted.key=value` overrides
on top, CLI winning (configuration.rs:19-24).

Differences from the reference, by design:
  - `HostOptions.processes` may carry either a managed-process spec
    (path/args/environment — the CPU co-optation plane) or a *device model*
    spec (`model:`/`model_args:`) executed as vectorized handlers on TPU.
  - `ExperimentalOptions` carries the TPU engine's static-shape knobs (event
    queue capacity, outbox capacity, rounds per jit chunk) alongside the
    reference's CPU-scheduler knobs, which here govern only the co-sim CPU
    host plane (`host_workers`, `host_scheduler`, `use_cpu_pinning`;
    `use_worker_spinning` has no analogue — workers park on condvars).
"""

from __future__ import annotations

import copy
import dataclasses
from dataclasses import dataclass, field
from typing import Any

import yaml

from shadow_tpu.config.units import parse_bits_per_sec, parse_time_ns, TimeUnit


class ConfigError(ValueError):
    pass


@dataclass
class GraphOptions:
    """reference: GraphOptions/GraphSource (configuration.rs, graph/mod.rs:495-530)."""

    # "gml" | "1_gbit_switch" (reference's built-in one-node graph)
    type: str = "1_gbit_switch"
    path: str | None = None  # GML file path
    inline: str | None = None  # GML text inline
    # direct edge weights vs shortest-path routing (graph/mod.rs:183-253)
    use_shortest_path: bool = True

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "GraphOptions":
        d = dict(d or {})
        g = GraphOptions(
            type=d.pop("type", "1_gbit_switch"),
            path=(d.pop("file", {}) or {}).get("path") if "file" in d else d.pop("path", None),
            inline=d.pop("inline", None),
            use_shortest_path=d.pop("use_shortest_path", True),
        )
        if d:
            raise ConfigError(f"unknown graph options: {sorted(d)}")
        return g


@dataclass
class GeneralOptions:
    """reference: GeneralOptions (configuration.rs:197)."""

    stop_time: int = 0  # ns (required)
    bootstrap_end_time: int = 0  # ns; loss disabled before this time
    seed: int = 1
    parallelism: int = 0  # 0 = all devices (reference: 0 = all cores)
    data_directory: str = "shadow.data"
    template_directory: str | None = None
    log_level: str = "info"
    # sim-time-stamped structured log (reference shadow_logger.rs): None =
    # off; a relative path lands inside data_directory
    log_file: str | None = None
    heartbeat_interval: int | None = parse_time_ns("1 s")
    progress: bool = False
    model_unblocked_syscall_latency: bool = False

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "GeneralOptions":
        d = dict(d)
        if "stop_time" not in d:
            raise ConfigError("general.stop_time is required")
        heartbeat = d.pop("heartbeat_interval", "1 s")
        g = GeneralOptions(
            stop_time=parse_time_ns(d.pop("stop_time"), TimeUnit.SEC),
            bootstrap_end_time=parse_time_ns(d.pop("bootstrap_end_time", 0), TimeUnit.SEC),
            seed=int(d.pop("seed", 1)),
            parallelism=int(d.pop("parallelism", 0)),
            data_directory=d.pop("data_directory", "shadow.data"),
            template_directory=d.pop("template_directory", None),
            log_level=d.pop("log_level", "info"),
            log_file=d.pop("log_file", None),
            heartbeat_interval=(
                parse_time_ns(heartbeat, TimeUnit.SEC) if heartbeat is not None else None
            ),
            progress=bool(d.pop("progress", False)),
            model_unblocked_syscall_latency=bool(
                d.pop("model_unblocked_syscall_latency", False)
            ),
        )
        if d:
            raise ConfigError(f"unknown general options: {sorted(d)}")
        return g


@dataclass
class NetworkOptions:
    """reference: NetworkOptions (configuration.rs:282)."""

    graph: GraphOptions = field(default_factory=GraphOptions)

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "NetworkOptions":
        d = dict(d or {})
        n = NetworkOptions(graph=GraphOptions.from_dict(d.pop("graph", None)))
        if d:
            raise ConfigError(f"unknown network options: {sorted(d)}")
        return n


@dataclass
class ExperimentalOptions:
    """reference: ExperimentalOptions (configuration.rs:314), TPU-adapted.

    Kept from the reference: `scheduler`, `runahead`, `use_dynamic_runahead`,
    `interface_qdisc`. New (static-shape knobs the TPU engine needs):
    `event_queue_capacity`, `event_queue_block`, `sends_per_host_round`,
    `max_round_inserts`, `rounds_per_chunk`, `microstep_limit`.
    """

    scheduler: str = "tpu"  # "tpu" | "cpu-reference" (pure-numpy oracle)
    runahead: int = parse_time_ns("1 ms")  # floor (reference default 1ms, runahead.rs)
    use_dynamic_runahead: bool = False
    # "fifo" | "round-robin" (QDiscMode, configuration.rs:960): the order a
    # managed host's same-window sends enter the network — emit order vs
    # one-per-socket interleave (acts in the co-sim staging; device models
    # have no socket structure to interleave)
    interface_qdisc: str = "fifo"
    use_codel: bool = True
    # strace-style per-process syscall logs: "off" | "standard" |
    # "deterministic" (StraceLoggingMode, configuration.rs:1162;
    # deterministic omits anything that could differ across machines)
    strace_logging_mode: str = "off"
    # queue-overflow shed policy at the exchange merge: "urgency" keeps the
    # most urgent events (tested contract); "append" is cheaper on TPU and
    # identical whenever queues are sized to never overflow
    overflow_shed: str = "urgency"
    # multi-device cross-shard exchange: "gather" replicates the outbox to
    # every shard; "alltoall" moves destination-sharded blocks so per-shard
    # ICI bytes and merge input are O(global sends / world) — identical
    # results while stats.a2a_shed stays 0 (see EngineConfig.exchange).
    # "auto" (the default) resolves to alltoall whenever world > 1 and
    # gather on a single device: the O(world)-replicated gather is never
    # the right default on a real mesh (it burns ICI linearly in the shard
    # count), and the 8-device dryrun gates that the flipped default stays
    # digest-identical to gather with zero sheds. Set "gather" explicitly
    # to keep the replicated exchange. "hierarchical" (explicit opt-in,
    # never auto-resolved) runs the exchange in two tiers: an intra-shard
    # (dst-shard, t, order) compaction first densifies each shard's sends
    # into per-destination prefixes, then the inter-shard alltoall moves
    # only the compacted prefixes plus an i32 fill-counter word — digests,
    # events, and every drop counter bit-identical to alltoall by
    # construction, with the two tiers charged separately in stats
    # (ici_intra / ici_inter; ici_bytes carries only the wire tier). See
    # docs/architecture.md "Hierarchical exchange".
    exchange: str = "auto"
    a2a_block: int = 0  # entries per (src, dst-shard) block; 0 = auto
    # static cap on post-sort merge gather rows (0 = unbounded): bounds the
    # exchange-merge's per-round gather work at the real traffic level
    # instead of the worst-case outbox (hosts x send budget). The exactness
    # bound is PER SHARD — the merge runs shard-locally, so with world > 1
    # it is: locally-destined rows + local host count (num_hosts / world)
    # + 1 <= merge_rows, NOT the global packet/host counts (sizing from
    # global counts over-provisions the permute on every shard; sizing from
    # a naive global/world split can under-provision a shard that receives
    # a traffic burst). Overflow sheds loudly into queue_overflow_dropped.
    # See EngineConfig.merge_rows and docs/usage.md.
    merge_rows: int = 0
    # Occupancy-adaptive merge gears (core/gears.py + docs/architecture.md
    # "Adaptive exchange"): compile the round body at a ladder of outbox
    # column widths and let the driver pick next chunk's gear from the
    # outbox-send high-water, so the exchange sort tracks ACTUAL per-round
    # traffic instead of the static worst case. 0/off = disabled (full
    # width always, today's exact program); "auto" = a ~{B/8, B/4, B/2, B}
    # ladder from the send budget; a list of ints = explicit widths (the
    # full budget is appended automatically). Exact on every workload: a
    # gear that would shed aborts the chunk and replays one gear up from a
    # pre-chunk snapshot — digests, event counts, and drop counters are
    # bit-identical to full width (tests/test_gears.py is the gate).
    merge_gears: Any = 0
    # packet delivery breadcrumbs on the CPU host planes (reference
    # packet.rs:16-39), debug-only: drops land in host-stats.json with
    # their full hop trail
    packet_breadcrumbs: bool = False
    # CPU model: simulated computation time charged per handled event
    # (reference host/cpu.rs; 0 = off). Applies to device-modeled hosts;
    # the pure-CPU oracle scheduler does not model it.
    cpu_delay: int = 0  # stored ns; bare numbers in YAML/CLI parse as ms
    # --- TPU engine static shapes (0 = auto-size from host count) ---
    event_queue_capacity: int = 0  # per-host pending-event slots
    # two-level bucketed event queue: slots per block (must divide the
    # queue capacity). The per-host slab carries incrementally-maintained
    # per-block min caches so the microstep's pop/push reductions scale
    # O(C/B + B) instead of O(C); results (events, digests, drop counters)
    # are bit-identical to the flat queue. 0 = flat (the B=C degenerate
    # case). Sweep tools/bench_bucketq.py to pick B; B ~ sqrt(C) balances
    # the two levels. See docs/architecture.md "Two-level event queue".
    event_queue_block: int = 0
    sends_per_host_round: int = 0  # per-host round send budget (drop above)
    max_round_inserts: int = 0  # max packets merged into one host per round; 0 = auto
    rounds_per_chunk: int = 0  # rounds per jit'd chunk between host syncs
    microstep_limit: int = 0  # safety bound on events/host/round; 0 = capacity
    # K-way microstep pop: fold up to K events per host per queue dispatch.
    # The microstep loop pops each host's K earliest in-window events in
    # one slab pass and folds them through the model handler, so
    # event-dense hosts (tgen-TCP) stop serializing one queue round-trip
    # per event. Execution order, digests, event counts, and drop counters
    # are bit-identical to K=1 by construction (an exactness guard defers
    # the rest of a batch whenever a push lands at an earlier key —
    # tests/test_popk.py is the gate). 1 = the exact single-event
    # microstep (default). Sweep tools/bench_popk.py to pick K; see
    # docs/architecture.md "K-way microsteps".
    microstep_events: int = 1
    # Device-resident per-host timer wheel (ops/wheel.py): calendar slots
    # for the model's declared timer_kinds (tgen RTO/DELACK, echo tick,
    # phold job). Timers route to the [H, S] wheel instead of occupying
    # event-queue slots; the microstep pops the (time, order) minimum of
    # queue ∪ wheel, so dispatch order / digests / events / drops are
    # bit-identical to the wheel-off path (tests/test_wheel.py gates it).
    # A full wheel spills to the queue (stats wheel{} block counts it —
    # a sizing signal, never a loss). 0 = off. Sweep tools/bench_wheel.py
    # to pick S; see docs/architecture.md "Timer wheel and calendar
    # merge". Requires microstep_events = 1 this round.
    timer_wheel: int = 0
    # wheel block-cache block size; 0 = auto (divisor of timer_wheel
    # near sqrt — the bucketed-queue balance rule)
    timer_wheel_block: int = 0
    # Sort-free calendar-queue exchange merge (ops/merge.py
    # merge_scatter_free): non-shedding rounds bucket incoming rows by
    # destination via scatter-add instead of the full (dst, t, order)
    # sort; overflow rounds fall back to the sort in-jit, so results are
    # bit-identical on every workload. Measured CPU win (the CPU merge
    # is sort-dominated); off by default.
    merge_scatter: bool = False

    def resolve_shapes(self, num_hosts: int) -> tuple[int, int, int]:
        """(queue_capacity, send_budget, rounds_per_chunk) with 0-valued
        knobs sized from the host count (r4, VERDICT r3 weak #7):

        - HBM: per-host slab bytes scale with capacity x hosts; at 1M
          lanes the round-3 defaults (64/8/64) blow the 15.75 GiB chip,
          while 4/1/8 fits with headroom (measured, BASELINE.md cfg 5).
        - XLA while-loop pathology: per-CALL cost of the jitted round
          loop grows superlinearly with rounds_per_chunk at >=1M lanes
          (0.36 s at rpc=8 vs 13.5 s at rpc=64 for the SAME 30 rounds),
          flat per-round up to ~512k — so big sims take short chunks.

        Explicit non-zero settings always win; shedding stays loud
        (queue_overflow_dropped / pkts_budget_dropped in stats).

        Above 524k hosts the engine additionally clamps the EFFECTIVE
        rounds-per-chunk to the microstep valve
        (EngineConfig.effective_rounds_per_chunk) so a config that pins
        rpc high for mid-size runs cannot re-trip the superlinear
        while-loop cost at the 1M-lane class; the clamp never fires at
        or below 524k hosts, so explicit settings still win there."""
        if num_hosts <= 1 << 17:  # <=131k: roomy shapes, long chunks
            auto = (64, 8, 64)
        elif num_hosts <= 1 << 19:  # <=524k: flat per-round regime edge
            auto = (16, 4, 32)
        else:  # 1M-lane class
            auto = (4, 1, 8)
        return (
            self.event_queue_capacity or auto[0],
            self.sends_per_host_round or auto[1],
            self.rounds_per_chunk or auto[2],
        )

    def resolve_exchange(self, world: int) -> str:
        """The engine-level exchange strategy for a given mesh size:
        "auto" flips to the destination-sharded alltoall whenever the sim
        actually runs multi-device (VERDICT r5 weak #4 — the replicated
        gather burns O(world) ICI and must not be the silent default on a
        real mesh); explicit settings always win."""
        if self.exchange != "auto":
            return self.exchange
        return "alltoall" if world > 1 else "gather"
    # CPU host plane worker threads for the co-sim window loop (reference
    # thread-per-core scheduler, thread_per_core.rs:25-210). Hosts share
    # nothing inside a window; results are identical to serial by
    # construction (per-source staging merged in host-id order)
    host_workers: int = 1
    # CPU host plane scheduling policy (reference scheduler crate):
    # "steal" = thread-per-core work stealing (thread_per_core.rs:192-210);
    # "per-host" = one dedicated thread per host, host_workers bounding
    # concurrency (thread_per_host.rs:25-60 + ParallelismBoundedThreadPool)
    host_scheduler: str = "steal"
    # pin host-plane workers to logical CPUs, packed node/socket/core-first
    # (reference use_cpu_pinning, core/affinity.c)
    use_cpu_pinning: bool = False

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "ExperimentalOptions":
        d = dict(d or {})
        e = ExperimentalOptions()
        if "runahead" in d:
            e.runahead = parse_time_ns(d.pop("runahead"), TimeUnit.MS)
        for f in (
            "scheduler",
            "interface_qdisc",
            "strace_logging_mode",
        ):
            if f in d:
                setattr(e, f, str(d.pop(f)))
        if "overflow_shed" in d:
            e.overflow_shed = str(d.pop("overflow_shed"))
        if "exchange" in d:
            e.exchange = str(d.pop("exchange"))
        if "a2a_block" in d:
            e.a2a_block = int(d.pop("a2a_block"))
        if e.a2a_block < 0:
            raise ConfigError(
                f"experimental.a2a_block must be >= 0 (0 = auto), "
                f"got {e.a2a_block}"
            )
        if e.exchange not in ("auto", "gather", "alltoall", "hierarchical"):
            raise ConfigError(
                f"experimental.exchange must be auto|gather|alltoall|"
                f"hierarchical, got {e.exchange!r}"
            )
        if "cpu_delay" in d:
            e.cpu_delay = parse_time_ns(d.pop("cpu_delay"), TimeUnit.MS)
        if "merge_gears" in d:
            mg = d.pop("merge_gears")
            # shape-validate here (loud config errors); the ladder itself
            # resolves against the send budget at build time
            # (core.gears.resolve_gear_ladder — the budget may be auto-sized)
            if isinstance(mg, str):
                if mg.lower() not in ("auto", "off"):
                    raise ConfigError(
                        f"experimental.merge_gears must be off|auto|int|"
                        f"[ints], got {mg!r}"
                    )
                mg = 0 if mg.lower() == "off" else "auto"
            elif isinstance(mg, list):
                if not all(isinstance(g, int) and g > 0 for g in mg):
                    raise ConfigError(
                        f"experimental.merge_gears list entries must be "
                        f"positive ints, got {mg!r}"
                    )
            elif mg is not None and not isinstance(mg, (int, bool)):
                raise ConfigError(
                    f"experimental.merge_gears must be off|auto|int|[ints], "
                    f"got {mg!r}"
                )
            e.merge_gears = mg or 0
        if e.strace_logging_mode not in ("off", "standard", "deterministic"):
            raise ConfigError(
                f"experimental.strace_logging_mode must be off|standard|"
                f"deterministic, got {e.strace_logging_mode!r}"
            )
        if e.overflow_shed not in ("urgency", "append"):
            raise ConfigError(
                f"experimental.overflow_shed must be urgency|append, "
                f"got {e.overflow_shed!r}"
            )
        if e.scheduler not in ("tpu", "cpu-reference"):
            raise ConfigError(
                f"experimental.scheduler must be tpu|cpu-reference, "
                f"got {e.scheduler!r}"
            )
        if "host_scheduler" in d:
            e.host_scheduler = str(d.pop("host_scheduler"))
        if e.host_scheduler not in ("steal", "per-host"):
            raise ConfigError(
                f"experimental.host_scheduler must be steal|per-host, "
                f"got {e.host_scheduler!r}"
            )
        for f in (
            "use_dynamic_runahead",
            "use_codel",
            "packet_breadcrumbs",
            "use_cpu_pinning",
            "merge_scatter",
        ):
            if f in d:
                setattr(e, f, bool(d.pop(f)))
        for f in (
            "event_queue_capacity",
            "event_queue_block",
            "sends_per_host_round",
            "max_round_inserts",
            "rounds_per_chunk",
            "microstep_limit",
            "microstep_events",
            "host_workers",
            "merge_rows",
            "timer_wheel",
            "timer_wheel_block",
        ):
            if f in d:
                setattr(e, f, int(d.pop(f)))
        if e.event_queue_block < 0:
            raise ConfigError(
                f"experimental.event_queue_block must be >= 0 (0 = flat), "
                f"got {e.event_queue_block}"
            )
        if e.microstep_events < 1:
            raise ConfigError(
                f"experimental.microstep_events must be >= 1, "
                f"got {e.microstep_events}"
            )
        if e.timer_wheel < 0:
            raise ConfigError(
                f"experimental.timer_wheel must be >= 0 (0 = off), "
                f"got {e.timer_wheel}"
            )
        if e.timer_wheel_block < 0 or (
            e.timer_wheel and e.timer_wheel_block
            and e.timer_wheel % e.timer_wheel_block
        ):
            raise ConfigError(
                f"experimental.timer_wheel_block="
                f"{e.timer_wheel_block} must be 0 (auto) or divide "
                f"timer_wheel={e.timer_wheel} evenly"
            )
        if e.timer_wheel and e.microstep_events > 1:
            raise ConfigError(
                f"unsupported knob pair: experimental.timer_wheel"
                f"={e.timer_wheel} x experimental.microstep_events"
                f"={e.microstep_events} — the wheel's pop path merges ONE "
                f"wheel candidate against the queue head per microstep, "
                f"and the K-way fold would need a merged 2K-candidate "
                f"batch with split clear/reserve accounting to stay "
                f"exact. ROADMAP item 1 tracks that follow-up. Until it "
                f"lands, drop one knob: run the wheel with "
                f"microstep_events=1 (the measured CPU winner) or keep "
                f"the wheel off (docs/usage.md 'Timer wheel')"
            )
        if d:
            raise ConfigError(f"unknown experimental options: {sorted(d)}")
        return e


@dataclass
class ObservabilityOptions:
    """The observability plane's knobs (no reference analogue — the
    reference's trackers/heartbeats observe host-side state; here the
    round loop runs inside jit, so tracing needs a device-resident ring,
    obs/tracer.py + docs/architecture.md "Observability").

    Everything here is an observer: enabling any knob leaves digests,
    event counts, and drop counters bit-identical (tests/test_tracer.py
    is the gate)."""

    # device-resident round tracer: record one ring row per scheduling
    # round inside the jitted loop, drain at chunk boundaries, export a
    # Chrome-trace timeline + Prometheus metrics + sim-stats extensions
    trace: bool = False
    # export paths, relative to general.data_directory (written by
    # write_outputs when trace is on); null skips that export
    trace_file: str | None = "trace.json"  # Chrome-trace/Perfetto JSON
    metrics_file: str | None = "metrics.prom"  # Prometheus text; None = off
    # wrap the chunk-dispatch loop in jax.profiler.trace(profile_dir):
    # XLA-level device profiles (xplane) land there, with the engine's
    # jax.named_scope annotations (shadow_microsteps / shadow_exchange /
    # shadow_merge) labeling the hot regions. None = off.
    profile_dir: str | None = None
    # HBM & capacity observatory (obs/memory.py + docs/architecture.md
    # "Memory observatory"): sample device.memory_stats() per shard at
    # chunk boundaries (per-shard HBM high-water; modeled fallback where
    # the backend has no allocator stats), add the static byte model +
    # live telemetry as a `memory{}` block to sim-stats, an `hbm=` field
    # to heartbeat lines, gauges to the Prometheus export, and a
    # wall-clock memory counter track to the Chrome trace. Pure host-side
    # observer: NO traced code changes — digests and the compiled
    # programs are byte-identical on or off.
    memory: bool = False
    # Network observatory (obs/netobs.py + docs/architecture.md "Network
    # observatory"): in-jit event-class accounting (timer/packet/app),
    # a per-shard flow-completion ledger ring (FCT distributions + a
    # Perfetto flow track), host-side per-link counter folds, and
    # per-round safe-window critical-path telemetry — a `network{}`
    # block in sim-stats, `ek=`/`fct=` heartbeat fields, and extra
    # trace-ring columns. Observer contract: digests/events/drops are
    # bit-identical on or off; with it OFF no observatory code is traced
    # and the default program is byte-unchanged (tests/test_netobs.py +
    # the jaxpr fingerprint gate).
    network: bool = False
    # flow-ledger ring capacity in records PER SHARD (sized so a chunk's
    # completions rarely wrap; a wrap overwrites the oldest records,
    # counted by the collector, while the fl_* stats lanes stay exact).
    # Only models with a flow port (tgen_tcp) carry a ledger; 0 disables
    # the ledger entirely (event classes + safe window still run).
    network_flows: int = 4096
    # also compile-and-read `Compiled.memory_analysis()` for every chunk
    # program the run's engine cached (the per-rung ledger in the
    # memory{} block). Reading the analysis needs a fresh lower+compile
    # per rung at report time — skip it on huge configs where recompiles
    # hurt more than the ledger helps.
    memory_ledger: bool = True
    # Runtime observatory (obs/runtime.py + docs/architecture.md
    # "Runtime observatory"): wall-clock attribution. A compile ledger
    # records lowering+compile wall per cached jitted program (base
    # chunk, gear variants, pressure rungs) with its trigger and
    # hit/miss counts; a WallLedger splits each chunk's wall into named
    # spans (compile / dispatch / host-python / snapshot / replay /
    # export) and tracks a per-chunk realtime factor (sim-s/wall-s); the
    # hybrid driver adds the per-window bridge-stall split. Exported as
    # a `runtime{}` sim-stats block, an `rt=` heartbeat field, and a
    # compile track in the Chrome trace. Pure host-side observer: NO
    # traced code changes — digests and the compiled programs are
    # byte-identical on or off (tests/test_runtime.py is the gate).
    runtime: bool = False

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "ObservabilityOptions":
        d = dict(d or {})
        o = ObservabilityOptions(
            trace=bool(d.pop("trace", False)),
            trace_file=d.pop("trace_file", "trace.json"),
            metrics_file=d.pop("metrics_file", "metrics.prom"),
            profile_dir=d.pop("profile_dir", None),
            memory=bool(d.pop("memory", False)),
            network=bool(d.pop("network", False)),
            network_flows=int(d.pop("network_flows", 4096)),
            memory_ledger=bool(d.pop("memory_ledger", True)),
            runtime=bool(d.pop("runtime", False)),
        )
        if o.network_flows < 0:
            raise ConfigError(
                f"observability.network_flows must be >= 0 (0 = no flow "
                f"ledger, event classes and safe-window only), "
                f"got {o.network_flows}"
            )
        # null disables an export; a non-null value must be a usable path
        # (str(None) would silently produce a file literally named "None")
        for f in ("trace_file", "metrics_file", "profile_dir"):
            v = getattr(o, f)
            if v is not None:
                v = str(v)
                setattr(o, f, v)
                if not v:
                    raise ConfigError(
                        f"observability.{f} must be non-empty (use null "
                        f"to disable)"
                    )
        if d:
            raise ConfigError(f"unknown observability options: {sorted(d)}")
        return o


@dataclass
class PressureOptions:
    """The pressure plane (core/pressure.py + docs/architecture.md
    "Pressure plane"): what happens when a fixed-shape lane would drop
    for capacity — queue-push overflow, merge/alltoall sheds, outbox
    overflow, per-host send-budget drops.

      drop      — today's semantics (default): drops are counted
                  (queue_overflow_dropped & friends) and the run goes
                  on. The engine program is bit-identical to before the
                  pressure plane existed.
      escalate  — drop-free by construction: the chunk aborts in-jit at
                  the first dropping round (mesh-uniform, psum'd), the
                  driver restores the pre-chunk snapshot, regrows the
                  queue capacity and/or outbox width one geometric rung
                  (growth_factor), and replays — accepted chunks carry
                  zero drops and are bit-identical to a run launched at
                  the final shape. Bounded by max_capacity/max_outbox
                  (the HBM guard); regrow is also proactive at chunk
                  boundaries once occupancy crosses `headroom`.
      abort     — loud failure: stop at the first dropping round with
                  honest artifacts instead of shedding silently.
    """

    policy: str = "drop"  # drop | escalate | abort
    # escalation ceilings (the HBM guard): 0 = auto (8x the initial
    # queue capacity / 4x the initial send budget)
    max_capacity: int = 0  # queue slots per host
    max_outbox: int = 0  # sends per host per round
    growth_factor: int = 2  # geometric rung ratio (>= 2 keeps the
    # bucketed queue's block divisibility: C * 2^k stays divisible by B)
    # proactive-regrow threshold: grow at a chunk boundary once the
    # occupancy high-water reaches ceil(headroom * capacity) (and the
    # outbox once a chunk's send high-water FILLS the budget). 0
    # disables proactive regrow (escalation stays purely reactive).
    headroom: float = 0.85
    # memory-informed escalation (obs/memory.py MemoryGuard): a candidate
    # rung is refused BEFORE dispatch when its predicted extra footprint
    # (static-model delta x the replay's snapshot+state concurrency) x
    # this safety factor exceeds the device's MEASURED headroom
    # (memory_stats bytes_limit - bytes_in_use) — replacing the
    # OOM-round-trip discovery with a poisoned rung. Inert where no
    # allocator limit is measurable (CPU backends) or until the first
    # sample lands. >= 1.0.
    memory_safety_factor: float = 1.25

    @property
    def active(self) -> bool:
        return self.policy != "drop"

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "PressureOptions":
        d = dict(d or {})
        p = PressureOptions(
            policy=str(d.pop("policy", "drop")),
            max_capacity=int(d.pop("max_capacity", 0)),
            max_outbox=int(d.pop("max_outbox", 0)),
            growth_factor=int(d.pop("growth_factor", 2)),
            headroom=float(d.pop("headroom", 0.85)),
            memory_safety_factor=float(d.pop("memory_safety_factor", 1.25)),
        )
        if p.policy not in ("drop", "escalate", "abort"):
            raise ConfigError(
                f"pressure.policy must be drop|escalate|abort, "
                f"got {p.policy!r}"
            )
        if p.max_capacity < 0 or p.max_outbox < 0:
            raise ConfigError(
                f"pressure ceilings must be >= 0 (0 = auto), got "
                f"max_capacity={p.max_capacity} max_outbox={p.max_outbox}"
            )
        if p.growth_factor < 2:
            raise ConfigError(
                f"pressure.growth_factor must be >= 2, "
                f"got {p.growth_factor}"
            )
        if not 0.0 <= p.headroom <= 1.0:
            raise ConfigError(
                f"pressure.headroom must be in [0, 1] (0 disables "
                f"proactive regrow), got {p.headroom}"
            )
        if p.memory_safety_factor < 1.0:
            raise ConfigError(
                f"pressure.memory_safety_factor must be >= 1.0 (a factor "
                f"below 1 would admit rungs past measured headroom), "
                f"got {p.memory_safety_factor}"
            )
        if d:
            raise ConfigError(f"unknown pressure options: {sorted(d)}")
        return p


@dataclass
class IntegrityOptions:
    """The integrity sentinel (core/integrity.py + docs/architecture.md
    "Integrity sentinel"): in-jit per-round invariant guards compiled
    into the round body only when `enabled` — conservation laws the
    state must satisfy regardless of workload (time monotonicity,
    event-class reconciliation, queue fill-cache agreement, counter
    monotonicity, outbox bounds, dual-digest virginity). With the block
    absent/off the engine traces ZERO sentinel code and the program is
    byte-identical to the pre-sentinel build.

    On a violation the chunk aborts at the violating round and the
    driver restores the pre-chunk snapshot and replays: a violation
    reproducing with the same (shard, round, bitmask) signature is a
    DETERMINISTIC engine bug -> loud IntegrityAbort naming the
    invariant, round, and shard; one that does not reproduce is
    transient silent data corruption -> counted in sim-stats
    integrity{transients,replays}, logged, and the run continues."""

    enabled: bool = False
    # second, independently-folded per-host digest lane (stats.digest2)
    # so a scribble on the digest plane itself is detectable
    # (core/integrity.classify_digest_pair)
    dual_digest: bool = True
    # consecutive non-reproducing violation replays of ONE chunk before
    # the sentinel gives up (violations persisting without ever
    # reproducing still stop the run — progress must stay bounded)
    max_replays: int = 3

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "IntegrityOptions":
        d = dict(d or {})
        o = IntegrityOptions(
            enabled=bool(d.pop("enabled", False)),
            dual_digest=bool(d.pop("dual_digest", True)),
            max_replays=int(d.pop("max_replays", 3)),
        )
        if o.max_replays < 1:
            raise ConfigError(
                f"integrity.max_replays must be >= 1, got {o.max_replays}"
            )
        if d:
            raise ConfigError(f"unknown integrity options: {sorted(d)}")
        return o


@dataclass
class FluidClassOptions:
    """One background traffic class of the fluid plane (net/fluid.py):
    an aggregate src-zone -> dst-zone demand active over [start, end).
    Zones are graph node ids — the class occupies BOTH zones' access
    links. Compose flash crowds from several staggered windows."""

    src_zone: int = 0
    dst_zone: int = 0
    rate: int = 0  # offered demand while active, bits/sec
    start: int = 0  # ns
    end: int = 0  # ns; 0 = the simulation horizon

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FluidClassOptions":
        d = dict(d)
        if "rate" not in d:
            raise ConfigError("fluid.classes entries need a rate")
        d.pop("name", None)  # labels are allowed, purely documentary
        c = FluidClassOptions(
            src_zone=int(d.pop("src_zone", 0)),
            dst_zone=int(d.pop("dst_zone", 0)),
            rate=parse_bits_per_sec(d.pop("rate")),
            start=parse_time_ns(d.pop("start", 0), TimeUnit.SEC),
            end=parse_time_ns(d.pop("end", 0), TimeUnit.SEC),
        )
        if c.rate <= 0:
            raise ConfigError(
                f"fluid.classes: rate must be > 0, got {c.rate}"
            )
        if c.end and c.end <= c.start:
            raise ConfigError(
                f"fluid.classes: end {c.end} <= start {c.start}"
            )
        if d:
            raise ConfigError(f"unknown fluid class options: {sorted(d)}")
        return c


@dataclass
class FluidOptions:
    """The fluid traffic plane (net/fluid.py + docs/architecture.md
    "Fluid traffic plane"): background-flow rate ODEs advanced once per
    round inside the jitted round body, conservatively coupled to the
    packet engine — background utilization inflates foreground latency
    (>= 1.0x, the fault plane's conservative rule) and optionally loss,
    while measured foreground bytes subtract from fluid capacity. With
    the block absent (no classes) the engine traces ZERO fluid code and
    the default program is byte-identical to the fluid-free build."""

    # per-link (graph-node access aggregate) capacity, bits/sec
    link_capacity: int = parse_bits_per_sec("1 Gbit")
    # rate-relaxation time constant of the forward-Euler ODE (ns)
    tau: int = parse_time_ns("50 ms")
    # offered utilization where coupling starts ramping (RED min-th)
    util_threshold: float = 0.7
    # extra foreground loss probability at full overload (0 = coupling
    # is latency-only; drops land in pkts_lost, attributed per link by
    # the network observatory's links fold)
    loss_max: float = 0.0
    # foreground latency multiplier at full overload; >= 1.0 (inflation
    # only — deflation would break the conservative-lookahead bound)
    latency_factor_max: float = 2.0
    seed: int | None = None  # None = general.seed (the loss-draw hash)
    classes: list[FluidClassOptions] = field(default_factory=list)

    @property
    def active(self) -> bool:
        return bool(self.classes)

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "FluidOptions":
        d = dict(d or {})
        seed = d.pop("seed", None)
        f = FluidOptions(
            link_capacity=parse_bits_per_sec(
                d.pop("link_capacity", "1 Gbit")
            ),
            tau=parse_time_ns(d.pop("tau", "50 ms"), TimeUnit.MS),
            util_threshold=float(d.pop("util_threshold", 0.7)),
            loss_max=float(d.pop("loss_max", 0.0)),
            latency_factor_max=float(d.pop("latency_factor_max", 2.0)),
            seed=int(seed) if seed is not None else None,
            classes=[
                FluidClassOptions.from_dict(c)
                for c in d.pop("classes", []) or []
            ],
        )
        if f.link_capacity <= 0:
            raise ConfigError(
                f"fluid.link_capacity must be > 0, got {f.link_capacity}"
            )
        if f.tau <= 0:
            raise ConfigError(f"fluid.tau must be > 0, got {f.tau}")
        if not 0.0 <= f.util_threshold < 1.0:
            raise ConfigError(
                f"fluid.util_threshold must be in [0, 1), "
                f"got {f.util_threshold}"
            )
        if not 0.0 <= f.loss_max <= 1.0:
            raise ConfigError(
                f"fluid.loss_max must be in [0, 1], got {f.loss_max}"
            )
        if f.latency_factor_max < 1.0:
            raise ConfigError(
                f"fluid.latency_factor_max must be >= 1.0 (got "
                f"{f.latency_factor_max}; deflation would shrink latency "
                f"below the conservative-lookahead bound)"
            )
        if d:
            raise ConfigError(f"unknown fluid options: {sorted(d)}")
        return f


@dataclass
class FaultChurnOptions:
    """Seeded host-churn: each host crashes once with probability `prob`
    at a uniform time in [bootstrap_end_time, stop_time), down for an
    exponential draw around `mean_downtime`."""

    prob: float = 0.0
    mean_downtime: int = parse_time_ns("1 s")  # ns

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "FaultChurnOptions | None":
        if d is None:
            return None
        d = dict(d)
        c = FaultChurnOptions(
            prob=float(d.pop("prob", 0.0)),
            mean_downtime=parse_time_ns(d.pop("mean_downtime", "1 s"), TimeUnit.SEC),
        )
        if not 0.0 <= c.prob <= 1.0:
            raise ConfigError(
                f"faults.host_churn.prob must be in [0, 1], got {c.prob}"
            )
        if d:
            raise ConfigError(f"unknown host_churn options: {sorted(d)}")
        return c


@dataclass
class FaultCrash:
    """One explicit host outage: down at `down_at`, back at `up_at`."""

    host: Any = 0  # host id (int) or host name (str)
    down_at: int = 0  # ns
    up_at: int = 0  # ns

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FaultCrash":
        d = dict(d)
        if "host" not in d or "down_at" not in d or "up_at" not in d:
            raise ConfigError(
                "faults.crashes entries need host, down_at, up_at"
            )
        c = FaultCrash(
            host=d.pop("host"),
            down_at=parse_time_ns(d.pop("down_at"), TimeUnit.SEC),
            up_at=parse_time_ns(d.pop("up_at"), TimeUnit.SEC),
        )
        if c.up_at <= c.down_at:
            raise ConfigError(
                f"faults.crashes: up_at must be > down_at (host {c.host!r})"
            )
        if d:
            raise ConfigError(f"unknown crash options: {sorted(d)}")
        return c


@dataclass
class FaultLossWindow:
    """A link-fault window: extra packet-loss probability and a latency
    multiplier active over [start, end). latency_factor must be >= 1.0 —
    deflation would break the conservative-lookahead bound."""

    start: int = 0  # ns
    end: int = 0  # ns
    loss: float = 0.0
    latency_factor: float = 1.0

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "FaultLossWindow":
        d = dict(d)
        if "start" not in d or "end" not in d:
            raise ConfigError("faults.loss_windows entries need start, end")
        w = FaultLossWindow(
            start=parse_time_ns(d.pop("start"), TimeUnit.SEC),
            end=parse_time_ns(d.pop("end"), TimeUnit.SEC),
            loss=float(d.pop("loss", 0.0)),
            latency_factor=float(d.pop("latency_factor", 1.0)),
        )
        if w.end <= w.start:
            raise ConfigError("faults.loss_windows: end must be > start")
        if not 0.0 <= w.loss <= 1.0:
            raise ConfigError(
                f"faults.loss_windows: loss must be in [0, 1], got {w.loss}"
            )
        if w.latency_factor < 1.0:
            raise ConfigError(
                f"faults.loss_windows: latency_factor must be >= 1.0 "
                f"(got {w.latency_factor}; deflation would shrink latency "
                f"below the conservative-lookahead bound)"
            )
        if d:
            raise ConfigError(f"unknown loss_window options: {sorted(d)}")
        return w


@dataclass
class SupervisorOptions:
    """Crash-resilient run supervisor (core/supervisor.py): periodic
    device snapshots of the sim state, retry-with-backoff on dispatch
    failure, replay from the last good snapshot with a digest cross-check,
    graceful abort after bounded retries. 0 snapshot interval = off."""

    snapshot_every_chunks: int = 0
    checkpoint_file: str | None = None  # on-disk .npz, relative to data dir
    max_retries: int = 3
    backoff_base_ms: int = 50

    @property
    def enabled(self) -> bool:
        return self.snapshot_every_chunks > 0

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "SupervisorOptions":
        d = dict(d or {})
        s = SupervisorOptions(
            snapshot_every_chunks=int(d.pop("snapshot_every_chunks", 0)),
            checkpoint_file=d.pop("checkpoint_file", None),
            max_retries=int(d.pop("max_retries", 3)),
            backoff_base_ms=int(d.pop("backoff_base_ms", 50)),
        )
        if s.snapshot_every_chunks < 0:
            raise ConfigError(
                f"faults.supervisor.snapshot_every_chunks must be >= 0, "
                f"got {s.snapshot_every_chunks}"
            )
        if s.max_retries < 0:
            raise ConfigError(
                f"faults.supervisor.max_retries must be >= 0, "
                f"got {s.max_retries}"
            )
        if s.backoff_base_ms < 0:
            raise ConfigError(
                f"faults.supervisor.backoff_base_ms must be >= 0, "
                f"got {s.backoff_base_ms}"
            )
        if s.checkpoint_file is not None and not str(s.checkpoint_file):
            raise ConfigError(
                "faults.supervisor.checkpoint_file must be non-empty "
                "(use null to disable)"
            )
        if d:
            raise ConfigError(f"unknown supervisor options: {sorted(d)}")
        return s


@dataclass
class FaultOptions:
    """The fault plane (core/faults.py + docs/architecture.md "Fault
    plane"): deterministic in-sim fault injection plus the crash-resilient
    run supervisor. Everything is seeded and bit-reproducible: same fault
    seed => same digests, across reruns, mesh shapes, and a mid-run
    snapshot resume (tests/test_faults.py). With the block absent the
    engine program is bit-identical to the fault-free build."""

    seed: int | None = None  # None = general.seed
    # what happens to a crashed host's pending events at/during the
    # outage: "hold" defers them to the restart (the CPU-model busy-floor
    # mechanics); "clear" drops every event whose execution time falls in
    # a down window (counted in stats.faults_dropped)
    restart_queue: str = "hold"
    host_churn: FaultChurnOptions | None = None
    crashes: list[FaultCrash] = field(default_factory=list)
    loss_windows: list[FaultLossWindow] = field(default_factory=list)
    supervisor: SupervisorOptions = field(default_factory=SupervisorOptions)

    @property
    def injecting(self) -> bool:
        """True when the block schedules any in-sim fault."""
        return bool(
            (self.host_churn is not None and self.host_churn.prob > 0)
            or self.crashes
            or self.loss_windows
        )

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "FaultOptions":
        d = dict(d or {})
        seed = d.pop("seed", None)
        f = FaultOptions(
            seed=int(seed) if seed is not None else None,
            restart_queue=str(d.pop("restart_queue", "hold")),
            host_churn=FaultChurnOptions.from_dict(d.pop("host_churn", None)),
            crashes=[FaultCrash.from_dict(c) for c in d.pop("crashes", []) or []],
            loss_windows=[
                FaultLossWindow.from_dict(w)
                for w in d.pop("loss_windows", []) or []
            ],
            supervisor=SupervisorOptions.from_dict(d.pop("supervisor", None)),
        )
        if f.restart_queue not in ("hold", "clear"):
            raise ConfigError(
                f"faults.restart_queue must be hold|clear, "
                f"got {f.restart_queue!r}"
            )
        if d:
            raise ConfigError(f"unknown faults options: {sorted(d)}")
        return f


@dataclass
class CampaignOptions:
    """The ensemble plane's sweep declaration (core/ensemble.py +
    tools/campaign.py + docs/architecture.md "Ensemble plane"): one
    vmapped program advances R replicas — seed sweeps, fault-schedule
    sweeps, A/B override pairs — per dispatch, with each replica
    bit-identical to its solo run (tests/test_ensemble.py is the gate).

    Replicas are the CROSS PRODUCT of the declared axes (an omitted axis
    contributes the base config), in (seed, fault_schedule, override)
    nesting order — so replica indices are stable and documentable:
    index = ((seed_i * len(fault_schedules)) + fault_i) * len(overrides)
    + override_i."""

    # seed axis: explicit list, or {start: S, count: N} for a range;
    # empty = [general.seed]
    seeds: list[int] = field(default_factory=list)
    # fault-schedule axis: each entry is a full `faults:` block (injection
    # fields only — the campaign's supervisor comes from the top-level
    # faults block), kept as the RAW mapping (validated at parse) because
    # the campaign driver expands replicas at the config-dict level;
    # empty = [the top-level faults block]
    fault_schedules: list[dict] = field(default_factory=list)
    # override axis: each entry maps dotted config paths to values
    # (the merge_cli_overrides syntax), e.g. {"experimental.cpu_delay": 2}.
    # Only values that change ARRAYS may vary — anything that changes an
    # EngineConfig static (shapes, queue layout, K, policies) is rejected
    # at build time. empty = [{}]
    overrides: list[dict] = field(default_factory=list)
    # replica index pairs expected to end bit-identical (A/A controls, or
    # A/B pairs whose delta should be inert); a pair that diverges is
    # reported in the ledger and — when `bisect` is on — pinpointed to
    # its first divergent chunk by snapshot-replay binary search
    expect_identical: list[list[int]] = field(default_factory=list)
    # per-replica digest ledger, written into general.data_directory
    # (null disables)
    ledger_file: str | None = "campaign-ledger.json"
    bisect: bool = True
    # replica-COUNT cap (cheap parse-time line of defense). The real HBM
    # guard is memory-informed at build time: tools/campaign.py computes
    # R x per-replica state bytes (obs/memory.py exact accounting)
    # against the measured device capacity and rejects with the
    # predicted numbers.
    max_replicas: int = 64

    @property
    def active(self) -> bool:
        """True when the block declares any sweep axis."""
        return bool(self.seeds or self.fault_schedules or self.overrides)

    @property
    def num_replicas(self) -> int:
        return (
            max(len(self.seeds), 1)
            * max(len(self.fault_schedules), 1)
            * max(len(self.overrides), 1)
        )

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "CampaignOptions":
        d = dict(d or {})
        seeds_raw = d.pop("seeds", []) or []
        if isinstance(seeds_raw, dict):
            sd = dict(seeds_raw)
            start, count = int(sd.pop("start", 1)), int(sd.pop("count", 0))
            if sd:
                raise ConfigError(f"unknown campaign.seeds keys: {sorted(sd)}")
            if count < 1:
                raise ConfigError(
                    f"campaign.seeds.count must be >= 1, got {count}"
                )
            seeds = list(range(start, start + count))
        else:
            seeds = [int(s) for s in seeds_raw]
        scheds = []
        for i, f in enumerate(d.pop("fault_schedules", []) or []):
            f = dict(f or {})
            parsed = FaultOptions.from_dict(f)  # loud validation up front
            if parsed.supervisor.enabled or parsed.supervisor.checkpoint_file:
                raise ConfigError(
                    f"campaign.fault_schedules[{i}]: supervisor settings "
                    f"belong in the top-level faults block (the supervisor "
                    f"wraps the whole campaign, not one replica)"
                )
            scheds.append(f)
        overrides = []
        for i, ov in enumerate(d.pop("overrides", []) or []):
            if ov is None:
                ov = {}
            if not isinstance(ov, dict):
                raise ConfigError(
                    f"campaign.overrides[{i}] must be a mapping of dotted "
                    f"config paths to values, got {ov!r}"
                )
            overrides.append(dict(ov))
        pairs = []
        for i, p in enumerate(d.pop("expect_identical", []) or []):
            if (
                not isinstance(p, (list, tuple))
                or len(p) != 2
                or not all(isinstance(x, int) and x >= 0 for x in p)
            ):
                raise ConfigError(
                    f"campaign.expect_identical[{i}] must be a pair of "
                    f"replica indices, got {p!r}"
                )
            pairs.append([int(p[0]), int(p[1])])
        c = CampaignOptions(
            seeds=seeds,
            fault_schedules=scheds,
            overrides=overrides,
            expect_identical=pairs,
            ledger_file=d.pop("ledger_file", "campaign-ledger.json"),
            bisect=bool(d.pop("bisect", True)),
            max_replicas=int(d.pop("max_replicas", 64)),
        )
        if c.ledger_file is not None and not str(c.ledger_file):
            raise ConfigError(
                "campaign.ledger_file must be non-empty (use null to disable)"
            )
        if c.max_replicas < 1:
            raise ConfigError(
                f"campaign.max_replicas must be >= 1, got {c.max_replicas}"
            )
        if c.active and c.num_replicas > c.max_replicas:
            raise ConfigError(
                f"campaign declares {c.num_replicas} replicas, over "
                f"max_replicas={c.max_replicas} (each replica holds a full "
                f"SimState in device memory; raise the guard deliberately)"
            )
        for p in c.expect_identical:
            if max(p) >= c.num_replicas:
                raise ConfigError(
                    f"campaign.expect_identical pair {p} references a "
                    f"replica >= num_replicas={c.num_replicas}"
                )
        if d:
            raise ConfigError(f"unknown campaign options: {sorted(d)}")
        return c


@dataclass
class ProcessOptions:
    """reference: ProcessOptions (configuration.rs:643).

    Either a managed process (path/args) or a device model (model/model_args).
    """

    path: str | None = None
    args: list[str] = field(default_factory=list)
    environment: dict[str, str] = field(default_factory=dict)
    start_time: int = 0  # ns
    shutdown_time: int | None = None
    expected_final_state: Any = "running"  # "running" | {"exited": code} | {"signaled": sig}
    model: str | None = None  # device-model name, e.g. "udp_echo_client"
    model_args: dict[str, Any] = field(default_factory=dict)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ProcessOptions":
        d = dict(d)
        p = ProcessOptions(
            path=d.pop("path", None),
            args=_split_args(d.pop("args", [])),
            environment=dict(d.pop("environment", {}) or {}),
            start_time=parse_time_ns(d.pop("start_time", 0), TimeUnit.SEC),
            shutdown_time=(
                parse_time_ns(d["shutdown_time"], TimeUnit.SEC)
                if d.get("shutdown_time") is not None
                else None
            ),
            expected_final_state=d.pop("expected_final_state", "running"),
            model=d.pop("model", None),
            model_args=dict(d.pop("model_args", {}) or {}),
        )
        d.pop("shutdown_time", None)
        if p.path is None and p.model is None:
            raise ConfigError("process needs either `path` (managed) or `model` (device)")
        if d:
            raise ConfigError(f"unknown process options: {sorted(d)}")
        return p


def _split_args(args: Any) -> list[str]:
    if isinstance(args, str):
        return args.split()
    return [str(a) for a in (args or [])]


@dataclass
class HostDefaultOptions:
    """reference: HostDefaultOptions (configuration.rs:550), cascaded per
    host — including the TCP socket-buffer sizes and autotuning flags the
    reference exposes there (socket_send_buffer / socket_recv_buffer +
    autotune booleans)."""

    log_level: str | None = None
    pcap_enabled: bool = False
    pcap_capture_size: int = 65535
    tcp_send_buffer: int = 256 * 1024  # bytes ("256 KiB" accepted)
    tcp_recv_buffer: int = 256 * 1024
    tcp_autotune: bool = True  # grow buffers under pressure up to buf_max
    tcp_buffer_max: int = 4 * 1024 * 1024
    tcp_sack: bool = True
    tcp_delayed_ack: bool = True
    tcp_nagle: bool = False

    @staticmethod
    def from_dict(d: dict[str, Any] | None) -> "HostDefaultOptions":
        from shadow_tpu.config.units import parse_bytes

        d = dict(d or {})
        h = HostDefaultOptions(
            log_level=d.pop("log_level", None),
            pcap_enabled=bool(d.pop("pcap_enabled", False)),
            pcap_capture_size=int(d.pop("pcap_capture_size", 65535)),
            tcp_send_buffer=parse_bytes(d.pop("tcp_send_buffer", 256 * 1024)),
            tcp_recv_buffer=parse_bytes(d.pop("tcp_recv_buffer", 256 * 1024)),
            tcp_autotune=bool(d.pop("tcp_autotune", True)),
            tcp_buffer_max=parse_bytes(
                d.pop("tcp_buffer_max", 4 * 1024 * 1024)
            ),
            tcp_sack=bool(d.pop("tcp_sack", True)),
            tcp_delayed_ack=bool(d.pop("tcp_delayed_ack", True)),
            tcp_nagle=bool(d.pop("tcp_nagle", False)),
        )
        if d:
            raise ConfigError(f"unknown host default options: {sorted(d)}")
        return h

    def tcp_config(self):
        """Materialize the per-host TcpConfig these options describe."""
        from shadow_tpu.tcp import TcpConfig

        return TcpConfig(
            send_buf=self.tcp_send_buffer,
            recv_buf=self.tcp_recv_buffer,
            autotune=self.tcp_autotune,
            buf_max=self.tcp_buffer_max,
            sack=self.tcp_sack,
            delayed_ack=self.tcp_delayed_ack,
            nagle=self.tcp_nagle,
        )


@dataclass
class HostOptions:
    """reference: HostOptions (configuration.rs:674)."""

    name: str = ""
    network_node_id: int = 0
    count: int = 1  # expand into name1..nameN (tooling convenience; tgen-style)
    ip_addr: str | None = None
    bandwidth_down: int | None = None  # bits/sec; falls back to graph node
    bandwidth_up: int | None = None
    processes: list[ProcessOptions] = field(default_factory=list)
    host_options: HostDefaultOptions = field(default_factory=HostDefaultOptions)

    @staticmethod
    def from_dict(name: str, d: dict[str, Any], defaults: HostDefaultOptions) -> "HostOptions":
        d = dict(d)
        # per-host overrides go through the same typed parser as the
        # defaults (raw setattr left unit strings like "128 KiB" unparsed)
        overrides = d.pop("host_options", {}) or {}
        for k in overrides:
            if not hasattr(defaults, k):
                raise ConfigError(f"unknown host option {k!r}")
        merged_defaults = HostDefaultOptions.from_dict(
            {**dataclasses.asdict(defaults), **overrides}
        )
        bw_down = d.pop("bandwidth_down", None)
        bw_up = d.pop("bandwidth_up", None)
        h = HostOptions(
            name=name,
            network_node_id=int(d.pop("network_node_id", 0)),
            count=int(d.pop("count", 1)),
            ip_addr=d.pop("ip_addr", None),
            bandwidth_down=parse_bits_per_sec(bw_down) if bw_down is not None else None,
            bandwidth_up=parse_bits_per_sec(bw_up) if bw_up is not None else None,
            processes=[ProcessOptions.from_dict(p) for p in d.pop("processes", [])],
            host_options=merged_defaults,
        )
        if d:
            raise ConfigError(f"unknown host options for {name!r}: {sorted(d)}")
        return h


@dataclass
class ConfigOptions:
    """Top-level config (reference: ConfigOptions, configuration.rs:112)."""

    general: GeneralOptions = field(default_factory=GeneralOptions)
    network: NetworkOptions = field(default_factory=NetworkOptions)
    experimental: ExperimentalOptions = field(default_factory=ExperimentalOptions)
    observability: ObservabilityOptions = field(
        default_factory=ObservabilityOptions
    )
    faults: FaultOptions = field(default_factory=FaultOptions)
    pressure: PressureOptions = field(default_factory=PressureOptions)
    integrity: IntegrityOptions = field(default_factory=IntegrityOptions)
    fluid: FluidOptions = field(default_factory=FluidOptions)
    campaign: CampaignOptions = field(default_factory=CampaignOptions)
    host_option_defaults: HostDefaultOptions = field(default_factory=HostDefaultOptions)
    hosts: list[HostOptions] = field(default_factory=list)

    @staticmethod
    def from_dict(d: dict[str, Any]) -> "ConfigOptions":
        d = dict(d)
        if "general" not in d:
            raise ConfigError("`general` section is required")
        defaults = HostDefaultOptions.from_dict(d.pop("host_option_defaults", None))
        hosts_raw = d.pop("hosts", {}) or {}
        hosts: list[HostOptions] = []
        for name, hd in hosts_raw.items():
            h = HostOptions.from_dict(name, hd or {}, defaults)
            if h.count == 1:
                hosts.append(h)
            else:
                for i in range(1, h.count + 1):
                    hi = copy.deepcopy(h)
                    hi.name = f"{name}{i}"
                    hi.count = 1
                    hosts.append(hi)
        cfg = ConfigOptions(
            general=GeneralOptions.from_dict(d.pop("general")),
            network=NetworkOptions.from_dict(d.pop("network", None)),
            experimental=ExperimentalOptions.from_dict(d.pop("experimental", None)),
            observability=ObservabilityOptions.from_dict(
                d.pop("observability", None)
            ),
            faults=FaultOptions.from_dict(d.pop("faults", None)),
            pressure=PressureOptions.from_dict(d.pop("pressure", None)),
            integrity=IntegrityOptions.from_dict(d.pop("integrity", None)),
            fluid=FluidOptions.from_dict(d.pop("fluid", None)),
            campaign=CampaignOptions.from_dict(d.pop("campaign", None)),
            host_option_defaults=defaults,
            hosts=hosts,
        )
        if d:
            raise ConfigError(f"unknown top-level sections: {sorted(d)}")
        return cfg

    def to_dict(self) -> dict[str, Any]:
        """Re-serializable form, written to data-dir/processed-config.yaml for
        provenance (reference manager.rs:182-193)."""
        return dataclasses.asdict(self)


def load_config(path_or_text: str, *, is_text: bool = False) -> ConfigOptions:
    """Load a YAML config from a path (or inline text / '-' for stdin)."""
    if is_text:
        text = path_or_text
    elif path_or_text == "-":
        import sys

        text = sys.stdin.read()
    else:
        with open(path_or_text) as f:
            text = f.read()
    data = yaml.safe_load(text)
    if not isinstance(data, dict):
        raise ConfigError("config must be a YAML mapping")
    return ConfigOptions.from_dict(data)


def merge_cli_overrides(cfg: ConfigOptions, overrides: dict[str, str]) -> ConfigOptions:
    """Apply `--section.key=value` CLI overrides; CLI wins over file
    (reference configuration.rs:19-24)."""
    cfg = copy.deepcopy(cfg)
    for dotted, raw in overrides.items():
        parts = dotted.split(".")
        obj: Any = cfg
        for p in parts[:-1]:
            if not hasattr(obj, p):
                raise ConfigError(f"unknown config path {dotted!r}")
            obj = getattr(obj, p)
        leaf = parts[-1]
        if not hasattr(obj, leaf):
            raise ConfigError(f"unknown config path {dotted!r}")
        cur = getattr(obj, leaf)
        val: Any = yaml.safe_load(raw)
        try:
            if leaf.endswith("_time") or leaf in ("heartbeat_interval",):
                val = parse_time_ns(val, TimeUnit.SEC)
            elif leaf in ("runahead", "cpu_delay"):
                # Same bare-number unit as the YAML path (milliseconds).
                val = parse_time_ns(val, TimeUnit.MS)
            elif leaf.startswith("bandwidth_"):
                val = parse_bits_per_sec(val)
            elif leaf == "merge_gears":
                pass  # polymorphic (off|auto|int|[ints]); validated at build
            elif isinstance(cur, bool):
                val = bool(val)
            elif isinstance(cur, int):
                val = int(val)
        except (TypeError, ValueError) as e:
            raise ConfigError(f"bad value for --{dotted}: {e}") from e
        setattr(obj, leaf, val)
    return cfg
