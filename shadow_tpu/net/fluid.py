"""Fluid traffic plane: device-resident background-flow rate ODEs,
conservatively coupled to the packet engine (ROADMAP item 5).

Emulating a flash crowd or an elephant/mice mix with a packet per
keystroke would blow both the event budget and HBM; Rain (PAPERS.md,
arxiv 2606.03352) argues the microsecond-scale foreground must stay
packet-exact — so the answer is a hybrid. A `fluid:` config block
compiles a set of background traffic CLASSES (src-zone -> dst-zone
demand with an active [start, end) window) into per-link fluid rate
ODEs advanced ONCE PER ROUND inside the jitted round body:

  forward-Euler over the round's committed window [now, window_end):
    rate_k'   = rate_k + min(dt/tau, 1) * (demand_k(t) - rate_k)
    bg[n]     = sum_k rate_k' over classes whose src or dst zone is n
    avail[n]  = max(capacity[n] - fg_rate[n], 0)      # packet plane first
    share[n]  = min(1, avail[n] / bg[n])              # DropTail clip
    g_k       = min(share[src_k], share[dst_k])       # class bottleneck
    carried_k = rate_k' * g_k                         # the new rate state
    util[n]   = (bg[n] + fg_rate[n]) / capacity[n]    # offered, may be >1

`fg_rate[n]` is the PACKET plane's measured bytes on link n this round
(the outbox fold, psum'd across the mesh) — foreground bytes subtract
from fluid capacity at round granularity, so the background can never
starve the exact plane. Carried background bytes accumulate into
`stats.fl_bg_bytes`, the DropTail-clipped remainder into
`stats.fl_bg_dropped` (counted, never silent). The clip-to-carried rate
update gives the classes an AIMD-flavored sawtooth: relax toward demand,
multiplicative clip at congestion.

Conservative coupling, one-way-safe in each direction:

  fluid -> packet: at round START, each host's access-link offered
  utilization (from the PREVIOUS round's ODE state) maps to a latency
  multiplier >= 1.0 (x1000 integer math, the fault plane's LAT_SCALE
  rule) and an extra loss probability in [0, fluid_loss_max], both
  ramping linearly from `util_threshold` to full overload and BOTH
  gated on background actually being present on the link (bg[n] > 0).
  Inflation can only GROW latency, so the conservative-lookahead bound
  — which uses the pre-inflation minimum — stays valid, exactly the
  fault plane's latency_factor argument; the safe-window psum is
  untouched. The loss draw is a COUNTER-BASED splitmix64 hash of
  (fluid seed, global host id, the host's emission counter) — a pure
  function that never advances the engine's per-host RNG lanes, so a
  zero-demand fluid block leaves every draw, every event, and every
  digest bit-identical to the fluid-off program.

  packet -> fluid: only the per-round byte fold above. The background
  plane reads aggregate bytes, never event content.

Determinism: the ODE is replicated f64 math over psum'd integer inputs
(identical on every shard, invariant to mesh shape), class/link folds
are fixed-order one-hot reductions (no float scatter-adds), and the
loss hash is pure in (seed, host, seq) — same seed => same digests,
across reruns AND mesh shapes (tests/test_fluid.py is the gate). With
the block absent the engine traces ZERO fluid code and the default
echo/phold/tgen jaxpr fingerprints are byte-unchanged; the gated
surface is pinned by the `tgen_fluid` fingerprint.
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import numpy as np

from shadow_tpu.simtime import TIME_MAX

# Latency multipliers are parts-per-thousand integers (core/faults.py
# LAT_SCALE): inflation stays pure i64 math in-jit. The import is
# DEFERRED (function-level, like this module's jnp imports): core's
# __init__ pulls in core.engine, which imports this module at load —
# a top-level core import here would make `import shadow_tpu.net.fluid`
# crash with a partially-initialized-module ImportError whenever it is
# the process's first shadow_tpu import (tools/net_report.py's fluid
# branch is exactly that entry point).


class FluidParams(NamedTuple):
    """Device-side compiled fluid schedule (EngineParams.fluid). All
    arrays are replicated — classes and links are global objects, like
    the engine's routing tables."""

    src_zone: Any  # i32[K] class source link (graph-node index)
    dst_zone: Any  # i32[K] class destination link
    demand: Any  # f64[K] offered demand while active, BYTES per second
    win_start: Any  # i64[K] activity window start (ns)
    win_end: Any  # i64[K] activity window end (ns)
    capacity: Any  # f64[N] per-link capacity, BYTES per second


@dataclasses.dataclass(frozen=True)
class FluidSchedule:
    """compile_fluid result: the static dims/knobs the EngineConfig
    needs plus the compiled arrays (None when no class is declared)."""

    classes: int  # K (0 = no fluid plumbing traced in)
    links: int  # N
    tau_ns: int
    util_threshold: float
    loss_max: float
    lat_max_x1000: int
    seed: int
    params: FluidParams | None

    @property
    def active(self) -> bool:
        return self.classes > 0


class FluidState(NamedTuple):
    """The fluid plane's carry lanes (SimState.fluid), registered in
    core/lanes.py (`fluid.rates` / `fluid.link_util`, float64) so the
    lane registry, shadowlint, the HBM byte model, and checkpoint
    save/restore all see them. Replicated across the mesh: every shard
    computes the identical global ODE from psum'd inputs."""

    rates: Any  # f64[K] current per-class carried rate, bytes/s
    link_util: Any  # f64[N] per-link offered utilization (may exceed 1)


def make_fluid_state(classes: int, links: int) -> FluidState:
    import jax.numpy as jnp

    return FluidState(
        rates=jnp.zeros((classes,), jnp.float64),
        link_util=jnp.zeros((links,), jnp.float64),
    )


# ---------------------------------------------------------------- compile


def compile_fluid(
    fopts,
    *,
    num_links: int,
    default_seed: int = 1,
    zone_of=None,
) -> FluidSchedule:
    """FluidOptions -> FluidSchedule. Host-side numpy; deterministic in
    the config alone (the ODE needs no compile-time draws, and the
    schedule is horizon-independent — a window past this run's stop
    time simply never activates). `zone_of` maps a config zone id (GML
    node id) to a graph-node index; identity with a bounds check by
    default."""
    import jax.numpy as jnp

    from shadow_tpu.core.faults import LAT_SCALE

    if zone_of is None:
        def zone_of(z):  # noqa: E731 - simple identity resolver
            z = int(z)
            if not 0 <= z < num_links:
                raise ValueError(
                    f"fluid zone {z} out of range [0, {num_links})"
                )
            return z

    seed = default_seed if fopts.seed is None else fopts.seed
    classes = list(fopts.classes)
    sched_kw = dict(
        links=num_links,
        tau_ns=int(fopts.tau),
        util_threshold=float(fopts.util_threshold),
        loss_max=float(fopts.loss_max),
        lat_max_x1000=int(round(fopts.latency_factor_max * LAT_SCALE)),
        seed=int(seed),
    )
    if not classes:
        return FluidSchedule(classes=0, params=None, **sched_kw)
    src = np.zeros((len(classes),), np.int32)
    dst = np.zeros((len(classes),), np.int32)
    dem = np.zeros((len(classes),), np.float64)
    ws = np.zeros((len(classes),), np.int64)
    we = np.zeros((len(classes),), np.int64)
    for i, c in enumerate(classes):
        src[i] = zone_of(c.src_zone)
        dst[i] = zone_of(c.dst_zone)
        dem[i] = c.rate / 8.0  # bits/s -> bytes/s
        ws[i] = c.start
        # end 0 = open-ended (runs to the simulation horizon, whatever
        # it is — TIME_MAX keeps a window that starts past THIS run's
        # horizon legal: it simply never activates)
        we[i] = c.end if c.end else TIME_MAX
        if we[i] <= ws[i]:
            raise ValueError(
                f"fluid class {i}: window [{ws[i]}, {we[i]}) is empty"
            )
    cap_bytes = fopts.link_capacity / 8.0
    return FluidSchedule(
        classes=len(classes),
        params=FluidParams(
            src_zone=jnp.asarray(src, jnp.int32),
            dst_zone=jnp.asarray(dst, jnp.int32),
            demand=jnp.asarray(dem, jnp.float64),
            win_start=jnp.asarray(ws, jnp.int64),
            win_end=jnp.asarray(we, jnp.int64),
            capacity=jnp.full((num_links,), cap_bytes, jnp.float64),
        ),
        **sched_kw,
    )


# ---------------------------------------------------------------- jit side


def _bg_link_load(fp: FluidParams, rates, links: int):
    """Per-link background load from per-class rates: a fixed-order
    one-hot [K, N] reduction (NOT a float scatter-add — the jaxpr audit
    pins float scatter-adds as a determinism hazard)."""
    import jax.numpy as jnp

    n_idx = jnp.arange(links, dtype=jnp.int32)[None, :]  # [1, N]
    charge = (
        (fp.src_zone[:, None] == n_idx).astype(jnp.float64)
        + (fp.dst_zone[:, None] == n_idx).astype(jnp.float64)
    )  # [K, N]: a class occupies its source AND destination access link
    return jnp.sum(rates[:, None] * charge, axis=0)  # f64[N]


def fluid_advance(cfg, fp: FluidParams, st: FluidState, fg_link_bytes,
                  now, window_end, done):
    """One forward-Euler step over the committed window (module
    docstring spells out the scheme). `fg_link_bytes` is the psum'd
    i64[N] foreground byte count this round. Returns
    (FluidState', delivered_bytes i64[], dropped_bytes i64[]) with the
    state held and the deltas zeroed on the done-round (which is not a
    scheduling round, mirroring stats.rounds)."""
    import jax.numpy as jnp

    n = cfg.fluid_links
    dt_ns = jnp.maximum(window_end - now, jnp.int64(0))
    dt_s = dt_ns.astype(jnp.float64) * 1e-9
    live = dt_s > 0.0

    active = (fp.win_start <= now) & (now < fp.win_end)
    demand = jnp.where(active, fp.demand, jnp.float64(0.0))
    alpha = jnp.minimum(dt_s / (cfg.fluid_tau_ns * 1e-9), 1.0)
    r = st.rates + alpha * (demand - st.rates)

    # foreground-first capacity: the packet plane's measured bytes this
    # round subtract from what the background may carry
    fg_rate = jnp.where(
        live, fg_link_bytes.astype(jnp.float64) / jnp.maximum(dt_s, 1e-18),
        jnp.float64(0.0),
    )
    bg = _bg_link_load(fp, r, n)
    avail = jnp.maximum(fp.capacity - fg_rate, 0.0)
    share = jnp.where(bg > avail, avail / jnp.maximum(bg, 1e-18), 1.0)
    # per-class bottleneck share: min over its two links (gathers from a
    # tiny replicated [N] table with trace-time-constant index arrays)
    g = jnp.minimum(share[fp.src_zone], share[fp.dst_zone])
    carried = r * g
    util = jnp.where(
        fp.capacity > 0.0, (bg + fg_rate) / fp.capacity, jnp.float64(0.0)
    )

    delivered = jnp.floor(jnp.sum(carried) * dt_s).astype(jnp.int64)
    dropped = jnp.floor(jnp.sum(r - carried) * dt_s).astype(jnp.int64)
    hold = done | ~live
    new = FluidState(
        rates=jnp.where(hold, st.rates, carried),
        link_util=jnp.where(hold, st.link_util, util),
    )
    z = jnp.int64(0)
    return new, jnp.where(hold, z, delivered), jnp.where(hold, z, dropped)


def fluid_host_effects(cfg, fp: FluidParams, st: FluidState, node_idx):
    """Per-host coupling factors at round start, from the PREVIOUS
    round's ODE state: (loss f32[H], lat_x1000 i64[H]).

    Both ramp linearly from `util_threshold` (no effect) to utilization
    1.0 (full effect: loss_max / lat_max) and saturate beyond, and both
    are gated on background load actually being present on the host's
    access link — a fluid block at zero demand (rates identically 0)
    therefore yields loss 0.0 and multiplier exactly LAT_SCALE on every
    host, leaving every downstream value bit-identical to the fluid-off
    program. The multiplier is >= LAT_SCALE by construction: inflation
    only (the conservative-lookahead argument in the module docstring).
    """
    import jax.numpy as jnp

    from shadow_tpu.core.faults import LAT_SCALE

    n = cfg.fluid_links
    idx = jnp.clip(node_idx.astype(jnp.int32), 0, n - 1)
    bg = _bg_link_load(fp, st.rates, n)  # f64[N]
    util_h = st.link_util[idx]  # [H] gather from a tiny replicated table
    bg_h = bg[idx] > 0.0
    thr = cfg.fluid_util_threshold
    over = jnp.clip((util_h - thr) / max(1.0 - thr, 1e-9), 0.0, 1.0)
    over = jnp.where(bg_h, over, jnp.float64(0.0))
    loss = (over * cfg.fluid_loss_max).astype(jnp.float32)
    lat = jnp.int64(LAT_SCALE) + jnp.floor(
        over * (cfg.fluid_lat_max_x1000 - LAT_SCALE)
    ).astype(jnp.int64)
    return loss, jnp.maximum(lat, jnp.int64(LAT_SCALE))


def fluid_send_uniform(seed: int, host_gid, ctr):
    """float32 in [0, 1): pure counter draw keyed on (fluid seed, global
    host id, the host's emission counter) — unique per send, invariant
    to mesh shape, and side-effect-free on the RNG lanes. The jnp mirror
    of core/faults.fault_uniform, built from the SAME pieces: the stride
    constants come from core/faults (one keying recipe) and the mix from
    ops/rng._splitmix64 (one jnp splitmix) — no third copy to drift."""
    import jax.numpy as jnp

    from shadow_tpu.core.faults import _CTR_STRIDE, _HOST_STRIDE
    from shadow_tpu.ops.rng import _splitmix64

    x = (
        jnp.uint64(seed & (2**64 - 1))
        + host_gid.astype(jnp.uint64) * jnp.uint64(int(_HOST_STRIDE))
        + ctr.astype(jnp.uint64) * jnp.uint64(int(_CTR_STRIDE))
    )
    _, z = _splitmix64(x)
    _, u = _splitmix64(z)
    return ((u >> jnp.uint64(40)).astype(jnp.float32)) * jnp.float32(
        1.0 / (1 << 24)
    )


# ---------------------------------------------------------------- reports


def assemble_fluid_report(*, stats, fluid_state, cfg) -> dict:
    """The ONE driver-side assembly of the sim-stats `fluid{}` block
    (the netobs assemble_network_report pattern): sim.py, bench.py, and
    tools read this shape, so it cannot drift between exporters.
    `stats` is the device-got Stats tuple — the gated fl_bg_* lanes are
    read here."""
    from shadow_tpu.core.faults import LAT_SCALE

    bg_bytes = int(np.asarray(stats.fl_bg_bytes))
    bg_dropped = int(np.asarray(stats.fl_bg_dropped))
    offered = bg_bytes + bg_dropped
    util = [round(float(u), 4) for u in np.asarray(fluid_state.link_util)]
    return {
        "classes": int(cfg.fluid_classes),
        "links": int(cfg.fluid_links),
        "bg_bytes": bg_bytes,
        "bg_dropped": bg_dropped,
        "delivered_share": (
            round(bg_bytes / offered, 4) if offered else None
        ),
        "link_util_final": util,
        "link_util_max": max(util) if util else 0.0,
        "loss_max": float(cfg.fluid_loss_max),
        "latency_factor_max": cfg.fluid_lat_max_x1000 / LAT_SCALE,
    }


def bench_fluid_block(report_fluid: dict) -> dict:
    """The compact `fluid{}` block BENCH rows carry (and
    tools/bench_compare.py diffs): background byte/drop coverage plus
    the hot-link utilization."""
    return {
        "bg_bytes": report_fluid.get("bg_bytes", 0),
        "bg_dropped": report_fluid.get("bg_dropped", 0),
        "delivered_share": report_fluid.get("delivered_share"),
        "link_util_max": report_fluid.get("link_util_max", 0.0),
    }


def background_share_sentence(fluid_block: dict, fg_bytes: int | None) -> str:
    """The net_report verdict's background-share sentence: how much of
    the modeled traffic rode the fluid plane (vs the packet-exact
    foreground, when the flow ledger measured it)."""
    bg = int(fluid_block.get("bg_bytes", 0))
    drp = int(fluid_block.get("bg_dropped", 0))
    if fg_bytes:
        total = bg + fg_bytes
        share = bg / total if total else 0.0
        return (
            f"background fluid plane carried {bg} bytes "
            f"({share * 100:.1f}% of all modeled bytes vs {fg_bytes} "
            f"packet-exact foreground bytes), {drp} dropped at congestion"
        )
    return (
        f"background fluid plane carried {bg} bytes "
        f"({drp} dropped at congestion); no foreground flow ledger to "
        f"compare against"
    )
