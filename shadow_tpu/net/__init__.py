"""Network plane: bandwidth token buckets, CoDel AQM, path latency/loss.

Reference components rebuilt here (vectorized over all hosts, device-side):
  - src/main/network/relay/ — token-bucket bandwidth enforcement
    (relay/mod.rs:276-319, token_bucket.rs)
  - src/main/network/router/codel_queue.rs — RFC-8289 CoDel AQM on the
    per-host ingress path
  - src/main/core/worker.rs:330-425 — Worker::send_packet latency/loss lookup

Where the reference *blocks a relay task* and reschedules it on token refill,
the TPU build computes departure times analytically from the same quantized
refill schedule — identical observable packet timing, no control flow.
"""

from shadow_tpu.net.tokenbucket import TBParams, TBState, tb_init, tb_conforming_remove
from shadow_tpu.net.codel import CodelState, codel_init, codel_on_packet, TARGET_NS, INTERVAL_NS

__all__ = [
    "TBParams",
    "TBState",
    "tb_init",
    "tb_conforming_remove",
    "CodelState",
    "codel_init",
    "codel_on_packet",
    "TARGET_NS",
    "INTERVAL_NS",
]
