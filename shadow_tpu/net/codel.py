"""CoDel active queue management, one control-law lane per host ingress.

Reference: src/main/network/router/codel_queue.rs — RFC-8289 CoDel guarding
each host's upstream router queue, with TARGET = 10 ms standing delay and
INTERVAL = 100 ms (codel_queue.rs:23,28), drop_next = now + INTERVAL/sqrt(count)
computed in f64 and rounded (codel_queue.rs:286-290), and re-entry hysteresis
`now - drop_next < 16*INTERVAL` (codel_queue.rs:279).

TPU recast: the queue itself is implicit — packets flow through the ingress
token bucket, and a packet's *standing delay* (sojourn) is its bucket delay
`depart - arrival`. The control law runs once per packet at arrival pop, in
arrival order (identical to dequeue order through the FIFO bucket), as a
branch-free state update over all hosts. Deviation from the reference, by
design: the `total_bytes_stored <= MTU` backlog exemption (codel_queue.rs:238)
is subsumed by the sojourn test — an empty implicit queue means zero bucket
delay, which is always below TARGET; there is no materialized byte count.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from shadow_tpu.config.units import parse_time_ns

TARGET_NS = parse_time_ns("10 ms")
INTERVAL_NS = parse_time_ns("100 ms")


class CodelState(NamedTuple):
    first_above: Array  # i64[H]; 0 = standing delay not above TARGET
    drop_next: Array  # i64[H] next scheduled drop time while dropping
    count: Array  # i32[H] drops in current dropping interval
    dropping: Array  # bool[H]


def codel_init(num_hosts: int) -> CodelState:
    return CodelState(
        first_above=jnp.zeros((num_hosts,), jnp.int64),
        drop_next=jnp.zeros((num_hosts,), jnp.int64),
        count=jnp.zeros((num_hosts,), jnp.int32),
        dropping=jnp.zeros((num_hosts,), bool),
    )


def _control_law(now, count) -> Array:
    """now + INTERVAL/sqrt(count), f64-rounded exactly like codel_queue.rs:286-290."""
    c = jnp.maximum(count, 1).astype(jnp.float64)
    return now + jnp.round(jnp.float64(INTERVAL_NS) / jnp.sqrt(c)).astype(jnp.int64)


def codel_on_packet(
    state: CodelState, now, sojourn_ns, mask
) -> tuple[CodelState, Array]:
    """Run the CoDel law for one packet per host where `mask`.

    `now` i64[H] = arrival pop time; `sojourn_ns` i64[H] = ingress queueing
    delay the packet will experience. Returns (state', drop[H] bool).
    """
    now = jnp.asarray(now, jnp.int64)
    sojourn = jnp.asarray(sojourn_ns, jnp.int64)
    mask = jnp.asarray(mask, bool)

    below = sojourn < TARGET_NS

    # --- tracking first_above_time (codel_queue.rs:238-262)
    fa_unset = state.first_above == 0
    new_first_above = jnp.where(
        below, 0, jnp.where(fa_unset, now + INTERVAL_NS, state.first_above)
    )
    ok_to_drop = ~below & ~fa_unset & (now >= state.first_above)

    # --- dropping state machine
    dropping = state.dropping
    count = state.count
    drop_next = state.drop_next
    drop = jnp.zeros_like(mask)

    # leave dropping mode when delay dips below target
    leave = dropping & ~ok_to_drop
    # while dropping: drop each time we cross drop_next
    fire = dropping & ok_to_drop & (now >= drop_next)
    count_f = count + 1
    drop_next_f = _control_law(drop_next, count_f)

    # enter dropping mode whenever ok_to_drop while in store mode
    # (codel_queue.rs:151-171); the 16*INTERVAL recency test only decides
    # whether the drop count resumes decayed or restarts at 1 (:271-290)
    enter = ~dropping & ok_to_drop
    recent = (now - drop_next) < 16 * INTERVAL_NS
    count_e = jnp.where(recent & (count > 2), count - 2, 1).astype(jnp.int32)
    drop_next_e = _control_law(now, count_e)

    new_dropping = jnp.where(leave, False, jnp.where(enter, True, dropping))
    new_count = jnp.where(fire, count_f, jnp.where(enter, count_e, count))
    new_drop_next = jnp.where(fire, drop_next_f, jnp.where(enter, drop_next_e, drop_next))
    drop = fire | enter

    return (
        CodelState(
            first_above=jnp.where(mask, new_first_above, state.first_above),
            drop_next=jnp.where(mask, new_drop_next, state.drop_next),
            count=jnp.where(mask, new_count, state.count),
            dropping=jnp.where(mask, new_dropping, state.dropping),
        ),
        drop & mask,
    )
