"""Token-bucket bandwidth limiter, one lane per host.

Reference: src/main/network/relay/token_bucket.rs (277 LoC) + relay/mod.rs:
276-319 — each host's uplink/downlink is a bucket refilled every 1 ms with a
burst allowance, and the relay forwards packets only when tokens conform,
rescheduling itself at the next refill otherwise.

TPU recast: the refill schedule is quantized exactly like the reference
(discrete intervals), but instead of blocking/rescheduling a relay task we
compute each packet's conforming departure time in closed form:

    tokens(t)   = min(capacity, tokens + elapsed_intervals * refill)
    depart      = t                          if tokens >= size
                = (itv(t) + k) * interval    with k = ceil((size-tokens)/refill)

All integer i64 math (bits, ns) — bit-deterministic on any backend. A
`refill == 0` lane means "unshaped" (no bandwidth configured) and passes
packets through untouched.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array


class TBParams(NamedTuple):
    """Pure-array params so the pytree shards cleanly under shard_map; the
    refill quantum (reference: 1 ms) is passed statically to the ops."""

    capacity: Array  # i64[H] burst size, bits
    refill: Array  # i64[H] bits added per interval; 0 = unshaped


class TBState(NamedTuple):
    tokens: Array  # i64[H] bits available at interval boundary `last_itv`
    last_itv: Array  # i64[H] interval index of last accounting


def tb_init(params: TBParams) -> TBState:
    """Buckets start full (token_bucket.rs: initialized to capacity).

    `tokens` is a fresh buffer (not an alias of params.capacity): engine state
    is donated to the jitted step while params are not."""
    return TBState(
        tokens=params.capacity + jnp.zeros_like(params.capacity),
        last_itv=jnp.zeros_like(params.capacity),
    )


def tb_conforming_remove(
    state: TBState, params: TBParams, interval_ns: int, t_ns, size_bits, mask
) -> tuple[TBState, Array]:
    """Charge `size_bits` per host where `mask`; return (state', depart_ns[H]).

    depart >= t_ns is the time the packet conforms. Packets larger than the
    burst capacity still depart after enough whole intervals (the reference
    grants an MTU burst allowance for the same reason: relay/mod.rs:276-319).

    A lane is FIFO (the reference relay forwards in queue order): accounting
    never moves backward, so a packet arriving while a predecessor is still
    waiting on refill is charged from the predecessor's boundary
    (`last_itv`), not from its own arrival interval — its stored tokens only
    exist at that boundary.
    """
    t_ns = jnp.asarray(t_ns, jnp.int64)
    size_bits = jnp.asarray(size_bits, jnp.int64)
    itv = jnp.maximum(t_ns // interval_ns, state.last_itv)
    elapsed = itv - state.last_itv
    # saturating refill (cap), computed without i64 overflow for huge gaps
    gain = jnp.where(
        elapsed < (1 << 20), elapsed * params.refill, params.capacity
    )
    tokens = jnp.minimum(params.capacity, state.tokens + gain)

    conforms = tokens >= size_bits
    deficit = jnp.maximum(size_bits - tokens, 0)
    refill_safe = jnp.maximum(params.refill, 1)
    k = (deficit + refill_safe - 1) // refill_safe  # ceil, >= 1 when deficit > 0
    depart_wait = (itv + k) * interval_ns

    shaped = params.refill > 0
    # conforming depart: immediate, unless the tokens live at a future
    # boundary inherited from a still-waiting predecessor
    depart_now = jnp.maximum(t_ns, itv * interval_ns)
    depart = jnp.where(shaped & ~conforms, depart_wait, jnp.where(shaped, depart_now, t_ns))
    new_tokens = jnp.where(conforms, tokens - size_bits, tokens + k * params.refill - size_bits)
    new_itv = jnp.where(conforms, itv, itv + k)

    upd = jnp.asarray(mask, bool) & shaped
    return (
        TBState(
            tokens=jnp.where(upd, new_tokens, state.tokens),
            last_itv=jnp.where(upd, new_itv, state.last_itv),
        ),
        depart,
    )
