"""Network graph: GML parse, shortest-path routing, IP assignment.

Reference components being rebuilt (not ported):
  - src/lib/gml-parser (Rust, 542 LoC): GML tokenizer/parser.
  - src/main/network/graph/mod.rs:134 `NetworkGraph::parse`;
    :183-228 parallel all-pairs Dijkstra -> `PathProperties{latency_ns,
    packet_loss}`; :230-253 direct-edge mode; :354-427 `IpAssignment`;
    :430-493 `RoutingInfo`.
  - configuration.rs GraphOptions "1_gbit_switch" built-in graph.

TPU-first recast: routing is materialized as dense node-by-node tables
(latency i64[N,N], loss f32[N,N]) that replicate onto every mesh shard so a
packet send is two gathers (src node, dst node) inside the vectorized
microstep — the reference instead does a HashMap lookup per packet
(worker.rs:392). Unreachable pairs hold latency -1 (the engine counts these
as pkts_unreachable; the reference errors at setup for disconnected graphs).

All-pairs shortest paths run once at setup on CPU via scipy's Dijkstra (the
reference uses rayon-parallel petgraph Dijkstra, graph/mod.rs:190-208); path
packet-loss composes as 1 - prod(1 - edge_loss) along the chosen path,
recovered from the predecessor matrix in topological (distance) order.
"""

from __future__ import annotations

import dataclasses
import ipaddress
import re
from typing import Any

import numpy as np

from shadow_tpu.config.options import ConfigError
from shadow_tpu.config.units import parse_bits_per_sec, parse_time_ns, TimeUnit


class GraphError(ConfigError):
    """Graph problems are config problems: the CLI's exit-2 contract covers
    both (reference exits with a config error for bad graphs too)."""


# --------------------------------------------------------------------------
# GML parsing (reference: src/lib/gml-parser)
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    \s*(?:
        (?P<comment>\#[^\n]*)
      | (?P<lbracket>\[)
      | (?P<rbracket>\])
      | (?P<string>"(?:[^"\\]|\\.)*")
      | (?P<number>[+-]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)
      | (?P<key>[A-Za-z_][A-Za-z0-9_]*)
    )""",
    re.VERBOSE,
)


def _tokenize_gml(text: str):
    pos = 0
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            if text[pos:].strip() == "":
                return
            raise GraphError(f"GML parse error at offset {pos}: {text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        yield m.lastgroup, m.group(m.lastgroup)


def _parse_gml_value(tokens, tok_type, tok):
    if tok_type == "lbracket":
        return _parse_gml_list(tokens)
    if tok_type == "string":
        return tok[1:-1].replace('\\"', '"').replace("\\\\", "\\")
    if tok_type == "number":
        if re.fullmatch(r"[+-]?\d+", tok):
            return int(tok)
        return float(tok)
    if tok_type == "key":  # bare words (GML allows unquoted values rarely)
        return tok
    raise GraphError(f"unexpected GML token {tok!r}")


def _parse_gml_list(tokens, *, toplevel: bool = False) -> list[tuple[str, Any]]:
    """A GML record is an ordered multimap: repeated keys (node, edge) stack.

    Only the implicit top-level record may end at EOF; a nested record that
    runs out of tokens is truncated input and must error, not silently drop
    everything after the cut."""
    items: list[tuple[str, Any]] = []
    for tok_type, tok in tokens:
        if tok_type == "rbracket":
            if toplevel:
                raise GraphError("unmatched ']' at GML top level")
            return items
        if tok_type != "key":
            raise GraphError(f"expected key in GML record, got {tok!r}")
        try:
            vt, vv = next(tokens)
        except StopIteration:
            raise GraphError(f"GML key {tok!r} has no value") from None
        items.append((tok, _parse_gml_value(tokens, vt, vv)))
    if not toplevel:
        raise GraphError("truncated GML: record not closed with ']'")
    return items


def parse_gml(text: str) -> dict[str, Any]:
    """Parse GML text into {"directed": bool, "nodes": [...], "edges": [...]}.

    Node/edge attributes keep their GML keys (id, source, target,
    host_bandwidth_down/up, latency, packet_loss, label, ...).
    """
    tokens = _tokenize_gml(text)
    top = _parse_gml_list(tokens, toplevel=True)  # implicit outer record
    graph_rec = None
    for k, v in top:
        if k == "graph":
            graph_rec = v
            break
    if graph_rec is None:
        raise GraphError("GML text has no `graph [...]` record")
    directed = False
    nodes, edges = [], []
    for k, v in graph_rec:
        if k == "directed":
            directed = bool(v)
        elif k == "node":
            nodes.append(dict(v))
        elif k == "edge":
            edges.append(dict(v))
    if not nodes:
        raise GraphError("graph has no nodes")
    return {"directed": directed, "nodes": nodes, "edges": edges}


# --------------------------------------------------------------------------
# The network graph + routing tables
# --------------------------------------------------------------------------

# built-in one-node graph (reference GraphOptions "1_gbit_switch")
ONE_GBIT_SWITCH_GML = """
graph [
  directed 0
  node [
    id 0
    host_bandwidth_down "1 Gbit"
    host_bandwidth_up "1 Gbit"
  ]
  edge [
    source 0
    target 0
    latency "1 ms"
    packet_loss 0.0
  ]
]
"""


@dataclasses.dataclass
class NetworkGraph:
    """Parsed graph + routing tables (reference NetworkGraph + RoutingInfo).

    node_ids: original GML ids in index order (configs reference these).
    lat_ns[N, N]: path latency; -1 where unreachable.
    loss[N, N]: path packet-loss probability in [0, 1).
    bw_down_bits / bw_up_bits [N]: per-node host bandwidth defaults (0 = none
    given; per-host config overrides win, sim_config.rs:203).
    """

    node_ids: np.ndarray  # i64[N] original GML ids
    lat_ns: np.ndarray  # i64[N, N]
    loss: np.ndarray  # f32[N, N]
    jitter_ns: np.ndarray  # i64[N, N] path jitter amplitude (0 = none)
    bw_down_bits: np.ndarray  # i64[N]
    bw_up_bits: np.ndarray  # i64[N]
    directed: bool

    def __post_init__(self):
        self._index_of = {int(g): i for i, g in enumerate(self.node_ids)}

    @property
    def num_nodes(self) -> int:
        return len(self.node_ids)

    def node_index(self, gml_id: int) -> int:
        try:
            return self._index_of[int(gml_id)]
        except KeyError:
            raise GraphError(f"config references unknown graph node id {gml_id}") from None

    @property
    def min_latency_ns_opt(self) -> int | None:
        """Smallest reachable path latency, or None for a graph with no
        routable pairs at all (legal for timer-only workloads — the engine
        then runs on the runahead floor). The synthetic zero diagonal is
        already excluded at build time (no-self-loop diagonals are -1)."""
        mask = self.lat_ns >= 0
        if not mask.any():
            return None
        eff = self.lat_ns[mask] - self.jitter_ns[mask]
        return int(eff.min())

    @property
    def min_latency_ns(self) -> int:
        """Smallest reachable path latency — the conservative-PDES lookahead
        bound (reference runahead.rs:5-13: round length <= min latency).
        With jitter the bound is the smallest latency MINUS its jitter
        amplitude (a jittered packet can arrive that early)."""
        v = self.min_latency_ns_opt
        if v is None:
            raise GraphError(
                "graph has no routable node pairs (a node needs a self-loop "
                "edge for same-node traffic, or an edge to another node)"
            )
        return v

    @property
    def has_jitter(self) -> bool:
        return bool((self.jitter_ns > 0).any())


def _edge_arrays(g: dict, index_of: dict[int, int]):
    n = len(index_of)
    lat = np.full((n, n), -1, np.int64)
    sur = np.zeros((n, n), np.float64)  # survival probability per direct edge
    jit = np.zeros((n, n), np.int64)
    for e in g["edges"]:
        try:
            s = index_of[int(e["source"])]
            d = index_of[int(e["target"])]
        except KeyError as k:
            raise GraphError(f"edge references unknown node {k}") from None
        if "latency" not in e:
            raise GraphError(f"edge {e.get('source')}->{e.get('target')} missing latency")
        l_ns = parse_time_ns(e["latency"], TimeUnit.MS)
        if l_ns <= 0:
            raise GraphError("edge latency must be > 0 (conservative lookahead)")
        p_loss = float(e.get("packet_loss", 0.0))
        if not (0.0 <= p_loss < 1.0):
            raise GraphError(f"packet_loss {p_loss} outside [0, 1)")
        # jitter (reference graph/mod.rs:68,87-92 parses it; here it is also
        # APPLIED: each packet draws latency uniformly in [lat-j, lat+j])
        j_ns = parse_time_ns(e["jitter"], TimeUnit.MS) if "jitter" in e else 0
        if not (0 <= j_ns < l_ns):
            raise GraphError(
                f"edge jitter {j_ns}ns must be in [0, latency) — a packet "
                f"must never arrive before the conservative lookahead bound"
            )
        pairs = [(s, d)] if g["directed"] else [(s, d), (d, s)]
        for a, b in pairs:
            # parallel edges: keep the lowest-latency one (deterministic)
            if lat[a, b] < 0 or l_ns < lat[a, b]:
                lat[a, b] = l_ns
                sur[a, b] = 1.0 - p_loss
                jit[a, b] = j_ns
    return lat, sur, jit


def _shortest_paths(lat: np.ndarray, sur: np.ndarray, jit: np.ndarray):
    """All-pairs shortest path by latency; compose survival (product) and
    jitter (sum) along the chosen path via the predecessor matrix
    (reference graph/mod.rs:183-228)."""
    from scipy.sparse import csr_matrix
    from scipy.sparse.csgraph import dijkstra

    n = lat.shape[0]
    mask = lat >= 0
    w = np.where(mask, lat, 0).astype(np.float64)
    graph = csr_matrix((w[mask], np.nonzero(mask)), shape=(n, n))
    dist, pred = dijkstra(graph, directed=True, return_predecessors=True)

    # self paths: the reference REQUIRES a self-loop on every node and routes
    # node-to-itself traffic over it (graph/mod.rs:210-216, get_edge_weight
    # errors without one). Dijkstra's synthetic zero diagonal must NOT leak
    # into the tables: a free self path would make min_latency_ns (the
    # conservative lookahead bound) collapse to 0 on every multi-node graph.
    # Deviation from the reference: instead of erroring at parse time for a
    # missing self-loop, the diagonal becomes unreachable (-1) and sim setup
    # rejects configs that actually place >= 2 hosts on such a node.
    self_edge = np.diag(mask)
    dist_ns = np.where(np.isinf(dist), -1, np.rint(dist)).astype(np.int64)
    path_sur = np.zeros((n, n), np.float64)
    # walk nodes per source in increasing-distance order: survival follows the
    # predecessor tree (optimal substructure), fully deterministic because
    # scipy's dijkstra tie-breaks are fixed for a fixed input.
    path_jit = np.zeros((n, n), np.int64)
    order = np.argsort(dist, axis=1, kind="stable")
    for s in range(n):
        ps = path_sur[s]
        pj = path_jit[s]
        ps[s] = 1.0
        for j in order[s]:
            p = pred[s, j]
            if p < 0:
                continue  # unreachable or the source itself
            ps[j] = ps[p] * sur[p, j]
            pj[j] = pj[p] + jit[p, j]
    for s in range(n):
        if self_edge[s]:
            dist_ns[s, s] = lat[s, s]
            path_sur[s, s] = sur[s, s]
            path_jit[s, s] = jit[s, s]
        else:
            dist_ns[s, s] = -1  # no self-loop: same-node pairs cannot route
            path_sur[s, s] = 0.0
            path_jit[s, s] = 0
    return dist_ns, path_sur, path_jit


def _direct_paths(lat: np.ndarray, sur: np.ndarray, jit: np.ndarray):
    """use_shortest_path=false: only direct edges route (graph/mod.rs:230-253)."""
    return lat.copy(), sur.copy(), jit.copy()


def _node_bandwidth(nd: dict, key: str) -> int:
    v = nd.get(key)
    return parse_bits_per_sec(v) if v is not None else 0


def build_graph(
    gml_text: str, *, use_shortest_path: bool = True
) -> NetworkGraph:
    g = parse_gml(gml_text)
    ids = [int(nd["id"]) for nd in g["nodes"]]
    if len(set(ids)) != len(ids):
        raise GraphError("duplicate node ids in graph")
    index_of = {gid: i for i, gid in enumerate(ids)}
    lat, sur, jit = _edge_arrays(g, index_of)
    if use_shortest_path:
        path_lat, path_sur, path_jit = _shortest_paths(lat, sur, jit)
    else:
        path_lat, path_sur, path_jit = _direct_paths(lat, sur, jit)
    loss = np.where(path_lat >= 0, 1.0 - path_sur, 0.0).astype(np.float32)
    return NetworkGraph(
        node_ids=np.asarray(ids, np.int64),
        lat_ns=path_lat,
        loss=loss,
        jitter_ns=path_jit,
        bw_down_bits=np.asarray(
            [_node_bandwidth(nd, "host_bandwidth_down") for nd in g["nodes"]], np.int64
        ),
        bw_up_bits=np.asarray(
            [_node_bandwidth(nd, "host_bandwidth_up") for nd in g["nodes"]], np.int64
        ),
        directed=bool(g["directed"]),
    )


def load_graph(options) -> NetworkGraph:
    """Build from config GraphOptions (reference load_network_graph,
    graph/mod.rs:495-530; xz-compressed files supported like GraphSource)."""
    if options.type == "1_gbit_switch":
        return build_graph(ONE_GBIT_SWITCH_GML, use_shortest_path=options.use_shortest_path)
    if options.type != "gml":
        raise GraphError(f"unknown graph type {options.type!r}")
    if options.inline is not None:
        text = options.inline
    elif options.path is not None:
        if options.path.endswith(".xz"):
            import lzma

            with lzma.open(options.path, "rt") as f:
                text = f.read()
        else:
            with open(options.path) as f:
                text = f.read()
    else:
        raise GraphError("graph.type=gml needs `path` or `inline`")
    return build_graph(text, use_shortest_path=options.use_shortest_path)


# --------------------------------------------------------------------------
# IP assignment (reference graph/mod.rs:354-427)
# --------------------------------------------------------------------------


class IpAssignment:
    """Sequential 11.0.0.0/8 assignment skipping .0 and .255 octets like the
    reference, with manual addresses honored and collisions rejected."""

    def __init__(self, base: str = "11.0.0.0"):
        self._next = int(ipaddress.IPv4Address(base)) + 1
        self._by_ip: dict[int, int] = {}  # ip -> host index
        self._by_host: dict[int, int] = {}

    def assign_manual(self, host: int, ip: str) -> int:
        addr = int(ipaddress.IPv4Address(ip))
        if addr in self._by_ip:
            raise GraphError(f"duplicate ip_addr {ip}")
        self._by_ip[addr] = host
        self._by_host[host] = addr
        return addr

    def assign(self, host: int) -> int:
        while True:
            addr = self._next
            self._next += 1
            if addr & 0xFF in (0, 255):  # skip network/broadcast-looking octets
                continue
            if addr not in self._by_ip:
                self._by_ip[addr] = host
                self._by_host[host] = addr
                return addr

    def ip_of(self, host: int) -> str:
        return str(ipaddress.IPv4Address(self._by_host[host]))

    def host_of(self, ip: str) -> int:
        return self._by_ip[int(ipaddress.IPv4Address(ip))]
