"""DNS / addressing registry.

Reference: `src/main/routing/dns.c` (230 LoC — global name<->IP registry
with per-host hostname files) and `address.c`; lookups surface to managed
code via `shadow_hostname_to_addr_ipv4` (handler/mod.rs:513-517) and the
shim's addrinfo emulation (shim_api_addrinfo.c).
"""

from __future__ import annotations

import ipaddress


class DnsError(Exception):
    pass


class Dns:
    def __init__(self):
        self._by_name: dict[str, str] = {}
        self._by_ip: dict[str, str] = {}

    def register(self, name: str, ip: str):
        ipaddress.ip_address(ip)  # validates
        if name in self._by_name and self._by_name[name] != ip:
            raise DnsError(f"hostname {name!r} already registered")
        if ip in self._by_ip and self._by_ip[ip] != name:
            raise DnsError(f"address {ip} already registered to {self._by_ip[ip]!r}")
        self._by_name[name] = ip
        self._by_ip[ip] = name

    def resolve(self, name: str) -> str | None:
        """name (or dotted-quad literal) -> IP, like getaddrinfo."""
        if name in self._by_name:
            return self._by_name[name]
        try:
            return str(ipaddress.ip_address(name))
        except ValueError:
            return None

    def reverse(self, ip: str) -> str | None:
        return self._by_ip.get(ip)

    def hosts_file(self) -> str:
        """An /etc/hosts rendering (the reference writes per-host hostname
        files for managed processes)."""
        lines = ["127.0.0.1 localhost"]
        for name in sorted(self._by_name):
            lines.append(f"{self._by_name[name]} {name}")
        return "\n".join(lines) + "\n"

    def __len__(self) -> int:
        return len(self._by_name)
