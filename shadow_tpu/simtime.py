"""Simulation / emulated time.

Reference: src/lib/shadow-shim-helper-rs/src/{simulation_time.rs,emulated_time.rs}.
SimulationTime = ns since simulation start. EmulatedTime = ns since the
emulation epoch 2000-01-01T00:00:00 UTC (emulated_time.rs:28-48), which is what
managed processes observe via clock_gettime.

All device-side times are int64 nanoseconds of *simulation* time; TIME_MAX is
the empty-slot / +inf sentinel used by the event-queue kernels.
"""

import datetime

NS_PER_USEC = 1_000
NS_PER_MSEC = 1_000_000
NS_PER_SEC = 1_000_000_000

# i64 max. Used as "no event" sentinel in device arrays.
TIME_MAX = (1 << 63) - 1

# 2000-01-01T00:00:00Z as unix seconds (reference emulated_time.rs:28-48).
EMUTIME_EPOCH_UNIX_SEC = int(
    datetime.datetime(2000, 1, 1, tzinfo=datetime.timezone.utc).timestamp()
)


def sim_to_emulated_ns(sim_ns: int) -> int:
    """SimulationTime (ns since sim start) -> EmulatedTime (ns since epoch)."""
    return EMUTIME_EPOCH_UNIX_SEC * NS_PER_SEC + sim_ns


def emulated_to_unix_ns(emu_ns: int) -> int:
    """EmulatedTime -> unix ns, for pcap timestamps / strace-style logs."""
    return emu_ns


def fmt_time(sim_ns: int) -> str:
    """Human display like the reference status bar (hh:mm:ss.mmm)."""
    s, ns = divmod(int(sim_ns), NS_PER_SEC)
    h, rem = divmod(s, 3600)
    m, sec = divmod(rem, 60)
    return f"{h:02d}:{m:02d}:{sec:02d}.{ns // NS_PER_MSEC:03d}"
