"""Python side of the native managed-process plane.

Reference counterpart: `ManagedThread` (managed_thread.rs:96-324 — spawn
with preload injection, the per-thread IPC channel, the resume loop
receiving `Syscall` events and replying Complete/DoNative) plus the syscall
handler dispatch (host/syscall/handler/mod.rs) and `MemoryCopier`
(process_vm_readv/writev, memory_manager/memory_copier.rs). The C++ shim
(`native/shim.cpp`) is the in-process half.

A `NativeProcess` plugs into a `CpuHost` exactly like a coroutine
`Process`: it advances only when the host event loop drives it, real time
never leaks in (the shared `sim_time_ns` is the only clock the child
sees), and blocking syscalls (nanosleep) park it on host-scheduled
wakeups. Syscalls the simulator does not emulate are answered
MSG_SYSCALL_NATIVE and execute in the child (the reference's
pass-through/regular-file policy).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import fcntl as fcntl_mod
import mmap
import os
import struct
import subprocess
import tempfile
import time

# ---- layout mirror of native/ipc.h ----------------------------------------

MSG_START = 1
MSG_SYSCALL = 2
MSG_START_OK = 3
MSG_SYSCALL_COMPLETE = 4
MSG_SYSCALL_NATIVE = 5
MSG_THREAD_START = 6
MSG_CLONE_DONE = 7
MSG_RUN_SIGNAL = 8
MSG_SIGNAL_DONE = 9

CHAN_EMPTY, CHAN_FULL, CHAN_CLOSED = 0, 1, 2

# message wire format is "<ii q 6q q" at channel offset + 8 (see ipc.h).
# One channel-pair slot per thread (slot 0 = main thread).
IPC_MAX_THREADS = 32
DOORBELL_OFF = 8
THREADS_OFF = 16
CHANPAIR_SIZE = 160
PAIR_TO_SHIM_OFF = 80
HEAP_START_OFF = THREADS_OFF + IPC_MAX_THREADS * CHANPAIR_SIZE
# + heap_start/heap_cur (MemoryMapper) + fork_sync barrier + pad
FORK_SYNC_OFF = HEAP_START_OFF + 16
# shim-local identity fast path: ids_valid u32 + pid/ppid/uid/gid i32 + pad
IDS_OFF = FORK_SYNC_OFF + 8
# descriptor fast path: fast_enabled u32 + fast_calls u32 + FASTFD_MAX
# 24-byte {vfd, kind, head, tail} entries + per-entry ring arena
FAST_ENABLED_OFF = IDS_OFF + 24
FAST_CALLS_OFF = FAST_ENABLED_OFF + 4
FAST_TABLE_OFF = FAST_CALLS_OFF + 4
FASTFD_MAX = 8
FASTFD_SIZE = 24
FAST_RINGS_OFF = FAST_TABLE_OFF + FASTFD_MAX * FASTFD_SIZE
FASTFD_RING_CAP = 32768
FAST_TX_STREAM = 1
IPC_SIZE = FAST_RINGS_OFF + FASTFD_MAX * FASTFD_RING_CAP
HEAP_MAX = 256 << 20  # SHADOW_HEAP_MAX in ipc.h

_libc = ctypes.CDLL(None, use_errno=True)
SYS_futex = 202
FUTEX_WAIT = 0
FUTEX_WAKE = 1


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


def _futex(addr, op, val, timeout_s: float | None = None) -> int:
    ts = None
    if timeout_s is not None:
        ts = _Timespec(int(timeout_s), int((timeout_s % 1.0) * 1e9))
    r = _libc.syscall(
        SYS_futex, ctypes.c_void_p(addr), op, val,
        ctypes.byref(ts) if ts is not None else None, None, 0,
    )
    return r


class _Iovec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


SYS_pidfd_getfd = 438


def _vfd_access_mode(obj) -> int:
    """O_ACCMODE bits for F_GETFL: a pipe end is O_WRONLY/O_RDONLY by
    direction; everything else (sockets, event/timer/signal/inotify fds)
    is O_RDWR. glibc's fdopen/freopen validate this against the stream
    mode."""
    from shadow_tpu.host.pipe import PipeEnd

    if isinstance(obj, PipeEnd):
        return 1 if obj.is_writer else 0  # O_WRONLY / O_RDONLY
    return 2  # O_RDWR


def _vfd_mode(obj) -> int:
    """st_mode for an emulated descriptor: sockets are S_IFSOCK, stream
    ends (pipes) and everything buffer-shaped are S_IFIFO, captured stdio
    (obj None) is a FIFO to the simulator — NEVER the real placeholder
    fd's identity."""
    if obj is not None and hasattr(obj, "PROTO"):
        return 0o140000 | 0o600  # S_IFSOCK
    from shadow_tpu.host.unix import UnixDgramSocket, UnixStreamSocket

    if isinstance(obj, (UnixStreamSocket, UnixDgramSocket)):
        return 0o140000 | 0o600
    return 0o010000 | 0o600  # S_IFIFO


def _synth_stat(obj) -> bytes:
    """x86-64 struct stat (144 bytes) for an emulated descriptor."""
    ino = (id(obj) if obj is not None else 3) & ((1 << 48) - 1)
    buf = bytearray(144)
    struct.pack_into("<QQQ", buf, 0, 0x11, ino, 1)  # dev, ino, nlink
    struct.pack_into("<III", buf, 24, _vfd_mode(obj), 0, 0)  # mode,uid,gid
    struct.pack_into("<q", buf, 40, 0)  # rdev: NOT a device
    struct.pack_into("<qqq", buf, 48, 0, 4096, 0)  # size, blksize, blocks
    return bytes(buf)


def _synth_statx(obj) -> bytes:
    """struct statx (256 bytes) for an emulated descriptor."""
    ino = (id(obj) if obj is not None else 3) & ((1 << 48) - 1)
    buf = bytearray(256)
    STATX_BASIC_STATS = 0x7FF
    struct.pack_into("<II", buf, 0, STATX_BASIC_STATS, 4096)
    struct.pack_into("<IIIH", buf, 16, 1, 0, 0, _vfd_mode(obj))
    struct.pack_into("<QQQ", buf, 32, ino, 0, 0)  # ino, size, blocks
    return bytes(buf)


def _pidfd_getfd(pidfd: int, target_fd: int) -> int:
    """Grab a COPY of another process's fd (execve fd-table preservation)."""
    fd = _libc.syscall(SYS_pidfd_getfd, pidfd, target_fd, 0)
    if fd < 0:
        raise OSError(ctypes.get_errno(), "pidfd_getfd")
    return fd


# MemoryMapper windows (reference memory_mapper.rs:84-110): child pid ->
# (ipc mmap, heap mmap). The shim remapped the child's heap onto a shared
# tmpfs file; accesses fully inside [heap_start, heap_cur) are served by a
# local memcpy on that mapping — zero kernel crossings — and everything
# else falls back to process_vm_readv/writev. Bounds are re-read from the
# IPC block on every access because the shim moves heap_cur on brk.
_HEAP_WINDOWS: dict[int, tuple[mmap.mmap, mmap.mmap]] = {}


def _heap_loc(pid: int, addr: int, n: int):
    w = _HEAP_WINDOWS.get(pid)
    if w is None:
        return None
    ipc_mm, heap_mm = w
    start, cur = struct.unpack_from("<QQ", ipc_mm, HEAP_START_OFF)
    if start and addr >= start and addr + n <= cur:
        return heap_mm, addr - start
    return None


def _vm_read(pid: int, addr: int, n: int) -> bytes:
    if n <= 0 or addr == 0:
        return b""
    loc = _heap_loc(pid, addr, n)
    if loc is not None:
        mm, off = loc
        return bytes(mm[off:off + n])
    buf = ctypes.create_string_buffer(n)
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), n)
    remote = _Iovec(ctypes.c_void_p(addr), n)
    got = _libc.process_vm_readv(pid, ctypes.byref(local), 1,
                                 ctypes.byref(remote), 1, 0)
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_readv")
    return buf.raw[:got]


def _vm_read_multi(pid: int, chunks: list[tuple[int, int]]) -> bytes:
    """Gather from MANY remote ranges in ONE process_vm_readv call (the
    kernel accepts up to IOV_MAX remote iovecs per syscall) — a writev/
    sendmsg with K iovecs costs one syscall instead of K. Returns the
    concatenation; a fault mid-way truncates at the faulting range, like
    the kernel's partial-transfer contract."""
    if any(a == 0 and n > 0 for a, n in chunks):
        # a NULL base with nonzero length is EFAULT in the kernel; silently
        # skipping it would shift subsequent data into the next iovec
        raise OSError(errno.EFAULT, "iovec with NULL base")
    chunks = [(a, n) for a, n in chunks if n > 0]
    if not chunks:
        return b""
    if len(chunks) == 1:
        return _vm_read(pid, chunks[0][0], chunks[0][1])
    locs = [_heap_loc(pid, a, n) for a, n in chunks]
    if all(l is not None for l in locs):  # whole gather inside the window
        return b"".join(
            bytes(l[0][l[1]:l[1] + n]) for l, (_, n) in zip(locs, chunks)
        )
    total = sum(n for _, n in chunks)
    buf = ctypes.create_string_buffer(total)
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), total)
    remote = (_Iovec * len(chunks))(
        *(_Iovec(ctypes.c_void_p(a), n) for a, n in chunks)
    )
    got = _libc.process_vm_readv(
        pid, ctypes.byref(local), 1, remote, len(chunks), 0
    )
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_readv")
    return buf.raw[:got]


def _vm_write_multi(pid: int, chunks: list[tuple[int, int]], data: bytes) -> int:
    """Scatter `data` across MANY remote ranges in ONE process_vm_writev
    call (readv/recvmsg out-params: K iovecs, one syscall)."""
    if any(a == 0 and n > 0 for a, n in chunks):
        raise OSError(errno.EFAULT, "iovec with NULL base")
    chunks = [(a, n) for a, n in chunks if n > 0]
    total = min(sum(n for _, n in chunks), len(data))
    if total == 0:
        return 0
    if len(chunks) == 1:
        return _vm_write(pid, chunks[0][0], data[: chunks[0][1]])
    locs = [_heap_loc(pid, a, n) for a, n in chunks]
    if all(l is not None for l in locs):  # whole scatter inside the window
        pos = 0
        for l, (_, nn) in zip(locs, chunks):
            take = min(nn, total - pos)
            if take <= 0:
                break
            l[0][l[1]:l[1] + take] = bytes(data[pos:pos + take])
            pos += take
        return pos
    buf = ctypes.create_string_buffer(bytes(data[:total]), total)
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), total)
    remote_list = []
    left = total
    for a, n in chunks:
        take = min(n, left)
        if take <= 0:
            break
        remote_list.append(_Iovec(ctypes.c_void_p(a), take))
        left -= take
    remote = (_Iovec * len(remote_list))(*remote_list)
    got = _libc.process_vm_writev(
        pid, ctypes.byref(local), 1, remote, len(remote_list), 0
    )
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_writev")
    return got


def _vm_write(pid: int, addr: int, data: bytes) -> int:
    if not data or addr == 0:
        return 0
    loc = _heap_loc(pid, addr, len(data))
    if loc is not None:
        mm, off = loc
        mm[off:off + len(data)] = bytes(data)
        return len(data)
    buf = ctypes.create_string_buffer(bytes(data), len(data))
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), len(data))
    remote = _Iovec(ctypes.c_void_p(addr), len(data))
    got = _libc.process_vm_writev(pid, ctypes.byref(local), 1,
                                  ctypes.byref(remote), 1, 0)
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_writev")
    return got


def shm_cleanup() -> int:
    """Unlink IPC files whose owning simulator process is gone (reference
    `shadow --shm-cleanup`, utility/shm_cleanup.rs — which also checks
    creator-PID liveness). Returns the number removed."""
    import glob
    import re

    removed = 0
    for path in glob.glob("/dev/shm/shadow-ipc-*"):
        m = re.match(r".*/shadow-ipc-(\d+)-", path)
        if m and os.path.exists(f"/proc/{m.group(1)}"):
            continue  # owner still alive
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


# ---- build helper ----------------------------------------------------------

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")


def shim_path() -> str:
    return os.path.join(_NATIVE_DIR, "build", "libshadow_shim.so")


_ARTIFACTS = (
    "libshadow_shim.so", "test_app", "test_busy", "test_udp_echo",
    "test_udp_client", "test_tcp_stream", "test_epoll_server",
    "test_filewrite", "test_sockaddr_len", "test_writev_sock",
    "test_threads", "test_fork", "test_thread_churn", "test_signal", "test_busyclock", "test_thread_nest", "test_determinism",
)


def ensure_built() -> bool:
    """Build the native plane if needed; False if no toolchain."""
    build = os.path.join(_NATIVE_DIR, "build")
    if all(os.path.exists(os.path.join(build, a)) for a in _ARTIFACTS):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR], check=True,
            capture_output=True, timeout=120,
        )
    except (subprocess.SubprocessError, FileNotFoundError):
        return False
    return all(os.path.exists(os.path.join(build, a)) for a in _ARTIFACTS)


# ---- IPC block -------------------------------------------------------------

class IpcBlock:
    """One shared-memory block (file-backed) mirroring native/ipc.h.

    Holds IPC_MAX_THREADS channel-pair slots; slot 0 is the main thread.
    `recv_any` waits on the shared doorbell futex (bumped by the shim after
    every send) instead of polling per-channel — one wait covers every
    thread. `cur_slot` tracks the slot whose request is being serviced so
    the ~70 `reply()` call sites in the syscall handlers stay slot-agnostic.
    """

    def __init__(self, path: str | None = None):
        if path is None:
            # owner pid is embedded in the name so shm_cleanup() can check
            # liveness before unlinking (reference utility/shm_cleanup.rs)
            fd, self.path = tempfile.mkstemp(
                prefix=f"shadow-ipc-{os.getpid()}-", dir="/dev/shm"
            )
        else:
            # fork blocks live at "<parent>.f<id>" — the shim derives the
            # same name from the fork id, so no string crosses the channel
            fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_EXCL, 0o600)
            self.path = path
        os.ftruncate(fd, IPC_SIZE)
        self._mm = mmap.mmap(fd, IPC_SIZE)
        os.close(fd)
        self._base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        self.cur_slot = 0
        # called right before ANY reply returns control to the guest, so
        # fd-table-mutating syscalls can re-sync the descriptor fast table
        # before the guest can act on the new fd meanings
        self.pre_reply = None

    @staticmethod
    def _shadow_off(slot: int) -> int:
        return THREADS_OFF + slot * CHANPAIR_SIZE

    @staticmethod
    def _shim_off(slot: int) -> int:
        return THREADS_OFF + slot * CHANPAIR_SIZE + PAIR_TO_SHIM_OFF

    def close(self):
        # close every channel (threads parked in chan_recv/chan_send see
        # CHAN_CLOSED and exit) before tearing down the mapping
        for slot in range(IPC_MAX_THREADS):
            for off in (self._shadow_off(slot), self._shim_off(slot)):
                self.set_chan_state(off, CHAN_CLOSED, wake=True)
        try:
            self._mm.close()
        except BufferError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
        try:  # the shim's MemoryMapper heap file rides on the same name
            os.unlink(self.path + ".heap")
        except OSError:
            pass

    # -- sim clock
    def set_time(self, t_ns: int):
        self._mm[0:8] = struct.pack("<q", t_ns)

    def set_flags(self, v: int):
        struct.pack_into("<I", self._mm, 12, v)

    # -- channel primitives (Python is the "shadow" side)
    def chan_state_at(self, off: int) -> int:
        return struct.unpack_from("<I", self._mm, off)[0]

    def set_chan_state(self, off: int, state: int, wake: bool = False):
        struct.pack_into("<I", self._mm, off, state)
        if wake:
            _futex(self._base + off, FUTEX_WAKE, 1 << 30)

    def recv_any(
        self, timeout_s: float
    ) -> tuple[int, int, list[int]] | None:
        """Wait for a message on any slot's to_shadow channel; returns
        (kind, num, args) or None on timeout. The source slot is recorded
        in `cur_slot`."""
        deadline = time.monotonic() + timeout_s
        while True:
            bell = struct.unpack_from("<I", self._mm, DOORBELL_OFF)[0]
            for slot in range(IPC_MAX_THREADS):
                off = self._shadow_off(slot)
                if self.chan_state_at(off) == CHAN_FULL:
                    kind, _pad, num, *rest = struct.unpack_from(
                        "<ii q 6q q", self._mm, off + 8
                    )
                    args = list(rest[:6])
                    self.set_chan_state(off, CHAN_EMPTY, wake=True)
                    self.cur_slot = slot
                    return (kind, num, args)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            _futex(
                self._base + DOORBELL_OFF, FUTEX_WAIT, bell,
                min(remaining, 0.2),
            )

    def publish_ids(self, pid: int, ppid: int, uid: int, gid: int):
        """Mirror the virtual identity into shared memory so the shim
        answers getpid/getppid/get[e]uid/get[e]gid locally (ipc.h ids
        block). Call whenever an id changes (spawn, fork, exec, set*id)."""
        struct.pack_into("<Iiiii", self._mm, IDS_OFF, 1, pid, ppid, uid, gid)

    # -- descriptor fast path (ipc.h FastFd). Every mutation below runs
    # only while ALL guest threads are parked (the one-thread-at-a-time
    # invariant: entries are synced pre-reply and rings drained at trap
    # entry), so plain reads/writes need no atomics on this side.
    def fast_set_enabled(self, on: bool):
        struct.pack_into("<I", self._mm, FAST_ENABLED_OFF, 1 if on else 0)

    def fast_set_entry(self, idx: int, vfd: int, kind: int):
        off = FAST_TABLE_OFF + idx * FASTFD_SIZE
        struct.pack_into("<iIQQ", self._mm, off, vfd, kind, 0, 0)

    def fast_clear_entry(self, idx: int):
        off = FAST_TABLE_OFF + idx * FASTFD_SIZE
        struct.pack_into("<iI", self._mm, off, -1, 0)

    def fast_drain(self, idx: int) -> bytes:
        """Take everything the shim produced into ring `idx` since the
        last drain (TX direction: shim is the producer)."""
        off = FAST_TABLE_OFF + idx * FASTFD_SIZE
        head, tail = struct.unpack_from("<QQ", self._mm, off + 8)
        if head == tail:
            return b""
        n = tail - head
        ring = FAST_RINGS_OFF + idx * FASTFD_RING_CAP
        pos = head % FASTFD_RING_CAP
        first = min(n, FASTFD_RING_CAP - pos)
        data = bytes(self._mm[ring + pos:ring + pos + first])
        if n > first:
            data += bytes(self._mm[ring:ring + (n - first)])
        struct.pack_into("<Q", self._mm, off + 8, tail)  # head = tail
        return data

    def fast_take_calls(self) -> int:
        n = struct.unpack_from("<I", self._mm, FAST_CALLS_OFF)[0]
        if n:
            struct.pack_into("<I", self._mm, FAST_CALLS_OFF, 0)
        return n

    def reply(self, kind: int, ret: int = 0):
        self.reply_slot(self.cur_slot, kind, ret)

    def reply_slot(
        self, slot: int, kind: int, ret: int = 0, num: int = 0,
        args: tuple = (),
    ):
        if self.pre_reply is not None:
            self.pre_reply()
        off = self._shim_off(slot)
        a = list(args) + [0] * (6 - len(args))
        struct.pack_into(
            "<ii q 6q q", self._mm, off + 8, kind, 0, num, *a,
            ctypes.c_int64(ret).value,
        )
        self.set_chan_state(off, CHAN_FULL, wake=True)


# ---- syscall numbers the policy references ---------------------------------

SYS = {
    "read": 0, "write": 1, "close": 3, "fstat": 5, "lseek": 8, "mmap": 9,
    "mprotect": 10, "munmap": 11, "brk": 12, "rt_sigaction": 13,
    "rt_sigprocmask": 14, "ioctl": 16, "pread64": 17, "writev": 20,
    "access": 21, "sched_yield": 24, "nanosleep": 35, "getpid": 39,
    "exit": 60, "uname": 63, "fcntl": 72, "getcwd": 79, "readlink": 89,
    "sigaltstack": 131, "arch_prctl": 158, "gettid": 186, "futex": 202,
    "set_tid_address": 218, "clock_gettime": 228, "clock_nanosleep": 230,
    "exit_group": 231, "openat": 257, "newfstatat": 262, "set_robust_list": 273,
    "prlimit64": 302, "getrandom": 318, "statx": 332, "rseq": 334,
    "clock_getres": 229, "getdents64": 217, "sched_getaffinity": 204,
    "kill": 62, "tgkill": 234, "madvise": 28, "poll": 7, "ppoll": 271,
    "pipe2": 293, "dup": 32, "getuid": 102, "getgid": 104, "geteuid": 107,
    "getegid": 108, "getppid": 110, "clone": 56, "clone3": 435, "tkill": 200,
    "fork": 57, "vfork": 58, "wait4": 61, "pause": 34, "getitimer": 36,
    "alarm": 37, "setitimer": 38, "gettimeofday": 96, "time": 201,
    "getcpu": 309,
    # uio / msg / select / dup / exec / misc (reference handler/uio.c,
    # select.c, unistd.c, handler/mod.rs:371-539 dispatch arms)
    "readv": 19, "preadv": 295, "preadv2": 327, "pwritev": 296,
    "pwritev2": 328, "sendmsg": 46, "recvmsg": 47, "sendmmsg": 307,
    "recvmmsg": 299, "select": 23, "pselect6": 270, "dup2": 33,
    "dup3": 292, "socketpair": 53, "execve": 59, "sysinfo": 99,
    "getrusage": 98, "getpgid": 121, "getpgrp": 111, "setpgid": 109,
    "getsid": 124, "setsid": 112, "umask": 95, "chdir": 80, "fchdir": 81,
    # sockets
    "socket": 41, "connect": 42, "accept": 43, "sendto": 44, "recvfrom": 45,
    "shutdown": 48, "bind": 49, "listen": 50, "getsockname": 51,
    "getpeername": 52, "setsockopt": 54, "getsockopt": 55, "accept4": 288,
    # epoll / timerfd / eventfd
    "epoll_create": 213, "epoll_wait": 232, "epoll_ctl": 233,
    "epoll_pwait": 281, "epoll_create1": 291,
    "timerfd_create": 283, "timerfd_settime": 286, "timerfd_gettime": 287,
    "eventfd2": 290, "eventfd": 284,
    # filesystem mutation + metadata families (r4; reference dispatch arms
    # handler/mod.rs:371-539, handler/fileat.c, handler/file.c): governed
    # passthrough like openat/read/write — paths resolve natively in the
    # child; the simulator sees the request first (inotify hook, vfd guard)
    "flock": 73, "fsync": 74, "fdatasync": 75, "truncate": 76,
    "ftruncate": 77, "getdents": 78, "rename": 82, "mkdir": 83, "rmdir": 84,
    "creat": 85, "link": 86, "unlink": 87, "symlink": 88, "chmod": 90,
    "fchmod": 91, "chown": 92, "fchown": 93, "lchown": 94, "getrlimit": 97,
    "times": 100, "statfs": 137, "fstatfs": 138, "mknod": 133,
    "fadvise64": 221, "mkdirat": 258, "unlinkat": 263, "renameat": 264,
    "linkat": 265,
    "symlinkat": 266, "readlinkat": 267, "fchmodat": 268, "faccessat": 269,
    "fchownat": 260, "mknodat": 259, "utimensat": 280, "fallocate": 285,
    "renameat2": 316, "memfd_create": 319, "faccessat2": 439,
    "mremap": 25, "msync": 26, "sendfile": 40, "copy_file_range": 326,
    "getxattr": 191, "lgetxattr": 192, "fgetxattr": 193, "listxattr": 194,
    "llistxattr": 195, "flistxattr": 196, "setxattr": 188, "lsetxattr": 189,
    "fsetxattr": 190, "removexattr": 197,
    # notification + signal fds (emulated; reference handler/eventfd.c
    # neighbors, signalfd/inotify arms in handler/mod.rs)
    "signalfd": 282, "signalfd4": 289,
    "inotify_init": 253, "inotify_add_watch": 254, "inotify_rm_watch": 255,
    "inotify_init1": 294,
    # the last stretch of the reference's 193-arm dispatch surface (r4):
    # legacy path syscalls, credential setters, caps, waitid, execveat
    "open": 2, "stat": 4, "lstat": 6, "pipe": 22, "pwrite64": 18,
    "utime": 132, "utimes": 235, "futimesat": 261, "readahead": 187,
    "sync_file_range": 277, "syncfs": 306, "close_range": 436,
    "epoll_pwait2": 441, "execveat": 322, "fchmodat2": 452,
    "fremovexattr": 199, "lremovexattr": 198, "get_robust_list": 274,
    "sched_setaffinity": 203, "getgroups": 115, "setgroups": 116,
    "getresuid": 118, "getresgid": 120, "setuid": 105, "setgid": 106,
    "setreuid": 113, "setregid": 114, "setresuid": 117, "setresgid": 119,
    "setfsuid": 122, "setfsgid": 123, "capget": 125, "capset": 126,
    "prctl": 157, "setrlimit": 160, "waitid": 247,
}
_N2NAME = {v: k for k, v in SYS.items()}

# syscalls whose handling can change what fd 1/2 mean (capture retarget,
# vfd shadowing, exec image swap): servicing one re-syncs the descriptor
# fast table before the guest resumes (NativeProcess._fast_pre_reply)
_FAST_MUTATORS = frozenset(
    SYS[n] for n in (
        "close", "close_range", "dup", "dup2", "dup3", "fcntl",
        "execve", "execveat",
    )
)

# pass-through set: memory management, real-file reads, process metadata the
# simulator doesn't virtualize (regular_file.c passthrough analogue)
_NATIVE_OK = {
    SYS[n]
    for n in (
        "mmap", "mprotect", "munmap", "brk", "madvise", "rt_sigprocmask",
        "sigaltstack", "arch_prctl", "set_tid_address", "set_robust_list",
        "rseq", "prlimit64", "openat", "fstat", "newfstatat",
        "statx", "lseek", "pread64", "access", "readlink", "getcwd",
        "getdents64", "umask", "chdir", "fchdir",
        # NOTE: the uid/gid GETTERS are NOT native — they report the
        # per-process EMULATED identity (set by the emulated setters; the
        # real host uid would leak machine state into simulated output,
        # the uname-nodename argument)
        # r4: read-only / child-local additions for real application
        # binaries (python3 et al) — none touch shared mutable state the
        # simulator governs
        "mremap", "msync", "getdents", "readlinkat", "faccessat",
        "faccessat2", "getrlimit", "statfs", "fadvise64",
        "getxattr", "lgetxattr", "listxattr", "llistxattr",
        # memfd is an anonymous child-local file: determinism-neutral
        "memfd_create",
        # r4 last-stretch additions: legacy/reads and child-local limits.
        # prctl is process-local (PR_SET_NAME etc.); the shim's SIGSYS
        # disposition is guarded separately, and seccomp-on-seccomp only
        # tightens. pipe is a real kernel pipe like pipe2.
        "stat", "lstat", "get_robust_list", "prctl", "setrlimit",
        # NOTE: pipe/pipe2 are NOT native (r4): a real pipe lets one
        # managed process block INSIDE the kernel waiting on another
        # (bash's command substitution deadlocked the one-runner
        # scheduler exactly there) — pipes are emulated vfds so blocking
        # happens in simulated time (reference descriptor/pipe.rs)
    )
}
# NOTE: uname is NOT native — its nodename field would leak the real
# machine's hostname (a determinism hole and wrong identity: glibc's
# gethostname() is implemented via uname on Linux). It is emulated with the
# simulated host's name instead.

# custom simulator syscalls (native/ipc.h; reference handler/mod.rs:333-337)
SHADOW_SYS_RESOLVE = 1000001
SHADOW_SYS_SELF_IP = 1000002
SHADOW_SYS_RESOLVE_REV = 1000003
_N2NAME[SHADOW_SYS_RESOLVE] = "shadow_resolve"
_N2NAME[SHADOW_SYS_SELF_IP] = "shadow_self_ip"
_N2NAME[SHADOW_SYS_RESOLVE_REV] = "shadow_resolve_rev"
# NOTE: futex is deliberately NOT native: a thread futex-blocking in the
# kernel is invisible to the simulator (it never syscalls again), deadlocking
# the one-runner-at-a-time scheduler — so futex is emulated (reference
# handler/futex.c for exactly this reason).

# clone(2) flag bits the thread plane interprets
CLONE_VM = 0x100
CLONE_PARENT_SETTID = 0x00100000
CLONE_CHILD_CLEARTID = 0x00200000
CLONE_CHILD_SETTID = 0x01000000

# signals (emulated dispositions + syscall-boundary delivery; reference
# host/syscall/handler/signal.rs + shim-side handler invocation)
SIG_DFL, SIG_IGN = 0, 1
SA_SIGINFO = 4
SIGKILL, SIGALRM, SIGTERM, SIGCHLD, SIGSTOP = 9, 14, 15, 17, 19
_SIG_DEFAULT_IGNORE = {17, 18, 23, 28}  # CHLD, CONT, URG, WINCH

# futex ops (cmd = op & 0x7f)
FUTEX_CMD_WAIT = 0
FUTEX_CMD_WAKE = 1
FUTEX_CMD_REQUEUE = 3
FUTEX_CMD_CMP_REQUEUE = 4
FUTEX_CMD_WAIT_BITSET = 9
FUTEX_CMD_WAKE_BITSET = 10
FUTEX_BITSET_ALL = 0xFFFFFFFF


class _Thread:
    """Per-thread bookkeeping (the reference's Thread + ManagedThread pair,
    thread.rs:221-245 / managed_thread.rs). One channel slot each; the
    simulator runs exactly one thread at a time (hosts are single-CPU in
    sim time), so states form a tiny scheduler:

      starting    slot allocated by clone, child not yet checked in
      start-ready child sent MSG_THREAD_START, owes a MSG_START_OK
      running     we replied; executing natively until its next trap
      blocked     parked mid-syscall on a file/timer/futex condition
      wake-ready  wake fired; owes a MSG_SYSCALL_COMPLETE(pending_reply)
      dead        exited
    """

    __slots__ = (
        "slot", "state", "vtid", "rtid", "clone_flags", "ptid_addr",
        "ctid_addr", "wake", "poll_deadline", "pending_reply",
        "blocked_num", "blocked_args", "parent_owed", "sig_stash",
    )

    def __init__(self, slot: int, vtid: int):
        self.slot = slot
        self.vtid = vtid
        self.rtid = 0
        self.state = "starting"
        self.clone_flags = 0
        self.ptid_addr = 0
        self.ctid_addr = 0
        self.wake = []  # (file, listener) / (None, timer token) while blocked
        self.poll_deadline = None  # absolute poll/epoll_wait timeout
        self.pending_reply = 0
        self.blocked_num = 0
        self.blocked_args = []
        self.parent_owed = None  # (parent slot, ret) reply deferred until
        # this child checks in — serializes clone bootstraps (see
        # _finish_clone)
        self.sig_stash = None  # work deferred while a handler runs:
        # ("syscall", num, args) or ("reply", ret)

# emulated sockets hand out fds in this range so the two fd spaces (the
# child's real kernel fds vs the simulator's virtual sockets) can't collide
VFD_BASE = 1000

AF_UNIX = 1
AF_INET = 2
AF_NETLINK = 16
SOCK_STREAM = 1
SOCK_DGRAM = 2
FIONREAD = 0x541B
FIONBIO = 0x5421
MSG_PEEK = 0x2
F_DUPFD = 0
F_GETFD = 1
F_SETFD = 2
F_GETFL = 3
F_SETFL = 4
F_DUPFD_CLOEXEC = 1030
O_WRONLY = 1
IOV_MAX = 1024
SOCK_TYPE_MASK = 0xFF
SOCK_NONBLOCK = 0x800
EAGAIN = 11
EBADF = 9
ENOTCONN = 107
ECONNREFUSED = 111
ECONNRESET = 104
EAFNOSUPPORT = 97
EINVAL = 22
EMSGSIZE = 90


def _errno_of(e: OSError) -> int:
    """Map host-plane OSErrors (message-prefixed like 'EMSGSIZE: ...', the
    reference errno-name convention) to a negative errno for the child."""
    name = str(e).split(":")[0].strip()
    return -getattr(errno, name, errno.EINVAL)


def _parse_sockaddr_in(raw: bytes) -> tuple[str, int] | None:
    if len(raw) < 8:
        return None
    family, port = struct.unpack_from("<H", raw, 0)[0], struct.unpack_from(">H", raw, 2)[0]
    if family != AF_INET:
        return None
    ip = ".".join(str(b) for b in raw[4:8])
    return ip, port


def _build_sockaddr_in(ip: str, port: int) -> bytes:
    parts = bytes(int(x) for x in (ip or "0.0.0.0").split("."))
    return struct.pack("<H", AF_INET) + struct.pack(">H", port or 0) + parts + b"\x00" * 8


def _write_sockaddr(cpid: int, addr_ptr: int, len_ptr: int, sa: bytes) -> None:
    """Kernel value-result semantics for (sockaddr*, socklen_t*) out-params:
    copy min(*len, len(sa)) bytes into the caller's buffer, then store the
    true length back through len_ptr (accept(2) NOTES)."""
    if not addr_ptr:
        return
    cap = len(sa)
    if len_ptr:
        raw = _vm_read(cpid, len_ptr, 4)
        if len(raw) == 4:
            cap = struct.unpack("<I", raw)[0]
    _vm_write(cpid, addr_ptr, sa[: min(cap, len(sa))])
    if len_ptr:
        _vm_write(cpid, len_ptr, struct.pack("<I", len(sa)))

NS_PER_SEC = 1_000_000_000

_SOCKET_SYSCALLS = {
    SYS[n]
    for n in (
        "socket", "connect", "accept", "accept4", "sendto", "recvfrom",
        "shutdown", "bind", "listen", "getsockname", "getpeername",
        "setsockopt", "getsockopt",
    )
}

_EPOLL_SYSCALLS = {
    SYS[n]
    for n in (
        "epoll_create", "epoll_create1", "epoll_ctl", "epoll_wait",
        "epoll_pwait", "timerfd_create", "timerfd_settime", "timerfd_gettime",
        "eventfd", "eventfd2",
    )
}

# inotify event masks (uapi/linux/inotify.h — ABI constants)
IN_ACCESS = 0x001
IN_MODIFY = 0x002
IN_ATTRIB = 0x004
IN_MOVED_FROM = 0x040
IN_MOVED_TO = 0x080
IN_CREATE = 0x100
IN_DELETE = 0x200
IN_DELETE_SELF = 0x400
IN_MOVE_SELF = 0x800
IN_IGNORED = 0x8000
IN_ISDIR = 0x40000000

# path-based filesystem mutations: inotify hook first, then passthrough
# (reference handler/fileat.c + handler/file.c arms)
_FS_PATH_SYSCALLS = {
    SYS[n]
    for n in (
        "truncate", "rename", "renameat", "renameat2", "mkdir", "mkdirat",
        "rmdir", "creat", "link", "linkat", "unlink", "unlinkat", "symlink",
        "symlinkat", "chmod", "chown", "lchown", "fchmodat", "fchownat",
        "mknod", "mknodat", "utimensat", "setxattr", "lsetxattr",
        "removexattr", "utime", "utimes", "futimesat", "fchmodat2",
        "lremovexattr",
    )
}

# fd-based filesystem mutations: vfd-guarded passthrough (flock is NOT
# here: a native flock could block the child invisibly in the kernel and
# deadlock the one-runner-at-a-time scheduler — same reason futex is
# emulated — so it gets a simulator-side lock table)
_FS_FD_SYSCALLS = {
    SYS[n]
    for n in (
        "ftruncate", "fsync", "fdatasync", "fchmod", "fchown",
        "fallocate", "fstatfs", "fgetxattr", "flistxattr", "fsetxattr",
        "fremovexattr", "sync_file_range", "syncfs", "readahead",
    )
}

LOCK_SH, LOCK_EX, LOCK_NB, LOCK_UN = 1, 2, 4, 8

AT_FDCWD = -100
AT_REMOVEDIR = 0x200
O_CREAT = 0x40
O_NONBLOCK = 0x800
O_CLOEXEC = 0o2000000  # == SOCK_CLOEXEC == EFD/TFD/SFD/EPOLL_CLOEXEC
SOCKFS_MAGIC = 0x534F434B

# inotify event selection per mutation syscall: (mask, extra-for-dirs)
_FS_EVENT = {
    SYS["unlink"]: IN_DELETE, SYS["unlinkat"]: IN_DELETE,
    SYS["rmdir"]: IN_DELETE | IN_ISDIR,
    SYS["mkdir"]: IN_CREATE | IN_ISDIR, SYS["mkdirat"]: IN_CREATE | IN_ISDIR,
    SYS["creat"]: IN_CREATE, SYS["link"]: IN_CREATE, SYS["linkat"]: IN_CREATE,
    SYS["symlink"]: IN_CREATE, SYS["symlinkat"]: IN_CREATE,
    SYS["mknod"]: IN_CREATE, SYS["mknodat"]: IN_CREATE,
    SYS["truncate"]: IN_MODIFY,
    SYS["chmod"]: IN_ATTRIB, SYS["chown"]: IN_ATTRIB, SYS["lchown"]: IN_ATTRIB,
    SYS["fchmodat"]: IN_ATTRIB, SYS["fchownat"]: IN_ATTRIB,
    SYS["utimensat"]: IN_ATTRIB, SYS["setxattr"]: IN_ATTRIB,
    SYS["lsetxattr"]: IN_ATTRIB, SYS["removexattr"]: IN_ATTRIB,
    SYS["utime"]: IN_ATTRIB, SYS["utimes"]: IN_ATTRIB,
    SYS["futimesat"]: IN_ATTRIB, SYS["fchmodat2"]: IN_ATTRIB,
    SYS["lremovexattr"]: IN_ATTRIB,
}


class _RandomFile:
    """Deterministic /dev/urandom|/dev/random stand-in (the reference
    virtualizes these through its file layer; preload-openssl covers the
    library path). Always readable; bytes come from the host's seeded RNG."""

    def __init__(self, host):
        self._host = host

    def read(self, n: int) -> bytes:
        return self._host.rng.randbytes(min(n, 1 << 16))

    def close(self):
        pass

    @property
    def state(self):
        from shadow_tpu.host.filestate import FileState

        return FileState.READABLE

    def add_listener(self, lst):
        pass

    def remove_listener(self, lst):
        pass


class SignalFd:
    """signalfd(2) emulation (reference handler signalfd arm + its
    descriptor type). Signals whose bit is set in `mask` are routed here by
    `_post_signal` instead of the handler/default path; read() returns
    packed 128-byte signalfd_siginfo records. Divergence from the kernel
    (documented): routing ignores the thread sigprocmask — the simulator
    emulates dispositions but passes rt_sigprocmask through natively, so a
    signal claimed by any signalfd goes to the fd unconditionally."""

    SIGINFO_BYTES = 128

    def __init__(self, mask: int):
        from shadow_tpu.host.descriptor import File

        self._file = File()  # composition: state bits + listeners
        self.mask = mask
        self._q: list[tuple[int, int]] = []  # (signo, sender pid)

    # File-protocol surface used by the vfd plane / poll / epoll
    @property
    def state(self):
        return self._file.state

    def add_listener(self, lst):
        self._file.add_listener(lst)

    def remove_listener(self, lst):
        self._file.remove_listener(lst)

    def push(self, signo: int, sender_pid: int):
        from shadow_tpu.host.filestate import FileState

        self._q.append((signo, sender_pid))
        self._file._set_state(on=FileState.READABLE)

    def read(self, n: int) -> bytes | None:
        from shadow_tpu.host.filestate import FileState

        if n < self.SIGINFO_BYTES:
            raise OSError(errno.EINVAL, "signalfd read < siginfo size")
        if not self._q:
            return None  # would block
        out = bytearray()
        while self._q and len(out) + self.SIGINFO_BYTES <= n:
            signo, spid = self._q.pop(0)
            rec = bytearray(self.SIGINFO_BYTES)
            struct.pack_into("<I", rec, 0, signo)  # ssi_signo
            struct.pack_into("<i", rec, 8, 0)  # ssi_code (SI_USER)
            struct.pack_into("<I", rec, 12, spid)  # ssi_pid
            out += rec
        if not self._q:
            self._file._set_state(off=FileState.READABLE)
        return bytes(out)

    def close(self):
        self._q.clear()
        self._file.close()


class InotifyFd:
    """inotify(7) emulation over the passthrough filesystem. The simulator
    cannot see the kernel-side effects of passthrough syscalls, but it DOES
    see every request first — so mutations observable at the dispatch layer
    (unlink/rename/mkdir/creat/chmod/truncate/O_CREAT opens and the
    fd-based ftruncate/fchmod via /proc fd resolution) generate events for
    watches registered by any process on the same host. write(2) to real
    fds is not hooked (it is pure passthrough); IN_MODIFY therefore fires
    on truncate paths, not on plain writes — documented minimal support
    (reference has full coverage via its virtual fs layer).

    Divergences:
    - Events are emitted at DISPATCH time, before the native syscall runs,
      gated only on an existence probe. Operations that fail for reasons
      the probe cannot see (EACCES, cross-device rename EXDEV, rmdir on a
      non-empty dir ENOTEMPTY/EBUSY) deliver phantom IN_DELETE/IN_MOVED/
      IN_CREATE events that real inotify would not; emitting post-success
      would need a completion hook the one-way dispatch does not have.
    - write(2)-driven IN_MODIFY is absent, as above."""

    def __init__(self, host):
        from shadow_tpu.host.descriptor import File

        self._file = File()
        self.host = host
        self.watches: dict[int, tuple[str, int]] = {}  # wd -> (path, mask)
        self._next_wd = 1
        self._q: list[bytes] = []
        host.__dict__.setdefault("_inotify_fds", []).append(self)

    @property
    def state(self):
        return self._file.state

    def add_listener(self, lst):
        self._file.add_listener(lst)

    def remove_listener(self, lst):
        self._file.remove_listener(lst)

    def add_watch(self, path: str, mask: int) -> int:
        path = os.path.normpath(path)
        for wd, (p, _) in self.watches.items():
            if p == path:  # kernel: same path updates and reuses the wd
                self.watches[wd] = (p, mask)
                return wd
        wd = self._next_wd
        self._next_wd += 1
        self.watches[wd] = (path, mask)
        return wd

    def rm_watch(self, wd: int) -> int:
        if wd not in self.watches:
            return -EINVAL
        del self.watches[wd]
        self._push(wd, IN_IGNORED, 0, "")
        return 0

    def _push(self, wd: int, mask: int, cookie: int, name: str):
        from shadow_tpu.host.filestate import FileState

        nb = name.encode()
        if nb:
            pad = 8 - (len(nb) + 1) % 8 if (len(nb) + 1) % 8 else 0
            nb = nb + b"\0" * (1 + pad)
        self._q.append(
            struct.pack("<iIII", wd, mask, cookie, len(nb)) + nb
        )
        self._file._set_state(on=FileState.READABLE)

    def note(self, path: str, mask: int, cookie: int = 0):
        """A mutation of `path` happened: deliver to matching watches —
        the parent-directory watch (with the basename) and the exact-path
        watch (self events for delete/move, plain otherwise)."""
        path = os.path.normpath(path)
        parent, name = os.path.split(path)
        for wd, (wpath, wmask) in list(self.watches.items()):
            if wpath == parent and (wmask & mask & ~IN_ISDIR):
                self._push(wd, mask, cookie, name)
            elif wpath == path:
                smask = mask
                if mask & IN_DELETE:
                    smask = IN_DELETE_SELF
                elif mask & (IN_MOVED_FROM | IN_MOVE_SELF):
                    smask = IN_MOVE_SELF
                if wmask & smask & ~IN_ISDIR:
                    self._push(wd, smask | (mask & IN_ISDIR), cookie, "")

    def read(self, n: int) -> bytes | None:
        from shadow_tpu.host.filestate import FileState

        if not self._q:
            return None  # would block
        if n < len(self._q[0]):
            raise OSError(errno.EINVAL, "inotify read buffer too small")
        out = bytearray()
        while self._q and len(out) + len(self._q[0]) <= n:
            out += self._q.pop(0)
        if not self._q:
            self._file._set_state(off=FileState.READABLE)
        return bytes(out)

    def close(self):
        fds = self.host.__dict__.get("_inotify_fds", [])
        if self in fds:
            fds.remove(self)
        self.watches.clear()
        self._q.clear()
        self._file.close()


class _Adopted:
    """Popen-shaped wrapper for a fork child we did not spawn (it is our
    grandchild, so waitpid is unavailable: liveness comes from /proc and
    the real zombie is left to its real parent)."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: int | None = None

    def poll(self):
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                if f.read().split(b") ", 1)[1][:1] == b"Z":
                    self.returncode = 0
        except OSError:
            self.returncode = 0
        return self.returncode

    def wait(self, timeout=None):
        deadline = time.monotonic() + (timeout or 10)
        while self.poll() is None and time.monotonic() < deadline:
            time.sleep(0.005)
        return self.returncode

    def kill(self):
        try:
            os.kill(self.pid, 9)
        except OSError:
            pass


class NativeProcess:
    """A real Linux binary co-opted into a CpuHost's simulated time."""

    # Wall-clock budget for one native compute stretch between syscalls.
    # Time syscalls are answered in-process (no IPC), so a CPU-bound child
    # is silent on the channel; this is a hung-child watchdog (the
    # reference's resource watchdog, manager.rs:447-454), NOT a scheduling
    # device — a slow machine only ever makes the sim slower, never changes
    # results, unless a child genuinely exceeds this budget.
    # wall-clock watchdogs, NOT simulated time: generous because a loaded
    # box (e.g. an XLA compile hogging the only core) can starve the child
    # for tens of seconds; overridable for slower CI machines
    WALL_TIMEOUT_S = float(os.environ.get("SHADOW_TPU_WALL_TIMEOUT", 60.0))
    START_TIMEOUT_S = float(os.environ.get("SHADOW_TPU_START_TIMEOUT", 30.0))

    def __init__(self, host, pid: int, name: str, argv: list[str],
                 env: dict | None = None, ipc_path: str | None = None):
        self.host = host
        self.pid = pid  # virtual pid
        self.name = name
        self.argv = argv
        self.env = env or {}
        self.state = None  # mirrors host.process.ProcState via strings
        self.exit_code: int | None = None
        self.term_signal: int | None = None  # set when a signal killed us
        self.stdout: list[bytes] = []
        self.stderr: list[bytes] = []
        self.ipc = IpcBlock(path=ipc_path)
        self.ipc.pre_reply = self._fast_pre_reply
        self._child: subprocess.Popen | None = None
        self.syscall_count = 0
        self._strace = None  # fn(t, pid, name, args, ret); see property
        # descriptor fast path: idx -> captured stream (1|2) per active
        # TX entry; dirty is set when a serviced syscall may remap fds
        self._fast_map: dict[int, int] = {}
        self._fast_dirty = False
        self.expected_final_state = "running"
        # virtual fds: emulated sockets living in the host's netns
        self._vfds: dict[int, object] = {}
        self._vfd_flags: dict[int, int] = {}  # O_NONBLOCK etc.
        self._stdio_dups: dict[int, int] = {}  # vfd -> 1|2 (dup'd stdio)
        # stdio numbers a native dup2 re-pointed at a REAL kernel object
        # (pipeline plumbing): excluded from capture until closed
        self._stdio_overridden: set[int] = set()
        # close-on-exec vfds: dropped by the execve respawn (git's
        # child_process protocol deadlocks on pipe EOF without this —
        # a spawned pack-objects must NOT inherit its own pipe's write end)
        self._vfd_cloexec: set[int] = set()
        self._next_vfd = VFD_BASE
        # fd numbers the child owns as REAL kernel fds in the vfd range
        # (native dup2(realfd, N>=VFD_BASE)): the allocator must never hand
        # them out as vfds or every intercepted syscall would shadow them
        self._reserved_fds: set[int] = set()
        # threads: slot -> _Thread; slot 0 = main (vtid == pid, Linux-style)
        self.threads: dict[int, _Thread] = {0: _Thread(0, pid)}
        self.threads[0].state = "running"
        self._runner: _Thread | None = self.threads[0]
        self._cur: _Thread = self.threads[0]  # thread being serviced
        self._next_slot = 1
        self._free_slots: list[int] = []  # recycled after clean thread exit
        # the shim has ONE in-flight CloneBoot: thread-clone handshakes are
        # process-wide critical sections; concurrent requests queue here
        self._clone_busy = False
        self._clone_queue: list[tuple[_Thread, list[int]]] = []
        # emulated futex table: addr -> FIFO [(thread, bitset)]
        self._futexes: dict[int, list] = {}
        # signals: emulated dispositions + pending queue (delivered at
        # syscall boundaries under simulator control)
        self._sigactions: dict[int, tuple[int, int]] = {}  # sig->(handler,flags)
        self._sig_pending: list[tuple[int, int | None]] = []  # (sig, slot|None)
        self._itimer_token = None
        self._itimer_interval_ns = 0
        # emulated identity (deterministic: the real host uid must never
        # leak into simulated output; setters update, getters report)
        self._uid = 0
        self._gid = 0
        # fork bookkeeping
        self.parent: NativeProcess | None = None
        self.children: list[NativeProcess] = []
        self._pending_forks: dict[int, NativeProcess] = {}
        self._next_fork_id = 1
        self._wait_waiters: list[_Thread] = []  # threads parked in wait4

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        """Spawn the child (posix_spawn + LD_PRELOAD, managed_thread.rs:548)
        and service it until it blocks or exits."""
        env = dict(os.environ)
        # the guest must not inherit the SIMULATOR's python/JAX runtime:
        # PYTHONPATH here pulls the TPU client's sitecustomize into every
        # managed python3 (wrong machine identity, real TPU connections,
        # nondeterministic startup). A config that wants these sets them
        # explicitly via the process `environment`.
        for k in list(env):
            if k in ("PYTHONPATH", "PYTHONHOME", "PYTHONSTARTUP") or \
                    k.startswith(("JAX_", "XLA_", "TPU_")):
                del env[k]
        env.update(self.env)
        env["LD_PRELOAD"] = shim_path()
        env["SHADOW_SHM_PATH"] = self.ipc.path
        self.ipc.set_time(self.host.now())
        hcfg = self.host.cfg
        if hcfg.model_unblocked_latency:
            self.ipc.set_flags((hcfg.unblocked_syscall_limit << 1) | 1)
        # ASLR is disabled by the shim itself (personality + one self
        # re-exec in its constructor): a preexec_fn here would force
        # subprocess off posix_spawn onto os.fork, which is deadlock-prone
        # under JAX's threads.
        self._child = subprocess.Popen(
            self.argv, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL,
        )
        self.state = "running"
        msg = self.ipc.recv_any(timeout_s=self.START_TIMEOUT_S)
        if msg is None or msg[0] != MSG_START:
            self._die(97)
            return
        self._register_heap()  # MemoryMapper window (set up pre-handshake)
        self._publish_ids()
        self._fast_init()
        self.ipc.reply_slot(0, MSG_START_OK)
        self._service_loop()

    def _publish_ids(self):
        self.ipc.publish_ids(
            self.pid,
            self.parent.pid if self.parent is not None else 1,
            self._uid,
            self._gid,
        )

    @property
    def strace(self):
        """Per-call trace hook `fn(t, pid, name, args, ret)`. Setting a
        hook — even after the process started — disables the descriptor
        fast path: strace must see EVERY call, and fast-answered writes
        never reach the simulator."""
        return self._strace

    @strace.setter
    def strace(self, fn):
        self._strace = fn
        if fn is not None:
            # disable unconditionally — not only when entries are live:
            # a transiently-empty _fast_map (e.g. both stdio fds shadowed
            # at attach time) must not leave the path armed for
            # _fast_sync to re-enable behind the hook's back
            if self._fast_map:
                self._fast_drain()  # rescue bytes written before attach
                for idx in self._fast_map:
                    self.ipc.fast_clear_entry(idx)
                self._fast_map = {}
            self.ipc.fast_set_enabled(False)
        elif self.state == "running":
            # detach: re-run the fast-init enable so per-fd entries and the
            # global flag transition TOGETHER. Without this, a later
            # _fast_sync (strace now None) could re-arm entries while the
            # flag stayed off — a latent armed-entries/disabled-flag split
            # that only the shim's flag gate kept harmless. Pre-start
            # detaches need nothing: start's _fast_init covers them.
            self._fast_sync()
            self.ipc.fast_set_enabled(True)

    # ---- descriptor fast path ---------------------------------------------
    # write(2) on captured stdio answered inside the shim from a shared
    # ring (ipc.h FastFd; the shim_sys.c "answer hot calls without a
    # context switch" precedent extended to descriptors). Soundness:
    # entries are re-synced BEFORE any reply to an fd-mutating syscall
    # (pre_reply hook — the guest cannot act on a new fd meaning until
    # that reply lands), and rings are drained at every trap entry — so
    # rings are empty at every simulator decision point, and capture
    # order vs slow-path writev/pwritev is preserved.

    def _fast_init(self):
        """Enable after the start handshake. Any strace mode disables the
        path (strace must see every call, like the reference's handler
        which never sees shim-answered time calls by design)."""
        if self.strace is not None:
            return
        self._fast_sync()
        self.ipc.fast_set_enabled(True)

    def _fast_sync(self):
        """Mirror the capture rules of the slow write arm: fd 1/2 is
        fast-writable iff no vfd shadows it and _stdio_target still maps
        it to a captured stream. Entry index == fd number.

        At most ONE fast fd per target stream: after `2>&1` both fds
        append to the stdout buffer, and two independent rings draining
        back-to-back would lose the guest's write interleaving. The
        non-canonical fd stays on the slow path, whose trap drains rings
        BEFORE appending — program order per stream is exact either way."""
        want: dict[int, int] = {}
        claimed: set[int] = set()
        # strace must see EVERY call: never (re-)arm entries while a hook
        # is attached, whatever the fd table looks like now (want stays
        # empty, so the diff below clears any live entries)
        for fd in (1, 2) if self._strace is None else ():
            if fd not in self._vfds:
                tgt = self._stdio_target(fd)
                if tgt is not None and tgt not in claimed:
                    want[fd] = tgt
                    claimed.add(tgt)
        cur = self._fast_map
        if want == cur:
            return
        for fd, tgt in list(cur.items()):
            if want.get(fd) != tgt:
                data = self.ipc.fast_drain(fd)
                if data:
                    (self.stdout if tgt == 1 else self.stderr).append(data)
                self.ipc.fast_clear_entry(fd)
                del cur[fd]
        for fd, tgt in want.items():
            if fd not in cur:
                self.ipc.fast_set_entry(fd, fd, FAST_TX_STREAM)
                cur[fd] = tgt

    def _fast_drain(self):
        """Collect ring contents + locally-answered call counts (trap
        entry, exit, and entry-retarget points)."""
        n = self.ipc.fast_take_calls()
        if n:
            self.syscall_count += n
            self.host.counters["syscalls"] += n
            self.host.counters["syscalls_fast"] += n
        for idx, tgt in self._fast_map.items():
            data = self.ipc.fast_drain(idx)
            if data:
                (self.stdout if tgt == 1 else self.stderr).append(data)

    def _fast_pre_reply(self):
        if self._fast_dirty:
            self._fast_dirty = False
            self._fast_sync()

    def _register_heap(self):
        """Map the shim's shared heap file so _vm_* serve heap accesses by
        local memcpy (MemoryMapper window; no-op if the shim didn't set
        one up — fork children, setup failure)."""
        try:
            fd = os.open(self.ipc.path + ".heap", os.O_RDWR)
        except OSError:
            return
        try:
            mm = mmap.mmap(fd, HEAP_MAX)
        except (OSError, ValueError):
            os.close(fd)
            return
        os.close(fd)
        self._heap_mm = mm
        _HEAP_WINDOWS[self._child.pid] = (self.ipc._mm, mm)

    def _unregister_heap(self):
        mm = getattr(self, "_heap_mm", None)
        if mm is None:
            return
        _HEAP_WINDOWS.pop(self._child.pid, None)
        self._heap_mm = None
        try:
            mm.close()
        except (BufferError, ValueError):
            pass

    @staticmethod
    def _drop_vfd(sock):
        """Refcounted close: fork children share the parent's emulated fd
        objects; the descriptor dies only with its last holder."""
        refs = getattr(sock, "_nrefs", 1)
        if refs > 1:
            sock._nrefs = refs - 1
        else:
            sock.close()

    def _die(self, code: int):
        self.state = "zombie"
        self.exit_code = code
        self._unregister_heap()
        self._flock_release()
        self._clear_wake()
        for sock in self._vfds.values():  # peers see HUP/RST, not silence
            self._drop_vfd(sock)
        self._vfds.clear()
        if self._child is not None and self._child.poll() is None:
            self._child.kill()
            self._child.wait()
        self._fast_drain()  # dying mid-burst: rescue unflushed ring bytes
        self.ipc.close()
        if self.parent is not None and self.parent.state == "running":
            parent = self.parent
            self.host.schedule(
                self.host.now(), lambda: parent._child_exited(self)
            )
        self.host.on_process_exit(self)

    def kill(self):
        if self.state != "zombie":
            self.term_signal = SIGKILL
            self._die(137)

    # ---- the service loop --------------------------------------------------

    def _service_loop(self):
        """Handle syscalls until every thread blocks in sim time or the
        process exits (ManagedThread::resume's event loop,
        managed_thread.rs:187-324). Exactly one thread runs at a time —
        the reference's host-is-single-CPU invariant — so syscall service
        order is simulator-chosen and deterministic."""
        while self.state == "running":
            if self._runner is None:
                nxt = self._pick_ready()
                if nxt is None:
                    return  # all threads parked: back to the host event loop
                self._resume_thread(nxt)
            msg = self.ipc.recv_any(timeout_s=self.WALL_TIMEOUT_S)
            if msg is None:
                if self._child.poll() is not None:
                    self._die(self._child.returncode)
                else:
                    self._die(98)  # hung child: reap (watchdog analogue)
                return
            kind, num, args = msg
            slot = self.ipc.cur_slot
            t = self.threads.get(slot)
            if t is None:
                continue  # message on a freed slot (late death)
            if kind == MSG_THREAD_START:
                # new thread checked in from the clone bootstrap; it stays
                # parked until the scheduler picks it (START_OK owed)
                t.rtid = num
                if t.state == "starting":
                    t.state = "start-ready"
                if t.parent_owed is not None:
                    # parent's clone return was deferred until this check-in
                    pslot, ret = t.parent_owed
                    t.parent_owed = None
                    self.ipc.reply_slot(pslot, MSG_SYSCALL_COMPLETE, ret)
                    self._clone_finished()
                continue
            if kind == MSG_CLONE_DONE:
                if args[2]:  # fork-style (shim's do_fork)
                    self._finish_fork(t, args)
                else:
                    self._finish_clone(t, args)
                continue
            if kind == MSG_SIGNAL_DONE:
                # a handler finished: deliver the next pending signal or
                # resume the stashed work (the interrupted syscall / the
                # blocked-syscall result)
                if self._deliver_signal(t):
                    continue
                stash, t.sig_stash = t.sig_stash, None
                if stash is None:
                    continue
                if stash[0] == "reply":
                    self.ipc.reply_slot(t.slot, MSG_SYSCALL_COMPLETE, stash[1])
                else:
                    self._cur = t
                    self.ipc.cur_slot = t.slot
                    if self._fast_map:
                        self._fast_drain()
                    self._handle(stash[1], stash[2])
                    if t.state != "running":
                        self._runner = None
                continue
            self.syscall_count += 1
            self.host.counters["syscalls"] += 1
            if self._fast_map:
                self._fast_drain()  # ring bytes precede this trap: order
            self._cur = t
            # pending signals run their handlers BEFORE the syscall is
            # serviced (syscall entry = the deterministic delivery point)
            if self._sig_pending and t.sig_stash is None:
                if self._deliver_signal(t):
                    t.sig_stash = ("syscall", num, args)
                    continue
            self._handle(num, args)
            if t.state != "running":
                self._runner = None  # parked/dead: schedule someone else

    # ---- thread scheduling -------------------------------------------------

    def _pick_ready(self) -> _Thread | None:
        """Lowest-slot thread owing a resume — deterministic order."""
        for slot in sorted(self.threads):
            t = self.threads[slot]
            if t.state in ("start-ready", "wake-ready"):
                return t
        return None

    def _resume_thread(self, t: _Thread):
        self.ipc.set_time(self.host.now())
        if t.state == "start-ready":
            self.ipc.reply_slot(t.slot, MSG_START_OK)
        elif (
            self._sig_pending
            and t.sig_stash is None
            and self._deliver_signal(t)
        ):
            # run the handler before the interrupted syscall's result is
            # returned (kernel ordering: handler first, then e.g. -EINTR)
            t.sig_stash = ("reply", t.pending_reply)
        else:  # wake-ready
            self.ipc.reply_slot(t.slot, MSG_SYSCALL_COMPLETE, t.pending_reply)
        t.state = "running"
        self._runner = t

    def _wake_thread(self, t: _Thread, ret: int):
        """Make a parked thread runnable with `ret` as its syscall result."""
        if self.state != "running" or t.state != "blocked":
            return
        self._clear_wake(t)
        t.state = "wake-ready"
        t.pending_reply = ret
        self._kick()

    def _kick(self):
        """Re-enter the service loop if it is not already running (wakes
        arrive from host events only while every thread is parked)."""
        if self.state == "running" and self._runner is None:
            self._service_loop()

    def _finish_clone(self, parent: _Thread, args: list[int]):
        """Parent reported the real clone result (MSG_CLONE_DONE)."""
        tid, slot = args[0], args[1]
        child = self.threads.get(slot)
        if tid < 0 or child is None:
            if child is not None and child.state == "starting":
                del self.threads[slot]
                self._free_slots.append(slot)
            self.ipc.reply_slot(parent.slot, MSG_SYSCALL_COMPLETE, tid)
            self._clone_finished()
            return
        checked_in = child.state != "starting"  # THREAD_START already seen?
        child.rtid = tid if tid > 0 else child.rtid
        # virtualize the tid the kernel wrote (PARENT_SETTID targets the
        # pthread descriptor's tid field): real tids vary run to run, the
        # virtual tid is deterministic. Safe from racing the child: it is
        # parked in the clone bootstrap until we grant MSG_START_OK.
        addrs = set()
        if child.clone_flags & CLONE_PARENT_SETTID and child.ptid_addr:
            addrs.add(child.ptid_addr)
        if child.clone_flags & CLONE_CHILD_SETTID and child.ctid_addr:
            addrs.add(child.ctid_addr)
        for addr in addrs:
            try:
                _vm_write(self._child.pid, addr, struct.pack("<i", child.vtid))
            except OSError:
                pass
        if checked_in:
            self.ipc.reply_slot(parent.slot, MSG_SYSCALL_COMPLETE, child.vtid)
            self._clone_finished()
        else:
            # hold the parent until the child has claimed its bootstrap
            # (g_pending_boot) and checked in. This (a) closes the window
            # where a second pthread_create would overwrite the shim's
            # single in-flight CloneBoot, and (b) keeps the service loop
            # listening — if the parent were resumed and then parked with
            # the child not yet checked in, the loop could return with the
            # late MSG_THREAD_START unheard forever.
            child.parent_owed = (parent.slot, child.vtid)

    # ---- signals -----------------------------------------------------------

    def _deliver_signal(self, t: _Thread) -> bool:
        """Send the next deliverable pending signal to thread t as a
        MSG_RUN_SIGNAL; True if one was sent (caller stashes its work until
        MSG_SIGNAL_DONE)."""
        i = 0
        while i < len(self._sig_pending):
            sig, slot = self._sig_pending[i]
            if slot is not None and slot != t.slot:
                i += 1
                continue
            handler, flags = self._sigactions.get(sig, (SIG_DFL, 0))
            self._sig_pending.pop(i)  # i now indexes the next entry
            if handler in (SIG_DFL, SIG_IGN):
                continue  # disposition changed since queueing: drop
            self.ipc.reply_slot(
                t.slot, MSG_RUN_SIGNAL, ret=0, num=sig,
                args=(handler, 1 if flags & SA_SIGINFO else 0),
            )
            return True
        return False

    def _post_signal(self, sig: int, slot: int | None = None,
                     sender: int = 0):
        """Queue a signal for this process (or a specific thread), applying
        dispositions (handler/ignore/default-terminate). `sender` is the
        originating pid (0 = kernel-generated), surfaced as ssi_pid.
        Reference: handler/signal.rs + process.rs signal delivery."""
        if self.state != "running":
            return
        # signalfd routing first: a signal claimed by any signalfd mask is
        # queued on the fd instead of running the handler/default path
        # (divergence from the kernel's procmask gating noted on SignalFd)
        if sig not in (SIGKILL, SIGSTOP):
            for f in self._vfds.values():
                if isinstance(f, SignalFd) and (f.mask >> (sig - 1)) & 1:
                    f.push(sig, sender)
                    return
        handler, _flags = self._sigactions.get(sig, (SIG_DFL, 0))
        if sig in (SIGKILL, SIGSTOP) or (
            handler == SIG_DFL and sig not in _SIG_DEFAULT_IGNORE
        ):
            self.term_signal = sig
            self._die(128 + sig)  # default action: terminate
            return
        if handler == SIG_IGN or (
            handler == SIG_DFL and sig in _SIG_DEFAULT_IGNORE
        ):
            return
        self._sig_pending.append((sig, slot))
        # interrupt one blocked thread so delivery is not postponed past
        # an arbitrarily long emulated block (EINTR semantics)
        for s in sorted(self.threads):
            t = self.threads[s]
            if t.state == "blocked" and (slot is None or slot == s):
                self._remove_futex_waiter(t)
                self._wake_thread(t, -errno.EINTR)
                break

    def _remove_futex_waiter(self, thr: _Thread):
        for addr in list(self._futexes):
            q = [(t, b) for t, b in self._futexes[addr] if t is not thr]
            if q:
                self._futexes[addr] = q
            else:
                del self._futexes[addr]

    def _itimer_fire(self):
        self._itimer_token = None
        if self.state != "running":
            return
        if self._itimer_interval_ns > 0:
            self._itimer_token = self.host.schedule(
                self.host.now() + self._itimer_interval_ns, self._itimer_fire
            )
        self._post_signal(SIGALRM)

    def _itimer_cancel(self) -> int:
        """Cancel the REAL itimer; returns remaining ns (0 if unarmed)."""
        if self._itimer_token is None:
            return 0
        remaining = max(0, self._itimer_token[0] - self.host.now())
        self.host.cancel(self._itimer_token)
        self._itimer_token = None
        return remaining

    # ---- threads + futex ---------------------------------------------------

    def _handle_clone(self, num: int, args: list[int]) -> bool:
        """Slot/block-allocation half of the clone handshakes (the shim's
        do_thread_clone / do_fork step 1; reference native_clone,
        managed_thread.rs:351-379 + handler/process.rs fork emulation)."""
        flags = args[0] if num == SYS["clone"] else 0
        CLONE_VFORK = 0x4000
        if num in (SYS["fork"], SYS["vfork"]) or not (flags & CLONE_VM) or (
            flags & CLONE_VFORK
        ):
            return self._handle_fork(num, args)
        if self._clone_busy:
            # another thread's clone bootstrap is in flight; the requester
            # stays parked (no reply) until it completes — the shim's
            # single g_pending_boot must never be overwritten early
            self._cur.state = "blocked"
            self._clone_queue.append((self._cur, list(args)))
            return True
        return self._start_thread_clone(self._cur, args)

    def _start_thread_clone(self, thr: _Thread, args: list[int]) -> bool:
        flags = args[0]
        if self._free_slots:
            slot = self._free_slots.pop(0)
        elif self._next_slot < IPC_MAX_THREADS:
            slot = self._next_slot
            self._next_slot += 1
        else:
            self.ipc.reply_slot(thr.slot, MSG_SYSCALL_COMPLETE, -errno.EAGAIN)
            return False
        self._clone_busy = True
        child = _Thread(slot, self.pid * 1000 + slot)
        child.clone_flags = flags
        child.ptid_addr = args[2]
        child.ctid_addr = args[3]
        self.threads[slot] = child
        self.ipc.reply_slot(thr.slot, MSG_SYSCALL_COMPLETE, slot)
        return False

    def _clone_finished(self):
        """The in-flight clone completed (child checked in, or failed):
        start the next queued one, if any."""
        self._clone_busy = False
        while self._clone_queue:
            thr, args = self._clone_queue.pop(0)
            if thr.state != "blocked" and thr.state != "running":
                continue
            thr.state = "running"
            if self._start_thread_clone(thr, args):
                continue  # re-queued (cannot happen: busy was False)
            break

    def _handle_fork(self, num: int, args: list[int]) -> bool:
        """Create the fork child's IPC block + process object; the shim maps
        '<our block>.f<id>', forks for real, and the child checks in with
        MSG_START on its own block (serviced by the child object's loop)."""
        fork_id = self._next_fork_id
        self._next_fork_id += 1
        self.host._next_pid += 1
        child = NativeProcess(
            self.host, self.host._next_pid, f"{self.name}.f{fork_id}",
            self.argv, self.env,
            # the child's block must live at the shim-derivable path
            ipc_path=self.ipc.path + f".f{fork_id}",
        )
        child.parent = self
        if self.host.cfg.model_unblocked_latency:
            child.ipc.set_flags(
                (self.host.cfg.unblocked_syscall_limit << 1) | 1
            )
        # fd table is inherited: same emulated objects, refcounted so a
        # close in one process does not tear the other's descriptor down
        child._vfds = dict(self._vfds)
        child._vfd_flags = dict(self._vfd_flags)
        child._stdio_dups = dict(self._stdio_dups)
        child._next_vfd = self._next_vfd
        child._reserved_fds = set(self._reserved_fds)
        child._stdio_overridden = set(self._stdio_overridden)
        child._vfd_cloexec = set(self._vfd_cloexec)
        child._uid, child._gid = self._uid, self._gid
        child._publish_ids()
        for sock in child._vfds.values():
            sock._nrefs = getattr(sock, "_nrefs", 1) + 1
        self._pending_forks[fork_id] = child
        self.ipc.reply(MSG_SYSCALL_COMPLETE, fork_id)
        return False

    def _finish_fork(self, parent_thr: _Thread, args: list[int]):
        rc, fork_id = args[0], args[1]
        child = self._pending_forks.pop(fork_id, None)
        if child is None or rc < 0:
            if child is not None:
                child.ipc.close()
            self.ipc.reply_slot(parent_thr.slot, MSG_SYSCALL_COMPLETE,
                                min(rc, -1) if rc < 0 else -errno.EAGAIN)
            return
        child._child = _Adopted(rc)
        child.state = "running"
        self.children.append(child)
        self.host.processes[child.pid] = child
        # the child's service loop starts when the host event fires (i.e.
        # once the parent's loop yields) — its MSG_START waits in the block
        self.host.schedule(self.host.now(), child._adopt_run)
        self.ipc.reply_slot(parent_thr.slot, MSG_SYSCALL_COMPLETE, child.pid)

    def _adopt_run(self):
        """First service entry for a fork child: answer its MSG_START."""
        if self.state != "running":
            return
        self.ipc.set_time(self.host.now())
        msg = self.ipc.recv_any(timeout_s=self.START_TIMEOUT_S)
        if msg is None or msg[0] != MSG_START:
            self._die(97)
            return
        self._fast_init()  # fresh block; entries from the inherited tables
        self.ipc.reply_slot(0, MSG_START_OK)
        self._service_loop()

    def _handle_wait4(self, args: list[int]) -> bool:
        """wait4: reap a zombie child (vpid + status), or park until one
        exits. WNOHANG honored; rusage ignored (zeroed)."""
        WNOHANG = 1
        want = ctypes.c_int32(args[0] & 0xFFFFFFFF).value
        cpid = self._child.pid

        def match(c):
            return want in (-1, 0) or want == c.pid

        for c in list(self.children):
            if c.state == "zombie" and match(c):
                self.children.remove(c)
                if args[1]:
                    # wait-status encoding: low 7 bits = killing signal
                    # (WIFSIGNALED), else exit code << 8 (WIFEXITED)
                    status = (
                        c.term_signal & 0x7F
                        if c.term_signal
                        else (c.exit_code or 0) << 8
                    )
                    _vm_write(cpid, args[1], struct.pack("<i", status))
                self.ipc.reply(MSG_SYSCALL_COMPLETE, c.pid)
                return False
        if not any(match(c) for c in self.children):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ECHILD)
            return False
        if args[2] & WNOHANG:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        thr = self._cur
        thr.state = "blocked"
        thr.blocked_num = SYS["wait4"]
        thr.blocked_args = list(args)
        self._wait_waiters.append(thr)
        return True

    def _handle_waitid(self, args: list[int]) -> bool:
        """waitid(2): the siginfo-shaped wait (reference handler parity).
        P_ALL/P_PID with WEXITED; WNOHANG honored (si_pid stays 0)."""
        P_ALL, P_PID = 0, 1
        WNOHANG = 1
        WEXITED = 4
        WNOWAIT = 0x01000000
        idtype, wid, infop, options = args[0], args[1], args[2], args[3]
        if idtype not in (P_ALL, P_PID) or not options & WEXITED:
            # only exit events exist in this plane (no job control)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False

        def match(c):
            return idtype == P_ALL or wid == c.pid

        def write_info(c):
            if not infop:
                return
            CLD_EXITED, CLD_KILLED = 1, 2
            buf = bytearray(128)
            struct.pack_into("<iii", buf, 0, SIGCHLD, 0,
                             CLD_KILLED if c.term_signal else CLD_EXITED)
            struct.pack_into("<iIi", buf, 16, c.pid, 0,
                             c.term_signal or (c.exit_code or 0))
            try:
                _vm_write(self._child.pid, infop, bytes(buf))
            except OSError:
                pass

        for c in list(self.children):
            if c.state == "zombie" and match(c):
                if not options & WNOWAIT:  # WNOWAIT peeks, leaves waitable
                    self.children.remove(c)
                write_info(c)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
                return False
        if not any(match(c) for c in self.children):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ECHILD)
            return False
        if options & WNOHANG:
            if infop:  # kernel zeroes si_pid to signal "nothing yet"
                try:
                    _vm_write(self._child.pid, infop, b"\0" * 128)
                except OSError:
                    pass
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        thr = self._cur
        thr.state = "blocked"
        thr.blocked_num = SYS["waitid"]
        thr.blocked_args = list(args)
        self._wait_waiters.append(thr)
        return True

    def _child_exited(self, child: NativeProcess):
        """A fork child died: retry any parked wait4/waitid
        (deterministically at the current sim time)."""
        waiters, self._wait_waiters = self._wait_waiters, []
        for thr in waiters:
            if thr.state != "blocked":
                continue
            thr.state = "running"
            self.ipc.set_time(self.host.now())
            self.ipc.cur_slot = thr.slot
            self._cur = thr
            if thr.blocked_num == SYS["waitid"]:
                self._handle_waitid(thr.blocked_args)
            else:
                self._handle_wait4(thr.blocked_args)
            if thr.state == "running":
                self._runner = thr
                self._kick_runner()
        # SIGCHLD after wait retries: a parked wait4 must win the status,
        # not be EINTR'd by its own child's death notification
        self._post_signal(SIGCHLD, sender=child.pid)

    def _kick_runner(self):
        """Enter the service loop for an already-resumed runner if we are
        not inside it (used by wake paths driven from host events)."""
        if self.state == "running" and self._runner is not None:
            self._service_loop()

    def _handle_futex(self, args: list[int]) -> bool:
        """Emulated futex (reference handler/futex.c): threads must block in
        SIM time, not invisibly in the kernel. Supports WAIT/WAKE (+_BITSET)
        and (CMP_)REQUEUE — the glibc pthread surface."""
        addr, op, val = args[0], args[1], args[2] & 0xFFFFFFFF
        cmd = op & 0x7F
        cpid = self._child.pid
        thr = self._cur

        if cmd in (FUTEX_CMD_WAIT, FUTEX_CMD_WAIT_BITSET):
            try:
                cur = struct.unpack("<I", _vm_read(cpid, addr, 4))[0]
                raw = _vm_read(cpid, args[3], 16) if args[3] else b""
            except (OSError, struct.error):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if cur != val:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EAGAIN)
                return False
            bitset = (
                args[5] & 0xFFFFFFFF
                if cmd == FUTEX_CMD_WAIT_BITSET
                else FUTEX_BITSET_ALL
            ) or FUTEX_BITSET_ALL
            thr.state = "blocked"
            self._futexes.setdefault(addr, []).append((thr, bitset))
            if len(raw) == 16:
                sec, nsec = struct.unpack("<qq", raw)
                t_ns = sec * NS_PER_SEC + nsec
                # WAIT: relative. WAIT_BITSET: absolute (sim clock).
                deadline = (
                    max(t_ns, self.host.now())
                    if cmd == FUTEX_CMD_WAIT_BITSET
                    else self.host.now() + max(0, t_ns)
                )
                token = self.host.schedule(
                    deadline,
                    lambda: self._futex_timeout(addr, thr),
                )
                thr.wake.append((None, token))
            return True

        if cmd in (FUTEX_CMD_WAKE, FUTEX_CMD_WAKE_BITSET):
            bitset = (
                args[5] & 0xFFFFFFFF
                if cmd == FUTEX_CMD_WAKE_BITSET
                else FUTEX_BITSET_ALL
            ) or FUTEX_BITSET_ALL
            n = self._futex_wake_addr(addr, val, bitset)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if cmd in (FUTEX_CMD_REQUEUE, FUTEX_CMD_CMP_REQUEUE):
            if cmd == FUTEX_CMD_CMP_REQUEUE:
                try:
                    cur = struct.unpack("<I", _vm_read(cpid, addr, 4))[0]
                except (OSError, struct.error):
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                if cur != (args[5] & 0xFFFFFFFF):
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EAGAIN)
                    return False
            woken = self._futex_wake_addr(addr, val, FUTEX_BITSET_ALL)
            moved = 0
            limit = args[3] & 0xFFFFFFFF  # val2: requeue cap
            q = self._futexes.get(addr, [])
            dst = self._futexes.setdefault(args[4], [])
            while q and moved < limit:
                dst.append(q.pop(0))
                moved += 1
            if not q:
                self._futexes.pop(addr, None)
            ret = woken + (moved if cmd == FUTEX_CMD_CMP_REQUEUE else 0)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, ret)
            return False

        self.ipc.reply(MSG_SYSCALL_COMPLETE, -38)  # unsupported op: loud
        return False

    def _futex_wake_addr(self, addr: int, n: int, bitset: int) -> int:
        """Wake up to n emulated waiters on addr (FIFO — park order is
        simulator-chosen, hence deterministic). Returns the count."""
        q = self._futexes.get(addr)
        if not q:
            return 0
        woken = 0
        keep = []
        for thr, wbits in q:
            if woken < n and (wbits & bitset) and thr.state == "blocked":
                self._clear_wake(thr)
                thr.state = "wake-ready"
                thr.pending_reply = 0
                woken += 1
            elif thr.state == "blocked":
                keep.append((thr, wbits))
        if keep:
            self._futexes[addr] = keep
        else:
            self._futexes.pop(addr, None)
        return woken

    def _futex_timeout(self, addr: int, thr: _Thread):
        if thr.state != "blocked":
            return
        q = self._futexes.get(addr, [])
        self._futexes[addr] = [(t, b) for t, b in q if t is not thr]
        if not self._futexes[addr]:
            self._futexes.pop(addr, None)
        self._clear_wake(thr)
        thr.state = "wake-ready"
        thr.pending_reply = -errno.ETIMEDOUT
        self._kick()

    # ---- blocking on emulated files ---------------------------------------

    def _block_on(self, files_masks, num: int, args: list[int],
                  timeout_ns: int | None = None):
        """Park the current thread until any watched file shows its mask (or
        the timeout fires), then RE-RUN the same syscall — the reference's
        SyscallCondition semantics (condition.rs:36-108)."""
        from shadow_tpu.host.filestate import StatusListener

        thr = self._cur
        thr.state = "blocked"

        def wake(_s=None, _c=None):
            if not thr.wake:
                return
            self._clear_wake(thr)
            self.host.schedule(self.host.now(), retry)

        def retry():
            if self.state != "running" or thr.state != "blocked":
                return
            thr.state = "running"  # tentative; _block_on re-parks on EAGAIN
            self.ipc.set_time(self.host.now())
            self.ipc.cur_slot = thr.slot
            self._cur = thr
            self._handle(num, args)
            if self.state != "running":
                return
            if thr.state == "running":  # replied: it is the runner again
                self._runner = thr
                self._service_loop()

        for f, mask in files_masks:
            lst = StatusListener(mask, wake)
            f.add_listener(lst)
            thr.wake.append((f, lst))
        if timeout_ns is not None:
            token = self.host.schedule(self.host.now() + timeout_ns, wake)
            thr.wake.append((None, token))

    def _clear_wake(self, thr: _Thread | None = None):
        ts = [thr] if thr is not None else list(self.threads.values())
        for t in ts:
            for f, l in t.wake:
                if f is None:
                    self.host.cancel(l)
                else:
                    f.remove_listener(l)
            t.wake = []

    # ---- dispatch ----------------------------------------------------------

    def _handle(self, num: int, args: list[int]) -> bool:
        """Returns True if the service loop should stop (blocked/exited)."""
        cpid = self._child.pid
        name = _N2NAME.get(num, str(num))
        if num in _FAST_MUTATORS:
            # this call may remap what fd 1/2 mean; re-sync the fast
            # table before the arm's reply resumes the guest (pre_reply)
            self._fast_dirty = True
        if self.strace is not None:
            self.strace(self.host.now(), self.pid, name, tuple(args[:3]), None)

        if num in _SOCKET_SYSCALLS:
            return self._handle_socket(num, args)
        if num in _EPOLL_SYSCALLS:
            return self._handle_epoll(num, args)
        if num == SYS["close"]:
            if args[0] in self._stdio_dups:
                del self._stdio_dups[args[0]]
                self._stdio_overridden.discard(args[0])
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if args[0] in self._vfds:
                sock = self._vfds.pop(args[0])
                self._vfd_flags.pop(args[0], None)
                self._drop_vfd(sock)
                self._stdio_overridden.discard(args[0])
                self._vfd_cloexec.discard(args[0])
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            else:
                self._flock_release(args[0])  # close drops flock locks
                self._stdio_overridden.discard(args[0])
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num == SYS["dup"]:
            # stdio fds are virtualized (captured), so their dups must be
            # too: glibc's perror dups stderr before writing, and a native
            # dup would alias the child's real stderr (DEVNULL)
            if args[0] in self._vfds:  # incl. a vfd dup2()d over fd 1/2
                self.ipc.reply(MSG_SYSCALL_COMPLETE, self._dup_vfd(args[0]))
                return False
            tgt = self._stdio_target(args[0])
            if tgt is not None:
                nfd = self._alloc_vfd()
                self._stdio_dups[nfd] = tgt
                self.ipc.reply(MSG_SYSCALL_COMPLETE, nfd)
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num in (SYS["dup2"], SYS["dup3"]):
            return self._handle_dup2(num, args)
        if num == SYS["fcntl"] and (
            args[1] in (F_DUPFD, F_DUPFD_CLOEXEC)
            and args[0] not in self._vfds
            and self._stdio_target(args[0]) is not None
        ):
            # dup-via-fcntl of a captured stdio fd: must stay virtual, same
            # as dup(2) — a native dup would alias the child's real
            # stderr/stdout (DEVNULL) and silently swallow output
            nfd = self._alloc_vfd()
            self._stdio_dups[nfd] = self._stdio_target(args[0])
            if args[1] == F_DUPFD_CLOEXEC:
                self._vfd_cloexec.add(nfd)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, nfd)
            return False
        if num == SYS["fcntl"] and args[0] in self._stdio_dups:
            if args[1] == F_GETFL:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, O_WRONLY)
            elif args[1] == F_GETFD:
                self.ipc.reply(
                    MSG_SYSCALL_COMPLETE,
                    1 if args[0] in self._vfd_cloexec else 0,
                )
            elif args[1] == F_SETFD:
                # honored at exec (glibc fdopen(..., "we") sets FD_CLOEXEC
                # right after dup)
                if args[2] & 1:
                    self._vfd_cloexec.add(args[0])
                else:
                    self._vfd_cloexec.discard(args[0])
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            elif args[1] == F_SETFL:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            else:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        if num == SYS["fcntl"]:
            if args[0] not in self._vfds:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
                return False
            if args[1] == F_SETFL:
                self._vfd_flags[args[0]] = args[2]
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            elif args[1] == F_GETFL:
                # status flags PLUS the access mode: glibc's fdopen(fd, "w")
                # validates F_GETFL against the stream mode and fails
                # EINVAL on a mismatch (git upload-pack died exactly there
                # when every vfd reported O_RDONLY)
                self.ipc.reply(
                    MSG_SYSCALL_COMPLETE,
                    self._vfd_flags.get(args[0], 0)
                    | _vfd_access_mode(self._vfds[args[0]]),
                )
            elif args[1] in (F_DUPFD, F_DUPFD_CLOEXEC):
                nfd = self._dup_vfd(args[0])
                if args[1] == F_DUPFD_CLOEXEC:
                    self._vfd_cloexec.add(nfd)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, nfd)
            elif args[1] == F_GETFD:
                self.ipc.reply(
                    MSG_SYSCALL_COMPLETE,
                    1 if args[0] in self._vfd_cloexec else 0,
                )
            elif args[1] == F_SETFD:
                if args[2] & 1:  # FD_CLOEXEC
                    self._vfd_cloexec.add(args[0])
                else:
                    self._vfd_cloexec.discard(args[0])
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            else:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)  # loud
            return False
        if num == SYS["openat"]:
            # virtualize the entropy devices (determinism: a passthrough
            # open would read real kernel entropy); everything else passes
            # through per the regular-file policy
            try:
                raw = _vm_read(cpid, args[1], 256)
                pathname = raw.split(b"\0", 1)[0]
            except OSError:
                pathname = b""
            if pathname in (b"/dev/urandom", b"/dev/random"):
                vfd = self._alloc_vfd()
                self._vfds[vfd] = _RandomFile(self.host)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, vfd)
                return False
            # inotify: O_CREAT open of a not-yet-existing path is IN_CREATE
            # (the simulator shares the child's fs view, so the existence
            # probe here matches what the native open will see)
            if args[2] & O_CREAT and self.host.__dict__.get("_inotify_fds"):
                p = self._child_path(args[0], args[1])
                if p is not None and not os.path.exists(p):
                    self._fs_note(p, IN_CREATE)
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num in (SYS["readv"], SYS["preadv"], SYS["preadv2"]):
            if args[0] in self._vfds:
                if num != SYS["readv"]:
                    # positioned io on an unseekable emulated descriptor
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ESPIPE)
                    return False
                return self._handle_readv(args)
            if self._stdio_target(args[0]) is not None:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)  # write-only
                return False
            self.ipc.reply(MSG_SYSCALL_NATIVE)  # regular-file uio
            return False
        if num in (SYS["pwritev"], SYS["pwritev2"]):
            if args[0] in self._vfds:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ESPIPE)
                return False
            tgt = self._stdio_target(args[0])
            if tgt is None:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
                return False
            # pwritev on captured stdio: treat as a plain gather write
            try:
                data = self._gather_write(cpid, SYS["writev"], args)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            (self.stdout if tgt == 1 else self.stderr).append(data)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, len(data))
            return False
        if num in (SYS["sendmsg"], SYS["recvmsg"], SYS["sendmmsg"],
                   SYS["recvmmsg"]):
            return self._handle_msg(num, args)
        if num in (SYS["select"], SYS["pselect6"]):
            return self._handle_select(num, args)
        if num == SYS["socketpair"]:
            return self._handle_socketpair(args)
        if num == SYS["execve"]:
            return self._handle_execve(args)
        if num == SYS["ioctl"] and args[0] in self._vfds:
            return self._handle_vfd_ioctl(args)
        if num == SYS["sysinfo"]:
            # deterministic machine facts (reference handler sysinfo arm):
            # uptime = simulated seconds, fixed 8 GiB RAM half free
            now_s = self.host.now() // NS_PER_SEC
            gib = 1 << 30
            buf = struct.pack(
                "<q3Q6QHH4x2QI", now_s, 0, 0, 0, 8 * gib, 4 * gib, 0, 0, 0, 0,
                len(self.host.processes) & 0xFFFF, 0, 0, 0, 1,
            )
            try:
                _vm_write(cpid, args[0], buf.ljust(112, b"\0"))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["getrusage"]:
            # deterministic: zero cpu times, fixed maxrss (reference
            # handler/resource.rs returns plausible-but-deterministic data)
            try:
                _vm_write(cpid, args[1], struct.pack(
                    "<4q14q", 0, 0, 0, 0, 10240, *([0] * 13)))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["uname"]:
            # virtualized: nodename is the SIMULATED host's name (glibc
            # gethostname() reads it from here); fixed release/version so
            # two runs on different machines behave identically
            def field(s: str) -> bytes:
                return s.encode()[:64].ljust(65, b"\0")

            uts = (field("Linux") + field(self.host.cfg.name)
                   + field("6.1.0-shadow") + field("#1 SMP")
                   + field("x86_64") + field("(none)"))
            try:
                _vm_write(cpid, args[0], uts)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SHADOW_SYS_RESOLVE:
            # shim getaddrinfo/gethostbyname: name -> IPv4 from the
            # simulator DNS (reference shadow_hostname_to_addr_ipv4)
            try:
                name = self._read_cstr(cpid, args[0], 256).decode(
                    "utf-8", "surrogateescape"
                )
                ip = self.host.resolve(name)
                if ip is None:
                    raise OSError("ENOENT: unknown host")
                import socket as _socket

                _vm_write(cpid, args[1], _socket.inet_aton(ip))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOENT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SHADOW_SYS_SELF_IP:
            import socket as _socket

            try:
                _vm_write(cpid, args[0],
                          _socket.inet_aton(self.host.cfg.ip))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SHADOW_SYS_RESOLVE_REV:
            # shim gethostbyaddr/getnameinfo: IPv4 -> simulated hostname
            # (glibc's reverse path would leak real DNS queries into the
            # simulated network; reference dns.c address registry)
            import socket as _socket

            ip = _socket.inet_ntoa(
                struct.pack("<I", args[0] & 0xFFFFFFFF)
            )
            name = self.host.rev_resolve(ip)
            if name is None:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOENT)
                return False
            data = name.encode()[: max(args[2] - 1, 0)] + b"\0"
            try:
                _vm_write(cpid, args[1], data)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num in (SYS["getpgid"], SYS["getpgrp"], SYS["getsid"]):
            # single-session model: every process leads its own group
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self.pid)
            return False
        if num in (SYS["setpgid"], SYS["setsid"]):
            self.ipc.reply(
                MSG_SYSCALL_COMPLETE,
                0 if num == SYS["setpgid"] else self.pid,
            )
            return False
        if num == SYS["times"]:
            # SIMULATED clock ticks, not real jiffies (clock(3)/timeout
            # loops must see the same timeline as clock_gettime); tms cpu
            # fields zeroed like getrusage
            CLK_TCK = 100
            ticks = self.host.now() * CLK_TCK // NS_PER_SEC
            try:
                if args[0]:
                    _vm_write(cpid, args[0], struct.pack("<4q", 0, 0, 0, 0))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, ticks)
            return False
        if num in _FS_PATH_SYSCALLS:
            return self._handle_fs_path(num, args)
        if num in _FS_FD_SYSCALLS:
            return self._handle_fs_fd(num, args)
        if num == SYS["flock"]:
            return self._handle_flock(args)
        if num in (SYS["fstat"], SYS["newfstatat"], SYS["statx"]) and (
            args[0] in self._vfds
            or self._stdio_target(args[0]) is not None
        ):
            # stat on an emulated descriptor (or captured stdio) must NOT
            # reach the kernel: the real fd behind the number is the
            # DEVNULL placeholder, and tools act on what stat says — GNU
            # grep silently suppresses ALL output when st_rdev says its
            # stdout is /dev/null (that one cost an afternoon). glibc >=
            # 2.33 implements fstat() as newfstatat(fd, "", AT_EMPTY_PATH),
            # so all three forms are covered here.
            if num == SYS["fstat"]:
                buf_ptr = args[1]
            else:
                flag_arg = args[3] if num == SYS["newfstatat"] else args[2]
                try:
                    pth = self._read_cstr(cpid, args[1], 8)
                except OSError:
                    pth = b"?"
                if pth != b"" or not flag_arg & 0x1000:  # AT_EMPTY_PATH
                    if pth.startswith(b"/"):
                        # absolute path: dirfd is ignored by the kernel
                        self.ipc.reply(MSG_SYSCALL_NATIVE)
                        return False
                    # path-relative with a virtual fd as dirfd: the number
                    # is no directory (and has no real kernel fd behind it)
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOTDIR)
                    return False
                buf_ptr = args[2] if num == SYS["newfstatat"] else args[4]
            obj = self._vfds.get(args[0])
            try:
                if num == SYS["statx"]:
                    _vm_write(cpid, buf_ptr, _synth_statx(obj))
                else:
                    _vm_write(cpid, buf_ptr, _synth_stat(obj))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num in (SYS["pipe"], SYS["pipe2"]):
            # emulated pipe (reference descriptor/pipe.rs): see the
            # _NATIVE_OK note — cross-process pipe blocking must park in
            # SIM time, not in the kernel
            from shadow_tpu.host.pipe import create_pipe

            r, w = create_pipe()
            rfd, wfd = self._alloc_vfd(), self._alloc_vfd()
            self._vfds[rfd] = r
            self._vfds[wfd] = w
            if num == SYS["pipe2"] and args[1] & O_NONBLOCK:
                self._vfd_flags[rfd] = O_NONBLOCK
                self._vfd_flags[wfd] = O_NONBLOCK
            if num == SYS["pipe2"] and args[1] & O_CLOEXEC:
                self._vfd_cloexec.add(rfd)
                self._vfd_cloexec.add(wfd)
            try:
                _vm_write(cpid, args[0], struct.pack("<ii", rfd, wfd))
            except OSError:
                self._close_virtual(rfd)
                self._close_virtual(wfd)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["open"]:
            # legacy open(2): same policy as openat — virtualize the
            # entropy devices, note O_CREAT for inotify, else passthrough
            return self._handle(SYS["openat"],
                                [AT_FDCWD & 0xFFFFFFFF, args[0], args[1],
                                 args[2], 0, 0])
        if num == SYS["pwrite64"]:
            if args[0] in self._vfds:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ESPIPE)
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num in (SYS["setuid"], SYS["setgid"], SYS["setreuid"],
                   SYS["setregid"], SYS["setresuid"], SYS["setresgid"],
                   SYS["setfsuid"], SYS["setfsgid"], SYS["setgroups"]):
            # EMULATED identity: record the requested id so the getters
            # agree (privilege-drop daemons verify with getuid after
            # setuid), WITHOUT the native drop — a real setuid would strip
            # the simulator's process_vm access to the child
            def _take(v):  # -1 = keep (setre*/setres* convention)
                v = ctypes.c_int32(v & 0xFFFFFFFF).value
                return None if v == -1 else v & 0xFFFFFFFF

            is_uid = num in (SYS["setuid"], SYS["setreuid"],
                             SYS["setresuid"], SYS["setfsuid"])
            attr = "_uid" if is_uid else "_gid"
            if num in (SYS["setuid"], SYS["setgid"], SYS["setfsuid"],
                       SYS["setfsgid"]):
                setattr(self, attr, args[0] & 0xFFFFFFFF)
            elif num in (SYS["setreuid"], SYS["setregid"]):
                eff = _take(args[1])
                if eff is None:
                    eff = _take(args[0])
                if eff is not None:
                    setattr(self, attr, eff)
            elif num in (SYS["setresuid"], SYS["setresgid"]):
                eff = _take(args[1])
                if eff is not None:
                    setattr(self, attr, eff)
            self._publish_ids()  # keep the shim-local fast path coherent
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num in (SYS["getuid"], SYS["geteuid"]):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self._uid)
            return False
        if num in (SYS["getgid"], SYS["getegid"]):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self._gid)
            return False
        if num in (SYS["getresuid"], SYS["getresgid"]):
            val = self._uid if num == SYS["getresuid"] else self._gid
            try:
                for ptr in args[:3]:
                    if ptr:
                        _vm_write(cpid, ptr, struct.pack("<I", val))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["getgroups"]:
            # one supplementary group: the emulated gid (size 0 queries
            # the count, like the kernel)
            if args[0] >= 1 and args[1]:
                try:
                    _vm_write(cpid, args[1], struct.pack("<I", self._gid))
                except OSError:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 1)
            return False
        if num in (SYS["capget"], SYS["capset"]):
            # no capability model in the simulation: report none, accept
            # any set (handler parity; callers treat caps as best-effort)
            if num == SYS["capget"] and args[1]:
                try:
                    _vm_write(cpid, args[1], b"\0" * 24)
                except OSError:
                    pass
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["sched_setaffinity"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)  # one-cpu host model
            return False
        if num == SYS["close_range"]:
            CLOSE_RANGE_CLOEXEC = 0x4
            first, last = args[0], min(args[1], 1 << 20)
            if args[2] & CLOSE_RANGE_CLOEXEC:
                # CLOEXEC-mark (not close) every emulated fd in range: the
                # exec drop honors it (systemd/runc-style pre-exec hygiene)
                for fd in list(self._vfds) + list(self._stdio_dups):
                    if first <= fd <= last:
                        self._vfd_cloexec.add(fd)
            if not (args[2] & CLOSE_RANGE_CLOEXEC):
                self._stdio_overridden -= {
                    f for f in self._stdio_overridden if first <= f <= last
                }
                # close every vfd in [first, last] (the implicit-close
                # contract dup2 also honors) and release any flock locks
                # real fds in the span held, then let the kernel close the
                # real fds. CLOEXEC-marking only is a no-op for vfds
                # (emulated descriptors deliberately survive exec).
                for fd in [f for f in self._vfds if first <= f <= last]:
                    self._close_virtual(fd)
                for fd in [
                    f for f in self._stdio_dups if first <= f <= last
                ]:
                    self._stdio_dups.pop(fd, None)
                self._flock_release(span=(first, last))
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num == SYS["epoll_pwait2"]:
            # timespec timeout -> ms, then the common epoll_wait path
            timeout_ms = -1
            if args[3]:
                try:
                    raw = _vm_read(cpid, args[3], 16)
                    if len(raw) == 16:
                        s, ns = struct.unpack("<qq", raw)
                        timeout_ms = (s * NS_PER_SEC + ns) // 1_000_000
                except OSError:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
            return self._handle_epoll(
                SYS["epoll_wait"], [args[0], args[1], args[2], timeout_ms]
            )
        if num == SYS["waitid"]:
            return self._handle_waitid(args)
        if num == SYS["execveat"]:
            # resolve dirfd-relative (incl. AT_EMPTY_PATH/fexecve) here;
            # the execve handler takes the override
            AT_EMPTY_PATH = 0x1000
            try:
                rel = self._read_cstr(cpid, args[1]).decode(
                    "utf-8", "surrogateescape"
                )
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if not rel and args[4] & AT_EMPTY_PATH:
                try:
                    path = os.readlink(f"/proc/{cpid}/fd/{args[0]}")
                except OSError:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
                    return False
            else:
                path = self._child_path(args[0], args[1])
                if path is None:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOENT)
                    return False
            return self._handle_execve(
                [args[1], args[2], args[3]], path_override=path
            )
        if num in (SYS["signalfd"], SYS["signalfd4"]):
            return self._handle_signalfd(num, args)
        if num in (SYS["inotify_init"], SYS["inotify_init1"],
                   SYS["inotify_add_watch"], SYS["inotify_rm_watch"]):
            return self._handle_inotify(num, args)
        if num == SYS["sendfile"]:
            return self._handle_sendfile(args)
        if num == SYS["copy_file_range"]:
            # regular-file-only syscall: emulated descriptors are EINVAL
            # (kernel contract), real files pass through
            if args[0] in self._vfds or args[2] in self._vfds:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num in _NATIVE_OK:
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num in (SYS["nanosleep"], SYS["clock_nanosleep"]):
            req_ptr = args[0] if num == SYS["nanosleep"] else args[2]
            raw = _vm_read(cpid, req_ptr, 16)
            sec, nsec = struct.unpack("<qq", raw) if len(raw) == 16 else (0, 0)
            t = sec * NS_PER_SEC + nsec
            TIMER_ABSTIME = 1
            if num == SYS["clock_nanosleep"] and args[1] & TIMER_ABSTIME:
                wake_at = max(self.host.now(), t)  # absolute deadline
            else:
                wake_at = self.host.now() + max(0, t)
            thr = self._cur
            thr.state = "blocked"
            token = self.host.schedule(
                wake_at, lambda: self._wake_thread(thr, 0)
            )
            thr.wake.append((None, token))
            return True  # parked

        if num in (SYS["write"], SYS["writev"]) and args[0] not in self._vfds and (
            self._stdio_target(args[0]) is not None
        ):
            # (a vfd dup2()d over fd 1/2 shadows the captured stdio)
            if num == SYS["writev"] and args[2] > IOV_MAX:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            tgt = self._stdio_target(args[0])
            try:
                data = self._gather_write(cpid, num, args)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            (self.stdout if tgt == 1 else self.stderr).append(data)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num == SYS["write"] and args[0] in self._vfds:
            f = self._vfds[args[0]]
            if not hasattr(f, "PROTO"):  # eventfd/timerfd/PIPE ends
                from shadow_tpu.host.filestate import FileState

                try:
                    data = _vm_read(cpid, args[1], min(args[2], 1 << 20))
                    n = f.write(data)
                except BrokenPipeError:
                    # kernel contract: EPIPE comes WITH SIGPIPE (default
                    # action kills — `seq | head -1` relies on it)
                    self._post_signal(13)
                    if self.state != "running":
                        return True
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EPIPE)
                    return False
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                if n is None:
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on(
                        [(f, FileState.WRITABLE | FileState.ERROR
                          | FileState.CLOSED)],
                        num, args,
                    )
                    return True
                self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
                return False
            return self._handle_socket(SYS["sendto"], [args[0], args[1], args[2], 0, 0, 0])
        if num == SYS["writev"] and args[0] in self._vfds:
            sock = self._vfds[args[0]]
            if args[2] > IOV_MAX:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            try:
                data = self._gather_write(cpid, num, args)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if not hasattr(sock, "PROTO"):
                # eventfd/timerfd/pipes: same semantics as write(2)
                from shadow_tpu.host.filestate import FileState

                try:
                    n = sock.write(data)
                except BrokenPipeError:
                    self._post_signal(13)  # SIGPIPE (kernel contract)
                    if self.state != "running":
                        return True
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EPIPE)
                    return False
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                if n is None:
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on(
                        [(sock, FileState.WRITABLE | FileState.ERROR
                          | FileState.CLOSED)],
                        num, args,
                    )
                    return True
                self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
                return False
            from shadow_tpu.host.sockets import UdpSocket

            try:
                if isinstance(sock, UdpSocket):
                    # one writev = one datagram (must not split per-iov)
                    n = sock.sendto(data, None)
                else:
                    n = sock.write(data)
            except (ConnectionResetError, BrokenPipeError):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                return False
            except OSError as e:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            if n is None:
                if self._nonblock(args[0]):
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                from shadow_tpu.host.filestate import FileState

                self._block_on(
                    [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                    num, args,
                )
                return True
            self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
            return False
        if num == SYS["read"] and args[0] in self._vfds:
            f = self._vfds[args[0]]
            if not hasattr(f, "PROTO"):  # timerfd/eventfd 8-byte reads
                from shadow_tpu.host.filestate import FileState

                try:
                    out = f.read(min(args[2], 1 << 16))
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                if out is None:
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on(
                        [(f, FileState.READABLE | FileState.ERROR | FileState.CLOSED)],
                        num, args,
                    )
                    return True
                _vm_write(cpid, args[1], out)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, len(out))
                return False
            return self._handle_socket(SYS["recvfrom"], [args[0], args[1], args[2], 0, 0, 0])

        if num == SYS["read"]:
            if args[0] == 0 and 0 not in self._stdio_overridden:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)  # stdin: EOF
            else:
                # real-file fds were opened natively; read them natively too
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num in (SYS["write"], SYS["writev"]) and args[0] not in self._vfds:
            # fd is neither stdio (handled above) nor a vfd: it's a regular
            # file the child opened natively — write it natively, mirroring
            # the read/openat passthrough policy (ref regular_file.c).
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num == SYS["ioctl"] and args[0] in (0, 1, 2) and (
            args[0] not in self._stdio_overridden
        ):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOTTY)
            return False

        if num == SYS["getrandom"]:
            n = min(args[1], 1 << 20)
            _vm_write(cpid, args[0], self.host.rng.randbytes(n))
            self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == SYS["getpid"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self.pid)
            return False
        if num == SYS["gettid"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self._cur.vtid)
            return False
        if num == SYS["getppid"]:
            self.ipc.reply(
                MSG_SYSCALL_COMPLETE,
                self.parent.pid if self.parent is not None else 1,
            )
            return False
        if num in (SYS["clone"], SYS["fork"], SYS["vfork"]):
            return self._handle_clone(num, args)
        if num == SYS["wait4"]:
            return self._handle_wait4(args)
        if num == SYS["futex"]:
            return self._handle_futex(args)
        if num == SYS["sched_yield"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["getcpu"]:
            # deterministic single-cpu host (vdso getcpu is patched to the
            # real syscall, which lands here)
            try:
                if args[0]:
                    _vm_write(cpid, args[0], struct.pack("<I", 0))
                if args[1]:
                    _vm_write(cpid, args[1], struct.pack("<I", 0))
            except OSError:
                pass
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["sched_getaffinity"]:
            # report one cpu (deterministic regardless of the real machine)
            if args[1] >= 8:
                _vm_write(cpid, args[2], struct.pack("<Q", 1))
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 8)
            return False
        if num == SYS["rt_sigaction"]:
            # emulated dispositions (handler/signal.rs); the shim's SIGSYS
            # handler is guarded — the app may not replace it
            SIGSYS = 31
            sig = args[0]
            if sig == SIGSYS:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)  # pretend success
                return False
            if args[2]:  # oldact out-param
                oh, of = self._sigactions.get(sig, (SIG_DFL, 0))
                try:
                    _vm_write(cpid, args[2], struct.pack("<qqqq", oh, of, 0, 0))
                except OSError:
                    pass
            if args[1]:  # new act: kernel struct {handler,flags,restorer,mask}
                raw = _vm_read(cpid, args[1], 32)
                if len(raw) < 16:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                handler, flags = struct.unpack_from("<qq", raw)
                self._sigactions[sig] = (handler, flags)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num in (SYS["kill"], SYS["tkill"], SYS["tgkill"]):
            if num == SYS["kill"]:
                tpid, sig, tslot = args[0], args[1], None
            elif num == SYS["tkill"]:
                tpid, sig = None, args[1]
                tslot = args[0]
            else:  # tgkill(tgid, tid, sig)
                tpid, sig = args[0], args[2]
                tslot = args[1]
            if tslot is not None:
                # vtid -> (process, slot): main thread vtid == pid
                vtid = tslot
                owner = None
                for pr in self.host.processes.values():
                    if not isinstance(pr, NativeProcess):
                        continue
                    if vtid == pr.pid:
                        owner, tslot = pr, 0
                        break
                    if any(t.vtid == vtid for t in pr.threads.values()):
                        owner = pr
                        tslot = next(
                            s for s, t in pr.threads.items() if t.vtid == vtid
                        )
                        break
                target = owner
            else:
                target = (
                    self
                    if tpid in (self.pid, 0)
                    else self.host.processes.get(tpid)
                )
            if not isinstance(target, NativeProcess) or target.state != "running":
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ESRCH)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            if sig != 0:
                target._post_signal(sig, tslot, sender=self.pid)
            return False
        if num == SYS["pause"]:
            thr = self._cur
            thr.state = "blocked"  # until a signal wakes it (-EINTR)
            return True
        if num == SYS["alarm"]:
            prev_ns = self._itimer_cancel()
            self._itimer_interval_ns = 0
            if args[0] > 0:
                self._itimer_token = self.host.schedule(
                    self.host.now() + args[0] * NS_PER_SEC, self._itimer_fire
                )
            self.ipc.reply(
                MSG_SYSCALL_COMPLETE, (prev_ns + NS_PER_SEC - 1) // NS_PER_SEC
            )
            return False
        if num in (SYS["setitimer"], SYS["getitimer"]):
            ITIMER_REAL = 0
            if args[0] != ITIMER_REAL:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EINVAL)
                return False
            old_ptr = args[2] if num == SYS["setitimer"] else args[1]
            if old_ptr:
                rem = (
                    max(0, self._itimer_token[0] - self.host.now())
                    if self._itimer_token is not None
                    else 0
                )
                iv = self._itimer_interval_ns
                try:
                    _vm_write(cpid, old_ptr, struct.pack(
                        "<qqqq", iv // NS_PER_SEC, (iv % NS_PER_SEC) // 1000,
                        rem // NS_PER_SEC, (rem % NS_PER_SEC) // 1000,
                    ))
                except OSError:
                    pass
            if num == SYS["setitimer"] and args[1]:
                raw = _vm_read(cpid, args[1], 32)
                if len(raw) < 32:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                iv_s, iv_us, val_s, val_us = struct.unpack("<qqqq", raw)
                self._itimer_cancel()
                self._itimer_interval_ns = iv_s * NS_PER_SEC + iv_us * 1000
                val_ns = val_s * NS_PER_SEC + val_us * 1000
                if val_ns > 0:
                    self._itimer_token = self.host.schedule(
                        self.host.now() + val_ns, self._itimer_fire
                    )
                else:
                    self._itimer_interval_ns = 0
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["exit"] and any(
            t is not self._cur and t.state != "dead"
            for t in self.threads.values()
        ):
            # thread exit while siblings live (pthread_exit path): emulate
            # CLONE_CHILD_CLEARTID — clear the tid word and wake emulated
            # futex waiters (pthread_join) — then let the thread die for
            # real. The kernel's own clear/wake happens invisibly later;
            # ours is the one the emulated waiters see. (thread.rs handling
            # of child-cleartid + handler/futex.c FUTEX_WAKE.)
            thr = self._cur
            thr.state = "dead"
            if thr.clone_flags & CLONE_CHILD_CLEARTID and thr.ctid_addr:
                try:
                    _vm_write(cpid, thr.ctid_addr, struct.pack("<i", 0))
                except OSError:
                    pass
                self._futex_wake_addr(thr.ctid_addr, 1 << 30, FUTEX_BITSET_ALL)
            self.ipc.reply(MSG_SYSCALL_NATIVE)  # the real thread exits
            # recycle the channel slot: both channels ended EMPTY (the exit
            # reply was the last traffic), so a future clone can reuse it
            del self.threads[thr.slot]
            self._free_slots.append(thr.slot)
            return True
        if num in (SYS["exit_group"], SYS["exit"]):
            self.state = "zombie"
            self.exit_code = args[0] & 0xFF
            self._unregister_heap()
            self._flock_release()
            self._clear_wake()
            for sock in self._vfds.values():
                self._drop_vfd(sock)
            self._vfds.clear()
            self.ipc.reply(MSG_SYSCALL_NATIVE)  # let it really exit
            self._child.wait(timeout=10)
            self.ipc.close()
            if self.parent is not None and self.parent.state == "running":
                parent = self.parent
                self.host.schedule(
                    self.host.now(), lambda: parent._child_exited(self)
                )
            self.host.on_process_exit(self)
            return True
        if num in (SYS["poll"], SYS["ppoll"]):
            return self._handle_poll(num, args)
        if num in (SYS["clock_gettime"], SYS["gettimeofday"], SYS["time"]):
            # the shim answers these locally; one in every
            # `unblocked_syscall_limit` calls escapes here when the
            # unblocked-latency model is on — charge the latency by parking
            # the thread, then answer with the ADVANCED clock so
            # spin-on-clock binaries make simulated progress
            # (reference handler/mod.rs:268-318)
            thr = self._cur
            thr.state = "blocked"
            wake_at = (
                self.host.now() + self.host.cfg.unblocked_syscall_latency_ns
            )
            saved = list(args)

            def finish(thr=thr, num=num, args=saved):
                if self.state != "running" or thr.state != "blocked":
                    return
                self._clear_wake(thr)
                now = self.host.now()
                ret = 0
                try:
                    if num == SYS["clock_gettime"] and args[1]:
                        _vm_write(self._child.pid, args[1], struct.pack(
                            "<qq", now // NS_PER_SEC, now % NS_PER_SEC))
                    elif num == SYS["gettimeofday"] and args[0]:
                        _vm_write(self._child.pid, args[0], struct.pack(
                            "<qq", now // NS_PER_SEC,
                            (now % NS_PER_SEC) // 1000))
                    elif num == SYS["time"]:
                        ret = now // NS_PER_SEC
                        if args[0]:
                            _vm_write(self._child.pid, args[0],
                                      struct.pack("<q", ret))
                except OSError:
                    ret = -errno.EFAULT
                thr.state = "wake-ready"
                thr.pending_reply = ret
                self._kick()

            token = self.host.schedule(wake_at, finish)
            thr.wake.append((None, token))
            return True

        # default: refuse with ENOSYS (surface unknown syscalls loudly)
        self.ipc.reply(MSG_SYSCALL_COMPLETE, -38)
        return False

    def _handle_poll(self, num: int, args: list[int]) -> bool:
        """poll/ppoll over emulated-socket vfds (reference poll.c/select.c
        handlers). Real kernel fds in the set are reported with revents=0;
        only vfds are pollable here."""
        from shadow_tpu.host.filestate import FileState

        POLLIN, POLLOUT, POLLERR, POLLHUP = 1, 4, 8, 0x10
        cpid = self._child.pid
        nfds = min(args[1], 64)
        raw = _vm_read(cpid, args[0], nfds * 8)
        fds = [
            struct.unpack_from("<ihh", raw, i * 8) for i in range(len(raw) // 8)
        ]
        timeout_ms = args[2] if num == SYS["poll"] else -1
        if num == SYS["ppoll"] and args[2]:
            ts = _vm_read(cpid, args[2], 16)
            if len(ts) == 16:
                s, ns = struct.unpack("<qq", ts)
                timeout_ms = (s * NS_PER_SEC + ns) // 1_000_000

        ready = 0
        out = bytearray(raw)
        watch = []
        for i, (fd, events, _) in enumerate(fds):
            revents = 0
            sock = self._vfds.get(fd)
            if sock is not None:
                st = sock.state
                if events & POLLIN and st & (
                    FileState.READABLE | FileState.ACCEPTABLE
                ):
                    revents |= POLLIN
                if events & POLLOUT and st & FileState.WRITABLE:
                    revents |= POLLOUT
                if st & FileState.ERROR:
                    revents |= POLLERR
                if st & (FileState.HUP | FileState.CLOSED):
                    revents |= POLLHUP
                mask = FileState.ERROR | FileState.HUP | FileState.CLOSED
                if events & POLLIN:
                    mask |= FileState.READABLE | FileState.ACCEPTABLE
                if events & POLLOUT:
                    mask |= FileState.WRITABLE
                watch.append((sock, mask))
            struct.pack_into("<h", out, i * 8 + 6, revents)
            if revents:
                ready += 1
        now = self.host.now()
        if ready:
            self._cur.poll_deadline = None
            _vm_write(cpid, args[0], bytes(out))
            self.ipc.reply(MSG_SYSCALL_COMPLETE, ready)
            return False
        if timeout_ms == 0 or (
            self._cur.poll_deadline is not None and now >= self._cur.poll_deadline
        ):
            self._cur.poll_deadline = None
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if not watch and timeout_ms < 0:
            self._die(99)  # infinite poll with nothing we can ever signal
            return True
        if timeout_ms < 0:
            self._block_on(watch, num, args)
        else:
            # absolute deadline survives re-runs so a timeout wake that
            # finds nothing ready reports 0 instead of re-arming in full
            if self._cur.poll_deadline is None:
                self._cur.poll_deadline = now + timeout_ms * 1_000_000
            self._block_on(watch, num, args,
                           timeout_ns=self._cur.poll_deadline - now)
        return True

    # ---- uio / msg / select / dup2 / socketpair / exec ---------------------
    # (reference: handler/uio.c, select.c, unistd.c dup arms, socket/unix.rs
    # socketpair, and the execve arm at handler/mod.rs:401)

    def _stdio_target(self, fd: int) -> int | None:
        """Resolve a fd to its captured-stdio target (1|2) or None. The dup
        table wins over the well-known numbers so `dup2(1, 2)` (2>&1) really
        redirects fd 2's writes into the stdout buffer. A REAL kernel fd
        dup2()d onto 0/1/2 (a shell wiring a pipeline stage's stdout into
        a pipe) takes the number OUT of capture: its I/O must reach the
        real object."""
        if fd in self._stdio_overridden:
            return None
        tgt = self._stdio_dups.get(fd)
        if tgt is not None:
            return tgt
        return fd if fd in (1, 2) else None

    def _share_vfd(self, old: int, new: int) -> int:
        """Point `new` at `old`'s emulated descriptor: shared object,
        refcounted so close() of either fd keeps the other alive.
        NOTE: status flags are per-fd here (the kernel shares them via the
        open file description); acceptable deviation — apps set O_NONBLOCK
        right after socket()/accept4 and before dup'ing."""
        sock = self._vfds[old]
        sock._nrefs = getattr(sock, "_nrefs", 1) + 1
        self._vfds[new] = sock
        self._vfd_flags[new] = self._vfd_flags.get(old, 0)
        return new

    def _alloc_vfd(self) -> int:
        while self._next_vfd in self._reserved_fds:
            self._next_vfd += 1
        nfd = self._next_vfd
        self._next_vfd += 1
        return nfd

    def _dup_vfd(self, old: int) -> int:
        return self._share_vfd(old, self._alloc_vfd())

    def _close_virtual(self, fd: int):
        """Silently drop whatever virtual thing occupies `fd` (dup2 target
        semantics: the previous descriptor is implicitly closed). Re-
        pointing a previously REAL-overridden stdio number at a virtual
        object also restores its capture semantics."""
        if fd in self._vfds:
            sock = self._vfds.pop(fd)
            self._vfd_flags.pop(fd, None)
            self._drop_vfd(sock)
        self._stdio_dups.pop(fd, None)
        self._stdio_overridden.discard(fd)
        self._vfd_cloexec.discard(fd)

    def _handle_dup2(self, num: int, args: list[int]) -> bool:
        old, new = args[0], args[1]
        if num == SYS["dup3"] and old == new:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        if old in self._vfds:
            if old == new:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, new)
                return False
            self._close_virtual(new)
            self._share_vfd(old, new)
            if num == SYS["dup3"] and args[2] & O_CLOEXEC:
                self._vfd_cloexec.add(new)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, new)
            return False
        tgt = self._stdio_target(old)
        if tgt is not None:
            if old == new:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, new)
                return False
            self._close_virtual(new)
            self._stdio_dups[new] = tgt
            if num == SYS["dup3"] and args[2] & O_CLOEXEC:
                self._vfd_cloexec.add(new)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, new)
            return False
        # real-file dup2: pass through — but dup2 implicitly closes the
        # target, so any virtual thing occupying that number must die too,
        # or the stale vfd would shadow the freshly dup'ed kernel fd
        self._close_virtual(new)
        if new >= VFD_BASE:
            # the child now owns a REAL kernel fd at this number; the vfd
            # allocator must never hand it out (it would shadow the live fd)
            self._reserved_fds.add(new)
        if new in (0, 1, 2):
            # the shell wired a real object (a pipe) onto a stdio number:
            # that number leaves capture until closed
            self._stdio_overridden.add(new)
        self.ipc.reply(MSG_SYSCALL_NATIVE)
        return False

    # ---- filesystem mutation / notification family (r4) --------------------
    # Reference: handler/fileat.c + handler/file.c dispatch arms
    # (handler/mod.rs:371-539). Policy mirrors the openat/read/write
    # passthrough: paths resolve natively inside the child; the simulator
    # vets the request first (vfd guard + inotify fan-out).

    def _child_path(self, dirfd: int, ptr: int) -> str | None:
        """Resolve a child path argument to an absolute simulator-side path
        (for inotify matching and existence probes only — the syscall
        itself still resolves natively in the child)."""
        try:
            raw = self._read_cstr(self._child.pid, ptr, 4096)
        except OSError:
            return None
        path = raw.decode("utf-8", "surrogateescape")
        if path.startswith("/"):
            return path
        dirfd &= 0xFFFFFFFF
        if dirfd >= 1 << 31:
            dirfd -= 1 << 32
        try:
            if dirfd == AT_FDCWD:
                base = os.readlink(f"/proc/{self._child.pid}/cwd")
            else:
                base = os.readlink(f"/proc/{self._child.pid}/fd/{dirfd}")
        except OSError:
            return None
        return os.path.join(base, path)

    def _fs_note(self, path: str | None, mask: int, cookie: int = 0):
        """Fan a filesystem event out to every inotify instance on this
        host (watches are host-scoped: the host's processes share one fs
        view, like the reference's per-host filesystem)."""
        if path is None or not mask:
            return
        for ifd in self.host.__dict__.get("_inotify_fds", []):
            ifd.note(path, mask, cookie)

    def _handle_fs_path(self, num: int, args: list[int]) -> bool:
        # the inotify fan-out is gated on live watchers AND on an
        # existence probe matching what the native syscall will see
        # (mkdir-EEXIST / unlink-ENOENT must not emit phantom events; the
        # simulator shares the child's fs view, so the probe agrees with
        # the syscall's outcome modulo permissions)
        if self.host.__dict__.get("_inotify_fds"):
            self._fs_path_events(num, args)
        self.ipc.reply(MSG_SYSCALL_NATIVE)
        return False

    def _fs_path_events(self, num: int, args: list[int]):
        S = SYS
        exists = os.path.lexists
        if num in (S["rename"], S["renameat"], S["renameat2"]):
            if num == S["rename"]:
                old = self._child_path(AT_FDCWD, args[0])
                new = self._child_path(AT_FDCWD, args[1])
            else:
                old = self._child_path(args[0], args[1])
                new = self._child_path(args[2], args[3])
            if not (old and exists(old)):
                return  # the rename will fail with ENOENT
            # cookies pair MOVED_FROM/TO across the HOST (watches are
            # host-scoped, so two processes renaming concurrently must not
            # collide on a per-process counter)
            cookie = self.host.__dict__.get("_fs_cookie", 0) + 1
            self.host.__dict__["_fs_cookie"] = cookie
            isdir = IN_ISDIR if os.path.isdir(old) else 0
            self._fs_note(old, IN_MOVED_FROM | isdir, cookie)
            self._fs_note(new, IN_MOVED_TO | isdir, cookie)
            return
        if num in (S["link"], S["symlink"], S["symlinkat"], S["linkat"],
                   S["mknod"], S["mknodat"], S["creat"]):
            if num in (S["link"], S["symlink"]):
                p = self._child_path(AT_FDCWD, args[1])
            elif num == S["symlinkat"]:
                p = self._child_path(args[1], args[2])
            elif num == S["linkat"]:
                p = self._child_path(args[2], args[3])
            elif num == S["mknodat"]:
                p = self._child_path(args[0], args[1])
            else:  # mknod, creat
                p = self._child_path(AT_FDCWD, args[0])
            if p and not exists(p):  # EEXIST emits nothing
                self._fs_note(p, IN_CREATE)
            return
        if num in (S["mkdir"], S["mkdirat"]):
            p = (self._child_path(AT_FDCWD, args[0]) if num == S["mkdir"]
                 else self._child_path(args[0], args[1]))
            if p and not exists(p):
                self._fs_note(p, IN_CREATE | IN_ISDIR)
            return
        if num in (S["unlink"], S["rmdir"], S["unlinkat"]):
            if num == S["unlinkat"]:
                p = self._child_path(args[0], args[1])
                mask = (IN_DELETE | IN_ISDIR if args[2] & AT_REMOVEDIR
                        else IN_DELETE)
            else:
                p = self._child_path(AT_FDCWD, args[0])
                mask = (IN_DELETE | IN_ISDIR if num == S["rmdir"]
                        else IN_DELETE)
            if p and exists(p):  # ENOENT emits nothing
                self._fs_note(p, mask)
            return
        # attrib/modify family: target must exist for the syscall to work
        if num in (S["fchmodat"], S["fchownat"], S["utimensat"],
                   S["futimesat"], S["fchmodat2"]):
            p = self._child_path(args[0], args[1])
        else:
            p = self._child_path(AT_FDCWD, args[0])
        if p and exists(p):
            self._fs_note(p, _FS_EVENT.get(num, IN_ATTRIB))

    def _handle_fs_fd(self, num: int, args: list[int]) -> bool:
        fd = args[0]
        if fd in self._vfds or fd in self._stdio_dups:
            if num == SYS["fstatfs"]:
                # minimal sockfs-shaped statfs for emulated descriptors.
                # struct statfs on x86-64 is EXACTLY 120 bytes (15 longs:
                # f_type f_bsize f_blocks f_bfree f_bavail f_files f_ffree
                # f_fsid[8B] f_namelen f_frsize f_flags f_spare[4]); packing
                # 16 would overflow the guest's buffer by 8 bytes.
                buf = struct.pack("<15q", SOCKFS_MAGIC, 4096, *([0] * 13))
                try:
                    _vm_write(self._child.pid, args[1], buf)
                except OSError:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            # ftruncate/fsync/flock/chmod/xattr on an emulated descriptor:
            # EINVAL (the kernel's answer for non-regular files)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        # real kernel fd: resolve its path for inotify, then pass through
        if num in (SYS["ftruncate"], SYS["fallocate"], SYS["fchmod"],
                   SYS["fchown"], SYS["fsetxattr"]):
            mask = (IN_MODIFY if num in (SYS["ftruncate"], SYS["fallocate"])
                    else IN_ATTRIB)
            try:
                path = os.readlink(f"/proc/{self._child.pid}/fd/{fd}")
            except OSError:
                path = None
            if path and path.startswith("/"):
                self._fs_note(path, mask)
        self.ipc.reply(MSG_SYSCALL_NATIVE)
        return False

    def _handle_flock(self, args: list[int]) -> bool:
        """flock(2) emulated against a HOST-scoped lock table keyed by
        (st_dev, st_ino) — a native flock could block the child invisibly
        in the kernel, deadlocking the one-runner-at-a-time scheduler
        (exactly the futex rationale; reference emulates file locks in its
        handler layer too). Blocked lockers park in SIM time and re-run on
        release. Divergence: lock ownership is tracked per (pid, fd), not
        per open-file-description, so dup'd fds count as separate owners."""
        fd, op = args[0], args[1]
        if fd in self._vfds or fd in self._stdio_dups:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False
        try:
            st = os.stat(f"/proc/{self._child.pid}/fd/{fd}")
        except OSError:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False
        table = self.host.__dict__.setdefault("_flocks", {})
        key = (st.st_dev, st.st_ino)
        ent = table.setdefault(key, {"ex": None, "sh": set(), "waiters": []})
        me = (self.pid, fd)
        base = op & ~LOCK_NB
        if base == LOCK_UN:
            released = ent["ex"] == me or me in ent["sh"]
            if ent["ex"] == me:
                ent["ex"] = None
            ent["sh"].discard(me)
            if released:
                self._flock_schedule_wake(key)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if base not in (LOCK_SH, LOCK_EX):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        others_ex = ent["ex"] is not None and ent["ex"] != me
        others_sh = bool(ent["sh"] - {me})
        if base == LOCK_SH and not others_ex:
            downgraded = ent["ex"] == me
            if downgraded:
                ent["ex"] = None
            ent["sh"].add(me)
            if downgraded:
                self._flock_schedule_wake(key)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if base == LOCK_EX and not others_ex and not others_sh:
            ent["sh"].discard(me)
            ent["ex"] = me
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        # Conversion semantics per flock(2): converting an existing lock is
        # NOT atomic — the old lock is removed first, then the new one is
        # requested, so a failed LOCK_NB conversion LOSES the old lock and
        # a blocking conversion parks lock-free (which also prevents two SH
        # holders upgrading concurrently from deadlocking on each other).
        dropped = ent["ex"] == me or me in ent["sh"]
        if ent["ex"] == me:
            ent["ex"] = None
        ent["sh"].discard(me)
        if dropped:
            self._flock_schedule_wake(key)
        if op & LOCK_NB:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EWOULDBLOCK)
            return False
        thr = self._cur
        thr.state = "blocked"
        thr.blocked_num = SYS["flock"]
        thr.blocked_args = list(args)
        ent["waiters"].append((self, thr))
        return True  # parked until a release re-runs us

    def _flock_schedule_wake(self, key):
        """Defer waiter retries to the host event loop (the releaser's
        service loop is live; re-entering another process's loop from here
        would nest schedulers)."""
        host = self.host
        host.schedule(host.now(), lambda: _flock_wake(host, key))

    def _flock_release(self, fd: int | None = None,
                       span: tuple[int, int] | None = None):
        """Release locks on close/close_range (kernel contract) or on
        process death; fd=None and span=None drops everything this pid
        holds or waits for."""
        table = self.host.__dict__.get("_flocks")
        if not table:
            return
        for key, ent in list(table.items()):
            def mine(m):
                if m[0] != self.pid:
                    return False
                if fd is not None:
                    return m[1] == fd
                if span is not None:
                    return span[0] <= m[1] <= span[1]
                return True

            changed = False
            if ent["ex"] is not None and mine(ent["ex"]):
                ent["ex"] = None
                changed = True
            n0 = len(ent["sh"])
            ent["sh"] = {m for m in ent["sh"] if not mine(m)}
            changed |= len(ent["sh"]) != n0
            if fd is None and span is None:  # process death: drop waiters
                ent["waiters"] = [
                    (p, t) for p, t in ent["waiters"] if p is not self
                ]
            if changed:
                self._flock_schedule_wake(key)

    def _handle_signalfd(self, num: int, args: list[int]) -> bool:
        fd = args[0] & 0xFFFFFFFF
        if fd >= 1 << 31:
            fd -= 1 << 32
        try:
            raw = _vm_read(self._child.pid, args[1], 8)
            mask = struct.unpack("<Q", raw)[0] if len(raw) == 8 else 0
        except OSError:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
            return False
        if fd == -1:
            vfd = self._alloc_vfd()
            self._vfds[vfd] = SignalFd(mask)
            if num == SYS["signalfd4"] and args[3] & 0x800:  # SFD_NONBLOCK
                self._vfd_flags[vfd] = O_NONBLOCK
            if num == SYS["signalfd4"] and args[3] & O_CLOEXEC:
                self._vfd_cloexec.add(vfd)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, vfd)
            return False
        sfd = self._vfds.get(fd)
        if not isinstance(sfd, SignalFd):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        sfd.mask = mask  # update-in-place form
        self.ipc.reply(MSG_SYSCALL_COMPLETE, fd)
        return False

    def _handle_inotify(self, num: int, args: list[int]) -> bool:
        S = SYS
        if num in (S["inotify_init"], S["inotify_init1"]):
            vfd = self._alloc_vfd()
            self._vfds[vfd] = InotifyFd(self.host)
            if num == S["inotify_init1"] and args[0] & 0x800:  # IN_NONBLOCK
                self._vfd_flags[vfd] = O_NONBLOCK
            if num == S["inotify_init1"] and args[0] & O_CLOEXEC:
                self._vfd_cloexec.add(vfd)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, vfd)
            return False
        ifd = self._vfds.get(args[0])
        if not isinstance(ifd, InotifyFd):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        if num == S["inotify_add_watch"]:
            path = self._child_path(AT_FDCWD, args[1])
            if path is None:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if not os.path.exists(path):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOENT)
                return False
            self.ipc.reply(
                MSG_SYSCALL_COMPLETE, ifd.add_watch(path, args[2])
            )
            return False
        self.ipc.reply(MSG_SYSCALL_COMPLETE, ifd.rm_watch(args[1]))
        return False

    def _handle_sendfile(self, args: list[int]) -> bool:
        """sendfile(out_fd, in_fd, offset*, count) with out_fd an emulated
        socket: python's http.server / socket.sendfile fast path. The
        child's file is read via /proc/<pid>/fd (same inode, simulator-side
        offset) and pushed through the emulated socket; the offset word is
        advanced in child memory like the kernel does. NULL offset would
        require mutating the child's file position from outside —
        unsupported, EINVAL (callers fall back to a send loop, python
        does)."""
        sock = self._vfds.get(args[0])
        if sock is None:
            # out_fd not emulated: regular-file-to-file copy, pass through
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if not hasattr(sock, "PROTO") or not args[2]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        count = min(args[3], 1 << 20)
        try:
            raw = _vm_read(self._child.pid, args[2], 8)
            off = struct.unpack("<q", raw)[0]
            with open(f"/proc/{self._child.pid}/fd/{args[1]}", "rb") as f:
                f.seek(off)
                data = f.read(count)
        except (OSError, struct.error):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False
        if not data:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        try:
            n = self._do_send(sock, data, None)
        except (ConnectionResetError, BrokenPipeError):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EPIPE)
            return False
        except OSError as e:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
            return False
        if n is None:  # would block
            from shadow_tpu.host.filestate import FileState

            if self._nonblock(args[0]):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                return False
            self._block_on(
                [(sock, FileState.WRITABLE | FileState.ERROR
                  | FileState.CLOSED)],
                SYS["sendfile"], args,
            )
            return True
        try:
            _vm_write(self._child.pid, args[2], struct.pack("<q", off + n))
        except OSError:
            pass
        self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
        return False

    def _read_iovs(self, cpid: int, iov_ptr: int, iovcnt: int):
        iovcnt = min(iovcnt, IOV_MAX)
        raw = _vm_read(cpid, iov_ptr, iovcnt * 16)
        return [struct.unpack_from("<QQ", raw, i * 16)
                for i in range(len(raw) // 16)]

    def _scatter(self, cpid: int, iovs, data: bytes) -> int:
        # one batched process_vm_writev across all iovecs
        return _vm_write_multi(cpid, list(iovs), data)

    def _handle_readv(self, args: list[int]) -> bool:
        from shadow_tpu.host.filestate import FileState

        cpid = self._child.pid
        f = self._vfds.get(args[0])
        if f is None:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False
        try:
            iovs = self._read_iovs(cpid, args[1], args[2])
        except OSError:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
            return False
        total = min(sum(ln for _, ln in iovs), 1 << 20)
        try:
            data = f.read(total)
        except (ConnectionResetError, BrokenPipeError):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
            return False
        except OSError as e:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
            return False
        if data is None:
            if self._nonblock(args[0]):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                return False
            self._block_on(
                [(f, FileState.READABLE | FileState.ACCEPTABLE
                  | FileState.HUP | FileState.ERROR | FileState.CLOSED)],
                SYS["readv"], args,
            )
            return True
        try:
            n = self._scatter(cpid, iovs, data)
        except OSError:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
            return False
        self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
        return False

    # msghdr (x86-64): name(8) namelen(4+4pad) iov(8) iovlen(8) control(8)
    # controllen(8) flags(4+4pad) = 56 bytes; mmsghdr adds u32 msg_len(+pad)
    _MSGHDR_FMT = "<QI4xQQQQi4x"
    _MSGHDR_SIZE = 56
    _MMSGHDR_STRIDE = 64

    def _read_msghdr(self, cpid: int, ptr: int):
        raw = _vm_read(cpid, ptr, self._MSGHDR_SIZE)
        if len(raw) < self._MSGHDR_SIZE:
            return None
        name, namelen, iov, iovlen, control, controllen, flags = (
            struct.unpack(self._MSGHDR_FMT, raw)
        )
        return name, namelen, iov, iovlen, control, controllen

    # ---- SCM_RIGHTS (r4; reference socket/unix.rs ancillary handling) ------

    def _parse_scm_rights(self, cpid: int, ctrl: int, ctrl_len: int):
        """Walk the sender's cmsg region; returns the list of emulated
        descriptor objects being passed (each with an in-flight reference
        taken), or a negative errno. Only vfds can cross: a real kernel fd
        lives in the sender's fd table and cannot be grafted into another
        process from outside — EBADF, loudly."""
        try:
            raw = _vm_read(cpid, ctrl, min(ctrl_len, 1024))
        except OSError:
            return -errno.EFAULT
        objs: list = []
        off = 0
        while off + 16 <= len(raw):
            clen, level, ctype = struct.unpack_from("<qii", raw, off)
            if clen < 16 or off + clen > len(raw):
                break
            if level == 1 and ctype == 0x01:  # SOL_SOCKET, SCM_RIGHTS
                for i in range((clen - 16) // 4):
                    fd = struct.unpack_from("<i", raw, off + 16 + 4 * i)[0]
                    obj = self._vfds.get(fd)
                    if obj is None:
                        for o in objs:
                            self._drop_vfd(o)
                        return -EBADF
                    obj._nrefs = getattr(obj, "_nrefs", 1) + 1
                    objs.append(obj)
            off += (clen + 7) & ~7
        return objs

    def _emit_rights(self, cpid: int, mptr: int, ctrl: int, ctrl_len: int,
                     objs: list) -> bool:
        """Install received fds into this process's vfd table and write the
        SCM_RIGHTS cmsg + msg_controllen back into child memory. Rights
        that don't fit the control buffer are dropped; returns True when
        that happened so the caller can set MSG_CTRUNC in msg_flags."""
        space = (min(ctrl_len, 1024) - 16) // 4 if ctrl else 0
        take, spill = objs[: max(space, 0)], objs[max(space, 0):]
        for obj in spill:
            self._drop_vfd(obj)
        new_len = 0
        if take:
            fds = []
            for obj in take:
                nfd = self._alloc_vfd()
                self._vfds[nfd] = obj  # the in-flight ref transfers here
                fds.append(nfd)
            cms = struct.pack("<qii", 16 + 4 * len(fds), 1, 0x01)
            cms += struct.pack(f"<{len(fds)}i", *fds)
            new_len = len(cms)
            try:
                _vm_write(cpid, ctrl, cms)
            except OSError:
                pass
        if ctrl:
            try:  # kernel updates msg_controllen in place (offset 40)
                _vm_write(cpid, mptr + 40, struct.pack("<Q", new_len))
            except OSError:
                pass
        return bool(spill)

    def _do_send(self, sock, data: bytes, addr):
        """Returns bytes sent or None = would-block; raises OSError."""
        from shadow_tpu.host.sockets import UdpSocket

        if isinstance(sock, UdpSocket):
            return sock.sendto(data, addr)
        return sock.write(data)

    def _do_recv(self, sock, total: int, peek: bool = False):
        """Returns (data, addr|None) or None = would-block. addr is
        (ip, port) for inet, ("@unix", src_name) for unix datagrams."""
        from shadow_tpu.host.sockets import UdpSocket
        from shadow_tpu.host.unix import UnixDgramSocket

        if isinstance(sock, UdpSocket):
            r = sock.peekfrom(total) if peek else sock.recvfrom(total)
            return None if r is None else r
        if isinstance(sock, UnixDgramSocket) and not peek:
            r = sock.recv_from(total)  # keeps the sender for msg_name
            return None if r is None else (r[0], ("@unix", r[1]))
        data = sock.peek(total) if peek else sock.read(total)
        return None if data is None else (data, None)

    def _handle_msg(self, num: int, args: list[int]) -> bool:
        from shadow_tpu.host.filestate import FileState

        cpid = self._child.pid
        S = SYS
        sock = self._vfds.get(args[0])
        if sock is None:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False
        single = num in (S["sendmsg"], S["recvmsg"])
        sending = num in (S["sendmsg"], S["sendmmsg"])
        vlen = 1 if single else min(args[2], 64)
        wait_r = (FileState.READABLE | FileState.HUP | FileState.ERROR
                  | FileState.CLOSED)
        wait_w = FileState.WRITABLE | FileState.ERROR | FileState.CLOSED
        done = 0
        for i in range(vlen):
            mptr = args[1] + (0 if single else i * self._MMSGHDR_STRIDE)
            try:
                hdr = self._read_msghdr(cpid, mptr)
            except OSError:
                hdr = None
            if hdr is None:
                if done:
                    break
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            name, namelen, iov_ptr, iovlen, ctrl, ctrl_len = hdr
            try:
                iovs = self._read_iovs(cpid, iov_ptr, iovlen)
            except OSError:
                # faulting iovec array = EFAULT (not a 0-byte transfer the
                # peer could observe), same contract as the msghdr fault
                if done:
                    break
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if sending:
                from shadow_tpu.host.unix import (
                    UnixDgramSocket,
                    UnixStreamSocket,
                )

                unix_dgram = isinstance(sock, UnixDgramSocket)
                try:
                    data = _vm_read_multi(
                        cpid, [(b, min(ln, 1 << 20)) for b, ln in iovs]
                    )
                    addr = sun = None
                    if name and unix_dgram:
                        # msg_name is a sockaddr_un: addressed datagram
                        # (the canonical fd-passing / sd_notify pattern)
                        sun = self._read_sun(name, namelen)
                    elif name and namelen >= 8:
                        addr = _parse_sockaddr_in(_vm_read(cpid, name, 16))
                except OSError:
                    if done:
                        break
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                rights = None
                if ctrl and ctrl_len >= 16:
                    rights = self._parse_scm_rights(cpid, ctrl, ctrl_len)
                    if isinstance(rights, int):  # negative errno
                        if done:
                            break
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, rights)
                        return False
                    if rights and not isinstance(
                        sock, (UnixStreamSocket, UnixDgramSocket)
                    ):
                        # fd passing is a unix-domain feature
                        for o in rights:
                            self._drop_vfd(o)
                        if done:
                            break
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                        return False
                    if rights and unix_dgram:
                        # rides WITH this datagram through send_to
                        sock._pending_rights = rights
                try:
                    if unix_dgram and sun is not None:
                        n = sock.send_to(self._unix_ns(), sun, bytes(data))
                    else:
                        n = self._do_send(sock, bytes(data), addr)
                except (ConnectionResetError, BrokenPipeError):
                    if done:
                        break
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                    return False
                except OSError as e:
                    if done:
                        break
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                    return False
                if n is None:  # would block
                    if rights and isinstance(sock, UnixStreamSocket):
                        # undo the in-flight refs: the re-run re-parses
                        for o in rights:
                            self._drop_vfd(o)
                    if done:
                        break
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on([(sock, wait_w)], num, args)
                    return True
                if rights and isinstance(sock, UnixStreamSocket):
                    peer = getattr(sock, "peer", None)
                    if peer is not None and not peer.closed:
                        peer.anc_rx.append(rights)
                    else:
                        for o in rights:
                            self._drop_vfd(o)
                if single:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
                    return False
                try:
                    _vm_write(cpid, mptr + self._MSGHDR_SIZE,
                              struct.pack("<I", n))
                except OSError:
                    pass
                done += 1
            else:
                total = min(sum(ln for _, ln in iovs), 1 << 20)
                peek = bool(
                    (args[2] if single else args[3]) & MSG_PEEK
                )
                try:
                    r = self._do_recv(sock, total, peek)
                except (ConnectionResetError, BrokenPipeError):
                    if done:
                        break
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                    return False
                except OSError as e:
                    if done:
                        break
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                    return False
                if r is None:
                    if done:
                        break
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on([(sock, wait_r)], num, args)
                    return True
                data, addr = r
                from shadow_tpu.host.unix import (
                    UnixDgramSocket,
                    UnixStreamSocket,
                )

                # rights transfer only on a CONSUMING read (kernel: a
                # MSG_PEEK leaves ancillary attached for the real recvmsg)
                robjs = None
                if not peek:
                    if isinstance(sock, UnixDgramSocket):
                        robjs = sock.claim_rights()
                    elif isinstance(sock, UnixStreamSocket) and sock.anc_rx:
                        robjs = sock.anc_rx.pop(0)
                # the payload is consumed at this point: out-param faults
                # degrade to partial writes instead of losing the syscall
                n = 0
                try:
                    n = self._scatter(cpid, iovs, data)
                    # peer name (value-result via the namelen field) + any
                    # passed fds (SCM_RIGHTS), flags zeroed
                    if name and addr is not None:
                        if addr[0] == "@unix":
                            src = addr[1]
                            sa = struct.pack("<H", AF_UNIX)
                            if src:
                                sa += ((b"\0" + src[1:].encode())
                                       if src.startswith("@")
                                       else src.encode() + b"\0")
                        else:
                            sa = _build_sockaddr_in(addr[0], addr[1])
                        _vm_write(cpid, name, sa[: min(namelen, len(sa))])
                        _vm_write(cpid, mptr + 8, struct.pack("<I", len(sa)))
                    msg_flags = 0
                    if robjs:
                        if self._emit_rights(cpid, mptr, ctrl, ctrl_len,
                                             robjs):
                            msg_flags |= 0x8  # MSG_CTRUNC: fds were lost
                        robjs = None
                    else:
                        _vm_write(cpid, mptr + 40, struct.pack("<Q", 0))
                    _vm_write(cpid, mptr + 48, struct.pack("<i", msg_flags))
                except OSError:
                    if robjs:
                        for o in robjs:
                            self._drop_vfd(o)
                if single:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
                    return False
                try:
                    _vm_write(cpid, mptr + self._MSGHDR_SIZE,
                              struct.pack("<I", n))
                except OSError:
                    pass
                done += 1
        self.ipc.reply(MSG_SYSCALL_COMPLETE, done)
        return False

    def _handle_select(self, num: int, args: list[int]) -> bool:
        """select/pselect6 over emulated vfds (reference handler/select.c).
        Real kernel fds in the sets are never ready (same policy as poll);
        the pselect sigmask is ignored (signals are emulated and delivered
        at syscall boundaries anyway)."""
        from shadow_tpu.host.filestate import FileState

        cpid = self._child.pid
        nfds = min(max(args[0], 0), 1024)
        nbytes = (nfds + 7) // 8
        bits = []
        for ptr in (args[1], args[2], args[3]):
            if ptr and nbytes:
                try:
                    raw = _vm_read(cpid, ptr, nbytes)
                except OSError:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                    return False
                bits.append(int.from_bytes(raw, "little"))
            else:
                bits.append(0)
        rbits, wbits, ebits = bits
        timeout_ns = None
        if args[4]:
            try:
                raw = _vm_read(cpid, args[4], 16)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            if len(raw) == 16:
                s, frac = struct.unpack("<qq", raw)
                timeout_ns = s * NS_PER_SEC + (
                    frac * 1000 if num == SYS["select"] else frac
                )
        out_r = out_w = out_e = 0
        watch = []
        for fd in range(nfds):
            m = 1 << fd
            want_r, want_w, want_e = rbits & m, wbits & m, ebits & m
            if not (want_r or want_w or want_e):
                continue
            sock = self._vfds.get(fd)
            if sock is None:
                continue  # real kernel fd: not pollable here
            st = sock.state
            if want_r and st & (
                FileState.READABLE | FileState.ACCEPTABLE
                | FileState.HUP | FileState.CLOSED
            ):
                out_r |= m
            if want_w and st & FileState.WRITABLE:
                out_w |= m
            if want_e and st & FileState.ERROR:
                out_e |= m
            mask = FileState.ERROR | FileState.CLOSED
            if want_r:
                mask |= (FileState.READABLE | FileState.ACCEPTABLE
                         | FileState.HUP)
            if want_w:
                mask |= FileState.WRITABLE
            watch.append((sock, mask))

        def writeback():
            try:
                for ptr, val in ((args[1], out_r), (args[2], out_w),
                                 (args[3], out_e)):
                    if ptr and nbytes:
                        _vm_write(cpid, ptr, val.to_bytes(nbytes, "little"))
            except OSError:
                return False
            return True

        ready = (bin(out_r).count("1") + bin(out_w).count("1")
                 + bin(out_e).count("1"))
        now = self.host.now()
        if ready:
            self._cur.poll_deadline = None
            ok = writeback()
            self.ipc.reply(MSG_SYSCALL_COMPLETE,
                           ready if ok else -errno.EFAULT)
            return False
        if timeout_ns == 0 or (
            self._cur.poll_deadline is not None
            and now >= self._cur.poll_deadline
        ):
            self._cur.poll_deadline = None
            ok = writeback()  # all-zero sets
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0 if ok else -errno.EFAULT)
            return False
        if not watch and timeout_ns is None:
            self._die(99)  # infinite select on nothing we can ever signal
            return True
        if timeout_ns is None:
            self._block_on(watch, num, args)
        else:
            if self._cur.poll_deadline is None:
                self._cur.poll_deadline = now + timeout_ns
            self._block_on(watch, num, args,
                           timeout_ns=self._cur.poll_deadline - now)
        return True

    def _handle_socketpair(self, args: list[int]) -> bool:
        from shadow_tpu.host.unix import UnixDgramSocket, UnixStreamSocket

        domain, typ = args[0], args[1]
        if domain != AF_UNIX:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAFNOSUPPORT)
            return False
        kind = typ & SOCK_TYPE_MASK
        if kind == SOCK_STREAM:
            a, b = UnixStreamSocket.make_pair()
        elif kind == SOCK_DGRAM:
            a, b = UnixDgramSocket.make_pair()
        else:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EOPNOTSUPP)
            return False
        fds = []
        for s in (a, b):
            fd = self._alloc_vfd()
            self._vfds[fd] = s
            if typ & SOCK_NONBLOCK:
                self._vfd_flags[fd] = 0x800
            fds.append(fd)
        try:
            _vm_write(self._child.pid, args[3], struct.pack("<ii", *fds))
        except OSError:
            for fd in fds:
                self._close_virtual(fd)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
            return False
        self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
        return False

    def _bytes_avail(self, sock) -> int:
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.pipe import StreamEnd
        from shadow_tpu.host.sockets import TcpSocket, UdpSocket

        if isinstance(sock, UdpSocket):
            return len(sock._rcv[0][2]) if sock._rcv else 0
        if isinstance(sock, TcpSocket):
            return int(sock.tcp.rcv_buf.readable())
        if isinstance(sock, StreamEnd) and sock._rx is not None:
            return len(sock._rx.data)
        return 8 if sock.state & FileState.READABLE else 0

    def _handle_vfd_ioctl(self, args: list[int]) -> bool:
        sock = self._vfds[args[0]]
        req = args[1]
        if req == FIONREAD:
            try:
                _vm_write(self._child.pid, args[2],
                          struct.pack("<i", self._bytes_avail(sock)))
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if req == FIONBIO:
            try:
                raw = _vm_read(self._child.pid, args[2], 4)
            except OSError:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            v = struct.unpack("<i", raw)[0] if len(raw) == 4 else 0
            flags = self._vfd_flags.get(args[0], 0)
            self._vfd_flags[args[0]] = (
                flags | 0x800 if v else flags & ~0x800
            )
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOTTY)
        return False

    def _read_cstr(self, cpid: int, addr: int, maxlen: int = 4096) -> bytes:
        """NUL-terminated string read that never crosses an unmapped page
        (process_vm_readv is all-or-nothing per iovec on fault)."""
        out = bytearray()
        while len(out) < maxlen:
            chunk = min(4096 - (addr & 0xFFF), maxlen - len(out))
            raw = _vm_read(cpid, addr, chunk)
            if not raw:
                break
            i = raw.find(b"\0")
            if i >= 0:
                out += raw[:i]
                return bytes(out)
            out += raw
            addr += len(raw)
        return bytes(out)

    def _read_cstr_array(self, cpid: int, ptr: int) -> list[str]:
        out = []
        for i in range(512):
            raw = _vm_read(cpid, ptr + i * 8, 8)
            if len(raw) < 8:
                break
            p = struct.unpack("<Q", raw)[0]
            if p == 0:
                break
            out.append(
                self._read_cstr(cpid, p).decode("utf-8", "surrogateescape")
            )
        return out

    def _handle_execve(self, args: list[int],
                       path_override: str | None = None) -> bool:
        """execve: replace the native child with a freshly spawned process
        image, exactly like the reference — which SIGKILLs the old native
        process and posix_spawns the target under a new ManagedThread
        (process.rs:1680-1725 update_for_exec) rather than letting the old
        image exec in place (the inherited seccomp filter would kill the
        new image before the shim constructor could install its handler).

        Virtual state survives per execve(2): vfds (no CLOEXEC emulation —
        our emulated descriptors are never close-on-exec), pending itimers,
        captured-stdio buffers, virtual pid, parent/children links. Signal
        dispositions reset to default. Natively-opened regular files of the
        old image are lost (deviation: the kernel would keep them; our
        passthrough files live in the dead process's fd table)."""
        cpid = self._child.pid
        try:
            path = (
                path_override
                if path_override is not None
                else self._read_cstr(cpid, args[0]).decode(
                    "utf-8", "surrogateescape"
                )
            )
            argv = self._read_cstr_array(cpid, args[1]) if args[1] else []
            envp = self._read_cstr_array(cpid, args[2]) if args[2] else []
        except OSError:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
            return False
        # resolve relative paths against the CALLER'S cwd (chdir is native,
        # so the child's cwd can differ from the simulator's); execveat
        # passes an already-resolved override
        try:
            child_cwd = os.readlink(f"/proc/{cpid}/cwd")
        except OSError:
            child_cwd = os.getcwd()
        if not os.path.isabs(path):
            path = os.path.join(child_cwd, path)
        # preflight the failure modes execve(2) documents so a doomed exec
        # errors in the OLD image instead of killing the process
        # (managed_thread.rs:556-577 does the same preemptive checks)
        if not path or not os.path.exists(path):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOENT)
            return False
        if os.path.isdir(path) or not os.access(path, os.X_OK):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.EACCES)
            return False
        if self.strace is not None:
            self.strace(self.host.now(), self.pid, "execve",
                        (path, len(argv), len(envp)), None)
        # preserve the old image's REAL fd table (exec semantics: every
        # non-CLOEXEC fd survives — a shell pipeline stage's stdin/stdout
        # pipes most of all). pidfd_getfd pulls each fd into the
        # simulator; the fds ride to the new image via pass_fds and the
        # shim remaps them to their original numbers from SHADOW_FD_MAP
        # before anything else runs.
        fd_map: list[tuple[int, int]] = []  # (target number, our dup)
        try:
            pidfd = os.pidfd_open(cpid)
        except OSError:
            pidfd = -1
        if pidfd >= 0:
            try:
                child_fds = sorted(
                    int(nm) for nm in os.listdir(f"/proc/{cpid}/fd")
                )
                # park ABOVE every target number so apply_fd_map's
                # dup2(src, tgt); close(src) sequence can never clobber a
                # src another entry still needs
                park_base = max([3000] + [f + 1 for f in child_fds])
                for tgt in child_fds:
                    if tgt in (0, 1, 2) and tgt not in self._stdio_overridden:
                        continue  # captured stdio: fresh DEVNULLs
                    if tgt in self._vfds or tgt in self._stdio_dups:
                        continue  # emulated objects survive via the tables
                    try:
                        with open(f"/proc/{cpid}/fdinfo/{tgt}") as f:
                            flags = int(
                                f.read().split("flags:")[1].split()[0], 8
                            )
                        if flags & O_CLOEXEC:  # dies at exec
                            continue
                        g = _pidfd_getfd(pidfd, tgt)
                        hi = fcntl_mod.fcntl(g, fcntl_mod.F_DUPFD, park_base)
                        os.close(g)
                        os.set_inheritable(hi, True)
                    except OSError:
                        continue
                    fd_map.append((tgt, hi))
            finally:
                os.close(pidfd)

        # spawn the new image FIRST (fresh IPC block, the CALLER'S envp plus
        # the simulator plumbing): a spawn failure — e.g. ENOEXEC for a bad
        # binary format the preflight can't see — must error in the OLD
        # image, which is still alive and blocked on this syscall
        new_ipc = IpcBlock()
        env = {}
        for kv in envp:
            k, _, v = kv.partition("=")
            env[k] = v
        env["LD_PRELOAD"] = shim_path()
        env["SHADOW_SHM_PATH"] = new_ipc.path
        env["SHADOW_FD_MAP"] = ",".join(f"{t}:{h}" for t, h in fd_map)
        new_ipc.set_time(self.host.now())
        hcfg = self.host.cfg
        if hcfg.model_unblocked_latency:
            new_ipc.set_flags((hcfg.unblocked_syscall_limit << 1) | 1)
        try:
            new_child = subprocess.Popen(
                argv or [path], executable=path, env=env, cwd=child_cwd,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                stdin=subprocess.DEVNULL,
                pass_fds=[h for _, h in fd_map],
            )
        except OSError as e:
            new_ipc.close()
            for _, h in fd_map:
                os.close(h)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -(e.errno or errno.ENOEXEC))
            return False
        for _, h in fd_map:  # our copies: the child holds its own now
            os.close(h)
        # point of no return: tear down the old native process (threads die
        # with it, per exec) and swap the new image in. Close-on-exec
        # EMULATED descriptors drop here (kernel contract; git's
        # child_process protocol relies on a spawned pack-objects NOT
        # holding its own pipe's write end — the EOF would never arrive
        # and both sides deadlock). Only now: a FAILED exec must leave
        # the old image's fd table untouched.
        for cfd in sorted(self._vfd_cloexec):
            if cfd in self._vfds:
                s = self._vfds.pop(cfd)
                self._vfd_flags.pop(cfd, None)
                self._drop_vfd(s)
            self._stdio_dups.pop(cfd, None)
        self._vfd_cloexec.clear()
        self._unregister_heap()
        self._clear_wake()
        self.ipc.close()
        old = self._child
        old.kill()
        old.wait()
        self.threads = {0: _Thread(0, self.pid)}
        self.threads[0].state = "running"
        self._runner = self._cur = self.threads[0]
        self._next_slot = 1
        self._free_slots = []
        self._clone_busy = False
        self._clone_queue = []
        self._futexes = {}
        self._sigactions = {}  # exec resets caught signals to default
        self._sig_pending = []
        self.argv = argv or [path]
        self.ipc = new_ipc
        self._child = new_child
        msg = self.ipc.recv_any(timeout_s=self.START_TIMEOUT_S)
        if msg is None or msg[0] != MSG_START:
            self._die(97)
            return True
        self._register_heap()  # the new image set up its own window
        self._publish_ids()  # same pid/ids, NEW ipc block
        self.ipc.pre_reply = self._fast_pre_reply
        self._fast_map = {}  # old entries died with the old block
        self._fast_dirty = False
        self._fast_init()
        self.ipc.reply_slot(0, MSG_START_OK)
        return False  # service loop continues with the new image

    def _handle_epoll(self, num: int, args: list[int]) -> bool:
        """epoll/timerfd/eventfd for real binaries, backed by the host-plane
        files (host/epoll.py, timerfd.py, eventfd.py — reference epoll.c,
        timerfd.rs, eventfd.rs)."""
        from shadow_tpu.host.epoll import Epoll
        from shadow_tpu.host.eventfd import EventFd
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.timerfd import TimerFd

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply

        def new_vfd(obj) -> int:
            fd = self._alloc_vfd()
            self._vfds[fd] = obj
            return fd

        O_NONBLOCK = 0x800  # == TFD_NONBLOCK == EFD_NONBLOCK
        if num in (S["epoll_create"], S["epoll_create1"]):
            fd = new_vfd(Epoll())
            if num == S["epoll_create1"] and args[0] & O_CLOEXEC:
                self._vfd_cloexec.add(fd)
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False
        if num == S["timerfd_create"]:
            fd = new_vfd(TimerFd(self.host))
            if args[1] & O_NONBLOCK:
                self._vfd_flags[fd] = O_NONBLOCK
            if args[1] & O_CLOEXEC:  # TFD_CLOEXEC
                self._vfd_cloexec.add(fd)
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False
        if num in (S["eventfd"], S["eventfd2"]):
            EFD_SEMAPHORE = 1
            flags = args[1] if num == S["eventfd2"] else 0  # legacy: no flags
            fd = new_vfd(EventFd(args[0], bool(flags & EFD_SEMAPHORE)))
            if flags & O_NONBLOCK:
                self._vfd_flags[fd] = O_NONBLOCK
            if flags & O_CLOEXEC:  # EFD_CLOEXEC
                self._vfd_cloexec.add(fd)
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False

        f = self._vfds.get(args[0])
        if f is None:
            reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False

        if num == S["epoll_ctl"]:
            EPOLL_CTL_ADD, EPOLL_CTL_DEL, EPOLL_CTL_MOD = 1, 2, 3
            if not isinstance(f, Epoll):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            target = self._vfds.get(args[2])
            if target is None:
                reply(MSG_SYSCALL_COMPLETE, -EBADF)
                return False
            events = data = 0
            if args[1] != EPOLL_CTL_DEL and args[3]:
                raw = _vm_read(cpid, args[3], 12)
                if len(raw) == 12:
                    events = struct.unpack_from("<I", raw, 0)[0]
                    data = struct.unpack_from("<Q", raw, 4)[0]
            try:
                if args[1] == EPOLL_CTL_ADD:
                    f.add(args[2], target, events, data)
                elif args[1] == EPOLL_CTL_MOD:
                    f.modify(args[2], events, data)
                elif args[1] == EPOLL_CTL_DEL:
                    f.remove(args[2])
                else:
                    reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                    return False
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["epoll_wait"], S["epoll_pwait"]):
            if not isinstance(f, Epoll):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            if args[2] <= 0:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            maxev = min(args[2], 64)
            evs = f.wait(maxev)
            now = self.host.now()
            if evs is not None:
                self._cur.poll_deadline = None
                out = bytearray()
                for e in evs:
                    out += struct.pack("<I", e.events) + struct.pack("<Q", e.data)
                _vm_write(cpid, args[1], bytes(out))
                reply(MSG_SYSCALL_COMPLETE, len(evs))
                return False
            timeout_ms = args[3]
            if timeout_ms == 0 or (
                self._cur.poll_deadline is not None and now >= self._cur.poll_deadline
            ):
                self._cur.poll_deadline = None
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if timeout_ms < 0:
                self._block_on([(f, FileState.READABLE)], num, args)
            else:
                if self._cur.poll_deadline is None:
                    self._cur.poll_deadline = now + timeout_ms * 1_000_000
                self._block_on([(f, FileState.READABLE)], num, args,
                               timeout_ns=self._cur.poll_deadline - now)
            return True

        if num == S["timerfd_settime"]:
            TFD_TIMER_ABSTIME = 1
            if not isinstance(f, TimerFd):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            raw = _vm_read(cpid, args[2], 32)  # struct itimerspec
            if len(raw) != 32:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            i_sec, i_ns, v_sec, v_ns = struct.unpack("<qqqq", raw)
            interval = i_sec * NS_PER_SEC + i_ns
            value = v_sec * NS_PER_SEC + v_ns
            now = self.host.now()
            if value == 0:
                deadline = None
            elif args[1] & TFD_TIMER_ABSTIME:
                deadline = value
            else:
                deadline = now + value
            old_rem, old_itv = f.settime(deadline, interval)
            if args[3]:
                _vm_write(
                    cpid, args[3],
                    struct.pack("<qqqq", old_itv // NS_PER_SEC,
                                old_itv % NS_PER_SEC, old_rem // NS_PER_SEC,
                                old_rem % NS_PER_SEC),
                )
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["timerfd_gettime"]:
            if not isinstance(f, TimerFd):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            rem, itv = f.gettime()
            _vm_write(
                cpid, args[1],
                struct.pack("<qqqq", itv // NS_PER_SEC, itv % NS_PER_SEC,
                            rem // NS_PER_SEC, rem % NS_PER_SEC),
            )
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    # ---- emulated sockets (the real-binary face of host/sockets.py;
    # reference: the inet syscall family, handler/mod.rs socket arms) ------

    def _nonblock(self, fd: int) -> bool:
        O_NONBLOCK = 0x800
        return bool(self._vfd_flags.get(fd, 0) & O_NONBLOCK)

    def _sock(self, fd: int):
        return self._vfds.get(fd)

    def _handle_socket(self, num: int, args: list[int]) -> bool:
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.sockets import (
            TcpListenerSocket,
            TcpSocket,
            UdpSocket,
        )

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply

        if num == S["socket"]:
            domain, typ = args[0], args[1]
            kind = typ & SOCK_TYPE_MASK
            if domain == AF_INET:
                if kind == SOCK_DGRAM:
                    sock = UdpSocket(self.host.netns)
                elif kind == SOCK_STREAM:
                    sock = TcpSocket(self.host.netns)
                else:
                    reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                    return False
            elif domain == AF_UNIX and kind == SOCK_STREAM:
                from shadow_tpu.host.unix import UnixStreamSocket

                sock = UnixStreamSocket()
            elif domain == AF_UNIX and kind == SOCK_DGRAM:
                from shadow_tpu.host.unix import UnixDgramSocket

                sock = UnixDgramSocket()
            elif domain == AF_NETLINK:
                from shadow_tpu.host.netlink import NetlinkSocket

                sock = NetlinkSocket(self.host)
            else:
                reply(MSG_SYSCALL_COMPLETE, -EAFNOSUPPORT)
                return False
            fd = self._alloc_vfd()
            self._vfds[fd] = sock
            if typ & SOCK_NONBLOCK:
                self._vfd_flags[fd] = 0x800
            if typ & O_CLOEXEC:  # SOCK_CLOEXEC
                self._vfd_cloexec.add(fd)
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False

        fd = args[0]
        sock = self._sock(fd)
        if sock is None:
            reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False

        from shadow_tpu.host.netlink import NetlinkSocket
        from shadow_tpu.host.unix import UnixDgramSocket, UnixStreamSocket

        if isinstance(sock, UnixStreamSocket):
            return self._handle_unix_socket(num, args, sock)
        if isinstance(sock, UnixDgramSocket):
            return self._handle_unix_dgram(num, args, sock)
        if isinstance(sock, NetlinkSocket):
            return self._handle_netlink_socket(num, args, sock)

        if num == S["bind"]:
            addr = _parse_sockaddr_in(_vm_read(cpid, args[1], min(args[2], 16)))
            if addr is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            try:
                sock.bind(addr[0], addr[1])
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -98)  # EADDRINUSE
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["listen"]:
            if isinstance(sock, TcpListenerSocket):
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if not isinstance(sock, TcpSocket):
                reply(MSG_SYSCALL_COMPLETE, -errno.EOPNOTSUPP)
                return False
            lst = TcpListenerSocket(self.host.netns, cfg=sock.cfg,
                                    backlog=max(args[1], 1))
            lst.local_ip, lst.local_port = sock.local_ip, sock.local_port
            if lst.local_port is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            self.host.netns._ports[(lst.PROTO, lst.local_port)] = lst
            self._vfds[fd] = lst
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["accept"], S["accept4"]):
            if not isinstance(sock, TcpListenerSocket):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            child = sock.accept()
            if child is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.ACCEPTABLE | FileState.CLOSED)], num, args
                )
                return True
            nfd = self._alloc_vfd()
            self._vfds[nfd] = child
            if num == S["accept4"] and args[3] & O_CLOEXEC:  # SOCK_CLOEXEC
                self._vfd_cloexec.add(nfd)
            if num == S["accept4"] and args[3] & SOCK_NONBLOCK:
                self._vfd_flags[nfd] = 0x800
            _write_sockaddr(
                cpid, args[1], args[2],
                _build_sockaddr_in(child.peer_ip, child.peer_port),
            )
            reply(MSG_SYSCALL_COMPLETE, nfd)
            return False

        if num == S["connect"]:
            addr = _parse_sockaddr_in(_vm_read(cpid, args[1], min(args[2], 16)))
            if addr is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            if isinstance(sock, UdpSocket):
                sock.connect(addr[0], addr[1])
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            from shadow_tpu.tcp import State as TS

            if sock.tcp.state == TS.ESTABLISHED:
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if sock.tcp.error is not None:
                reply(MSG_SYSCALL_COMPLETE, -ECONNREFUSED)
                return False
            if sock.peer_ip is None:
                sock.connect(addr[0], addr[1])
                if sock.tcp.state == TS.ESTABLISHED:  # loopback fast path
                    reply(MSG_SYSCALL_COMPLETE, 0)
                    return False
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -errno.EINPROGRESS)
                    return False
            elif self._nonblock(fd):
                reply(MSG_SYSCALL_COMPLETE, -errno.EALREADY)
                return False
            self._block_on(
                [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                num, args,
            )
            return True

        if num == S["sendto"]:
            data = _vm_read(cpid, args[1], min(args[2], 1 << 20))
            if isinstance(sock, UdpSocket):
                addr = None
                if args[4]:
                    addr = _parse_sockaddr_in(_vm_read(cpid, args[4], 16))
                try:
                    n = sock.sendto(data, addr)
                except OSError as e:
                    reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                    return False
                reply(MSG_SYSCALL_COMPLETE, n)
                return False
            # TCP stream send
            try:
                n = sock.write(data)
            except (ConnectionResetError, BrokenPipeError):
                reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                return False
            if n is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                    num, args,
                )
                return True
            reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == S["recvfrom"]:
            wait_mask = (
                FileState.READABLE | FileState.HUP | FileState.ERROR | FileState.CLOSED
            )
            peek = bool(args[3] & MSG_PEEK)
            if isinstance(sock, UdpSocket):
                n_req = min(args[2], 1 << 20)
                r = sock.peekfrom(n_req) if peek else sock.recvfrom(n_req)
                if r is None:
                    if self._nonblock(fd):
                        reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on([(sock, wait_mask)], num, args)
                    return True
                data, addr = r
                _vm_write(cpid, args[1], data)
                _write_sockaddr(
                    cpid, args[4], args[5], _build_sockaddr_in(addr[0], addr[1])
                )
                reply(MSG_SYSCALL_COMPLETE, len(data))
                return False
            n_req = min(args[2], 1 << 20)
            data = sock.peek(n_req) if peek else sock.read(n_req)
            if data is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on([(sock, wait_mask)], num, args)
                return True
            _vm_write(cpid, args[1], data)
            reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num == S["shutdown"]:
            if isinstance(sock, TcpSocket):
                sock.shutdown_write()
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["getsockname"]:
            sa = _build_sockaddr_in(sock.local_ip or "0.0.0.0", sock.local_port or 0)
            _write_sockaddr(cpid, args[1], args[2], sa)
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["getpeername"]:
            if sock.peer_ip is None:
                reply(MSG_SYSCALL_COMPLETE, -ENOTCONN)
                return False
            sa = _build_sockaddr_in(sock.peer_ip, sock.peer_port)
            _write_sockaddr(cpid, args[1], args[2], sa)
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["setsockopt"]:
            reply(MSG_SYSCALL_COMPLETE, 0)  # accepted and ignored
            return False

        if num == S["getsockopt"]:
            # real clients read these out-params; SO_ERROR especially is the
            # async-connect completion check (curl/wget poll for writable
            # then read SO_ERROR) — leaving it unwritten feeds them garbage
            SOL_SOCKET = 1
            SO_ERROR, SO_TYPE, SO_SNDBUF, SO_RCVBUF = 4, 3, 7, 8
            SO_ACCEPTCONN = 30
            val = 0
            if args[1] == SOL_SOCKET:
                if args[2] == SO_ERROR:
                    # same failure signal the blocking-connect path reports
                    err = getattr(getattr(sock, "tcp", None), "error", None)
                    val = errno.ECONNREFUSED if err is not None else 0
                elif args[2] == SO_TYPE:
                    val = SOCK_DGRAM if isinstance(sock, UdpSocket) else SOCK_STREAM
                elif args[2] in (SO_SNDBUF, SO_RCVBUF):
                    val = 256 * 1024
                elif args[2] == SO_ACCEPTCONN:
                    val = 1 if isinstance(sock, TcpListenerSocket) else 0
            try:
                if args[3]:
                    _vm_write(cpid, args[3], struct.pack("<i", val))
                if args[4]:
                    _vm_write(cpid, args[4], struct.pack("<I", 4))
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    def _unix_ns(self) -> dict:
        """Per-host unix namespace. Abstract names ('\\0'-prefixed) and
        filesystem paths share one registry keyed by the decoded name —
        paths are per-host virtual names here, no real inode is created
        (reference keeps real fs sockets; abstract_unix_ns.rs for @names)."""
        return self.host.netns.abstract_unix

    def _read_sun(self, ptr: int, alen: int) -> str | None:
        """Decode a sockaddr_un into the namespace key ('@name' for
        abstract, the path otherwise)."""
        raw = _vm_read(self._child.pid, ptr, min(max(alen, 2), 110))
        if len(raw) < 2 or struct.unpack("<H", raw[:2])[0] != AF_UNIX:
            return None
        path = raw[2:]
        if path.startswith(b"\0"):  # abstract: name is length-bounded
            return "@" + path[1:].decode("utf-8", "surrogateescape")
        return path.split(b"\0", 1)[0].decode("utf-8", "surrogateescape")

    def _handle_unix_socket(self, num: int, args: list[int], sock) -> bool:
        """AF_UNIX stream sockets for native binaries: bind (abstract or
        path), listen, accept, connect — same-host only, like the kernel
        (reference socket/unix.rs)."""
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.unix import UnixStreamSocket

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply
        fd = args[0]
        read_sun = self._read_sun

        if num == S["bind"]:
            name = read_sun(args[1], args[2])
            if not name:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            try:
                sock.bind_abstract(self._unix_ns(), name)
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EADDRINUSE)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["listen"]:
            try:
                sock.listen()
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["connect"]:
            name = read_sun(args[1], args[2])
            listener = self._unix_ns().get(name) if name else None
            if listener is None or not getattr(listener, "listening", False):
                reply(MSG_SYSCALL_COMPLETE, -ECONNREFUSED)
                return False
            try:
                sock.connect_to(listener)
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["accept"], S["accept4"]):
            child = sock.accept() if sock.listening else None
            if child is None:
                if not sock.listening:
                    reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                    return False
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.ACCEPTABLE | FileState.CLOSED)],
                    num, args,
                )
                return True
            nfd = self._alloc_vfd()
            self._vfds[nfd] = child
            if num == S["accept4"] and args[3] & O_CLOEXEC:  # SOCK_CLOEXEC
                self._vfd_cloexec.add(nfd)
            if num == S["accept4"] and args[3] & SOCK_NONBLOCK:
                self._vfd_flags[nfd] = 0x800
            # unnamed peer address (the kernel reports an empty sun_path)
            if args[1]:
                try:
                    _write_sockaddr(cpid, args[1], args[2],
                                    struct.pack("<H", AF_UNIX))
                except OSError:
                    pass
            reply(MSG_SYSCALL_COMPLETE, nfd)
            return False

        if num in (S["getsockname"], S["getpeername"]):
            if num == S["getpeername"]:
                if not sock.connected:
                    reply(MSG_SYSCALL_COMPLETE, -ENOTCONN)
                    return False
                name = sock.peer_name or ""
            else:
                name = sock.bound_name or ""
            sa = struct.pack("<H", AF_UNIX)
            if name.startswith("@"):
                sa += b"\0" + name[1:].encode()
            elif name:
                sa += name.encode() + b"\0"
            try:
                _write_sockaddr(cpid, args[1], args[2], sa)
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["sendto"]:
            data = _vm_read(cpid, args[1], min(args[2], 1 << 20))
            try:
                n = sock.write(data)
            except (BrokenPipeError, ConnectionResetError):
                reply(MSG_SYSCALL_COMPLETE, -errno.EPIPE)
                return False
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            if n is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.WRITABLE | FileState.ERROR
                      | FileState.CLOSED)], num, args,
                )
                return True
            reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == S["recvfrom"]:
            peek = bool(args[3] & MSG_PEEK)
            n_req = min(args[2], 1 << 20)
            try:
                data = sock.peek(n_req) if peek else sock.read(n_req)
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            if data is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.READABLE | FileState.HUP
                      | FileState.ERROR | FileState.CLOSED)], num, args,
                )
                return True
            _vm_write(cpid, args[1], data)
            reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num == S["shutdown"]:
            sock.shutdown_write()
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["setsockopt"], S["getsockopt"]):
            if num == S["getsockopt"]:
                try:
                    if args[3]:
                        _vm_write(cpid, args[3], struct.pack("<i", 0))
                    if args[4]:
                        _vm_write(cpid, args[4], struct.pack("<I", 4))
                except OSError:
                    pass
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    def _handle_unix_dgram(self, num: int, args: list[int], sock) -> bool:
        """AF_UNIX datagram sockets (glibc syslog's /dev/log transport;
        reference socket/unix.rs dgram): boundaries preserved, sendto by
        name or connected peer, same-host only."""
        from shadow_tpu.host.filestate import FileState

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply
        fd = args[0]

        if num == S["bind"]:
            name = self._read_sun(args[1], args[2])
            if not name:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            try:
                sock.bind_abstract(self._unix_ns(), name)
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EADDRINUSE)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["connect"]:
            name = self._read_sun(args[1], args[2])
            try:
                sock.connect_name(self._unix_ns(), name or "")
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -ECONNREFUSED)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["sendto"]:
            data = _vm_read(cpid, args[1], min(args[2], 1 << 20))
            name = self._read_sun(args[4], args[5]) if args[4] else None
            try:
                n = sock.send_to(self._unix_ns(), name, data)
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == S["recvfrom"]:
            peek = bool(args[3] & MSG_PEEK)
            n_req = min(args[2], 1 << 20)
            if peek:
                pk = sock.peek(n_req)
                r = None if pk is None else (pk, "")
            else:
                r = sock.recv_from(n_req)
            if r is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.READABLE | FileState.CLOSED)],
                    num, args,
                )
                return True
            data, src = r
            _vm_write(cpid, args[1], data)
            if args[4] and src:
                sa = struct.pack("<H", AF_UNIX)
                sa += (b"\0" + src[1:].encode()) if src.startswith("@") \
                    else src.encode() + b"\0"
                try:
                    _write_sockaddr(cpid, args[4], args[5], sa)
                except OSError:
                    pass
            reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num in (S["getsockname"], S["getpeername"]):
            name = (sock.bound_name if num == S["getsockname"]
                    else sock.peer_name) or ""
            sa = struct.pack("<H", AF_UNIX)
            if name.startswith("@"):
                sa += b"\0" + name[1:].encode()
            elif name:
                sa += name.encode() + b"\0"
            try:
                _write_sockaddr(cpid, args[1], args[2], sa)
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["setsockopt"], S["getsockopt"], S["shutdown"]):
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    def _handle_netlink_socket(self, num: int, args: list[int], sock) -> bool:
        """Minimal rtnetlink (host/netlink.py): bind/getsockname plus
        GETLINK/GETADDR dumps (reference socket/netlink.rs)."""
        from shadow_tpu.host.filestate import FileState

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply
        fd = args[0]

        if num == S["bind"]:
            raw = _vm_read(cpid, args[1], min(args[2], 12))
            if len(raw) >= 8:
                sock.pid = struct.unpack_from("<I", raw, 4)[0]
            if sock.pid == 0:
                sock.pid = self.pid  # kernel-assigned port id
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["getsockname"]:
            sa = struct.pack("<HHII", AF_NETLINK, 0, sock.pid, 0)
            try:
                _write_sockaddr(cpid, args[1], args[2], sa)
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -errno.EFAULT)
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["sendto"]:
            data = _vm_read(cpid, args[1], min(args[2], 1 << 16))
            reply(MSG_SYSCALL_COMPLETE, sock.submit(data))
            return False

        if num == S["recvfrom"]:
            peek = bool(args[3] & MSG_PEEK)
            n_req = min(args[2], 1 << 20)
            data = sock.peek(n_req) if peek else sock.read(n_req)
            if data is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.READABLE | FileState.CLOSED)],
                    num, args,
                )
                return True
            _vm_write(cpid, args[1], data)
            if args[4]:  # src addr: the kernel (pid 0)
                try:
                    _write_sockaddr(cpid, args[4], args[5],
                                    struct.pack("<HHII", AF_NETLINK, 0, 0, 0))
                except OSError:
                    pass
            reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num in (S["setsockopt"], S["getsockopt"]):
            if num == S["getsockopt"]:
                try:
                    if args[3]:
                        _vm_write(cpid, args[3], struct.pack("<i", 0))
                    if args[4]:
                        _vm_write(cpid, args[4], struct.pack("<I", 4))
                except OSError:
                    pass
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    def _gather_write(self, cpid: int, num: int, args: list[int]) -> bytes:
        if num == SYS["write"]:
            return _vm_read(cpid, args[1], min(args[2], 1 << 20))
        # IOV_MAX (1024, kernel limit) iovs so a legal writev is never
        # silently truncated; callers reject counts above it with EINVAL.
        # One batched process_vm_readv for all iovecs (tools/membench.py
        # measures the per-call saving vs one read per iovec)
        iov_cnt = min(args[2], IOV_MAX)
        raw = _vm_read(cpid, args[1], iov_cnt * 16)
        chunks = [
            struct.unpack_from("<QQ", raw, i * 16)
            for i in range(len(raw) // 16)
        ]
        return _vm_read_multi(
            cpid, [(b, min(ln, 1 << 20)) for b, ln in chunks]
        )


def _flock_wake(host, key):
    """Retry every waiter parked on `key` (host event context: no service
    loop is live, so re-entering a waiter's loop is safe — same pattern as
    the wait4 retry in _child_exited)."""
    table = host.__dict__.get("_flocks", {})
    ent = table.get(key)
    if ent is None:
        return
    waiters, ent["waiters"] = ent["waiters"], []
    for proc, thr in waiters:
        if proc.state != "running" or thr.state != "blocked":
            continue
        proc.ipc.set_time(host.now())
        proc.ipc.cur_slot = thr.slot
        proc._cur = thr
        thr.state = "running"
        parked = proc._handle_flock(thr.blocked_args)
        if not parked and thr.state == "running":
            proc._runner = thr
            proc._kick_runner()
    if ent["ex"] is None and not ent["sh"] and not ent["waiters"]:
        table.pop(key, None)


def spawn_native(host, argv: list[str], name: str | None = None,
                 start_time: int = 0, env: dict | None = None) -> NativeProcess:
    """Schedule a real binary onto a CpuHost (Host::add_application analogue)."""
    host._next_pid += 1
    proc = NativeProcess(host, host._next_pid, name or os.path.basename(argv[0]),
                         argv, env)
    host.processes[proc.pid] = proc
    host.schedule(max(start_time, host.now()), proc.start)
    return proc
