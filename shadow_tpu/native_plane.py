"""Python side of the native managed-process plane.

Reference counterpart: `ManagedThread` (managed_thread.rs:96-324 — spawn
with preload injection, the per-thread IPC channel, the resume loop
receiving `Syscall` events and replying Complete/DoNative) plus the syscall
handler dispatch (host/syscall/handler/mod.rs) and `MemoryCopier`
(process_vm_readv/writev, memory_manager/memory_copier.rs). The C++ shim
(`native/shim.cpp`) is the in-process half.

A `NativeProcess` plugs into a `CpuHost` exactly like a coroutine
`Process`: it advances only when the host event loop drives it, real time
never leaks in (the shared `sim_time_ns` is the only clock the child
sees), and blocking syscalls (nanosleep) park it on host-scheduled
wakeups. Syscalls the simulator does not emulate are answered
MSG_SYSCALL_NATIVE and execute in the child (the reference's
pass-through/regular-file policy).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import mmap
import os
import struct
import subprocess
import tempfile

# ---- layout mirror of native/ipc.h ----------------------------------------

MSG_START = 1
MSG_SYSCALL = 2
MSG_START_OK = 3
MSG_SYSCALL_COMPLETE = 4
MSG_SYSCALL_NATIVE = 5

CHAN_EMPTY, CHAN_FULL, CHAN_CLOSED = 0, 1, 2

# message wire format is "<ii q 6q q" at channel offset + 8 (see ipc.h)
TO_SHADOW_OFF = 16
TO_SHIM_OFF = 96
IPC_SIZE = 176

_libc = ctypes.CDLL(None, use_errno=True)
SYS_futex = 202
FUTEX_WAIT = 0
FUTEX_WAKE = 1


class _Timespec(ctypes.Structure):
    _fields_ = [("tv_sec", ctypes.c_long), ("tv_nsec", ctypes.c_long)]


def _futex(addr, op, val, timeout_s: float | None = None) -> int:
    ts = None
    if timeout_s is not None:
        ts = _Timespec(int(timeout_s), int((timeout_s % 1.0) * 1e9))
    r = _libc.syscall(
        SYS_futex, ctypes.c_void_p(addr), op, val,
        ctypes.byref(ts) if ts is not None else None, None, 0,
    )
    return r


class _Iovec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p), ("iov_len", ctypes.c_size_t)]


def _vm_read(pid: int, addr: int, n: int) -> bytes:
    if n <= 0 or addr == 0:
        return b""
    buf = ctypes.create_string_buffer(n)
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), n)
    remote = _Iovec(ctypes.c_void_p(addr), n)
    got = _libc.process_vm_readv(pid, ctypes.byref(local), 1,
                                 ctypes.byref(remote), 1, 0)
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_readv")
    return buf.raw[:got]


def _vm_write(pid: int, addr: int, data: bytes) -> int:
    if not data or addr == 0:
        return 0
    buf = ctypes.create_string_buffer(bytes(data), len(data))
    local = _Iovec(ctypes.cast(buf, ctypes.c_void_p), len(data))
    remote = _Iovec(ctypes.c_void_p(addr), len(data))
    got = _libc.process_vm_writev(pid, ctypes.byref(local), 1,
                                  ctypes.byref(remote), 1, 0)
    if got < 0:
        raise OSError(ctypes.get_errno(), "process_vm_writev")
    return got


def shm_cleanup() -> int:
    """Unlink IPC files whose owning simulator process is gone (reference
    `shadow --shm-cleanup`, utility/shm_cleanup.rs — which also checks
    creator-PID liveness). Returns the number removed."""
    import glob
    import re

    removed = 0
    for path in glob.glob("/dev/shm/shadow-ipc-*"):
        m = re.match(r".*/shadow-ipc-(\d+)-", path)
        if m and os.path.exists(f"/proc/{m.group(1)}"):
            continue  # owner still alive
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    return removed


# ---- build helper ----------------------------------------------------------

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")


def shim_path() -> str:
    return os.path.join(_NATIVE_DIR, "build", "libshadow_shim.so")


_ARTIFACTS = (
    "libshadow_shim.so", "test_app", "test_busy", "test_udp_echo",
    "test_udp_client", "test_tcp_stream", "test_epoll_server",
    "test_filewrite", "test_sockaddr_len", "test_writev_sock",
)


def ensure_built() -> bool:
    """Build the native plane if needed; False if no toolchain."""
    build = os.path.join(_NATIVE_DIR, "build")
    if all(os.path.exists(os.path.join(build, a)) for a in _ARTIFACTS):
        return True
    try:
        subprocess.run(
            ["make", "-C", _NATIVE_DIR], check=True,
            capture_output=True, timeout=120,
        )
    except (subprocess.SubprocessError, FileNotFoundError):
        return False
    return all(os.path.exists(os.path.join(build, a)) for a in _ARTIFACTS)


# ---- IPC block -------------------------------------------------------------

class IpcBlock:
    """One shared-memory block (file-backed) mirroring native/ipc.h."""

    def __init__(self):
        # owner pid is embedded in the name so shm_cleanup() can check
        # liveness before unlinking (reference utility/shm_cleanup.rs)
        fd, self.path = tempfile.mkstemp(
            prefix=f"shadow-ipc-{os.getpid()}-", dir="/dev/shm"
        )
        os.ftruncate(fd, IPC_SIZE)
        self._mm = mmap.mmap(fd, IPC_SIZE)
        os.close(fd)
        self._state_addrs = {}
        base = ctypes.addressof(ctypes.c_char.from_buffer(self._mm))
        for name, off in (("to_shadow", TO_SHADOW_OFF), ("to_shim", TO_SHIM_OFF)):
            self._state_addrs[name] = base + off

    def close(self):
        ch_off = TO_SHADOW_OFF
        self.set_chan_state(ch_off + 0, CHAN_CLOSED, wake=True)
        try:
            self._mm.close()
        except BufferError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- sim clock
    def set_time(self, t_ns: int):
        self._mm[0:8] = struct.pack("<q", t_ns)

    # -- channel primitives (Python is the "shadow" side)
    def _chan_off(self, name: str) -> int:
        return TO_SHADOW_OFF if name == "to_shadow" else TO_SHIM_OFF

    def chan_state(self, name: str) -> int:
        off = self._chan_off(name)
        return struct.unpack_from("<I", self._mm, off)[0]

    def set_chan_state(self, off_or_name, state: int, wake: bool = False):
        off = (
            self._chan_off(off_or_name)
            if isinstance(off_or_name, str)
            else off_or_name
        )
        struct.pack_into("<I", self._mm, off, state)
        if wake:
            addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm)) + off
            _futex(addr, FUTEX_WAKE, 1 << 30)

    def recv_syscall(self, timeout_s: float) -> tuple[int, list[int]] | None:
        """Wait for a message on to_shadow; returns (num, args) or None."""
        off = TO_SHADOW_OFF
        addr = ctypes.addressof(ctypes.c_char.from_buffer(self._mm)) + off
        deadline_attempts = max(1, int(timeout_s / 0.05))
        for _ in range(deadline_attempts):
            state = self.chan_state("to_shadow")
            if state == CHAN_FULL:
                kind, _pad, num, *rest = struct.unpack_from(
                    "<ii q 6q q", self._mm, off + 8
                )
                args = list(rest[:6])
                self.set_chan_state(off, CHAN_EMPTY, wake=True)
                return (kind, num, args)
            _futex(addr, FUTEX_WAIT, state, 0.05)
        return None

    def reply(self, kind: int, ret: int = 0):
        off = TO_SHIM_OFF
        struct.pack_into(
            "<ii q 6q q", self._mm, off + 8, kind, 0, 0, 0, 0, 0, 0, 0, 0,
            ctypes.c_int64(ret).value,
        )
        self.set_chan_state(off, CHAN_FULL, wake=True)


# ---- syscall numbers the policy references ---------------------------------

SYS = {
    "read": 0, "write": 1, "close": 3, "fstat": 5, "lseek": 8, "mmap": 9,
    "mprotect": 10, "munmap": 11, "brk": 12, "rt_sigaction": 13,
    "rt_sigprocmask": 14, "ioctl": 16, "pread64": 17, "writev": 20,
    "access": 21, "sched_yield": 24, "nanosleep": 35, "getpid": 39,
    "exit": 60, "uname": 63, "fcntl": 72, "getcwd": 79, "readlink": 89,
    "sigaltstack": 131, "arch_prctl": 158, "gettid": 186, "futex": 202,
    "set_tid_address": 218, "clock_gettime": 228, "clock_nanosleep": 230,
    "exit_group": 231, "openat": 257, "newfstatat": 262, "set_robust_list": 273,
    "prlimit64": 302, "getrandom": 318, "statx": 332, "rseq": 334,
    "clock_getres": 229, "getdents64": 217, "sched_getaffinity": 204,
    "kill": 62, "tgkill": 234, "madvise": 28, "poll": 7, "ppoll": 271,
    "pipe2": 293, "dup": 32, "getuid": 102, "getgid": 104, "geteuid": 107,
    "getegid": 108, "getppid": 110,
    # sockets
    "socket": 41, "connect": 42, "accept": 43, "sendto": 44, "recvfrom": 45,
    "shutdown": 48, "bind": 49, "listen": 50, "getsockname": 51,
    "getpeername": 52, "setsockopt": 54, "getsockopt": 55, "accept4": 288,
    # epoll / timerfd / eventfd
    "epoll_create": 213, "epoll_wait": 232, "epoll_ctl": 233,
    "epoll_pwait": 281, "epoll_create1": 291,
    "timerfd_create": 283, "timerfd_settime": 286, "timerfd_gettime": 287,
    "eventfd2": 290, "eventfd": 284,
}
_N2NAME = {v: k for k, v in SYS.items()}

# pass-through set: memory management, real-file reads, process metadata the
# simulator doesn't virtualize (regular_file.c passthrough analogue)
_NATIVE_OK = {
    SYS[n]
    for n in (
        "mmap", "mprotect", "munmap", "brk", "madvise", "rt_sigprocmask",
        "sigaltstack", "arch_prctl", "set_tid_address", "set_robust_list",
        "rseq", "prlimit64", "futex", "openat", "fstat", "newfstatat",
        "statx", "lseek", "pread64", "access", "readlink", "getcwd",
        "getdents64", "uname", "getuid", "getgid", "geteuid",
        "getegid", "pipe2",
    )
}

# emulated sockets hand out fds in this range so the two fd spaces (the
# child's real kernel fds vs the simulator's virtual sockets) can't collide
VFD_BASE = 1000

AF_INET = 2
SOCK_STREAM = 1
SOCK_DGRAM = 2
F_DUPFD = 0
F_GETFD = 1
F_SETFD = 2
F_GETFL = 3
F_SETFL = 4
F_DUPFD_CLOEXEC = 1030
O_WRONLY = 1
IOV_MAX = 1024
SOCK_TYPE_MASK = 0xFF
SOCK_NONBLOCK = 0x800
EAGAIN = 11
EBADF = 9
ENOTCONN = 107
ECONNREFUSED = 111
ECONNRESET = 104
EAFNOSUPPORT = 97
EINVAL = 22
EMSGSIZE = 90


def _errno_of(e: OSError) -> int:
    """Map host-plane OSErrors (message-prefixed like 'EMSGSIZE: ...', the
    reference errno-name convention) to a negative errno for the child."""
    name = str(e).split(":")[0].strip()
    return -getattr(errno, name, errno.EINVAL)


def _parse_sockaddr_in(raw: bytes) -> tuple[str, int] | None:
    if len(raw) < 8:
        return None
    family, port = struct.unpack_from("<H", raw, 0)[0], struct.unpack_from(">H", raw, 2)[0]
    if family != AF_INET:
        return None
    ip = ".".join(str(b) for b in raw[4:8])
    return ip, port


def _build_sockaddr_in(ip: str, port: int) -> bytes:
    parts = bytes(int(x) for x in (ip or "0.0.0.0").split("."))
    return struct.pack("<H", AF_INET) + struct.pack(">H", port or 0) + parts + b"\x00" * 8


def _write_sockaddr(cpid: int, addr_ptr: int, len_ptr: int, sa: bytes) -> None:
    """Kernel value-result semantics for (sockaddr*, socklen_t*) out-params:
    copy min(*len, len(sa)) bytes into the caller's buffer, then store the
    true length back through len_ptr (accept(2) NOTES)."""
    if not addr_ptr:
        return
    cap = len(sa)
    if len_ptr:
        raw = _vm_read(cpid, len_ptr, 4)
        if len(raw) == 4:
            cap = struct.unpack("<I", raw)[0]
    _vm_write(cpid, addr_ptr, sa[: min(cap, len(sa))])
    if len_ptr:
        _vm_write(cpid, len_ptr, struct.pack("<I", len(sa)))

NS_PER_SEC = 1_000_000_000

_SOCKET_SYSCALLS = {
    SYS[n]
    for n in (
        "socket", "connect", "accept", "accept4", "sendto", "recvfrom",
        "shutdown", "bind", "listen", "getsockname", "getpeername",
        "setsockopt", "getsockopt",
    )
}

_EPOLL_SYSCALLS = {
    SYS[n]
    for n in (
        "epoll_create", "epoll_create1", "epoll_ctl", "epoll_wait",
        "epoll_pwait", "timerfd_create", "timerfd_settime", "timerfd_gettime",
        "eventfd", "eventfd2",
    )
}


class NativeProcess:
    """A real Linux binary co-opted into a CpuHost's simulated time."""

    # Wall-clock budget for one native compute stretch between syscalls.
    # Time syscalls are answered in-process (no IPC), so a CPU-bound child
    # is silent on the channel; this is a hung-child watchdog (the
    # reference's resource watchdog, manager.rs:447-454), NOT a scheduling
    # device — a slow machine only ever makes the sim slower, never changes
    # results, unless a child genuinely exceeds this budget.
    WALL_TIMEOUT_S = 60.0

    def __init__(self, host, pid: int, name: str, argv: list[str],
                 env: dict | None = None):
        self.host = host
        self.pid = pid  # virtual pid
        self.name = name
        self.argv = argv
        self.env = env or {}
        self.state = None  # mirrors host.process.ProcState via strings
        self.exit_code: int | None = None
        self.stdout: list[bytes] = []
        self.stderr: list[bytes] = []
        self.ipc = IpcBlock()
        self._child: subprocess.Popen | None = None
        self.syscall_count = 0
        self.expected_final_state = "running"
        self.strace = None  # fn(t, pid, name, args, ret)
        # virtual fds: emulated sockets living in the host's netns
        self._vfds: dict[int, object] = {}
        self._vfd_flags: dict[int, int] = {}  # O_NONBLOCK etc.
        self._stdio_dups: dict[int, int] = {}  # vfd -> 1|2 (dup'd stdio)
        self._next_vfd = VFD_BASE
        self._wake: list = []  # (file, listener) pairs while blocked
        self._poll_deadline: int | None = None  # absolute poll timeout

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        """Spawn the child (posix_spawn + LD_PRELOAD, managed_thread.rs:548)
        and service it until it blocks or exits."""
        env = dict(os.environ)
        env.update(self.env)
        env["LD_PRELOAD"] = shim_path()
        env["SHADOW_SHM_PATH"] = self.ipc.path
        self.ipc.set_time(self.host.now())
        self._child = subprocess.Popen(
            self.argv, env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            stdin=subprocess.DEVNULL,
        )
        self.state = "running"
        msg = self.ipc.recv_syscall(timeout_s=10.0)
        if msg is None or msg[0] != MSG_START:
            self._die(97)
            return
        self.ipc.reply(MSG_START_OK)
        self._service_loop()

    def _die(self, code: int):
        self.state = "zombie"
        self.exit_code = code
        self._clear_wake()
        for sock in self._vfds.values():  # peers see HUP/RST, not silence
            sock.close()
        self._vfds.clear()
        if self._child is not None and self._child.poll() is None:
            self._child.kill()
            self._child.wait()
        self.ipc.close()
        self.host.on_process_exit(self)

    def kill(self):
        if self.state != "zombie":
            self._die(137)

    # ---- the service loop --------------------------------------------------

    def _service_loop(self):
        """Handle syscalls until the child blocks in sim time or exits
        (ManagedThread::resume's event loop, managed_thread.rs:187-324)."""
        while True:
            msg = self.ipc.recv_syscall(timeout_s=self.WALL_TIMEOUT_S)
            if msg is None:
                if self._child.poll() is not None:
                    self._die(self._child.returncode)
                else:
                    self._die(98)  # hung child: reap (watchdog analogue)
                return
            _, num, args = msg
            self.syscall_count += 1
            self.host.counters["syscalls"] += 1
            stop = self._handle(num, args)
            if stop:
                return

    def _resume_after_sleep(self):
        if self.state != "running":
            return
        self.ipc.set_time(self.host.now())
        self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
        self._service_loop()

    # ---- blocking on emulated files ---------------------------------------

    def _block_on(self, files_masks, num: int, args: list[int],
                  timeout_ns: int | None = None):
        """Park this process until any watched file shows its mask (or the
        timeout fires), then RE-RUN the same syscall — the reference's
        SyscallCondition semantics (condition.rs:36-108)."""
        from shadow_tpu.host.filestate import StatusListener

        def wake(_s=None, _c=None):
            if not self._wake:
                return
            self._clear_wake()
            self.host.schedule(self.host.now(), retry)

        def retry():
            if self.state != "running":
                return
            self.ipc.set_time(self.host.now())
            if not self._handle(num, args):
                self._service_loop()

        for f, mask in files_masks:
            lst = StatusListener(mask, wake)
            f.add_listener(lst)
            self._wake.append((f, lst))
        if timeout_ns is not None:
            token = self.host.schedule(self.host.now() + timeout_ns, wake)
            self._wake.append((None, token))

    def _clear_wake(self):
        for f, l in self._wake:
            if f is None:
                self.host.cancel(l)
            else:
                f.remove_listener(l)
        self._wake = []

    # ---- dispatch ----------------------------------------------------------

    def _handle(self, num: int, args: list[int]) -> bool:
        """Returns True if the service loop should stop (blocked/exited)."""
        cpid = self._child.pid
        name = _N2NAME.get(num, str(num))
        if self.strace is not None:
            self.strace(self.host.now(), self.pid, name, tuple(args[:3]), None)

        if num in _SOCKET_SYSCALLS:
            return self._handle_socket(num, args)
        if num in _EPOLL_SYSCALLS:
            return self._handle_epoll(num, args)
        if num == SYS["close"]:
            if args[0] in self._stdio_dups:
                del self._stdio_dups[args[0]]
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if args[0] in self._vfds:
                sock = self._vfds.pop(args[0])
                self._vfd_flags.pop(args[0], None)
                sock.close()
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num == SYS["dup"]:
            # stdio fds are virtualized (captured), so their dups must be
            # too: glibc's perror dups stderr before writing, and a native
            # dup would alias the child's real stderr (DEVNULL)
            tgt = args[0] if args[0] in (1, 2) else self._stdio_dups.get(args[0])
            if tgt is not None:
                nfd = self._next_vfd
                self._next_vfd += 1
                self._stdio_dups[nfd] = tgt
                self.ipc.reply(MSG_SYSCALL_COMPLETE, nfd)
            elif args[0] in self._vfds:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)  # loud
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num == SYS["fcntl"] and (
            args[1] in (F_DUPFD, F_DUPFD_CLOEXEC)
            and (args[0] in (1, 2) or args[0] in self._stdio_dups)
        ):
            # dup-via-fcntl of a captured stdio fd: must stay virtual, same
            # as dup(2) — a native dup would alias the child's real
            # stderr/stdout (DEVNULL) and silently swallow output
            tgt = args[0] if args[0] in (1, 2) else self._stdio_dups[args[0]]
            nfd = self._next_vfd
            self._next_vfd += 1
            self._stdio_dups[nfd] = tgt
            self.ipc.reply(MSG_SYSCALL_COMPLETE, nfd)
            return False
        if num == SYS["fcntl"] and args[0] in self._stdio_dups:
            if args[1] == F_GETFL:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, O_WRONLY)
            elif args[1] in (F_GETFD, F_SETFD, F_SETFL):
                # CLOEXEC bookkeeping is meaningless on a virtual fd; accept
                # (glibc fdopen(..., "we") sets FD_CLOEXEC right after dup)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            else:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        if num == SYS["fcntl"]:
            if args[0] not in self._vfds:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
                return False
            if args[1] == F_SETFL:
                self._vfd_flags[args[0]] = args[2]
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            elif args[1] == F_GETFL:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, self._vfd_flags.get(args[0], 0))
            else:
                # F_DUPFD etc: unsupported on emulated sockets — fail loudly
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
            return False
        if num in _NATIVE_OK:
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num in (SYS["nanosleep"], SYS["clock_nanosleep"]):
            req_ptr = args[0] if num == SYS["nanosleep"] else args[2]
            raw = _vm_read(cpid, req_ptr, 16)
            sec, nsec = struct.unpack("<qq", raw) if len(raw) == 16 else (0, 0)
            t = sec * NS_PER_SEC + nsec
            TIMER_ABSTIME = 1
            if num == SYS["clock_nanosleep"] and args[1] & TIMER_ABSTIME:
                wake_at = max(self.host.now(), t)  # absolute deadline
            else:
                wake_at = self.host.now() + max(0, t)
            self.host.schedule(wake_at, self._resume_after_sleep)
            return True  # parked

        if num in (SYS["write"], SYS["writev"]) and (
            args[0] in (1, 2) or args[0] in self._stdio_dups
        ):
            if num == SYS["writev"] and args[2] > IOV_MAX:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            tgt = args[0] if args[0] in (1, 2) else self._stdio_dups[args[0]]
            data = self._gather_write(cpid, num, args)
            (self.stdout if tgt == 1 else self.stderr).append(data)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num == SYS["write"] and args[0] in self._vfds:
            f = self._vfds[args[0]]
            if not hasattr(f, "PROTO"):  # eventfd counters etc.
                try:
                    data = _vm_read(cpid, args[1], min(args[2], 16))
                    n = f.write(data)
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                if n is None:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                else:
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
                return False
            return self._handle_socket(SYS["sendto"], [args[0], args[1], args[2], 0, 0, 0])
        if num == SYS["writev"] and args[0] in self._vfds:
            sock = self._vfds[args[0]]
            if args[2] > IOV_MAX:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            data = self._gather_write(cpid, num, args)
            if not hasattr(sock, "PROTO"):
                # eventfd/timerfd: same semantics as write(2) on the vfd
                try:
                    n = sock.write(data[:16])
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                self.ipc.reply(
                    MSG_SYSCALL_COMPLETE, -EAGAIN if n is None else n
                )
                return False
            from shadow_tpu.host.sockets import UdpSocket

            try:
                if isinstance(sock, UdpSocket):
                    # one writev = one datagram (must not split per-iov)
                    n = sock.sendto(data, None)
                else:
                    n = sock.write(data)
            except (ConnectionResetError, BrokenPipeError):
                self.ipc.reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                return False
            except OSError as e:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            if n is None:
                if self._nonblock(args[0]):
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                from shadow_tpu.host.filestate import FileState

                self._block_on(
                    [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                    num, args,
                )
                return True
            self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
            return False
        if num == SYS["read"] and args[0] in self._vfds:
            f = self._vfds[args[0]]
            if not hasattr(f, "PROTO"):  # timerfd/eventfd 8-byte reads
                from shadow_tpu.host.filestate import FileState

                try:
                    out = f.read(min(args[2], 1 << 16))
                except (OSError, AttributeError) as e:
                    code = _errno_of(e) if isinstance(e, OSError) else -EINVAL
                    self.ipc.reply(MSG_SYSCALL_COMPLETE, code)
                    return False
                if out is None:
                    if self._nonblock(args[0]):
                        self.ipc.reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on(
                        [(f, FileState.READABLE | FileState.ERROR | FileState.CLOSED)],
                        num, args,
                    )
                    return True
                _vm_write(cpid, args[1], out)
                self.ipc.reply(MSG_SYSCALL_COMPLETE, len(out))
                return False
            return self._handle_socket(SYS["recvfrom"], [args[0], args[1], args[2], 0, 0, 0])

        if num == SYS["read"]:
            if args[0] == 0:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)  # stdin: EOF
            else:
                # real-file fds were opened natively; read them natively too
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num in (SYS["write"], SYS["writev"]) and args[0] not in self._vfds:
            # fd is neither stdio (handled above) nor a vfd: it's a regular
            # file the child opened natively — write it natively, mirroring
            # the read/openat passthrough policy (ref regular_file.c).
            self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False

        if num == SYS["ioctl"] and args[0] in (0, 1, 2):
            self.ipc.reply(MSG_SYSCALL_COMPLETE, -errno.ENOTTY)
            return False

        if num == SYS["getrandom"]:
            n = min(args[1], 1 << 20)
            data = bytes(self.host.rng.getrandbits(8) for _ in range(n))
            _vm_write(cpid, args[0], data)
            self.ipc.reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == SYS["getpid"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self.pid)
            return False
        if num == SYS["gettid"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, self.pid)
            return False
        if num == SYS["getppid"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 1)
            return False
        if num == SYS["sched_yield"]:
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if num == SYS["sched_getaffinity"]:
            # report one cpu (deterministic regardless of the real machine)
            if args[1] >= 8:
                _vm_write(cpid, args[2], struct.pack("<Q", 1))
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 8)
            return False
        if num == SYS["rt_sigaction"]:
            # guard the shim's SIGSYS handler (shim_seccomp.c keeps SIGSYS)
            SIGSYS = 31
            if args[0] == SIGSYS:
                self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)  # pretend success
            else:
                self.ipc.reply(MSG_SYSCALL_NATIVE)
            return False
        if num in (SYS["exit_group"], SYS["exit"]):
            self.state = "zombie"
            self.exit_code = args[0] & 0xFF
            self._clear_wake()
            for sock in self._vfds.values():
                sock.close()
            self._vfds.clear()
            self.ipc.reply(MSG_SYSCALL_NATIVE)  # let it really exit
            self._child.wait(timeout=10)
            self.ipc.close()
            self.host.on_process_exit(self)
            return True
        if num in (SYS["poll"], SYS["ppoll"]):
            return self._handle_poll(num, args)

        # default: refuse with ENOSYS (surface unknown syscalls loudly)
        self.ipc.reply(MSG_SYSCALL_COMPLETE, -38)
        return False

    def _handle_poll(self, num: int, args: list[int]) -> bool:
        """poll/ppoll over emulated-socket vfds (reference poll.c/select.c
        handlers). Real kernel fds in the set are reported with revents=0;
        only vfds are pollable here."""
        from shadow_tpu.host.filestate import FileState

        POLLIN, POLLOUT, POLLERR, POLLHUP = 1, 4, 8, 0x10
        cpid = self._child.pid
        nfds = min(args[1], 64)
        raw = _vm_read(cpid, args[0], nfds * 8)
        fds = [
            struct.unpack_from("<ihh", raw, i * 8) for i in range(len(raw) // 8)
        ]
        timeout_ms = args[2] if num == SYS["poll"] else -1
        if num == SYS["ppoll"] and args[2]:
            ts = _vm_read(cpid, args[2], 16)
            if len(ts) == 16:
                s, ns = struct.unpack("<qq", ts)
                timeout_ms = (s * NS_PER_SEC + ns) // 1_000_000

        ready = 0
        out = bytearray(raw)
        watch = []
        for i, (fd, events, _) in enumerate(fds):
            revents = 0
            sock = self._vfds.get(fd)
            if sock is not None:
                st = sock.state
                if events & POLLIN and st & (
                    FileState.READABLE | FileState.ACCEPTABLE
                ):
                    revents |= POLLIN
                if events & POLLOUT and st & FileState.WRITABLE:
                    revents |= POLLOUT
                if st & FileState.ERROR:
                    revents |= POLLERR
                if st & (FileState.HUP | FileState.CLOSED):
                    revents |= POLLHUP
                mask = FileState.ERROR | FileState.HUP | FileState.CLOSED
                if events & POLLIN:
                    mask |= FileState.READABLE | FileState.ACCEPTABLE
                if events & POLLOUT:
                    mask |= FileState.WRITABLE
                watch.append((sock, mask))
            struct.pack_into("<h", out, i * 8 + 6, revents)
            if revents:
                ready += 1
        now = self.host.now()
        if ready:
            self._poll_deadline = None
            _vm_write(cpid, args[0], bytes(out))
            self.ipc.reply(MSG_SYSCALL_COMPLETE, ready)
            return False
        if timeout_ms == 0 or (
            self._poll_deadline is not None and now >= self._poll_deadline
        ):
            self._poll_deadline = None
            self.ipc.reply(MSG_SYSCALL_COMPLETE, 0)
            return False
        if not watch and timeout_ms < 0:
            self._die(99)  # infinite poll with nothing we can ever signal
            return True
        if timeout_ms < 0:
            self._block_on(watch, num, args)
        else:
            # absolute deadline survives re-runs so a timeout wake that
            # finds nothing ready reports 0 instead of re-arming in full
            if self._poll_deadline is None:
                self._poll_deadline = now + timeout_ms * 1_000_000
            self._block_on(watch, num, args,
                           timeout_ns=self._poll_deadline - now)
        return True

    def _handle_epoll(self, num: int, args: list[int]) -> bool:
        """epoll/timerfd/eventfd for real binaries, backed by the host-plane
        files (host/epoll.py, timerfd.py, eventfd.py — reference epoll.c,
        timerfd.rs, eventfd.rs)."""
        from shadow_tpu.host.epoll import Epoll
        from shadow_tpu.host.eventfd import EventFd
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.timerfd import TimerFd

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply

        def new_vfd(obj) -> int:
            fd = self._next_vfd
            self._next_vfd += 1
            self._vfds[fd] = obj
            return fd

        O_NONBLOCK = 0x800  # == TFD_NONBLOCK == EFD_NONBLOCK
        if num in (S["epoll_create"], S["epoll_create1"]):
            reply(MSG_SYSCALL_COMPLETE, new_vfd(Epoll()))
            return False
        if num == S["timerfd_create"]:
            fd = new_vfd(TimerFd(self.host))
            if args[1] & O_NONBLOCK:
                self._vfd_flags[fd] = O_NONBLOCK
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False
        if num in (S["eventfd"], S["eventfd2"]):
            EFD_SEMAPHORE = 1
            flags = args[1] if num == S["eventfd2"] else 0  # legacy: no flags
            fd = new_vfd(EventFd(args[0], bool(flags & EFD_SEMAPHORE)))
            if flags & O_NONBLOCK:
                self._vfd_flags[fd] = O_NONBLOCK
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False

        f = self._vfds.get(args[0])
        if f is None:
            reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False

        if num == S["epoll_ctl"]:
            EPOLL_CTL_ADD, EPOLL_CTL_DEL, EPOLL_CTL_MOD = 1, 2, 3
            if not isinstance(f, Epoll):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            target = self._vfds.get(args[2])
            if target is None:
                reply(MSG_SYSCALL_COMPLETE, -EBADF)
                return False
            events = data = 0
            if args[1] != EPOLL_CTL_DEL and args[3]:
                raw = _vm_read(cpid, args[3], 12)
                if len(raw) == 12:
                    events = struct.unpack_from("<I", raw, 0)[0]
                    data = struct.unpack_from("<Q", raw, 4)[0]
            try:
                if args[1] == EPOLL_CTL_ADD:
                    f.add(args[2], target, events, data)
                elif args[1] == EPOLL_CTL_MOD:
                    f.modify(args[2], events, data)
                elif args[1] == EPOLL_CTL_DEL:
                    f.remove(args[2])
                else:
                    reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                    return False
            except OSError as e:
                reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["epoll_wait"], S["epoll_pwait"]):
            if not isinstance(f, Epoll):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            if args[2] <= 0:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            maxev = min(args[2], 64)
            evs = f.wait(maxev)
            now = self.host.now()
            if evs is not None:
                self._poll_deadline = None
                out = bytearray()
                for e in evs:
                    out += struct.pack("<I", e.events) + struct.pack("<Q", e.data)
                _vm_write(cpid, args[1], bytes(out))
                reply(MSG_SYSCALL_COMPLETE, len(evs))
                return False
            timeout_ms = args[3]
            if timeout_ms == 0 or (
                self._poll_deadline is not None and now >= self._poll_deadline
            ):
                self._poll_deadline = None
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if timeout_ms < 0:
                self._block_on([(f, FileState.READABLE)], num, args)
            else:
                if self._poll_deadline is None:
                    self._poll_deadline = now + timeout_ms * 1_000_000
                self._block_on([(f, FileState.READABLE)], num, args,
                               timeout_ns=self._poll_deadline - now)
            return True

        if num == S["timerfd_settime"]:
            TFD_TIMER_ABSTIME = 1
            if not isinstance(f, TimerFd):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            raw = _vm_read(cpid, args[2], 32)  # struct itimerspec
            if len(raw) != 32:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            i_sec, i_ns, v_sec, v_ns = struct.unpack("<qqqq", raw)
            interval = i_sec * NS_PER_SEC + i_ns
            value = v_sec * NS_PER_SEC + v_ns
            now = self.host.now()
            if value == 0:
                deadline = None
            elif args[1] & TFD_TIMER_ABSTIME:
                deadline = value
            else:
                deadline = now + value
            old_rem, old_itv = f.settime(deadline, interval)
            if args[3]:
                _vm_write(
                    cpid, args[3],
                    struct.pack("<qqqq", old_itv // NS_PER_SEC,
                                old_itv % NS_PER_SEC, old_rem // NS_PER_SEC,
                                old_rem % NS_PER_SEC),
                )
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["timerfd_gettime"]:
            if not isinstance(f, TimerFd):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            rem, itv = f.gettime()
            _vm_write(
                cpid, args[1],
                struct.pack("<qqqq", itv // NS_PER_SEC, itv % NS_PER_SEC,
                            rem // NS_PER_SEC, rem % NS_PER_SEC),
            )
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    # ---- emulated sockets (the real-binary face of host/sockets.py;
    # reference: the inet syscall family, handler/mod.rs socket arms) ------

    def _nonblock(self, fd: int) -> bool:
        O_NONBLOCK = 0x800
        return bool(self._vfd_flags.get(fd, 0) & O_NONBLOCK)

    def _sock(self, fd: int):
        return self._vfds.get(fd)

    def _handle_socket(self, num: int, args: list[int]) -> bool:
        from shadow_tpu.host.filestate import FileState
        from shadow_tpu.host.sockets import (
            TcpListenerSocket,
            TcpSocket,
            UdpSocket,
        )

        cpid = self._child.pid
        S = SYS
        reply = self.ipc.reply

        if num == S["socket"]:
            domain, typ = args[0], args[1]
            if domain != AF_INET:
                reply(MSG_SYSCALL_COMPLETE, -EAFNOSUPPORT)
                return False
            kind = typ & SOCK_TYPE_MASK
            if kind == SOCK_DGRAM:
                sock = UdpSocket(self.host.netns)
            elif kind == SOCK_STREAM:
                sock = TcpSocket(self.host.netns)
            else:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            fd = self._next_vfd
            self._next_vfd += 1
            self._vfds[fd] = sock
            if typ & SOCK_NONBLOCK:
                self._vfd_flags[fd] = 0x800
            reply(MSG_SYSCALL_COMPLETE, fd)
            return False

        fd = args[0]
        sock = self._sock(fd)
        if sock is None:
            reply(MSG_SYSCALL_COMPLETE, -EBADF)
            return False

        if num == S["bind"]:
            addr = _parse_sockaddr_in(_vm_read(cpid, args[1], min(args[2], 16)))
            if addr is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            try:
                sock.bind(addr[0], addr[1])
            except OSError:
                reply(MSG_SYSCALL_COMPLETE, -98)  # EADDRINUSE
                return False
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["listen"]:
            if isinstance(sock, TcpListenerSocket):
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if not isinstance(sock, TcpSocket):
                reply(MSG_SYSCALL_COMPLETE, -errno.EOPNOTSUPP)
                return False
            lst = TcpListenerSocket(self.host.netns, cfg=sock.cfg,
                                    backlog=max(args[1], 1))
            lst.local_ip, lst.local_port = sock.local_ip, sock.local_port
            if lst.local_port is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            self.host.netns._ports[(lst.PROTO, lst.local_port)] = lst
            self._vfds[fd] = lst
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["accept"], S["accept4"]):
            if not isinstance(sock, TcpListenerSocket):
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            child = sock.accept()
            if child is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.ACCEPTABLE | FileState.CLOSED)], num, args
                )
                return True
            nfd = self._next_vfd
            self._next_vfd += 1
            self._vfds[nfd] = child
            if num == S["accept4"] and args[3] & SOCK_NONBLOCK:
                self._vfd_flags[nfd] = 0x800
            _write_sockaddr(
                cpid, args[1], args[2],
                _build_sockaddr_in(child.peer_ip, child.peer_port),
            )
            reply(MSG_SYSCALL_COMPLETE, nfd)
            return False

        if num == S["connect"]:
            addr = _parse_sockaddr_in(_vm_read(cpid, args[1], min(args[2], 16)))
            if addr is None:
                reply(MSG_SYSCALL_COMPLETE, -EINVAL)
                return False
            if isinstance(sock, UdpSocket):
                sock.connect(addr[0], addr[1])
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            from shadow_tpu.tcp import State as TS

            if sock.tcp.state == TS.ESTABLISHED:
                reply(MSG_SYSCALL_COMPLETE, 0)
                return False
            if sock.tcp.error is not None:
                reply(MSG_SYSCALL_COMPLETE, -ECONNREFUSED)
                return False
            if sock.peer_ip is None:
                sock.connect(addr[0], addr[1])
                if sock.tcp.state == TS.ESTABLISHED:  # loopback fast path
                    reply(MSG_SYSCALL_COMPLETE, 0)
                    return False
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -errno.EINPROGRESS)
                    return False
            elif self._nonblock(fd):
                reply(MSG_SYSCALL_COMPLETE, -errno.EALREADY)
                return False
            self._block_on(
                [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                num, args,
            )
            return True

        if num == S["sendto"]:
            data = _vm_read(cpid, args[1], min(args[2], 1 << 20))
            if isinstance(sock, UdpSocket):
                addr = None
                if args[4]:
                    addr = _parse_sockaddr_in(_vm_read(cpid, args[4], 16))
                try:
                    n = sock.sendto(data, addr)
                except OSError as e:
                    reply(MSG_SYSCALL_COMPLETE, _errno_of(e))
                    return False
                reply(MSG_SYSCALL_COMPLETE, n)
                return False
            # TCP stream send
            try:
                n = sock.write(data)
            except (ConnectionResetError, BrokenPipeError):
                reply(MSG_SYSCALL_COMPLETE, -ECONNRESET)
                return False
            if n is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on(
                    [(sock, FileState.WRITABLE | FileState.ERROR | FileState.CLOSED)],
                    num, args,
                )
                return True
            reply(MSG_SYSCALL_COMPLETE, n)
            return False

        if num == S["recvfrom"]:
            wait_mask = (
                FileState.READABLE | FileState.HUP | FileState.ERROR | FileState.CLOSED
            )
            if isinstance(sock, UdpSocket):
                r = sock.recvfrom(min(args[2], 1 << 20))
                if r is None:
                    if self._nonblock(fd):
                        reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                        return False
                    self._block_on([(sock, wait_mask)], num, args)
                    return True
                data, addr = r
                _vm_write(cpid, args[1], data)
                _write_sockaddr(
                    cpid, args[4], args[5], _build_sockaddr_in(addr[0], addr[1])
                )
                reply(MSG_SYSCALL_COMPLETE, len(data))
                return False
            data = sock.read(min(args[2], 1 << 20))
            if data is None:
                if self._nonblock(fd):
                    reply(MSG_SYSCALL_COMPLETE, -EAGAIN)
                    return False
                self._block_on([(sock, wait_mask)], num, args)
                return True
            _vm_write(cpid, args[1], data)
            reply(MSG_SYSCALL_COMPLETE, len(data))
            return False

        if num == S["shutdown"]:
            if isinstance(sock, TcpSocket):
                sock.shutdown_write()
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["getsockname"]:
            sa = _build_sockaddr_in(sock.local_ip or "0.0.0.0", sock.local_port or 0)
            _write_sockaddr(cpid, args[1], args[2], sa)
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num == S["getpeername"]:
            if sock.peer_ip is None:
                reply(MSG_SYSCALL_COMPLETE, -ENOTCONN)
                return False
            sa = _build_sockaddr_in(sock.peer_ip, sock.peer_port)
            _write_sockaddr(cpid, args[1], args[2], sa)
            reply(MSG_SYSCALL_COMPLETE, 0)
            return False

        if num in (S["setsockopt"], S["getsockopt"]):
            reply(MSG_SYSCALL_COMPLETE, 0)  # accepted and ignored
            return False

        reply(MSG_SYSCALL_COMPLETE, -EINVAL)
        return False

    def _gather_write(self, cpid: int, num: int, args: list[int]) -> bytes:
        if num == SYS["write"]:
            return _vm_read(cpid, args[1], min(args[2], 1 << 20))
        out = bytearray()
        # IOV_MAX (1024, kernel limit) iovs so a legal writev is never
        # silently truncated; callers reject counts above it with EINVAL
        iov_cnt = min(args[2], IOV_MAX)
        raw = _vm_read(cpid, args[1], iov_cnt * 16)
        for i in range(len(raw) // 16):
            base, ln = struct.unpack_from("<QQ", raw, i * 16)
            out += _vm_read(cpid, base, min(ln, 1 << 20))
        return bytes(out)


def spawn_native(host, argv: list[str], name: str | None = None,
                 start_time: int = 0, env: dict | None = None) -> NativeProcess:
    """Schedule a real binary onto a CpuHost (Host::add_application analogue)."""
    host._next_pid += 1
    proc = NativeProcess(host, host._next_pid, name or os.path.basename(argv[0]),
                         argv, env)
    host.processes[proc.pid] = proc
    host.schedule(max(start_time, host.now()), proc.start)
    return proc
