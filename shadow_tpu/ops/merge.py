"""Deterministic batch merge of cross-host events into per-host queues.

This is the round-barrier half of the reference's cross-host path: in Shadow a
worker locks the destination host's `Mutex<EventQueue>` and pushes
(src/main/core/worker.rs:644-654). On TPU there are no locks: all packets
emitted during a round are staged in a flat outbox, exchanged at the barrier,
and inserted here with a single sorted scatter whose order is fully determined
by the packed event order key — so the result is bit-identical for any shard
count or arrival interleaving.

Algorithm (all static shapes, O(N log N + H·C)):
  1. sort entries by (dst, time, order) — invalid entries sort to the end, so
     under overflow pressure the *latest* events are shed, never the most
     urgent ones;
  2. rank r of each entry within its dst segment via searchsorted;
  3. build each host's free-slot map: rank → slot index (scatter of slot ids
     keyed by the running count of free slots);
  4. scatter entry r into its dst's r-th free slot; entries beyond the free
     count or beyond `max_inserts` land in `dropped` (counted, never silent).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from shadow_tpu.ops.events import EventQueue
from shadow_tpu.simtime import TIME_MAX


def merge_flat_events(
    q: EventQueue,
    dst,  # i32[N] local host index of each entry
    t,  # i64[N]
    order,  # i64[N] packed tiebreak key (unique per live entry)
    kind,  # i32[N]
    payload,  # i32[N, P]
    valid,  # bool[N]
    max_inserts: int,
    shed_urgency: bool = True,
) -> EventQueue:
    """`shed_urgency=True` (default): overflow sheds by (time, order) so the
    most urgent events always win slots — the tested contract. False: a
    2×i32 sort grouped by dst with append-order ranks; identical simulation
    results whenever nothing overflows (pop_min re-derives the total order
    from slot contents), at a fraction of the sort cost — the engine's
    `cheap_shed` knob for workloads sized to never overflow."""
    num_hosts, cap = q.t.shape
    n = dst.shape[0]
    r_cap = min(max_inserts, cap)

    # -- 1. sort by (dst, t, order); invalid entries get dst=num_hosts (sort
    # last). The sort is the hot op of the whole engine (measured ~85% of
    # round cost on v5e) — keep its operand set minimal: kind/payload are
    # gathered by the carried index afterwards instead of riding the sort.
    dst_key = jnp.where(valid, dst.astype(jnp.int32), jnp.int32(num_hosts))
    if shed_urgency:
        s_dst, s_t, s_order, s_idx = lax.sort(
            (dst_key, t, order, jnp.arange(n, dtype=jnp.int32)),
            num_keys=3,
        )
    else:
        s_dst, s_idx = lax.sort(
            (dst_key, jnp.arange(n, dtype=jnp.int32)), num_keys=2
        )
        s_t = t[s_idx]
        s_order = order[s_idx]
    s_kind = kind[s_idx]
    s_payload = payload[s_idx]
    s_valid = s_dst < num_hosts

    # -- 2. rank within destination segment
    seg_start = jnp.searchsorted(s_dst, s_dst, side="left")
    rank = jnp.arange(n, dtype=jnp.int64) - seg_start

    # -- 3. free-slot map per host: slot_of_rank[h, r] = index of r-th free slot
    free = q.t == TIME_MAX  # [H, C]
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1  # [H, C]
    scatter_r = jnp.where(free & (free_rank < r_cap), free_rank, r_cap)
    slot_of_rank = jnp.full((num_hosts, r_cap), -1, jnp.int32)
    hh = jnp.broadcast_to(jnp.arange(num_hosts)[:, None], free.shape)
    cc = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32)[None, :], free.shape)
    slot_of_rank = slot_of_rank.at[hh, scatter_r].set(cc, mode="drop")

    # -- 4. scatter entries into (dst, slot)
    in_rank = s_valid & (rank < r_cap)
    h_safe = jnp.where(s_valid, s_dst, 0).astype(jnp.int32)
    r_safe = jnp.where(in_rank, rank, 0).astype(jnp.int32)
    slot = slot_of_rank[h_safe, r_safe]  # [N]
    ok = in_rank & (slot >= 0)
    h_scatter = jnp.where(ok, h_safe, num_hosts)  # out-of-bounds → dropped
    s_scatter = jnp.where(ok, slot, 0)

    new_t = q.t.at[h_scatter, s_scatter].set(s_t, mode="drop")
    new_order = q.order.at[h_scatter, s_scatter].set(s_order, mode="drop")
    new_kind = q.kind.at[h_scatter, s_scatter].set(s_kind.astype(jnp.int32), mode="drop")
    new_payload = q.payload.at[h_scatter, s_scatter].set(s_payload, mode="drop")

    # -- overflow accounting (int scatter-add: order-independent, deterministic)
    lost = s_valid & ~ok
    dropped = q.dropped.at[jnp.where(lost, h_safe, num_hosts)].add(
        jnp.where(lost, 1, 0).astype(jnp.int64), mode="drop"
    )
    return EventQueue(
        t=new_t, order=new_order, kind=new_kind, payload=new_payload, dropped=dropped
    )
