"""Deterministic batch merge of cross-host events into per-host queues.

This is the round-barrier half of the reference's cross-host path: in Shadow a
worker locks the destination host's `Mutex<EventQueue>` and pushes
(src/main/core/worker.rs:644-654). On TPU there are no locks: all packets
emitted during a round are staged in a flat outbox, exchanged at the barrier,
and inserted here in an order fully determined by the packed event order key —
so the result is bit-identical for any shard count or arrival interleaving.

Algorithm (all static shapes, gather-only — no scatters; measured on v5e the
original scatter formulation was ~60% of total round cost):
  1. sort entries by (dst, time, order) — invalid entries sort to the end, so
     under overflow pressure the *latest* events are shed, never the most
     urgent ones;
  2. per-host segment starts via an H-sized searchsorted over the sorted dst
     column (NOT an N-sized one: N >> H and TPU binary-search gathers are the
     dominant cost);
  3. each host's r-th free slot *gathers* the r-th entry of its segment:
     `new[h, c] = entry[s_idx[seg_start[h] + free_rank[h, c]]]` masked by
     free/rank/segment-length bounds. Entries beyond the free count or beyond
     `max_inserts` land in `dropped` (counted, never silent).

The gather inversion is exact because the old scatter mapped segment rank r to
the r-th free slot — the same bijection read from the other side.

Gather economics (v5e, N=60k, H=10k: each [H, C]-indexed gather ~1 ms): only
TWO gathers run — the sorted->original index map `s_idx[j]`, then ONE
row-gather of all event fields bit-packed into an [N, W] i32 matrix (row
gathers move contiguous words, amortizing the per-element index cost that made
seven separate field gathers the dominant merge cost).

`merge_rows` (round 5) statically truncates the sorted-permute gather: every
row a non-shedding round needs lives in the first (valid + H + 1) sorted
positions, so only that prefix is materialized — the permute cost tracks the
REAL per-round traffic instead of the worst-case outbox (H x send budget).
Rows past the bound shed by sorted position and are counted, never silent.

Merge gears (round 7) shrink the SORT itself the same way `merge_rows`
shrank the gather: every entry point here is width-parameterized (N is just
the length of the flat arrays handed in), so the engine compiles the round
body at a ladder of outbox column widths and feeds the sort H x gear_cols
rows instead of H x B. The truncation is positional on the [H, B] lane
layout (host h's k-th send sits in column k), so it is exact whenever no
host staged more than gear_cols sends that round — `gear_shed_count` is the
exact detector, and the driver replays a shedding chunk one gear up from a
pre-chunk snapshot (core/engine.py `_gear_sliced_outbox`, core/gears.py).

Formulations tried and rejected in round 5 (measured on the v5e, kept for
the record — all three looked faster in isolated microbenches and were not):
  - fully-SoA element gathers per field: in-context element gathers are
    descriptor-rate-bound (~7 ns/element, ~5 gathers) — 8.6 s/chunk vs the
    packed row gather's 0.74 s (row descriptors amortize all 9 words);
  - vmap(dynamic_slice) per-host contiguous blocks: lowers to a
    10k-iteration while LOOP on TPU (~0.45 s per field per chunk);
  - lax.gather with multi-element slice_sizes: same while-loop lowering.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from shadow_tpu.ops.events import (
    BucketQueue,
    EventQueue,
    as_flat,
    bucket_rebuild,
)
from shadow_tpu.simtime import TIME_MAX


def _merge_scatter(q, s_dst, s_idx, t, order, kind, payload, r_cap,
                   merge_rows=0):
    """CPU insertion path: rank entries within their dst segment and scatter
    each into its dst's rank-th free slot (the round-1 formulation)."""
    num_hosts, cap = q.t.shape
    n = s_dst.shape[0]
    s_t = t[s_idx]
    s_order = order[s_idx]
    s_kind = kind[s_idx].astype(jnp.int32)
    s_payload = payload[s_idx]
    s_valid = s_dst < num_hosts

    seg_start = jnp.searchsorted(s_dst, s_dst, side="left")
    rank = jnp.arange(n, dtype=jnp.int64) - seg_start

    free = q.t == TIME_MAX  # [H, C]
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
    scatter_r = jnp.where(free & (free_rank < r_cap), free_rank, r_cap)
    slot_of_rank = jnp.full((num_hosts, r_cap), -1, jnp.int32)
    hh = jnp.broadcast_to(jnp.arange(num_hosts)[:, None], free.shape)
    cc = jnp.broadcast_to(jnp.arange(cap, dtype=jnp.int32)[None, :], free.shape)
    slot_of_rank = slot_of_rank.at[hh, scatter_r].set(cc, mode="drop")

    in_rank = s_valid & (rank < r_cap)
    if merge_rows > 0:
        # mirror the gather path's positional truncation bit-exactly: its
        # sorted array interleaves one token per host, so this path's
        # position p sits at gather position p + s_dst[p] + 1 (tokens for
        # hosts 0..s_dst[p] precede it). Rows landing at or past the bound
        # shed there and must shed identically here.
        gather_pos = jnp.arange(n, dtype=jnp.int64) + s_dst + 1
        in_rank = in_rank & (gather_pos < merge_rows)
    h_safe = jnp.where(s_valid, s_dst, 0).astype(jnp.int32)
    r_safe = jnp.where(in_rank, rank, 0).astype(jnp.int32)
    slot = slot_of_rank[h_safe, r_safe]
    ok = in_rank & (slot >= 0)
    h_scatter = jnp.where(ok, h_safe, num_hosts)
    s_scatter = jnp.where(ok, slot, 0)

    lost = s_valid & ~ok
    dropped = q.dropped.at[jnp.where(lost, h_safe, num_hosts)].add(
        jnp.where(lost, 1, 0).astype(jnp.int64), mode="drop"
    )
    return EventQueue(
        t=q.t.at[h_scatter, s_scatter].set(s_t, mode="drop"),
        order=q.order.at[h_scatter, s_scatter].set(s_order, mode="drop"),
        kind=q.kind.at[h_scatter, s_scatter].set(s_kind, mode="drop"),
        payload=q.payload.at[h_scatter, s_scatter].set(s_payload, mode="drop"),
        dropped=dropped,
    )


def gear_shed_count(sent_round, gear_cols: int):
    """Exact count of outbox entries a gear-truncated merge would lose:
    host h's sends occupy lane columns 0..sent_round[h]-1, so exactly
    max(sent_round[h] - gear_cols, 0) of its entries sit in the trimmed
    columns. Zero iff the truncation is lossless — the gear-shed detector
    (fed into stats.gear_shed; a nonzero delta aborts the chunk for a
    snapshot replay one gear up, so results stay bit-identical to the
    full-width merge)."""
    return jnp.sum(jnp.maximum(sent_round.astype(jnp.int64) - gear_cols, 0))


def dshard_segments(dshard, t, order, world: int):
    """Group local outbox rows by destination shard via ONE `lax.sort`.

    Sorts rows by (dst shard, t, order) with one sentinel token per shard
    group riding along at (shard, -1, -1) — the same token trick the
    merge's per-host segment extraction uses — then recovers each group's
    start with a second tiny stable sort over the token positions. Invalid
    rows must arrive with `dshard == world` so they sort past every real
    group.

    Returns (s_tag i32[M], first i32[world + 1], seg_len i32[world]):
    `s_tag` is the sorted permutation tag (0 = token, else source row
    index + 1), `first[j]` the sorted position of group j's token, and
    `seg_len[j]` the count of valid rows destined for shard j — those rows
    sit immediately after the token in (t, order) urgency order. Shared by
    the flat alltoall exchange and the hierarchical exchange's intra-shard
    compaction tier, so the two paths cannot drift on what "compacted
    per-destination prefix" means (the bit-identity contract between
    them)."""
    n_loc = dshard.shape[0]
    iota = jnp.arange(n_loc, dtype=jnp.int32)
    q_keys = jnp.arange(world + 1, dtype=jnp.int32)
    all_sh = jnp.concatenate([dshard, q_keys])
    all_t = jnp.concatenate([t, jnp.full((world + 1,), -1, t.dtype)])
    all_o = jnp.concatenate([order, jnp.full((world + 1,), -1, order.dtype)])
    all_idx = jnp.concatenate([iota + 1, jnp.zeros((world + 1,), jnp.int32)])
    s_sh, _, _, s_tag = lax.sort((all_sh, all_t, all_o, all_idx), num_keys=3)
    m = n_loc + world + 1
    is_tok = s_tag == 0
    key2 = jnp.where(is_tok, s_sh, jnp.int32(world + 1))
    pos = jnp.arange(m, dtype=jnp.int32)
    _, tok_pos = lax.sort((key2, pos), num_keys=1, is_stable=True)
    first = tok_pos[: world + 1]
    seg_len = first[1:] - first[:-1] - 1  # i32[world]
    return s_tag, first, seg_len


def _pack_words(t, order, kind, payload):
    """[N] i64 ×2, [N] i32, [N, P] i32 -> [N, 4 + 1 + P] i32 row matrix."""
    t2 = lax.bitcast_convert_type(t, jnp.int32)  # [N, 2]
    o2 = lax.bitcast_convert_type(order, jnp.int32)  # [N, 2]
    return jnp.concatenate([t2, o2, kind[:, None], payload], axis=1)


def _unpack_words(g, p_words):
    """[H, C, 4 + 1 + P] i32 -> (t i64, order i64, kind i32, payload i32[P])."""
    g_t = lax.bitcast_convert_type(g[..., 0:2], jnp.int64)
    g_order = lax.bitcast_convert_type(g[..., 2:4], jnp.int64)
    return g_t, g_order, g[..., 4], g[..., 5 : 5 + p_words]


def merge_plan(
    q_t,  # i64[H, C] — the queue's time plane ONLY (free-slot source)
    dst,
    t,
    order,
    kind,
    payload,
    valid,
    max_inserts: int,
    shed_urgency: bool = True,
    merge_rows: int = 0,
):
    """The sort/gather half of the gather-path merge, WITHOUT writing the
    queue: returns (take bool[H, C], g i32[H, C, W], dropped_add i64[H]).

    Split out so the engine can wrap only THIS half in the empty-round
    `lax.cond`: a cond whose branches return the whole queue copies every
    slab at the branch boundary each round (traced at ~55% of the PHOLD
    round cost on v5e); a cond returning the plan copies one [H, C, W]
    packed block, and `merge_apply` runs unconditionally as a single cheap
    where-pass. Takes only the queue's TIME plane: passing the whole queue
    through the cond made every plane a second consumer and forced XLA to
    copy the slabs around the branch anyway (measured as a 40% round-cost
    regression on PHOLD-torus before the narrowing)."""
    return _merge_gather_plan(
        q_t, dst, t, order, kind, payload, valid, max_inserts, shed_urgency,
        merge_rows,
    )


def merge_empty_plan(q_t, p_words: int):
    """A no-op insertion plan (the empty-round cond branch)."""
    num_hosts, cap = q_t.shape
    return (
        jnp.zeros((num_hosts, cap), bool),
        jnp.zeros((num_hosts, cap, 5 + p_words), jnp.int32),
        jnp.zeros((num_hosts,), jnp.int64),
    )


def merge_apply(q: EventQueue, take, g, dropped_add) -> EventQueue:
    """Write a `merge_plan` into the queue (one masked slab pass)."""
    p_words = q.payload.shape[2]
    g_t, g_order, g_kind, g_payload = _unpack_words(g, p_words)
    return EventQueue(
        t=jnp.where(take, g_t, q.t),
        order=jnp.where(take, g_order, q.order),
        kind=jnp.where(take, g_kind, q.kind),
        payload=jnp.where(take[:, :, None], g_payload, q.payload),
        dropped=q.dropped + dropped_add,
    )


def merge_flat_events(
    q: EventQueue,
    dst,  # i32[N] local host index of each entry
    t,  # i64[N]
    order,  # i64[N] packed tiebreak key (unique per live entry)
    kind,  # i32[N]
    payload,  # i32[N, P]
    valid,  # bool[N]
    max_inserts: int,
    shed_urgency: bool = True,
    force_path: str | None = None,  # tests: 'gather' | 'scatter'
    merge_rows: int = 0,
) -> EventQueue:
    """`shed_urgency=True` (default): overflow sheds by (time, order) so the
    most urgent events always win slots — the tested contract. False: a
    single-key sort grouped by dst with buffer-order ranks; identical
    simulation results whenever nothing overflows (pop_min re-derives the
    total order from slot contents), at a fraction of the sort cost — the
    engine's `cheap_shed` knob for workloads sized to never overflow.

    Accepts either queue type: a `BucketQueue` merges through its flat slab
    view and comes back with freshly rebuilt block caches — merges are
    wholesale cache-rebuild points (along with checkpoint restore). This
    entry point rebuilds unconditionally (the hybrid bridge's per-window
    injection lands here); the engine's split plan/apply path refreshes the
    caches itself so empty rounds can skip the rebuild."""
    if isinstance(q, BucketQueue):
        merged = merge_flat_events(
            as_flat(q), dst, t, order, kind, payload, valid, max_inserts,
            shed_urgency=shed_urgency, force_path=force_path,
            merge_rows=merge_rows,
        )
        return bucket_rebuild(merged, q.block)
    num_hosts, cap = q.t.shape
    n = dst.shape[0]
    r_cap = min(max_inserts, cap)

    dst_key = jnp.where(valid, dst.astype(jnp.int32), jnp.int32(num_hosts))
    iota = jnp.arange(n, dtype=jnp.int32)

    path = force_path or (
        "scatter" if jax.default_backend() == "cpu" else "gather"
    )
    if path == "scatter":
        # scatter formulation: faster on CPU (TPU scatters are the slow path
        # the gather variant below exists to avoid; CPU scatters are cheap).
        # Identical insertion set and order -> identical queues and digests.
        if shed_urgency:
            s_dst, _, _, s_idx = lax.sort(
                (dst_key, t, order, iota), num_keys=3
            )
        else:
            idx_bits = max(1, (n - 1).bit_length())
            packed = (dst_key.astype(jnp.int64) << idx_bits) | iota.astype(
                jnp.int64
            )
            s_packed = lax.sort(packed)
            s_dst = (s_packed >> idx_bits).astype(jnp.int32)
            s_idx = (s_packed & ((1 << idx_bits) - 1)).astype(jnp.int32)
        return _merge_scatter(
            q, s_dst, s_idx, t, order, kind, payload, r_cap, merge_rows
        )

    return merge_apply(
        q,
        *_merge_gather_plan(
            q.t, dst, t, order, kind, payload, valid, max_inserts,
            shed_urgency, merge_rows
        ),
    )


def merge_scatter_free(
    q: EventQueue,
    dst,  # i32[N] local host index of each entry
    t,  # i64[N]
    order,  # i64[N]
    kind,  # i32[N]
    payload,  # i32[N, P]
    valid,  # bool[N]
    max_inserts: int,
    shed_urgency: bool = True,
    merge_rows: int = 0,
) -> EventQueue:
    """Sort-free calendar-queue merge: bucket incoming exchange rows by
    destination via scatter-add instead of the full (dst, t, order) sort
    — the non-shedding FAST PATH; the sort path stays as the shed/
    overflow fallback (`merge_flat_events`).

    Why no sort is needed when nothing sheds: the sort serves two
    purposes — grouping rows by destination, and ordering them by
    urgency WITHIN a destination so overflow sheds the latest. Slot
    positions are unobservable (`migrate_queue`'s invariant: pops
    re-derive the (time, order) total order from slot contents, drops
    depend only on the free-slot COUNT), so when every row fits, ANY
    deterministic row -> free-slot bijection yields a bit-identical
    simulation. The within-destination order is then irrelevant and the
    sort is pure overhead.

    Fast-path admission is exact and cheap: a scatter-add histogram
    counts arrivals per destination; the fast path runs iff every
    destination's count fits both its free slots and the insert cap
    (and, under a `merge_rows` bound, the sorted-prefix bound provably
    cannot bind). Otherwise the call falls through to the sort path,
    whose shed order is the tested urgency/append contract — so enabling
    the scatter merge NEVER changes digests, events, or drop counters
    on any workload (tests/test_wheel.py gates equality on forced
    overflow too).

    Slot assignment without a sort: iterative scatter-max peeling. Each
    pass scatters row indices with `max` onto a per-destination cell;
    the winner (one per contended destination, fully deterministic)
    takes the destination's next free rank and drops out. Passes needed
    = the max arrivals to any ONE destination that round — 1-2 for
    balanced traffic, bounded by the insert cap in the worst case —
    each pass a handful of O(N) scatters/gathers versus the
    O(M log M) 4-operand sort (M = N + H + 1) it replaces.
    `shed_urgency` is accepted for signature parity and only shapes the
    FALLBACK's shed order (the fast path never sheds)."""
    num_hosts, cap = q.t.shape
    n = dst.shape[0]
    r_cap = min(max_inserts, cap)
    dst_safe = jnp.where(valid, dst.astype(jnp.int32), jnp.int32(num_hosts))

    cnt = jnp.zeros((num_hosts + 1,), jnp.int32).at[dst_safe].add(
        jnp.ones((n,), jnp.int32)
    )
    free_cnt = jnp.sum((q.t == TIME_MAX).astype(jnp.int32), axis=1)
    fits = jnp.all(
        cnt[:num_hosts] <= jnp.minimum(free_cnt, jnp.int32(r_cap))
    )
    if merge_rows > 0:
        # conservative: with every valid row + one token per host + the
        # sentinel inside the bound, no sorted position can shed
        n_valid = jnp.sum(valid.astype(jnp.int32))
        fits = fits & (n_valid + num_hosts + 1 <= merge_rows)

    def fast(queue: EventQueue) -> EventQueue:
        # per-destination free-slot ranking (the same rank -> slot
        # bijection the scatter path uses)
        free = queue.t == TIME_MAX
        free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1
        hh = jnp.broadcast_to(
            jnp.arange(num_hosts)[:, None], free.shape
        )
        cc = jnp.broadcast_to(
            jnp.arange(cap, dtype=jnp.int32)[None, :], free.shape
        )
        scatter_r = jnp.where(free & (free_rank < r_cap), free_rank, r_cap)
        slot_of_rank = jnp.full((num_hosts, r_cap), -1, jnp.int32)
        slot_of_rank = slot_of_rank.at[hh, scatter_r].set(cc, mode="drop")

        iota = jnp.arange(n, dtype=jnp.int32)

        def cond(carry):
            _, _, unassigned = carry
            return jnp.any(unassigned)

        def body(carry):
            rank, fill, unassigned = carry
            dst_u = jnp.where(unassigned, dst_safe, jnp.int32(num_hosts))
            win = jnp.full((num_hosts + 1,), -1, jnp.int32).at[dst_u].max(
                jnp.where(unassigned, iota, -1)
            )
            iswin = unassigned & (win[dst_safe] == iota)
            rank = jnp.where(iswin, fill[dst_safe], rank)
            fill = fill.at[dst_safe].add(iswin.astype(jnp.int32))
            return rank, fill, unassigned & ~iswin

        rank, _, _ = lax.while_loop(
            cond,
            body,
            (
                jnp.zeros((n,), jnp.int32),
                jnp.zeros((num_hosts + 1,), jnp.int32),
                valid,
            ),
        )
        # every valid row has a distinct (dst, rank) with rank < its
        # destination's free count <= r_cap, so the slot lookup never
        # misses; invalid rows scatter to host index H and drop
        slot = slot_of_rank[jnp.where(valid, dst_safe, 0), rank]
        h_sc = jnp.where(valid, dst_safe, jnp.int32(num_hosts))
        s_sc = jnp.where(valid, slot, 0)
        return EventQueue(
            t=queue.t.at[h_sc, s_sc].set(t, mode="drop"),
            order=queue.order.at[h_sc, s_sc].set(order, mode="drop"),
            kind=queue.kind.at[h_sc, s_sc].set(
                kind.astype(jnp.int32), mode="drop"
            ),
            payload=queue.payload.at[h_sc, s_sc].set(payload, mode="drop"),
            dropped=queue.dropped,  # fast path never sheds
        )

    def fallback(queue: EventQueue) -> EventQueue:
        return merge_flat_events(
            queue, dst, t, order, kind, payload, valid, max_inserts,
            shed_urgency=shed_urgency, merge_rows=merge_rows,
        )

    return lax.cond(fits, fast, fallback, q)


def _merge_gather_plan(
    q_t, dst, t, order, kind, payload, valid, max_inserts, shed_urgency,
    merge_rows=0,
):
    num_hosts, cap = q_t.shape
    n = dst.shape[0]
    r_cap = min(max_inserts, cap)
    dst_key = jnp.where(valid, dst.astype(jnp.int32), jnp.int32(num_hosts))
    iota = jnp.arange(n, dtype=jnp.int32)

    # -- 1. sort entries TOGETHER with one query token per host (plus an end
    # sentinel): token h carries (dst=h, t=-1, order=-1) so it sorts to the
    # very front of host h's segment — real times/orders are never negative.
    # Segment starts then fall out of ONE cheap 2-operand extraction sort
    # below instead of a searchsorted (H parallel binary searches and the
    # 'sort'-method scatter both measured ~3x slower than this on v5e).
    m = n + num_hosts + 1
    q_keys = jnp.arange(num_hosts + 1, dtype=jnp.int32)
    if shed_urgency:
        all_dst = jnp.concatenate([dst_key, q_keys])
        all_t = jnp.concatenate([t, jnp.full((num_hosts + 1,), -1, t.dtype)])
        all_order = jnp.concatenate(
            [order, jnp.full((num_hosts + 1,), -1, order.dtype)]
        )
        # data entries carry idx+1; tokens carry 0 (doubles as the flag)
        all_idx = jnp.concatenate(
            [iota + 1, jnp.zeros((num_hosts + 1,), jnp.int32)]
        )
        s_dst, _, _, s_tag = lax.sort(
            (all_dst, all_t, all_order, all_idx), num_keys=3
        )
    else:
        # pack (dst, index+1) into one key; tokens get index 0 and therefore
        # sort first within their dst group
        idx_bits = max(1, n.bit_length())
        if (num_hosts + 1) << idx_bits <= 2**31:
            packed = jnp.concatenate(
                [(dst_key << idx_bits) | (iota + 1), q_keys << idx_bits]
            )
            s_packed = lax.sort(packed)
            s_dst = s_packed >> idx_bits
            s_tag = s_packed & ((1 << idx_bits) - 1)
        else:
            packed = jnp.concatenate(
                [
                    (dst_key.astype(jnp.int64) << idx_bits)
                    | (iota.astype(jnp.int64) + 1),
                    q_keys.astype(jnp.int64) << idx_bits,
                ]
            )
            s_packed = lax.sort(packed)
            s_dst = (s_packed >> idx_bits).astype(jnp.int32)
            s_tag = (s_packed & ((1 << idx_bits) - 1)).astype(jnp.int32)
    s_idx = s_tag - 1  # original entry index; -1 at token positions

    # -- 2. segment bounds: extract token positions ordered by host id. The
    # tokens are mutually ordered by dst, so a stable sort on (is_token ?
    # dst : num_hosts+1) compacts their positions into the first H+1 slots.
    is_tok = s_tag == 0
    key2 = jnp.where(is_tok, s_dst, jnp.int32(num_hosts + 1))
    pos = jnp.arange(m, dtype=jnp.int32)
    _, tok_pos = lax.sort((key2, pos), num_keys=1, is_stable=True)
    first = tok_pos[: num_hosts + 1]  # [H+1] position of token h
    # host h's entries live at (first[h], first[h+1]) exclusive of tokens
    seg_len = first[1:] - first[:-1] - 1  # i32[H]

    # -- 3. r-th free slot of host h gathers sorted entry at
    # first[h] + 1 + r (the +1 skips host h's own token), bounded by the
    # segment length, the insert cap, and the merge_rows truncation
    k = m if merge_rows <= 0 else min(merge_rows, m)
    n_ins = jnp.minimum(
        jnp.minimum(seg_len, r_cap),
        jnp.maximum(k - 1 - first[:-1], 0),
    )  # i32[H]
    free = q_t == TIME_MAX  # [H, C]
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1  # [H, C]
    take = free & (free_rank < n_ins[:, None])
    j = jnp.where(take, first[:-1, None] + 1 + free_rank, 0)  # [H, C]
    words = _pack_words(t, order, kind.astype(jnp.int32), payload)
    # row permutation (gather 1), truncated to the first k sorted positions
    # (every row `take` can reference satisfies j < k by the n_ins bound);
    # token rows (s_idx == -1) wrap to the last row — never selected by
    # `take`, and harmless to fetch. Note (r5): the composed form
    # `words[s_idx[j]]` — skipping the [M, W] materialization — was tried
    # and measured ~7% SLOWER at M = 400k: the second gather's rows are
    # near-sequential in w_sorted (per-host segments) but random in the
    # original entry order, and locality wins over the saved pass.
    w_sorted = words[s_idx[:k]]  # [K, W]
    g = w_sorted[j]  # [H, C, W] row gather — all fields at once (gather 2)

    # -- overflow accounting (elementwise: order-independent, deterministic)
    inserted = jnp.sum(take.astype(jnp.int32), axis=1)
    dropped_add = (seg_len - inserted).astype(jnp.int64)
    return take, g, dropped_add
