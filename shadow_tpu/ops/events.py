"""Per-host event queues as fixed-capacity SoA arrays in HBM.

Reference model being rebuilt (not ported):
  - src/main/core/work/event_queue.rs:10-55 — per-host `BinaryHeap` with a
    monotonic-time assertion and `next_event_time` peek.
  - src/main/core/work/event.rs:102-155 — deterministic total order:
    (time, packets-before-local-tasks, src host id, per-src sequence number).

TPU-first recast: a queue is a `[H, C]` slab per field (times i64, order-key
i64, kind i32, payload i32×P). Empty slots hold TIME_MAX / ORDER_MAX. All ops
are branch-free masked reductions/scatters over the full slab so every host
advances in the same fused kernel; `H` is the sharded axis on the device mesh.

The total order is packed into two i64 keys compared lexicographically:
  primary   = event time (ns)
  secondary = `order` = (is_local_task << 62) | (src_host << 40) | seq
Packets sort before local tasks at equal times (is_local=1 for local tasks),
matching event.rs:131-155; `seq` is a per-source monotonically increasing
counter so concurrent sends resolve identically under any sharding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from shadow_tpu.simtime import TIME_MAX

# payload words per event: models pack (src, size, flow/port, aux) etc.
EVENT_PAYLOAD_WORDS = 4

# order-key field widths: 1 bit is_local | 22 bits src host | 40 bits seq
_SEQ_BITS = 40
_SRC_SHIFT = _SEQ_BITS
_LOCAL_SHIFT = 62
SEQ_MASK = (1 << _SEQ_BITS) - 1
ORDER_MAX = (1 << 63) - 1  # empty-slot sentinel, compares after any real key


class EventQueue(NamedTuple):
    """SoA event slab for H hosts × C slots (all arrays shard on axis 0)."""

    t: Array  # i64[H, C] event time; TIME_MAX = empty
    order: Array  # i64[H, C] secondary sort key; ORDER_MAX = empty
    kind: Array  # i32[H, C] event kind (model handler index)
    payload: Array  # i32[H, C, P]
    dropped: Array  # i64[H] events lost to capacity overflow (observability)


class Event(NamedTuple):
    """One popped event per host (all [H])."""

    t: Array  # i64[H]
    order: Array  # i64[H]
    kind: Array  # i32[H]
    payload: Array  # i32[H, P]


def pack_order(is_local, src_host, seq) -> Array:
    """Pack the deterministic tiebreak key (event.rs:131-155 equivalent).

    Field limits — enforced statically via `check_order_limits`, not per-draw
    (this runs in the hot pop/merge path): src_host < 2^22 (≈4.2M hosts) and
    seq < 2^40 (≈1.1e12 events per source; a source emits at most one event
    per microstep, so wrap is unreachable in any real simulation length).
    """
    is_local = jnp.asarray(is_local, jnp.int64)
    src_host = jnp.asarray(src_host, jnp.int64)
    seq = jnp.asarray(seq, jnp.int64)
    return (is_local << _LOCAL_SHIFT) | (src_host << _SRC_SHIFT) | (seq & SEQ_MASK)


def unpack_order_src(order) -> Array:
    """Recover the sending host's global id from a packed order key (packets
    carry their source here — the reference's Packet keeps src addr fields)."""
    return (jnp.asarray(order, jnp.int64) >> _SRC_SHIFT) & (
        (1 << (_LOCAL_SHIFT - _SRC_SHIFT)) - 1
    )


def check_order_limits(num_hosts: int) -> None:
    """Static guard called at simulation build time: the packed key must never
    collide with ORDER_MAX (empty-slot sentinel) or spill src bits into the
    is_local bit."""
    if num_hosts >= (1 << (_LOCAL_SHIFT - _SRC_SHIFT)):
        raise ValueError(
            f"num_hosts={num_hosts} exceeds the {1 << (_LOCAL_SHIFT - _SRC_SHIFT)}"
            " host limit of the packed event-order key"
        )


def make_queue(num_hosts: int, capacity: int) -> EventQueue:
    return EventQueue(
        t=jnp.full((num_hosts, capacity), TIME_MAX, jnp.int64),
        order=jnp.full((num_hosts, capacity), ORDER_MAX, jnp.int64),
        kind=jnp.zeros((num_hosts, capacity), jnp.int32),
        payload=jnp.zeros((num_hosts, capacity, EVENT_PAYLOAD_WORDS), jnp.int32),
        dropped=jnp.zeros((num_hosts,), jnp.int64),
    )


def next_time(q: EventQueue) -> Array:
    """Per-host earliest pending event time (event_queue.rs:52-54 peek)."""
    return jnp.min(q.t, axis=1)


def queue_len(q: EventQueue) -> Array:
    return jnp.sum((q.t != TIME_MAX).astype(jnp.int32), axis=1)


def pop_min(q: EventQueue, limit) -> tuple[EventQueue, Event, Array]:
    """Pop each host's earliest event strictly before `limit` (i64 scalar or [H]).

    Returns (queue', event, active[H] bool). Inactive hosts get a dummy event
    (t=TIME_MAX) and their queue is untouched. Ties on time break by the packed
    `order` key — the device analogue of Event::cmp (event.rs:102-110).
    """
    limit = jnp.asarray(limit, jnp.int64)
    tmin = jnp.min(q.t, axis=1)  # [H]
    active = tmin < limit
    # among slots at the min time, take the smallest order key. On TPU the
    # winner is read back with a one-hot masked SUM over slots, not
    # argmin+gather: per-row dynamic gathers lower to a slow custom kernel
    # (~100 us per call at H=10k) while masked reductions are effectively
    # free. The one-hot is exact because order keys are globally unique
    # (pack_order) — at most one live slot can match (tmin, omin). On CPU
    # the gather formulation is faster; both compute the identical event, so
    # digests do not depend on the backend choice.
    at_min = (q.t == tmin[:, None]) & (q.t != TIME_MAX)
    cand_order = jnp.where(at_min, q.order, ORDER_MAX)
    omin = jnp.min(cand_order, axis=1)  # [H]
    onehot = at_min & (q.order == omin[:, None])  # [H, C], <=1 true per row

    if jax.default_backend() == "cpu":
        idx = jnp.argmin(cand_order, axis=1)  # [H]
        hh = jnp.arange(q.t.shape[0])
        ev = Event(
            t=jnp.where(active, q.t[hh, idx], TIME_MAX),
            order=jnp.where(active, q.order[hh, idx], ORDER_MAX),
            kind=jnp.where(active, q.kind[hh, idx], 0),
            payload=jnp.where(active[:, None], q.payload[hh, idx], 0),
        )
    else:

        def sel(v, default):
            got = jnp.sum(jnp.where(onehot, v, 0), axis=1, dtype=v.dtype)
            return jnp.where(active, got, default)

        ev = Event(
            t=sel(q.t, TIME_MAX),
            order=sel(q.order, ORDER_MAX),
            kind=sel(q.kind, 0),
            payload=jnp.where(
                active[:, None],
                jnp.sum(
                    jnp.where(onehot[:, :, None], q.payload, 0),
                    axis=1,
                    dtype=q.payload.dtype,
                ),
                0,
            ),
        )
    clear = active[:, None] & onehot
    return (
        q._replace(
            t=jnp.where(clear, TIME_MAX, q.t),
            order=jnp.where(clear, ORDER_MAX, q.order),
        ),
        ev,
        active,
    )


def push_many(q: EventQueue, pushes) -> EventQueue:
    """Push up to len(pushes) events per host in ONE pass over the slab.

    `pushes` is a sequence of (mask, t, order, kind, payload) tuples (arrays
    as in `push_one`). Semantics are identical to calling `push_one` in
    sequence — push k lands in the k-th free slot counting only earlier
    pushes that fired — but the slab is read and written once: sequential
    `push_one` calls each carry an argmax reduction that fences XLA fusion,
    so k pushes cost k full [H, C] memory passes; here the free-rank cumsum
    is computed once and every push is an elementwise one-hot on top of it
    (measured as the dominant per-microstep cost at 10k hosts x capacity 64).
    """
    free = q.t == TIME_MAX  # [H, C]
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1  # [H, C]
    free_count = jnp.sum(free.astype(jnp.int32), axis=1)  # [H]
    h = q.t.shape[0]
    need = jnp.zeros((h,), jnp.int32)  # free slots consumed by earlier pushes
    new_t, new_order, new_kind, new_payload = q.t, q.order, q.kind, q.payload
    dropped = q.dropped
    for mask, t, order, kind, payload in pushes:
        ok = mask & (need < free_count)
        oh = ok[:, None] & free & (free_rank == need[:, None])
        new_t = jnp.where(oh, jnp.asarray(t, jnp.int64)[:, None], new_t)
        new_order = jnp.where(
            oh, jnp.asarray(order, jnp.int64)[:, None], new_order
        )
        new_kind = jnp.where(
            oh, jnp.asarray(kind, jnp.int32)[:, None], new_kind
        )
        new_payload = jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :],
            new_payload,
        )
        dropped = dropped + jnp.where(mask & ~ok, 1, 0).astype(jnp.int64)
        need = need + ok.astype(jnp.int32)
    return EventQueue(
        t=new_t, order=new_order, kind=new_kind, payload=new_payload,
        dropped=dropped,
    )


def push_one(q: EventQueue, mask, t, order, kind, payload) -> EventQueue:
    """Push one event per host where `mask` ([H] bool) is set.

    Args are per-host arrays: t i64[H], order i64[H], kind i32[H],
    payload i32[H, P]. Overflow (no free slot) increments `dropped` instead of
    silently corrupting — the static-shape analogue of the reference heap's
    unbounded growth, surfaced in sim-stats.
    """
    free = q.t == TIME_MAX  # [H, C]
    has_free = jnp.any(free, axis=1)
    slot = jnp.argmax(free, axis=1)  # first free slot per host
    do = mask & has_free
    oh = do[:, None] & (jnp.arange(q.t.shape[1])[None, :] == slot[:, None])
    return q._replace(
        t=jnp.where(oh, jnp.asarray(t, jnp.int64)[:, None], q.t),
        order=jnp.where(oh, jnp.asarray(order, jnp.int64)[:, None], q.order),
        kind=jnp.where(oh, jnp.asarray(kind, jnp.int32)[:, None], q.kind),
        payload=jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :], q.payload
        ),
        dropped=q.dropped + jnp.where(mask & ~has_free, 1, 0).astype(jnp.int64),
    )
