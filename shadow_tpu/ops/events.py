"""Per-host event queues as fixed-capacity SoA arrays in HBM.

Reference model being rebuilt (not ported):
  - src/main/core/work/event_queue.rs:10-55 — per-host `BinaryHeap` with a
    monotonic-time assertion and `next_event_time` peek.
  - src/main/core/work/event.rs:102-155 — deterministic total order:
    (time, packets-before-local-tasks, src host id, per-src sequence number).

TPU-first recast: a queue is a `[H, C]` slab per field (times i64, order-key
i64, kind i32, payload i32×P). Empty slots hold TIME_MAX / ORDER_MAX. All ops
are branch-free masked reductions/scatters over the full slab so every host
advances in the same fused kernel; `H` is the sharded axis on the device mesh.

The total order is packed into two i64 keys compared lexicographically:
  primary   = event time (ns)
  secondary = `order` = (is_local_task << 62) | (src_host << 40) | seq
Packets sort before local tasks at equal times (is_local=1 for local tasks),
matching event.rs:131-155; `seq` is a per-source monotonically increasing
counter so concurrent sends resolve identically under any sharding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from shadow_tpu.simtime import TIME_MAX

# payload words per event: models pack (src, size, flow/port, aux) etc.
EVENT_PAYLOAD_WORDS = 4

# order-key field widths: 1 bit is_local | 22 bits src host | 40 bits seq
_SEQ_BITS = 40
_SRC_SHIFT = _SEQ_BITS
_LOCAL_SHIFT = 62
SEQ_MASK = (1 << _SEQ_BITS) - 1
ORDER_MAX = (1 << 63) - 1  # empty-slot sentinel, compares after any real key


class EventQueue(NamedTuple):
    """SoA event slab for H hosts × C slots (all arrays shard on axis 0)."""

    t: Array  # i64[H, C] event time; TIME_MAX = empty
    order: Array  # i64[H, C] secondary sort key; ORDER_MAX = empty
    kind: Array  # i32[H, C] event kind (model handler index)
    payload: Array  # i32[H, C, P]
    dropped: Array  # i64[H] events lost to capacity overflow (observability)


class Event(NamedTuple):
    """One popped event per host (all [H])."""

    t: Array  # i64[H]
    order: Array  # i64[H]
    kind: Array  # i32[H]
    payload: Array  # i32[H, P]


class PoppedK(NamedTuple):
    """Each host's K earliest in-window events, PEEKED (not yet removed).

    The K-way microstep pops a batch, folds as many events as its exactness
    guard allows, and then removes exactly that executed prefix with
    `clear_popped` — deferred events never leave the slab, so no re-push
    (and no spurious drop accounting) is ever needed. Events are sorted by
    the (time, order) total key along axis 1; `active[h, j]` is a prefix
    mask per host (times are sorted, so `t < limit` can only switch off)."""

    t: Array  # i64[H, K] (TIME_MAX where inactive)
    order: Array  # i64[H, K] (ORDER_MAX where inactive)
    kind: Array  # i32[H, K]
    payload: Array  # i32[H, K, P]
    active: Array  # bool[H, K]
    idx: Array  # i32[H, K] slab column holding each event (for the clear)

    def event(self, j: int) -> Event:
        return Event(
            t=self.t[:, j], order=self.order[:, j],
            kind=self.kind[:, j], payload=self.payload[:, j],
        )


def pack_order(is_local, src_host, seq) -> Array:
    """Pack the deterministic tiebreak key (event.rs:131-155 equivalent).

    Field limits — enforced statically via `check_order_limits`, not per-draw
    (this runs in the hot pop/merge path): src_host < 2^22 (≈4.2M hosts) and
    seq < 2^40 (≈1.1e12 events per source; a source emits at most one event
    per microstep, so wrap is unreachable in any real simulation length).
    """
    is_local = jnp.asarray(is_local, jnp.int64)
    src_host = jnp.asarray(src_host, jnp.int64)
    seq = jnp.asarray(seq, jnp.int64)
    return (is_local << _LOCAL_SHIFT) | (src_host << _SRC_SHIFT) | (seq & SEQ_MASK)


def unpack_order_src(order) -> Array:
    """Recover the sending host's global id from a packed order key (packets
    carry their source here — the reference's Packet keeps src addr fields)."""
    return (jnp.asarray(order, jnp.int64) >> _SRC_SHIFT) & (
        (1 << (_LOCAL_SHIFT - _SRC_SHIFT)) - 1
    )


def check_order_limits(num_hosts: int) -> None:
    """Static guard called at simulation build time: the packed key must never
    collide with ORDER_MAX (empty-slot sentinel) or spill src bits into the
    is_local bit."""
    if num_hosts >= (1 << (_LOCAL_SHIFT - _SRC_SHIFT)):
        raise ValueError(
            f"num_hosts={num_hosts} exceeds the {1 << (_LOCAL_SHIFT - _SRC_SHIFT)}"
            " host limit of the packed event-order key"
        )


def make_queue(num_hosts: int, capacity: int) -> EventQueue:
    return EventQueue(
        t=jnp.full((num_hosts, capacity), TIME_MAX, jnp.int64),
        order=jnp.full((num_hosts, capacity), ORDER_MAX, jnp.int64),
        kind=jnp.zeros((num_hosts, capacity), jnp.int32),
        payload=jnp.zeros((num_hosts, capacity, EVENT_PAYLOAD_WORDS), jnp.int32),
        dropped=jnp.zeros((num_hosts,), jnp.int64),
    )


def next_time(q: EventQueue) -> Array:
    """Per-host earliest pending event time (event_queue.rs:52-54 peek)."""
    return jnp.min(q.t, axis=1)


def queue_len(q: EventQueue) -> Array:
    return jnp.sum((q.t != TIME_MAX).astype(jnp.int32), axis=1)


def pop_min(q: EventQueue, limit) -> tuple[EventQueue, Event, Array]:
    """Pop each host's earliest event strictly before `limit` (i64 scalar or [H]).

    Returns (queue', event, active[H] bool). Inactive hosts get a dummy event
    (t=TIME_MAX) and their queue is untouched. Ties on time break by the packed
    `order` key — the device analogue of Event::cmp (event.rs:102-110).
    """
    limit = jnp.asarray(limit, jnp.int64)
    tmin = jnp.min(q.t, axis=1)  # [H]
    active = tmin < limit
    # among slots at the min time, take the smallest order key. On TPU the
    # winner is read back with a one-hot masked SUM over slots, not
    # argmin+gather: per-row dynamic gathers lower to a slow custom kernel
    # (~100 us per call at H=10k) while masked reductions are effectively
    # free. The one-hot is exact because order keys are globally unique
    # (pack_order) — at most one live slot can match (tmin, omin). On CPU
    # the gather formulation is faster; both compute the identical event, so
    # digests do not depend on the backend choice.
    at_min = (q.t == tmin[:, None]) & (q.t != TIME_MAX)
    cand_order = jnp.where(at_min, q.order, ORDER_MAX)
    omin = jnp.min(cand_order, axis=1)  # [H]
    onehot = at_min & (q.order == omin[:, None])  # [H, C], <=1 true per row

    if jax.default_backend() == "cpu":
        idx = jnp.argmin(cand_order, axis=1)  # [H]
        hh = jnp.arange(q.t.shape[0])
        ev = Event(
            t=jnp.where(active, q.t[hh, idx], TIME_MAX),
            order=jnp.where(active, q.order[hh, idx], ORDER_MAX),
            kind=jnp.where(active, q.kind[hh, idx], 0),
            payload=jnp.where(active[:, None], q.payload[hh, idx], 0),
        )
    else:

        def sel(v, default):
            got = jnp.sum(jnp.where(onehot, v, 0), axis=1, dtype=v.dtype)
            return jnp.where(active, got, default)

        ev = Event(
            t=sel(q.t, TIME_MAX),
            order=sel(q.order, ORDER_MAX),
            kind=sel(q.kind, 0),
            payload=jnp.where(
                active[:, None],
                jnp.sum(
                    jnp.where(onehot[:, :, None], q.payload, 0),
                    axis=1,
                    dtype=q.payload.dtype,
                ),
                0,
            ),
        )
    clear = active[:, None] & onehot
    return (
        q._replace(
            t=jnp.where(clear, TIME_MAX, q.t),
            order=jnp.where(clear, ORDER_MAX, q.order),
        ),
        ev,
        active,
    )


def pop_k(q, limit, k: int, force_path: str | None = None) -> PoppedK:
    """PEEK each host's k earliest events strictly before `limit` — the
    K-way microstep's batch extraction (works on either queue type through
    the flat planes).

    Nothing is written: the caller removes the prefix it actually executed
    with `clear_popped`, so a single read of the key planes plus ONE
    kind/payload extraction and ONE clear write replace the k reads AND k
    writes of every [H, C] plane that k successive `pop_min` calls pay —
    the amortization the K-way microstep is built on. The j-th column
    equals what the j-th successive `q_pop_min` would return (order keys
    are globally unique, so ties exist only among the empty-slot
    sentinels, which `active` masks out).

    Two formulations, pinned by `force_path` ('gather' | 'onehot'), same
    backend split as `pop_min`, identical results:

      - gather (CPU default): k iterated (min-time, min-order) selections
        over working copies of the key planes — measured ~4x faster than
        the XLA-CPU generic-comparator row sort at H=10k, C=28, k=8 —
        then row gathers for kind/payload (cheap on CPU);
      - onehot (TPU default): one per-row `lax.sort` over the packed key
        (a fused sorting network, no per-row gathers), then one-hot
        masked-sum extraction per batch column."""
    qf = as_flat(q)
    h, c = qf.t.shape
    k = min(k, c)
    limit = jnp.broadcast_to(jnp.asarray(limit, jnp.int64), (h,))
    cols = jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[None, :], (h, c))
    path = force_path or (
        "gather" if jax.default_backend() == "cpu" else "onehot"
    )
    if path == "gather":
        wt, wo = qf.t, qf.order
        t_cols, o_cols, i_cols = [], [], []
        for _ in range(k):
            tmin = jnp.min(wt, axis=1)
            cand = jnp.where(wt == tmin[:, None], wo, ORDER_MAX)
            omin = jnp.min(cand, axis=1)
            idx_j = jnp.argmax(
                (wt == tmin[:, None]) & (wo == omin[:, None]), axis=1
            ).astype(jnp.int32)
            # narrow to the ONE winning slot (empty slots share the
            # sentinel pair, so the raw match can cover several columns)
            oh = cols == idx_j[:, None]
            wt = jnp.where(oh, TIME_MAX, wt)
            wo = jnp.where(oh, ORDER_MAX, wo)
            t_cols.append(tmin)
            o_cols.append(omin)
            i_cols.append(idx_j)
        ev_t = jnp.stack(t_cols, axis=1)
        ev_o = jnp.stack(o_cols, axis=1)
        idx = jnp.stack(i_cols, axis=1)
        active = ev_t < limit[:, None]  # prefix per host (times ascend)
        hh = jnp.arange(h)[:, None]
        ev_kind = jnp.where(active, qf.kind[hh, idx], 0)
        ev_payload = jnp.where(active[:, :, None], qf.payload[hh, idx], 0)
    else:
        s_t, s_o, s_i = jax.lax.sort(
            (qf.t, qf.order, cols), dimension=1, num_keys=2
        )
        ev_t, ev_o, idx = s_t[:, :k], s_o[:, :k], s_i[:, :k]
        active = ev_t < limit[:, None]
        # one-hot masked sums, one [H, C] pass per batch column (see the
        # pop_min one-hot rationale: per-row dynamic gathers lower to slow
        # custom kernels on TPU). Exact: each column index hits one slot.
        ks, ps = [], []
        for j in range(k):
            oh = active[:, j, None] & (cols == idx[:, j : j + 1])
            ks.append(jnp.sum(jnp.where(oh, qf.kind, 0), axis=1, dtype=qf.kind.dtype))
            ps.append(
                jnp.sum(
                    jnp.where(oh[:, :, None], qf.payload, 0),
                    axis=1,
                    dtype=qf.payload.dtype,
                )
            )
        ev_kind = jnp.stack(ks, axis=1)
        ev_payload = jnp.stack(ps, axis=1)
    return PoppedK(
        t=jnp.where(active, ev_t, TIME_MAX),
        order=jnp.where(active, ev_o, ORDER_MAX),
        kind=ev_kind,
        payload=ev_payload,
        active=active,
        idx=idx,
    )


def clear_popped(q, popped: PoppedK, m):
    """Remove the first `m[h]` ([H] i32) events of a `pop_k` batch from the
    slab — the executed prefix; deferred events past `m` stay in place.

    One write pass over the t/order planes. For a `BucketQueue` the block
    caches are maintained by a victim-block recompute covering up to K
    victims: only blocks that lost a slot get their (bt, bo) minimum
    recomputed (the K-way analogue of `bq_pop_min`'s single-victim
    recompute); untouched blocks keep their cached values bit-for-bit."""
    qf = as_flat(q)
    h, c = qf.t.shape
    k = popped.idx.shape[1]
    take = popped.active & (jnp.arange(k, dtype=jnp.int32)[None, :] < m[:, None])
    cols = jnp.arange(c, dtype=jnp.int32)[None, :]
    clear = jnp.zeros((h, c), bool)
    for j in range(k):
        clear = clear | (take[:, j, None] & (cols == popped.idx[:, j : j + 1]))
    new_t = jnp.where(clear, TIME_MAX, qf.t)
    new_order = jnp.where(clear, ORDER_MAX, qf.order)
    if not isinstance(q, BucketQueue):
        return q._replace(t=new_t, order=new_order)
    nb = q.bt.shape[1]
    b = c // nb
    cleared3 = clear.reshape(h, nb, b)
    touched = jnp.any(cleared3, axis=2)  # [H, NB] blocks that lost a slot
    t3 = new_t.reshape(h, nb, b)
    o3 = new_order.reshape(h, nb, b)
    nbt = jnp.min(t3, axis=2)
    nbo = jnp.min(jnp.where(t3 == nbt[:, :, None], o3, ORDER_MAX), axis=2)
    return q._replace(
        t=new_t,
        order=new_order,
        bt=jnp.where(touched, nbt, q.bt),
        bo=jnp.where(touched, nbo, q.bo),
        # dtype pinned (see block_minima): sum promotion must not widen
        # the i32 cache
        bfill=q.bfill - jnp.sum(cleared3, axis=2, dtype=jnp.int32),
    )


def _push_fields(push):
    """(mask, t, order, kind, payload, reserve|None): pushes are 5-tuples;
    the K-way microstep appends a 6th element — a per-host i32 RESERVE of
    free slots spoken for by already-popped batch events that executed
    after this push's event (in K=1 they were still sitting in the queue
    when the push landed, so the push must not be allowed to use their
    space — that is what keeps drop decisions bit-identical to K=1)."""
    mask, t, order, kind, payload = push[:5]
    reserve = push[5] if len(push) > 5 else None
    return mask, t, order, kind, payload, reserve


def push_many(q: EventQueue, pushes) -> EventQueue:
    """Push up to len(pushes) events per host in ONE pass over the slab.

    `pushes` is a sequence of (mask, t, order, kind, payload[, reserve])
    tuples (arrays as in `push_one`; `reserve` is the K-way microstep's
    capacity hold, see `_push_fields`). Semantics are identical to calling
    `push_one` in sequence — push k lands in the k-th free slot counting
    only earlier pushes that fired — but the slab is read and written once:
    sequential `push_one` calls each carry an argmax reduction that fences
    XLA fusion, so k pushes cost k full [H, C] memory passes; here the
    free-rank cumsum is computed once and every push is an elementwise
    one-hot on top of it (measured as the dominant per-microstep cost at
    10k hosts x capacity 64).
    """
    free = q.t == TIME_MAX  # [H, C]
    free_rank = jnp.cumsum(free.astype(jnp.int32), axis=1) - 1  # [H, C]
    free_count = jnp.sum(free.astype(jnp.int32), axis=1)  # [H]
    h = q.t.shape[0]
    need = jnp.zeros((h,), jnp.int32)  # free slots consumed by earlier pushes
    new_t, new_order, new_kind, new_payload = q.t, q.order, q.kind, q.payload
    dropped = q.dropped
    for push in pushes:
        mask, t, order, kind, payload, reserve = _push_fields(push)
        avail = free_count if reserve is None else free_count - reserve
        ok = mask & (need < avail)
        oh = ok[:, None] & free & (free_rank == need[:, None])
        new_t = jnp.where(oh, jnp.asarray(t, jnp.int64)[:, None], new_t)
        new_order = jnp.where(
            oh, jnp.asarray(order, jnp.int64)[:, None], new_order
        )
        new_kind = jnp.where(
            oh, jnp.asarray(kind, jnp.int32)[:, None], new_kind
        )
        new_payload = jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :],
            new_payload,
        )
        dropped = dropped + jnp.where(mask & ~ok, 1, 0).astype(jnp.int64)
        need = need + ok.astype(jnp.int32)
    return EventQueue(
        t=new_t, order=new_order, kind=new_kind, payload=new_payload,
        dropped=dropped,
    )


def push_one(q: EventQueue, mask, t, order, kind, payload) -> EventQueue:
    """Push one event per host where `mask` ([H] bool) is set.

    Args are per-host arrays: t i64[H], order i64[H], kind i32[H],
    payload i32[H, P]. Overflow (no free slot) increments `dropped` instead of
    silently corrupting — the static-shape analogue of the reference heap's
    unbounded growth, surfaced in sim-stats.
    """
    free = q.t == TIME_MAX  # [H, C]
    has_free = jnp.any(free, axis=1)
    slot = jnp.argmax(free, axis=1)  # first free slot per host
    do = mask & has_free
    oh = do[:, None] & (jnp.arange(q.t.shape[1])[None, :] == slot[:, None])
    return q._replace(
        t=jnp.where(oh, jnp.asarray(t, jnp.int64)[:, None], q.t),
        order=jnp.where(oh, jnp.asarray(order, jnp.int64)[:, None], q.order),
        kind=jnp.where(oh, jnp.asarray(kind, jnp.int32)[:, None], q.kind),
        payload=jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :], q.payload
        ),
        dropped=q.dropped + jnp.where(mask & ~has_free, 1, 0).astype(jnp.int64),
    )


# --------------------------------------------------------------------------
# two-level bucketed queue (per-block incremental min-caches)
# --------------------------------------------------------------------------


class BucketQueue(NamedTuple):
    """Two-level SoA event slab: the flat [H, C] planes of `EventQueue` plus
    per-block cached minima over C/B blocks of B contiguous slots.

    Invariant (the *block-min invariant*, enforced by tests/test_bucketq.py):
    for every host h and block j,

      (bt[h, j], bo[h, j]) == lexicographic min of (t, order) over the
                              block's live slots  (TIME_MAX/ORDER_MAX if empty)
      bfill[h, j]          == number of live slots in the block

    Caches are maintained INCREMENTALLY on the microstep hot path — a pop
    recomputes only the victim block's minimum, a push is a 2-way min update
    of its block cache — and rebuilt wholesale only at the cross-shard
    exchange merge and on checkpoint restore (`bucket_rebuild`). pop/push
    semantics are bit-identical to the flat `EventQueue` ops: the same event
    pops, pushes land in the same slots, drops count the same — the flat
    queue IS the B=C degenerate case. What changes is the per-microstep
    footprint: the min reductions run over [H, C/B] block minima plus one
    [H, B] victim block instead of the whole [H, C] slab (O(C/B + B) versus
    O(C) per event)."""

    t: Array  # i64[H, C] event time; TIME_MAX = empty
    order: Array  # i64[H, C] secondary sort key; ORDER_MAX = empty
    kind: Array  # i32[H, C]
    payload: Array  # i32[H, C, P]
    dropped: Array  # i64[H]
    bt: Array  # i64[H, C/B] cached block-min time
    bo: Array  # i64[H, C/B] order key at that minimum
    bfill: Array  # i32[H, C/B] live slots per block

    @property
    def block(self) -> int:
        """Slots per block (B)."""
        return self.t.shape[1] // self.bt.shape[1]


def as_flat(q) -> EventQueue:
    """The flat-slab view of either queue type (shared planes, no copy)."""
    if isinstance(q, BucketQueue):
        return EventQueue(q.t, q.order, q.kind, q.payload, q.dropped)
    return q


def block_minima(t, order, num_blocks: int):
    """(bt, bo, bfill) recomputed wholesale from the slab — the rebuild
    primitive used at the exchange merge and on checkpoint restore."""
    h, c = t.shape
    b = c // num_blocks
    t3 = t.reshape(h, num_blocks, b)
    o3 = order.reshape(h, num_blocks, b)
    bt = jnp.min(t3, axis=2)
    bo = jnp.min(jnp.where(t3 == bt[:, :, None], o3, ORDER_MAX), axis=2)
    # dtype pinned: numpy-style sum promotion would widen the i32 bfill
    # cache to i64 (the registry drift the memory observatory surfaced —
    # lanes.py registers queue.bfill as int32 and the byte model charges it
    # as such)
    bfill = jnp.sum(t3 != TIME_MAX, axis=2, dtype=jnp.int32)
    return bt, bo, bfill


def bucket_rebuild(q, block: int) -> BucketQueue:
    """Wrap a flat queue (or refresh a bucketed one) with freshly computed
    block caches."""
    q = as_flat(q)
    h, c = q.t.shape
    if block <= 0 or c % block:
        raise ValueError(
            f"block={block} must be positive and divide capacity {c}"
        )
    bt, bo, bfill = block_minima(q.t, q.order, c // block)
    return BucketQueue(q.t, q.order, q.kind, q.payload, q.dropped, bt, bo, bfill)


def make_bucket_queue(num_hosts: int, capacity: int, block: int) -> BucketQueue:
    return bucket_rebuild(make_queue(num_hosts, capacity), block)


def bq_next_time(q: BucketQueue) -> Array:
    """Per-host earliest pending event time from the [H, C/B] caches alone —
    no slab read (the flat `next_time` is a full [H, C] reduction)."""
    return jnp.min(q.bt, axis=1)


def bq_pop_min(
    q: BucketQueue, limit, force_path: str | None = None
) -> tuple[BucketQueue, Event, Array]:
    """`pop_min` over the two-level queue: identical event, slot clear, and
    `active` as the flat op, computed from [H, C/B] + [H, B] reductions.

    The winning block is the lexicographic min over the cached
    (bt, bo) pairs; the winning slot is found inside that one block. The
    victim block's cache is then recomputed from its B slots — the only
    incremental maintenance a pop needs. Block selection by (bt, bo) is
    exact because order keys are globally unique: at most one block can
    match (tmin, omin) while a host is active, and inactive hosts never
    write (multiple empty blocks share the sentinel pair, but active
    implies the winner holds a real event).

    `force_path` ('gather' | 'onehot') pins the backend formulation — the
    tests' lever for exercising the TPU one-hot path on CPU; both compute
    the identical event and slab."""
    limit = jnp.asarray(limit, jnp.int64)
    h, c = q.t.shape
    nb = q.bt.shape[1]
    b = c // nb
    tmin = jnp.min(q.bt, axis=1)  # [H]
    active = tmin < limit
    cand = jnp.where(q.bt == tmin[:, None], q.bo, ORDER_MAX)
    omin = jnp.min(cand, axis=1)  # [H]
    t3 = q.t.reshape(h, nb, b)
    o3 = q.order.reshape(h, nb, b)
    k3 = q.kind.reshape(h, nb, b)
    p3 = q.payload.reshape(h, nb, b, q.payload.shape[-1])

    path = force_path or (
        "gather" if jax.default_backend() == "cpu" else "onehot"
    )
    if path == "gather":
        # gather formulation for READS (same backend split as the flat
        # pop_min: CPU row gathers are cheap, and they touch only [H, B]
        # victim blocks); writes stay one-hot `where` passes — measured on
        # XLA-CPU a [H, C] scatter costs ~3x the compare+select pair
        bidx = jnp.argmin(cand, axis=1)  # [H] winning block
        hh = jnp.arange(h)
        blk_t = t3[hh, bidx]  # [H, B]
        blk_o = o3[hh, bidx]
        soh = (
            active[:, None]
            & (blk_t == tmin[:, None])
            & (blk_o == omin[:, None])
        )  # <=1 true per row
        sidx = jnp.argmax(soh, axis=1)  # [H] winning slot within block
        ev = Event(
            t=jnp.where(active, blk_t[hh, sidx], TIME_MAX),
            order=jnp.where(active, blk_o[hh, sidx], ORDER_MAX),
            kind=jnp.where(active, k3[hh, bidx, sidx], 0),
            payload=jnp.where(active[:, None], p3[hh, bidx, sidx], 0),
        )
        col = bidx * b + sidx
        clear = active[:, None] & (jnp.arange(c)[None, :] == col[:, None])
        boh = active[:, None] & (jnp.arange(nb)[None, :] == bidx[:, None])
    else:
        # one-hot formulation: per-row dynamic gathers lower to slow custom
        # kernels on TPU; exact masked SUMS extract the victim block instead
        # (one hit per row — see the flat pop_min's one-hot rationale)
        boh = active[:, None] & (q.bt == tmin[:, None]) & (q.bo == omin[:, None])

        def ext(v3):
            return jnp.sum(jnp.where(boh[:, :, None], v3, 0), axis=1, dtype=v3.dtype)

        blk_t = ext(t3)  # [H, B] victim block (zeros when inactive)
        blk_o = ext(o3)
        blk_k = ext(k3)
        blk_p = jnp.sum(
            jnp.where(boh[:, :, None, None], p3, 0), axis=1, dtype=p3.dtype
        )
        soh = active[:, None] & (blk_t == tmin[:, None]) & (blk_o == omin[:, None])

        def sel(vb, default):
            got = jnp.sum(jnp.where(soh, vb, 0), axis=1, dtype=vb.dtype)
            return jnp.where(active, got, default)

        ev = Event(
            t=sel(blk_t, TIME_MAX),
            order=sel(blk_o, ORDER_MAX),
            kind=sel(blk_k, 0),
            payload=jnp.where(
                active[:, None],
                jnp.sum(
                    jnp.where(soh[:, :, None], blk_p, 0), axis=1,
                    dtype=blk_p.dtype,
                ),
                0,
            ),
        )
        clear = (boh[:, :, None] & soh[:, None, :]).reshape(h, c)
    # slot clear + victim-block cache recompute, shared by both paths: each
    # produced the victim block (blk_t, blk_o) [H, B] and active-gated slot
    # (soh) / block (boh) one-hots — keeping this in ONE place is what keeps
    # the two backend formulations from diverging
    new_t = jnp.where(clear, TIME_MAX, q.t)
    new_order = jnp.where(clear, ORDER_MAX, q.order)
    bt2 = jnp.where(soh, TIME_MAX, blk_t)
    bo2 = jnp.where(soh, ORDER_MAX, blk_o)
    nbt = jnp.min(bt2, axis=1)
    nbo = jnp.min(jnp.where(bt2 == nbt[:, None], bo2, ORDER_MAX), axis=1)
    return (
        q._replace(
            t=new_t,
            order=new_order,
            bt=jnp.where(boh, nbt[:, None], q.bt),
            bo=jnp.where(boh, nbo[:, None], q.bo),
            bfill=q.bfill - boh.astype(jnp.int32),
        ),
        ev,
        active,
    )


def bq_push_many(
    q: BucketQueue, pushes, force_path: str | None = None
) -> BucketQueue:
    """`push_many` over the two-level queue: identical slot assignment and
    drop accounting as the flat op.

    `push_many` is defined as sequential `push_one` semantics (each push
    lands in the first free slot of the state its predecessors left), so
    both formulations here chase the FIRST not-full block from the RUNNING
    `bfill` cache — no [H, C] free-count reduction ever runs:

      - CPU: gather the target block's B slots from the updated slab and
        take its first free slot (per-row gathers are cheap on CPU);
      - TPU: free masks are computed once up front and the k-th push lands
        at pre-ranked free-slot k (the same bijection the flat op uses) —
        one [H, C/B + B]-shaped one-hot per push, no gathers.

    Block-major × slot order == plain slot order, so the written slab is
    bit-identical to the flat `push_many`. Each push 2-way-min-updates its
    block's (bt, bo) cache and bumps `bfill` — pops stay cheap without ever
    rebuilding. `force_path` ('gather' | 'onehot') pins the formulation for
    tests; both write the identical slab."""
    h, c = q.t.shape
    nb = q.bt.shape[1]
    b = c // nb
    path = force_path or (
        "gather" if jax.default_backend() == "cpu" else "onehot"
    )
    cpu = path == "gather"
    hh = jnp.arange(h)
    cols = jnp.arange(c, dtype=jnp.int32)[None, :]
    blks = jnp.arange(nb, dtype=jnp.int32)[None, :]
    if not cpu:
        # pre-ranked free structure (computed once, like the flat op):
        # push k of this call lands at global free rank k, found as
        # (block where the rank falls by cached occupancy, local rank)
        free3 = q.t.reshape(h, nb, b) == TIME_MAX
        lrank = jnp.cumsum(free3.astype(jnp.int32), axis=2) - 1  # [H, NB, B]
        bfree0 = b - q.bfill
        excl = jnp.cumsum(bfree0, axis=1) - bfree0  # exclusive block prefix
        need = jnp.zeros((h,), jnp.int32)
    new_t, new_order, new_kind, new_payload = q.t, q.order, q.kind, q.payload
    bt, bo, bfill = q.bt, q.bo, q.bfill
    dropped = q.dropped
    for push in pushes:
        mask, t, order, kind, payload, reserve = _push_fields(push)
        not_full = bfill < b  # [H, NB] running occupancy
        if reserve is None:
            ok = mask & jnp.any(not_full, axis=1)
        else:
            # reserved slots (see _push_fields) shrink the RUNNING free
            # total; b*nb - sum(bfill) == original free - successes so far,
            # so this is exactly the flat op's `need + reserve < free_count`
            free_running = b * nb - jnp.sum(bfill, axis=1)
            ok = mask & (free_running > reserve)
        if cpu:
            tb = jnp.argmax(not_full, axis=1)  # first not-full block
            blk = new_t.reshape(h, nb, b)[hh, tb]  # [H, B] current slots
            sidx = jnp.argmax(blk == TIME_MAX, axis=1)  # its first free slot
            col = (tb * b + sidx).astype(jnp.int32)
            oh = ok[:, None] & (cols == col[:, None])
            boh = ok[:, None] & (blks == tb[:, None].astype(jnp.int32))
        else:
            nd = need[:, None]
            # interval test against the ORIGINAL free structure (excl/bfree0
            # pair): ranks are assigned on the entry state, like the flat op
            boh = ok[:, None] & (excl <= nd) & (nd < excl + bfree0)  # <=1/row
            r = nd - excl  # local free rank within the target block
            oh = (boh[:, :, None] & free3 & (lrank == r[:, :, None])).reshape(
                h, c
            )
            need = need + ok.astype(jnp.int32)
        t_arr = jnp.asarray(t, jnp.int64)
        o_arr = jnp.asarray(order, jnp.int64)
        new_t = jnp.where(oh, t_arr[:, None], new_t)
        new_order = jnp.where(oh, o_arr[:, None], new_order)
        new_kind = jnp.where(
            oh, jnp.asarray(kind, jnp.int32)[:, None], new_kind
        )
        new_payload = jnp.where(
            oh[:, :, None], jnp.asarray(payload, jnp.int32)[:, None, :],
            new_payload,
        )
        # incremental cache maintenance: lexicographic 2-way min against the
        # RUNNING cache (two pushes into one block chain correctly)
        better = boh & (
            (t_arr[:, None] < bt)
            | ((t_arr[:, None] == bt) & (o_arr[:, None] < bo))
        )
        bt = jnp.where(better, t_arr[:, None], bt)
        bo = jnp.where(better, o_arr[:, None], bo)
        bfill = bfill + boh.astype(jnp.int32)
        dropped = dropped + jnp.where(mask & ~ok, 1, 0).astype(jnp.int64)
    return BucketQueue(
        new_t, new_order, new_kind, new_payload, dropped, bt, bo, bfill
    )


# --------------------------------------------------------------------------
# capacity migration (the pressure plane's escalation primitive)
# --------------------------------------------------------------------------


def migrate_queue(q, new_capacity: int, block: int = 0):
    """Re-seat a queue's events into a slab of `new_capacity` slots per
    host — the pressure plane's escalation primitive (core/pressure.py)
    and the cross-capacity checkpoint-restore path.

    Exactness argument (gated by tests/test_pressure.py): slot POSITIONS
    are unobservable — pops select by the (time, order) total key over
    the whole slab, pushes/drops depend only on the free-slot COUNT, and
    the digest folds popped keys — so any slab holding the same event
    multiset with the same capacity behaves bit-identically. Growth pads
    empty columns (TIME_MAX/ORDER_MAX sentinels) after the existing
    slots; shrink first compacts live events to the front (stable in
    column order) then truncates the now-empty tail. The result is
    therefore indistinguishable from a queue BUILT at `new_capacity`
    carrying the same events.

    Caller contract on shrink: every live event must fit
    (`q_len(q) <= new_capacity` per host) — slots holding real events
    must never truncate. This function is pure/traceable, so the loud
    refusal lives in the host-side callers (core/pressure.py,
    core/checkpoint.py); see `migration_fits`.

    `block` > 0 returns a `BucketQueue` with freshly rebuilt caches
    (migration is a rebuild point, like the exchange merge); 0 returns a
    flat `EventQueue`. Works on either input queue type."""
    qf = as_flat(q)
    h, c = qf.t.shape
    new_capacity = int(new_capacity)
    if new_capacity < 1:
        raise ValueError(f"new_capacity must be >= 1, got {new_capacity}")
    if block < 0 or (block and new_capacity % block):
        raise ValueError(
            f"block={block} must be 0 (flat) or divide new_capacity="
            f"{new_capacity} evenly"
        )
    t, order, kind, payload = qf.t, qf.order, qf.kind, qf.payload
    if new_capacity < c:
        # compact live slots to the front, stable in column order (jax
        # sorts are stable), so the truncated tail is all-empty whenever
        # the caller's occupancy contract holds
        live = t != TIME_MAX
        key = jnp.where(
            live,
            jnp.broadcast_to(jnp.arange(c, dtype=jnp.int32)[None, :], (h, c)),
            jnp.int32(c),
        )
        idx = jnp.argsort(key, axis=1)
        t = jnp.take_along_axis(t, idx, axis=1)
        order = jnp.take_along_axis(order, idx, axis=1)
        kind = jnp.take_along_axis(kind, idx, axis=1)
        payload = jnp.take_along_axis(payload, idx[:, :, None], axis=1)
        t = t[:, :new_capacity]
        order = order[:, :new_capacity]
        kind = kind[:, :new_capacity]
        payload = payload[:, :new_capacity]
    elif new_capacity > c:
        pad = new_capacity - c
        t = jnp.concatenate(
            [t, jnp.full((h, pad), TIME_MAX, jnp.int64)], axis=1
        )
        order = jnp.concatenate(
            [order, jnp.full((h, pad), ORDER_MAX, jnp.int64)], axis=1
        )
        kind = jnp.concatenate(
            [kind, jnp.zeros((h, pad), jnp.int32)], axis=1
        )
        payload = jnp.concatenate(
            [payload, jnp.zeros((h, pad, payload.shape[-1]), jnp.int32)],
            axis=1,
        )
    out = EventQueue(t=t, order=order, kind=kind, payload=payload,
                     dropped=qf.dropped)
    if block:
        return bucket_rebuild(out, block)
    return out


def grow_queue(q: EventQueue, new_capacity: int) -> EventQueue:
    """`migrate_queue` restricted to growth (C' > C) on a flat queue —
    the escalation fast path: live slots keep their columns, new empty
    columns append (no compaction pass)."""
    if new_capacity <= q.t.shape[1]:
        raise ValueError(
            f"grow_queue: new_capacity={new_capacity} must exceed the "
            f"current capacity {q.t.shape[1]}"
        )
    return migrate_queue(q, new_capacity, block=0)


def grow_bucket_queue(
    q: BucketQueue, new_capacity: int, block: int = 0
) -> BucketQueue:
    """`grow_queue` for the two-level queue: pad the flat planes, then
    rebuild the (bt, bo, bfill) caches wholesale for the new block grid
    (migration is a rebuild point — trusting grown caches would leave
    the new blocks' minima unset)."""
    if new_capacity <= q.t.shape[1]:
        raise ValueError(
            f"grow_bucket_queue: new_capacity={new_capacity} must exceed "
            f"the current capacity {q.t.shape[1]}"
        )
    return migrate_queue(q, new_capacity, block=block or q.block)


def migration_fits(q, new_capacity: int):
    """Per-host predicate: every live event fits in `new_capacity` slots
    (bool[H]). Hosts where this is False would lose events on shrink —
    the host-side refusal check `migrate_queue`'s shrink contract
    requires (pure, so callers can read it off-device with one sum)."""
    return q_len(q) <= jnp.int32(int(new_capacity))


# ---- queue-kind dispatchers (trace-time: the queue type is static) --------


def q_next_time(q) -> Array:
    return bq_next_time(q) if isinstance(q, BucketQueue) else next_time(q)


def q_pop_min(q, limit):
    return bq_pop_min(q, limit) if isinstance(q, BucketQueue) else pop_min(q, limit)


def q_head(q) -> tuple[Array, Array]:
    """Per-host head key: the (time, order) pair `q_pop_min` would pop
    next, (TIME_MAX, ORDER_MAX) where the queue is empty. The timer-wheel
    engine integration compares the queue head against the wheel head to
    decide which structure pops this microstep (core/engine.py
    `_pop_min_merged`), so this must agree with the pop selection
    bit-for-bit: bucketed queues reduce the [H, C/B] caches (each block's
    `bo` is the order AT its min time, so the min over blocks at the
    global min time is the head order — block selection exactness as in
    `bq_pop_min`); flat queues pay one [H, C] reduction pair."""
    if isinstance(q, BucketQueue):
        t, o = q.bt, q.bo
    else:
        t, o = q.t, q.order
    tmin = jnp.min(t, axis=1)
    omin = jnp.min(jnp.where(t == tmin[:, None], o, ORDER_MAX), axis=1)
    return tmin, omin


def q_len(q) -> Array:
    """Per-host live-slot count (occupancy) for either queue type. The
    bucketed queue sums its [H, C/B] `bfill` caches instead of scanning
    the [H, C] slab — the cheap read the occupancy high-water tracking
    relies on (one call per round, core/engine.py)."""
    if isinstance(q, BucketQueue):
        return jnp.sum(q.bfill, axis=1)
    return queue_len(q)


def q_push_many(q, pushes):
    return bq_push_many(q, pushes) if isinstance(q, BucketQueue) else push_many(q, pushes)


def q_pop_k(q, limit, k: int) -> PoppedK:
    """K-way peek for either queue type (`pop_k` reads through the flat
    planes; the bucketed caches are maintained at `q_clear_popped`)."""
    return pop_k(q, limit, k)


def q_clear_popped(q, popped: PoppedK, m):
    return clear_popped(q, popped, m)


def kind_in(kind, kinds: tuple[int, ...]) -> Array:
    """bool mask: `kind` equals any of the STATIC `kinds` tuple — the
    network observatory's event-class membership test (a chain of eqs
    XLA fuses; `kinds` is trace-time static, typically 1-2 entries).
    An empty tuple yields all-False without reading `kind`'s values."""
    if not kinds:
        return jnp.zeros(kind.shape, bool)
    m = kind == kinds[0]
    for k in kinds[1:]:
        m = m | (kind == k)
    return m
