"""Device-resident per-host timer wheel: a fixed-slot calendar for model
timer events (RTO / delayed-ACK / periodic ticks) that keeps them out of
the packet event queue entirely.

Blueprint (PAPERS.md): "A Grouped Sorting Queue Supporting Dynamic
Updates for Timer Management" (arxiv 2601.09081) and Eiffel's bucketed
FFS queues (arxiv 1810.03060). Both observe that timer workloads are
dominated by push/cancel churn on entries that are NOT due yet, so the
structure should make `next-due` and `pop-due` cheap without keeping a
totally-ordered heap. The TPU recast: the wheel is a per-host `[H, S]`
SoA slab with per-block (min-time, min-order, fill) caches — literally
the `BucketQueue` machinery from `ops/events.py` re-aimed at timers.
The block-min cache plane plays the role of Eiffel's find-first-set
bitmap: `next_time` is one `[H, S/B]` reduction, a pop touches one
victim block, and a push is a running-occupancy one-hot. Grouped
sorting's "dynamic update" is `wheel_cancel`: order keys are globally
unique, so a cancel is one masked compare over the slab plus a victim-
block cache recompute — no re-sort, no tombstones.

Why this is EXACT (the property the engine integration leans on,
tests/test_wheel.py is the gate): slot positions are unobservable — the
engine pops the lexicographic (time, order) minimum of queue ∪ wheel,
so any split of the pending-event multiset between the two structures
dispatches the identical sequence. Capacity is the one observable
difference: a wheel push that would overflow SPILLS to the event queue
(`route` masks in core/engine.py), so no event is ever lost to the
wheel — spills are counted (stats.wheel_spilled), never silent, and the
wheel's own `dropped` lane is structurally zero.

All lane dtypes are sourced from the registry (core/lanes.py `wheel.*`
entries mirror the `queue.*` widths — the wheel reuses the queue's
machinery, so the widths must stay in lockstep; shadowlint's wheel rule
checks exactly that).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from shadow_tpu.ops.events import (
    BucketQueue,
    bq_next_time,
    bq_pop_min,
    bq_push_many,
    make_bucket_queue,
    migrate_queue,
    q_len,
)
from shadow_tpu.simtime import TIME_MAX
from shadow_tpu.ops.events import ORDER_MAX

# The wheel IS a BucketQueue: same SoA planes ([H, S] t/order/kind/payload
# + dropped), same block-min caches (bt/bo/bfill over S/WB blocks), same
# incremental maintenance ops. The alias is the design statement — every
# exactness property proven for the bucketed queue (tests/test_bucketq.py)
# transfers to the wheel for free, and checkpoint/migration/HBM pricing
# reuse the queue paths verbatim.
TimerWheel = BucketQueue


def resolve_wheel_block(slots: int, block: int = 0) -> int:
    """The wheel's block size: an explicit divisor wins; 0 auto-picks the
    divisor of `slots` nearest sqrt(slots) (ties prefer the larger block
    — `B ~ sqrt(S)` balances the [H, S/B] cache reduction against the
    [H, B] victim-block recompute, the same rule the bucketed queue's
    sweep settled on, tools/bench_bucketq.py)."""
    slots = int(slots)
    if slots < 1:
        raise ValueError(f"wheel slots must be >= 1, got {slots}")
    block = int(block)
    if block:
        if block < 1 or slots % block:
            raise ValueError(
                f"wheel block={block} must divide slots={slots} evenly"
            )
        return block
    target = slots ** 0.5
    divisors = [b for b in range(1, slots + 1) if slots % b == 0]
    return min(divisors, key=lambda b: (abs(b - target), -b))


def make_wheel(num_hosts: int, slots: int, block: int = 0) -> TimerWheel:
    """A fresh (empty) per-host timer wheel: [H, S] lanes + block caches."""
    return make_bucket_queue(num_hosts, slots, resolve_wheel_block(slots, block))


def wheel_next_time(w: TimerWheel) -> Array:
    """Per-host earliest pending timer (i64[H], TIME_MAX = none) from the
    [H, S/B] caches alone — the term the engine folds into its round
    min-next-event reduction (`_effective_next`)."""
    return bq_next_time(w)


def wheel_len(w: TimerWheel) -> Array:
    """Per-host live timer count (i32[H]) from the fill caches."""
    return q_len(w)


def wheel_free(w: TimerWheel) -> Array:
    """Per-host free slots (i32[H]) — the spill-routing input: a push is
    diverted to the event queue when no slot is free, so the wheel itself
    can never drop (its `dropped` lane is an invariant zero)."""
    return jnp.int32(w.t.shape[1]) - q_len(w)


def wheel_push_many(w: TimerWheel, pushes) -> TimerWheel:
    """Push routed timer events (same (mask, t, order, kind, payload)
    tuples as the queue ops). The CALLER masks overflow away via
    `wheel_free` (core/engine._route_timer_pushes) — by that contract the
    running-occupancy push can never hit a full wheel."""
    return bq_push_many(w, pushes)


def wheel_pop_min(w: TimerWheel, limit) -> tuple[TimerWheel, "object", Array]:
    """Pop each host's earliest due timer strictly before `limit` (i64
    scalar or [H]) — identical semantics to `q_pop_min`; the engine
    merges the result with the queue pop under the (time, order)
    tie-break so dispatch order is bit-identical to the wheel-off path."""
    return bq_pop_min(w, limit)


def wheel_cancel(w: TimerWheel, mask, order) -> tuple[TimerWheel, Array]:
    """Cancel (remove without firing) the pending timer whose packed
    order key equals `order[h]` for each masked host. Returns
    (wheel', found bool[H]).

    Order keys are globally unique (ops/events.pack_order), so at most
    one slot per host can match — the removal is one masked compare over
    the [H, S] key plane plus a victim-block cache recompute, the
    grouped-sorting-queue "dynamic update" with no re-sort. A miss
    (timer already fired, spilled to the queue, or never existed) leaves
    the wheel untouched and reports found=False — callers that must
    cancel spilled timers fall back to their queue-side lazy-cancel
    path."""
    mask = jnp.asarray(mask, bool)
    order = jnp.asarray(order, jnp.int64)
    h, s = w.t.shape
    nb = w.bt.shape[1]
    b = s // nb
    hit = mask[:, None] & (w.order == order[:, None]) & (w.t != TIME_MAX)
    found = jnp.any(hit, axis=1)
    new_t = jnp.where(hit, TIME_MAX, w.t)
    new_order = jnp.where(hit, ORDER_MAX, w.order)
    hit3 = hit.reshape(h, nb, b)
    touched = jnp.any(hit3, axis=2)  # [H, WB] blocks that lost their slot
    t3 = new_t.reshape(h, nb, b)
    o3 = new_order.reshape(h, nb, b)
    nbt = jnp.min(t3, axis=2)
    nbo = jnp.min(jnp.where(t3 == nbt[:, :, None], o3, ORDER_MAX), axis=2)
    return (
        w._replace(
            t=new_t,
            order=new_order,
            bt=jnp.where(touched, nbt, w.bt),
            bo=jnp.where(touched, nbo, w.bo),
            # dtype pinned: the i32 fill cache must not widen through the
            # sum (registry width, core/lanes.py)
            bfill=w.bfill - jnp.sum(hit3, axis=2, dtype=jnp.int32),
        ),
        found,
    )


def migrate_wheel(w: TimerWheel, new_slots: int, block: int = 0) -> TimerWheel:
    """Re-seat the wheel at `new_slots` slots per host — the checkpoint
    cross-shape restore path (core/checkpoint.py). Same exactness
    argument as `migrate_queue` (slot positions unobservable); the
    caller checks `migration_fits` before a shrink."""
    return migrate_queue(w, new_slots, block=resolve_wheel_block(new_slots, block))
