"""Per-host counter RNG: vectorized xoshiro256++ lanes, one per simulated host.

The reference seeds one Xoshiro256++ per host from the global seed
(src/main/host/host.rs:233) so packet-loss draws and app randomness are
deterministic per host regardless of scheduling. Same contract here: state is
uint64[H, 4]; draws advance a host's lane ONLY under an explicit mask, so the
per-host draw sequence depends only on that host's event history — never on
how hosts are grouped into shards or microsteps. That masked-advance rule is
what keeps the determinism gate (tests/test_determinism.py) true across mesh
shapes.

Seeding uses splitmix64(global_seed, host_id), the standard xoshiro seeding
recipe (capability-equivalent to the reference; not bit-equal to rand_xoshiro).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array


class RngState(NamedTuple):
    s: Array  # uint64[H, 4]


_GOLDEN = jnp.uint64(0x9E3779B97F4A7C15)


def _splitmix64(x: Array) -> tuple[Array, Array]:
    x = x + _GOLDEN
    z = x
    z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    z = z ^ (z >> jnp.uint64(31))
    return x, z


def rng_init(num_hosts: int, seed: int) -> RngState:
    x = jnp.uint64(seed) + jnp.arange(num_hosts, dtype=jnp.uint64) * jnp.uint64(
        0xD1342543DE82EF95
    )
    lanes = []
    for _ in range(4):
        x, z = _splitmix64(x)
        lanes.append(z)
    return RngState(s=jnp.stack(lanes, axis=1))


def _rotl(x: Array, k: int) -> Array:
    return (x << jnp.uint64(k)) | (x >> jnp.uint64(64 - k))


def rng_next_u64(state: RngState, mask) -> tuple[RngState, Array]:
    """Draw a uint64 per host; advance state only where `mask` ([H] bool).

    xoshiro256++ step: result = rotl(s0+s3,23)+s0; standard state transition.
    """
    s0, s1, s2, s3 = (state.s[:, i] for i in range(4))
    result = _rotl(s0 + s3, 23) + s0
    t = s1 << jnp.uint64(17)
    s2n = s2 ^ s0
    s3n = s3 ^ s1
    s1n = s1 ^ s2n
    s0n = s0 ^ s3n
    s2n = s2n ^ t
    s3n = _rotl(s3n, 45)
    new = jnp.stack([s0n, s1n, s2n, s3n], axis=1)
    mask = jnp.asarray(mask, bool)
    return RngState(s=jnp.where(mask[:, None], new, state.s)), result


def rng_uniform(state: RngState, mask) -> tuple[RngState, Array]:
    """Draw float32 in [0, 1) per host (masked advance).

    Top 24 bits → f32 mantissa; enough resolution for packet-loss draws
    (reference draws f64 against edge loss probability, worker.rs:374-390).
    """
    state, x = rng_next_u64(state, mask)
    u24 = (x >> jnp.uint64(40)).astype(jnp.uint32)
    return state, u24.astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))
