"""Device-side kernels: event-queue SoA ops, per-host RNG, sorted batch merge.

These are the TPU equivalents of the reference's per-host
`BinaryHeap<Reverse<Event>>` (src/main/core/work/event_queue.rs) and its
deterministic `Event` ordering (src/main/core/work/event.rs:102-155), recast as
fixed-shape vectorized array programs so XLA can fuse and tile them.
"""

from shadow_tpu.ops.events import (
    BucketQueue,
    EventQueue,
    EVENT_PAYLOAD_WORDS,
    PoppedK,
    as_flat,
    block_minima,
    bucket_rebuild,
    bq_next_time,
    bq_pop_min,
    bq_push_many,
    clear_popped,
    make_bucket_queue,
    make_queue,
    next_time,
    queue_len,
    pop_k,
    pop_min,
    push_many,
    push_one,
    pack_order,
    check_order_limits,
    q_clear_popped,
    q_len,
    q_next_time,
    q_pop_k,
    q_pop_min,
    q_push_many,
    ORDER_MAX,
)
from shadow_tpu.ops.merge import merge_flat_events
from shadow_tpu.ops.rng import RngState, rng_init, rng_next_u64, rng_uniform

__all__ = [
    "BucketQueue",
    "EventQueue",
    "EVENT_PAYLOAD_WORDS",
    "PoppedK",
    "as_flat",
    "block_minima",
    "bucket_rebuild",
    "bq_next_time",
    "bq_pop_min",
    "bq_push_many",
    "clear_popped",
    "make_bucket_queue",
    "make_queue",
    "next_time",
    "queue_len",
    "pop_k",
    "pop_min",
    "push_many",
    "push_one",
    "pack_order",
    "check_order_limits",
    "q_clear_popped",
    "q_len",
    "q_next_time",
    "q_pop_k",
    "q_pop_min",
    "q_push_many",
    "ORDER_MAX",
    "merge_flat_events",
    "RngState",
    "rng_init",
    "rng_next_u64",
    "rng_uniform",
]
