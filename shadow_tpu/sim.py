"""Simulation driver: config -> graph -> engine -> run loop -> outputs.

This is the analogue of the reference's L5/L6 stack (SURVEY.md §1):
  - `SimConfig::new` (sim_config.rs:47): expand config into per-host specs,
    load the graph, assign IPs, compute routing.
  - `Controller::run` / `Manager::run` (controller.rs:40, manager.rs:219):
    build hosts, seed boot events, drive the round loop, merge stats, write
    `processed-config.yaml` (manager.rs:182-193) and `sim-stats.json`
    (manager.rs:544-546).
  - heartbeat logging (manager.rs:675-717) and the status-bar progress line
    (controller.rs:115-168, utility/status_bar.rs).

The scheduling loop itself is on-device (`core.engine`); this module only
decides how many jitted chunks to run and when to print. The reference's
equivalent of `chunks` is the Manager's `while window` loop — here each chunk
is `rounds_per_chunk` whole scheduling rounds fused into one device program,
which is the batching that amortizes dispatch latency (SURVEY.md §7 hard
part 5).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import yaml

from shadow_tpu.config.options import ConfigError, ConfigOptions
from shadow_tpu.core.engine import Engine, EngineConfig, EngineParams
from shadow_tpu.models.base import get_model
from shadow_tpu.net import TBParams
from shadow_tpu.net.graph import IpAssignment, NetworkGraph, load_graph
from shadow_tpu.simtime import NS_PER_SEC, TIME_MAX

MTU_BITS = 1500 * 8
UNLIMITED_BW = 1 << 40  # token-bucket params for "no bandwidth configured"


@dataclasses.dataclass
class HostSpec:
    """One simulated host after config expansion (reference HostInfo,
    sim_config.rs:168-192)."""

    host_id: int
    name: str
    node_index: int  # index into graph tables (NOT the GML id)
    ip: str
    bw_down_bits: int  # 0 = unlimited
    bw_up_bits: int
    model: str
    model_args: dict[str, Any]
    start_time: int
    shutdown_time: int | None
    pcap_enabled: bool
    pcap_capture_size: int
    # per-host TCP defaults for the CPU plane (reference HostDefaultOptions
    # socket buffer / autotune knobs); None on pure-device hosts
    tcp_cfg: Any = None
    # managed programs (hybrid/co-sim hosts): [{path, args, start_time, ...}]
    programs: list = dataclasses.field(default_factory=list)


class _ModeledPcap:
    """Per-round pcap synthesis for device-modeled sims: each captured
    round's pre-exchange outbox is pulled to the host and written as
    synthesized UDP frames (src host = lane row, arrival timestamp; ports
    are a deterministic synthesis — model events carry no transport
    header). Reference: pcap_writer.rs + network_interface.c capture."""

    def __init__(self, sim: "Simulation"):
        from shadow_tpu.host.sockets import NetPacket
        from shadow_tpu.obs.pcap import PcapWriter

        self._NetPacket = NetPacket
        self.step = sim.engine.build_capture_step()
        self._ips = [h.ip for h in sim.hosts]
        data_dir = sim.cfg.general.data_directory or "shadow_tpu.data"
        self.writers = {}
        for h in sim.hosts:
            if not h.pcap_enabled:
                continue
            host_dir = os.path.join(data_dir, "hosts", h.name)
            os.makedirs(host_dir, exist_ok=True)
            self.writers[h.host_id] = PcapWriter(
                os.path.join(host_dir, "eth0.pcap"), h.pcap_capture_size
            )

    def write_round(self, outbox):
        t = np.asarray(jax.device_get(outbox.t))
        if not (t < TIME_MAX).any():
            return
        dst = np.asarray(jax.device_get(outbox.dst))
        payload = np.asarray(jax.device_get(outbox.payload))
        n_hosts = len(self._ips)
        for src, col in zip(*np.nonzero(t < TIME_MAX)):
            d = int(dst[src, col])
            if not (0 <= d < n_hosts):
                continue
            size = int(payload[src, col, 0])  # PAYLOAD_SIZE_WORD
            pkt = self._NetPacket(
                src_ip=self._ips[int(src)],
                src_port=40000,
                dst_ip=self._ips[d],
                dst_port=40000,
                proto=17,  # synthesized as UDP
                payload=b"\x00" * max(0, min(size, 65000)),
            )
            ts = int(t[src, col])
            w = self.writers.get(int(src))
            if w is not None:
                w.write(ts, pkt)  # egress (timestamped at arrival: the
                # outbox stores only the delivery time)
            w = self.writers.get(d)
            if w is not None:
                w.write(ts, pkt)  # ingress at the destination

    def close(self):
        for w in self.writers.values():
            w.close()


def _resolve_host_basics(cfg: ConfigOptions, graph: NetworkGraph):
    """Shared per-host resolution for both expanders: stable name order,
    manual-IPs-first assignment (graph/mod.rs:370), graph node lookup, and
    graph-bandwidth fallback. Yields (host_id, host_options, node, ip,
    bw_down, bw_up)."""
    ips = IpAssignment()
    ordered = sorted(cfg.hosts, key=lambda h: h.name)
    # the reference requires a self-loop on every graph node
    # (graph/mod.rs:210-216); enforce it where it matters — a node carrying
    # >= 2 hosts with an unreachable diagonal can never route same-node
    # traffic, which is a config error, not per-packet drops
    hosts_per_node: dict[int, int] = {}
    for h in ordered:
        n = graph.node_index(h.network_node_id)
        hosts_per_node[n] = hosts_per_node.get(n, 0) + h.count
    for n, cnt in sorted(hosts_per_node.items()):
        if cnt >= 2 and graph.lat_ns[n, n] < 0:
            raise ConfigError(
                f"graph node {int(graph.node_ids[n])} hosts {cnt} hosts but "
                f"has no self-loop edge: same-node traffic cannot route "
                f"(the reference requires a self-loop per node)"
            )
    for i, h in enumerate(ordered):
        if h.ip_addr is not None:
            ips.assign_manual(i, h.ip_addr)
    for i, h in enumerate(ordered):
        if not h.processes:
            raise ConfigError(f"host {h.name!r} has no processes")
        node = graph.node_index(h.network_node_id)
        if h.ip_addr is None:
            ips.assign(i)
        bw_down = h.bandwidth_down if h.bandwidth_down is not None else int(
            graph.bw_down_bits[node]
        )
        bw_up = h.bandwidth_up if h.bandwidth_up is not None else int(
            graph.bw_up_bits[node]
        )
        yield i, h, node, ips.ip_of(i), bw_down, bw_up


def expand_hosts(cfg: ConfigOptions, graph: NetworkGraph) -> list[HostSpec]:
    """Config hosts -> HostSpecs with IPs and node indices resolved.

    Hosts are sorted by name for a config-order-independent host-id mapping
    (the reference shuffles hosts for scheduler balance, manager.rs:272 —
    sharding here is by contiguous id range, so a stable order is what keeps
    runs reproducible across config reorderings)."""
    specs: list[HostSpec] = []
    for i, h, node, ip, bw_down, bw_up in _resolve_host_basics(cfg, graph):
        dev_models = [p for p in h.processes if p.model is not None]
        if len(dev_models) != 1:
            raise ConfigError(
                f"host {h.name!r}: exactly one device-model process per host "
                f"is supported (got {len(dev_models)})"
            )
        p = dev_models[0]
        specs.append(
            HostSpec(
                host_id=i,
                name=h.name,
                node_index=node,
                ip=ip,
                bw_down_bits=bw_down,
                bw_up_bits=bw_up,
                model=p.model,
                model_args=dict(p.model_args),
                start_time=p.start_time,
                shutdown_time=p.shutdown_time,
                pcap_enabled=h.host_options.pcap_enabled,
                pcap_capture_size=h.host_options.pcap_capture_size,
            )
        )
    return specs


def config_is_hybrid(cfg: ConfigOptions) -> bool:
    """True if any host runs managed programs (`path:`) instead of models."""
    return any(p.path is not None for h in cfg.hosts for p in h.processes)


def expand_hosts_hybrid(cfg: ConfigOptions, graph: NetworkGraph) -> list[HostSpec]:
    """Config -> specs for co-simulation. Program hosts (`path:`) run on
    CpuHosts behind the hybrid device proxy; model hosts (`model:`) run
    fully on device — a MIXED simulation shares one device network between
    both planes (models/mixed.py)."""
    from shadow_tpu.programs import PROGRAM_REGISTRY

    specs: list[HostSpec] = []
    for i, h, node, ip, bw_down, bw_up in _resolve_host_basics(cfg, graph):
        model_procs = [p for p in h.processes if p.model is not None]
        if model_procs:
            if len(h.processes) != 1:
                raise ConfigError(
                    f"host {h.name!r}: a modeled host runs exactly one "
                    f"model process (got {len(h.processes)} processes)"
                )
            p = model_procs[0]
            # loud rejection instead of silent intent-dropping: the mixed
            # plane does not (yet) honor these on modeled lanes
            if p.shutdown_time is not None:
                raise ConfigError(
                    f"host {h.name!r}: shutdown_time on a modeled host in a "
                    f"mixed simulation is not supported"
                )
            if h.host_options.pcap_enabled:
                raise ConfigError(
                    f"host {h.name!r}: pcap on a modeled host in a mixed "
                    f"simulation is not supported (model packets carry no "
                    f"bytes; enable pcap on the program hosts instead)"
                )
            specs.append(
                HostSpec(
                    host_id=i, name=h.name, node_index=node, ip=ip,
                    bw_down_bits=bw_down, bw_up_bits=bw_up,
                    model=p.model, model_args=dict(p.model_args),
                    start_time=p.start_time, shutdown_time=None,
                    pcap_enabled=False,
                    pcap_capture_size=h.host_options.pcap_capture_size,
                    programs=[],
                )
            )
            continue
        for p in h.processes:
            if "/" in p.path:
                # real binary for the native managed-process plane
                if not os.path.exists(p.path):
                    raise ConfigError(
                        f"host {h.name!r}: binary {p.path!r} not found"
                    )
            elif p.path not in PROGRAM_REGISTRY:
                raise ConfigError(
                    f"host {h.name!r}: unknown program {p.path!r}; "
                    f"available: {sorted(PROGRAM_REGISTRY)} "
                    f"(use a path containing '/' for a real binary)"
                )
        specs.append(
            HostSpec(
                host_id=i,
                name=h.name,
                node_index=node,
                ip=ip,
                bw_down_bits=bw_down,
                bw_up_bits=bw_up,
                model="hybrid",
                model_args={},
                start_time=0,
                shutdown_time=None,
                pcap_enabled=h.host_options.pcap_enabled,
                pcap_capture_size=h.host_options.pcap_capture_size,
                tcp_cfg=h.host_options.tcp_config(),
                programs=[
                    {
                        "path": p.path,
                        "args": _program_args(p),
                        "argv_raw": list(p.args),  # verbatim argv (native bins)
                        "environment": dict(p.environment),
                        "start_time": p.start_time,
                        "shutdown_time": p.shutdown_time,
                        "expected_final_state": p.expected_final_state,
                    }
                    for p in h.processes
                ],
            )
        )
    return specs


def _program_args(p) -> dict:
    """Program args: `args: ["key=value", ...]` entries become a dict; the
    reference passes argv strings the same way (ProcessOptions.args)."""
    out: dict[str, Any] = {}
    for a in p.args:
        if "=" in a:
            k, v = a.split("=", 1)
            out[k] = v
        else:
            out.setdefault("_argv", []).append(a)
    out.update({f"env_{k}": v for k, v in p.environment.items()})
    return out


def build_simulation(cfg: ConfigOptions, **kw):
    """Factory: modeled sims -> `Simulation` (device-only, mesh-scalable);
    program sims -> `HybridSimulation` (CPU plane + device network)."""
    if config_is_hybrid(cfg):
        from shadow_tpu.cosim import HybridSimulation

        return HybridSimulation(cfg, **kw)
    return Simulation(cfg, **kw)


def _tb_params(bws: np.ndarray, interval_ns: int) -> TBParams:
    """Bandwidth -> token bucket: refill = bits per interval, burst capacity =
    refill + one MTU (reference relay/token_bucket.rs: 1ms refill quantum with
    an MTU burst allowance, relay/mod.rs:276-319)."""
    unlimited = bws <= 0
    per_itv = np.maximum(bws * interval_ns // NS_PER_SEC, 1)
    refill = np.where(unlimited, UNLIMITED_BW, per_itv).astype(np.int64)
    cap = np.where(unlimited, UNLIMITED_BW, per_itv + MTU_BITS).astype(np.int64)
    return TBParams(capacity=jnp.asarray(cap), refill=jnp.asarray(refill))


def resolve_world(parallelism: int) -> int:
    """0 = all local devices (reference: 0 = all cores, configuration.rs)."""
    avail = jax.device_count()
    if parallelism <= 0:
        return avail
    if parallelism > avail:
        raise ConfigError(
            f"general.parallelism={parallelism} exceeds {avail} available devices"
        )
    return parallelism


def heartbeat_line(
    now_ns: int,
    wall: float,
    events: int,
    microsteps: int,
    rounds: int,
    ici_bytes: int,
    q_hwm: int,
    *,
    xw: tuple[int, int] | None = None,
    fault: tuple[int, int] | None = None,
    gear: int | None = None,
    cap: int | None = None,
    hbm: int | None = None,
    ek: tuple[int, int] | None = None,
    fct: int | None = None,
    bg: tuple[int, int] | None = None,
    iv: tuple[int, int] | None = None,
    rt: float | None = None,
    rep: tuple[int, int] | None = None,
) -> str:
    """The `[heartbeat]` progress line, shared by the Simulation run loop
    and the campaign driver so tools/parse_shadow.py has ONE format to
    track. Optional fields ride along in a fixed order (faults, gear,
    cap, hbm, rep, then ratio); lines without them are byte-identical to
    the older formats, which the parser keeps reading (gated by
    literal-line tests). `cap` is the ACTIVE per-host queue capacity on
    pressure-plane runs (escalation regrows it mid-run); `hbm` is the
    per-shard HBM high-water in bytes (memory observatory runs —
    obs/memory.py, the reference's per-host allocated-memory heartbeat);
    `rep` is (replicas done, total) on ensemble campaign runs; `ek` is
    (timer events, packet events) and `fct` the flows completed so far —
    both only on network-observatory runs (obs/netobs.py); `bg` is
    (background bytes delivered, background bytes dropped) — only on
    fluid-traffic-plane runs (net/fluid.py); `iv` is
    (transient SDC survived, sentinel replays) — only on
    integrity-sentinel runs (core/integrity.py); `rt` is the LAST
    chunk's realtime factor (sim-s/wall-s) — only on runtime-observatory
    runs (obs/runtime.py; unlike `ratio=`, which is the run-cumulative
    average, `rt=` is the fresh per-chunk number the serving posture
    tracks); `xw` is (intra-shard compaction bytes, inter-shard wire
    bytes), cumulative — only on hierarchical-exchange runs
    (core/engine.py _exchange_hierarchical; it rides right after q_hwm=,
    before faults=, matching HEARTBEAT_RE's position anchor)."""
    xw_f = f"xw={xw[0]}/{xw[1]} " if xw is not None else ""
    fault_f = f"faults={fault[0]}/{fault[1]} " if fault is not None else ""
    gear_f = f"gear={gear} " if gear is not None else ""
    cap_f = f"cap={cap} " if cap is not None else ""
    hbm_f = f"hbm={hbm} " if hbm is not None else ""
    ek_f = f"ek={ek[0]}/{ek[1]} " if ek is not None else ""
    fct_f = f"fct={fct} " if fct is not None else ""
    bg_f = f"bg={bg[0]}/{bg[1]} " if bg is not None else ""
    iv_f = f"iv={iv[0]}/{iv[1]} " if iv is not None else ""
    rt_f = f"rt={rt:.2f} " if rt is not None else ""
    rep_f = f"rep={rep[0]}/{rep[1]} " if rep is not None else ""
    return (
        f"[heartbeat] sim_time={now_ns / NS_PER_SEC:.3f}s "
        f"wall={wall:.2f}s events={events} "
        f"rounds={rounds} "
        f"msteps/round={microsteps / max(rounds, 1):.1f} "
        f"ev/mstep={events / max(microsteps, 1):.2f} "
        f"ici_bytes={ici_bytes} q_hwm={q_hwm} "
        f"{xw_f}"
        f"{fault_f}"
        f"{gear_f}"
        f"{cap_f}"
        f"{hbm_f}"
        f"{ek_f}"
        f"{fct_f}"
        f"{bg_f}"
        f"{iv_f}"
        f"{rt_f}"
        f"{rep_f}"
        f"ratio={now_ns / NS_PER_SEC / max(wall, 1e-9):.2f}x "
        f"{resource_heartbeat()}"
    )


class Simulation:
    """Built simulation: engine + host specs + run loop."""

    def __init__(self, cfg: ConfigOptions, *, world: int | None = None):
        self.cfg = cfg
        self.graph = load_graph(cfg.network.graph)
        self.hosts = expand_hosts(cfg, self.graph)
        if not self.hosts:
            raise ConfigError("config defines no hosts")
        models = {h.model for h in self.hosts}
        if len(models) != 1:
            raise ConfigError(
                f"all hosts must run one device model per simulation for "
                f"vectorized dispatch; got {sorted(models)}"
            )
        self.model = get_model(models.pop())()

        ex = cfg.experimental
        world = resolve_world(cfg.general.parallelism) if world is None else world
        # pad the host count to a multiple of the mesh size with inert hosts
        # (empty queues never activate; the digest ignores them)
        self._num_real = len(self.hosts)
        num_hosts = -(-self._num_real // world) * world
        qcap, send_budget, rpc = ex.resolve_shapes(num_hosts)
        # fault plane (core/faults.py): compile the seeded schedule into
        # device arrays + the static dims the round body specializes on.
        # Churn draws run over the real-host prefix only, so the schedule
        # is invariant to mesh padding.
        from shadow_tpu.core.faults import FaultSchedule, compile_faults

        try:
            self._fault_sched = (
                compile_faults(
                    cfg.faults,
                    num_hosts=num_hosts,
                    num_real=self._num_real,
                    stop_time=cfg.general.stop_time,
                    bootstrap_end=cfg.general.bootstrap_end_time,
                    default_seed=cfg.general.seed,
                    name_to_id={h.name: h.host_id for h in self.hosts},
                )
                if cfg.faults.injecting
                else FaultSchedule(0, 0, False, None)
            )
        except ValueError as e:
            raise ConfigError(f"faults: {e}") from e
        if self._fault_sched.active and ex.scheduler == "cpu-reference":
            raise ConfigError(
                "faults: the cpu-reference scheduler does not model the "
                "fault plane; run the tpu scheduler or drop the faults block"
            )
        # fluid traffic plane (net/fluid.py): compile the background
        # classes onto the graph's node space. Zone ids are GML node ids,
        # resolved through the same graph.node_index the hosts use.
        from shadow_tpu.net.fluid import FluidSchedule, compile_fluid

        try:
            self._fluid_sched = (
                compile_fluid(
                    cfg.fluid,
                    num_links=int(self.graph.lat_ns.shape[0]),
                    default_seed=cfg.general.seed,
                    zone_of=self.graph.node_index,
                )
                if cfg.fluid.active
                # inactive: every knob pinned to the EngineConfig
                # DEFAULTS (not general.seed etc.) — a fluid-off config
                # must produce the identical EngineConfig regardless of
                # seed, or ensemble replicas differing only in seed
                # would fail static reconciliation
                else FluidSchedule(0, 0, 50_000_000, 0.7, 0.0, 2000, 1,
                                   None)
            )
        except (ValueError, KeyError) as e:
            raise ConfigError(f"fluid: {e}") from e
        if self._fluid_sched.active and ex.scheduler == "cpu-reference":
            raise ConfigError(
                "fluid: the cpu-reference scheduler does not model the "
                "fluid traffic plane; run the tpu scheduler or drop the "
                "fluid block"
            )
        # pressure plane (core/pressure.py): validated here so every
        # unsupported combination fails at build, not mid-run
        press = cfg.pressure
        if press.active:
            if ex.scheduler == "cpu-reference":
                raise ConfigError(
                    "pressure: the cpu-reference scheduler does not model "
                    "the pressure plane; run the tpu scheduler or keep "
                    "policy: drop"
                )
            if any(h.pcap_enabled for h in self.hosts):
                raise ConfigError(
                    "pressure: escalate/abort are not supported with pcap "
                    "capture (the single-round capture loop has no "
                    "snapshot-replay seam); disable pcap or keep "
                    "policy: drop"
                )
        # integrity sentinel (core/integrity.py): validated at build so
        # unsupported combinations fail loudly, not mid-run
        if cfg.integrity.enabled:
            if ex.scheduler == "cpu-reference":
                raise ConfigError(
                    "integrity: the cpu-reference scheduler does not "
                    "model the sentinel's in-jit guards; run the tpu "
                    "scheduler or disable the integrity block"
                )
            if any(h.pcap_enabled for h in self.hosts):
                raise ConfigError(
                    "integrity: the sentinel is not supported with pcap "
                    "capture (the single-round capture loop has no "
                    "snapshot-replay seam for quarantine-and-replay); "
                    "disable pcap or the integrity block"
                )
        if press.policy == "escalate":
            if ex.merge_rows > 0:
                raise ConfigError(
                    "pressure: escalate cannot cure a merge_rows bound "
                    "(its shed is positional, not capacity-sized) — drop "
                    "merge_rows or keep policy: drop/abort"
                )
            if ex.a2a_block > 0:
                raise ConfigError(
                    "pressure: escalate cannot cure an explicit "
                    "a2a_block's sheds (the resized programs scale only "
                    "the AUTO block with the send budget) — drop "
                    "a2a_block (auto-sizing follows escalation) or keep "
                    "policy: drop/abort"
                )
            if press.max_capacity and press.max_capacity < qcap:
                raise ConfigError(
                    f"pressure.max_capacity={press.max_capacity} is below "
                    f"the configured queue capacity {qcap}"
                )
            if press.max_outbox and press.max_outbox < send_budget:
                raise ConfigError(
                    f"pressure.max_outbox={press.max_outbox} is below the "
                    f"configured send budget {send_budget}"
                )
        # timer wheel (ops/wheel.py): validated here so a model with no
        # timer_kinds fails at config parse, not engine build
        if ex.timer_wheel and not tuple(
            getattr(self.model, "timer_kinds", ())
        ):
            raise ConfigError(
                f"experimental.timer_wheel: model {self.model.name!r} "
                f"declares no timer_kinds — nothing would route to the "
                f"wheel; drop the knob or use a model with timers"
            )
        self.engine_cfg = EngineConfig(
            num_hosts=num_hosts,
            stop_time=cfg.general.stop_time,
            bootstrap_end_time=cfg.general.bootstrap_end_time,
            runahead_floor=ex.runahead,
            static_min_latency=max(self.graph.min_latency_ns_opt or 0, 1),
            use_jitter=self.graph.has_jitter,
            use_dynamic_runahead=ex.use_dynamic_runahead,
            use_codel=ex.use_codel,
            queue_capacity=qcap,
            queue_block=ex.event_queue_block,
            sends_per_host_round=send_budget,
            max_round_inserts=ex.max_round_inserts or qcap,
            rounds_per_chunk=rpc,
            microstep_limit=ex.microstep_limit,
            microstep_events=ex.microstep_events,
            world=world,
            # exact elision: with no bandwidth limits anywhere, token buckets
            # and CoDel are provable no-ops (see EngineConfig.shaping)
            shaping=any(
                h.bw_up_bits > 0 or h.bw_down_bits > 0 for h in self.hosts
            ),
            cheap_shed=ex.overflow_shed == "append",
            cpu_delay_ns=ex.cpu_delay,
            exchange=ex.resolve_exchange(world),
            a2a_block=ex.a2a_block,
            merge_rows=ex.merge_rows,
            # round tracer ring sized to the chunk length: the run loop
            # drains at every chunk boundary, so the ring can never wrap
            trace_rounds=rpc if cfg.observability.trace else 0,
            # network observatory (obs/netobs.py): event classes + safe
            # window ride the knob; the flow ledger only for models that
            # declare a flow port (tgen) — other models carry no ring
            netobs=cfg.observability.network,
            flow_records=(
                cfg.observability.network_flows
                if cfg.observability.network
                and getattr(self.model, "flow_ledger", False)
                else 0
            ),
            fault_crash_windows=self._fault_sched.crash_windows,
            fault_loss_windows=self._fault_sched.loss_windows,
            fault_queue_clear=self._fault_sched.queue_clear,
            # pressure plane: escalate/abort trace the first-drop abort
            # condition into the chunk loop; drop (default) leaves the
            # program bit-identical to the pre-pressure engine
            pressure_abort=press.active,
            # integrity sentinel: per-round invariant guards + the
            # first-violation abort condition; OFF traces zero sentinel
            # code (the default program stays byte-identical)
            integrity=cfg.integrity.enabled,
            integrity_dual=cfg.integrity.enabled and cfg.integrity.dual_digest,
            # timer wheel + sort-free calendar merge (ops/wheel.py,
            # ops/merge.py): both off by default — the default program
            # stays byte-identical (jaxpr fingerprints are the gate)
            wheel_slots=ex.timer_wheel,
            wheel_block=ex.timer_wheel_block,
            merge_scatter=ex.merge_scatter,
            # fluid traffic plane (net/fluid.py): zero classes (the
            # default) traces no fluid code — the program stays
            # byte-identical to the fluid-free engine
            fluid_classes=self._fluid_sched.classes,
            fluid_links=self._fluid_sched.links,
            fluid_tau_ns=self._fluid_sched.tau_ns,
            fluid_util_threshold=self._fluid_sched.util_threshold,
            fluid_loss_max=self._fluid_sched.loss_max,
            fluid_lat_max_x1000=self._fluid_sched.lat_max_x1000,
            fluid_seed=self._fluid_sched.seed,
        )
        # occupancy-adaptive merge gears (core/gears.py): resolved against
        # the (possibly auto-sized) send budget; [] = disabled
        from shadow_tpu.core.gears import resolve_gear_ladder

        try:
            self._gear_ladder = resolve_gear_ladder(ex.merge_gears, send_budget)
        except ValueError as e:
            raise ConfigError(f"experimental.merge_gears: {e}") from e
        self._gearctl = None  # built per run()
        self._pressctl = None  # ResilienceController when pressure is active
        self._ob_hwm_run = 0  # run-wide outbox high-water (gear runs reset
        # the device counter per chunk, so the run max is tracked here)
        mesh = None
        if world > 1:
            mesh = jax.sharding.Mesh(np.array(jax.devices()[:world]), ("hosts",))
        self.engine = Engine(self.engine_cfg, self.model, mesh)
        # runtime observatory (obs/runtime.py): the compile ledger hooks
        # the engine's program caches BEFORE the first dispatch so the
        # base program's cold compile is recorded. Host-side only.
        self._rt_compiles = None
        if cfg.observability.runtime:
            from shadow_tpu.obs.runtime import CompileLedger

            self._rt_compiles = CompileLedger()
            self.engine.attach_compile_ledger(self._rt_compiles)
        self._build_state()

    # ---- build ------------------------------------------------------------

    def _model_hosts(self) -> list[dict]:
        return [
            {
                "host_id": h.host_id,
                "name": h.name,
                "start_time": h.start_time,
                "shutdown_time": h.shutdown_time,
                "ip": h.ip,
                "model_args": h.model_args,
            }
            for h in self.hosts
        ]

    def _pad(self, tree):
        """Pad model [H_real, ...] arrays to the engine's H_total."""
        pad = self.engine_cfg.num_hosts - self._num_real

        def f(a):
            a = np.asarray(a)
            if pad == 0:
                return jnp.asarray(a)
            width = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
            return jnp.asarray(np.pad(a, width))

        return jax.tree.map(f, tree)

    def _build_state(self):
        from shadow_tpu.core.engine import host_build_context

        cfg, ecfg = self.cfg, self.engine_cfg
        try:
            mparams, mstate, events = self.model.build(
                self._model_hosts(), cfg.general.seed
            )
        except (ValueError, KeyError) as e:
            raise ConfigError(f"model {self.model.name!r}: {e}") from e
        node_of = np.zeros((ecfg.num_hosts,), np.int32)
        bw_up = np.zeros((ecfg.num_hosts,), np.int64)
        bw_down = np.zeros((ecfg.num_hosts,), np.int64)
        for h in self.hosts:
            node_of[h.host_id] = h.node_index
            bw_up[h.host_id] = h.bw_up_bits
            bw_down[h.host_id] = h.bw_down_bits
        with host_build_context():
            params = EngineParams(
                node_of=jnp.asarray(node_of),
                lat_ns=jnp.asarray(self.graph.lat_ns),
                loss=jnp.asarray(self.graph.loss),
                jitter_ns=jnp.asarray(self.graph.jitter_ns),
                eg_tb=_tb_params(bw_up, ecfg.tb_interval_ns),
                in_tb=_tb_params(bw_down, ecfg.tb_interval_ns),
                model=self._pad(mparams),
                faults=self._fault_sched.params,
                fluid=self._fluid_sched.params,
            )
            padded_state = self._pad(mstate)
        # kept for the cpu-reference scheduler path (golden engine inputs)
        self._golden_inputs = (params, padded_state, events)
        self.state, self.params = self.engine.init_state(
            params, padded_state, events, seed=cfg.general.seed
        )

    # ---- run --------------------------------------------------------------

    def run(self, *, progress: bool | None = None, log=sys.stderr) -> dict:
        """Drive chunks until done. Returns the final stats report dict."""
        cfg = self.cfg
        if cfg.experimental.scheduler == "cpu-reference":
            return self._run_golden()
        show_progress = cfg.general.progress if progress is None else progress
        hb_ns = cfg.general.heartbeat_interval
        t0 = time.monotonic()
        next_hb = hb_ns
        capture = self._pcap_capture_begin()
        simlog = None
        if cfg.general.log_file:
            from shadow_tpu.obs import SimLogger

            path = cfg.general.log_file
            if not os.path.isabs(path):
                path = os.path.join(cfg.general.data_directory, path)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            simlog = SimLogger(path, level=cfg.general.log_level)
        tracer = None
        if self.engine_cfg.trace_rounds:
            from shadow_tpu.obs import RoundTracer

            tracer = RoundTracer(self.engine_cfg.trace_rounds)
            # a restored checkpoint (or a prior run()) leaves rows in the
            # ring; start draining from the current cursor, not zero
            tracer.sync_cursor(self.state.trace)
            self._tracer = tracer
        flowcol = None
        if self.engine_cfg.flow_ledger_active:
            # network observatory's flow ledger (obs/netobs.py): drained
            # at the same chunk boundaries as the trace ring, with the
            # same checkpoint-resume cursor adoption (pre-snapshot
            # records are never replayed as fresh completions)
            from shadow_tpu.obs.netobs import FlowCollector

            flowcol = FlowCollector(self.engine_cfg.flow_records)
            flowcol.sync_cursor(self.state.flows)
            self._flowcol = flowcol

        def _drain_flows():
            if flowcol is not None:
                n = flowcol.drain(self.state.flows)
                if n and tracer is not None:
                    # this drain's records feed the Perfetto flow track
                    tracer.note_flows(flowcol.last_drained)
        profiling = bool(cfg.observability.profile_dir)
        if profiling:
            os.makedirs(cfg.observability.profile_dir, exist_ok=True)
            jax.profiler.start_trace(cfg.observability.profile_dir)
        monitor = None
        if cfg.observability.memory:
            # HBM observatory (obs/memory.py): per-shard live sampling at
            # chunk boundaries. Host-side observer only — the traced
            # programs are byte-identical with this on or off.
            from shadow_tpu.obs.memory import MemoryMonitor, modeled_shard_bytes

            devs = (
                list(self.engine.mesh.devices.flat)
                if self.engine.mesh is not None
                else [jax.devices()[0]]
            )
            monitor = MemoryMonitor(devs)
            self._memmon = monitor
            # the modeled fallback, recomputed per sample: escalation
            # regrows the state's shapes mid-run
            self._modeled_shard_bytes = lambda: modeled_shard_bytes(
                self.state, self.params, self.engine_cfg.world
            )
        # runtime observatory (obs/runtime.py): the wall-clock
        # attribution plane. Per-chunk spans (dispatch / export /
        # snapshot / replay / compile, residual = host_python) plus a
        # per-chunk realtime-factor series feeding the `rt=` heartbeat
        # field. Host-side observer only.
        from shadow_tpu.obs.runtime import span_or_null

        wallled = None
        if cfg.observability.runtime:
            from shadow_tpu.obs.runtime import WallLedger

            wallled = WallLedger()
            wallled.sync_sim(int(self.state.now))
            self._wallled = wallled
            if self._rt_compiles is not None:
                # compiles recorded mid-chunk reattribute their seconds
                # out of the enclosing dispatch span
                self._rt_compiles.wall = wallled
        gearctl = None
        resilience = None
        pressure_on = cfg.pressure.active
        integrity_on = cfg.integrity.enabled
        if (self._gear_ladder or pressure_on or integrity_on) and (
            capture is None
        ):
            # the shared snapshot-replay seam (core/pressure.py): adaptive
            # merge gears dispatch at the width the controller picked from
            # last chunk's outbox-send high-water — a shed (exact, in-jit)
            # discards the chunk and replays it one gear up — and the
            # pressure plane's escalate policy regrows queue/outbox shapes
            # and replays at the first capacity drop, so accepted chunks
            # are bit-identical to full width at the final shape. The
            # capture path stays full-width/fixed-shape: its single-round
            # dispatches re-sync every round anyway (pressure policies are
            # rejected with capture at build time).
            from shadow_tpu.core.gears import GearController
            from shadow_tpu.core.pressure import ResilienceController

            gearctl = (
                GearController(self._gear_ladder) if self._gear_ladder
                else None
            )
            self._gearctl = gearctl
            reshard = None
            if self.engine.mesh is not None:
                specs = jax.tree.map(
                    lambda s: jax.sharding.NamedSharding(self.engine.mesh, s),
                    self.engine.state_specs(),
                )
                reshard = lambda st: jax.device_put(st, specs)  # noqa: E731
            memguard = None
            if pressure_on and monitor is not None:
                # memory-informed escalation: predicted-vs-measured rung
                # admission BEFORE dispatch (obs/memory.py MemoryGuard;
                # inert until a sample measures an allocator limit)
                from shadow_tpu.obs.memory import MemoryGuard

                memguard = MemoryGuard(
                    self.engine_cfg, monitor,
                    safety_factor=cfg.pressure.memory_safety_factor,
                )
            resilience = ResilienceController(
                gearctl=gearctl,
                pressure=cfg.pressure if pressure_on else None,
                integrity=cfg.integrity if integrity_on else None,
                queue_block=self.engine_cfg.queue_block,
                reshard=reshard,
                log=log,
                memory=memguard,
                wall=wallled,
            )
            self._pressctl = resilience if pressure_on else None
            self._resil = resilience
            # test-only SDC-injection seam (tests/test_integrity.py):
            # a hook set on the Simulation before run() rides into the
            # controller's post-snapshot/pre-dispatch slot
            resilience.test_scribble = getattr(
                self, "_integrity_test_scribble", None
            )
        sup = None
        if cfg.faults.supervisor.enabled and capture is None:
            # crash-resilient supervisor (core/supervisor.py): periodic
            # device snapshots + retry-with-backoff on dispatch failure +
            # graceful abort that still exports the completed prefix. The
            # capture path keeps its single-round dispatches unsupervised
            # (pcap writes are host-side effects a replay would duplicate).
            from shadow_tpu.core.checkpoint import save_checkpoint
            from shadow_tpu.core.supervisor import ChunkSupervisor

            so = cfg.faults.supervisor
            ckpt = so.checkpoint_file
            if ckpt is not None:
                if not os.path.isabs(ckpt):
                    ckpt = os.path.join(cfg.general.data_directory, ckpt)
                os.makedirs(os.path.dirname(ckpt) or ".", exist_ok=True)

            def _save(path, snap_state):
                # save_checkpoint dumps sim.state: point it at the
                # supervisor's snapshot for the write, then restore the
                # binding (the old reference may hold donated buffers)
                prev = self.state
                self.state = snap_state
                try:
                    return save_checkpoint(path, self)
                finally:
                    self.state = prev

            sup = ChunkSupervisor(
                snapshot_every_chunks=so.snapshot_every_chunks,
                max_retries=so.max_retries,
                backoff_base_s=so.backoff_base_ms / 1000.0,
                checkpoint_path=ckpt,
                save_fn=_save if ckpt else None,
                log=log,
                memory=monitor,
                memory_modeled_fn=(
                    self._modeled_shard_bytes if monitor is not None
                    else None
                ),
                wall=wallled,
            )
            self._supervisor = sup
            sup.note_state(self.state)
        last_gear = None
        chunks = 0

        def _chunk_step(st):
            nonlocal last_gear
            if resilience is not None:
                st, lg, hwm = resilience.run_chunk(
                    st,
                    lambda s, g, c, b: self.engine.run_chunk_resized(
                        s, self.params, g, c, b
                    ),
                )
                last_gear = lg if gearctl is not None else None
                self._ob_hwm_run = max(self._ob_hwm_run, hwm)
                return st
            return self.engine.run_chunk(st, self.params)

        def _policy_abort(e, t_chunk, kind="pressure"):
            # a policy stopped the run. Pressure-abort exports the
            # dropping state itself (the honest record — the drop is in
            # the counters); escalate-cornered and integrity-abort
            # export the last good pre-chunk snapshot (an integrity
            # violation's state is by definition corrupt — exporting it
            # would be the poison this plane exists to catch; the
            # report names the violated invariant instead). Either way
            # the artifacts cover exactly what the exported state saw.
            print(f"[{kind}] aborting run: {e}", file=log)
            good = resilience.abort_export_state()
            if good is not None:
                self.state = good
            if tracer is not None:
                jax.block_until_ready(self.state)
                tracer.drain(
                    self.state.trace,
                    wall_t0=t_chunk, wall_t1=time.monotonic(),
                )
                tracer.truncate_to_round(int(self.state.stats.rounds))
            if flowcol is not None:
                # drained records beyond the exported state's own ledger
                # cursor cover rounds the artifacts do not — drop them
                # (FlowCollector.truncate_to_cursor docs this), then
                # re-seat the trace's flow track on the kept record set
                jax.block_until_ready(self.state)
                _drain_flows()
                flowcol.truncate_to_cursor(
                    np.asarray(jax.device_get(self.state.flows.cursor))
                )
                if tracer is not None:
                    tracer.reset_flows(flowcol.records())
            if kind == "integrity":
                self._integrity_aborted = True
                if tracer is not None and resilience is not None and (
                    resilience.iv_deterministic is not None
                ):
                    tracer.note_violation(resilience.iv_deterministic)
            else:
                self._pressure_aborted = True

        from shadow_tpu.core.integrity import IntegrityAbort
        from shadow_tpu.core.pressure import PressureAbort

        try:
            while not bool(self.state.done):
                t_chunk = time.monotonic()
                if wallled is not None:
                    wallled.chunk_start()
                if capture is not None:
                    with span_or_null(wallled, "dispatch"):
                        self.state, sent = capture.step(
                            self.state, self.params
                        )
                        if wallled is not None:
                            jax.block_until_ready(self.state)
                    with span_or_null(wallled, "export"):
                        capture.write_round(sent)
                elif sup is not None:
                    from shadow_tpu.core.supervisor import SupervisorAbort

                    try:
                        with span_or_null(wallled, "dispatch"):
                            self.state = sup.run_chunk(
                                self.state, _chunk_step
                            )
                    except IntegrityAbort as e:
                        _policy_abort(e, t_chunk, kind="integrity")
                        break
                    except PressureAbort as e:
                        _policy_abort(e, t_chunk)
                        break
                    except SupervisorAbort as e:
                        # graceful abort: export the completed prefix from
                        # the supervisor's snapshot, not the in-hand state
                        # (abort_export_state docs the poisoned/donation
                        # rationale)
                        print(f"[supervisor] aborting run: {e}", file=log)
                        good = sup.abort_export_state()
                        if good is not None:
                            self.state = good
                            if tracer is not None:
                                # chunks that succeeded AFTER the snapshot
                                # were already drained; drop their rows so
                                # the trace covers exactly the exported
                                # prefix (truncate_to_round docs this)
                                tracer.truncate_to_round(
                                    int(self.state.stats.rounds)
                                )
                            if flowcol is not None:
                                # same contract for flow records: the
                                # exported state's ledger cursor is the
                                # truth of what its prefix completed —
                                # and the trace's flow track re-seats on
                                # the kept record set
                                flowcol.truncate_to_cursor(
                                    np.asarray(jax.device_get(
                                        self.state.flows.cursor
                                    ))
                                )
                                if tracer is not None:
                                    tracer.reset_flows(flowcol.records())
                        self._aborted = True
                        break
                else:
                    try:
                        with span_or_null(wallled, "dispatch"):
                            self.state = _chunk_step(self.state)
                            if wallled is not None:
                                # async dispatch: without the block the
                                # device time would leak into whichever
                                # span syncs first
                                jax.block_until_ready(self.state)
                    except IntegrityAbort as e:
                        _policy_abort(e, t_chunk, kind="integrity")
                        break
                    except PressureAbort as e:
                        _policy_abort(e, t_chunk)
                        break
                with span_or_null(wallled, "export"):
                    if tracer is not None:
                        # pair the drained rounds with the true wall span
                        # of this dispatch (block: async dispatch would
                        # pin the span to enqueue time, not device time)
                        jax.block_until_ready(self.state)
                        tracer.drain(
                            self.state.trace,
                            wall_t0=t_chunk, wall_t1=time.monotonic(),
                        )
                    if flowcol is not None:
                        jax.block_until_ready(self.state)
                        _drain_flows()
                    if monitor is not None:
                        t_s = time.monotonic()
                        shard_bytes = monitor.sample(
                            modeled_bytes=self._modeled_shard_bytes(),
                            wall_t=t_s,
                        )
                        if tracer is not None:
                            tracer.note_memory(t_s, shard_bytes)
                chunks += 1
                now_ns = int(self.state.now)
                if wallled is not None:
                    # close the chunk (heartbeat/progress printing below
                    # lands in the NEXT chunk's host_python residual)
                    wallled.chunk_end(now_ns)
                wall = time.monotonic() - t0
                if hb_ns and now_ns >= next_hb:
                    ev = int(np.asarray(self.state.stats.events).sum())
                    # event-density telemetry (the K-way microstep's target
                    # quantities): microsteps per round is how serialized the
                    # round loop is, events per microstep is how well the
                    # K-fold amortizes — the same two numbers bench.py tracks
                    msteps = int(np.asarray(self.state.stats.microsteps).sum())
                    rounds = int(self.state.stats.rounds)
                    ici = int(np.asarray(self.state.stats.ici_bytes).sum())
                    qhwm = int(np.asarray(self.state.stats.q_occ_hwm).max())
                    # faults= rides along only when the fault plane is
                    # active, gear= only on adaptive runs (old-format
                    # lines stay byte-identical; parse_shadow reads both)
                    # xw= rides along only on hierarchical-exchange runs:
                    # cumulative (intra compaction, inter wire) tier bytes
                    xw = None
                    if self.engine_cfg.hier_active:
                        xw = (
                            int(np.asarray(self.state.stats.ici_intra).sum()),
                            int(np.asarray(self.state.stats.ici_inter).sum()),
                        )
                    fault = None
                    if self.engine_cfg.faults_active:
                        fd = int(np.asarray(self.state.stats.faults_dropped).sum())
                        fy = int(np.asarray(self.state.stats.faults_delayed).sum())
                        fault = (fd, fy)
                    # cap= rides along only on pressure-plane runs (the
                    # ACTIVE capacity — escalation regrows it mid-run)
                    cap = (
                        self.state.queue.t.shape[1]
                        if pressure_on else None
                    )
                    # hbm= rides along only on memory-observatory runs:
                    # the per-shard HBM high-water so far (bytes)
                    hbm = (
                        monitor.hwm_bytes() if monitor is not None else None
                    )
                    # ek= (timer/packet event counts) and fct= (flows
                    # completed) ride along only on network-observatory
                    # runs (fct only when a flow ledger is active)
                    ek = fct = None
                    if self.engine_cfg.netobs:
                        ek = (
                            int(np.asarray(self.state.stats.ec_timer).sum()),
                            int(np.asarray(self.state.stats.ec_pkt).sum()),
                        )
                        if self.engine_cfg.flow_ledger_active:
                            fct = int(
                                np.asarray(self.state.stats.fl_done).sum()
                            )
                    # bg= rides along only on fluid-traffic-plane runs:
                    # cumulative background bytes delivered/dropped
                    # (replicated scalars — read, never summed)
                    bg = None
                    if self.engine_cfg.fluid_active:
                        bg = (
                            int(np.asarray(self.state.stats.fl_bg_bytes)),
                            int(np.asarray(
                                self.state.stats.fl_bg_dropped
                            )),
                        )
                    # iv= rides along only on integrity-sentinel runs:
                    # (transient SDC survived, sentinel replays) so far
                    iv = (
                        (resilience.iv_transients, resilience.iv_replays)
                        if integrity_on and resilience is not None else None
                    )
                    # rt= rides along only on runtime-observatory runs:
                    # the LAST chunk's realtime factor (sim-s/wall-s)
                    rt = (
                        wallled.rt_last if wallled is not None else None
                    )
                    print(
                        heartbeat_line(
                            now_ns, wall, ev, msteps, rounds, ici, qhwm,
                            xw=xw, fault=fault, gear=last_gear, cap=cap,
                            hbm=hbm, ek=ek, fct=fct, bg=bg, iv=iv, rt=rt,
                        ),
                        file=log,
                    )
                    if simlog is not None:
                        simlog.info(
                            now_ns, "manager",
                            f"heartbeat events={ev} "
                            f"rounds={int(self.state.stats.rounds)}",
                        )
                    next_hb = (now_ns // hb_ns + 1) * hb_ns
                if show_progress:
                    pct = min(100.0, 100.0 * now_ns / max(cfg.general.stop_time, 1))
                    print(f"\rprogress: {pct:5.1f}% ", end="", file=log, flush=True)
        finally:
            if profiling:
                jax.profiler.stop_trace()
        if show_progress:
            print(file=log)
        if capture is not None:
            capture.close()
        self._wall_seconds = time.monotonic() - t0
        self._chunks = chunks
        if simlog is not None:
            simlog.info(
                int(self.state.now), "manager",
                f"simulation done chunks={chunks}",
            )
            simlog.close()
        return self.stats_report()

    def _pcap_capture_begin(self):
        """When any host has pcap_enabled, switch the run loop to captured
        single-round dispatches and open per-host eth0.pcap writers (the
        modeled-sim analogue of the reference's per-interface capture; the
        co-sim plane captures real packets, here frames are synthesized
        from packet events). Returns None when no host captures."""
        specs = [h for h in self.hosts if h.pcap_enabled]
        if not specs:
            return None
        return _ModeledPcap(self)

    def _run_golden(self) -> dict:
        """`experimental.scheduler: cpu-reference` — run the independent
        pure-Python golden engine instead of the device engine (the
        reference's two-scheduler determinism capability, src/test/
        determinism 2a/2b vs 2c: scheduler choice must not change results).
        """
        from shadow_tpu.core.golden import run_golden

        params, mstate, events = self._golden_inputs
        t0 = time.monotonic()
        gold = run_golden(
            self.engine_cfg, self.model, params, mstate, events,
            seed=self.cfg.general.seed,
        )
        self._wall_seconds = time.monotonic() - t0
        self._chunks = 0
        self._golden = gold
        n = self._num_real
        sim_s = gold.now / NS_PER_SEC
        self._golden_report = {
            "simulated_seconds": sim_s,
            "wall_seconds": self._wall_seconds,
            "sim_wall_ratio": sim_s / max(self._wall_seconds, 1e-9),
            "scheduler": "cpu-reference",
            "rounds": gold.rounds,
            "microsteps": gold.microsteps,
            "events_processed": int(gold.stats["events"][:n].sum()),
            "packets_sent": int(gold.stats["pkts_sent"][:n].sum()),
            "packets_delivered": int(gold.stats["pkts_delivered"][:n].sum()),
            "packets_lost": int(gold.stats["pkts_lost"][:n].sum()),
            "packets_unreachable": int(gold.stats["pkts_unreachable"][:n].sum()),
            "packets_codel_dropped": int(
                gold.stats["pkts_codel_dropped"][:n].sum()
            ),
            "queue_overflow_dropped": int(gold.stats["dropped"][:n].sum()),
            "packets_budget_dropped": int(
                gold.stats["pkts_budget_dropped"][:n].sum()
            ),
            "outbox_overflow_dropped": 0,  # golden has no staging outbox
            "monotonic_violations": int(
                gold.stats["monotonic_violations"][:n].sum()
            ),
            "determinism_digest": f"{int(np.bitwise_xor.reduce(gold.digests[:n])):016x}",
            "model_report": self.model.report(
                jax.tree.map(lambda a: np.asarray(a)[:n], gold.model_state),
                self._model_hosts(),
            ),
        }
        return self._golden_report

    # ---- outputs ----------------------------------------------------------

    def stats_report(self) -> dict:
        """sim-stats content (reference sim_stats.rs counters + tracker.c)."""
        if getattr(self, "_golden_report", None) is not None:
            return self._golden_report  # cpu-reference run: device state unused
        s = jax.device_get(self.state.stats)
        n = self._num_real
        wall = getattr(self, "_wall_seconds", None)
        sim_s = int(self.state.now) / NS_PER_SEC
        report = {
            "simulated_seconds": sim_s,
            "wall_seconds": wall,
            "sim_wall_ratio": (sim_s / wall) if wall else None,
            "rounds": int(s.rounds),
            "microsteps": int(np.asarray(s.microsteps).sum()),
            "events_processed": int(s.events[:n].sum()),
            "packets_sent": int(s.pkts_sent[:n].sum()),
            "packets_delivered": int(s.pkts_delivered[:n].sum()),
            "packets_lost": int(s.pkts_lost[:n].sum()),
            "packets_unreachable": int(s.pkts_unreachable[:n].sum()),
            "packets_codel_dropped": int(s.pkts_codel_dropped[:n].sum()),
            "queue_overflow_dropped": int(
                np.asarray(jax.device_get(self.state.queue.dropped))[:n].sum()
            ),
            "packets_budget_dropped": int(s.pkts_budget_dropped[:n].sum()),
            "faults_dropped": int(s.faults_dropped[:n].sum()),
            "faults_delayed": int(s.faults_delayed[:n].sum()),
            "outbox_overflow_dropped": int(np.asarray(s.ob_dropped).sum()),
            # alltoall block-overflow sheds: structurally zero when
            # a2a_block is sized right — exported so a mis-sized block is
            # visible in sim-stats, not only in test asserts
            "alltoall_shed_dropped": int(np.asarray(s.a2a_shed).sum()),
            "bucket_cache_rebuilds": int(np.asarray(s.bq_rebuilds).sum()),
            "popk_deferred": int(np.asarray(s.popk_deferred).sum()),
            "ici_bytes": int(np.asarray(s.ici_bytes).sum()),
            "queue_occupancy_hwm": int(s.q_occ_hwm[:n].max()) if n else 0,
            # always-on: the most sends any one host staged in a round.
            # Gear runs reset the device counter per chunk (the controller
            # needs a fresh signal), so fold in the Python-tracked run max.
            "outbox_send_hwm": max(
                int(np.asarray(s.outbox_hwm).max()), self._ob_hwm_run
            ),
            "monotonic_violations": int(s.monotonic_violations[:n].sum()),
            "determinism_digest": f"{int(np.bitwise_xor.reduce(s.digest[:n])):016x}",
            "model_report": self.model.report(
                jax.tree.map(lambda a: np.asarray(a)[:n], jax.device_get(self.state.model)),
                self._model_hosts(),
            ),
        }
        if self.engine_cfg.hier_active:
            # hierarchical-exchange block (core/engine.py
            # _exchange_hierarchical): the two-tier byte split. intra is
            # compaction staging traffic (stays on-shard, HBM-side);
            # inter is what actually crossed the ICI — the same number
            # ici_bytes above carries, broken out so trend tooling
            # (bench rows, tools/bench_compare.py) can guard the
            # inter-shard tier against regressing toward the flat cost.
            report["exchange"] = {
                "kind": "hierarchical",
                "block": self.engine_cfg.hier_block_size,
                "ici_intra_bytes": int(np.asarray(s.ici_intra).sum()),
                "ici_inter_bytes": int(np.asarray(s.ici_inter).sum()),
            }
        if self.engine_cfg.wheel_active:
            # timer-wheel block (ops/wheel.py): occupancy high-water +
            # spill count — the slot-sizing signal (tools/bench_wheel.py
            # sweeps S; tools/net_report.py breaks this out in its
            # verdict). wheel_dropped is an invariant zero (spill
            # routing pre-empts overflow; the sentinel guards it).
            report["wheel"] = {
                "slots": self.engine_cfg.wheel_slots,
                "block": self.state.wheel.block,
                "occupancy_hwm": int(s.wheel_occ_hwm[:n].max()) if n else 0,
                "spilled": int(s.wheel_spilled[:n].sum()),
                "dropped": int(
                    np.asarray(
                        jax.device_get(self.state.wheel.dropped)
                    )[:n].sum()
                ),
            }
        if self._gearctl is not None:
            report["gears"] = self._gearctl.report()
        if self._pressctl is not None:
            rc = self._pressctl
            report["pressure"] = {
                **rc.report(),
                # the shapes the run ENDED at (escalation regrows them;
                # fixed-shape runs echo the configured values)
                "capacity": self.state.queue.t.shape[1],
                "outbox": self.state.outbox.t.shape[1],
                "base_capacity": self.engine_cfg.queue_capacity,
                "base_outbox": self.engine_cfg.sends_per_host_round,
            }
            # flat counters for trend tooling (bench rows, parse_shadow
            # consumers) — same numbers as the block above
            report["pressure_regrows"] = (
                rc.regrows + rc.proactive_regrows
            )
            report["pressure_replays"] = rc.replays
            if getattr(self, "_pressure_aborted", False):
                report["pressure_aborted"] = True
                report["aborted"] = True
        if self.engine_cfg.integrity:
            # integrity sentinel block (core/integrity.py): the
            # transient/replay accounting — the documented scribble
            # waves as counted, survived events — plus the second
            # digest fold (the dual lane that makes a scribble on the
            # digest plane itself classifiable,
            # core/integrity.classify_digest_pair) and, after an
            # IntegrityAbort, the deterministic violation's naming.
            rc = getattr(self, "_resil", None)
            block: dict[str, Any] = (
                rc.integrity_report() if rc is not None
                else {
                    "transients": 0,
                    "replays": 0,
                    "max_replays": self.cfg.integrity.max_replays,
                }
            )
            if self.engine_cfg.integrity_dual:
                block["determinism_digest2"] = (
                    f"{int(np.bitwise_xor.reduce(np.asarray(s.digest2)[:n])):016x}"
                )
            report["integrity"] = block
            if getattr(self, "_integrity_aborted", False):
                report["integrity_aborted"] = True
                report["aborted"] = True
        if self.engine_cfg.netobs:
            # network observatory block (obs/netobs.py): event classes,
            # safe-window critical path, flow ledger, per-link fold —
            # assembled by the ONE shared helper (bench rows and the
            # hybrid driver use the same one, so the block's shape
            # cannot drift between exporters). The gated stats lanes
            # (ec_* / fl_* / win_bound) are read inside it and listed in
            # lanes.STATS_EXPORT_EXEMPT with that export path recorded.
            from shadow_tpu.obs.netobs import (
                assemble_network_report, node_map,
            )

            report["network"] = assemble_network_report(
                stats=s,
                num_real=n,
                rounds=int(s.rounds),
                node_of=node_map(self.hosts, n),
                model=self.model,
                model_state=self._model_host_view(),
                flow_ledger=self.engine_cfg.flow_ledger_active,
                collector=getattr(self, "_flowcol", None),
            )
        if self.engine_cfg.fluid_active:
            # fluid traffic plane block (net/fluid.py): the background
            # byte accounting and final link-utilization view, assembled
            # by the ONE shared helper (bench rows use the same one, so
            # the block's shape cannot drift between exporters). The
            # gated fl_bg_* stats lanes are read inside it and listed in
            # lanes.STATS_EXPORT_EXEMPT with that export path recorded.
            from shadow_tpu.net.fluid import assemble_fluid_report

            report["fluid"] = assemble_fluid_report(
                stats=s,
                fluid_state=jax.device_get(self.state.fluid),
                cfg=self.engine_cfg,
            )
        memmon = getattr(self, "_memmon", None)
        if memmon is not None:
            # HBM observatory block (obs/memory.py): static byte model +
            # per-rung compiled ledger + per-shard live high-water
            from shadow_tpu.obs.memory import observatory_report

            report["memory"] = observatory_report(
                self.engine, self.state, self.params, memmon,
                ledger=self.cfg.observability.memory_ledger,
            )
        if self.cfg.observability.runtime:
            # runtime observatory block (obs/runtime.py): per-span
            # wall attribution + realtime-factor series + the compile
            # ledger — assembled by the ONE shared helper (the hybrid
            # driver and bench rows use the same one, so the block's
            # shape cannot drift between exporters)
            from shadow_tpu.obs.runtime import assemble_runtime_report

            report["runtime"] = assemble_runtime_report(
                wall=getattr(self, "_wallled", None),
                compiles=getattr(self, "_rt_compiles", None),
                total_wall_s=wall,
            )
        sup = getattr(self, "_supervisor", None)
        if sup is not None:
            report["supervisor"] = sup.report()
        if getattr(self, "_aborted", False):
            # bounded retries exhausted: everything above describes the
            # COMPLETED prefix (the supervisor's last good snapshot) —
            # unless the snapshot was poisoned, which the top-level flag
            # makes impossible to miss
            report["aborted"] = True
            if sup is not None and sup.poisoned:
                report["poisoned"] = True
        tracer = getattr(self, "_tracer", None)
        if tracer is not None:
            # tracing opted in: the per-host planes are cheap relative to
            # the trace itself, so the full vectors ride along (gated —
            # a 1M-host untraced sim must not grow MB-scale JSON)
            report["trace"] = tracer.summary()
            report["per_host"] = {
                "events_processed": [int(x) for x in s.events[:n]],
                "queue_occupancy_hwm": [int(x) for x in s.q_occ_hwm[:n]],
            }
        return report

    def _model_host_view(self):
        """The model state as a host-side tree sliced to the real hosts,
        fetched ONCE per device state (memoized on the state's model
        pytree identity): stats_report's network block and
        write_outputs' host-stats extras both read it, and the transfer
        is the whole model state — at the million-host scale, paying it
        twice per report is real traffic."""
        st = self.state.model
        cached = getattr(self, "_model_view_cache", None)
        if cached is not None and cached[0] is st:
            return cached[1]
        view = jax.tree.map(
            lambda a: np.asarray(a)[: self._num_real], jax.device_get(st)
        )
        self._model_view_cache = (st, view)
        return view

    def host_digests(self) -> np.ndarray:
        return np.asarray(jax.device_get(self.state.stats.digest))[: self._num_real]

    def write_outputs(
        self, data_dir: str | None = None, report: dict | None = None
    ) -> str:
        """Write the data directory (reference data-dir layout:
        processed-config.yaml, sim-stats.json, hosts/<name>/). Pass the report
        from run() to avoid recomputing the device->host stats transfer."""
        data_dir = data_dir or self.cfg.general.data_directory
        os.makedirs(data_dir, exist_ok=True)
        with open(os.path.join(data_dir, "processed-config.yaml"), "w") as f:
            yaml.safe_dump(self.cfg.to_dict(), f, sort_keys=False)
        if report is None:
            report = self.stats_report()
        with open(os.path.join(data_dir, "sim-stats.json"), "w") as f:
            json.dump(report, f, indent=2)
        gold = getattr(self, "_golden", None)
        if gold is not None:
            events_c, sent_c = gold.stats["events"], gold.stats["pkts_sent"]
            deliv_c, lost_c = gold.stats["pkts_delivered"], gold.stats["pkts_lost"]
            digests = gold.digests
            occ_c = None  # the golden oracle does not track occupancy
        else:
            s = jax.device_get(self.state.stats)
            events_c, sent_c = s.events, s.pkts_sent
            deliv_c, lost_c = s.pkts_delivered, s.pkts_lost
            digests = self.host_digests()
            occ_c = s.q_occ_hwm
        # network observatory: per-host network counters ride into
        # host-stats.json on gated runs (engine drop lanes by cause +
        # the model's per-host hook — bytes/retransmits on tgen)
        net_ph: dict[str, Any] = {}
        if getattr(self.engine_cfg, "netobs", False) and gold is None:
            net_ph = {
                "packets_codel_dropped": s.pkts_codel_dropped,
                "packets_budget_dropped": s.pkts_budget_dropped,
                "packets_unreachable": s.pkts_unreachable,
            }
            if hasattr(self.model, "per_host_network"):
                for k, v in self.model.per_host_network(
                    self._model_host_view()
                ).items():
                    net_ph[k] = v
        for h in self.hosts:
            hd = os.path.join(data_dir, "hosts", h.name)
            os.makedirs(hd, exist_ok=True)
            with open(os.path.join(hd, "host-stats.json"), "w") as f:
                json.dump(
                    {
                        "name": h.name,
                        "ip": h.ip,
                        "events_processed": int(events_c[h.host_id]),
                        "packets_sent": int(sent_c[h.host_id]),
                        "packets_delivered": int(deliv_c[h.host_id]),
                        "packets_lost": int(lost_c[h.host_id]),
                        **(
                            {"queue_occupancy_hwm": int(occ_c[h.host_id])}
                            if occ_c is not None
                            else {}
                        ),
                        **{
                            k: int(np.asarray(v)[h.host_id])
                            for k, v in net_ph.items()
                            if h.host_id < len(np.asarray(v))
                        },
                        "determinism_digest": f"{int(digests[h.host_id]):016x}",
                    },
                    f,
                    indent=2,
                )
        self._write_trace_outputs(data_dir, report)
        return data_dir

    def _write_trace_outputs(self, data_dir: str, report: dict | None):
        """Export the round tracer's artifacts (Chrome trace + Prometheus
        metrics) into the data dir. No-op unless `observability.trace` ran."""
        tracer = getattr(self, "_tracer", None)
        if tracer is not None:
            compiles = getattr(self, "_rt_compiles", None)
            if compiles is not None:
                # runtime observatory: the compile track (one X event
                # per recorded program compile on the wall-clock
                # timeline, obs/runtime.CompileLedger.events)
                tracer.note_compiles(compiles.events())
            tracer.write_artifacts(data_dir, self.cfg.observability, report)


def resource_heartbeat() -> str:
    """Process-resource snippet for heartbeat lines (the reference logs
    getrusage + /proc/meminfo every interval in a tornettools-parseable
    format, manager.rs:675-717)."""
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        # ru_maxrss is KiB on Linux but bytes on macOS
        rss_div = (1 << 30) if sys.platform == "darwin" else (1 << 20)
        rss_gib = ru.ru_maxrss / rss_div
        mem_avail = ""
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemAvailable:"):
                        mem_avail = f" mem_avail_gib={int(line.split()[1]) / (1 << 20):.1f}"
                        break
        except OSError:
            pass
        return (
            f"rss_gib={rss_gib:.2f} utime_min={ru.ru_utime / 60:.1f} "
            f"stime_min={ru.ru_stime / 60:.1f}{mem_avail}"
        )
    except Exception:
        return ""


def run_simulation(cfg: ConfigOptions, **kw) -> tuple[Simulation, dict]:
    sim = Simulation(cfg, **kw)
    report = sim.run()
    return sim, report
