"""Built-in managed programs (the analogue of the reference's test/traffic
binaries: tgen flows `src/test/tgen/`, PHOLD `src/test/phold/`, echo
servers in `src/test/socket/`).

A program is a generator `def prog(ctx): yield ("syscall", ...)` run by
`shadow_tpu.host.process`. Configs reference them by `path:` name; the
registry stands in for an on-disk binary (real executables arrive with the
native managed-process plane)."""

from __future__ import annotations

PROGRAM_REGISTRY: dict[str, object] = {}


def register_program(fn=None, *, name: str | None = None):
    def deco(f):
        PROGRAM_REGISTRY[name or f.__name__] = f
        return f

    return deco(fn) if fn is not None else deco


def get_program(name: str):
    if name not in PROGRAM_REGISTRY:
        raise KeyError(
            f"unknown program {name!r}; available: {sorted(PROGRAM_REGISTRY)}"
        )
    return PROGRAM_REGISTRY[name]


# ---------------------------------------------------------------- programs

MS = 1_000_000
SEC = 1_000_000_000


@register_program
def udp_echo_server(ctx):
    """Echo datagrams back to their sender forever (test/socket echo)."""
    port = int(ctx.args.get("port", 9000))
    fd = yield ("socket", "udp")
    yield ("bind", fd, ("0.0.0.0", port))
    while True:
        data, addr = yield ("recvfrom", fd, 65536)
        yield ("sendto", fd, data, addr)


@register_program
def udp_ping(ctx):
    """Send `count` datagrams to `server`, await each echo, log RTTs."""
    server = ctx.args.get("server", "server")
    port = int(ctx.args.get("port", 9000))
    count = int(ctx.args.get("count", 10))
    interval = int(ctx.args.get("interval_ns", 100 * MS))
    size = int(ctx.args.get("size", 64))
    ip = yield ("resolve", server)
    fd = yield ("socket", "udp")
    yield ("connect", fd, (ip, port))
    ok = 0
    for i in range(count):
        t0 = yield ("clock_gettime",)
        yield ("sendto", fd, bytes([i % 256]) * size)
        data, _ = yield ("recvfrom", fd, 65536)
        t1 = yield ("clock_gettime",)
        assert data == bytes([i % 256]) * size
        ok += 1
        yield ("write_stdout", f"seq={i} rtt_ns={t1 - t0}\n".encode())
        if i + 1 < count:
            yield ("nanosleep", interval)
    yield ("write_stdout", f"done ok={ok}/{count}\n".encode())
    yield ("exit", 0)


@register_program
def udp_blast(ctx):
    """Fire `count` datagrams at `server` without awaiting replies (one-way
    load source for mixed-plane tests where the modeled peer never echoes)."""
    server = ctx.args.get("server", "server")
    port = int(ctx.args.get("port", 9000))
    count = int(ctx.args.get("count", 5))
    interval = int(ctx.args.get("interval_ns", 100 * MS))
    size = int(ctx.args.get("size", 64))
    ip = yield ("resolve", server)
    fd = yield ("socket", "udp")
    yield ("connect", fd, (ip, port))
    for i in range(count):
        yield ("sendto", fd, bytes([i % 256]) * size)
        if i + 1 < count:
            yield ("nanosleep", interval)
    yield ("write_stdout", f"blast done {count}\n".encode())
    yield ("exit", 0)


@register_program
def tgen_server(ctx):
    """Accept TCP connections; drain each until EOF (tgen fixed_size sink).

    Serves `conns` connections sequentially, then exits 0 (or runs forever
    with conns=0)."""
    port = int(ctx.args.get("port", 8080))
    conns = int(ctx.args.get("conns", 0))
    fd = yield ("socket", "tcp")
    yield ("bind", fd, ("0.0.0.0", port))
    yield ("listen", fd)
    served = 0
    while conns == 0 or served < conns:
        cfd, peer = yield ("accept", fd)
        total = 0
        while (data := (yield ("recv", cfd, 65536))) != b"":
            total += len(data)
        yield ("write_stdout", f"conn={served} from={peer[0]} bytes={total}\n".encode())
        yield ("close", cfd)
        served += 1
    yield ("exit", 0)


@register_program
def tgen_client(ctx):
    """Stream `size` bytes to `server` over TCP, then FIN (tgen fixed_size)."""
    server = ctx.args.get("server", "server")
    port = int(ctx.args.get("port", 8080))
    size = int(ctx.args.get("size", 1 << 20))
    ip = yield ("resolve", server)
    fd = yield ("socket", "tcp")
    yield ("connect", fd, (ip, port))
    t0 = yield ("clock_gettime",)
    sent = 0
    block = bytes(range(256)) * 256  # 64 KiB pattern
    while sent < size:
        sent += yield ("send", fd, block[: min(len(block), size - sent)])
    yield ("shutdown", fd)
    t1 = yield ("clock_gettime",)
    yield (
        "write_stdout",
        f"sent={sent} elapsed_ns={t1 - t0} "
        f"goodput_mbps={sent * 8e3 / max(t1 - t0, 1):.2f}\n".encode(),
    )
    yield ("exit", 0)


@register_program
def tgen_duration_client(ctx):
    """Stream to `server` for `duration` seconds, then FIN (the reference's
    tgen fixed_duration flow, src/test/tgen/fixed_duration)."""
    server = ctx.args.get("server", "server")
    port = int(ctx.args.get("port", 8080))
    duration_ns = int(float(ctx.args.get("duration_s", 5)) * SEC)
    ip = yield ("resolve", server)
    fd = yield ("socket", "tcp")
    yield ("connect", fd, (ip, port))
    t0 = yield ("clock_gettime",)
    block = bytes(range(256)) * 256
    sent = 0
    while True:
        now = yield ("clock_gettime",)
        if now - t0 >= duration_ns:
            break
        sent += yield ("send", fd, block)
    yield ("shutdown", fd)
    yield (
        "write_stdout",
        f"sent={sent} duration_ns={now - t0} "
        f"goodput_mbps={sent * 8e3 / max(now - t0, 1):.2f}\n".encode(),
    )
    yield ("exit", 0)


@register_program
def unix_echo_pair(ctx):
    """Single-host unix-domain smoke workload: a socketpair echo plus an
    abstract-namespace listener/connector (reference socket/unix tests)."""
    a, b = yield ("socketpair",)
    yield ("write", a, b"ping")
    data = yield ("read", b, 16)
    assert data == b"ping", data
    lst = yield ("socket", "unix")
    yield ("bind", lst, "@echo")
    yield ("listen", lst)
    cli = yield ("socket", "unix")
    yield ("connect", cli, "@echo")
    srv, _ = yield ("accept", lst)
    yield ("write", cli, b"hello-unix")
    got = yield ("read", srv, 64)
    yield ("write_stdout", b"unix ok: " + got + b"\n")
    yield ("exit", 0)


@register_program
def phold_proc(ctx):
    """PHOLD as a managed program (the reference runs PHOLD as a real socket
    binary, src/test/phold/): hold `population` jobs, mature each after an
    exponential delay, forward to a uniform-random peer."""
    import math

    peers = ctx.args["peers"]  # list of hostnames
    port = int(ctx.args.get("port", 9000))
    population = int(ctx.args.get("population", 2))
    mean_delay = int(ctx.args.get("mean_delay_ns", 100 * MS))
    size = int(ctx.args.get("size", 64))
    fd = yield ("socket", "udp")
    yield ("bind", fd, ("0.0.0.0", port))
    ips = []
    for p in peers:
        ips.append((yield ("resolve", p)))
    ep = yield ("epoll_create",)
    yield ("epoll_ctl", ep, "add", fd, 0x001)
    tfd = yield ("timerfd_create",)
    yield ("epoll_ctl", ep, "add", tfd, 0x001)

    def draw_delay(u: float) -> int:
        return max(1, int(-mean_delay * math.log(1.0 - u)))

    pending = []  # maturity deadlines
    now = yield ("clock_gettime",)
    for _ in range(population):
        r = yield ("getrandom", 4)
        u = int.from_bytes(r, "little") / 2**32
        pending.append(now + draw_delay(u))
    forwarded = 0
    while True:
        pending.sort()
        yield ("timerfd_settime", tfd, pending[0] if pending else None, 0)
        evs = yield ("epoll_wait", ep)
        now = yield ("clock_gettime",)
        for efd, _, _ in evs:
            if efd == tfd:
                yield ("read", tfd, 8)
                while pending and pending[0] <= now:
                    pending.pop(0)
                    r = yield ("getrandom", 4)
                    dst = ips[int.from_bytes(r, "little") % len(ips)]
                    yield ("sendto", fd, b"j" * size, (dst, port))
                    forwarded += 1
            elif efd == fd:
                while (r := (yield ("read_nonblock", fd, 65536))) is not None:
                    rr = yield ("getrandom", 4)
                    u = int.from_bytes(rr, "little") / 2**32
                    pending.append(now + draw_delay(u))
