"""Token-bucket and CoDel unit tests (reference analogue:
token_bucket.rs tests and codel_queue.rs:330-530 tests)."""

import jax.numpy as jnp
import numpy as np

from shadow_tpu.net import (
    INTERVAL_NS,
    TARGET_NS,
    TBParams,
    codel_init,
    codel_on_packet,
    tb_conforming_remove,
    tb_init,
)

MS = 1_000_000
ITV = 1 * MS  # 1 ms refill quantum


def _tb(cap_bits, refill_bits, n=1):
    p = TBParams(
        capacity=jnp.full((n,), cap_bits, jnp.int64),
        refill=jnp.full((n,), refill_bits, jnp.int64),
    )
    return p, tb_init(p)


def _remove(s, p, t, bits, mask=True):
    m = jnp.full(p.capacity.shape, mask)
    s, depart = tb_conforming_remove(
        s, p, ITV, jnp.full(p.capacity.shape, t, jnp.int64),
        jnp.full(p.capacity.shape, bits, jnp.int64), m
    )
    return s, int(depart[0])


def test_tb_conforming_passes_immediately():
    p, s = _tb(30_000, 1_000)
    s, d = _remove(s, p, 5 * MS, 20_000)
    assert d == 5 * MS
    # 10_000 left; next 20_000 at same time must wait ceil(10000/1000)=10 itvs
    s, d = _remove(s, p, 5 * MS, 20_000)
    assert d == 15 * MS


def test_tb_refill_is_quantized():
    p, s = _tb(10_000, 1_000)
    s, d = _remove(s, p, 0, 10_000)  # drain full burst at t=0
    assert d == 0
    # at t=2.5ms only 2 whole intervals refilled -> 2000 bits; need 3000
    s, d = _remove(s, p, int(2.5 * MS), 3_000)
    assert d == 3 * MS


def test_tb_fifo_no_overtake_no_double_refill():
    """A packet popped while a predecessor is still waiting on refill must not
    depart before it, roll accounting backward, or re-accrue spent refill."""
    p, s = _tb(10_000, 1_000)
    # A: 20000 bits at t=0 -> drains burst, borrows 10 intervals -> departs 10ms
    s, d = _remove(s, p, 0, 20_000)
    assert d == 10 * MS
    assert int(s.last_itv[0]) == 10
    # B: 5000 bits at t=0.5ms: charged from A's boundary, departs 15ms (not 5ms)
    s, d = _remove(s, p, MS // 2, 5_000)
    assert d == 15 * MS
    assert int(s.last_itv[0]) == 15  # never rolled back
    # C: 1000 bits at t=6ms: interval 6-15 refill is already spent -> 16ms
    s, d = _remove(s, p, 6 * MS, 1_000)
    assert d == 16 * MS
    # total delivered by 16ms: 26000 bits <= burst 10000 + 16*1000 = 26000


def test_tb_conforming_at_future_boundary():
    """Leftover tokens stored at a future boundary are only usable there."""
    p, s = _tb(10_000, 1_000)
    s, d = _remove(s, p, 0, 19_000)  # borrows to itv 9, leaves 0 tokens...
    assert d == 9 * MS
    # 1000 bits at t=1ms: one refill lands at boundary 10 -> departs 10ms
    s, d = _remove(s, p, MS, 1_000)
    assert d == 10 * MS


def test_tb_unshaped_passthrough():
    p, s = _tb(0, 0)
    s, d = _remove(s, p, 7 * MS, 10**9)
    assert d == 7 * MS
    assert int(s.tokens[0]) == 0  # untouched


def test_tb_huge_gap_no_overflow():
    p, s = _tb(30_000, 1_000)
    s, d = _remove(s, p, 0, 30_000)
    s, d = _remove(s, p, 10**15, 30_000)  # ~11.5 days later
    assert d == 10**15


def test_codel_first_drop_after_one_interval():
    """Sustained over-target delay must start dropping after ONE interval of
    persistence, regardless of how late in the sim congestion begins
    (entry law: codel_queue.rs:151-171)."""
    start = 2_000 * MS  # past the 16*INTERVAL-from-zero edge
    s = codel_init(1)
    mask = jnp.ones((1,), bool)
    sojourn = jnp.full((1,), TARGET_NS + 5 * MS, jnp.int64)
    drops = []
    t = start
    for i in range(15):
        s, drop = codel_on_packet(s, jnp.full((1,), t, jnp.int64), sojourn, mask)
        drops.append((t - start) // MS if bool(drop[0]) else None)
        t += 10 * MS
    fired = [d for d in drops if d is not None]
    assert fired, "no drops under sustained over-target delay"
    # first drop at the first packet with now >= first_above (= start+INTERVAL)
    assert fired[0] == INTERVAL_NS // MS


def test_codel_no_drop_below_target():
    s = codel_init(1)
    mask = jnp.ones((1,), bool)
    sojourn = jnp.full((1,), TARGET_NS - 1, jnp.int64)
    t = 0
    for _ in range(30):
        s, drop = codel_on_packet(s, jnp.full((1,), t, jnp.int64), sojourn, mask)
        assert not bool(drop[0])
        t += 10 * MS
    assert not bool(s.dropping[0])


def test_codel_recovers_when_delay_clears():
    s = codel_init(1)
    mask = jnp.ones((1,), bool)
    over = jnp.full((1,), TARGET_NS * 3, jnp.int64)
    under = jnp.full((1,), 0, jnp.int64)
    t = 0
    for _ in range(25):
        s, _ = codel_on_packet(s, jnp.full((1,), t, jnp.int64), over, mask)
        t += 10 * MS
    assert bool(s.dropping[0])
    s, drop = codel_on_packet(s, jnp.full((1,), t, jnp.int64), under, mask)
    assert not bool(drop[0])
    assert not bool(s.dropping[0])
    assert int(s.first_above[0]) == 0


def test_codel_drop_rate_accelerates():
    """While dropping persists, inter-drop gaps shrink (INTERVAL/sqrt(count))."""
    s = codel_init(4)
    mask = jnp.ones((4,), bool)
    sojourn = jnp.full((4,), TARGET_NS * 4, jnp.int64)
    drop_times = []
    t = 0
    for _ in range(400):
        s, drop = codel_on_packet(s, jnp.full((4,), t, jnp.int64), sojourn, mask)
        if bool(drop[0]):
            drop_times.append(t)
        t += 5 * MS
    gaps = np.diff(drop_times)
    assert len(gaps) > 5
    assert gaps[-1] < gaps[1]  # accelerating
