"""Shared test harness: build and run small sims directly against the engine
(the config->sim builder layer has its own tests)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from shadow_tpu.core import Engine, EngineConfig, EngineParams
from shadow_tpu.models import get_model
from shadow_tpu.net import TBParams


def build_sim(
    model_name: str,
    hosts: list[dict],
    stop: int,
    world: int = 1,
    latency: int = 50_000_000,
    loss: float = 0.0,
    bw_bits: int = 0,
    qcap: int = 32,
    sends_budget: int = 8,
    seed: int = 1,
    runahead_floor: int = 1_000_000,
    use_codel: bool = True,
    cpu_delay_ns: int = 0,
    jitter: int = 0,
    exchange: str = "gather",
    queue_block: int = 0,
    microstep_events: int = 1,
    trace_rounds: int = 0,
    netobs: bool = False,
    flow_records: int = 0,
    integrity: bool = False,
    integrity_dual: bool | None = None,
    merge_rows: int = 0,
    faults: dict | None = None,
    bootstrap_end: int = 0,
    rounds_per_chunk: int = 64,
    microstep_limit: int = 0,
    wheel_slots: int = 0,
    wheel_block: int = 0,
    merge_scatter: bool = False,
    fluid: dict | None = None,
):
    """(cfg, model, params, model_state, initial_events) — shared between the
    device engine runner and the golden reference runner so both see byte-
    identical inputs. `faults` is a `faults:` config dict (FaultOptions
    schema) compiled through the same core/faults path the drivers use;
    `fluid` likewise a `fluid:` config dict (FluidOptions schema) compiled
    through net/fluid.compile_fluid onto the harness's single-node graph
    (every zone id must be 0)."""
    h = len(hosts)
    fault_sched = None
    fault_kw = {}
    if faults:
        from shadow_tpu.config.options import FaultOptions
        from shadow_tpu.core.faults import compile_faults

        fault_sched = compile_faults(
            FaultOptions.from_dict(faults),
            num_hosts=h, stop_time=stop, default_seed=seed,
            bootstrap_end=bootstrap_end,
            name_to_id={d.get("name", f"h{i}"): i
                        for i, d in enumerate(hosts)},
        )
        fault_kw = dict(
            fault_crash_windows=fault_sched.crash_windows,
            fault_loss_windows=fault_sched.loss_windows,
            fault_queue_clear=fault_sched.queue_clear,
        )
    fluid_sched = None
    fluid_kw = {}
    if fluid:
        from shadow_tpu.config.options import FluidOptions
        from shadow_tpu.net.fluid import compile_fluid

        fluid_sched = compile_fluid(
            FluidOptions.from_dict(fluid),
            num_links=1, default_seed=seed,
        )
        if fluid_sched.active:
            fluid_kw = dict(
                fluid_classes=fluid_sched.classes,
                fluid_links=fluid_sched.links,
                fluid_tau_ns=fluid_sched.tau_ns,
                fluid_util_threshold=fluid_sched.util_threshold,
                fluid_loss_max=fluid_sched.loss_max,
                fluid_lat_max_x1000=fluid_sched.lat_max_x1000,
                fluid_seed=fluid_sched.seed,
            )
    cfg = EngineConfig(
        num_hosts=h,
        stop_time=stop,
        bootstrap_end_time=bootstrap_end,
        runahead_floor=runahead_floor,
        static_min_latency=latency,
        queue_capacity=qcap,
        queue_block=queue_block,
        sends_per_host_round=sends_budget,
        max_round_inserts=qcap,
        rounds_per_chunk=rounds_per_chunk,
        microstep_limit=microstep_limit,
        world=world,
        use_codel=use_codel,
        cpu_delay_ns=cpu_delay_ns,
        use_jitter=jitter > 0,
        exchange=exchange,
        microstep_events=microstep_events,
        trace_rounds=trace_rounds,
        netobs=netobs,
        flow_records=flow_records,
        # integrity sentinel: dual digest rides along by default when the
        # guards are on (the drivers' IntegrityOptions.dual_digest default)
        integrity=integrity,
        integrity_dual=(
            integrity if integrity_dual is None else integrity_dual
        ),
        merge_rows=merge_rows,
        wheel_slots=wheel_slots,
        wheel_block=wheel_block,
        merge_scatter=merge_scatter,
        **fault_kw,
        **fluid_kw,
    )
    model = get_model(model_name)()
    mparams, mstate, events = model.build(hosts, seed=seed)
    params = EngineParams(
        node_of=jnp.zeros((h,), jnp.int32),
        lat_ns=jnp.full((1, 1), latency, jnp.int64),
        loss=jnp.full((1, 1), loss, jnp.float32),
        jitter_ns=jnp.full((1, 1), jitter, jnp.int64),
        eg_tb=TBParams(
            capacity=jnp.full((h,), 30_000, jnp.int64),
            refill=jnp.full((h,), bw_bits // 1000, jnp.int64),
        ),
        in_tb=TBParams(
            capacity=jnp.full((h,), 30_000, jnp.int64),
            refill=jnp.full((h,), bw_bits // 1000, jnp.int64),
        ),
        model=mparams,
        faults=fault_sched.params if fault_sched is not None else None,
        fluid=(
            fluid_sched.params
            if fluid_sched is not None and fluid_sched.active else None
        ),
    )
    return cfg, model, params, mstate, events


def run_golden_sim(model_name: str, hosts: list[dict], stop: int, seed: int = 1, **kw):
    from shadow_tpu.core.golden import run_golden

    cfg, model, params, mstate, events = build_sim(
        model_name, hosts, stop, world=1, seed=seed, **kw
    )
    return run_golden(cfg, model, params, mstate, events, seed=seed)


def run_sim(
    model_name: str,
    hosts: list[dict],
    stop: int,
    world: int = 1,
    seed: int = 1,
    **kw,
):
    cfg, model, params, mstate, events = build_sim(
        model_name, hosts, stop, world=world, seed=seed, **kw
    )
    mesh = None
    if world > 1:
        mesh = jax.sharding.Mesh(np.array(jax.devices()[:world]), ("hosts",))
    eng = Engine(cfg, model, mesh)
    state, params = eng.init_state(params, mstate, events, seed=seed)
    chunks = 0
    while not bool(state.done):
        state = eng.run_chunk(state, params)
        chunks += 1
        assert chunks < 500, "simulation failed to terminate"
    stats = jax.device_get(state.stats)
    report = model.report(jax.device_get(state.model), hosts)
    return state, stats, report


def mk_hosts(n: int, model_args=None, **extra) -> list[dict]:
    return [
        {
            "host_id": i,
            "name": f"h{i}",
            "start_time": 0,
            "model_args": dict(model_args or {}),
            **extra,
        }
        for i in range(n)
    ]
