"""Native managed-process plane tests: real Linux binaries co-opted via
LD_PRELOAD shim + seccomp/SIGSYS + shared-memory futex channels (reference
L0: src/lib/shim, managed_thread.rs; SURVEY.md §3.2-3.3)."""

from __future__ import annotations

import os
import subprocess

import pytest

from shadow_tpu.host import CpuHost, HostConfig

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TEST_APP = os.path.join(REPO, "native", "build", "test_app")
TEST_BUSY = os.path.join(REPO, "native", "build", "test_busy")

SEC = 1_000_000_000


def run_one(argv, seed=4, until=5 * SEC, start_time=0):
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=seed, host_id=0))
    p = spawn_native(h, argv, start_time=start_time)
    h.execute(until)
    return h, p


def test_simulated_clock_and_nanosleep():
    _, p = run_one([TEST_APP, "3"])
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0
    assert "start t=0\n" in out
    assert "tick 0 t=250000000" in out
    assert "tick 1 t=500000000" in out
    assert "tick 2 t=750000000" in out
    assert "end t=750000000" in out


def test_busy_loop_consumes_zero_simulated_time():
    _, p = run_one([TEST_BUSY])
    assert p.exit_code == 0, b"".join(p.stdout) + b"".join(p.stderr)
    assert "delta_ns=0" in b"".join(p.stdout).decode()


def test_native_determinism_and_seed():
    a = run_one([TEST_APP, "2"])[1]
    b = run_one([TEST_APP, "2"])[1]
    assert b"".join(a.stdout) == b"".join(b.stdout)
    c = run_one([TEST_APP, "2"], seed=99)[1]
    assert b"".join(a.stdout) != b"".join(c.stdout)  # getrandom differs


def test_two_processes_interleave_in_sim_time():
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    p1 = spawn_native(h, [TEST_APP, "2"])
    p2 = spawn_native(h, [TEST_APP, "2"], start_time=100_000_000)
    h.execute(5 * SEC)
    assert p1.exit_code == 0 and p2.exit_code == 0
    out2 = b"".join(p2.stdout).decode()
    assert "start t=100000000" in out2  # started 100ms late in sim time
    assert "tick 0 t=350000000" in out2


def test_start_time_and_exit_code():
    _, p = run_one([TEST_APP, "0"], start_time=1 * SEC)
    assert p.exit_code == 0
    assert "start t=1000000000" in b"".join(p.stdout).decode()


def test_shim_noop_outside_simulator():
    """Without SHADOW_SHM_PATH the preloaded shim must stand down."""
    env = dict(os.environ)
    env["LD_PRELOAD"] = os.path.join(REPO, "native", "build", "libshadow_shim.so")
    env.pop("SHADOW_SHM_PATH", None)
    r = subprocess.run([TEST_APP, "0"], env=env, capture_output=True, timeout=30)
    assert r.returncode == 0
    assert b"start t=" in r.stdout  # real clock, but it ran fine


def test_native_binary_via_config():
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "2 s", "seed": 12},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "box": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": TEST_APP,
                            "args": ["2"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                }
            },
        }
    )
    sim = HybridSimulation(cfg)
    report = sim.run()
    assert report["process_failures"] == 0
    proc = sim.procs[0]
    assert "tick 1 t=500000000" in b"".join(proc.stdout).decode()


def test_regular_file_write_passthrough(tmp_path):
    """write/writev to a natively-opened regular file must pass through
    (advisor finding: fell to ENOSYS while the read path passed through)."""
    out_path = str(tmp_path / "fw.out")
    _, p = run_one(
        [os.path.join(REPO, "native", "build", "test_filewrite"), out_path]
    )
    assert p.exit_code == 0, b"".join(p.stdout) + b"".join(p.stderr)
    assert b"roundtrip: hello file world" in b"".join(p.stdout)


TEST_THREADS = os.path.join(REPO, "native", "build", "test_threads")


def test_pthreads_create_join_mutex_condvar():
    """Multi-threaded managed process: clone trampoline, per-thread IPC
    slots, emulated futex (mutex + condvar + join), per-thread sleeps in
    simulated time (reference src/test/threads + src/test/clone)."""
    _, p = run_one([TEST_THREADS])
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "worker 0: counter=1 t=10ms" in out
    assert "worker 1: counter=3 t=20ms" in out
    assert "worker 2: counter=6 t=30ms" in out
    assert "worker 3: counter=10 t=40ms" in out
    assert "main: joined counter=10 retsum=42 t=40ms" in out


def test_pthreads_two_runs_identical():
    a = run_one([TEST_THREADS])[1]
    b = run_one([TEST_THREADS])[1]
    assert p_out(a) == p_out(b)


def p_out(p):
    return b"".join(p.stdout) + b"".join(p.stderr)


TEST_FORK = os.path.join(REPO, "native", "build", "test_fork")


def test_fork_udp_server_and_wait4():
    """fork(): child gets its own IPC block + virtual pid, inherits the fd
    table, talks to the parent over an emulated UDP socket, and is reaped
    with wait4 (status plumbed). Reference: handler/process.rs fork +
    src/test/clone."""
    h, p = run_one([TEST_FORK])
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert 'parent: got "hello-from-child ppid_ok=1" t=30ms' in out
    assert "parent: reaped match=1 exit=7 t=30ms" in out
    # the fork child ran as its own process object on the host
    kids = [q for q in h.processes.values() if q.name.endswith(".f1")]
    assert len(kids) == 1 and kids[0].exit_code == 7


def test_fork_two_runs_identical():
    a = run_one([TEST_FORK])[1]
    b = run_one([TEST_FORK])[1]
    assert p_out(a) == p_out(b)


TEST_CHURN = os.path.join(REPO, "native", "build", "test_thread_churn")


def test_thread_slot_recycling():
    """40 sequential create/join cycles > 32 IPC slots: slots must recycle
    after clean thread exit, and clone handshakes serialize correctly."""
    _, p = run_one([TEST_CHURN], until=10 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "churn done counter=40 t=40ms" in out


TEST_SIGNAL = os.path.join(REPO, "native", "build", "test_signal")


def test_signals_kill_itimer_pause():
    """Cross-process kill -> handler at syscall boundary + EINTR'd
    nanosleep; periodic ITIMER_REAL against pause(); SIGTERM default
    action terminates a child (reference src/test/signal, src/test/itimer)."""
    _, p = run_one([TEST_SIGNAL], until=10 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "parent: usr1=1 sleep_interrupted=1 t=20ms" in out
    assert "parent: alrm=5 t=70ms" in out
    assert "parent: child_reaped=1 t=70ms" in out


def test_signals_two_runs_identical():
    a = run_one([TEST_SIGNAL], until=10 * SEC)[1]
    b = run_one([TEST_SIGNAL], until=10 * SEC)[1]
    assert p_out(a) == p_out(b)


TEST_BUSYCLOCK = os.path.join(REPO, "native", "build", "test_busyclock")


def test_unblocked_syscall_latency_model():
    """A spin-on-clock binary makes simulated progress when the
    unblocked-syscall latency model is on (reference
    handler/mod.rs:268-318): every Nth locally-answered time call escapes
    to the simulator and is charged latency."""
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0,
                           model_unblocked_latency=True))
    p = spawn_native(h, [TEST_BUSYCLOCK], start_time=0)
    h.execute(5 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "busyclock done spins=5119999" in out  # deterministic count


TEST_NEST = os.path.join(REPO, "native", "build", "test_thread_nest")


def test_nested_concurrent_thread_creation():
    """Workers spawning sub-workers: clone handshakes from different
    threads must serialize through the single in-flight bootstrap."""
    for _ in range(3):  # race-sensitive: a few repeats
        _, p = run_one([TEST_NEST], until=5 * SEC)
        out = b"".join(p.stdout).decode()
        assert p.exit_code == 0, out + b"".join(p.stderr).decode()
        assert "nest done total=12" in out


TEST_DET = os.path.join(REPO, "native", "build", "test_determinism")


def test_rdtsc_rng_aslr_determinism():
    """rdtsc/rdtscp trap to sim time (7ms sleep == 7e6 ticks at the nominal
    1 GHz), /dev/urandom + getrandom come from the seeded host RNG, ASLR is
    off (stable stack address). Two runs byte-identical; seed changes RNG
    output. (Reference shim_rdtsc.c + preload-openssl + ASLR disable.)"""
    a = run_one([TEST_DET])[1]
    out = b"".join(a.stdout).decode()
    assert a.exit_code == 0, out + b"".join(a.stderr).decode()
    assert "tsc start=0 delta=7000000\n" in out
    assert "stackaddr=0x" in out  # exact value is env-size dependent; the
    # determinism claim is the two-run equality below
    b = run_one([TEST_DET])[1]
    assert p_out(a) == p_out(b)
    c = run_one([TEST_DET], seed=99)[1]
    assert p_out(a) != p_out(c)


def test_vm_multi_null_iovec_is_efault():
    """Regression (r3 advisor): a NULL iov_base with nonzero length must be
    EFAULT (kernel contract), not silently skipped — skipping shifted
    subsequent bytes into the wrong iovec on readv/recvmsg paths."""
    import ctypes
    import errno

    from shadow_tpu.native_plane import _vm_read_multi, _vm_write_multi

    buf = ctypes.create_string_buffer(b"hello", 5)
    addr = ctypes.addressof(buf)
    pid = os.getpid()
    assert _vm_read_multi(pid, [(addr, 5)]) == b"hello"
    with pytest.raises(OSError) as e:
        _vm_read_multi(pid, [(addr, 5), (0, 3)])
    assert e.value.errno == errno.EFAULT
    with pytest.raises(OSError) as e:
        _vm_write_multi(pid, [(0, 3), (addr, 5)], b"abc")
    assert e.value.errno == errno.EFAULT
    # zero-length NULL iovec stays legal (kernel ignores it)
    assert _vm_read_multi(pid, [(addr, 5), (0, 0)]) == b"hello"


def test_memory_mapper_window():
    """r4 MemoryMapper (reference memory_mapper.rs): the shim remaps the
    child's heap onto a shared tmpfs file; the simulator serves heap reads
    from its own mapping. The window must register and byte-match the
    process_vm path over the same range."""
    import struct

    from shadow_tpu import native_plane as nplane

    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    p = spawn_native(h, [TEST_APP, "1000"])
    h.execute(1)  # boot; child parks in its first nanosleep
    cpid = p._child.pid
    w = nplane._HEAP_WINDOWS.get(cpid)
    assert w is not None, "heap window did not register"
    start, cur = struct.unpack_from("<QQ", w[0], nplane.HEAP_START_OFF)
    assert cur > start > 0
    n = min(cur - start, 32768)
    assert nplane._heap_loc(cpid, start, n) is not None
    via_window = nplane._vm_read(cpid, start, n)
    saved = nplane._HEAP_WINDOWS.pop(cpid)  # force the kernel path
    try:
        via_kernel = nplane._vm_read(cpid, start, n)
    finally:
        nplane._HEAP_WINDOWS[cpid] = saved
    assert via_window == via_kernel
    assert len(via_window) == n
    p.kill()
