"""Work-stealing host scheduler (VERDICT r4 #8; reference
thread_per_core.rs:25-210 — per-thread queues + steal-on-idle) and the
serial-vs-parallel determinism gate."""

from __future__ import annotations

import os
import threading
import time

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork
from shadow_tpu.host.scheduler import WorkStealingPool

MS = 1_000_000
SEC = 1_000_000_000


def test_steals_rebalance_synthetic_skew():
    """Round-robin gives worker 0 one pathological item and worker 1 many
    quick ones... here inverted: ALL the slow work lands on one worker's
    queue; the other must steal it. (Synthetic skew on a 1-core box: the
    sleeps release the GIL, so stealing shows up as wall-time overlap.)"""
    pool = WorkStealingPool(2)
    done_by: dict[int, str] = {}
    lock = threading.Lock()

    # 8 items; round-robin puts 0,2,4,6 on worker 0 and 1,3,5,7 on worker
    # 1 — but worker 1's items finish instantly (no sleep), so it steals
    def work_skewed(i):
        if i % 2 == 0:
            time.sleep(0.03)
        with lock:
            done_by[i] = threading.current_thread().name

    pool.run(range(8), work_skewed)
    pool.shutdown()
    assert len(done_by) == 8
    workers = set(done_by.values())
    assert len(workers) == 2, f"one worker did everything: {done_by}"
    assert pool.steals > 0, "no steal ever happened under skew"
    # the slow (even) items ended up split across BOTH workers
    slow_workers = {done_by[i] for i in (0, 2, 4, 6)}
    assert len(slow_workers) == 2


def test_empty_round_and_reuse():
    pool = WorkStealingPool(3)
    pool.run([], lambda x: None)  # empty round must not wedge
    out = []
    for _ in range(5):  # rounds are reusable back to back
        pool.run(range(7), lambda i: out.append(i))
    pool.shutdown()
    assert len(out) == 35


def test_serial_vs_parallel_byte_identical():
    """The determinism gate (reference determinism suite, two schedulers):
    the SAME native workload on 1 worker vs 4 workers produces
    byte-identical process output and host counters."""
    from shadow_tpu.native_plane import ensure_built, spawn_native

    if not ensure_built():
        pytest.skip("native toolchain unavailable")
    repo = os.path.join(os.path.dirname(__file__), "..")
    udp_echo = os.path.join(repo, "native", "build", "test_udp_echo")
    udp_client = os.path.join(repo, "native", "build", "test_udp_client")

    def once(workers: int):
        hosts = [
            CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=5,
                               host_id=i))
            for i in range(4)
        ]
        net = CpuNetwork(hosts, latency_ns=lambda s, d: 15 * MS,
                         workers=workers)
        srv = spawn_native(hosts[0], [udp_echo, "9000", "6"])
        clis = [
            spawn_native(
                hosts[i], [udp_client, "10.0.0.1", "9000", "2"],
                start_time=i * 10 * MS,
            )
            for i in (1, 2, 3)
        ]
        net.run(5 * SEC)
        return (
            tuple(b"".join(c.stdout) for c in clis),
            b"".join(srv.stdout),
            tuple(tuple(sorted(h.counters.items())) for h in hosts),
        )

    assert once(1) == once(4)


def test_worker_exception_propagates_instead_of_hanging():
    pool = WorkStealingPool(2)

    def boom(i):
        if i == 3:
            raise RuntimeError("host exploded")

    with pytest.raises(RuntimeError, match="host exploded"):
        pool.run(range(6), boom)
    # the pool survives for the next round
    out = []
    pool.run(range(4), lambda i: out.append(i))
    pool.shutdown()
    assert len(out) == 4
