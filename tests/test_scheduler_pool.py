"""Host-plane schedulers (reference scheduler crate): work stealing
(thread_per_core.rs:25-210 — per-thread queues + steal-on-idle),
thread-per-host (thread_per_host.rs:25-60 — dedicated thread, bounded
parallelism), CPU pinning (core/affinity.c), and the serial-vs-parallel
determinism gate."""

from __future__ import annotations

import os
import threading
import time

import pytest

from shadow_tpu.host import CpuHost, HostConfig, affinity
from shadow_tpu.host.network import CpuNetwork
from shadow_tpu.host.scheduler import ThreadPerHostPool, WorkStealingPool

MS = 1_000_000
SEC = 1_000_000_000


def test_steals_rebalance_synthetic_skew():
    """Round-robin gives worker 0 one pathological item and worker 1 many
    quick ones... here inverted: ALL the slow work lands on one worker's
    queue; the other must steal it. (Synthetic skew on a 1-core box: the
    sleeps release the GIL, so stealing shows up as wall-time overlap.)"""
    pool = WorkStealingPool(2)
    done_by: dict[int, str] = {}
    lock = threading.Lock()

    # 8 items; round-robin puts 0,2,4,6 on worker 0 and 1,3,5,7 on worker
    # 1 — but worker 1's items finish instantly (no sleep), so it steals
    def work_skewed(i):
        if i % 2 == 0:
            time.sleep(0.03)
        with lock:
            done_by[i] = threading.current_thread().name

    pool.run(range(8), work_skewed)
    pool.shutdown()
    assert len(done_by) == 8
    workers = set(done_by.values())
    assert len(workers) == 2, f"one worker did everything: {done_by}"
    assert pool.steals > 0, "no steal ever happened under skew"
    # the slow (even) items ended up split across BOTH workers
    slow_workers = {done_by[i] for i in (0, 2, 4, 6)}
    assert len(slow_workers) == 2


def test_empty_round_and_reuse():
    pool = WorkStealingPool(3)
    pool.run([], lambda x: None)  # empty round must not wedge
    out = []
    for _ in range(5):  # rounds are reusable back to back
        pool.run(range(7), lambda i: out.append(i))
    pool.shutdown()
    assert len(out) == 35


def test_serial_vs_parallel_byte_identical():
    """The determinism gate (reference determinism suite, two schedulers):
    the SAME native workload on 1 worker vs 4 workers produces
    byte-identical process output and host counters."""
    from shadow_tpu.native_plane import spawn_native
    from tests.subproc import native_plane_skip_reason

    reason = native_plane_skip_reason()
    if reason is not None:
        pytest.skip(reason)
    repo = os.path.join(os.path.dirname(__file__), "..")
    udp_echo = os.path.join(repo, "native", "build", "test_udp_echo")
    udp_client = os.path.join(repo, "native", "build", "test_udp_client")

    def once(workers: int):
        hosts = [
            CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=5,
                               host_id=i))
            for i in range(4)
        ]
        net = CpuNetwork(hosts, latency_ns=lambda s, d: 15 * MS,
                         workers=workers)
        srv = spawn_native(hosts[0], [udp_echo, "9000", "6"])
        clis = [
            spawn_native(
                hosts[i], [udp_client, "10.0.0.1", "9000", "2"],
                start_time=i * 10 * MS,
            )
            for i in (1, 2, 3)
        ]
        net.run(5 * SEC)
        return (
            tuple(b"".join(c.stdout) for c in clis),
            b"".join(srv.stdout),
            tuple(tuple(sorted(h.counters.items())) for h in hosts),
        )

    assert once(1) == once(4)


def test_per_host_pool_thread_stability():
    """thread_per_host.rs's core contract: a host runs on the SAME
    dedicated thread every round, for its whole lifetime."""

    class FakeHost:
        def __init__(self, hid):
            self.host_id = hid

    hosts = [FakeHost(i) for i in range(6)]
    pool = ThreadPerHostPool(parallelism=2)
    seen: dict[int, set[int]] = {h.host_id: set() for h in hosts}
    lock = threading.Lock()

    def work(h):
        with lock:
            seen[h.host_id].add(threading.get_ident())

    for _ in range(8):
        pool.run(hosts, work)
    assert pool.thread_count == 6  # one dedicated thread per host
    pool.shutdown()
    for hid, tids in seen.items():
        assert len(tids) == 1, f"host {hid} migrated threads: {tids}"
    # distinct hosts got distinct threads
    all_tids = [next(iter(t)) for t in seen.values()]
    assert len(set(all_tids)) == 6


def test_per_host_pool_default_host_ids_still_get_distinct_threads():
    """Hosts left at the default host_id (0) must NOT collapse onto one
    thread — keying is by object identity (review catch)."""

    class FakeHost:
        host_id = 0  # everyone at the default

    hosts = [FakeHost() for _ in range(4)]
    pool = ThreadPerHostPool(parallelism=4)
    tids: dict[int, int] = {}
    lock = threading.Lock()

    def work(h):
        with lock:
            tids[id(h)] = threading.get_ident()

    pool.run(hosts, work)
    pool.shutdown()
    assert pool.thread_count == 4
    assert len(set(tids.values())) == 4


def test_per_host_pool_parallelism_bound():
    """The semaphore bounds how many hosts RUN concurrently even though
    every host has its own thread (ParallelismBoundedThreadPool)."""
    pool = ThreadPerHostPool(parallelism=2)
    running = 0
    peak = 0
    lock = threading.Lock()

    class FakeHost:
        def __init__(self, hid):
            self.host_id = hid

    def work(_h):
        nonlocal running, peak
        with lock:
            running += 1
            peak = max(peak, running)
        time.sleep(0.01)  # off-GIL so concurrency is real
        with lock:
            running -= 1

    pool.run([FakeHost(i) for i in range(8)], work)
    pool.shutdown()
    assert peak <= 2, f"parallelism bound violated: peak={peak}"


def test_per_host_pool_exception_propagates():
    class FakeHost:
        def __init__(self, hid):
            self.host_id = hid

    pool = ThreadPerHostPool(parallelism=4)
    hosts = [FakeHost(i) for i in range(5)]

    def boom(h):
        if h.host_id == 2:
            raise RuntimeError("host exploded")

    with pytest.raises(RuntimeError, match="host exploded"):
        pool.run(hosts, boom)
    out = []
    pool.run(hosts, lambda h: out.append(h.host_id))
    pool.shutdown()
    assert sorted(out) == [0, 1, 2, 3, 4]


def test_serial_vs_per_host_byte_identical():
    """Determinism gate for the thread-per-host policy: same workload,
    serial vs per-host threads, byte-identical output."""
    from shadow_tpu.native_plane import spawn_native
    from tests.subproc import native_plane_skip_reason

    reason = native_plane_skip_reason()
    if reason is not None:
        pytest.skip(reason)
    repo = os.path.join(os.path.dirname(__file__), "..")
    udp_echo = os.path.join(repo, "native", "build", "test_udp_echo")
    udp_client = os.path.join(repo, "native", "build", "test_udp_client")

    def once(workers: int, sched: str):
        hosts = [
            CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=5,
                               host_id=i))
            for i in range(4)
        ]
        net = CpuNetwork(hosts, latency_ns=lambda s, d: 15 * MS,
                         workers=workers, scheduler=sched)
        srv = spawn_native(hosts[0], [udp_echo, "9000", "6"])
        clis = [
            spawn_native(
                hosts[i], [udp_client, "10.0.0.1", "9000", "2"],
                start_time=i * 10 * MS,
            )
            for i in (1, 2, 3)
        ]
        net.run(5 * SEC)
        return (
            tuple(b"".join(c.stdout) for c in clis),
            b"".join(srv.stdout),
            tuple(tuple(sorted(h.counters.items())) for h in hosts),
        )

    assert once(1, "steal") == once(2, "per-host")


def test_affinity_assign_packs_cores_first():
    """affinity.c's greedy on a synthetic 2-node, 4-core, 8-cpu (SMT)
    machine: workers land on distinct physical cores before any
    hyperthread sibling is reused, alternating NUMA nodes stay balanced."""
    cpus = [
        # node 0, socket 0: cores 0,1; SMT siblings 4,5
        affinity.CpuInfo(cpu=0, core=0, socket=0, node=0),
        affinity.CpuInfo(cpu=1, core=1, socket=0, node=0),
        affinity.CpuInfo(cpu=4, core=0, socket=0, node=0),
        affinity.CpuInfo(cpu=5, core=1, socket=0, node=0),
        # node 1, socket 1: cores 2,3; SMT siblings 6,7
        affinity.CpuInfo(cpu=2, core=2, socket=1, node=1),
        affinity.CpuInfo(cpu=3, core=3, socket=1, node=1),
        affinity.CpuInfo(cpu=6, core=2, socket=1, node=1),
        affinity.CpuInfo(cpu=7, core=3, socket=1, node=1),
    ]
    got = affinity.assign(8, cpus)
    # all 8 logical cpus used exactly once before any repeats
    assert sorted(got) == list(range(8))
    # the first 4 workers cover 4 DISTINCT physical cores
    by_cpu = {c.cpu: c for c in cpus}
    first4 = {(by_cpu[c].node, by_cpu[c].socket, by_cpu[c].core)
              for c in got[:4]}
    assert len(first4) == 4, f"SMT sibling reused early: {got[:4]}"
    # nodes alternate (load balance at node level)
    nodes = [by_cpu[c].node for c in got[:4]]
    assert sorted(nodes) == [0, 0, 1, 1]


def test_per_host_pinning_follows_running_slot():
    """With pinning on, the CPUs occupied at any instant are the
    parallelism slots' CPUs — concurrently-admitted hosts never share a
    pinned CPU while an assigned CPU sits idle. (Single-CPU box: assert
    the slot free-list mechanics rather than real placement.)"""

    class FakeHost:
        def __init__(self, hid):
            self.host_id = hid

    pool = ThreadPerHostPool(parallelism=2, pin_cpus=[0, 0])
    in_flight_cpus: list[int] = []
    lock = threading.Lock()

    def work(_h):
        with lock:
            # while running, this host's slot CPU is OUT of the free list
            in_flight_cpus.append(len(pool._free_cpus))
        time.sleep(0.005)

    pool.run([FakeHost(i) for i in range(6)], work)
    pool.shutdown()
    # every observation saw <= parallelism CPUs checked out, and at least
    # one observation saw a CPU checked out at all
    assert all(0 <= n <= 2 for n in in_flight_cpus)
    assert min(in_flight_cpus) < 2
    assert len(pool._free_cpus) == 2  # all returned after the round


def test_make_pool_rejects_unknown_policy():
    from shadow_tpu.host.scheduler import make_pool

    with pytest.raises(ValueError, match="per-host"):
        make_pool("per_host", 2)  # typo'd underscore must not silently steal
    with pytest.raises(ValueError, match="scheduler"):
        CpuNetwork([], latency_ns=lambda s, d: 1, scheduler="bogus")


def test_affinity_assign_more_workers_than_cpus():
    cpus = [affinity.CpuInfo(cpu=0, core=0, socket=0, node=0)]
    assert affinity.assign(3, cpus) == [0, 0, 0]
    assert affinity.assign(2, []) == [0, 0]


def test_affinity_topology_and_pin_on_this_box():
    """Smoke the real sysfs parse + a real pin on whatever this box has."""
    cpus = affinity.topology()
    assert cpus, "topology() returned no CPUs"
    allowed = set(os.sched_getaffinity(0))
    assert {c.cpu for c in cpus} <= allowed
    target = affinity.assign(1, cpus)[0]
    try:
        assert affinity.pin_current(target) is True
        assert os.sched_getaffinity(0) == {target}
    finally:
        os.sched_setaffinity(0, allowed)  # restore even on assert failure


def test_worker_exception_propagates_instead_of_hanging():
    pool = WorkStealingPool(2)

    def boom(i):
        if i == 3:
            raise RuntimeError("host exploded")

    with pytest.raises(RuntimeError, match="host exploded"):
        pool.run(range(6), boom)
    # the pool survives for the next round
    out = []
    pool.run(range(4), lambda i: out.append(i))
    pool.shutdown()
    assert len(out) == 4
