"""TCP state-machine tests (capability mirror of src/lib/tcp/src/tests/)."""

from __future__ import annotations

import os
import random

import pytest

from shadow_tpu.tcp import (
    ACK,
    FIN,
    RST,
    SYN,
    RenoCongestion,
    RttEstimator,
    Segment,
    State,
    TcpConfig,
    TcpError,
    TcpState,
)
from shadow_tpu.tcp.buffers import RecvBuffer, SendBuffer
from shadow_tpu.tcp.seq import MOD, seq_diff, seq_gt, seq_lt, wrapping_add

from tcp_harness import MS, Wire, handshake, transfer


# ---------------------------------------------------------------- seq math


def test_seq_wraparound():
    near_top = MOD - 5
    assert wrapping_add(near_top, 10) == 5
    assert seq_lt(near_top, 5)  # 5 is "after" near_top across the wrap
    assert seq_gt(5, near_top)
    assert seq_diff(5, near_top) == 10
    assert seq_diff(near_top, 5) == -10


# ----------------------------------------------------------------- buffers


def test_send_buffer_ack_slice():
    b = SendBuffer(100)
    assert b.write(b"hello world") == 11
    assert b.slice(0, 5) == b"hello"
    assert b.slice(6, 5) == b"world"
    assert b.ack_to(6) == 6
    assert b.una_off == 6
    assert b.slice(6, 5) == b"world"
    assert b.write(b"x" * 1000) == 100 - 5  # capacity clamp


def test_recv_buffer_out_of_order_reassembly():
    b = RecvBuffer(1000)
    nxt = 0
    nxt = b.insert(nxt, 5, b"56789")  # hole at [0,5)
    assert nxt == 0 and b.readable() == 0
    nxt = b.insert(nxt, 0, b"01234")
    assert nxt == 10
    assert b.read(100) == b"0123456789"


def test_recv_buffer_overlap_dup():
    b = RecvBuffer(1000)
    nxt = b.insert(0, 0, b"abcdef")
    nxt = b.insert(nxt, 3, b"defghi")  # overlapping retransmit
    assert nxt == 9
    assert b.read(100) == b"abcdefghi"


# --------------------------------------------------------------- handshake


def test_three_way_handshake():
    c, s, w = handshake()
    assert c.state == State.ESTABLISHED
    assert s.state == State.ESTABLISHED
    # options negotiated both ways
    assert c.mss == s.mss == 1460
    assert c.snd_wscale == s.rcv_wscale
    assert s.snd_wscale == c.rcv_wscale


def test_listener_ignores_non_syn():
    lst = TcpState(TcpConfig(), iss=0)
    lst.listen()
    assert lst.accept_segment(0, Segment(ACK, seq=1, ack=1), child_iss=1) is None
    assert lst.accept_segment(0, Segment(RST, seq=1), child_iss=1) is None


def test_connection_refused():
    c = TcpState(TcpConfig(), iss=100)
    c.connect(0)
    syn = c.poll_segments(0)[0]
    # closed peer answers RST|ACK (rst_for); deliver it back
    from shadow_tpu.tcp.state import rst_for

    rst = rst_for(syn)
    assert rst.flags & RST
    c.on_segment(MS, rst)
    assert c.state == State.CLOSED
    assert c.error == TcpError.REFUSED


def test_simultaneous_open():
    cfg = TcpConfig()
    a, b = TcpState(cfg, iss=10), TcpState(cfg, iss=20)
    a.connect(0)
    b.connect(0)
    syn_a = a.poll_segments(0)[0]
    syn_b = b.poll_segments(0)[0]
    a.on_segment(MS, syn_b)
    b.on_segment(MS, syn_a)
    w = Wire(a, b, MS)
    w.now = MS
    w.run(until=lambda: a.state == State.ESTABLISHED and b.state == State.ESTABLISHED)


# ------------------------------------------------------------ data transfer


def test_small_transfer():
    c, s, w = handshake()
    data = b"the quick brown fox"
    assert transfer(c, s, w, data) == data


def test_large_transfer_exceeds_window_and_cwnd():
    c, s, w = handshake()
    data = os.urandom(700_000)  # > send_buf, > recv window
    assert transfer(c, s, w, data) == data


def test_bidirectional_transfer():
    c, s, w = handshake()
    d1, d2 = os.urandom(50_000), os.urandom(80_000)
    got_s = bytearray()
    got_c = bytearray()
    sent1 = sent2 = 0

    def pump():
        nonlocal sent1, sent2
        sent1 += c.send(d1[sent1:])
        sent2 += s.send(d2[sent2:])
        while r := s.recv(65536):
            got_s.extend(r)
        while r := c.recv(65536):
            got_c.extend(r)
        return len(got_s) == len(d1) and len(got_c) == len(d2)

    w.run(100_000, until=pump)
    assert bytes(got_s) == d1 and bytes(got_c) == d2


def test_transfer_with_loss_retransmits():
    random.seed(7)
    dropped = set()

    def drop(idx, src, seg):
        if seg.payload and random.random() < 0.1:
            dropped.add(idx)
            return True
        return False

    c, s, w = handshake(drop=drop)
    data = os.urandom(200_000)
    assert transfer(c, s, w, data, max_steps=200_000) == data
    assert dropped, "loss hook never fired"
    assert c.retransmits > 0


def test_fast_retransmit_on_dup_acks():
    # drop exactly one data segment early; enough later data must trigger
    # 3 dup-ACKs -> fast retransmit well before the 1s RTO
    state = {"dropped": False}

    def drop(idx, src, seg):
        if src == "a" and seg.payload and not state["dropped"]:
            state["dropped"] = True
            return True
        return False

    c, s, w = handshake(drop=drop)
    data = os.urandom(100_000)
    got = transfer(c, s, w, data, max_steps=100_000)
    assert got == data
    assert c.retransmits >= 1
    # fast retransmit implies recovery happened without full RTO stall:
    # total time must be far below the 1s minimum RTO + backoff
    assert w.now < 1_000 * MS


def test_zero_window_and_probe():
    # autotune off: this test REQUIRES the window to close (autotuning
    # would grow the buffer instead, which is its own test)
    cfg = TcpConfig(recv_buf=2000, window_scaling=False, autotune=False)
    c, s, w = handshake(cfg=cfg)
    data = os.urandom(10_000)
    sent = 0
    # don't read at the server: window must close, sender must stall
    def fill():
        nonlocal sent
        sent += c.send(data[sent:])
        return s.rcv_buf.window() == 0 and c.snd_wnd == 0

    w.run(50_000, until=fill)
    assert s.rcv_buf.readable() >= 1900
    # now drain; probes + window updates must resume the flow
    got = bytearray()

    def pump():
        nonlocal sent
        sent += c.send(data[sent:])
        while r := s.recv(65536):
            got.extend(r)
        return len(got) == len(data)

    w.run(200_000, until=pump)
    assert bytes(got) == data


# ------------------------------------------------------------------- close


def test_clean_close_sequence():
    c, s, w = handshake()
    c.close(w.now)
    w.run(until=lambda: s.rcv_fin_seen)
    assert s.state == State.CLOSE_WAIT
    assert s.recv(10) == b""  # EOF
    s.close(w.now)
    w.run(until=lambda: s.state == State.CLOSED and c.state == State.TIME_WAIT)
    # TIME_WAIT expires -> CLOSED
    w.run(until=lambda: c.state == State.CLOSED)
    assert c.error is None and s.error is None


def test_simultaneous_close():
    c, s, w = handshake()
    c.close(w.now)
    s.close(w.now)
    w.run(until=lambda: c.state == State.CLOSED and s.state == State.CLOSED)
    assert c.error is None and s.error is None


def test_close_with_pending_data_flushes_first():
    c, s, w = handshake()
    data = os.urandom(30_000)
    queued = c.send(data)
    assert queued == len(data)
    c.close(w.now)
    got = bytearray()

    def pump():
        while r := s.recv(65536):
            got.extend(r)
        return s.rcv_fin_seen and len(got) == len(data)

    w.run(100_000, until=pump)
    assert bytes(got) == data


def test_abort_sends_rst():
    c, s, w = handshake()
    c.send(b"hello")
    w.run(until=lambda: s.rcv_buf.readable() == 5)
    c.abort(w.now)
    w.run(until=lambda: s.state == State.CLOSED)
    assert s.error == TcpError.RESET
    assert c.state == State.CLOSED


def test_send_after_shutdown_raises():
    c, s, w = handshake()
    c.shutdown_write(w.now)
    with pytest.raises(BrokenPipeError):
        c.send(b"nope")


# ------------------------------------------------------------- reno + rto


def test_reno_slow_start_doubles_then_avoids():
    cc = RenoCongestion(mss=1000, initial_window_mss=2)
    assert cc.cwnd == 2000
    cc.on_ack(1000)
    assert cc.cwnd == 3000  # slow start: +MSS per ACK
    cc.ssthresh = 3000
    cc.on_ack(1000)  # now in congestion avoidance
    assert cc.cwnd == 3000  # accumulator below cwnd
    for _ in range(3):
        cc.on_ack(1000)
    assert cc.cwnd == 4000  # one full cwnd of ACKs -> +1 MSS


def test_reno_fast_recovery_cycle():
    cc = RenoCongestion(mss=1000, initial_window_mss=10)
    for _ in range(3):
        cc.on_dup_ack()
    assert cc.in_fast_recovery
    assert cc.ssthresh == 5000
    assert cc.cwnd == 5000 + 3000
    cc.on_dup_ack()
    assert cc.cwnd == 9000  # inflation
    cc.on_ack(1000)  # recovery exit
    assert not cc.in_fast_recovery
    assert cc.cwnd == 5000


def test_reno_timeout_resets_to_one_mss():
    cc = RenoCongestion(mss=1000, initial_window_mss=10)
    cc.on_retransmit_timeout()
    assert cc.cwnd == 1000
    assert cc.ssthresh == 5000


def test_rto_estimator_rfc6298():
    r = RttEstimator()
    r.on_measurement(100 * MS)
    assert r.srtt == 100 * MS
    assert r.rto == 1_000 * MS  # clamped to 1s min
    for _ in range(20):
        r.on_measurement(100 * MS)
    assert r.rttvar < 20 * MS
    r.on_timeout()
    r.on_timeout()
    assert r.current_rto() == 4 * r.rto  # exponential backoff


def test_connect_times_out():
    cfg = TcpConfig(max_retries=3)
    c = TcpState(cfg, iss=0)
    c.connect(0)
    c.poll_segments(0)
    now = 0
    for _ in range(10):
        t = c.next_timer()
        if t is None:
            break
        now = t
        c.on_timer(now)
        c.poll_segments(now)
    assert c.state == State.CLOSED
    assert c.error == TcpError.TIMED_OUT


# ------------------------------------------------- review regression tests


def test_idle_established_connection_stays_alive():
    """Post-handshake idle connection must not spuriously RTO (review: the
    SYN_SENT->ESTABLISHED path used to re-arm the timer with nothing in
    flight, killing every idle client after max_retries backoffs)."""
    c, s, w = handshake()
    assert c.next_timer() is None
    assert s.next_timer() is None
    # and a long quiet period changes nothing
    w.run(10)
    assert c.state == State.ESTABLISHED and s.state == State.ESTABLISHED
    assert c.error is None and s.error is None


def test_close_in_syn_sent_clears_timers():
    c = TcpState(TcpConfig(), iss=0)
    c.connect(0)
    c.poll_segments(0)
    c.close(0)
    assert c.state == State.CLOSED
    assert c.next_timer() is None
    assert c.error is None


def test_close_in_syn_received_eventually_fins():
    cfg = TcpConfig()
    client = TcpState(cfg, iss=1000)
    lst = TcpState(cfg, iss=0)
    lst.listen()
    client.connect(0)
    syn = client.poll_segments(0)[0]
    server = lst.accept_segment(MS, syn, child_iss=5000)
    server.close(MS)  # close while still in SYN_RECEIVED
    assert server.state == State.FIN_WAIT_1
    w = Wire(client, server, MS)
    w.now = MS
    w.run(until=lambda: client.rcv_fin_seen and server.state != State.FIN_WAIT_1)
    assert client.state == State.CLOSE_WAIT


def test_window_update_acks_are_not_dup_acks():
    c, s, w = handshake()
    c.send(b"x" * 5000)
    w.run(until=lambda: c.nxt_off > 0)
    base = c.una_off
    una_seq = c._snd_una_seq()
    # three pure ACKs with unchanged ack but growing windows (window updates)
    for wnd_field in (100, 200, 300):
        c.on_segment(w.now, Segment(ACK, seq=c.rcv_nxt, ack=una_seq, wnd=wnd_field))
    assert not c.cong.in_fast_recovery
    assert c.cong.dup_acks == 0


def test_lost_zero_window_probe_is_retransmitted():
    cfg = TcpConfig(recv_buf=1460, window_scaling=False, autotune=False)
    c, s, w = handshake(cfg=cfg)
    # fill the peer window exactly, then queue one more byte
    c.send(b"a" * 1460)
    w.run(until=lambda: c.snd_wnd == 0 and c._bytes_in_flight() == 0)
    c.send(b"z")
    assert c.poll_segments(w.now) == []  # window closed: nothing sendable yet
    # probe fires; drop it on the floor (don't deliver); the sender must
    # still hold a retransmission path for the in-flight probe byte
    deadline = c.next_timer()
    assert deadline is not None
    c.on_timer(deadline)
    segs = c.poll_segments(deadline)
    assert any(s_.payload == b"z" for s_ in segs)
    assert c.next_timer() is not None  # something will retry


def test_fin_after_hole_filled_by_retransmission():
    """A lost data segment followed by FIN: when the retransmission fills the
    hole, the receiver must still see EOF and enter CLOSE_WAIT (review: the
    buffer used to consume the FIN silently, acking it without ever setting
    rcv_fin_seen — the receiver then hung in ESTABLISHED forever)."""
    state = {"n": 0}

    def drop(idx, src, seg):
        # drop the first full-size data segment once, leaving a hole with
        # more data and the FIN queued behind it
        if src == "a" and seg.payload and len(seg.payload) > 500 and state["n"] == 0:
            state["n"] = 1
            return True
        return False

    c, s, w = handshake(drop=drop)
    c.send(os.urandom(4000))
    c.close(w.now)
    w.run(200_000, until=lambda: s.rcv_fin_seen and c.fin_acked)
    assert s.state == State.CLOSE_WAIT


# -------------------------------------------------------------- digestion


def test_transfer_deterministic():
    """Same seed + same wire => byte-identical segment trace (the TCP-level
    analogue of the determinism gate, SURVEY.md §4.3)."""

    def trace():
        c, s, w = handshake()
        data = bytes(range(256)) * 100
        transfer(c, s, w, data)
        return [(t, src, repr(seg)) for t, src, seg in w.sent]

    assert trace() == trace()


# ---------------------------------------------- advisor-round-1 regressions


def test_passive_side_third_ack_window_is_scaled():
    """RFC 7323: only SYN-flagged segments carry unscaled windows. The
    handshake-completing ACK must be scaled by snd_wscale on the passive
    side (advisor finding: it was treated as unscaled, underestimating the
    peer's window by 2^wscale until the next update)."""
    c, s, w = handshake()
    assert c.rcv_wscale > 0  # default 256 KiB recv_buf => wscale 2
    assert s.snd_wscale == c.rcv_wscale
    # the third ACK advertised (client window >> wscale); the server's view
    # must be the re-scaled value, i.e. within one scale-quantum of the
    # client's real window, not 4x smaller
    real = c.rcv_buf.window()
    assert s.snd_wnd >= real - (1 << c.rcv_wscale)
    assert s.snd_wnd > 0xFFFF  # impossible if the shift was dropped


def test_late_ack_after_rto_rewind_advances_una():
    """An ACK covering data transmitted before an RTO go-back-N rewind must
    advance una_off/send-buffer even though nxt_off was rewound (advisor
    finding: capped at nxt_off - una_off, i.e. zero after rewind)."""
    # delayed_ack off: this test hand-delivers segments with no timer
    # servicing, and a held delack would stall the ACK it asserts on
    c, s, w = handshake(cfg=TcpConfig(delayed_ack=False))
    payload = bytes(1000)
    c.send(payload)
    # deliver data to the server, but swallow everything the server says
    # until after the client's RTO fires
    for seg in c.poll_segments(w.now):
        s.on_segment(w.now, seg)
    acks = s.poll_segments(w.now)
    assert acks and any(seq_gt(a.ack, c.iss) for a in acks)
    # fire the client's retransmission timeout -> go-back-N rewind
    t = c.next_timer()
    assert t is not None
    c.on_timer(t)
    assert c.nxt_off == c.una_off  # rewound
    # now the (late) ACK for the original transmission arrives
    for a in acks:
        c.on_segment(t, a)
    assert c.una_off == len(payload)
    assert c.nxt_off >= c.una_off
    # nothing left to retransmit: the late ACK covered it all
    assert not any(seg.payload for seg in c.poll_segments(t + 1))


def test_third_ack_window_update_any_iss():
    """The forced handshake window update must fire for ISS values whose
    sequence space makes seq_lt(snd_wl1=0, seg.seq) false (~half of all
    random ISS draws) — review finding on the round-2 scaling fix."""
    from shadow_tpu.tcp import TcpConfig

    for iss in (1000, (1 << 31) + 5, (1 << 32) - 10):
        cfg = TcpConfig()
        client = TcpState(cfg, iss=iss)
        listener = TcpState(cfg, iss=0)
        listener.listen()
        client.connect(0)
        syn = client.poll_segments(0)[0]
        server = listener.accept_segment(0, syn, child_iss=5000)
        wire = Wire(client, server, 10 * MS)
        wire.run(until=lambda: client.state == State.ESTABLISHED
                 and server.state == State.ESTABLISHED)
        assert server.snd_wnd > 0xFFFF, (
            f"iss={iss}: server snd_wnd={server.snd_wnd} "
            "(third-ACK window update did not fire or was unscaled)"
        )
