"""Co-simulation bridge tests: CPU-emulated hosts over the device network
plane (the host↔device staging contract, SURVEY.md §7 hard part 6)."""

from __future__ import annotations

import pytest

from shadow_tpu.config.options import ConfigOptions
from shadow_tpu.cosim import HybridSimulation

MS = 1_000_000


def _cfg(hosts: dict, stop="3 s", seed=7, extra=None):
    d = {
        "general": {"stop_time": stop, "seed": seed},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": hosts,
    }
    if extra:
        d.update(extra)
    return ConfigOptions.from_dict(d)


def _stdout(sim: HybridSimulation, host_name: str) -> str:
    for spec, host in zip(sim.specs, sim.hosts):
        if spec.name == host_name:
            return "".join(
                b"".join(p.stdout).decode() for p in host.processes.values()
            )
    raise KeyError(host_name)


def test_udp_ping_over_device_plane():
    cfg = _cfg(
        {
            "server": {
                "network_node_id": 0,
                "processes": [{"path": "udp_echo_server", "args": ["port=9000"]}],
            },
            "client": {
                "network_node_id": 0,
                "count": 2,
                "processes": [
                    {
                        "path": "udp_ping",
                        "args": ["server=server", "port=9000", "count=4"],
                        "expected_final_state": {"exited": 0},
                    }
                ],
            },
        }
    )
    sim = HybridSimulation(cfg)
    report = sim.run()
    assert report["process_failures"] == 0
    assert report["packets_sent"] == 16  # 2 clients x 4 pings x 2 directions
    assert report["packets_delivered"] == 16
    for c in ("client1", "client2"):
        out = _stdout(sim, c)
        assert "done ok=4/4" in out
        # every RTT identical + deterministic under the conservative clamp
        rtts = {l.split("rtt_ns=")[1] for l in out.splitlines() if "rtt_ns" in l}
        assert len(rtts) == 1


def test_tgen_tcp_flow_over_device_plane():
    size = 200_000
    cfg = _cfg(
        {
            "server": {
                "network_node_id": 0,
                "processes": [
                    {
                        "path": "tgen_server",
                        "args": ["port=8080", "conns=1"],
                        "expected_final_state": {"exited": 0},
                    }
                ],
            },
            "client": {
                "network_node_id": 0,
                "processes": [
                    {
                        "path": "tgen_client",
                        "args": ["server=server", "port=8080", f"size={size}"],
                        "expected_final_state": {"exited": 0},
                    }
                ],
            },
        },
        stop="10 s",
    )
    sim = HybridSimulation(cfg)
    report = sim.run()
    assert report["process_failures"] == 0
    assert f"bytes={size}" in _stdout(sim, "server")
    assert f"sent={size}" in _stdout(sim, "client")


def test_hybrid_determinism_two_runs():
    def once():
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "processes": [{"path": "udp_echo_server"}],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 3,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "count=6", "size=200"],
                        }
                    ],
                },
            },
            seed=99,
        )
        sim = HybridSimulation(cfg)
        report = sim.run()
        outs = {s.name: _stdout(sim, s.name) for s in sim.specs}
        return report["determinism_digest"], outs, report["packets_sent"]

    assert once() == once()


def test_mixed_model_and_program_builds():
    """Mixing device models and managed programs is supported since round 3
    (models/mixed.py; full behavior covered in tests/test_mixed.py) — the
    config simply builds a MixedModel-backed co-simulation."""
    cfg_dict = {
        "general": {"stop_time": "1 s"},
        "network": {"graph": {"type": "1_gbit_switch"}},
        "hosts": {
            "a": {
                "network_node_id": 0,
                "processes": [{"path": "udp_echo_server"}],
            },
            "b": {
                "network_node_id": 0,
                "processes": [{"model": "timer", "model_args": {"interval": "1 s"}}],
            },
        },
    }
    cfg = ConfigOptions.from_dict(cfg_dict)
    sim = HybridSimulation(cfg, world=1)
    from shadow_tpu.models.mixed import MixedModel

    assert isinstance(sim.model, MixedModel)
    r = sim.run()
    # the modeled timer ticked on device while the program host idled
    assert r["events_processed"] >= 1


def test_build_simulation_factory_dispatch():
    from shadow_tpu.sim import build_simulation, Simulation

    model_cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "1 s"},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "n": {
                    "count": 4,
                    "network_node_id": 0,
                    "processes": [
                        {"model": "timer", "model_args": {"interval": "100 ms"}}
                    ],
                }
            },
        }
    )
    assert isinstance(build_simulation(model_cfg, world=1), Simulation)
    prog_cfg = _cfg(
        {
            "s": {"network_node_id": 0, "processes": [{"path": "udp_echo_server"}]},
        },
        stop="1 s",
    )
    assert isinstance(build_simulation(prog_cfg), HybridSimulation)


def test_hybrid_determinism_sixteen_hosts():
    """Two-run digest equality at >=16 CPU hosts over the device plane —
    the scale point where service order, per-host RNG lanes, and the
    window barrier would expose any wall-clock leakage (VERDICT r1 #9)."""

    def once():
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "count": 2,
                    "processes": [{"path": "udp_echo_server"}],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 14,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server1", "count=3", "size=120"],
                        }
                    ],
                },
            },
            stop="2 s",
            seed=31,
        )
        sim = HybridSimulation(cfg)
        report = sim.run()
        outs = {s.name: _stdout(sim, s.name) for s in sim.specs}
        return report["determinism_digest"], outs, report["packets_sent"]

    first = once()
    assert len(first[1]) == 16
    assert first == once()


def test_rr_qdisc_reorders_and_stays_deterministic():
    """interface_qdisc: round-robin interleaves a host's same-window sends
    one per socket (reference QDiscMode wired into network_interface.c);
    fifo keeps emit order. Both must be deterministic."""
    from shadow_tpu.cosim import _rr_reorder

    # two sockets (A=1, B=2) on host 0, one socket on host 1
    staged = [
        (0, 10, 1, 100, 0, 1),  # A0
        (0, 10, 1, 100, 1, 1),  # A1
        (0, 10, 1, 100, 2, 2),  # B0
        (0, 10, 1, 100, 3, 1),  # A2
        (1, 10, 0, 100, 0, 9),
    ]
    out = _rr_reorder(staged)
    keys = [(e[0], e[4]) for e in out]
    assert keys == [(0, 0), (0, 2), (0, 1), (0, 3), (1, 0)]  # A,B,A,A then h1

    def run(qdisc):
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "processes": [{"path": "udp_echo_server"}],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 2,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "count=3"],
                        }
                    ],
                },
            },
            stop="1 s",
            extra={"experimental": {"interface_qdisc": qdisc}},
        )
        sim = HybridSimulation(cfg)
        report = sim.run()
        return report["determinism_digest"]

    assert run("round-robin") == run("round-robin")  # deterministic


def test_mesh_invariance_one_vs_eight_devices():
    """The co-sim plane must produce IDENTICAL results on a 1-device and an
    8-device mesh (VERDICT r2 missing #7; same bar as the modeled-sim
    determinism suite): digests, packet counts, and every client's stdout."""

    def once(world):
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": "udp_echo_server", "args": ["port=9000"]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 5,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "port=9000", "count=3"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
            stop="4 s",
        )
        sim = HybridSimulation(cfg, world=world)
        report = sim.run()
        outs = {
            spec.name: "".join(
                b"".join(p.stdout).decode() for p in host.processes.values()
            )
            for spec, host in zip(sim.specs, sim.hosts)
        }
        return report, outs

    r1, o1 = once(1)
    r8, o8 = once(8)
    assert r1["determinism_digest"] == r8["determinism_digest"]
    for k in ("packets_sent", "packets_delivered", "packets_lost",
              "process_failures", "events_processed", "syscalls"):
        assert r1[k] == r8[k], k
    assert o1 == o8


def test_parallel_host_plane_matches_serial():
    """experimental.host_workers > 1 runs CpuHosts on a thread pool inside
    each window; per-source staging merged in host-id order makes the result
    byte-identical to serial (reference thread_per_core.rs determinism bar,
    src/test/determinism scheduler-invariance)."""

    def once(workers):
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": "udp_echo_server", "args": ["port=9000"]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 30,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "port=9000", "count=3"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
            stop="4 s",
            extra={"experimental": {"host_workers": workers}},
        )
        sim = HybridSimulation(cfg, world=1)
        report = sim.run()
        outs = {
            spec.name: "".join(
                b"".join(p.stdout).decode() for p in host.processes.values()
            )
            for spec, host in zip(sim.specs, sim.hosts)
        }
        return report, outs

    r1, o1 = once(1)
    r4, o4 = once(4)
    assert r1["determinism_digest"] == r4["determinism_digest"]
    for k in ("packets_sent", "packets_delivered", "events_processed",
              "syscalls", "process_failures"):
        assert r1[k] == r4[k], k
    assert o1 == o4


def test_per_host_scheduler_with_pinning_matches_serial():
    """host_scheduler: per-host (thread_per_host.rs) + use_cpu_pinning
    (affinity.c) through the full hybrid sim — digest-identical to the
    serial default."""

    def once(extra):
        cfg = _cfg(
            {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": "udp_echo_server", "args": ["port=9000"]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "count": 10,
                    "processes": [
                        {
                            "path": "udp_ping",
                            "args": ["server=server", "port=9000", "count=2"],
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
            stop="4 s",
            extra={"experimental": extra} if extra else None,
        )
        sim = HybridSimulation(cfg, world=1)
        return sim.run()

    r_serial = once(None)
    r_ph = once(
        {
            "host_scheduler": "per-host",
            "host_workers": 2,
            "use_cpu_pinning": True,
        }
    )
    assert r_serial["determinism_digest"] == r_ph["determinism_digest"]
    for k in ("packets_sent", "packets_delivered", "events_processed",
              "syscalls"):
        assert r_serial[k] == r_ph[k], k
