"""Descriptor fast path: write(2) on captured stdio answered inside the
shim from a shared ring (native/ipc.h FastFd; the shim_sys.c
answer-hot-calls-locally precedent extended to descriptors).

The gates here are the dangerous paths: entry invalidation when fd 1/2
is remapped (dup2 of a socket over stdout MUST stop the ring), ordering
across slow-path writev interleavings, ring overflow, fork/exec block
swaps, and byte-equality against the all-slow-path strace mode."""

from __future__ import annotations

import os

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork
from shadow_tpu.native_plane import spawn_native
from tests.subproc import native_plane_skip_reason

MS = 1_000_000
SEC = 1_000_000_000

# toolchain-unavailable OR the shim-cannot-load (exit-97) environment —
# the probe classifies the latter so these legs skip with evidence
# instead of hard-F'ing on every exit_code/output assert
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))


def _run_sh(script: str, stop=2 * SEC, strace=None, hosts=1, latency=10 * MS):
    hs = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=3,
                           host_id=i))
        for i in range(hosts)
    ]
    net = CpuNetwork(hs, latency_ns=lambda s, d: latency)
    p = spawn_native(hs[0], ["/bin/sh", "-c", script])
    if strace is not None:
        p.strace = strace
    net.run(stop)
    return hs[0], p


def test_fast_writes_hit_and_capture_in_order():
    h, p = _run_sh(
        "i=0; while [ $i -lt 150 ]; do echo out$i; i=$((i+1)); done"
    )
    out = b"".join(p.stdout)
    assert out.count(b"\n") == 150
    assert out.startswith(b"out0\n") and out.endswith(b"out149\n")
    assert h.counters["syscalls_fast"] >= 150
    # fast calls are folded into the total, not double-booked
    assert h.counters["syscalls"] >= h.counters["syscalls_fast"]
    assert p.exit_code == 0


def test_stderr_redirect_interleaves_on_one_stream():
    """2>&1 makes fd 2's fast entry target the STDOUT buffer; strict
    program order must survive the two entries draining into one list."""
    h, p = _run_sh(
        "exec 2>&1; i=0; while [ $i -lt 40 ]; do "
        "echo o$i; echo e$i 1>&2; i=$((i+1)); done"
    )
    out = b"".join(p.stdout).decode()
    assert b"".join(p.stderr) == b""
    lines = out.splitlines()
    assert lines[:4] == ["o0", "e0", "o1", "e1"]
    assert len(lines) == 80
    assert h.counters["syscalls_fast"] > 0


def test_large_write_rides_slow_path_in_order():
    """A single write larger than the 32 KiB ring must forward (slow
    path) while neighboring small writes stay fast — byte order intact
    within ONE process (no pipeline children muddying the capture)."""
    hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3, host_id=0))]
    net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
    p = spawn_native(hs[0], [
        "/usr/bin/python3", "-c",
        "import os\n"
        "os.write(1, b'head\\n')\n"
        "os.write(1, b'x' * 65536)\n"  # > FASTFD_RING_CAP: slow path
        "os.write(1, b'\\ntail\\n')\n",
    ])
    net.run(2 * SEC)
    out = b"".join(p.stdout)
    assert out.startswith(b"head\n")
    assert out.endswith(b"\ntail\n")
    assert out.count(b"x") == 65536
    assert hs[0].counters["syscalls_fast"] > 0


def test_dup2_socket_over_stdout_invalidates_entry():
    """After dup2(sock, 1), writes to fd 1 must reach the SOCKET — a
    stale fast entry would silently swallow them into the capture
    buffer. Exercised via a shell that redirects echo into a UDP
    connection (/dev/udp is a bash-ism; use a python3 guest instead)."""
    hs = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=3,
                           host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
    srv = spawn_native(hs[0], [
        "/usr/bin/python3", "-c",
        "import socket\n"
        "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
        "s.bind(('10.0.0.1', 7000))\n"
        "d, a = s.recvfrom(100)\n"
        "print('got', d.decode().strip())\n",
    ])
    cli = spawn_native(hs[1], [
        "/usr/bin/python3", "-c",
        "import os, socket, sys\n"
        "print('before-dup')\n"
        "sys.stdout.flush()\n"
        "s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)\n"
        "s.connect(('10.0.0.1', 7000))\n"
        "os.dup2(s.fileno(), 1)\n"
        "os.write(1, b'via-socket\\n')\n"  # must hit the wire, not capture
        "os.dup2(2, 1)\n"  # restore a captured stream
        "os.write(1, b'after-restore\\n')\n",
    ], start_time=50 * MS)
    net.run(3 * SEC)
    assert b"got via-socket" in b"".join(srv.stdout)
    cli_out = b"".join(cli.stdout) + b"".join(cli.stderr)
    assert b"before-dup" in cli_out
    assert b"after-restore" in cli_out
    assert b"via-socket" not in cli_out  # never captured


def test_fork_children_get_their_own_fast_entries():
    h, p = _run_sh(
        "echo parent-pre; (echo child-sub); /bin/echo forked-image; "
        "echo parent-post"
    )
    out = b"".join(p.stdout)
    assert b"parent-pre\n" in out and b"parent-post\n" in out
    # forked children (subshell + external command) write through their
    # OWN blocks' fast entries into their own captures (reaped children
    # leave p.children; the host process table keeps them)
    child_out = b"".join(
        b"".join(pr.stdout)
        for pid, pr in sorted(h.processes.items())
        if pr is not p
    )
    assert b"child-sub\n" in child_out
    assert b"forked-image\n" in child_out
    assert out.count(b"\n") == 2  # nothing leaked across captures


def test_execve_swaps_blocks_without_losing_bytes():
    """Output written fast BEFORE an in-place exec must survive the IPC
    block swap; the new image's writes flow through fresh entries."""
    h, p = _run_sh("echo pre-exec; exec /bin/echo post-exec")
    out = b"".join(p.stdout)
    assert out == b"pre-exec\npost-exec\n"
    assert p.exit_code == 0


def test_strace_mode_is_byte_identical_to_fast_mode():
    """strace modes disable the fast path entirely; the captured bytes
    must be identical either way (the slow-vs-fast determinism gate)."""
    script = (
        "i=0; while [ $i -lt 60 ]; do echo ln$i; echo er$i 1>&2; "
        "i=$((i+1)); done"
    )

    def run(mode_fast: bool):
        hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3,
                                 host_id=0))]
        net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
        p = spawn_native(hs[0], ["/bin/sh", "-c", script])
        if not mode_fast:
            p.strace = lambda *a: None  # any strace hook forces slow path
        net.run(2 * SEC)
        return (b"".join(p.stdout), b"".join(p.stderr),
                hs[0].counters["syscalls"], hs[0].counters["syscalls_fast"])

    fo, fe, fn, ff = run(True)
    so, se, sn, sf = run(False)
    assert (fo, fe) == (so, se)
    assert sf == 0 and ff > 0
    assert fn == sn  # folded accounting matches trap-per-call exactly


def test_same_stream_aliases_keep_program_order():
    """dup2(1, 2) then ALTERNATING write(1)/write(2) with no other
    syscalls in between: both fds append to the stdout buffer, and the
    capture must preserve strict program order. (Review catch: two
    independent rings for one stream drained back-to-back lost the
    interleaving; now at most one fd per stream is fast and the other's
    slow-path trap drains first.)"""
    hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3, host_id=0))]
    net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
    p = spawn_native(hs[0], [
        "/usr/bin/python3", "-c",
        "import os\n"
        "os.dup2(1, 2)\n"
        "for i in range(30):\n"
        "    os.write(1, b'A%d ' % i)\n"
        "    os.write(2, b'B%d ' % i)\n",
    ])
    net.run(2 * SEC)
    out = b"".join(p.stdout).decode()
    expect = "".join(f"A{i} B{i} " for i in range(30))
    assert out == expect
    assert hs[0].counters["syscalls_fast"] > 0  # fd 1 stayed fast


def test_close_range_resyncs_fast_table():
    """close_range mutates the capture tables (runc/systemd hygiene);
    a stale fast entry must not survive it. Gate: byte-equality with
    the all-slow-path strace mode on the same workload."""
    code = (
        "import os\n"
        "os.write(1, b'before\\n')\n"
        "os.close_range(3, 1023)\n"  # hygiene sweep, fds 1/2 untouched
        "os.write(1, b'after\\n')\n"
        "os.write(2, b'err\\n')\n"
    )

    def run(fast: bool):
        hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3,
                                 host_id=0))]
        net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
        p = spawn_native(hs[0], ["/usr/bin/python3", "-c", code])
        if not fast:
            p.strace = lambda *a: None
        net.run(2 * SEC)
        return b"".join(p.stdout), b"".join(p.stderr), p.exit_code

    assert run(True) == run(False)


def test_bad_buffer_returns_efault_not_sigsegv():
    """write(1, bad_ptr, n) on a fast fd must fail exactly like the slow
    path (-EFAULT surfaced as OSError), not kill the guest with SIGSEGV
    inside the SIGSYS handler. The shim copies into the ring via
    process_vm_readv-on-self so the kernel does the fault check (note a
    devnull write-probe canNOT work: /dev/null never reads the buffer)."""
    hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3, host_id=0))]
    net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
    p = spawn_native(hs[0], [
        "/usr/bin/python3", "-c",
        "import ctypes, os\n"
        "os.write(1, b'alive\\n')\n"
        "write = ctypes.CDLL(None, use_errno=True).write\n"
        "r = write(1, ctypes.c_void_p(0x10), 16)\n"  # unmapped pointer
        "assert r == -1 and ctypes.get_errno() == 14, (r, ctypes.get_errno())\n"
        "os.write(1, b'survived\\n')\n",
    ])
    net.run(2 * SEC)
    out = b"".join(p.stdout)
    assert out == b"alive\nsurvived\n", (out, b"".join(p.stderr))
    assert p.exit_code == 0
    assert p.term_signal is None


def test_latency_model_escape_still_advances_time():
    """With model_unblocked_syscall_latency on, every Nth fast write
    forwards so a write loop cannot freeze simulated time."""
    hs = [CpuHost(HostConfig(name="a", ip="10.0.0.1", seed=3, host_id=0,
                             model_unblocked_latency=True))]
    net = CpuNetwork(hs, latency_ns=lambda s, d: 10 * MS)
    p = spawn_native(hs[0], [
        "/bin/sh", "-c",
        "i=0; while [ $i -lt 300 ]; do echo t$i; i=$((i+1)); done",
    ])
    net.run(2 * SEC)
    out = b"".join(p.stdout)
    assert out.count(b"\n") == 300
    assert hs[0].counters["syscalls_fast"] > 0
    assert p.exit_code == 0
