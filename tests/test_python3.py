"""python3 — the hardest unmodified binary in this image (threads, GC, its
own event loops, a huge syscall surface) — plus the r4 syscall families it
motivated: filesystem mutation (unlink/rename/mkdir/fsync/flock/statfs/
ftruncate/chmod), memfd_create, inotify, signalfd, and SCM_RIGHTS fd
passing. Reference: the fileat.c/file.c dispatch arms
(handler/mod.rs:371-539) and the examples/apps third-party corpus."""

from __future__ import annotations

import os
import shutil

import pytest

from shadow_tpu.host import CpuHost, HostConfig
from shadow_tpu.host.network import CpuNetwork

from tests.subproc import native_plane_skip_reason

# toolchain-unavailable OR the shim-cannot-load (exit-97) container
# (tests/subproc.py native_plane_skip_reason classifies the signature)
_skip = native_plane_skip_reason()
pytestmark = pytest.mark.skipif(_skip is not None, reason=str(_skip))

from shadow_tpu.native_plane import spawn_native  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PY = "/opt/venv/bin/python3"
FSMUT = os.path.join(REPO, "native", "build", "test_fsmut")
SCM = os.path.join(REPO, "native", "build", "test_scm")

MS = 1_000_000
SEC = 1_000_000_000

PAYLOAD = bytes(range(256)) * 64  # 16 KiB, content-checkable

SERVER = (
    "import http.server, os\n"
    "os.makedirs('{docs}', exist_ok=True)\n"
    "open('{docs}/d.bin', 'wb').write(bytes(range(256)) * 64)\n"
    "os.chdir('{docs}')\n"
    "http.server.HTTPServer(('0.0.0.0', 8000),\n"
    "    http.server.SimpleHTTPRequestHandler).serve_forever()\n"
)
CLIENT = (
    "import urllib.request, sys, time\n"
    "d = urllib.request.urlopen('http://h0:8000/d.bin', timeout=30).read()\n"
    "print('got', len(d), 'at', time.time())\n"
    "sys.exit(0 if d == bytes(range(256)) * 64 else 1)\n"
)


def two_hosts(seed=7, lat_ms=10):
    hosts = [
        CpuHost(HostConfig(name=f"h{i}", ip=f"10.0.0.{i + 1}", seed=seed,
                           host_id=i))
        for i in range(2)
    ]
    net = CpuNetwork(hosts, latency_ns=lambda s, d: lat_ms * MS)
    return hosts, net


def _run_http(tmpdir: str, seed: int = 7):
    docs = os.path.join(tmpdir, "docs")
    shutil.rmtree(docs, ignore_errors=True)
    hosts, net = two_hosts(seed=seed)
    srv = spawn_native(hosts[0], [PY, "-c", SERVER.format(docs=docs)])
    cli = spawn_native(hosts[1], [PY, "-c", CLIENT], start_time=500 * MS)
    net.run(4 * SEC)
    return srv, cli, hosts


@pytest.mark.skipif(not os.path.exists(PY), reason="no python3 in image")
def test_python3_http_server_and_urllib_client(tmp_path):
    """An unmodified CPython runs http.server on one simulated host and a
    urllib client on another; the 16 KiB body is byte-verified end to end
    (exit 0 only on exact content match)."""
    srv, cli, hosts = _run_http(str(tmp_path))
    assert cli.exit_code == 0, b"".join(cli.stderr)[-2000:]
    assert b"got 16384" in b"".join(cli.stdout)
    assert srv.state == "running"  # the daemon survived to stop time
    # the GET is visible in the server's (simulated-time-stamped) log
    assert b"GET /d.bin" in b"".join(srv.stderr)


@pytest.mark.skipif(not os.path.exists(PY), reason="no python3 in image")
def test_python3_http_transfer_is_deterministic(tmp_path):
    """Two runs are byte-identical: client output (which embeds the
    simulated completion TIME) and per-host syscall counts all match."""

    def once(i):
        srv, cli, hosts = _run_http(str(tmp_path / f"r{i}"), seed=11)
        return (
            b"".join(cli.stdout),
            cli.exit_code,
            tuple(h.counters["syscalls"] for h in hosts),
            tuple(h.counters["pkts_recv"] for h in hosts),
        )

    a, b = once(0), once(1)
    assert a == b
    assert a[1] == 0


@pytest.mark.skipif(not os.path.exists(PY), reason="no python3 in image")
def test_python3_against_device_plane(tmp_path):
    """python3 server + client through the FULL hybrid plane: traffic rides
    the device network (token buckets, loss draw, latency, exchange), DNS
    via the simulator registry, reverse-DNS via the shim's gethostbyaddr_r
    interposer (a stall here pushed listen() 10 sim-seconds late)."""
    from shadow_tpu.config.options import ConfigOptions
    from shadow_tpu.cosim import HybridSimulation

    docs = str(tmp_path / "docs")
    cfg = ConfigOptions.from_dict(
        {
            "general": {"stop_time": "4 s", "seed": 7},
            "network": {"graph": {"type": "1_gbit_switch"}},
            "hosts": {
                "server": {
                    "network_node_id": 0,
                    "processes": [
                        {"path": PY, "args": ["-c", SERVER.format(docs=docs)]}
                    ],
                },
                "client": {
                    "network_node_id": 0,
                    "processes": [
                        {
                            "path": PY,
                            "args": [
                                "-c",
                                CLIENT.replace("http://h0", "http://server"),
                            ],
                            "start_time": "1 s",
                            "expected_final_state": {"exited": 0},
                        }
                    ],
                },
            },
        }
    )
    sim = HybridSimulation(cfg, world=1)
    r = sim.run()
    assert r["process_failures"] == 0
    out = b"".join(
        b"".join(p.stdout)
        for h in sim.hosts
        for p in h.processes.values()
    )
    assert b"got 16384" in out


def test_fs_mutation_family_and_inotify(tmp_path):
    """unlink/rename/mkdir/rmdir/fsync/fdatasync/ftruncate/flock/chmod/
    fchmod/statfs/fstatfs/memfd_create all work under the shim, and the
    dispatch-layer inotify emulation sees the expected events (2 creates,
    2 deletes, 1 rename pair) — the write-tmp-then-rename commit pattern
    most applications use."""
    scratch = str(tmp_path / "scratch")
    os.makedirs(scratch)
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    p = spawn_native(h, [FSMUT, scratch])
    h.execute(5 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "inotify create=2 delete=2 moved_from=1 moved_to=1" in out
    assert "fsmut ok" in out


def test_scm_rights_and_signalfd():
    """SCM_RIGHTS: a socketpair end crosses processes over a unix stream
    socket and carries live traffic; signalfd: SIGUSR1 routed to the fd
    is read back as a siginfo record."""
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    p = spawn_native(h, [SCM])
    h.execute(5 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "scm_rights ok" in out
    assert "signalfd ok" in out  # incl. ssi_pid sender attribution
    # addressed dgram sendmsg + peek-does-not-consume + msg_name writeback
    assert "dgram rights ok" in out


def test_flock_contention_in_sim_time(tmp_path):
    """flock is emulated against a host-scoped lock table (a native flock
    would block the child invisibly in the kernel and wedge the scheduler,
    the futex rationale): LOCK_NB sees EWOULDBLOCK while held; a blocking
    LOCK_EX parks in SIM time and acquires exactly at release."""
    lock = str(tmp_path / "lockfile")
    binpath = os.path.join(REPO, "native", "build", "test_flock")
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    from shadow_tpu.native_plane import spawn_native as _sp

    holder = _sp(h, [binpath, lock, "hold", "300"])
    waiter = _sp(h, [binpath, lock, "wait"], start_time=50 * MS)
    h.execute(5 * SEC)
    assert holder.exit_code == 0, b"".join(holder.stderr)
    assert waiter.exit_code == 0, b"".join(waiter.stderr)
    wout = b"".join(waiter.stdout).decode()
    assert "nb busy at 50" in wout
    assert "acquired at 300" in wout  # exactly the holder's release time


def test_last_stretch_dispatch_arms(tmp_path):
    """r4 closes the reference's dispatch surface: legacy open/stat/pipe,
    pwrite, utimes, emulated credential setters (a NATIVE setuid would
    strip the simulator's process_vm access), capget/capset,
    sched_setaffinity, waitid (siginfo-shaped reap), close_range across
    emulated vfds."""
    h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
    p = spawn_native(
        h, [os.path.join(REPO, "native", "build", "test_misc2"),
            str(tmp_path)]
    )
    h.execute(5 * SEC)
    out = b"".join(p.stdout).decode()
    assert p.exit_code == 0, out + b"".join(p.stderr).decode()
    assert "misc2 ok" in out


BASH_SCRIPT = (
    "echo start; seq 1 20 | grep -v 7 | sort -rn | head -3 | tr '\\n' ' '; "
    "echo; for i in 1 2 3; do echo loop $i; done | wc -l; "
    "x=$(date +%s); echo epoch=$x; sleep 0.3; echo done; exit 0"
)


@pytest.mark.skipif(not os.path.exists("/bin/bash"), reason="no bash")
def test_bash_pipelines_and_command_substitution():
    """An unmodified bash runs a compound script under the shim: 5-stage
    coreutils pipelines (fork/execve/dup2 over EMULATED pipes — blocking
    parks in sim time instead of wedging the scheduler in the kernel),
    command substitution, and date reading the SIMULATED clock. Two runs
    are byte-identical across the whole process tree."""

    def once():
        h = CpuHost(HostConfig(name="n1", ip="10.0.0.1", seed=4, host_id=0))
        p = spawn_native(h, ["/bin/bash", "-c", BASH_SCRIPT])
        h.execute(8 * SEC)
        tree = {
            q.pid: (
                tuple(getattr(q, "argv", ())), q.exit_code,
                b"".join(q.stdout),
            )
            for q in h.processes.values()
        }
        return p.exit_code, b"".join(p.stdout), tree

    code, out, tree = once()
    assert code == 0, tree
    assert b"start\n" in out
    assert b"epoch=0\n" in out  # date(1) reads the SIMULATED clock
    assert b"done\n" in out
    # the pipeline tail stages carried the right bytes
    flat = b"".join(v[2] for v in tree.values())
    assert b"20 19 18 " in flat  # seq|grep -v 7|sort -rn|head -3|tr
    assert b"3\n" in flat  # for-loop | wc -l
    assert all(v[1] == 0 for v in tree.values()), tree
    assert once() == (code, out, tree)  # deterministic process tree


GIT = "/usr/bin/git"


@pytest.mark.skipif(not os.path.exists(GIT), reason="no git in image")
def test_git_clone_over_simulated_network(tmp_path):
    """Stock git: `git daemon` serves a repo on one simulated host and
    `git clone git://...` fetches it on another — by simulated hostname.
    This exercises the deepest process machinery in one shot: the
    daemon's double fork, upload-pack spawning pack-objects over
    CLOEXEC pipes (exec must drop them or the pack stream never sees
    EOF), fdopen validating F_GETFL access modes, and the pkt-line/
    sideband protocol over the emulated TCP stack."""
    import subprocess as sp

    base = tmp_path / "srv"
    bare = base / "repo.git"
    bare.mkdir(parents=True)
    env = {**os.environ, "GIT_AUTHOR_DATE": "2000-01-01T00:00:00",
           "GIT_COMMITTER_DATE": "2000-01-01T00:00:00"}
    sp.run([GIT, "init", "-q", "--bare", str(bare)], check=True)
    work = base / "w"
    sp.run([GIT, "clone", "-q", str(bare), str(work)], check=True,
           stderr=sp.DEVNULL)
    (work / "f.txt").write_text("hello simulated world\n")
    for cmd in (["config", "user.email", "t@t"], ["config", "user.name", "t"],
                ["add", "f.txt"], ["commit", "-qm", "init"]):
        sp.run([GIT, "-C", str(work)] + cmd, check=True, env=env)
    sp.run([GIT, "-C", str(work), "push", "-q", "origin", "HEAD"],
           check=True, stderr=sp.DEVNULL)

    def once(i):
        dst = str(tmp_path / f"clone{i}")
        hosts, net = two_hosts(seed=13)
        srv = spawn_native(
            hosts[0],
            [GIT, "daemon", "--reuseaddr", "--export-all",
             f"--base-path={base}", "--port=9418"],
        )
        cli = spawn_native(
            hosts[1], [GIT, "clone", "git://h0/repo.git", dst],
            start_time=500 * MS,
        )
        net.run(20 * SEC)
        assert cli.exit_code == 0, b"".join(cli.stderr)[-500:]
        with open(os.path.join(dst, "f.txt")) as f:
            assert f.read() == "hello simulated world\n"
        return tuple(h.counters["syscalls"] for h in hosts), tuple(
            h.counters["pkts_recv"] for h in hosts
        )

    assert once(0) == once(1)  # byte-deterministic across reruns
